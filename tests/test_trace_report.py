"""tools/trace_report.py smoke: tiny fit with the JSONL sink enabled, then
the CLI renders it and the anomaly checks run (ISSUE-2 CI satellite)."""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.utils.config import get_config, set_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "trace_report.py")


def _load_cli_module():
    spec = importlib.util.spec_from_file_location("trace_report", CLI)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def sink(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    old = get_config().telemetry_path
    set_config(telemetry_path=path)
    yield path
    set_config(telemetry_path=old)


def test_cli_renders_a_real_fit(sink):
    x = np.random.default_rng(0).normal(size=(256, 6))
    PCA().setInputCol("f").setK(2).fit(x)
    assert os.path.exists(sink)
    proc = subprocess.run(
        [sys.executable, CLI, sink],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "PCA" in out
    assert "phase" in out  # the per-phase table header rendered
    # the anomaly checker ran (either clean or flagged)
    assert "anomaly checks: ok" in out or "!!" in out


def test_cli_in_process_main(sink):
    x = np.random.default_rng(1).normal(size=(128, 4))
    PCA().setInputCol("f").setK(2).fit(x)
    mod = _load_cli_module()
    assert mod.main([sink]) == 0
    assert mod.main([sink, "--last", "1"]) == 0


def test_cli_missing_file_fails_cleanly():
    mod = _load_cli_module()
    assert mod.main(["/nonexistent/t.jsonl"]) == 1


def test_overlap_anomaly_fires():
    mod = _load_cli_module()
    rec = {
        "type": "fit_report",
        "estimator": "X",
        "wall_seconds": 10.0,
        "rows_ingested": 100,
        "phases": {
            "fold.dispatch": {"count": 4, "sum": 1.0},
            "fold.wait": {"count": 1, "sum": 5.0},
        },
        "compile": {},
    }
    anomalies = mod.check_anomalies(rec)
    assert any("not overlapping" in a for a in anomalies)


def test_compile_dominated_anomaly_fires():
    mod = _load_cli_module()
    rec = {
        "type": "fit_report",
        "estimator": "X",
        "wall_seconds": 2.0,
        "rows_ingested": 100,
        "phases": {},
        "compile": {"count": 3, "seconds": 1.5},
    }
    anomalies = mod.check_anomalies(rec)
    assert any("compile-dominated" in a for a in anomalies)


def test_strict_exit_code(tmp_path):
    mod = _load_cli_module()
    import json

    rec = {
        "type": "fit_report",
        "estimator": "X",
        "wall_seconds": 10.0,
        "rows_ingested": 100,
        "phases": {
            "fold.dispatch": {"count": 4, "sum": 1.0},
            "fold.wait": {"count": 1, "sum": 5.0},
        },
        "compile": {},
    }
    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps(rec) + "\n")
    assert mod.main([str(p)]) == 0
    assert mod.main([str(p), "--strict"]) == 2


def test_recovered_but_degraded_anomaly_fires():
    mod = _load_cli_module()
    rec = {
        "type": "fit_report",
        "estimator": "X",
        "wall_seconds": 1.0,
        "rows_ingested": 100,
        "phases": {},
        "compile": {},
        "counters": {
            "retry.attempts{site=ingest.chunk}": 2.0,
            "chunk.bisections{}": 1.0,
        },
    }
    anomalies = mod.check_anomalies(rec)
    assert any("recovered-but-degraded" in a for a in anomalies)


def test_newer_schema_skipped_with_note_not_keyerror(tmp_path, capsys):
    """Schema-tolerance satellite: a record from a future schema renders as
    a skip-note, and --strict turns skips into exit 2."""
    mod = _load_cli_module()
    import json

    future = {"type": "fit_report", "schema": 99, "estimator": "X"}
    ok = {
        "type": "fit_report",
        "estimator": "Y",
        "wall_seconds": 1.0,
        "rows_ingested": 10,
        "phases": {},
        "compile": {},
    }
    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps(future) + "\n" + json.dumps(ok) + "\n")
    assert mod.main([str(p)]) == 0  # the good record still rendered
    captured = capsys.readouterr()
    assert "newer than this tool" in captured.err
    assert "Y" in captured.out
    assert mod.main([str(p), "--strict"]) == 2


def test_malformed_record_skipped_not_traceback(tmp_path, capsys):
    mod = _load_cli_module()
    import json

    # phases as a list breaks the renderer's .items(); must skip, not raise
    bad = {"type": "fit_report", "estimator": "X", "phases": [1, 2]}
    ok = {
        "type": "fit_report",
        "estimator": "Y",
        "wall_seconds": 1.0,
        "rows_ingested": 10,
        "phases": {},
        "compile": {},
    }
    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps(bad) + "\n" + json.dumps(ok) + "\n")
    assert mod.main([str(p)]) == 0
    captured = capsys.readouterr()
    assert "skipping unrenderable record" in captured.err
    assert "Y" in captured.out


def test_overlap_fraction_and_fit_id_rendered():
    mod = _load_cli_module()
    import io

    rec = {
        "type": "fit_report",
        "estimator": "X",
        "fit_id": "abc123def456",
        "overlap_fraction": 0.75,
        "wall_seconds": 1.0,
        "rows_ingested": 10,
        "phases": {},
        "compile": {},
    }
    buf = io.StringIO()
    mod.render_record(rec, out=buf)
    out = buf.getvalue()
    assert "fit=abc123def456" in out
    assert "overlap: 0.75" in out


def test_fault_injection_anomaly_fires_and_strict_exits_2(tmp_path):
    mod = _load_cli_module()
    import json

    rec = {
        "type": "fit_report",
        "estimator": "X",
        "wall_seconds": 1.0,
        "rows_ingested": 100,
        "phases": {},
        "compile": {},
        "counters": {"fault.injected{site=fold.dispatch,kind=oom}": 3.0},
    }
    anomalies = mod.check_anomalies(rec)
    assert any("fault injection active" in a for a in anomalies)
    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps(rec) + "\n")
    assert mod.main([str(p)]) == 0
    assert mod.main([str(p), "--strict"]) == 2


def test_slo_breach_anomaly_fires_and_strict_exits_2(tmp_path):
    """Schema-5 satellite: counted slo.breach during the fit window is the
    slo-breach-during-fit anomaly, and --strict gates on it."""
    mod = _load_cli_module()
    import json

    rec = {
        "type": "fit_report",
        "schema": 5,
        "estimator": "X",
        "wall_seconds": 1.0,
        "rows_ingested": 100,
        "phases": {},
        "compile": {},
        "counters": {"slo.breach{objective=fold.wait:p99}": 2.0},
    }
    anomalies = mod.check_anomalies(rec)
    assert any("slo-breach-during-fit" in a for a in anomalies)
    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps(rec) + "\n")
    assert mod.main([str(p)]) == 0
    assert mod.main([str(p), "--strict"]) == 2


def test_health_summary_rendered_from_schema_5():
    mod = _load_cli_module()
    import io

    rec = {
        "type": "fit_report",
        "schema": 5,
        "estimator": "X",
        "wall_seconds": 1.0,
        "rows_ingested": 10,
        "phases": {},
        "compile": {},
        "health": {
            "state": "DEGRADED",
            "components": {
                "device": "OK",
                "transport": "DEGRADED",
                "stream": "OK",
                "workers": "OK",
                "resilience": "OK",
            },
            "polls": 7,
            "transitions": 2,
            "slo_breaches": 1,
        },
    }
    buf = io.StringIO()
    mod.render_record(rec, out=buf)
    out = buf.getvalue()
    assert "health: DEGRADED (transport=DEGRADED)" in out
    assert "7 poll(s)" in out
    assert "1 SLO breach(es)" in out


def test_health_summary_absent_prints_nothing():
    mod = _load_cli_module()
    import io

    rec = {
        "type": "fit_report",
        "schema": 5,
        "estimator": "X",
        "wall_seconds": 1.0,
        "rows_ingested": 10,
        "phases": {},
        "compile": {},
        "health": {},
    }
    buf = io.StringIO()
    mod.render_record(rec, out=buf)
    assert "health:" not in buf.getvalue()
