"""MultilayerPerceptronClassifier — nonlinear-capacity and quality tests.

The XOR-style oracle is the point: no linear model in this package can
exceed ~50% there, so passing proves the hidden layers actually train.
sklearn's MLPClassifier (lbfgs solver) is the quality reference.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import (
    MultilayerPerceptronClassificationModel,
    MultilayerPerceptronClassifier,
)


@pytest.fixture(scope="module")
def xor_data():
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, size=(1500, 2))
    y = ((x[:, 0] * x[:, 1]) > 0).astype(float)
    return x[:1000], y[:1000], x[1000:], y[1000:]


@pytest.fixture(scope="module")
def blobs3():
    rng = np.random.default_rng(1)
    centers = rng.normal(scale=4, size=(3, 6))
    x = np.concatenate([c + rng.normal(size=(200, 6)) for c in centers])
    y = np.repeat(np.arange(3.0), 200)
    return x, y


def test_solves_xor(xor_data):
    xtr, ytr, xte, yte = xor_data
    m = (
        MultilayerPerceptronClassifier().setLayers([2, 16, 8, 2])
        .setMaxIter(300).setSeed(1).fit((xtr, ytr))
    )
    acc = (m._predict_matrix(xte) == yte).mean()
    assert acc > 0.95, acc  # a linear model caps at ~0.5 here
    assert m.iterations > 5 and np.isfinite(m.trainLoss)


def test_quality_vs_sklearn(blobs3):
    sk_nn = pytest.importorskip("sklearn.neural_network")
    x, y = blobs3
    m = (
        MultilayerPerceptronClassifier().setLayers([6, 16, 3])
        .setMaxIter(200).setSeed(2).fit((x, y))
    )
    ours = (m._predict_matrix(x) == y).mean()
    sk = sk_nn.MLPClassifier(
        hidden_layer_sizes=(16,), solver="lbfgs", max_iter=200, random_state=2
    ).fit(x, y)
    assert ours >= sk.score(x, y) - 0.03, (ours, sk.score(x, y))


def test_gd_solver_reduces_loss(xor_data):
    xtr, ytr, _, _ = xor_data
    m = (
        MultilayerPerceptronClassifier().setLayers([2, 8, 2])
        .setSolver("gd").setStepSize(0.5).setMaxIter(50).setSeed(0)
        .fit((xtr, ytr))
    )
    assert np.isfinite(m.trainLoss) and m.trainLoss < np.log(2.0)


def test_determinism_and_columns(blobs3):
    pd = pytest.importorskip("pandas")
    x, y = blobs3
    kw = dict(maxIter=60, seed=7)
    m1 = MultilayerPerceptronClassifier(**kw).setLayers([6, 8, 3]).fit((x, y))
    m2 = MultilayerPerceptronClassifier(**kw).setLayers([6, 8, 3]).fit((x, y))
    np.testing.assert_array_equal(m1.weights, m2.weights)
    out = m1.transform(pd.DataFrame({"features": list(x[:30])}))
    assert {"rawPrediction", "probability", "prediction"} <= set(out.columns)
    p = np.stack(out["probability"])
    np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-9)
    raw = np.stack(out["rawPrediction"])
    np.testing.assert_array_equal(
        out["prediction"].to_numpy(), raw.argmax(1).astype(float)
    )


def test_validation(blobs3):
    x, y = blobs3
    with pytest.raises(ValueError, match="setLayers"):
        MultilayerPerceptronClassifier().fit((x, y))
    with pytest.raises(ValueError, match="layers\\[0\\]"):
        MultilayerPerceptronClassifier().setLayers([4, 8, 3]).fit((x, y))
    with pytest.raises(ValueError, match="layers\\[-1\\]"):
        MultilayerPerceptronClassifier().setLayers([6, 8, 2]).fit((x, y))
    with pytest.raises(ValueError, match="solver"):
        MultilayerPerceptronClassifier().setSolver("adam")


def test_persistence_roundtrip(tmp_path, blobs3):
    x, y = blobs3
    m = (
        MultilayerPerceptronClassifier().setLayers([6, 10, 3])
        .setMaxIter(80).setSeed(3).fit((x, y))
    )
    path = str(tmp_path / "mlp")
    m.save(path)
    loaded = MultilayerPerceptronClassificationModel.load(path)
    assert loaded.getLayers() == [6, 10, 3]
    np.testing.assert_array_equal(loaded.weights, m.weights)
    np.testing.assert_array_equal(
        loaded._predict_matrix(x[:50]), m._predict_matrix(x[:50])
    )


def test_weighted_fit_is_honored(blobs3):
    """(X, y, w) weights the loss (an extension over pyspark's MLP):
    zero-weight junk rows must not move the fit."""
    x, y = blobs3
    junk_x = np.concatenate([x, x[:50] + 100.0])
    junk_y = np.concatenate([y, (y[:50] + 1) % 3])
    w = np.concatenate([np.ones(len(x)), np.zeros(50)])
    kw = dict(maxIter=60, seed=4)
    m_w = (
        MultilayerPerceptronClassifier(**kw).setLayers([6, 8, 3])
        .fit((junk_x, junk_y, w))
    )
    m_ref = (
        MultilayerPerceptronClassifier(**kw).setLayers([6, 8, 3])
        .fit((x, y))
    )
    # identical loss surfaces -> identical L-BFGS trajectories from the
    # same init (padding differs, but pad rows carry zero weight)
    np.testing.assert_allclose(m_w.weights, m_ref.weights, rtol=1e-6, atol=1e-8)
