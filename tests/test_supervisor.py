"""Unit tests for resilience.supervisor: slot leases, bounded respawn,
the per-slot circuit breaker, and the all-quarantined half-open probe.

The session-level behavior (real worker processes dying under a stage)
lives in test_chaos_matrix.py; these tests drive the supervisor directly
with fake workers so every breaker transition is cheap and exact.
"""

import types

import pytest

from spark_rapids_ml_tpu.resilience.supervisor import (
    WorkerSupervisor,
    active_summary,
)
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY


class FakeWorker:
    def __init__(self, env):
        self.env = env
        self.dead = False
        self.closed = False
        self.proc = types.SimpleNamespace(poll=lambda: None, pid=id(self))

    def close(self):
        self.closed = True


@pytest.fixture
def spawned():
    return []


@pytest.fixture
def sup(spawned):
    def spawn(extra):
        spawned.append(FakeWorker(extra))
        return spawned[-1]

    s = WorkerSupervisor(spawn, 2, breaker_threshold=2, backoff_s=0.0)
    yield s
    s.close()


class TestLeases:
    def test_checkout_spawns_once_and_reuses(self, sup, spawned):
        w = sup.checkout(0)
        assert sup.checkout(0) is w
        assert len(spawned) == 1
        assert w.env["TPU_ML_WORKER_SLOT"] == "0"

    def test_success_resets_the_breaker_streak(self, sup):
        sup.checkout(0)
        sup.report_crash(0, "boom")
        sup.checkout(0)
        sup.report_success(0)
        sup.report_crash(0, "boom")  # streak restarted: 1 < threshold 2
        assert sup.quarantined_slots() == []

    def test_summary_carries_lease_state(self, sup):
        sup.checkout(1)
        sup.report_success(1)
        summ = sup.summary()
        assert summ["slots"] == 2 and summ["breaker_threshold"] == 2
        lease = summ["leases"]["1"]
        assert lease["live"] and lease["tasks_done"] == 1
        assert not lease["quarantined"]


class TestCircuitBreaker:
    def test_crash_loop_quarantines_at_threshold(self, sup):
        snap0 = REGISTRY.snapshot()
        sup.checkout(0)
        assert sup.report_crash(0, "boom") is False
        sup.checkout(0)  # respawn after the first crash
        assert sup.report_crash(0, "boom") is True
        assert sup.quarantined_slots() == [0]
        assert sup.checkout(0) is None  # breaker open: no more respawns
        assert sup.available_slots() == [1]
        d = REGISTRY.snapshot().delta(snap0)
        assert d.counter("worker.quarantine", slot="0") == 1
        assert d.counter("worker.respawn", slot="0") == 1

    def test_all_quarantined_half_opens_one_probe(self, sup):
        for slot in (0, 1):
            for err in ("a", "b"):
                sup.checkout(slot)
                sup.report_crash(slot, err)
        assert sorted(sup.quarantined_slots()) == [0, 1]
        sup.begin_stage()
        probes = sup.available_slots()
        assert len(probes) == 1  # exactly one half-open probe slot
        assert sup.checkout(probes[0]) is not None
        # the probe gets ONE chance: the next crash re-opens instantly
        assert sup.report_crash(probes[0], "still bad") is True
        assert sorted(sup.quarantined_slots()) == [0, 1]


class TestLifecycle:
    def test_close_closes_workers_and_refuses_checkout(self):
        spawned = []

        def spawn(extra):
            spawned.append(FakeWorker(extra))
            return spawned[-1]

        s = WorkerSupervisor(spawn, 1, breaker_threshold=2, backoff_s=0.0)
        w = s.checkout(0)
        s.close()
        assert w.closed
        assert s.checkout(0) is None
        s.close()  # idempotent

    def test_active_summary_lists_live_supervisors(self):
        s = WorkerSupervisor(
            lambda e: FakeWorker(e), 3, breaker_threshold=2, backoff_s=0.0
        )
        try:
            summ = active_summary()
            sups = summ.get("supervisors", [summ])
            assert any(entry.get("slots") == 3 for entry in sups)
        finally:
            s.close()
