"""StandardScaler / Normalizer tests — differential vs scikit-learn."""

import numpy as np
import pytest
from sklearn.preprocessing import StandardScaler as SkScaler
from sklearn.preprocessing import normalize as sk_normalize

from spark_rapids_ml_tpu.models.scaler import Normalizer, StandardScaler, StandardScalerModel


@pytest.fixture
def data(rng):
    x = rng.normal(size=(300, 12)) * rng.uniform(0.1, 5.0, size=12)[None, :]
    return x + rng.uniform(-3, 3, size=12)[None, :]


class TestStandardScaler:
    def test_moments_match_numpy(self, data):
        model = StandardScaler().setInputCol("f").fit(data, num_partitions=3)
        np.testing.assert_allclose(model.mean, data.mean(axis=0), rtol=1e-10)
        np.testing.assert_allclose(model.std, data.std(axis=0, ddof=1), rtol=1e-10)

    def test_defaults_match_spark(self, data):
        """Spark defaults: withStd=True, withMean=False."""
        model = StandardScaler().setInputCol("f").fit(data)
        out = model.transform(data)
        np.testing.assert_allclose(out, data / data.std(axis=0, ddof=1), rtol=1e-9)

    def test_with_mean_matches_sklearn(self, data):
        model = (
            StandardScaler().setInputCol("f").setWithMean(True).fit(data)
        )
        out = model.transform(data)
        want = SkScaler().fit_transform(data) * np.sqrt((len(data) - 1) / len(data))
        # sklearn uses population std; rescale to sample-std semantics
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_constant_feature_passthrough(self, rng):
        x = rng.normal(size=(50, 3))
        x[:, 1] = 7.0  # zero variance
        model = StandardScaler().setInputCol("f").setWithMean(True).fit(x)
        out = model.transform(x)
        np.testing.assert_allclose(out[:, 1], 0.0, atol=1e-12)  # centered, unscaled
        assert np.all(np.isfinite(out))

    def test_persistence_roundtrip(self, data, tmp_path):
        model = StandardScaler().setInputCol("f").setWithMean(True).fit(data)
        model.save(tmp_path / "s")
        loaded = StandardScalerModel.load(tmp_path / "s")
        np.testing.assert_array_equal(loaded.mean, model.mean)
        assert loaded.getWithMean() is True
        np.testing.assert_allclose(loaded.transform(data), model.transform(data))


class TestNormalizer:
    @pytest.mark.parametrize("p", [1.0, 2.0, 3.0])
    def test_matches_sklearn(self, data, p):
        out = Normalizer().setInputCol("f").setP(p).transform(data)
        want = sk_normalize(data, norm={1.0: "l1", 2.0: "l2"}.get(p, "l2"))
        if p in (1.0, 2.0):
            np.testing.assert_allclose(out, want, rtol=1e-6)
        norms = np.sum(np.abs(out) ** p, axis=1) ** (1 / p)
        np.testing.assert_allclose(norms, 1.0, rtol=1e-6)

    def test_inf_norm(self, data):
        out = Normalizer().setInputCol("f").setP(float("inf")).transform(data)
        np.testing.assert_allclose(np.max(np.abs(out), axis=1), 1.0, rtol=1e-9)

    def test_zero_row_untouched(self):
        x = np.array([[0.0, 0.0], [3.0, 4.0]])
        out = Normalizer().setInputCol("f").transform(x)
        np.testing.assert_array_equal(out[0], [0.0, 0.0])
        np.testing.assert_allclose(out[1], [0.6, 0.8], rtol=1e-9)


class TestMinMaxScaler:
    def test_matches_sklearn(self, data):
        from sklearn.preprocessing import MinMaxScaler as SkMinMax

        from spark_rapids_ml_tpu.models.scaler import MinMaxScaler

        model = MinMaxScaler().setInputCol("f").fit(data, num_partitions=3)
        out = model.transform(data)
        want = SkMinMax().fit_transform(data)
        np.testing.assert_allclose(out, want, atol=1e-12)
        np.testing.assert_allclose(model.originalMin, data.min(axis=0))
        np.testing.assert_allclose(model.originalMax, data.max(axis=0))

    def test_custom_range(self, data):
        from spark_rapids_ml_tpu.models.scaler import MinMaxScaler

        model = (
            MinMaxScaler().setInputCol("f").setMin(-2.0).setMax(3.0).fit(data)
        )
        out = model.transform(data)
        assert out.min() >= -2.0 - 1e-12 and out.max() <= 3.0 + 1e-12
        np.testing.assert_allclose(out.min(axis=0), -2.0, atol=1e-12)
        np.testing.assert_allclose(out.max(axis=0), 3.0, atol=1e-12)

    def test_constant_feature_maps_to_midpoint(self, rng):
        from spark_rapids_ml_tpu.models.scaler import MinMaxScaler

        x = rng.normal(size=(50, 3))
        x[:, 1] = 7.0
        out = MinMaxScaler().setInputCol("f").fit(x).transform(x)
        np.testing.assert_allclose(out[:, 1], 0.5)  # 0.5*(0+1)

    def test_positive_data_multi_partition_pads_do_not_pollute(self, rng):
        # all-positive data: a zero pad row would fake a 0.0 minimum if the
        # pad mask were missing (the bug class range_stats masks against)
        from spark_rapids_ml_tpu.models.scaler import MinMaxScaler

        x = rng.uniform(5.0, 9.0, size=(257, 4))  # odd size: ragged buckets
        model = MinMaxScaler().setInputCol("f").fit(x, num_partitions=4)
        np.testing.assert_allclose(model.originalMin, x.min(axis=0))
        m1 = MinMaxScaler().setInputCol("f").fit(x, num_partitions=1)
        np.testing.assert_allclose(model.originalMin, m1.originalMin)
        np.testing.assert_allclose(model.originalMax, m1.originalMax)

    def test_bad_range_rejected(self, data):
        from spark_rapids_ml_tpu.models.scaler import MinMaxScaler

        with pytest.raises(ValueError, match="must be <"):
            MinMaxScaler().setInputCol("f").setMin(1.0).setMax(1.0).fit(data)

    def test_persistence_roundtrip_both_layouts(self, data, tmp_path):
        from spark_rapids_ml_tpu.models.scaler import (
            MinMaxScaler,
            MinMaxScalerModel,
        )

        model = MinMaxScaler().setInputCol("f").setMax(2.0).fit(data)
        model.save(tmp_path / "native")
        loaded = MinMaxScalerModel.load(tmp_path / "native")
        np.testing.assert_array_equal(loaded.originalMin, model.originalMin)
        assert loaded.getMax() == 2.0
        model.save(tmp_path / "spark", layout="spark")
        loaded2 = MinMaxScalerModel.load(str(tmp_path / "spark"))
        np.testing.assert_array_equal(loaded2.originalMax, model.originalMax)
        np.testing.assert_allclose(
            loaded2.transform(data), model.transform(data), atol=0
        )


class TestMaxAbsScaler:
    def test_matches_sklearn(self, data):
        from sklearn.preprocessing import MaxAbsScaler as SkMaxAbs

        from spark_rapids_ml_tpu.models.scaler import MaxAbsScaler

        model = MaxAbsScaler().setInputCol("f").fit(data, num_partitions=3)
        np.testing.assert_allclose(
            model.transform(data), SkMaxAbs().fit_transform(data), atol=1e-12
        )

    def test_zero_feature_passes_through(self, rng):
        from spark_rapids_ml_tpu.models.scaler import MaxAbsScaler

        x = rng.normal(size=(40, 3))
        x[:, 2] = 0.0
        out = MaxAbsScaler().setInputCol("f").fit(x).transform(x)
        np.testing.assert_array_equal(out[:, 2], 0.0)
        assert np.abs(out).max() <= 1.0 + 1e-12

    def test_persistence_roundtrip_both_layouts(self, data, tmp_path):
        from spark_rapids_ml_tpu.models.scaler import (
            MaxAbsScaler,
            MaxAbsScalerModel,
        )

        model = MaxAbsScaler().setInputCol("f").fit(data)
        model.save(tmp_path / "native")
        np.testing.assert_array_equal(
            MaxAbsScalerModel.load(tmp_path / "native").maxAbs, model.maxAbs
        )
        model.save(tmp_path / "spark", layout="spark")
        loaded = MaxAbsScalerModel.load(str(tmp_path / "spark"))
        np.testing.assert_array_equal(loaded.maxAbs, model.maxAbs)


class TestBinarizer:
    def test_matches_sklearn(self, data):
        from sklearn.preprocessing import Binarizer as SkBin

        from spark_rapids_ml_tpu.models.scaler import Binarizer

        out = Binarizer().setInputCol("f").setThreshold(0.5).transform(data)
        np.testing.assert_array_equal(
            out, SkBin(threshold=0.5).transform(data)
        )

    def test_strict_inequality_at_threshold(self):
        from spark_rapids_ml_tpu.models.scaler import Binarizer

        x = np.array([[0.0, 0.5, 1.0]])
        out = Binarizer().setInputCol("f").setThreshold(0.5).transform(x)
        np.testing.assert_array_equal(out, [[0.0, 0.0, 1.0]])  # 0.5 -> 0

    def test_in_pipeline_with_minmax(self, rng):
        from spark_rapids_ml_tpu.models.pipeline import Pipeline
        from spark_rapids_ml_tpu.models.scaler import Binarizer, MinMaxScaler

        x = rng.uniform(-4, 4, size=(120, 5))
        pipe = Pipeline(stages=[
            MinMaxScaler().setInputCol("f").setOutputCol("s"),
            Binarizer().setInputCol("s").setOutputCol("b").setThreshold(0.5),
        ])
        # ndarray containers: each stage transforms the matrix in sequence
        out = pipe.fit(x).transform(x)
        b = out["b"] if hasattr(out, "keys") else out
        vals = np.stack(b.to_numpy()) if hasattr(b, "to_numpy") else np.asarray(b)
        assert set(np.unique(vals)) <= {0.0, 1.0}
        span = x.max(0) - x.min(0)
        want = ((x - x.min(0)) / span > 0.5).astype(float)
        np.testing.assert_array_equal(vals.reshape(want.shape), want)


class TestRobustScaler:
    def test_matches_sklearn_within_sketch_resolution(self, rng):
        from sklearn.preprocessing import RobustScaler as SkRobust

        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        x = rng.normal(size=(20_000, 4)) * np.array([1.0, 5.0, 0.3, 10.0])
        model = (
            RobustScaler()
            .setInputCol("f")
            .setWithCentering(True)
            .fit(x, num_partitions=3)
        )
        sk = SkRobust(with_centering=True).fit(x)
        span = x.max(0) - x.min(0)
        tol = 2 * span / 4096  # the documented value-resolution bound
        np.testing.assert_allclose(model.median, sk.center_, atol=tol.max())
        np.testing.assert_allclose(model.range, sk.scale_, atol=2 * tol.max())
        out = model.transform(x)
        want = sk.transform(x)
        np.testing.assert_allclose(out, want, atol=0.02)

    def test_exact_on_grid_data(self):
        # integer-grid data with bins aligned: quantiles are exact
        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        x = np.tile(np.arange(101, dtype=float)[:, None], (1, 2))  # 0..100
        m = RobustScaler().setInputCol("f").setNumBins(101).fit(x)
        # 25th/75th percentile of 0..100 -> ~25/~75, range ~50; median ~50
        assert abs(m.median[0] - 50.0) <= 1.0
        assert abs(m.range[0] - 50.0) <= 2.0

    def test_spark_defaults_no_centering(self, rng):
        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        x = rng.normal(size=(5_000, 3)) + 100.0
        m = RobustScaler().setInputCol("f").fit(x)
        out = m.transform(x)
        # withCentering=False (Spark default): the offset survives scaling
        assert out.mean() > 10.0

    def test_constant_feature_passes_through(self, rng):
        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        x = rng.normal(size=(200, 3))
        x[:, 1] = 4.2
        out = (
            RobustScaler().setInputCol("f").setWithCentering(True)
            .fit(x).transform(x)
        )
        np.testing.assert_allclose(out[:, 1], 0.0, atol=1e-12)  # centered, /1

    def test_multi_partition_parity(self, rng):
        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        x = rng.uniform(2.0, 9.0, size=(1001, 4))
        m1 = RobustScaler().setInputCol("f").fit(x, num_partitions=1)
        m4 = RobustScaler().setInputCol("f").fit(x, num_partitions=4)
        np.testing.assert_allclose(m1.median, m4.median, atol=1e-12)
        np.testing.assert_allclose(m1.range, m4.range, atol=1e-12)

    def test_bad_quantile_bounds_rejected(self, rng):
        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        with pytest.raises(ValueError, match="lower < upper"):
            RobustScaler().setInputCol("f").setLower(0.8).setUpper(0.2).fit(
                rng.normal(size=(10, 2))
            )

    def test_persistence_roundtrip_both_layouts(self, rng, tmp_path):
        from spark_rapids_ml_tpu.models.scaler import (
            RobustScaler,
            RobustScalerModel,
        )

        x = rng.normal(size=(500, 3))
        model = RobustScaler().setInputCol("f").setWithCentering(True).fit(x)
        model.save(tmp_path / "native")
        loaded = RobustScalerModel.load(tmp_path / "native")
        np.testing.assert_array_equal(loaded.median, model.median)
        assert loaded.getWithCentering() is True
        model.save(tmp_path / "spark", layout="spark")
        loaded2 = RobustScalerModel.load(str(tmp_path / "spark"))
        np.testing.assert_array_equal(loaded2.range, model.range)
        np.testing.assert_allclose(
            loaded2.transform(x), model.transform(x), atol=0
        )


class TestImputer:
    def test_mean_matches_sklearn(self, rng):
        from sklearn.impute import SimpleImputer

        from spark_rapids_ml_tpu.models.scaler import Imputer

        x = rng.normal(size=(400, 5))
        mask = rng.random(x.shape) < 0.15
        x[mask] = np.nan
        model = Imputer().setInputCol("f").fit(x, num_partitions=3)
        out = model.transform(x)
        want = SimpleImputer(strategy="mean").fit_transform(x)
        np.testing.assert_allclose(out, want, atol=1e-10)

    def test_median_matches_sklearn_within_sketch(self, rng):
        from sklearn.impute import SimpleImputer

        from spark_rapids_ml_tpu.models.scaler import Imputer

        x = rng.normal(size=(10_000, 4)) * np.array([1, 5, 0.5, 8])
        mask = rng.random(x.shape) < 0.2
        x[mask] = np.nan
        model = (
            Imputer().setInputCol("f").setStrategy("median")
            .fit(x, num_partitions=4)
        )
        sk = SimpleImputer(strategy="median").fit(x)
        span = np.nanmax(x, 0) - np.nanmin(x, 0)
        np.testing.assert_allclose(
            model.surrogate, sk.statistics_, atol=(2 * span / 4096).max()
        )

    def test_custom_missing_sentinel(self, rng):
        from spark_rapids_ml_tpu.models.scaler import Imputer

        x = rng.normal(size=(200, 3))
        x[x[:, 0] > 1.0, 0] = -999.0
        model = (
            Imputer().setInputCol("f").setMissingValue(-999.0).fit(x)
        )
        out = model.transform(x)
        assert not (out == -999.0).any()
        clean = x[x[:, 0] != -999.0, 0]
        np.testing.assert_allclose(
            model.surrogate[0], clean.mean(), atol=1e-12
        )

    @pytest.mark.parametrize("strategy", ["mean", "median"])
    def test_all_missing_feature_warns_and_zeroes(self, rng, strategy):
        # the median leg also covers the +/-inf bound neutralization that
        # keeps the histogram pass finite for an all-missing feature
        from spark_rapids_ml_tpu.models.scaler import Imputer

        x = rng.normal(size=(50, 3))
        x[:, 1] = np.nan
        with pytest.warns(UserWarning, match="no valid entries"):
            model = Imputer().setInputCol("f").setStrategy(strategy).fit(x)
        assert model.surrogate[1] == 0.0
        assert np.all(np.isfinite(model.surrogate))
        out = model.transform(x)
        np.testing.assert_array_equal(out[:, 1], 0.0)

    def test_mode_strategy_rejected_with_reason(self):
        from spark_rapids_ml_tpu.models.scaler import Imputer

        with pytest.raises(ValueError, match="mode"):
            Imputer().setStrategy("mode")

    def test_multi_partition_parity(self, rng):
        from spark_rapids_ml_tpu.models.scaler import Imputer

        x = rng.normal(size=(999, 4))
        x[rng.random(x.shape) < 0.1] = np.nan
        for strategy in ("mean", "median"):
            m1 = (
                Imputer().setInputCol("f").setStrategy(strategy)
                .fit(x, num_partitions=1)
            )
            m4 = (
                Imputer().setInputCol("f").setStrategy(strategy)
                .fit(x, num_partitions=4)
            )
            np.testing.assert_allclose(m1.surrogate, m4.surrogate, atol=1e-12)

    def test_persistence_native_roundtrip(self, rng, tmp_path):
        from spark_rapids_ml_tpu.models.scaler import Imputer, ImputerModel

        x = rng.normal(size=(100, 3))
        x[0, 0] = np.nan
        model = Imputer().setInputCol("f").fit(x)
        model.save(tmp_path / "imp")
        loaded = ImputerModel.load(tmp_path / "imp")
        np.testing.assert_array_equal(loaded.surrogate, model.surrogate)
        with pytest.raises(NotImplementedError, match="native layout"):
            model.save(tmp_path / "sp", layout="spark")


class TestElementwiseProduct:
    def test_matches_numpy(self, rng):
        from spark_rapids_ml_tpu.models.scaler import ElementwiseProduct

        x = rng.normal(size=(100, 4))
        w = np.array([0.0, 1.0, -2.0, 0.5])
        out = (
            ElementwiseProduct().setInputCol("f").setScalingVec(w).transform(x)
        )
        np.testing.assert_array_equal(out, x * w)

    def test_dim_mismatch_and_unset_rejected(self, rng):
        from spark_rapids_ml_tpu.models.scaler import ElementwiseProduct

        x = rng.normal(size=(10, 3))
        with pytest.raises(ValueError, match="must be set"):
            ElementwiseProduct().setInputCol("f").transform(x)
        with pytest.raises(ValueError, match="2 entries"):
            ElementwiseProduct().setInputCol("f").setScalingVec(
                [1.0, 2.0]
            ).transform(x)


class TestVectorSlicer:
    def test_selects_in_given_order(self, rng):
        from spark_rapids_ml_tpu.models.scaler import VectorSlicer

        x = rng.normal(size=(50, 5))
        out = (
            VectorSlicer().setInputCol("f").setIndices([3, 0]).transform(x)
        )
        np.testing.assert_array_equal(out, x[:, [3, 0]])

    def test_validation(self, rng):
        from spark_rapids_ml_tpu.models.scaler import VectorSlicer

        x = rng.normal(size=(10, 3))
        with pytest.raises(ValueError, match="unique"):
            VectorSlicer().setIndices([1, 1])
        with pytest.raises(ValueError, match="non-negative"):
            VectorSlicer().setIndices([-1])
        with pytest.raises(ValueError, match="out of bounds"):
            VectorSlicer().setInputCol("f").setIndices([7]).transform(x)
        with pytest.raises(ValueError, match="must be set"):
            VectorSlicer().setInputCol("f").transform(x)


class TestDCT:
    def test_matches_scipy_ortho(self, rng):
        from scipy.fft import dct as scipy_dct

        from spark_rapids_ml_tpu.models.scaler import DCT

        x = rng.normal(size=(50, 16))
        out = DCT().setInputCol("f").transform(x)
        want = scipy_dct(x, type=2, norm="ortho", axis=1)
        np.testing.assert_allclose(out, want, atol=1e-10)

    def test_inverse_round_trips(self, rng):
        from spark_rapids_ml_tpu.models.scaler import DCT

        x = rng.normal(size=(40, 9))
        fwd = DCT().setInputCol("f").transform(x)
        back = DCT().setInputCol("f").setInverse(True).transform(fwd)
        np.testing.assert_allclose(back, x, atol=1e-10)

    def test_basis_is_orthonormal(self):
        from spark_rapids_ml_tpu.ops.scaler import dct2_matrix

        b = np.asarray(dct2_matrix(12))
        np.testing.assert_allclose(b @ b.T, np.eye(12), atol=1e-12)

    def test_integer_input_promotes(self):
        from spark_rapids_ml_tpu.models.scaler import DCT

        xi = np.arange(24).reshape(3, 8)
        out = DCT().setInputCol("f").transform(xi)
        from scipy.fft import dct as scipy_dct

        np.testing.assert_allclose(
            out, scipy_dct(xi.astype(float), type=2, norm="ortho", axis=1),
            atol=1e-10,
        )


class TestPolynomialExpansion:
    def test_spark_documented_ordering(self):
        # the MLlib doc example: degree 2 on (x, y) -> (x, x*x, y, x*y, y*y)
        from spark_rapids_ml_tpu.models.scaler import PolynomialExpansion

        out = (
            PolynomialExpansion().setInputCol("f").setDegree(2)
            .transform(np.array([[2.0, 3.0]]))
        )
        np.testing.assert_array_equal(out, [[2, 4, 3, 6, 9]])

    def test_monomial_set_matches_sklearn(self, rng):
        from sklearn.preprocessing import PolynomialFeatures

        from spark_rapids_ml_tpu.models.scaler import PolynomialExpansion

        x = rng.normal(size=(50, 4))
        ours = (
            PolynomialExpansion().setInputCol("f").setDegree(3).transform(x)
        )
        sk = PolynomialFeatures(degree=3, include_bias=False).fit_transform(x)
        assert ours.shape == sk.shape
        # same monomial VALUES per row (ordering conventions differ)
        np.testing.assert_allclose(
            np.sort(ours, axis=1), np.sort(sk, axis=1), atol=1e-9
        )

    def test_width_and_cap(self, rng):
        import math

        from spark_rapids_ml_tpu.models.scaler import PolynomialExpansion

        x = rng.normal(size=(10, 6))
        out = PolynomialExpansion().setInputCol("f").setDegree(2).transform(x)
        assert out.shape[1] == math.comb(8, 2) - 1  # C(n+d, d) - 1 = 27
        with pytest.raises(ValueError, match="cap is 100000"):
            PolynomialExpansion().setInputCol("f").setDegree(5).transform(
                rng.normal(size=(2, 64))
            )
        with pytest.raises(ValueError, match="degree"):
            PolynomialExpansion().setDegree(0)

    def test_degree_one_is_identity(self, rng):
        from spark_rapids_ml_tpu.models.scaler import PolynomialExpansion

        x = rng.normal(size=(20, 5))
        np.testing.assert_array_equal(
            PolynomialExpansion().setInputCol("f").setDegree(1).transform(x), x
        )

    def test_wide_input_no_recursion_limit(self, rng):
        from spark_rapids_ml_tpu.models.scaler import PolynomialExpansion

        x = rng.normal(size=(3, 1500))
        out = PolynomialExpansion().setInputCol("f").setDegree(1).transform(x)
        np.testing.assert_array_equal(out, x)
