"""StandardScaler / Normalizer tests — differential vs scikit-learn."""

import numpy as np
import pytest
from sklearn.preprocessing import StandardScaler as SkScaler
from sklearn.preprocessing import normalize as sk_normalize

from spark_rapids_ml_tpu.models.scaler import Normalizer, StandardScaler, StandardScalerModel


@pytest.fixture
def data(rng):
    x = rng.normal(size=(300, 12)) * rng.uniform(0.1, 5.0, size=12)[None, :]
    return x + rng.uniform(-3, 3, size=12)[None, :]


class TestStandardScaler:
    def test_moments_match_numpy(self, data):
        model = StandardScaler().setInputCol("f").fit(data, num_partitions=3)
        np.testing.assert_allclose(model.mean, data.mean(axis=0), rtol=1e-10)
        np.testing.assert_allclose(model.std, data.std(axis=0, ddof=1), rtol=1e-10)

    def test_defaults_match_spark(self, data):
        """Spark defaults: withStd=True, withMean=False."""
        model = StandardScaler().setInputCol("f").fit(data)
        out = model.transform(data)
        np.testing.assert_allclose(out, data / data.std(axis=0, ddof=1), rtol=1e-9)

    def test_with_mean_matches_sklearn(self, data):
        model = (
            StandardScaler().setInputCol("f").setWithMean(True).fit(data)
        )
        out = model.transform(data)
        want = SkScaler().fit_transform(data) * np.sqrt((len(data) - 1) / len(data))
        # sklearn uses population std; rescale to sample-std semantics
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_constant_feature_passthrough(self, rng):
        x = rng.normal(size=(50, 3))
        x[:, 1] = 7.0  # zero variance
        model = StandardScaler().setInputCol("f").setWithMean(True).fit(x)
        out = model.transform(x)
        np.testing.assert_allclose(out[:, 1], 0.0, atol=1e-12)  # centered, unscaled
        assert np.all(np.isfinite(out))

    def test_persistence_roundtrip(self, data, tmp_path):
        model = StandardScaler().setInputCol("f").setWithMean(True).fit(data)
        model.save(tmp_path / "s")
        loaded = StandardScalerModel.load(tmp_path / "s")
        np.testing.assert_array_equal(loaded.mean, model.mean)
        assert loaded.getWithMean() is True
        np.testing.assert_allclose(loaded.transform(data), model.transform(data))


class TestNormalizer:
    @pytest.mark.parametrize("p", [1.0, 2.0, 3.0])
    def test_matches_sklearn(self, data, p):
        out = Normalizer().setInputCol("f").setP(p).transform(data)
        want = sk_normalize(data, norm={1.0: "l1", 2.0: "l2"}.get(p, "l2"))
        if p in (1.0, 2.0):
            np.testing.assert_allclose(out, want, rtol=1e-6)
        norms = np.sum(np.abs(out) ** p, axis=1) ** (1 / p)
        np.testing.assert_allclose(norms, 1.0, rtol=1e-6)

    def test_inf_norm(self, data):
        out = Normalizer().setInputCol("f").setP(float("inf")).transform(data)
        np.testing.assert_allclose(np.max(np.abs(out), axis=1), 1.0, rtol=1e-9)

    def test_zero_row_untouched(self):
        x = np.array([[0.0, 0.0], [3.0, 4.0]])
        out = Normalizer().setInputCol("f").transform(x)
        np.testing.assert_array_equal(out[0], [0.0, 0.0])
        np.testing.assert_allclose(out[1], [0.6, 0.8], rtol=1e-9)
