"""Tokenizer / HashingTF / IDF — Spark's text trio on host containers."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.feature import IDF, HashingTF, IDFModel, Tokenizer

pd = pytest.importorskip("pandas")


@pytest.fixture()
def docs():
    return pd.DataFrame(
        {
            "text": [
                "TPU kernels are Fast",
                "fast kernels fast pipelines",
                "spark pipelines on tpu",
            ]
        }
    )


def test_tokenizer_lowercases_and_splits(docs):
    out = Tokenizer().setInputCol("text").setOutputCol("words").transform(docs)
    assert list(out["words"][0]) == ["tpu", "kernels", "are", "fast"]
    assert list(out["words"][1]) == ["fast", "kernels", "fast", "pipelines"]


def test_hashing_tf_counts_and_binary(docs):
    words = Tokenizer().setInputCol("text").setOutputCol("words").transform(docs)
    tf = (
        HashingTF().setInputCol("words").setOutputCol("tf")
        .setNumFeatures(64).transform(words)
    )
    mat = np.stack(tf["tf"])
    assert mat.shape == (3, 64)
    # doc 1 has 'fast' twice → some bucket holds 2; counts sum to token counts
    np.testing.assert_array_equal(mat.sum(1), [4, 4, 4])
    assert mat[1].max() == 2.0
    binary = (
        HashingTF().setInputCol("words").setOutputCol("tf")
        .setNumFeatures(64).setBinary(True).transform(words)
    )
    assert np.stack(binary["tf"])[1].max() == 1.0


def test_idf_matches_spark_formula(docs):
    words = Tokenizer().setInputCol("text").setOutputCol("words").transform(docs)
    tf = (
        HashingTF().setInputCol("words").setOutputCol("tf")
        .setNumFeatures(32).transform(words)
    )
    model = IDF().setInputCol("tf").setOutputCol("tfidf").fit(tf)
    mat = np.stack(tf["tf"])
    df = (mat > 0).sum(0)
    np.testing.assert_allclose(model.idf, np.log((3 + 1) / (df + 1)))
    out = model.transform(tf)
    np.testing.assert_allclose(
        np.stack(out["tfidf"]), mat * model.idf[None, :]
    )
    assert model.numDocs == 3


def test_idf_min_doc_freq_and_partition_invariance(docs):
    words = Tokenizer().setInputCol("text").setOutputCol("words").transform(docs)
    tf = (
        HashingTF().setInputCol("words").setOutputCol("tf")
        .setNumFeatures(32).transform(words)
    )
    mat = np.stack(tf["tf"])
    m = IDF().setMinDocFreq(2).setInputCol("tf").fit(tf)
    df = (mat > 0).sum(0)
    assert (m.idf[df < 2] == 0).all()
    assert (m.idf[df >= 2] != 0).all()
    # monoid: partition count cannot change the model
    m4 = IDF().setMinDocFreq(2).fit(mat)
    m1 = IDF().setMinDocFreq(2).fit(mat, num_partitions=3)
    np.testing.assert_allclose(m4.idf, m1.idf)


def test_text_pipeline_and_persistence(tmp_path, docs):
    from spark_rapids_ml_tpu.models.pipeline import Pipeline

    pipe = Pipeline(
        stages=[
            Tokenizer().setInputCol("text").setOutputCol("words"),
            HashingTF().setInputCol("words").setOutputCol("tf").setNumFeatures(64),
            IDF().setInputCol("tf").setOutputCol("tfidf"),
        ]
    )
    model = pipe.fit(docs)
    out = model.transform(docs)
    assert np.stack(out["tfidf"]).shape == (3, 64)
    idf_model = model.stages[-1]
    idf_model.save(str(tmp_path / "idf"))
    loaded = IDFModel.load(str(tmp_path / "idf"))
    np.testing.assert_allclose(loaded.idf, idf_model.idf)


def test_guards_and_defaults(docs):
    # default output columns exist (the package-wide contract)
    out = Tokenizer().setInputCol("text").transform(docs)
    assert "tokens" in out.columns
    # raw-string input (forgot the Tokenizer) raises instead of hashing chars
    with pytest.raises(TypeError, match="run Tokenizer first"):
        HashingTF().setInputCol("text").setNumFeatures(8).transform(docs)
    # dense-output guard names the knob
    big = pd.DataFrame({"w": [["a"]] * 20000})
    with pytest.raises(ValueError, match="setNumFeatures"):
        HashingTF().setInputCol("w").setNumFeatures(1 << 18).transform(big)
