"""IsotonicRegression — exact sklearn differential (same L2 PAV)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.regression import (
    IsotonicRegression,
    IsotonicRegressionModel,
)


@pytest.fixture()
def noisy_monotone():
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 10, size=400)
    y = np.log1p(x) * 3 + rng.normal(scale=0.4, size=400)
    return x[:, None], y


def test_matches_sklearn_exactly(noisy_monotone):
    sk_iso = pytest.importorskip("sklearn.isotonic")
    x, y = noisy_monotone
    m = IsotonicRegression().fit((x, y))
    sk = sk_iso.IsotonicRegression(out_of_bounds="clip").fit(x[:, 0], y)
    grid = np.linspace(-1, 11, 300)[:, None]
    np.testing.assert_allclose(
        m._predict_matrix(grid), sk.predict(grid[:, 0]), atol=1e-9
    )


def test_antitonic_and_feature_index(noisy_monotone):
    sk_iso = pytest.importorskip("sklearn.isotonic")
    x, y = noisy_monotone
    x2 = np.concatenate([np.zeros_like(x), x], axis=1)
    m = (
        IsotonicRegression().setIsotonic(False).setFeatureIndex(1)
        .fit((x2, -y))
    )
    sk = sk_iso.IsotonicRegression(
        increasing=False, out_of_bounds="clip"
    ).fit(x[:, 0], -y)
    grid = np.linspace(0, 10, 200)
    grid2 = np.stack([np.zeros_like(grid), grid], axis=1)
    np.testing.assert_allclose(
        m._predict_matrix(grid2), sk.predict(grid), atol=1e-9
    )


def test_weighted_equals_duplication(noisy_monotone):
    x, y = noisy_monotone
    dup = np.arange(0, len(x), 3)
    w = np.ones(len(x)); w[dup] = 2.0
    m_w = IsotonicRegression().fit((x, y, w))
    m_d = IsotonicRegression().fit(
        (np.concatenate([x, x[dup]]), np.concatenate([y, y[dup]]))
    )
    grid = np.linspace(0, 10, 100)[:, None]
    np.testing.assert_allclose(
        m_w._predict_matrix(grid), m_d._predict_matrix(grid), atol=1e-9
    )


def test_clamping_and_persistence(tmp_path, noisy_monotone):
    x, y = noisy_monotone
    m = IsotonicRegression().fit((x, y))
    lo = m.predict(-100.0)
    hi = m.predict(100.0)
    assert lo == m.predictions[0] and hi == m.predictions[-1]
    assert np.all(np.diff(m.predictions) >= -1e-12)  # monotone
    path = str(tmp_path / "iso")
    m.save(path)
    loaded = IsotonicRegressionModel.load(path)
    np.testing.assert_allclose(loaded.boundaries, m.boundaries)
    np.testing.assert_allclose(
        loaded._predict_matrix(x[:50]), m._predict_matrix(x[:50])
    )


def test_tied_feature_values_pool_before_pav():
    """Duplicate x pool into one weighted point BEFORE PAV — the isotonic
    optimum (sklearn agrees); post-hoc averaging of separately-fitted tie
    points would not be the L2 minimizer."""
    sk_iso = pytest.importorskip("sklearn.isotonic")
    x = np.array([[0.0], [0.0], [1.0]])
    y = np.array([0.0, 10.0, 2.0])
    m = IsotonicRegression().fit((x, y))
    sk = sk_iso.IsotonicRegression(out_of_bounds="clip").fit(x[:, 0], y)
    np.testing.assert_allclose(
        m._predict_matrix(np.array([[0.0], [0.5], [1.0]])),
        sk.predict([0.0, 0.5, 1.0]),
        atol=1e-12,
    )
    # heavily-tied calibration-style data, exact sklearn agreement
    rng = np.random.default_rng(5)
    xt = rng.integers(0, 12, size=600).astype(float)
    yt = xt * 0.5 + rng.normal(scale=1.0, size=600)
    wt = rng.uniform(0.5, 2.0, size=600)
    m2 = IsotonicRegression().fit((xt[:, None], yt, wt))
    sk2 = sk_iso.IsotonicRegression(out_of_bounds="clip").fit(
        xt, yt, sample_weight=wt
    )
    grid = np.linspace(-1, 13, 200)
    np.testing.assert_allclose(
        m2._predict_matrix(grid[:, None]), sk2.predict(grid), atol=1e-9
    )


def test_negative_feature_index_rejected():
    x = np.random.default_rng(0).normal(size=(20, 3))
    y = x[:, 0]
    with pytest.raises(ValueError, match="featureIndex"):
        IsotonicRegression(featureIndex=-1).fit((x, y))
