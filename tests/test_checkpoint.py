"""Mid-training checkpoint/resume tests — the subsystem the reference lacks
(model persistence only, SURVEY.md §5)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.kmeans import KMeans
from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer


@pytest.fixture
def blobs(rng):
    centers = np.array([[0.0, 0.0, 0.0], [10.0, 10.0, 0.0], [-10.0, 5.0, 5.0]])
    x = np.concatenate([c + rng.normal(scale=0.5, size=(80, 3)) for c in centers])
    rng.shuffle(x)
    return x


class TestCheckpointer:
    def test_save_load_roundtrip(self, tmp_path):
        ckpt = TrainingCheckpointer(tmp_path)
        a = np.arange(12.0).reshape(3, 4)
        ckpt.save(0, {"centers": a}, {"cost": 1.5})
        step, arrays, state = ckpt.latest()
        assert step == 0
        np.testing.assert_array_equal(arrays["centers"], a)
        assert state["cost"] == 1.5

    def test_latest_picks_newest(self, tmp_path):
        ckpt = TrainingCheckpointer(tmp_path, keep=5)
        for s in range(4):
            ckpt.save(s, {"v": np.asarray([s])})
        step, arrays, _ = ckpt.latest()
        assert step == 3
        assert arrays["v"][0] == 3

    def test_retention(self, tmp_path):
        ckpt = TrainingCheckpointer(tmp_path, keep=2)
        for s in range(5):
            ckpt.save(s, {"v": np.asarray([s])})
        assert ckpt.steps() == [3, 4]

    def test_empty_dir_returns_none(self, tmp_path):
        assert TrainingCheckpointer(tmp_path).latest() is None

    def test_partial_write_is_invisible(self, tmp_path):
        """A torn write (tmp dir left behind) must not be seen as a state."""
        ckpt = TrainingCheckpointer(tmp_path)
        ckpt.save(1, {"v": np.asarray([1.0])})
        (tmp_path / ".tmp-2").mkdir()  # simulated mid-crash leftover
        step, _, _ = ckpt.latest()
        assert step == 1

    def test_corrupt_step_skipped(self, tmp_path):
        ckpt = TrainingCheckpointer(tmp_path)
        ckpt.save(1, {"v": np.asarray([1.0])})
        bad = tmp_path / "step-000000002"
        bad.mkdir()
        (bad / "arrays.npz").write_bytes(b"not a zip")
        step, arrays, _ = ckpt.latest()
        assert step == 1 and arrays["v"][0] == 1.0

    def test_stray_tmp_ignored_and_swept_on_next_save(self, tmp_path):
        """A writer killed mid-save leaves .tmp-<other-step> orphans: they
        must never count as state, and the NEXT save sweeps them all."""
        ckpt = TrainingCheckpointer(tmp_path)
        ckpt.save(3, {"v": np.asarray([3.0])})
        for stray in (".tmp-1", ".tmp-7"):
            d = tmp_path / stray
            d.mkdir()
            (d / "arrays.npz").write_bytes(b"torn")
        step, arrays, _ = ckpt.latest()
        assert step == 3 and arrays["v"][0] == 3.0
        ckpt.save(4, {"v": np.asarray([4.0])})
        leftovers = [p.name for p in tmp_path.iterdir() if p.name.startswith(".tmp-")]
        assert leftovers == []
        step, arrays, _ = ckpt.latest()
        assert step == 4 and arrays["v"][0] == 4.0

    def test_resume_after_replace_yields_newest_step(self, tmp_path):
        """os.replace publication: once save() returns, a fresh reader (a
        resumed process) sees exactly the newest step."""
        ckpt = TrainingCheckpointer(tmp_path, keep=3)
        for s in (2, 5, 9):
            ckpt.save(s, {"v": np.asarray([float(s)])}, {"chunks": s})
        fresh = TrainingCheckpointer(tmp_path, keep=3)
        step, arrays, state = fresh.latest()
        assert step == 9
        assert arrays["v"][0] == 9.0
        assert state["chunks"] == 9


class TestKMeansResume:
    def test_resume_matches_uninterrupted(self, blobs, tmp_path):
        """Interrupt after 2 iterations, resume from the checkpoint directory:
        the final centers must equal an uninterrupted run's."""
        mk = lambda: KMeans().setInputCol("f").setK(3).setSeed(1).setMaxIter(12)
        full = mk().fit(blobs)

        mk().setMaxIter(2).fit(blobs, checkpoint_dir=str(tmp_path / "ck"))
        resumed = mk().fit(blobs, checkpoint_dir=str(tmp_path / "ck"))

        c_full = full.clusterCenters[np.lexsort(full.clusterCenters.T)]
        c_res = resumed.clusterCenters[np.lexsort(resumed.clusterCenters.T)]
        np.testing.assert_allclose(c_res, c_full, atol=1e-6)

    def test_resume_skips_completed_iterations(self, blobs, tmp_path, monkeypatch):
        """Resuming a converged run must not re-run init (no re-seeding)."""
        mk = lambda: KMeans().setInputCol("f").setK(3).setSeed(1).setMaxIter(12)
        mk().fit(blobs, checkpoint_dir=str(tmp_path / "ck"))

        est = mk()
        def boom(*a, **k):
            raise AssertionError("init must not run on resume")
        monkeypatch.setattr(est, "_init_centers", boom)
        model = est.fit(blobs, checkpoint_dir=str(tmp_path / "ck"))
        assert model.clusterCenters.shape == (3, 3)

    def test_resume_with_different_k_rejected(self, blobs, tmp_path):
        KMeans().setInputCol("f").setK(3).setSeed(1).setMaxIter(3).setTol(0.0).fit(
            blobs, checkpoint_dir=str(tmp_path / "ck")
        )
        with pytest.raises(ValueError, match="3 centers but k=5"):
            KMeans().setInputCol("f").setK(5).setSeed(1).fit(
                blobs, checkpoint_dir=str(tmp_path / "ck")
            )

    def test_checkpoint_every(self, rng, tmp_path):
        # unstructured data: Lloyd keeps moving, so no early convergence break
        x = rng.uniform(size=(400, 5))
        KMeans().setInputCol("f").setK(8).setSeed(1).setMaxIter(6).setTol(0.0).fit(
            x, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=3
        )
        steps = TrainingCheckpointer(tmp_path / "ck").steps()
        assert steps == [2, 5]
