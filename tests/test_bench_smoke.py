"""Tier-1 guard for the bench harness: ``bench.py --smoke`` must keep
producing its JSON contract — including the ``streamed_fit_rows_per_s``
out-of-core metric — on the CPU backend, and appending a ``perf_ledger``
entry that the regression sentinel accepts (ISSUE 5).

Runs the bench as a subprocess (it owns platform/x64 setup) with the shared
compilation cache so repeat runs stay cheap.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_json_contract(tmp_path):
    ledger = str(tmp_path / "PERF_LEDGER.jsonl")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR="/tmp/jax_test_cache",
        TPU_ML_PERF_LEDGER_PATH=ledger,
        TPU_ML_PERF_SENTINEL="1",  # the bench gates itself on the sentinel
    )
    env.pop("TPU_ML_FAULT_PLAN", None)  # the zero-fault assertion below
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]

    json_lines = [
        ln for ln in proc.stdout.splitlines() if ln.startswith("{")
    ]
    assert json_lines, f"no JSON line in bench output:\n{proc.stdout[-2000:]}"
    data = json.loads(json_lines[-1])

    assert data["value"] > 0
    assert data["unit"]
    assert "SMOKE" in data["metric"]

    extras = {m["metric"]: m for m in data["extra_metrics"]}
    assert "streamed_fit_rows_per_s" in extras, sorted(extras)
    sf = extras["streamed_fit_rows_per_s"]
    assert sf["unit"] == "rows/s"
    assert sf["value"] > 0
    # pipeline introspection must ride along so perf regressions in the
    # overlap machinery are visible in the bench record
    assert "overlapped_dispatches" in sf
    # flight-recorder evidence (ISSUE 4): the recorded H2D<->compute
    # overlap fraction of the timed streamed reps — structural only, no
    # absolute-time assertions (wall-clock is host-load-dependent)
    assert "overlap_fraction" in sf
    if sf["overlap_fraction"] is not None:
        assert 0.0 <= sf["overlap_fraction"] <= 1.0

    # the telemetry snapshot makes every BENCH_r* round phase-attributable
    # (ISSUE 2): full registry state keyed counters/gauges/spans/histograms
    tel = data["telemetry"]
    assert set(tel) == {"counters", "gauges", "spans", "histograms"}
    # the bench's streamed-fit stage ran through the instrumented pipeline,
    # so its spans must appear in the snapshot (reset_metrics in
    # _paired_slope clears earlier stages; the streamed-fit stage and the
    # DataFrame fit run after the last reset)
    assert any(
        phase.startswith(("fold.", "ingest.")) for phase in tel["spans"]
    ), sorted(tel["spans"])
    # no TPU_ML_FAULT_PLAN is set, so the resilience layer must be inert:
    # zero synthetic faults fired during the bench
    injected = [k for k in tel["counters"] if k.startswith("fault.injected")]
    assert injected == [], injected

    # the live-exporter stage (ISSUE 8): the bench scraped its own /healthz
    # (must be 200 on this healthy process) and /metrics (must contain the
    # streamed-fit counter families) over real HTTP on an ephemeral port —
    # a hard contract in --smoke, so rc=0 above already proves the scrape
    # succeeded; the evidence block records what it saw
    hl = data["health"]
    assert hl["healthz"] == 200
    assert hl["state"] == "OK"
    assert hl["components"].get("transport") == "OK"
    assert hl["components"].get("stream") == "OK"
    assert hl["port"] > 0
    assert hl["metrics_scrape_bytes"] > 0
    # the monitor's poll published its gauges into the same registry the
    # snapshot serialized
    assert "health.state{component=overall}" in data["telemetry"]["gauges"]

    # the run appended one perf-ledger entry holding every emitted metric
    # plus the analytical cost-model numbers (ISSUE 5)
    with open(ledger, encoding="utf-8") as f:
        entries = [json.loads(ln) for ln in f if ln.strip()]
    assert len(entries) == 1
    entry = entries[0]
    assert entry["type"] == "perf_ledger"
    assert entry["smoke"] is True
    assert data["metric"] in entry["metrics"]
    assert "streamed_fit_rows_per_s" in entry["metrics"]
    assert entry["metrics"]["streamed_fit_rows_per_s"]["unit"] == "rows/s"
    assert "analytical_flops" in entry["cost_model"]
    # the health verdict stamps the ledger so the sentinel's reader can
    # tell environment problems from genuine regressions (ISSUE 8)
    assert entry["health_state"] == "OK"
    # TPU_ML_PERF_SENTINEL=1 already ran the gate in-process (exit 0 above
    # proves a fresh ledger passes); the standalone CLI agrees
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "perf_sentinel.py"),
            ledger,
            "--strict",
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
