"""Distributed-layer tests on the 8-device virtual CPU mesh.

These validate the SPMD paths the reference never had: psum Gram allreduce,
the ring feature-sharded Gram, and the end-to-end sharded fit — all compiled
and executed over a real (virtual-device) Mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.parallel import gram as G
from spark_rapids_ml_tpu.parallel import mesh as M


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return M.create_mesh(data=4, feat=2)


@pytest.fixture
def x(rng):
    return rng.normal(size=(256, 32))


class TestShardedGram:
    def test_matches_local(self, mesh8, x, rng):
        xs = jax.device_put(x, M.data_sharding(mesh8))
        stats = G.sharded_gram_stats(xs, mesh8)
        np.testing.assert_allclose(np.asarray(stats.xtx), x.T @ x, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(stats.col_sum), x.sum(0), rtol=1e-10)
        assert int(stats.count) == 256

    def test_jit_compiles_once(self, mesh8, x):
        xs = jax.device_put(x, M.data_sharding(mesh8))
        fn = jax.jit(lambda a: G.sharded_gram_stats(a, mesh8))
        s1 = fn(xs)
        np.testing.assert_allclose(np.asarray(s1.xtx), x.T @ x, rtol=1e-10)


class TestRingGram:
    def test_matches_local(self, mesh8, x):
        xs = jax.device_put(x, M.data_sharding(mesh8, feature_sharded=True))
        g, col_sum, count = G.ring_gram(xs, mesh8)
        np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(col_sum), x.sum(0), rtol=1e-10)
        assert int(count) == 256

    def test_gram_output_is_feature_sharded(self, mesh8, x):
        xs = jax.device_put(x, M.data_sharding(mesh8, feature_sharded=True))
        g, _, _ = G.ring_gram(xs, mesh8)
        # block-rows live on the feat axis: each shard is [n/feat, n]
        shard_shapes = {s.data.shape for s in g.addressable_shards}
        assert shard_shapes == {(16, 32)}

    def test_larger_feat_axis(self, x):
        mesh = M.create_mesh(data=2, feat=4)
        xs = jax.device_put(x, M.data_sharding(mesh, feature_sharded=True))
        g, _, _ = G.ring_gram(xs, mesh)
        np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-10)


class TestDistributedFit:
    @pytest.mark.parametrize("feature_sharded", [False, True])
    @pytest.mark.parametrize("mean_centering", [False, True])
    def test_matches_single_device(self, mesh8, x, feature_sharded, mean_centering):
        fit = G.make_distributed_fit(
            mesh8, 5, mean_centering=mean_centering, feature_sharded=feature_sharded
        )
        pc, ev = fit(jnp.asarray(x))
        pc_ref, ev_ref = L.pca_fit_local(jnp.asarray(x), 5, mean_centering=mean_centering)
        np.testing.assert_allclose(np.asarray(pc), np.asarray(pc_ref), atol=1e-8)
        np.testing.assert_allclose(np.asarray(ev), np.asarray(ev_ref), atol=1e-10)

    def test_outputs_replicated(self, mesh8, x):
        fit = G.make_distributed_fit(mesh8, 3)
        pc, _ = fit(jnp.asarray(x))
        assert pc.sharding.is_fully_replicated

    def test_randomized_solver_distributed(self, mesh8, rng):
        """Sharded Gram + randomized Rayleigh–Ritz as one SPMD program."""
        base = rng.normal(size=(256, 4))
        x = base @ rng.normal(size=(4, 32)) + 0.01 * rng.normal(size=(256, 32))
        fit = G.make_distributed_fit(mesh8, 3, solver="randomized")
        pc, ev = fit(jnp.asarray(x))
        pc_ref, _ = L.pca_fit_local(jnp.asarray(x), 3)
        np.testing.assert_allclose(
            np.abs(np.asarray(pc)), np.abs(np.asarray(pc_ref)), atol=1e-6
        )
        assert pc.sharding.is_fully_replicated and ev.shape == (3,)


class TestMeshHelpers:
    def test_factor_mesh(self):
        assert M.factor_mesh(8) == (4, 2)
        assert M.factor_mesh(16) == (4, 4)
        assert M.factor_mesh(1) == (1, 1)
        assert M.factor_mesh(6) == (3, 2)

    def test_create_mesh_validates(self):
        with pytest.raises(ValueError):
            M.create_mesh(data=16, feat=2)

    def test_hybrid_mesh_falls_back_single_slice(self):
        # CPU devices report no slice topology → flat (data, feat) mesh
        mesh = M.create_hybrid_mesh(feat=2)
        assert mesh.axis_names == (M.DATA_AXIS, M.FEAT_AXIS)
        assert mesh.shape[M.FEAT_AXIS] == 2

    def test_shard_map_shim_decorator_form(self, mesh8):
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        @M.shard_map(mesh=mesh8, in_specs=P(M.DATA_AXIS), out_specs=P(), check_rep=False)
        def total(v):
            return lax.psum(v.sum(), M.DATA_AXIS)

        x = np.arange(16.0)
        assert float(total(x)) == x.sum()
