"""Distributed-layer tests on the 8-device virtual CPU mesh.

These validate the SPMD paths the reference never had: psum Gram allreduce,
the ring feature-sharded Gram, and the end-to-end sharded fit — all compiled
and executed over a real (virtual-device) Mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.parallel import gram as G
from spark_rapids_ml_tpu.parallel import mesh as M


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return M.create_mesh(data=4, feat=2)


@pytest.fixture
def x(rng):
    return rng.normal(size=(256, 32))


class TestShardedGram:
    def test_matches_local(self, mesh8, x, rng):
        xs = jax.device_put(x, M.data_sharding(mesh8))
        stats = G.sharded_gram_stats(xs, mesh8)
        np.testing.assert_allclose(np.asarray(stats.xtx), x.T @ x, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(stats.col_sum), x.sum(0), rtol=1e-10)
        assert int(stats.count) == 256

    def test_jit_compiles_once(self, mesh8, x):
        xs = jax.device_put(x, M.data_sharding(mesh8))
        fn = jax.jit(lambda a: G.sharded_gram_stats(a, mesh8))
        s1 = fn(xs)
        np.testing.assert_allclose(np.asarray(s1.xtx), x.T @ x, rtol=1e-10)


class TestRingGram:
    def test_matches_local(self, mesh8, x):
        xs = jax.device_put(x, M.data_sharding(mesh8, feature_sharded=True))
        g, col_sum, count = G.ring_gram(xs, mesh8)
        np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(col_sum), x.sum(0), rtol=1e-10)
        assert int(count) == 256

    def test_gram_output_is_feature_sharded(self, mesh8, x):
        xs = jax.device_put(x, M.data_sharding(mesh8, feature_sharded=True))
        g, _, _ = G.ring_gram(xs, mesh8)
        # block-rows live on the feat axis: each shard is [n/feat, n]
        shard_shapes = {s.data.shape for s in g.addressable_shards}
        assert shard_shapes == {(16, 32)}

    def test_larger_feat_axis(self, x):
        mesh = M.create_mesh(data=2, feat=4)
        xs = jax.device_put(x, M.data_sharding(mesh, feature_sharded=True))
        g, _, _ = G.ring_gram(xs, mesh)
        np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-10)


class TestDistributedFit:
    @pytest.mark.parametrize("feature_sharded", [False, True])
    @pytest.mark.parametrize("mean_centering", [False, True])
    def test_matches_single_device(self, mesh8, x, feature_sharded, mean_centering):
        fit = G.make_distributed_fit(
            mesh8, 5, mean_centering=mean_centering, feature_sharded=feature_sharded
        )
        pc, ev = fit(jnp.asarray(x))
        pc_ref, ev_ref = L.pca_fit_local(jnp.asarray(x), 5, mean_centering=mean_centering)
        np.testing.assert_allclose(np.asarray(pc), np.asarray(pc_ref), atol=1e-8)
        np.testing.assert_allclose(np.asarray(ev), np.asarray(ev_ref), atol=1e-10)

    def test_outputs_replicated(self, mesh8, x):
        fit = G.make_distributed_fit(mesh8, 3)
        pc, _ = fit(jnp.asarray(x))
        assert pc.sharding.is_fully_replicated

    def test_randomized_solver_distributed(self, mesh8, rng):
        """Sharded Gram + randomized Rayleigh–Ritz as one SPMD program."""
        base = rng.normal(size=(256, 4))
        x = base @ rng.normal(size=(4, 32)) + 0.01 * rng.normal(size=(256, 32))
        fit = G.make_distributed_fit(mesh8, 3, solver="randomized")
        pc, ev = fit(jnp.asarray(x))
        pc_ref, _ = L.pca_fit_local(jnp.asarray(x), 3)
        np.testing.assert_allclose(
            np.abs(np.asarray(pc)), np.abs(np.asarray(pc_ref)), atol=1e-6
        )
        assert pc.sharding.is_fully_replicated and ev.shape == (3,)


class TestMeshHelpers:
    def test_factor_mesh(self):
        assert M.factor_mesh(8) == (4, 2)
        assert M.factor_mesh(16) == (4, 4)
        assert M.factor_mesh(1) == (1, 1)
        assert M.factor_mesh(6) == (3, 2)

    def test_create_mesh_validates(self):
        with pytest.raises(ValueError):
            M.create_mesh(data=16, feat=2)

    def test_hybrid_mesh_falls_back_single_slice(self):
        # CPU devices report no slice topology → flat (data, feat) mesh
        mesh = M.create_hybrid_mesh(feat=2)
        assert mesh.axis_names == (M.DATA_AXIS, M.FEAT_AXIS)
        assert mesh.shape[M.FEAT_AXIS] == 2

    def test_hybrid_mesh_explicit_slice_groups_layout(self):
        # the DCN-aware layout contract: feat rows never cross a slice
        # boundary; the data axis concatenates slices
        import jax

        devices = jax.devices()
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
        mesh = M.create_hybrid_mesh(feat=2, slice_groups=groups)
        assert mesh.shape[M.DATA_AXIS] == 4 and mesh.shape[M.FEAT_AXIS] == 2
        by_slice = {devices[i]: s for s, g in enumerate(groups) for i in g}
        for row in mesh.devices:
            assert len({by_slice[d] for d in row}) == 1

    def test_hybrid_mesh_slice_groups_validation(self):
        with pytest.raises(ValueError, match="equal-size"):
            M.create_hybrid_mesh(slice_groups=[[0, 1, 2], [3]])
        with pytest.raises(ValueError, match="partition"):
            M.create_hybrid_mesh(slice_groups=[[0, 1], [1, 2]])
        with pytest.raises(ValueError, match="feat=3"):
            M.create_hybrid_mesh(feat=3, slice_groups=[[0, 1, 2, 3]])

    def test_shard_map_shim_decorator_form(self, mesh8):
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        @M.shard_map(mesh=mesh8, in_specs=P(M.DATA_AXIS), out_specs=P(), check_rep=False)
        def total(v):
            return lax.psum(v.sum(), M.DATA_AXIS)

        x = np.arange(16.0)
        assert float(total(x)) == x.sum()


class TestFullLoopFits:
    """The entire iterative fit as ONE XLA program (while_loop + psum inside
    shard_map) — must match the per-step driver loop exactly."""

    def test_logreg_full_loop_matches_core(self):
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.linear import LogisticRegression
        from spark_rapids_ml_tpu.ops import linear as LIN
        from spark_rapids_ml_tpu.parallel import linear as PL
        from spark_rapids_ml_tpu.parallel import mesh as M

        rng = np.random.default_rng(50)
        rows, n = 512, 6
        x = rng.normal(size=(rows, n))
        p = 1.0 / (1.0 + np.exp(-(x @ rng.normal(size=n) - 0.2)))
        y = (rng.random(rows) < p).astype(np.float64)

        mesh = M.create_mesh(data=8, feat=1)
        xa = np.concatenate([x, np.ones((rows, 1))], axis=1)
        fit = PL.make_distributed_logreg_fit(
            mesh, reg_param=1e-3, max_iter=15, tol=1e-9
        )
        w, iters, step = fit(
            jax.device_put(jnp.asarray(xa), M.data_sharding(mesh)),
            jax.device_put(jnp.asarray(y), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(M.DATA_AXIS))),
            jax.device_put(jnp.ones(rows), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(M.DATA_AXIS))),
        )
        core = (
            LogisticRegression().setRegParam(1e-3).setMaxIter(15).setTol(1e-9)
            .fit((x, y))
        )
        np.testing.assert_allclose(
            np.asarray(w)[:-1], core.coefficients, atol=1e-8
        )
        np.testing.assert_allclose(float(np.asarray(w)[-1]), core.intercept, atol=1e-8)
        assert int(iters) >= 2

    def test_kmeans_full_loop_matches_core(self):
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.kmeans import KMeans
        from spark_rapids_ml_tpu.parallel import kmeans as PK
        from spark_rapids_ml_tpu.parallel import mesh as M

        rng = np.random.default_rng(51)
        centers_true = rng.normal(size=(5, 4)) * 6.0
        x = np.concatenate(
            [rng.normal(size=(64, 4)) * 0.4 + c for c in centers_true]
        )
        rng.shuffle(x)
        init = x[:5].copy()

        mesh = M.create_mesh(data=8, feat=1)
        fit = PK.make_distributed_kmeans_fit(mesh, max_iter=12, tol=1e-6)
        centers, cost, iters = fit(
            jax.device_put(jnp.asarray(x), M.data_sharding(mesh)),
            jax.device_put(jnp.ones(len(x)), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(M.DATA_AXIS))),
            jnp.asarray(init),
        )
        # core loop from the same init: monkey-route by calling the ops loop
        from spark_rapids_ml_tpu.ops import kmeans as KM

        c = jnp.asarray(init)
        cost_ref = None
        for _ in range(12):
            stats = KM.kmeans_stats(jnp.asarray(x), c)
            new_c = KM.update_centers(stats, c)
            cost_ref = float(stats.cost)
            shift = float(KM.center_shift_sq(c, new_c))
            c = new_c
            if shift <= 1e-12:
                break
        np.testing.assert_allclose(np.asarray(centers), np.asarray(c), atol=1e-8)
        np.testing.assert_allclose(float(cost), cost_ref, rtol=1e-10)
