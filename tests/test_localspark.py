"""localspark engine tests: DataFrame semantics + the worker-process
execution boundary (cloudpickle, Arrow IPC, schema validation, reuse).

These are the engine's own unit tests; the estimator integration suite that
runs on BOTH localspark and real pyspark lives in
``test_spark_integration.py``.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu.localspark import (
    LocalSparkSession,
    Row,
    functions as F,
    types as T,
)
from spark_rapids_ml_tpu.localspark.session import WorkerException


@pytest.fixture(scope="module")
def spark():
    with LocalSparkSession(parallelism=3) as s:
        yield s


def _features_df(spark, rows=30, dim=4, parallelism=None, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, dim))
    schema = T.StructType(
        [
            T.StructField("features", T.ArrayType(T.DoubleType())),
            T.StructField("idx", T.LongType()),
        ]
    )
    df = spark.createDataFrame(
        [(row.tolist(), i) for i, row in enumerate(x)],
        schema,
        numPartitions=parallelism,
    )
    return df, x


class TestTypes:
    def test_struct_arrow_round_trip(self):
        s = T.StructType(
            [
                T.StructField("a", T.ArrayType(T.DoubleType())),
                T.StructField("b", T.LongType()),
                T.StructField("c", T.StringType()),
            ]
        )
        arrow = s.to_arrow()
        assert arrow.field("a").type == pa.list_(pa.float64())
        assert T.from_arrow_schema(arrow) == s

    def test_equality(self):
        assert T.DoubleType() == T.DoubleType()
        assert T.ArrayType(T.DoubleType()) == T.ArrayType(T.DoubleType())
        assert T.ArrayType(T.DoubleType()) != T.ArrayType(T.LongType())


class TestDataFrameBasics:
    def test_create_and_collect(self, spark):
        df, x = _features_df(spark)
        rows = df.collect()
        assert len(rows) == 30
        # Row supports positional, by-name, and attribute access
        r = rows[7]
        assert r[1] == 7 and r["idx"] == 7 and r.idx == 7
        np.testing.assert_allclose(r["features"], x[7])

    def test_partitioning(self, spark):
        df, _ = _features_df(spark)
        assert df.rdd.getNumPartitions() == 3
        df8 = df.repartition(8)
        assert df8.rdd.getNumPartitions() == 8
        assert df8.count() == 30

    def test_select_first_limit(self, spark):
        df, x = _features_df(spark)
        sel = df.select("features")
        assert sel.schema.names == ["features"]
        first = sel.first()
        np.testing.assert_allclose(first[0], x[0])
        assert len(df.limit(5).collect()) == 5
        with pytest.raises(KeyError):
            df.select("nope")

    def test_where(self, spark):
        df, _ = _features_df(spark)
        assert df.where(F.col("idx") >= 20).count() == 10
        assert df.where((F.col("idx") >= 10) & (F.col("idx") < 12)).count() == 2

    def test_sample_seeded_and_unbiased_across_partitions(self, spark):
        df, _ = _features_df(spark, rows=600)
        s1 = df.sample(fraction=0.3, seed=7).collect()
        s2 = df.sample(fraction=0.3, seed=7).collect()
        assert [r.idx for r in s1] == [r.idx for r in s2]  # deterministic
        assert 100 < len(s1) < 260
        # rows must come from every partition, not a head
        idx = np.array([r.idx for r in s1])
        for lo in (0, 200, 400):
            assert ((idx >= lo) & (idx < lo + 200)).any()

    def test_random_split(self, spark):
        df, _ = _features_df(spark, rows=500)
        a, b = df.randomSplit([0.8, 0.2], seed=3)
        na, nb = a.count(), b.count()
        assert na + nb == 500
        assert 330 < na < 470
        # disjoint
        ia = {r.idx for r in a.collect()}
        ib = {r.idx for r in b.collect()}
        assert not (ia & ib)

    def test_to_arrow(self, spark):
        df, x = _features_df(spark)
        table = df.toArrow()
        assert table.num_rows == 30
        assert table.schema.field("features").type == pa.list_(pa.float64())

    def test_schema_inference_from_names(self, spark):
        df = spark.createDataFrame(
            [([1.0, 2.0], 3, "a"), ([0.5, 1.5], 4, "b")], ["vec", "n", "s"]
        )
        assert df.schema["vec"].dataType == T.ArrayType(T.DoubleType())
        assert df.schema["n"].dataType == T.LongType()
        assert df.schema["s"].dataType == T.StringType()

    def test_pandas_input(self, spark):
        pd = pytest.importorskip("pandas")
        pdf = pd.DataFrame({"a": [1.0, 2.0, 3.0], "b": [1, 2, 3]})
        df = spark.createDataFrame(pdf)
        assert df.count() == 3
        assert df.schema["a"].dataType == T.DoubleType()


class TestMapInArrowBoundary:
    def test_identity_roundtrip(self, spark):
        df, x = _features_df(spark)

        def ident(batches):
            yield from batches

        out = df.mapInArrow(ident, df.schema)
        assert out.count() == 30

    def test_closure_crosses_process(self, spark):
        """The plan function runs in ANOTHER PROCESS: module state mutated
        there must not be visible here, and captured state must arrive."""
        df, x = _features_df(spark)
        factor = 3.5  # captured in the closure -> cloudpickle must carry it

        def scale(batches):
            import os

            for b in batches:
                arr = np.asarray(
                    [np.asarray(v) * factor for v in b.column("features").to_pylist()]
                )
                flat = arr.reshape(-1)
                offsets = pa.array(
                    np.arange(0, flat.size + 1, arr.shape[1], dtype=np.int32)
                )
                col = pa.ListArray.from_arrays(offsets, pa.array(flat))
                pid = pa.array(np.full(b.num_rows, os.getpid(), dtype=np.int64))
                yield pa.RecordBatch.from_arrays(
                    [col, pid], schema=out_schema.to_arrow()
                )

        out_schema = T.StructType(
            [
                T.StructField("scaled", T.ArrayType(T.DoubleType())),
                T.StructField("pid", T.LongType()),
            ]
        )
        rows = df.select("features").mapInArrow(scale, out_schema).collect()
        import os as driver_os

        worker_pids = {r.pid for r in rows}
        assert driver_os.getpid() not in worker_pids  # really another process
        np.testing.assert_allclose(rows[0]["scaled"], x[0] * factor, rtol=1e-12)

    def test_worker_exception_carries_traceback(self, spark):
        df, _ = _features_df(spark)

        def boom(batches):
            for b in batches:
                raise ValueError("deliberate kaboom in worker")
            yield  # pragma: no cover

        out = df.mapInArrow(boom, df.schema)
        with pytest.raises(WorkerException, match="deliberate kaboom"):
            out.collect()

    def test_output_schema_mismatch_detected(self, spark):
        df, _ = _features_df(spark)

        def wrong_cols(batches):
            for b in batches:
                yield pa.RecordBatch.from_arrays(
                    [pa.array(np.zeros(b.num_rows))], names=["unexpected"]
                )

        declared = T.StructType([T.StructField("expected", T.DoubleType())])
        with pytest.raises(WorkerException, match="missing declared column"):
            df.mapInArrow(wrong_cols, declared).collect()

    def test_worker_print_does_not_corrupt_protocol(self, spark):
        df, _ = _features_df(spark)

        def chatty(batches):
            print("spamming stdout from the worker")
            yield from batches

        assert df.mapInArrow(chatty, df.schema).count() == 30

    def test_worker_reuse_across_jobs(self, spark):
        """Same worker process serves successive jobs (Spark's
        python.worker.reuse): per-process caches amortize."""
        df, _ = _features_df(spark)

        def tag_pid(batches):
            import os

            for b in batches:
                yield pa.RecordBatch.from_arrays(
                    [pa.array(np.full(b.num_rows, os.getpid(), dtype=np.int64))],
                    names=["pid"],
                )

        schema = T.StructType([T.StructField("pid", T.LongType())])
        pids1 = {r.pid for r in df.mapInArrow(tag_pid, schema).collect()}
        pids2 = {r.pid for r in df.mapInArrow(tag_pid, schema).collect()}
        assert pids1 == pids2 and len(pids1) == 1

    def test_two_workers_parallel(self):
        with LocalSparkSession(parallelism=4, num_workers=2) as s:
            df, _ = _features_df(s, rows=40)

            def tag_pid(batches):
                import os

                n = sum(b.num_rows for b in batches)
                yield pa.RecordBatch.from_arrays(
                    [pa.array(np.full(n, os.getpid(), dtype=np.int64))],
                    names=["pid"],
                )

            schema = T.StructType([T.StructField("pid", T.LongType())])
            pids = {r.pid for r in df.mapInArrow(tag_pid, schema).collect()}
            assert len(pids) == 2  # tasks really landed on two processes

    def test_empty_partition_runs_fn(self, spark):
        # 5 rows over 3 partitions + a filter that empties some: the fn must
        # still execute and emitting nothing must be fine
        df, _ = _features_df(spark, rows=5)
        empty = df.where(F.col("idx") > 100)

        def ident(batches):
            yield from batches

        assert empty.mapInArrow(ident, df.schema).count() == 0

    def test_unpicklable_fn_fails_at_submit(self, spark):
        df, _ = _features_df(spark)
        import threading

        lock = threading.Lock()  # unpicklable even for cloudpickle

        def bad(batches):
            with lock:
                yield from batches

        with pytest.raises(TypeError):
            df.mapInArrow(bad, df.schema).collect()

    def test_ddl_string_schema_rejected(self, spark):
        df, _ = _features_df(spark)
        with pytest.raises(TypeError, match="StructType"):
            df.mapInArrow(lambda it: it, "a double")


class TestReviewRegressions:
    def test_create_from_arrow_table(self, spark):
        table = pa.table({"a": [1.0, 2.0, 3.0], "b": [1, 2, 3]})
        df = spark.createDataFrame(table)
        assert df.count() == 3
        assert df.schema["a"].dataType == T.DoubleType()

    def test_sample_positional_forms(self, spark):
        df, _ = _features_df(spark, rows=200)
        kw = {r.idx for r in df.sample(fraction=0.5, seed=9).collect()}
        pos = {r.idx for r in df.sample(0.5, 9).collect()}
        assert kw == pos
        assert df.sample(0.5).count() > 0

    def test_dead_worker_is_replaced(self):
        with LocalSparkSession(parallelism=2) as s:
            df, _ = _features_df(s, rows=10)

            def suicide(batches):
                import os
                import signal

                os.kill(os.getpid(), signal.SIGKILL)
                yield  # pragma: no cover

            with pytest.raises(WorkerException, match="died mid-task"):
                df.mapInArrow(suicide, df.schema).collect()
            # the session recovers with a fresh worker on the next job
            assert df.count() == 10

            def ident(batches):
                yield from batches

            assert df.mapInArrow(ident, df.schema).count() == 10

    @pytest.mark.chaos
    def test_fault_plan_kill_replaces_worker(self, monkeypatch):
        # workers snapshot os.environ at spawn, so a TPU_ML_FAULT_PLAN set
        # before session creation rides into the worker process and kills it
        # mid-task (exit code 113); clearing the env before the next job
        # means the replacement worker spawns WITHOUT the plan and survives
        monkeypatch.setenv("TPU_ML_FAULT_PLAN", "worker.task:kill:1")
        with LocalSparkSession(parallelism=1) as s:
            df, _ = _features_df(s, rows=10)

            def ident(batches):
                yield from batches

            with pytest.raises(WorkerException, match="died mid-task"):
                df.mapInArrow(ident, df.schema).collect()
            doomed_pid = None
            if s._workers:  # the dead worker is still listed until _ensure_workers
                doomed_pid = s._workers[0].proc.pid

            monkeypatch.delenv("TPU_ML_FAULT_PLAN")
            assert df.mapInArrow(ident, df.schema).count() == 10
            assert s._workers[0].proc.pid != doomed_pid

    def test_missing_partition_result_raises_not_silent(self):
        # a None in the results list used to be yielded as an EMPTY batch
        # list — silent data loss dressed up as an empty partition. It must
        # raise, naming the partition(s) that never produced a payload.
        from spark_rapids_ml_tpu.localspark import session as S

        with pytest.raises(WorkerException, match=r"partition\(s\) \[1\]"):
            S._require_results([[], None, []], "mapInArrow")
        assert S._require_results([[], []], "mapInArrow") == [[], []]

    def test_rand_offset_continuation(self):
        # rand(seed) must yield the same per-row stream regardless of how a
        # partition is chunked: evaluating at row offset k must continue the
        # stream exactly where k prior rows left it
        c = F.rand(7)

        def batch(n):
            return pa.record_batch([pa.array(np.zeros(n))], names=["x"])

        full = np.asarray(c.evaluate(batch(30), 0, 0))
        head = np.asarray(c.evaluate(batch(10), 0, 0))
        tail = np.asarray(c.evaluate(batch(20), 0, 10))
        np.testing.assert_array_equal(np.concatenate([head, tail]), full)
        # different partitions get different streams
        other = np.asarray(c.evaluate(batch(30), 1, 0))
        assert not np.array_equal(full, other)


class TestRow:
    def test_row_api(self):
        r = Row([1.0, "x"], ["a", "b"])
        assert r[0] == 1.0 and r["b"] == "x" and r.a == 1.0
        assert r.asDict() == {"a": 1.0, "b": "x"}
        with pytest.raises(KeyError):
            r["nope"]
        with pytest.raises(AttributeError):
            r.nope
