"""Model-selection layer tests: grids, evaluators, CV, train/val split."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    BinaryClassificationEvaluator,
    ClusteringEvaluator,
    CrossValidator,
    KMeans,
    LinearRegression,
    LogisticRegression,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
    RegressionEvaluator,
    TrainValidationSplit,
)


class TestParamGridBuilder:
    def test_cartesian_product(self):
        grid = (
            ParamGridBuilder()
            .addGrid("regParam", [0.0, 0.1, 1.0])
            .addGrid("fitIntercept", [True, False])
            .build()
        )
        assert len(grid) == 6
        assert {m["regParam"] for m in grid} == {0.0, 0.1, 1.0}

    def test_base_on(self):
        grid = (
            ParamGridBuilder()
            .baseOn(maxIter=7)
            .addGrid("regParam", [0.0, 0.1])
            .build()
        )
        assert all(m["maxIter"] == 7 for m in grid)

    def test_param_object_key(self):
        grid = ParamGridBuilder().addGrid(LinearRegression.regParam, [0.5]).build()
        assert grid == [{"regParam": 0.5}]


class TestRegressionEvaluator:
    def test_metrics(self):
        y = np.array([1.0, 2.0, 3.0, 4.0])
        p = np.array([1.5, 2.0, 2.5, 4.0])
        ev = RegressionEvaluator()
        assert abs(ev.evaluate((None, y), predictions=p) - np.sqrt(0.125)) < 1e-12
        assert (
            abs(ev.setMetricName("mae").evaluate((None, y), predictions=p) - 0.25)
            < 1e-12
        )
        r2 = ev.setMetricName("r2").evaluate((None, y), predictions=p)
        assert 0.8 < r2 < 1.0
        assert ev.isLargerBetter() and not ev.setMetricName("rmse").isLargerBetter()

    def test_bad_metric(self):
        with pytest.raises(ValueError):
            RegressionEvaluator().setMetricName("mape")


class TestBinaryEvaluator:
    def test_auc_perfect_and_random(self, rng):
        y = np.array([0, 0, 1, 1], dtype=float)
        ev = BinaryClassificationEvaluator()
        assert ev.evaluate((None, y), predictions=np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
        assert ev.evaluate((None, y), predictions=np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
        # ties → 0.5 contribution each
        assert ev.evaluate((None, y), predictions=np.zeros(4)) == 0.5

    def test_auc_matches_sklearn_formula(self, rng):
        y = (rng.normal(size=200) > 0).astype(float)
        p = y * 0.3 + rng.normal(size=200) * 0.5
        ev = BinaryClassificationEvaluator()
        auc = ev.evaluate((None, y), predictions=p)
        # brute-force pairwise
        pos, neg = p[y == 1], p[y == 0]
        brute = np.mean(
            (pos[:, None] > neg[None, :]) + 0.5 * (pos[:, None] == neg[None, :])
        )
        assert abs(auc - brute) < 1e-12

    def test_accuracy(self):
        y = np.array([0, 1, 1, 0], dtype=float)
        ev = BinaryClassificationEvaluator().setMetricName("accuracy")
        assert ev.evaluate((None, y), predictions=np.array([0.1, 0.9, 0.4, 0.2])) == 0.75


class TestMulticlassClassificationEvaluator:
    # hand-checkable 3-class confusion: y true counts [3, 2, 1]
    Y = np.array([0, 0, 0, 1, 1, 2], dtype=float)
    P = np.array([0, 0, 1, 1, 2, 2], dtype=float)

    def test_accuracy(self):
        ev = MulticlassClassificationEvaluator(metricName="accuracy")
        assert abs(ev.evaluate((None, self.Y), predictions=self.P) - 4 / 6) < 1e-12

    def test_weighted_precision_recall_f1(self):
        # per class: prec = [2/2, 1/2, 1/2], rec = [2/3, 1/2, 1/1],
        # weights = [3/6, 2/6, 1/6]
        ev = MulticlassClassificationEvaluator()
        wp = ev.setMetricName("weightedPrecision").evaluate(
            (None, self.Y), predictions=self.P
        )
        assert abs(wp - (0.5 * 1.0 + (2 / 6) * 0.5 + (1 / 6) * 0.5)) < 1e-12
        wr = ev.setMetricName("weightedRecall").evaluate(
            (None, self.Y), predictions=self.P
        )
        assert abs(wr - (0.5 * (2 / 3) + (2 / 6) * 0.5 + (1 / 6) * 1.0)) < 1e-12
        f1c = [2 * 1.0 * (2 / 3) / (1.0 + 2 / 3), 0.5, 2 * 0.5 * 1.0 / 1.5]
        f1 = ev.setMetricName("f1").evaluate((None, self.Y), predictions=self.P)
        assert abs(f1 - (0.5 * f1c[0] + (2 / 6) * f1c[1] + (1 / 6) * f1c[2])) < 1e-12

    def test_f1_default_and_larger_better(self):
        ev = MulticlassClassificationEvaluator()
        assert ev.getOrDefault("metricName") == "f1"
        assert ev.isLargerBetter()
        assert not ev.setMetricName("logLoss").isLargerBetter()

    def test_bad_metric(self):
        with pytest.raises(ValueError):
            MulticlassClassificationEvaluator().setMetricName("recallByLabel")

    def test_log_loss_matches_formula(self):
        y = np.array([0.0, 1.0, 2.0])
        probs = np.array(
            [[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.25, 0.25, 0.5]]
        )
        ev = MulticlassClassificationEvaluator(metricName="logLoss")
        got = ev.evaluate((None, y), predictions=probs)
        want = -np.mean(np.log([0.7, 0.8, 0.5]))
        assert abs(got - want) < 1e-12

    def test_log_loss_clips_zero_probability(self):
        y = np.array([0.0])
        probs = np.array([[0.0, 1.0]])
        got = MulticlassClassificationEvaluator(metricName="logLoss").evaluate(
            (None, y), predictions=probs
        )
        assert np.isfinite(got) and got > 30  # -log(1e-15)

    def test_log_loss_rejects_hard_predictions(self):
        ev = MulticlassClassificationEvaluator(metricName="logLoss")
        with pytest.raises(ValueError, match="probability matrix"):
            ev.evaluate((None, self.Y), predictions=self.P)

    def test_cv_selects_reg_param_on_three_classes(self, rng):
        # 3 linearly-separable-ish clusters; crushing L2 must lose on f1
        rows = 420
        centers = np.array([[2.0, 0.0, 0.0], [0.0, 2.0, 0.0], [0.0, 0.0, 2.0]])
        y = np.arange(rows, dtype=float) % 3
        x = centers[y.astype(int)] + 0.6 * rng.normal(size=(rows, 3))
        grid = ParamGridBuilder().addGrid("regParam", [0.001, 100.0]).build()
        cv = CrossValidator(
            estimator=LogisticRegression(maxIter=40),
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(),
            numFolds=3,
        )
        cvm = cv.fit((x, y))
        assert cvm.bestIndex == 0
        assert cvm.avgMetrics[0] > cvm.avgMetrics[1]
        assert cvm.bestModel.coefficientMatrix.shape == (3, 3)

    def test_log_loss_on_binary_promotes_proba_vector(self, rng):
        # binary predict_proba_matrix returns [rows] P(class 1); logLoss
        # must promote it to the [rows, 2] layout, not crash mid-CV
        rows = 200
        y = (np.arange(rows) % 2).astype(float)
        x = np.where(y[:, None] > 0, 1.5, -1.5) + 0.8 * rng.normal(
            size=(rows, 3)
        )
        grid = ParamGridBuilder().addGrid("regParam", [0.01, 50.0]).build()
        cv = CrossValidator(
            estimator=LogisticRegression(maxIter=30),
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(metricName="logLoss"),
            numFolds=2,
        )
        cvm = cv.fit((x, y))
        assert cvm.bestIndex == 0
        assert np.all(np.isfinite(cvm.avgMetrics))

    def test_cv_log_loss_uses_probability_surface(self, rng):
        rows = 300
        centers = np.array([[2.5, 0.0], [0.0, 2.5], [-2.5, -2.5]])
        y = np.arange(rows, dtype=float) % 3
        x = centers[y.astype(int)] + 0.5 * rng.normal(size=(rows, 2))
        grid = ParamGridBuilder().addGrid("regParam", [0.001, 50.0]).build()
        cv = CrossValidator(
            estimator=LogisticRegression(maxIter=40),
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(metricName="logLoss"),
            numFolds=2,
        )
        cvm = cv.fit((x, y))
        assert cvm.bestIndex == 0  # smaller logLoss wins (isLargerBetter=False)
        assert cvm.avgMetrics[0] < cvm.avgMetrics[1]


class TestWeightedEvaluators:
    """weightCol (Spark 3.0+ evaluator surface): the oracle is row
    duplication — integer-weighted metrics must equal unweighted metrics
    on a dataset with each row repeated weight-many times."""

    def _weighted_and_duplicated(self, rng, rows=120):
        y = (rng.random(rows) > 0.4).astype(float)
        p = np.clip(y * 0.6 + rng.random(rows) * 0.5, 0, 1)
        w = rng.integers(1, 5, size=rows).astype(float)
        rep = np.repeat(np.arange(rows), w.astype(int))
        return y, p, w, y[rep], p[rep]

    def test_weighted_regression_matches_duplication(self, rng):
        y, p, w, yd, pd_ = self._weighted_and_duplicated(rng)
        for metric in ("rmse", "mse", "mae", "r2"):
            ev = RegressionEvaluator(metricName=metric, weightCol="w")
            got = ev.evaluate((None, y, w), predictions=p)
            want = RegressionEvaluator(metricName=metric).evaluate(
                (None, yd), predictions=pd_
            )
            assert abs(got - want) < 1e-12, metric

    def test_weighted_auc_matches_duplication_with_ties(self, rng):
        y, p, w, yd, pd_ = self._weighted_and_duplicated(rng)
        p = np.round(p, 1)  # force tied scores through the tie correction
        ev = BinaryClassificationEvaluator(weightCol="w")
        got = ev.evaluate((None, y, w), predictions=p)
        want = BinaryClassificationEvaluator().evaluate(
            (None, yd), predictions=np.round(pd_, 1)
        )
        assert abs(got - want) < 1e-12

    def test_weighted_binary_accuracy(self, rng):
        y, p, w, yd, pd_ = self._weighted_and_duplicated(rng)
        got = BinaryClassificationEvaluator(
            metricName="accuracy", weightCol="w"
        ).evaluate((None, y, w), predictions=p)
        want = BinaryClassificationEvaluator(metricName="accuracy").evaluate(
            (None, yd), predictions=pd_
        )
        assert abs(got - want) < 1e-12

    def test_weighted_multiclass_matches_duplication(self, rng):
        rows = 150
        y = (np.arange(rows) % 3).astype(float)
        p = y.copy()
        flip = rng.random(rows) < 0.25
        p[flip] = (p[flip] + 1) % 3
        w = rng.integers(1, 4, size=rows).astype(float)
        rep = np.repeat(np.arange(rows), w.astype(int))
        for metric in ("f1", "accuracy", "weightedPrecision", "weightedRecall"):
            got = MulticlassClassificationEvaluator(
                metricName=metric, weightCol="w"
            ).evaluate((None, y, w), predictions=p)
            want = MulticlassClassificationEvaluator(metricName=metric).evaluate(
                (None, y[rep]), predictions=p[rep]
            )
            assert abs(got - want) < 1e-12, metric

    def test_weighted_log_loss_matches_duplication(self, rng):
        rows = 90
        y = (np.arange(rows) % 3).astype(float)
        probs = rng.dirichlet(np.ones(3), size=rows)
        w = rng.integers(1, 4, size=rows).astype(float)
        rep = np.repeat(np.arange(rows), w.astype(int))
        got = MulticlassClassificationEvaluator(
            metricName="logLoss", weightCol="w"
        ).evaluate((None, y, w), predictions=probs)
        want = MulticlassClassificationEvaluator(metricName="logLoss").evaluate(
            (None, y[rep]), predictions=probs[rep]
        )
        assert abs(got - want) < 1e-12

    def test_weight_col_without_weight_slot_raises(self, rng):
        y = np.array([0.0, 1.0])
        ev = RegressionEvaluator(weightCol="w")
        with pytest.raises(ValueError, match="weight slot"):
            ev.evaluate((None, y), predictions=y)

    def test_weighted_silhouette_matches_duplication(self, rng):
        rows = 80
        x = np.vstack(
            [rng.normal(size=(rows // 2, 3)) + 3,
             rng.normal(size=(rows // 2, 3)) - 3]
        )
        p = np.repeat([0.0, 1.0], rows // 2)
        w = rng.integers(1, 4, size=rows).astype(float)
        rep = np.repeat(np.arange(rows), w.astype(int))
        # weighted a/b means differ from duplication only by the self-pair
        # exclusion (a duplicated row keeps its copies at distance 0, which
        # the weighted form counts for the OTHER copies) — compare loosely
        got = ClusteringEvaluator(weightCol="w").evaluate(
            (x, None, w), predictions=p
        )
        want = ClusteringEvaluator().evaluate((x[rep], None), predictions=p[rep])
        assert abs(got - want) < 0.02
        assert got > 0.8  # well-separated blobs


class TestClusteringEvaluator:
    def test_well_separated_beats_random(self, rng):
        a = rng.normal(size=(50, 4)) + 10
        b = rng.normal(size=(50, 4)) - 10
        x = np.vstack([a, b])
        good = np.array([0] * 50 + [1] * 50)
        bad = rng.integers(0, 2, 100)
        ev = ClusteringEvaluator()
        s_good = ev.evaluate(x, predictions=good)
        s_bad = ev.evaluate(x, predictions=bad)
        assert s_good > 0.9 > s_bad


class TestClusteringEvaluatorEdgeCases:
    def test_singletons_do_not_win(self, rng):
        """Every-point-its-own-cluster must not score 1.0 (singletons get 0,
        the sklearn/Spark convention) — else fragmented k wins model selection."""
        x = np.vstack(
            [rng.normal(size=(40, 3)) + 9, rng.normal(size=(40, 3)) - 9]
        )
        ev = ClusteringEvaluator()
        fragmented = ev.evaluate(x, predictions=np.arange(80))
        true_split = ev.evaluate(x, predictions=np.array([0] * 40 + [1] * 40))
        assert fragmented == 0.0
        assert true_split > fragmented

    def test_large_subsample_memory(self, rng):
        """maxRows at the default with wide features must not allocate a
        [rows, rows, dims] broadcast (the Gram-identity path keeps it 2-D)."""
        x = rng.normal(size=(3000, 256)).astype(np.float32)
        p = (x[:, 0] > 0).astype(int)
        s = ClusteringEvaluator().evaluate(x, predictions=p)
        assert np.isfinite(s)


class TestAUCUsesScores:
    def test_proba_surface_preferred_over_thresholded(self, rng):
        """CV's AUC must rank probabilities, not thresholded 0/1 labels."""
        from spark_rapids_ml_tpu.models.tuning import _fit_and_eval

        x = rng.normal(size=(400, 4))
        y = (x[:, 0] + rng.normal(size=400) > 0).astype(float)
        ev = BinaryClassificationEvaluator()
        model, auc_scores = _fit_and_eval(
            LogisticRegression(), {}, ev, (x[:300], y[:300]), (x[300:], y[300:])
        )
        hard = (model.predict_proba_matrix(x[300:]) >= 0.5).astype(float)
        auc_hard = ev.evaluate((None, y[300:]), predictions=hard)
        assert auc_scores > auc_hard  # score ranking strictly beats 0/1 ties


class TestCrossValidator:
    def test_selects_correct_reg_param(self, rng):
        # y depends linearly on x: the un-regularized candidate must win
        x = rng.normal(size=(300, 6))
        w = rng.normal(size=6)
        y = x @ w + 0.01 * rng.normal(size=300)
        grid = ParamGridBuilder().addGrid("regParam", [0.0, 10.0]).build()
        cv = CrossValidator(
            estimator=LinearRegression(),
            estimatorParamMaps=grid,
            evaluator=RegressionEvaluator(),
            numFolds=3,
        )
        cvm = cv.fit((x, y))
        assert cvm.bestIndex == 0
        assert len(cvm.avgMetrics) == 2
        assert cvm.avgMetrics[0] < cvm.avgMetrics[1]
        np.testing.assert_allclose(cvm.bestModel.coefficients, w, atol=0.01)

    def test_transform_delegates_to_best(self, rng):
        x = rng.normal(size=(200, 4))
        y = x @ np.arange(1.0, 5.0)
        cv = CrossValidator(
            estimator=LinearRegression(),
            estimatorParamMaps=[{}],
            evaluator=RegressionEvaluator(),
            numFolds=2,
        )
        cvm = cv.fit((x, y))
        pred = np.asarray(cvm.transform(x))
        np.testing.assert_allclose(pred, y, atol=1e-5)

    def test_classification_auc(self, rng):
        x = rng.normal(size=(400, 5))
        y = (x[:, 0] + 0.3 * rng.normal(size=400) > 0).astype(float)
        grid = ParamGridBuilder().addGrid("regParam", [0.01, 100.0]).build()
        cv = CrossValidator(
            estimator=LogisticRegression(),
            estimatorParamMaps=grid,
            evaluator=BinaryClassificationEvaluator(),
            numFolds=2,
        )
        cvm = cv.fit((x, y))
        assert cvm.bestIndex == 0  # heavy L2 kills the signal

    def test_collect_sub_models(self, rng):
        x = rng.normal(size=(100, 3))
        y = x @ np.ones(3)
        cv = CrossValidator(
            estimator=LinearRegression(),
            estimatorParamMaps=[{}, {"regParam": 0.1}],
            evaluator=RegressionEvaluator(),
            numFolds=2,
            collectSubModels=True,
        )
        cvm = cv.fit((x, y))
        assert len(cvm.subModels) == 2  # folds
        assert len(cvm.subModels[0]) == 2  # candidates

    def test_bad_folds(self):
        with pytest.raises(ValueError):
            CrossValidator(
                estimator=LinearRegression(),
                evaluator=RegressionEvaluator(),
                numFolds=1,
            ).fit((np.zeros((4, 2)), np.zeros(4)))

    def test_unsupervised_kmeans_grid(self, rng):
        a = rng.normal(size=(60, 3)) + 8
        b = rng.normal(size=(60, 3)) - 8
        x = np.vstack([a, b]).astype(np.float32)
        grid = ParamGridBuilder().addGrid("k", [2, 6]).build()
        cv = CrossValidator(
            estimator=KMeans().setSeed(0),
            estimatorParamMaps=grid,
            evaluator=ClusteringEvaluator(),
            numFolds=2,
        )
        cvm = cv.fit(x)
        assert cvm.bestIndex == 0  # true structure has 2 clusters


class TestTrainValidationSplit:
    def test_basic(self, rng):
        x = rng.normal(size=(300, 5))
        w = rng.normal(size=5)
        y = x @ w
        tvs = TrainValidationSplit(
            estimator=LinearRegression(),
            estimatorParamMaps=ParamGridBuilder().addGrid("regParam", [0.0, 50.0]).build(),
            evaluator=RegressionEvaluator(),
            trainRatio=0.7,
        )
        m = tvs.fit((x, y))
        assert m.bestIndex == 0
        assert len(m.validationMetrics) == 2
        np.testing.assert_allclose(m.bestModel.coefficients, w, atol=1e-4)

    def test_bad_ratio(self):
        with pytest.raises(ValueError):
            TrainValidationSplit(
                estimator=LinearRegression(),
                evaluator=RegressionEvaluator(),
                trainRatio=1.5,
            ).fit((np.zeros((4, 2)), np.zeros(4)))


class TestContainers:
    def test_pandas_cv(self, rng):
        pd = pytest.importorskip("pandas")
        x = rng.normal(size=(120, 3))
        y = x @ np.ones(3) + 0.01 * rng.normal(size=120)
        df = pd.DataFrame(
            {"features": list(x), "label": y}
        )
        cv = CrossValidator(
            estimator=LinearRegression()
            .setFeaturesCol("features")
            .setLabelCol("label")
            .setPredictionCol("prediction"),
            estimatorParamMaps=[{}],
            evaluator=RegressionEvaluator(),
            numFolds=2,
        )
        cvm = cv.fit(df)
        assert cvm.avgMetrics[0] < 0.1

    def test_weighted_3tuple_cv(self, rng):
        # (X, y, w) instance-weighted data must thread through fold slicing
        x = rng.normal(size=(160, 3))
        y = x @ np.ones(3) + 0.01 * rng.normal(size=160)
        w = rng.uniform(0.5, 2.0, size=160)
        cv = CrossValidator(
            estimator=LinearRegression(),
            estimatorParamMaps=[{"regParam": 0.0}, {"regParam": 0.1}],
            evaluator=RegressionEvaluator(),
            numFolds=3,
        )
        cvm = cv.fit((x, y, w))
        assert min(cvm.avgMetrics) < 0.1
        assert cvm.bestModel.coefficients.shape == (3,)

    def test_weighted_3tuple_tvs(self, rng):
        from spark_rapids_ml_tpu.models.tuning import TrainValidationSplit

        x = rng.normal(size=(160, 3))
        y = x @ np.ones(3) + 0.01 * rng.normal(size=160)
        w = rng.uniform(0.5, 2.0, size=160)
        tvs = TrainValidationSplit(
            estimator=LinearRegression(),
            estimatorParamMaps=[{}],
            evaluator=RegressionEvaluator(),
            trainRatio=0.8,
        )
        tm = tvs.fit((x, y, w))
        assert tm.validationMetrics[0] < 0.1

    def test_weights_change_weighted_fit(self, rng):
        # weights actually reach the estimator: near-zero weight on a
        # poisoned half must recover the clean coefficients
        x = rng.normal(size=(200, 2))
        y = x @ np.array([1.0, -2.0])
        y_bad = y.copy()
        y_bad[100:] += 100.0  # poisoned rows
        w = np.ones(200)
        w[100:] = 1e-9
        from spark_rapids_ml_tpu.models.tuning import TrainValidationSplit

        tvs = TrainValidationSplit(
            estimator=LinearRegression(),
            estimatorParamMaps=[{}],
            evaluator=RegressionEvaluator(),
            trainRatio=0.75,
            seed=3,
        )
        tm = tvs.fit((x, y_bad, w))
        np.testing.assert_allclose(
            tm.bestModel.coefficients, [1.0, -2.0], atol=1e-3
        )


class TestBinaryEvaluatorRawPrediction:
    def _data(self):
        rng = np.random.default_rng(8)
        x = rng.normal(size=(400, 4))
        p = 1 / (1 + np.exp(-(x @ np.array([2.0, -1.0, 0.5, 0.0]))))
        y = (rng.uniform(size=400) < p).astype(float)
        return x, y

    def test_auc_uses_probability_vector_column(self):
        import pandas as pd

        from spark_rapids_ml_tpu import LogisticRegression
        from spark_rapids_ml_tpu.models.tuning import (
            BinaryClassificationEvaluator,
        )

        x, y = self._data()
        df = pd.DataFrame({"features": list(x), "label": y})
        m = (
            LogisticRegression().setRegParam(0.01)
            .setProbabilityCol("probability").fit(df)
        )
        out = m.transform(df)
        ev = BinaryClassificationEvaluator().setRawPredictionCol("probability")
        auc_vec = ev.evaluate(out)
        # oracle: rank-based AUC over P(y=1)
        proba = np.stack(out["probability"].to_numpy())[:, 1]
        from sklearn.metrics import roc_auc_score

        assert abs(auc_vec - roc_auc_score(y, proba)) < 1e-12
        # hard predictions alone give a coarser (different) AUC
        ev_hard = BinaryClassificationEvaluator().setRawPredictionCol("")
        assert auc_vec >= ev_hard.evaluate(out)

    def test_missing_raw_col_falls_back_to_prediction(self):
        import pandas as pd

        from spark_rapids_ml_tpu import LogisticRegression
        from spark_rapids_ml_tpu.models.tuning import (
            BinaryClassificationEvaluator,
        )

        x, y = self._data()
        df = pd.DataFrame({"features": list(x), "label": y})
        out = LogisticRegression().setRegParam(0.01).fit(df).transform(df)
        # default rawPredictionCol="rawPrediction" AND the 'probability'
        # fallback are absent -> degrade to predictionCol, LOUDLY
        with pytest.warns(UserWarning, match="degrade to the two-level"):
            auc = BinaryClassificationEvaluator().evaluate(out)
        assert 0.5 <= auc <= 1.0


class TestNewEvaluatorMetrics:
    def test_regression_var_matches_spark_definition(self, rng):
        x = rng.normal(size=200)
        y = 2 * x + rng.normal(size=200) * 0.1
        pred = 2 * x
        got = RegressionEvaluator(metricName="var").evaluate(
            (None, y), predictions=pred
        )
        want = np.mean((pred - y.mean()) ** 2)
        assert abs(got - want) < 1e-12
        assert RegressionEvaluator(metricName="var").isLargerBetter()

    def test_weighted_var_matches_duplication(self, rng):
        y = rng.normal(size=60)
        pred = y + rng.normal(size=60) * 0.2
        w = rng.integers(1, 4, size=60).astype(float)
        got = RegressionEvaluator(metricName="var", weightCol="w").evaluate(
            (None, y, w), predictions=pred
        )
        rep = np.repeat(np.arange(60), w.astype(int))
        want = RegressionEvaluator(metricName="var").evaluate(
            (None, y[rep]), predictions=pred[rep]
        )
        assert abs(got - want) < 1e-12

    def test_area_under_pr_perfect_and_sklearn_close(self, rng):
        from sklearn.metrics import auc as sk_auc
        from sklearn.metrics import precision_recall_curve

        y = (rng.uniform(size=500) < 0.3).astype(float)
        ev = BinaryClassificationEvaluator(metricName="areaUnderPR")
        # perfect ranking -> 1.0
        assert abs(ev.evaluate((None, y), predictions=y) - 1.0) < 1e-12
        # noisy scores: trapezoid over the same curve sklearn computes
        scores = y + rng.normal(size=500) * 0.8
        got = ev.evaluate((None, y), predictions=scores)
        prec, rec, _ = precision_recall_curve(y, scores)
        want = sk_auc(rec, prec)  # sklearn's trapezoid over its PR points
        assert abs(got - want) < 0.01
        assert 0.3 < got <= 1.0

    def test_area_under_pr_no_positives_is_zero(self):
        ev = BinaryClassificationEvaluator(metricName="areaUnderPR")
        assert ev.evaluate((None, np.zeros(10)), predictions=np.arange(10.0)) == 0.0

    def test_area_under_pr_zero_weight_leading_group(self):
        ev = BinaryClassificationEvaluator(
            metricName="areaUnderPR", weightCol="w"
        )
        got = ev.evaluate(
            (None, np.array([0.0, 1.0, 0.0]), np.array([0.0, 1.0, 1.0])),
            predictions=np.array([3.0, 2.0, 1.0]),
        )
        assert np.isfinite(got)
        assert abs(got - 1.0) < 1e-12  # w>0 subset ranks perfectly

    def test_cv_area_under_pr_ranks_on_probability_surface(self, rng):
        from spark_rapids_ml_tpu.models.tuning import _fit_and_eval

        x = rng.normal(size=(400, 4))
        y = (x[:, 0] + rng.normal(size=400) > 0).astype(float)
        ev = BinaryClassificationEvaluator(metricName="areaUnderPR")
        model, pr_scores = _fit_and_eval(
            LogisticRegression(), {}, ev, (x[:300], y[:300]), (x[300:], y[300:])
        )
        hard = (model.predict_proba_matrix(x[300:]) >= 0.5).astype(float)
        pr_hard = ev.evaluate((None, y[300:]), predictions=hard)
        assert pr_scores > pr_hard  # probability surface, not 0/1 labels
