"""Serve-side telemetry: TransformReport, per-partition counters, the
analytical cost model, and the transform_id log join key.

Covers the ISSUE-5 transform-path list: a fitted SparkPCA.transform over a
multi-partition localspark DataFrame produces a TransformReport whose
per-partition rows/bytes/latency merge correctly from worker processes
(telemetry trailer), the report round-trips through the JSONL sink and
TransformReport.from_dict, lazy plans finalize only at materialization,
in-core array transforms finalize eagerly, cost-model FLOPs/bytes are
stamped on both fit and transform windows, and package log records inside
a transform window carry %(transform_id)s.
"""

from __future__ import annotations

import json
import logging

import numpy as np
import pytest

from spark_rapids_ml_tpu import telemetry as T
from spark_rapids_ml_tpu.telemetry import costmodel
from spark_rapids_ml_tpu.telemetry.report import TransformReport
from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE
from spark_rapids_ml_tpu.utils.config import get_config, set_config


@pytest.fixture(autouse=True)
def clean():
    T.reset_metrics()
    TIMELINE.clear()
    yield
    T.reset_metrics()
    TIMELINE.clear()


@pytest.fixture
def pca_df_and_model():
    """A 3-partition localspark DataFrame and a SparkPCA model fitted on it."""
    from spark_rapids_ml_tpu.localspark import types as LT
    from spark_rapids_ml_tpu.localspark.session import LocalSparkSession
    from spark_rapids_ml_tpu.spark import SparkPCA

    rng = np.random.default_rng(11)
    x = rng.normal(size=(600, 8))
    schema = LT.StructType(
        [LT.StructField("features", LT.ArrayType(LT.DoubleType()))]
    )
    with LocalSparkSession(parallelism=3, num_workers=2) as spark:
        df = spark.createDataFrame([(r.tolist(),) for r in x], schema)
        model = SparkPCA().setInputCol("features").setK(3).fit(df)
        yield df, model


class TestTransformReport:
    def test_multipartition_counters_merge(self, pca_df_and_model, tmp_path):
        """The acceptance path: per-partition rows/bytes/latency from the
        worker trailer roll into one TransformReport, exported as JSONL."""
        df, model = pca_df_and_model
        path = str(tmp_path / "telemetry.jsonl")
        old = get_config().telemetry_path
        set_config(telemetry_path=path)
        try:
            out = model.transform(df)
            # the plan is lazy: no report until an action materializes it
            assert model.transform_report is None
            table = out.toArrow()
        finally:
            set_config(telemetry_path=old)
        assert table.num_rows == 600

        rep = model.transform_report
        assert rep is not None
        assert rep.transformer == "SparkPCAModel"
        assert len(rep.transform_id) == 12
        assert rep.wall_seconds > 0
        assert rep.rows == 600
        assert rep.bytes > 0

        # 3 input partitions ran through the instrumented arrow fn; their
        # counters merge per partition label and sum to the total
        assert len(rep.partitions) == 3
        assert sum(p["rows"] for p in rep.partitions.values()) == 600
        for p in rep.partitions.values():
            assert p["rows"] > 0 and p["bytes"] > 0 and p["batches"] >= 1
            assert p["seconds"] > 0
        lat = rep.partition_latency
        assert lat["count"] == 3
        for q in ("p50", "p90", "p99"):
            assert lat[q] > 0
        assert lat["p50"] <= lat["p99"] * (1 + 1e-9)
        # the window's trace_range spans (plan/dispatch/worker) made it in
        assert rep.phases

        # the JSONL sink got the transform_report (the fixture's fit ran
        # before the path was set) and the record round-trips losslessly
        records = [
            json.loads(line)
            for line in open(path, encoding="utf-8")
            if line.strip()
        ]
        rec = [r for r in records if r["type"] == "transform_report"][-1]
        assert rec["schema"] == 1
        back = TransformReport.from_dict(rec)
        assert back.rows == rep.rows
        assert back.transform_id == rep.transform_id
        assert set(back.partitions) == set(rep.partitions)
        assert rec == TransformReport.from_dict(rec).to_dict()

    def test_cost_model_stamped_on_fit_and_transform(self, pca_df_and_model):
        """Analytical FLOPs/bytes from XLA's AOT cost model reach both
        reports — including when the kernels dispatched in worker
        processes (counter-driven rollup over the trailer)."""
        df, model = pca_df_and_model
        fit_cm = model.fit_report.cost_model
        assert "linalg.gram_stats" in fit_cm.get("kernels", {})
        assert fit_cm["analytical_flops"] > 0
        assert fit_cm["peak_flops"] > 0

        model.transform(df).toArrow()
        cm = model.transform_report.cost_model
        assert "linalg.project" in cm.get("kernels", {})
        k = cm["kernels"]["linalg.project"]
        assert k["calls"] == 3  # one dispatch per partition
        assert k["flops"] > 0 and k["bytes_accessed"] > 0
        assert cm["analytical_flops"] >= k["flops"] * 3 * (1 - 1e-6)
        assert cm["analytical_bytes"] > 0
        if "roofline_utilization" in cm:
            assert 0 < cm["roofline_utilization"] < 1

    def test_transform_timeline_exported_with_transform_id(
        self, pca_df_and_model, tmp_path
    ):
        df, model = pca_df_and_model
        tl_path = str(tmp_path / "timeline.jsonl")
        old = get_config().timeline_path
        set_config(timeline_path=tl_path)
        try:
            model.transform(df).toArrow()
        finally:
            set_config(timeline_path=old)
        records = [
            json.loads(line)
            for line in open(tl_path, encoding="utf-8")
            if line.strip()
        ]
        assert records, "transform materialization exported no timeline"
        rec = records[-1]
        assert rec["type"] == "timeline"
        assert rec["transform_id"] == model.transform_report.transform_id
        names = {e.get("name") for e in rec["events"]}
        assert "transform.partition" in names

    def test_in_core_array_transform_finalizes_eagerly(self):
        from spark_rapids_ml_tpu.models.pca import PCA

        x = np.random.default_rng(3).normal(size=(256, 6))
        model = PCA().setInputCol("f").setK(2).fit(x)
        out = model.transform(x)
        assert np.asarray(out).shape == (256, 2)
        rep = model.transform_report
        assert rep is not None  # arrays are not lazy plans
        assert rep.transformer == "PCAModel"
        assert rep.wall_seconds > 0
        cm = rep.cost_model
        assert "linalg.project" in cm.get("kernels", {})


class TestTransformIdLogFilter:
    def test_log_records_carry_transform_id(self, caplog):
        cap = T.begin_transform("Demo", "uid0")
        try:
            with caplog.at_level(logging.WARNING, logger="spark_rapids_ml_tpu"):
                logging.getLogger("spark_rapids_ml_tpu").warning("inside")
        finally:
            rep = T.end_transform(cap)
        assert caplog.records[-1].transform_id == rep.transform_id
        # outside any window the filter stamps the "-" placeholder
        logging.getLogger("spark_rapids_ml_tpu").warning("outside")
        assert caplog.records[-1].transform_id == "-"

    def test_release_is_idempotent(self):
        cap = T.begin_transform("Demo")
        T.release_transform_context(cap)
        T.release_transform_context(cap)  # second release is a no-op
        rep = T.end_transform(cap)  # end after release still reports
        assert rep.transformer == "Demo"
        assert len(rep.transform_id) == 12


class TestWindowSummaryUnit:
    def test_counter_driven_rollup(self):
        """window_summary needs only the costmodel.* counters — the shape
        of worker-side captures arriving via the telemetry trailer."""
        from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

        snap = REGISTRY.snapshot()
        REGISTRY.counter_inc("costmodel.calls", 2, kernel="k")
        REGISTRY.counter_inc("costmodel.flops", 200.0, kernel="k")
        REGISTRY.counter_inc("costmodel.bytes", 64.0, kernel="k")
        delta = REGISTRY.snapshot().delta(snap)
        cm = costmodel.window_summary(delta, wall_seconds=2.0)
        assert cm["kernels"]["k"] == pytest.approx(
            {"calls": 2, "flops": 100.0, "bytes_accessed": 32.0}
        )
        assert cm["analytical_flops"] == 200.0
        assert cm["achieved_flop_s"] == 100.0
        assert cm["roofline_utilization"] == pytest.approx(
            100.0 / cm["peak_flops"]
        )

    def test_empty_window_is_empty_dict(self):
        from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

        snap = REGISTRY.snapshot()
        delta = REGISTRY.snapshot().delta(snap)
        assert costmodel.window_summary(delta, 1.0) == {}


class TestNestedTransformGuard:
    def test_chained_stages_book_rows_once(self):
        """Chained lazy plans drive transform generators re-entrantly in one
        thread; only the outermost stage may book the volume counters, or a
        two-stage pipeline double-counts every input row. Per-stage latency
        stays unconditional — stage timing is real work."""
        import pyarrow as pa

        from spark_rapids_ml_tpu.spark import arrow_fns
        from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

        class _Stage(arrow_fns._InstrumentedTransformFn):
            def _run(self, batches):
                yield from batches

        batch = pa.RecordBatch.from_arrays(
            [pa.array([1.0, 2.0, 3.0])], names=["x"]
        )
        snap = REGISTRY.snapshot()
        out = list(_Stage()(_Stage()(iter([batch]))))
        assert out[0].num_rows == 3
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("transform.rows") == 3  # once, not per stage
        assert delta.counter("transform.batches") == 1
        assert delta.hist("transform.partition_seconds").count == 2
