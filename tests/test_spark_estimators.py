"""Widened Spark integration: GLM/KMeans/scaler plan functions + wrappers.

Same strategy as test_spark_arrow.py — the mapInArrow bodies are exercised
as plain Arrow-iterator functions (no pyspark needed), and the Spark-facing
wrappers are verified to fall through to the core paths on non-Spark input.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    LogisticRegression,
    StandardScaler,
)
from spark_rapids_ml_tpu.spark import (
    SparkKMeans,
    SparkLinearRegression,
    SparkLogisticRegression,
    SparkStandardScaler,
    arrow_fns,
)


def _labeled_batches(x, y, sizes, w=None):
    out, at = [], 0
    for s in sizes:
        cols = [
            pa.FixedSizeListArray.from_arrays(
                pa.array(x[at : at + s].reshape(-1)), x.shape[1]
            ),
            pa.array(y[at : at + s]),
        ]
        names = ["features", "label"]
        if w is not None:
            cols.append(pa.array(w[at : at + s]))
            names.append("wt")
        out.append(pa.RecordBatch.from_arrays(cols, names=names))
        at += s
    assert at == len(x)
    return out


@pytest.fixture
def xy(rng):
    x = rng.normal(size=(300, 6))
    coef = rng.normal(size=6)
    y = x @ coef + 0.01 * rng.normal(size=300)
    return x, y, coef


class TestArraysSerialization:
    def test_round_trip_sum_merge(self, rng):
        a = {"m": rng.normal(size=(4, 4)), "v": rng.normal(size=4), "s": np.array(3.0)}
        b = {"m": rng.normal(size=(4, 4)), "v": rng.normal(size=4), "s": np.array(2.0)}
        shapes = {"m": (4, 4), "v": (4,), "s": ()}
        merged = arrow_fns.arrays_from_batches(
            [arrow_fns.arrays_to_batch(a), arrow_fns.arrays_to_batch(b)], shapes
        )
        np.testing.assert_allclose(merged["m"], a["m"] + b["m"], rtol=1e-12)
        np.testing.assert_allclose(merged["s"], 5.0)

    def test_rows_fallback(self, rng):
        a = {"v": rng.normal(size=3)}
        rows = [{"v": a["v"].tolist()}]
        out = arrow_fns.arrays_from_rows(rows, {"v": (3,)})
        np.testing.assert_allclose(out["v"], a["v"], rtol=1e-12)

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="no partition statistics"):
            arrow_fns.arrays_from_batches([], {"v": (2,)})


class TestLinregPlan:
    def test_stats_match_direct_fit(self, xy):
        from spark_rapids_ml_tpu.ops import linear as LIN
        import jax.numpy as jnp

        x, y, coef = xy
        fn = arrow_fns.make_linreg_partition_fn("features", "label")
        batches = _labeled_batches(x, y, [100, 120, 80])
        shapes = {
            "xtx": (6, 6), "xty": (6,), "x_sum": (6,),
            "y_sum": (), "y_sq": (), "count": (),
        }
        arrays = arrow_fns.arrays_from_batches(fn(iter(batches)), shapes)
        stats = LIN.LinearStats(**{k: jnp.asarray(v) for k, v in arrays.items()})
        c, b = LIN.solve_normal(stats, reg_param=0.0, fit_intercept=True)
        np.testing.assert_allclose(np.asarray(c), coef, atol=0.01)
        assert float(arrays["count"]) == 300.0

    def test_weighted(self, xy, rng):
        from spark_rapids_ml_tpu.ops import linear as LIN
        import jax.numpy as jnp

        x, y, _ = xy
        w = rng.integers(1, 4, 300).astype(np.float64)
        fn = arrow_fns.make_linreg_partition_fn("features", "label", "wt")
        shapes = {
            "xtx": (6, 6), "xty": (6,), "x_sum": (6,),
            "y_sum": (), "y_sq": (), "count": (),
        }
        arrays = arrow_fns.arrays_from_batches(
            fn(iter(_labeled_batches(x, y, [150, 150], w))), shapes
        )
        stats = LIN.LinearStats(**{k: jnp.asarray(v) for k, v in arrays.items()})
        c, b = LIN.solve_normal(stats, reg_param=0.0, fit_intercept=True)
        m_ref = LinearRegression().fit((x, y, w))
        np.testing.assert_allclose(np.asarray(c), m_ref.coefficients, atol=1e-6)


class TestLogregPlan:
    def test_newton_iterations_converge(self, rng):
        from spark_rapids_ml_tpu.ops import linear as LIN
        import jax.numpy as jnp

        x = rng.normal(size=(400, 4))
        y = (x[:, 0] + 0.3 * rng.normal(size=400) > 0).astype(float)
        batches = _labeled_batches(x, y, [200, 200])
        d = 5
        shapes = {"hess": (d, d), "grad": (d,), "loss": (), "count": ()}
        w_full = np.zeros(d)
        for _ in range(15):
            fn = arrow_fns.make_logreg_newton_partition_fn(
                "features", "label", w_full
            )
            arrays = arrow_fns.arrays_from_batches(fn(iter(batches)), shapes)
            stats = LIN.NewtonStats(**{k: jnp.asarray(v) for k, v in arrays.items()})
            new_w, step = LIN.newton_update(
                jnp.asarray(w_full), stats, reg_param=0.01
            )
            w_full = np.asarray(new_w)
            if float(step) < 1e-6:
                break
        m_ref = LogisticRegression().setRegParam(0.01).fit((x, y))
        np.testing.assert_allclose(w_full[:-1], m_ref.coefficients, rtol=1e-4)


class TestKMeansPlan:
    def test_lloyd_step_matches_core(self, rng):
        from spark_rapids_ml_tpu.ops import kmeans as KM
        import jax.numpy as jnp

        a = rng.normal(size=(60, 3)) + 5
        b = rng.normal(size=(60, 3)) - 5
        x = np.vstack([a, b])
        centers = x[[0, 60]]
        fn = arrow_fns.make_kmeans_partition_fn("features", centers)
        batches = [
            pa.RecordBatch.from_arrays(
                [pa.FixedSizeListArray.from_arrays(pa.array(chunk.reshape(-1)), 3)],
                names=["features"],
            )
            for chunk in (x[:70], x[70:])
        ]
        shapes = {"sums": (2, 3), "counts": (2,), "cost": ()}
        arrays = arrow_fns.arrays_from_batches(fn(iter(batches)), shapes)
        ref = KM.kmeans_stats(jnp.asarray(x), jnp.asarray(centers))
        np.testing.assert_allclose(arrays["sums"], np.asarray(ref.sums), rtol=1e-6)
        np.testing.assert_allclose(arrays["counts"], np.asarray(ref.counts))
        np.testing.assert_allclose(arrays["cost"], float(ref.cost), rtol=1e-6)


class TestMomentsPlan:
    def test_matches_scaler_fit(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops import scaler as S

        x = rng.normal(size=(250, 8)) * 3 + 1
        fn = arrow_fns.make_moments_partition_fn("features")
        batches = [
            pa.RecordBatch.from_arrays(
                [pa.FixedSizeListArray.from_arrays(pa.array(chunk.reshape(-1)), 8)],
                names=["features"],
            )
            for chunk in (x[:100], x[100:])
        ]
        shapes = {"count": (), "total": (8,), "total_sq": (8,)}
        arrays = arrow_fns.arrays_from_batches(fn(iter(batches)), shapes)
        stats = S.MomentStats(**{k: jnp.asarray(v) for k, v in arrays.items()})
        mean, std = S.finalize_moments(stats)
        np.testing.assert_allclose(np.asarray(mean), x.mean(0), rtol=1e-9)
        np.testing.assert_allclose(np.asarray(std), x.std(0, ddof=1), rtol=1e-9)


class TestMatrixMapPlan:
    def test_scalar_output_column(self, rng):
        x = rng.normal(size=(50, 4))
        fn = arrow_fns.make_matrix_map_partition_fn(
            "features", "pred", lambda m: m.sum(axis=1)
        )
        batch = pa.RecordBatch.from_arrays(
            [pa.FixedSizeListArray.from_arrays(pa.array(x.reshape(-1)), 4)],
            names=["features"],
        )
        out = list(fn(iter([batch])))[0]
        assert out.schema.field("pred").type == pa.float64()
        np.testing.assert_allclose(
            out.column("pred").to_numpy(), x.sum(axis=1), rtol=1e-12
        )

    def test_list_output_column(self, rng):
        x = rng.normal(size=(50, 4))
        fn = arrow_fns.make_matrix_map_partition_fn(
            "features", "out", lambda m: m[:, :2]
        )
        batch = pa.RecordBatch.from_arrays(
            [pa.FixedSizeListArray.from_arrays(pa.array(x.reshape(-1)), 4)],
            names=["features"],
        )
        out = list(fn(iter([batch])))[0]
        assert out.schema.field("out").type == pa.list_(pa.float64())


class TestBinaryLabelValidationInPlan:
    def test_non_binary_labels_rejected(self, rng):
        x = rng.normal(size=(50, 3))
        y = rng.integers(1, 3, 50).astype(float)  # {1, 2}: invalid coding
        fn = arrow_fns.make_logreg_newton_partition_fn(
            "features", "label", np.zeros(4)
        )
        with pytest.raises(ValueError, match="0/1 labels"):
            list(fn(iter(_labeled_batches(x, y, [50]))))


class TestWrapperFallThrough:
    """Non-Spark inputs route to the core estimators and return Spark-model
    subclasses, so one estimator object serves both worlds."""

    def test_linreg(self, xy):
        x, y, coef = xy
        m = SparkLinearRegression().fit((x, y))
        np.testing.assert_allclose(m.coefficients, coef, atol=0.01)
        core = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m.coefficients, core.coefficients, atol=1e-12)

    def test_logreg(self, rng):
        x = rng.normal(size=(200, 3))
        y = (x[:, 0] > 0).astype(float)
        m = SparkLogisticRegression().setRegParam(0.1).fit((x, y))
        core = LogisticRegression().setRegParam(0.1).fit((x, y))
        np.testing.assert_allclose(m.coefficients, core.coefficients, atol=1e-10)

    def test_kmeans(self, rng):
        x = np.vstack([rng.normal(size=(40, 2)) + 4, rng.normal(size=(40, 2)) - 4])
        m = SparkKMeans().setK(2).setSeed(0).fit(x)
        core = KMeans().setK(2).setSeed(0).fit(x)
        np.testing.assert_allclose(
            np.sort(m.clusterCenters, axis=0), np.sort(core.clusterCenters, axis=0)
        )

    def test_scaler(self, rng):
        x = rng.normal(size=(100, 5)) * 2 + 3
        m = SparkStandardScaler().setInputCol("f").fit(x)
        np.testing.assert_allclose(m.mean, x.mean(0), rtol=1e-9)
        out = np.asarray(m.transform(x))
        np.testing.assert_allclose(out.std(0, ddof=1), np.ones(5), rtol=1e-9)

    def test_logreg_multinomial_fall_through_predicts(self, rng):
        # >=3-class local data trains multinomial; the wrapper must carry
        # coefficientMatrix/interceptVector through or predict crashes
        x = rng.normal(size=(300, 4))
        y = np.argmax(x[:, :3], axis=1).astype(float)
        m = SparkLogisticRegression().setRegParam(0.1).fit((x, y))
        assert m.coefficientMatrix is not None and m.coefficientMatrix.shape[0] == 3
        assert m.interceptVector is not None
        preds = np.asarray(m.transform(x))
        assert preds.shape == (300,)
        assert np.mean(preds == y) > 0.8
        assert float(m.predict(x[0])) in (0.0, 1.0, 2.0)

    def test_checkpoint_kwargs_fall_through(self, rng, tmp_path):
        x = rng.normal(size=(120, 3))
        y = (x[:, 0] > 0).astype(float)
        m = SparkLogisticRegression().fit(
            (x, y), checkpoint_dir=str(tmp_path), checkpoint_every=1
        )
        assert m.coefficients is not None
        # at least one durable checkpoint landed
        assert any(tmp_path.iterdir())

    def test_checkpoint_kwargs_linreg_rejected_clearly(self, xy):
        x, y, coef = xy
        # LinearRegression has no mid-training loop: a checkpoint request is
        # a clear NotImplementedError, not a raw TypeError deep in core fit
        with pytest.raises(NotImplementedError, match="closed-form"):
            SparkLinearRegression().fit((x, y), checkpoint_dir="/tmp/nope")
        with pytest.raises(TypeError, match="unexpected"):
            SparkLinearRegression().fit((x, y), checkpont_dir="/tmp/typo")

    def test_unweighted_none_3tuple_cv(self, rng):
        # (X, y, None) is the documented unweighted 3-tuple form; fold
        # slicing must pass the None through untouched
        from spark_rapids_ml_tpu.models.tuning import (
            CrossValidator,
            RegressionEvaluator,
        )

        x = rng.normal(size=(90, 3))
        y = x @ np.ones(3)
        cv = CrossValidator(
            estimator=LinearRegression(),
            estimatorParamMaps=[{}],
            evaluator=RegressionEvaluator(),
            numFolds=2,
        )
        cvm = cv.fit((x, y, None))
        assert cvm.avgMetrics[0] < 0.1


class TestRangeStatsPlan:
    def test_partition_rows_fold_with_min_max_monoid(self, rng):
        from spark_rapids_ml_tpu.spark.estimators import (
            SparkMaxAbsScaler,
            SparkMinMaxScaler,
        )

        # all-positive data across RAGGED partitions: a sum-merge or an
        # unmasked pad would corrupt the min; the fold must be min/max
        x = rng.uniform(2.0, 9.0, size=(231, 6))
        fn = arrow_fns.make_range_stats_partition_fn("features")
        batches = [
            pa.RecordBatch.from_arrays(
                [pa.FixedSizeListArray.from_arrays(pa.array(c.reshape(-1)), 6)],
                names=["features"],
            )
            for c in (x[:97], x[97:])
        ]
        # two separate partition invocations -> two stats rows to fold
        rows = list(fn(iter(batches[:1]))) + list(fn(iter(batches[1:])))
        stats = arrow_fns.range_stats_from_batches(rows, 6)
        np.testing.assert_allclose(np.asarray(stats.min), x.min(0), atol=0)
        np.testing.assert_allclose(np.asarray(stats.max), x.max(0), atol=0)
        np.testing.assert_allclose(
            np.asarray(stats.max_abs), np.abs(x).max(0), atol=0
        )
        assert float(np.asarray(stats.count)) == 231

        # wrapper fall-through on local data matches the core estimators
        m = SparkMinMaxScaler().setInputCol("f").fit(x)
        np.testing.assert_allclose(m.originalMin, x.min(0))
        out = SparkMaxAbsScaler().setInputCol("f").fit(x).transform(x)
        np.testing.assert_allclose(out, x / np.abs(x).max(0), atol=1e-12)
