"""Fused StandardScaler→PCA (BASELINE config 4): ``standardize=True`` runs
the decomposition on the covariance of (x−μ)/σ derived from the SAME
one-pass GramStats — differential-equal to the explicit two-stage pipeline,
with no second pass over the data.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import PCA, StandardScaler
from spark_rapids_ml_tpu.models.pca import PCAModel


@pytest.fixture
def x(rng):
    # wildly different feature scales: the case standardization exists for
    return rng.normal(size=(400, 8)) * np.array(
        [1.0, 50.0, 0.01, 5.0, 100.0, 1.0, 0.5, 10.0]
    ) + rng.normal(size=8) * 3.0


class TestStandardizedPCA:
    def test_equals_explicit_scaler_pipeline(self, x):
        fused = PCA().setInputCol("f").setK(3).setStandardize(True).fit(x)
        scaler = (
            StandardScaler().setInputCol("f").setWithMean(True).setWithStd(True)
            .fit(x)
        )
        xs = np.asarray(scaler.transform(x))
        staged = PCA().setInputCol("f").setK(3).setMeanCentering(True).fit(xs)
        np.testing.assert_allclose(np.abs(fused.pc), np.abs(staged.pc), atol=1e-9)
        np.testing.assert_allclose(
            fused.explainedVariance, staged.explainedVariance, atol=1e-9
        )
        # transform standardizes internally: fused(model, raw x) ==
        # staged(model, scaled x)
        got = np.asarray(fused.transform(x))
        want = np.asarray(staged.transform(xs))
        np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-8)

    def test_matches_sklearn_correlation_pca(self, x):
        sk = pytest.importorskip("sklearn")
        from sklearn.decomposition import PCA as SkPCA
        from sklearn.preprocessing import StandardScaler as SkScaler

        xs = SkScaler().fit_transform(x) * np.sqrt(len(x) / (len(x) - 1))
        # sklearn scaler uses population std; rescale to sample-std space
        sk_pc = SkPCA(n_components=3).fit(xs).components_.T
        fused = PCA().setInputCol("f").setK(3).setStandardize(True).fit(x)
        cos = np.abs(np.sum(fused.pc * sk_pc, axis=0)) / (
            np.linalg.norm(fused.pc, axis=0) * np.linalg.norm(sk_pc, axis=0)
        )
        assert cos.min() > 1 - 1e-9

    def test_row_fallback_and_native_standardize(self, x):
        model = PCA().setInputCol("f").setK(2).setStandardize(True).fit(x)
        want = np.asarray(model.transform(x))
        got = np.asarray(model.transform_rows(list(x)))
        np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-8)

    def test_persistence_round_trips_mean_std(self, x, tmp_path):
        model = PCA().setInputCol("f").setK(2).setStandardize(True).fit(x)
        p = str(tmp_path / "m")
        model.save(p)
        loaded = PCAModel.load(p)
        np.testing.assert_allclose(loaded.mean, model.mean)
        np.testing.assert_allclose(loaded.std, model.std)
        np.testing.assert_allclose(
            np.asarray(loaded.transform(x)), np.asarray(model.transform(x))
        )
        # plain models keep saving without the fields
        plain = PCA().setInputCol("f").setK(2).fit(x)
        p2 = str(tmp_path / "m2")
        plain.save(p2)
        assert PCAModel.load(p2).mean is None

    def test_svd_solver_rejected(self, x):
        with pytest.raises(ValueError, match="covariance solver"):
            PCA().setInputCol("f").setK(2).setStandardize(True).setSolver(
                "svd"
            ).fit(x)

    def test_zero_variance_feature_passes_through(self, rng):
        x = rng.normal(size=(100, 4))
        x[:, 2] = 7.0  # constant feature
        model = PCA().setInputCol("f").setK(2).setStandardize(True).fit(x)
        out = np.asarray(model.transform(x))
        assert np.isfinite(out).all()

    def test_spark_layout_save_rejected(self, x, tmp_path):
        # stock Spark PCAModel cannot carry the scaling state — must refuse
        # rather than silently produce a model that projects raw data
        model = PCA().setInputCol("f").setK(2).setStandardize(True).fit(x)
        with pytest.raises(NotImplementedError, match="scaling state"):
            model.save(str(tmp_path / "m"), layout="spark")
