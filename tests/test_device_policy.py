"""Worker device-assignment policy (utils/devicepolicy.py).

The reference never had to solve this: Spark's GPU resource scheduling hands
every executor its own device before task code runs (JniRAPIDSML.java:27-58
then merely loads the library per-process). On a TPU host the accelerator is
claimed at interpreter start by site-level bootstrap hooks, so the framework
must own the policy — scrub the triggers from worker envs and fail fast,
never hang, when a worker lands on the wrong platform.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu.localspark import LocalSparkSession
from spark_rapids_ml_tpu.localspark import types as LT
from spark_rapids_ml_tpu.localspark.session import WorkerException
from spark_rapids_ml_tpu.utils import devicepolicy


def test_worker_env_scrubs_bootstrap_triggers():
    env = devicepolicy.worker_env("cpu")
    for var in devicepolicy.ACCELERATOR_BOOTSTRAP_VARS:
        assert env[var] is None  # None == remove from inherited env
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env[devicepolicy.PLATFORM_VAR] == "cpu"


def test_probe_armed_only_on_accelerator_hosts(monkeypatch):
    for var in devicepolicy.ACCELERATOR_BOOTSTRAP_VARS:
        monkeypatch.delenv(var, raising=False)
    assert devicepolicy.PROBE_VAR not in devicepolicy.worker_env("cpu")
    # presence of any bootstrap trigger in the PARENT env arms the probe
    monkeypatch.setenv(devicepolicy.ACCELERATOR_BOOTSTRAP_VARS[0], "x")
    assert devicepolicy.worker_env("cpu")[devicepolicy.PROBE_VAR] == "1"


def test_worker_env_none_platform_inherits_everything():
    assert devicepolicy.worker_env(None) == {}


def test_scrub_vars_extensible_via_env(monkeypatch):
    monkeypatch.setenv("TPU_ML_WORKER_SCRUB_VARS", "MY_PLUGIN_TRIGGER, OTHER")
    assert "MY_PLUGIN_TRIGGER" in devicepolicy.scrub_vars()
    assert "OTHER" in devicepolicy.scrub_vars()


def test_apply_overrides_deletes_on_none():
    base = {"KEEP": "1", "DROP": "2"}
    out = devicepolicy.apply_overrides(base, {"DROP": None, "NEW": "3"})
    assert out == {"KEEP": "1", "NEW": "3"}


def test_probe_platform_matches_cpu():
    # conftest forces the CPU backend in this process
    assert devicepolicy.probe_platform("cpu", timeout=30) == "cpu"


def test_probe_platform_mismatch_raises():
    with pytest.raises(devicepolicy.DevicePolicyError, match="assigned platform"):
        devicepolicy.probe_platform("tpu", timeout=30)


def _trivial_job(session):
    """One mapInArrow round trip through a real worker process."""
    df = session.createDataFrame(
        [([1.0, 2.0],)],
        LT.StructType([LT.StructField("x", LT.ArrayType(LT.DoubleType()))]),
        numPartitions=1,
    )

    def fn(batches):
        for b in batches:
            yield b

    return df.mapInArrow(
        fn, schema=LT.StructType([LT.StructField("x", LT.ArrayType(LT.DoubleType()))])
    ).collect()


def test_default_policy_runs_on_accelerator_host(monkeypatch):
    """The default session must complete a job even when the parent env
    carries accelerator bootstrap triggers (the scenario that used to hang
    indefinitely on TPU-attached hosts)."""
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", os.environ.get("PALLAS_AXON_POOL_IPS", ""))
    with LocalSparkSession(parallelism=1) as session:
        rows = _trivial_job(session)
    assert np.allclose(rows[0]["x"], [1.0, 2.0])


def test_wrong_platform_fails_fast_not_hang():
    """A worker assigned a platform it cannot get must error within the
    probe bound — the driver sees a WorkerException naming the policy."""
    session = LocalSparkSession(
        parallelism=1,
        worker_env={
            devicepolicy.PLATFORM_VAR: "tpu",  # expect tpu...
            "JAX_PLATFORMS": "cpu",            # ...but force cpu: mismatch
            devicepolicy.PROBE_VAR: "1",
            devicepolicy.PROBE_TIMEOUT_VAR: "30",
        },
    )
    try:
        with pytest.raises(WorkerException) as err:
            _trivial_job(session)
        assert "device-policy probe" in str(err.value)
        assert "device policy violation" in str(err.value)
    finally:
        session.stop()


def test_probe_timeout_fails_fast():
    """Even if JAX init blocks (simulated with a tiny timeout), the worker
    exits with a diagnosis instead of hanging the job."""
    session = LocalSparkSession(
        parallelism=1,
        worker_env={
            devicepolicy.PROBE_VAR: "1",
            devicepolicy.PROBE_TIMEOUT_VAR: "0.000001",
        },
    )
    try:
        with pytest.raises(WorkerException) as err:
            _trivial_job(session)
        assert "did not complete within" in str(err.value)
    finally:
        session.stop()


def test_use_platform_wins_and_probes():
    # use_platform must (a) win over any interpreter-start hook by issuing a
    # late jax.config.update, (b) bounded-probe, (c) return the platform
    from spark_rapids_ml_tpu.utils import devicepolicy

    assert devicepolicy.use_platform("cpu", probe_timeout=30) == "cpu"
    import jax

    assert jax.devices()[0].platform == "cpu"


def test_use_platform_mismatch_raises():
    import jax
    import pytest

    from spark_rapids_ml_tpu.utils import devicepolicy

    try:
        with pytest.raises(devicepolicy.DevicePolicyError):
            # the CPU backend is already initialized: the first probe sees
            # the platform mismatch, use_platform clears the stale backend
            # set and re-probes, and the re-init with an unknown platform
            # fails — a DevicePolicyError either way, never a hang
            devicepolicy.use_platform("nonexistent_platform", probe_timeout=30)
    finally:
        jax.config.update("jax_platforms", "cpu")  # restore for later tests


def test_probe_transport_subprocess_cpu_ok():
    # CPU-scrubbed child: proves the subprocess probe mechanics (fresh
    # interpreter, self-bounded exit, platform on stdout) without touching
    # any accelerator transport
    ok, detail = devicepolicy.probe_transport_subprocess(
        timeout=60, env_overrides=devicepolicy.worker_env("cpu")
    )
    assert ok, detail
    assert detail == "cpu"


def test_probe_transport_subprocess_failure_is_returned_not_raised():
    # a child whose probe must time out instantly reports (False, diagnosis)
    ok, detail = devicepolicy.probe_transport_subprocess(
        timeout=1e-6, env_overrides=devicepolicy.worker_env("cpu")
    )
    assert not ok
    assert "did not complete within" in detail


def test_wait_for_transport_recovers_after_transient_failure():
    calls = []

    def flaky_probe(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            return False, "wedged"
        return True, "axon"

    msgs = []
    platform = devicepolicy.wait_for_transport(
        window=60,
        attempt_timeout=5,
        backoff_start=0.01,
        backoff_max=0.02,
        log=msgs.append,
        probe=flaky_probe,
    )
    assert platform == "axon"
    assert len(calls) == 3
    assert any("retrying" in m for m in msgs)


def test_wait_for_transport_window_expiry_raises_with_attempt_log():
    def dead_probe(timeout):
        return False, "transport permanently wedged"

    with pytest.raises(devicepolicy.DevicePolicyError) as err:
        devicepolicy.wait_for_transport(
            window=0.05,
            attempt_timeout=1,
            backoff_start=0.02,
            backoff_max=0.02,
            log=lambda m: None,
            probe=dead_probe,
        )
    assert "did not become healthy" in str(err.value)
    assert "permanently wedged" in str(err.value)


def test_probe_platform_none_accepts_any(monkeypatch):
    # expected=None must mean "any platform is fine" even when the worker
    # env contract var is present — an env var must not re-enable a check
    # the caller explicitly opted out of
    from spark_rapids_ml_tpu.utils import devicepolicy

    monkeypatch.setenv(devicepolicy.PLATFORM_VAR, "tpu")
    assert devicepolicy.probe_platform(expected=None, timeout=30) == "cpu"
