"""Incremental partial_fit/finalize — equality with one-shot fits.

The monoid structure guarantees streaming == batch; these tests pin that
contract for every solver route.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    PCA,
    IncrementalLinearRegression,
    IncrementalPCA,
    IncrementalStandardScaler,
    IncrementalTruncatedSVD,
    LinearRegression,
    StandardScaler,
    TruncatedSVD,
)


@pytest.fixture
def x(rng):
    return rng.normal(size=(400, 16)) @ rng.normal(size=(16, 16))


def _chunks(x, sizes):
    out, at = [], 0
    for s in sizes:
        out.append(x[at : at + s])
        at += s
    assert at == len(x)
    return out


class TestIncrementalPCA:
    @pytest.mark.parametrize("solver", ["full", "svd"])
    def test_streaming_equals_batch(self, x, solver):
        inc = IncrementalPCA().setInputCol("f").setK(4).setSolver(solver)
        for chunk in _chunks(x, [150, 130, 120]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = PCA().setInputCol("f").setK(4).setSolver(solver).fit(x)
        np.testing.assert_allclose(m_inc.pc, m_batch.pc, atol=1e-9)
        np.testing.assert_allclose(
            m_inc.explainedVariance, m_batch.explainedVariance, atol=1e-12
        )

    def test_centered_gram_route(self, x):
        xc = x + 5.0
        inc = IncrementalPCA().setInputCol("f").setK(3).setMeanCentering(True)
        for chunk in _chunks(xc, [200, 200]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = PCA().setInputCol("f").setK(3).setMeanCentering(True).fit(xc)
        np.testing.assert_allclose(m_inc.pc, m_batch.pc, atol=1e-9)

    def test_centered_svd_route_rejected(self, x):
        inc = IncrementalPCA().setK(2).setSolver("svd").setMeanCentering(True)
        with pytest.raises(ValueError, match="global mean"):
            inc.partial_fit(x)

    def test_rows_seen_and_reset(self, x):
        inc = IncrementalPCA().setK(2)
        inc.partial_fit(x[:100]).partial_fit(x[100:250])
        assert inc.n_rows_seen == 250
        inc.reset()
        assert inc.n_rows_seen == 0
        with pytest.raises(ValueError, match="before any partial_fit"):
            inc.finalize()

    def test_inconsistent_width_rejected(self, x):
        inc = IncrementalPCA().setK(2)
        inc.partial_fit(x)
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit(x[:, :8])

    def test_solver_switch_mid_stream_rejected(self, x):
        inc = IncrementalPCA().setK(2).setSolver("full")
        inc.partial_fit(x[:100])
        inc._set(solver="svd")
        with pytest.raises(ValueError, match="solver changed mid-stream"):
            inc.partial_fit(x[100:])
        with pytest.raises(ValueError, match="solver changed mid-stream"):
            inc.finalize()  # switch AFTER the last batch is the same mistake
        # reset clears the pin
        inc.reset()
        inc.partial_fit(x)
        assert inc.finalize().pc.shape == (16, 2)

    def test_transform_from_finalized(self, x):
        inc = IncrementalPCA().setInputCol("f").setK(3)
        inc.partial_fit(x)
        model = inc.finalize()
        out = np.asarray(model.transform(x))
        np.testing.assert_allclose(out, x @ model.pc, atol=1e-8)


class TestIncrementalTruncatedSVD:
    @pytest.mark.parametrize("solver", ["gram", "svd"])
    def test_streaming_equals_batch(self, x, solver):
        inc = IncrementalTruncatedSVD().setInputCol("f").setK(5).setSolver(solver)
        for chunk in _chunks(x, [100, 300]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = TruncatedSVD().setInputCol("f").setK(5).setSolver(solver).fit(x)
        np.testing.assert_allclose(m_inc.components, m_batch.components, atol=1e-9)
        np.testing.assert_allclose(
            m_inc.singularValues, m_batch.singularValues, rtol=1e-10
        )


class TestIncrementalScaler:
    def test_streaming_equals_batch(self, x):
        inc = IncrementalStandardScaler().setInputCol("f")
        for chunk in _chunks(x, [50, 250, 100]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = StandardScaler().setInputCol("f").fit(x)
        np.testing.assert_allclose(m_inc.mean, m_batch.mean, rtol=1e-12)
        np.testing.assert_allclose(m_inc.std, m_batch.std, rtol=1e-12)

    def test_unfinalized_raises(self):
        with pytest.raises(ValueError, match="before any partial_fit"):
            IncrementalStandardScaler().finalize()

    def test_kwargs_forwarded(self, x):
        inc = IncrementalStandardScaler(inputCol="f", withMean=True)
        assert inc.getOrDefault("withMean") is True

    def test_width_mismatch_rejected(self, x):
        inc = IncrementalStandardScaler().partial_fit(x)
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit(x[:, :4])


class TestIncrementalLinearRegression:
    @pytest.fixture
    def xy(self, rng):
        x = rng.normal(size=(400, 8))
        w = np.array([1.0, -2.0, 0.0, 3.0, 0.0, 0.5, 0.0, -1.0])
        y = x @ w + 0.8 + 0.01 * rng.normal(size=400)
        return x, y

    def test_streaming_equals_batch(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression(regParam=0.05)
        for lo, hi in [(0, 150), (150, 280), (280, 400)]:
            inc.partial_fit((x[lo:hi], y[lo:hi]))
        assert inc.n_rows_seen == 400
        m_inc = inc.finalize()
        m_batch = LinearRegression(regParam=0.05).fit((x, y))
        np.testing.assert_allclose(m_inc.coefficients, m_batch.coefficients, atol=1e-10)
        np.testing.assert_allclose(m_inc.intercept, m_batch.intercept, atol=1e-10)

    def test_streaming_elastic_net_equals_batch(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression(
            regParam=0.1, elasticNetParam=1.0, tol=1e-12
        )
        for lo, hi in [(0, 200), (200, 400)]:
            inc.partial_fit((x[lo:hi], y[lo:hi]))
        m_inc = inc.finalize()
        m_batch = LinearRegression(
            regParam=0.1, elasticNetParam=1.0, tol=1e-12
        ).fit((x, y))
        np.testing.assert_allclose(m_inc.coefficients, m_batch.coefficients, atol=1e-10)

    def test_weighted_stream(self, xy):
        x, y = xy
        w = np.linspace(0.5, 2.0, len(x))
        inc = IncrementalLinearRegression()
        inc.partial_fit((x[:250], y[:250], w[:250]))
        inc.partial_fit((x[250:], y[250:], w[250:]))
        # rows, not the weight sum (LinearStats.count is the weight sum)
        assert inc.n_rows_seen == len(x)
        m_inc = inc.finalize()
        m_batch = LinearRegression().fit((x, y, w))
        np.testing.assert_allclose(m_inc.coefficients, m_batch.coefficients, atol=1e-10)

    def test_unfinalized_raises(self):
        with pytest.raises(ValueError, match="before any partial_fit"):
            IncrementalLinearRegression().finalize()

    def test_width_mismatch_rejected(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression().partial_fit((x, y))
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit((x[:, :4], y))

    def test_reset(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression().partial_fit((x, y))
        inc.reset()
        assert inc.n_rows_seen == 0
        with pytest.raises(ValueError, match="before any partial_fit"):
            inc.finalize()
