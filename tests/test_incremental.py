"""Incremental partial_fit/finalize — equality with one-shot fits.

The monoid structure guarantees streaming == batch; these tests pin that
contract for every solver route.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    PCA,
    IncrementalKMeans,
    IncrementalLinearRegression,
    IncrementalPCA,
    IncrementalStandardScaler,
    IncrementalTruncatedSVD,
    LinearRegression,
    StandardScaler,
    TruncatedSVD,
)


@pytest.fixture
def x(rng):
    return rng.normal(size=(400, 16)) @ rng.normal(size=(16, 16))


def _chunks(x, sizes):
    out, at = [], 0
    for s in sizes:
        out.append(x[at : at + s])
        at += s
    assert at == len(x)
    return out


class TestIncrementalPCA:
    @pytest.mark.parametrize("solver", ["full", "svd"])
    def test_streaming_equals_batch(self, x, solver):
        inc = IncrementalPCA().setInputCol("f").setK(4).setSolver(solver)
        for chunk in _chunks(x, [150, 130, 120]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = PCA().setInputCol("f").setK(4).setSolver(solver).fit(x)
        np.testing.assert_allclose(m_inc.pc, m_batch.pc, atol=1e-9)
        np.testing.assert_allclose(
            m_inc.explainedVariance, m_batch.explainedVariance, atol=1e-12
        )

    def test_centered_gram_route(self, x):
        xc = x + 5.0
        inc = IncrementalPCA().setInputCol("f").setK(3).setMeanCentering(True)
        for chunk in _chunks(xc, [200, 200]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = PCA().setInputCol("f").setK(3).setMeanCentering(True).fit(xc)
        np.testing.assert_allclose(m_inc.pc, m_batch.pc, atol=1e-9)

    def test_centered_svd_route_rejected(self, x):
        inc = IncrementalPCA().setK(2).setSolver("svd").setMeanCentering(True)
        with pytest.raises(ValueError, match="global mean"):
            inc.partial_fit(x)

    def test_rows_seen_and_reset(self, x):
        inc = IncrementalPCA().setK(2)
        inc.partial_fit(x[:100]).partial_fit(x[100:250])
        assert inc.n_rows_seen == 250
        inc.reset()
        assert inc.n_rows_seen == 0
        with pytest.raises(ValueError, match="before any partial_fit"):
            inc.finalize()

    def test_inconsistent_width_rejected(self, x):
        inc = IncrementalPCA().setK(2)
        inc.partial_fit(x)
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit(x[:, :8])

    def test_solver_switch_mid_stream_rejected(self, x):
        inc = IncrementalPCA().setK(2).setSolver("full")
        inc.partial_fit(x[:100])
        inc._set(solver="svd")
        with pytest.raises(ValueError, match="solver changed mid-stream"):
            inc.partial_fit(x[100:])
        with pytest.raises(ValueError, match="solver changed mid-stream"):
            inc.finalize()  # switch AFTER the last batch is the same mistake
        # reset clears the pin
        inc.reset()
        inc.partial_fit(x)
        assert inc.finalize().pc.shape == (16, 2)

    def test_transform_from_finalized(self, x):
        inc = IncrementalPCA().setInputCol("f").setK(3)
        inc.partial_fit(x)
        model = inc.finalize()
        out = np.asarray(model.transform(x))
        np.testing.assert_allclose(out, x @ model.pc, atol=1e-8)


class TestIncrementalTruncatedSVD:
    @pytest.mark.parametrize("solver", ["gram", "svd"])
    def test_streaming_equals_batch(self, x, solver):
        inc = IncrementalTruncatedSVD().setInputCol("f").setK(5).setSolver(solver)
        for chunk in _chunks(x, [100, 300]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = TruncatedSVD().setInputCol("f").setK(5).setSolver(solver).fit(x)
        np.testing.assert_allclose(m_inc.components, m_batch.components, atol=1e-9)
        np.testing.assert_allclose(
            m_inc.singularValues, m_batch.singularValues, rtol=1e-10
        )


class TestIncrementalScaler:
    def test_streaming_equals_batch(self, x):
        inc = IncrementalStandardScaler().setInputCol("f")
        for chunk in _chunks(x, [50, 250, 100]):
            inc.partial_fit(chunk)
        m_inc = inc.finalize()
        m_batch = StandardScaler().setInputCol("f").fit(x)
        np.testing.assert_allclose(m_inc.mean, m_batch.mean, rtol=1e-12)
        np.testing.assert_allclose(m_inc.std, m_batch.std, rtol=1e-12)

    def test_unfinalized_raises(self):
        with pytest.raises(ValueError, match="before any partial_fit"):
            IncrementalStandardScaler().finalize()

    def test_kwargs_forwarded(self, x):
        inc = IncrementalStandardScaler(inputCol="f", withMean=True)
        assert inc.getOrDefault("withMean") is True

    def test_width_mismatch_rejected(self, x):
        inc = IncrementalStandardScaler().partial_fit(x)
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit(x[:, :4])


class TestIncrementalLinearRegression:
    @pytest.fixture
    def xy(self, rng):
        x = rng.normal(size=(400, 8))
        w = np.array([1.0, -2.0, 0.0, 3.0, 0.0, 0.5, 0.0, -1.0])
        y = x @ w + 0.8 + 0.01 * rng.normal(size=400)
        return x, y

    def test_streaming_equals_batch(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression(regParam=0.05)
        for lo, hi in [(0, 150), (150, 280), (280, 400)]:
            inc.partial_fit((x[lo:hi], y[lo:hi]))
        assert inc.n_rows_seen == 400
        m_inc = inc.finalize()
        m_batch = LinearRegression(regParam=0.05).fit((x, y))
        np.testing.assert_allclose(m_inc.coefficients, m_batch.coefficients, atol=1e-10)
        np.testing.assert_allclose(m_inc.intercept, m_batch.intercept, atol=1e-10)

    def test_streaming_elastic_net_equals_batch(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression(
            regParam=0.1, elasticNetParam=1.0, tol=1e-12
        )
        for lo, hi in [(0, 200), (200, 400)]:
            inc.partial_fit((x[lo:hi], y[lo:hi]))
        m_inc = inc.finalize()
        m_batch = LinearRegression(
            regParam=0.1, elasticNetParam=1.0, tol=1e-12
        ).fit((x, y))
        np.testing.assert_allclose(m_inc.coefficients, m_batch.coefficients, atol=1e-10)

    def test_weighted_stream(self, xy):
        x, y = xy
        w = np.linspace(0.5, 2.0, len(x))
        inc = IncrementalLinearRegression()
        inc.partial_fit((x[:250], y[:250], w[:250]))
        inc.partial_fit((x[250:], y[250:], w[250:]))
        # rows, not the weight sum (LinearStats.count is the weight sum)
        assert inc.n_rows_seen == len(x)
        m_inc = inc.finalize()
        m_batch = LinearRegression().fit((x, y, w))
        np.testing.assert_allclose(m_inc.coefficients, m_batch.coefficients, atol=1e-10)

    def test_unfinalized_raises(self):
        with pytest.raises(ValueError, match="before any partial_fit"):
            IncrementalLinearRegression().finalize()

    def test_width_mismatch_rejected(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression().partial_fit((x, y))
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit((x[:, :4], y))

    def test_reset(self, xy):
        x, y = xy
        inc = IncrementalLinearRegression().partial_fit((x, y))
        inc.reset()
        assert inc.n_rows_seen == 0
        with pytest.raises(ValueError, match="before any partial_fit"):
            inc.finalize()


class TestIncrementalKMeans:
    """Mini-batch semantics (Sculley) — NOT monoid-exact like the others:
    the contract is convergence quality, seeding, weighting, lifecycle."""

    def _blobs(self, rng, rows=1200):
        anchors = np.array(
            [[6.0, 0.0, 0.0], [0.0, 6.0, 0.0], [0.0, 0.0, 6.0]]
        )
        y = np.arange(rows) % 3
        return anchors[y] + 0.5 * rng.normal(size=(rows, 3)), anchors

    def test_streaming_recovers_blob_structure(self, rng):
        x, anchors = self._blobs(rng)
        inc = IncrementalKMeans(k=3, seed=5).setSeedRows(300)
        for chunk in np.array_split(x, 8):
            inc.partial_fit(chunk)
        model = inc.finalize()
        assert inc.n_rows_seen == len(x)
        d = np.linalg.norm(
            model.clusterCenters[:, None, :] - anchors[None, :, :], axis=2
        )
        assert d.min(axis=0).max() < 1.0  # every anchor has a nearby center
        # the model is a NORMAL KMeansModel: transform works
        preds = np.asarray(model.transform(x))
        assert len(np.unique(preds)) == 3

    def test_seed_buffering_and_short_stream_finalize(self, rng):
        x, _ = self._blobs(rng, rows=600)
        inc = IncrementalKMeans(k=3, seed=5).setSeedRows(500)
        inc.partial_fit(x[:200])  # below the buffer threshold
        # a short stream still finalizes: seeding happens from the buffer
        m_short = inc.finalize()
        assert np.isfinite(m_short.trainingCost)
        assert m_short.clusterCenters.shape == (3, 3)
        # nothing streamed at all -> a clear error
        with pytest.raises(ValueError, match="no rows were streamed"):
            IncrementalKMeans(k=3).finalize()

    def test_seed_failure_keeps_the_buffer(self, rng):
        # a buffer without k positive-weight rows raises WITHOUT consuming
        # what was streamed; feeding more rows afterwards succeeds
        x, _ = self._blobs(rng, rows=300)
        inc = IncrementalKMeans(k=3, seed=5).setSeedRows(100)
        with pytest.raises(ValueError, match="positive weight"):
            inc.partial_fit(x[:150], sample_weight=np.zeros(150))
        inc.partial_fit(x[150:])  # buffer crossed threshold again: seeds
        m = inc.finalize()
        assert np.isfinite(m.trainingCost)

    def test_init_mode_random_honored(self, rng):
        # the param must change the seeding (not silently run k-means++);
        # quality bounds stay loose — uniform seeds can land two-in-a-blob
        # and the 1/n mini-batch rate then separates them only slowly
        x, anchors = self._blobs(rng, rows=900)

        def run(mode):
            inc = (
                IncrementalKMeans(k=3, seed=5, initMode=mode)
                .setSeedRows(300)
            )
            for chunk in np.array_split(x, 6):
                inc.partial_fit(chunk)
            return inc.finalize().clusterCenters

        c_rand, c_kpp = run("random"), run("k-means++")
        assert np.all(np.isfinite(c_rand))
        assert not np.allclose(c_rand, c_kpp)  # different seeding ran
        d = np.linalg.norm(c_rand[:, None, :] - anchors[None, :, :], axis=2)
        assert d.min() < 1.0  # at least lands on the blob structure

    def test_zero_weight_rows_never_seed_or_move_centers(self, rng):
        x, anchors = self._blobs(rng, rows=900)
        poison = np.full((100, 3), 40.0)
        xa = np.vstack([x, poison])
        w = np.concatenate([np.ones(len(x)), np.zeros(100)])
        perm = rng.permutation(len(xa))
        xa, w = xa[perm], w[perm]
        inc = IncrementalKMeans(k=3, seed=5).setSeedRows(400)
        for sl in np.array_split(np.arange(len(xa)), 5):
            inc.partial_fit(xa[sl], sample_weight=w[sl])
        centers = inc.finalize().clusterCenters
        assert np.abs(centers).max() < 10.0  # nothing pulled toward 40

    def test_reset_and_width_mismatch(self, rng):
        x, _ = self._blobs(rng, rows=400)
        inc = IncrementalKMeans(k=3, seed=5).setSeedRows(100)
        inc.partial_fit(x)
        with pytest.raises(ValueError, match="inconsistent feature dim"):
            inc.partial_fit(x[:, :2])
        inc.reset()
        assert inc.n_rows_seen == 0
        with pytest.raises(ValueError, match="seeding"):
            inc.finalize()

    def test_seed_rows_validation(self):
        with pytest.raises(ValueError, match="seedRows"):
            IncrementalKMeans().setSeedRows(0)
