"""tpulint: per-rule fixtures, suppressions, baseline, and the meta-test.

Every rule gets (a) a minimal true-positive snippet that MUST fire and
(b) a nearby false-positive pattern — the idiom the codebase actually
uses — that MUST stay clean. The meta-test then lints the live package
with the checked-in baseline, which is exactly what CI's strict run does:
these tests failing and CI failing are the same event.

Pure stdlib on purpose (no jax import): the lint layer must work in
jax-free checkouts, so its tests prove that property by existing.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from spark_rapids_ml_tpu.analysis.engine import (
    Baseline,
    Finding,
    LintedModule,
    lint_paths,
    lint_source,
)
from spark_rapids_ml_tpu.analysis import rules as R

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fire(source: str, rule, relpath: str = "pkg/mod.py") -> list:
    """Unsuppressed findings of one rule on a dedented snippet."""
    found = lint_source(textwrap.dedent(source), relpath, [rule])
    return [f for f in found if not f.suppressed]


# ---------------------------------------------------------------------------
# TPL001 donated-carry


class TestDonatedCarry:
    def test_undonated_carry_fires(self):
        src = """
            import jax
            def step(carry, x):
                return carry + x
            prog = jax.jit(step)
        """
        found = fire(src, R.DonatedCarryRule())
        assert len(found) == 1
        assert "carry" in found[0].message
        assert found[0].rule == "TPL001"

    def test_positional_index_named(self):
        src = """
            import jax
            def run(x, w, centers0, budget):
                return centers0
            prog = jax.jit(run, donate_argnums=1)
        """
        (f,) = fire(src, R.DonatedCarryRule())
        assert "arg 2" in f.message

    def test_donated_carry_clean(self):
        src = """
            import jax
            def step(carry, x):
                return carry + x
            prog = jax.jit(step, donate_argnums=0)
        """
        assert fire(src, R.DonatedCarryRule()) == []

    def test_donate_argnames_clean(self):
        src = """
            import jax
            def step(carry, x):
                return carry + x
            prog = jax.jit(step, donate_argnames=("carry",))
        """
        assert fire(src, R.DonatedCarryRule()) == []

    def test_decorated_def_fires(self):
        src = """
            import jax
            from functools import partial
            @partial(jax.jit, static_argnums=(1,))
            def fold(acc, n):
                return acc + n
        """
        (f,) = fire(src, R.DonatedCarryRule())
        assert "acc" in f.message

    def test_no_carry_param_clean(self):
        src = """
            import jax
            def kernel(x, y):
                return x @ y
            prog = jax.jit(kernel)
        """
        assert fire(src, R.DonatedCarryRule()) == []

    def test_same_name_other_scope_not_confused(self):
        # two defs named `run`; the jit call must resolve to ITS `run`
        src = """
            import jax
            def make_a():
                def run(x, w, centers0, budget):
                    return centers0
                return jax.jit(run, donate_argnums=2)
            def make_b():
                def run(x, key):
                    return x
                return jax.jit(run)
        """
        assert fire(src, R.DonatedCarryRule()) == []


# ---------------------------------------------------------------------------
# TPL002 host-sync


class TestHostSync:
    def test_float_in_jitted_fires(self):
        src = """
            import jax
            @jax.jit
            def f(x):
                return float(x)
        """
        (f,) = fire(src, R.HostSyncRule())
        assert f.rule == "TPL002"

    def test_item_in_jit_target_fires(self):
        src = """
            import jax
            def g(x):
                return x.item()
            prog = jax.jit(g)
        """
        (f,) = fire(src, R.HostSyncRule())
        assert ".item()" in f.message

    def test_shape_read_clean(self):
        src = """
            import jax
            @jax.jit
            def f(x):
                return float(x.shape[0]) + float(len(x))
        """
        assert fire(src, R.HostSyncRule()) == []

    def test_np_asarray_in_traced_fires(self):
        src = """
            import jax
            import numpy as np
            @jax.jit
            def f(x):
                return np.asarray(x)
        """
        (f,) = fire(src, R.HostSyncRule())
        assert "jnp" in f.message

    def test_ops_module_methods_flagged_everywhere(self):
        src = """
            def helper(x):
                x.block_until_ready()
        """
        found = fire(src, R.HostSyncRule(), "spark_rapids_ml_tpu/ops/foo.py")
        assert len(found) == 1

    def test_telemetry_exempt(self):
        src = """
            import jax
            @jax.jit
            def f(x):
                return float(x)
        """
        found = fire(
            src, R.HostSyncRule(), "spark_rapids_ml_tpu/telemetry/foo.py"
        )
        assert found == []

    def test_untraced_host_code_clean(self):
        src = """
            import numpy as np
            def host_path(x):
                return float(np.asarray(x).sum())
        """
        assert fire(src, R.HostSyncRule()) == []

    def test_serving_module_methods_flagged_everywhere(self):
        """serving/ holds the ops/ whole-module bar: a sync method is
        warm-path latency even outside a traced function."""
        src = """
            def helper(x):
                x.block_until_ready()
        """
        found = fire(
            src, R.HostSyncRule(), "spark_rapids_ml_tpu/serving/foo.py"
        )
        assert len(found) == 1
        assert "serving/" in found[0].message


# ---------------------------------------------------------------------------
# TPL003 recompile-hazard


class TestRecompileHazard:
    def test_jit_in_loop_fires(self):
        src = """
            import jax
            def f(fn, xs):
                out = []
                for x in xs:
                    out.append(jax.jit(fn)(x))
                return out
        """
        (f,) = fire(src, R.RecompileHazardRule())
        assert "loop" in f.message

    def test_jit_per_call_fires(self):
        src = """
            import jax
            def transform(fn, x):
                return jax.jit(fn)(x)
        """
        (f,) = fire(src, R.RecompileHazardRule())
        assert "per call" in f.message

    def test_module_scope_clean(self):
        src = """
            import jax
            def kernel(x):
                return x * 2
            _prog = jax.jit(kernel)
        """
        assert fire(src, R.RecompileHazardRule()) == []

    def test_lru_cached_factory_clean(self):
        src = """
            import jax
            from functools import lru_cache
            @lru_cache(maxsize=32)
            def make_prog(mesh):
                def fold(c, x):
                    return c + x
                return jax.jit(fold, donate_argnums=0)
        """
        assert fire(src, R.RecompileHazardRule()) == []

    def test_suppression_comment(self):
        src = """
            import jax
            def build(fn):
                # hand-rolled once-guard  # tpulint: disable=TPL003
                return jax.jit(fn)
        """
        assert fire(src, R.RecompileHazardRule()) == []

    def test_aot_lower_per_call_in_serving_fires(self):
        src = """
            def dispatch(prog, avals):
                return prog.lower(avals).compile()
        """
        (f,) = fire(
            src,
            R.RecompileHazardRule(),
            "spark_rapids_ml_tpu/serving/foo.py",
        )
        assert "AOT .lower()" in f.message and "per call" in f.message

    def test_aot_lower_in_loop_in_serving_fires(self):
        src = """
            def warm(prog, ladder):
                for avals in ladder:
                    prog.lower(avals).compile()
        """
        (f,) = fire(
            src,
            R.RecompileHazardRule(),
            "spark_rapids_ml_tpu/serving/foo.py",
        )
        assert "loop" in f.message

    def test_aot_lower_in_cached_factory_clean(self):
        src = """
            from functools import lru_cache
            @lru_cache(maxsize=None)
            def compiled_for(prog, avals):
                return prog.lower(avals).compile()
        """
        assert fire(
            src,
            R.RecompileHazardRule(),
            "spark_rapids_ml_tpu/serving/foo.py",
        ) == []

    def test_str_lower_exempt_in_serving(self):
        src = """
            def norm(name):
                return name.lower()
        """
        assert fire(
            src,
            R.RecompileHazardRule(),
            "spark_rapids_ml_tpu/serving/foo.py",
        ) == []

    def test_aot_lower_outside_serving_not_flagged(self):
        src = """
            def dispatch(prog, avals):
                return prog.lower(avals).compile()
        """
        assert fire(src, R.RecompileHazardRule()) == []


# ---------------------------------------------------------------------------
# TPL004 retry-discipline


class TestRetryDiscipline:
    def test_sleep_in_except_fires(self):
        src = """
            import time
            def fetch(fn):
                for attempt in range(3):
                    try:
                        return fn()
                    except OSError:
                        time.sleep(2 ** attempt)
        """
        (f,) = fire(src, R.RetryDisciplineRule())
        assert "call_with_retry" in f.message

    def test_backoff_variable_fires(self):
        src = """
            import time
            def poll(backoff):
                time.sleep(backoff * 2)
        """
        (f,) = fire(src, R.RetryDisciplineRule())
        assert f.rule == "TPL004"

    def test_plain_sleep_clean(self):
        src = """
            import time
            def heartbeat(interval):
                time.sleep(interval)
        """
        assert fire(src, R.RetryDisciplineRule()) == []

    def test_retry_module_exempt(self):
        src = """
            import time
            def call_with_retry(fn):
                try:
                    return fn()
                except OSError:
                    time.sleep(1.0)
        """
        found = fire(
            src, R.RetryDisciplineRule(),
            "spark_rapids_ml_tpu/resilience/retry.py",
        )
        assert found == []


# ---------------------------------------------------------------------------
# TPL005 name-registry


def _names_rule():
    return R.NameRegistryRule(
        metrics=frozenset({"ingest.rows"}),
        prefixes=("device.",),
        spans=frozenset({"fold.dispatch"}),
        instants=frozenset({"stream.chunk"}),
        sites=frozenset({"worker.task"}),
    )


class TestNameRegistry:
    def test_unregistered_metric_fires(self):
        src = """
            REGISTRY.counter_inc("ingest.rowz", 5)
        """
        (f,) = fire(src, _names_rule())
        assert "ingest.rowz" in f.message and f.rule == "TPL005"

    def test_registered_names_clean(self):
        src = """
            REGISTRY.counter_inc("ingest.rows", 5)
            REGISTRY.gauge_set("device.hbm_bytes", 1)
            with trace_range("fold.dispatch"):
                pass
            TIMELINE.record_instant("stream.chunk", rows=5)
        """
        assert fire(src, _names_rule()) == []

    def test_fault_site_checked(self):
        src = """
            from spark_rapids_ml_tpu.resilience import faults
            faults.inject("worker.taskz")
        """
        (f,) = fire(src, _names_rule())
        assert "fault site" in f.message

    def test_dynamic_name_with_unregistered_prefix_fires(self):
        src = """
            def emit(reg, k, v):
                reg.gauge_set(f"devize.{k}", v)
        """
        (f,) = fire(src, _names_rule())
        assert "prefix" in f.message

    def test_nonliteral_skipped(self):
        src = """
            def emit(reg, name, v):
                reg.counter_inc(name, v)
        """
        assert fire(src, _names_rule()) == []

    def test_live_registries_load(self):
        # the default constructor reads the real declaration modules
        rule = R.NameRegistryRule()
        assert "span.seconds" in rule.metrics
        assert "worker.task" in rule.sites


# ---------------------------------------------------------------------------
# TPL006 knob-inventory


class TestKnobInventory:
    def test_undeclared_knob_fires(self):
        rule = R.KnobInventoryRule(declared=frozenset({"TPU_ML_KNOWN"}))
        src = """
            import os
            v = os.environ.get("TPU_ML_MYSTERY_KNOB", "1")
        """
        (f,) = fire(src, rule)
        assert "TPU_ML_MYSTERY_KNOB" in f.message and f.rule == "TPL006"

    def test_declared_knob_clean(self):
        rule = R.KnobInventoryRule(declared=frozenset({"TPU_ML_KNOWN"}))
        src = """
            import os
            v = os.environ.get("TPU_ML_KNOWN", "1")
        """
        assert fire(src, rule) == []

    def test_docstring_mention_clean(self):
        rule = R.KnobInventoryRule(declared=frozenset())
        src = '''
            def f():
                """Reads TPU_ML_SOMETHING from the environment."""
                return 1
        '''
        assert fire(src, rule) == []

    def test_knobs_module_exempt(self):
        rule = R.KnobInventoryRule(declared=frozenset())
        src = """
            NAME = "TPU_ML_NEW_KNOB"
        """
        found = fire(
            src, rule, "spark_rapids_ml_tpu/utils/knobs.py"
        )
        assert found == []

    def test_live_inventory_covers_repo_reads(self):
        from spark_rapids_ml_tpu.utils import knobs

        assert "TPU_ML_MIN_BUCKET" in knobs.KNOBS
        assert knobs.FAULT_PLAN.name == "TPU_ML_FAULT_PLAN"
        # every declaration renders into the table
        table = knobs.markdown_table()
        for name in knobs.KNOBS:
            assert name in table


# ---------------------------------------------------------------------------
# TPL007 telemetry-race


class TestTelemetryRace:
    PATH = "spark_rapids_ml_tpu/telemetry/mod.py"

    def test_unlocked_mutation_fires(self):
        src = """
            _events = []
            def record(e):
                _events.append(e)
        """
        (f,) = fire(src, R.TelemetryRaceRule(), self.PATH)
        assert "_events" in f.message and f.rule == "TPL007"

    def test_locked_mutation_clean(self):
        src = """
            import threading
            _events = []
            _lock = threading.Lock()
            def record(e):
                with _lock:
                    _events.append(e)
        """
        assert fire(src, R.TelemetryRaceRule(), self.PATH) == []

    def test_global_rebind_fires(self):
        src = """
            _cache = {}
            def reset():
                global _cache
                _cache = {}
        """
        (f,) = fire(src, R.TelemetryRaceRule(), self.PATH)
        assert "_cache" in f.message

    def test_subscript_store_fires(self):
        src = """
            _by_name = {}
            def put(k, v):
                _by_name[k] = v
        """
        (f,) = fire(src, R.TelemetryRaceRule(), self.PATH)
        assert "_by_name" in f.message

    def test_outside_scoped_dirs_clean(self):
        src = """
            _events = []
            def record(e):
                _events.append(e)
        """
        assert fire(src, R.TelemetryRaceRule(), "pkg/models/foo.py") == []

    def test_local_mutable_clean(self):
        src = """
            def collect(xs):
                out = []
                for x in xs:
                    out.append(x)
                return out
        """
        assert fire(src, R.TelemetryRaceRule(), self.PATH) == []


# ---------------------------------------------------------------------------
# TPL008 swallowed-exception


class TestSwallowedException:
    def test_except_pass_fires(self):
        src = """
            def f(fn):
                try:
                    fn()
                except Exception:
                    pass
        """
        (f,) = fire(src, R.SwallowedExceptionRule())
        assert f.rule == "TPL008"

    def test_bare_except_fires(self):
        src = """
            def f(fn):
                try:
                    fn()
                except:
                    pass
        """
        (f,) = fire(src, R.SwallowedExceptionRule())
        assert "bare except" in f.message

    def test_commented_pass_clean(self):
        src = """
            def f(fn):
                try:
                    fn()
                except Exception:
                    pass  # best-effort cleanup; process exits right after
        """
        assert fire(src, R.SwallowedExceptionRule()) == []

    def test_narrow_handler_clean(self):
        src = """
            def f(fn):
                try:
                    fn()
                except OSError:
                    pass
        """
        assert fire(src, R.SwallowedExceptionRule()) == []

    def test_handled_broad_clean(self):
        src = """
            def f(fn, log):
                try:
                    fn()
                except Exception as e:
                    log.warning("failed: %s", e)
        """
        assert fire(src, R.SwallowedExceptionRule()) == []


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, fingerprints


class TestSuppression:
    SRC = """
        import jax
        def step(carry, x):
            return carry + x
        prog = jax.jit(step)
    """

    def test_same_line_suppression(self):
        src = self.SRC.replace(
            "prog = jax.jit(step)",
            "prog = jax.jit(step)  # tpulint: disable=TPL001",
        )
        found = lint_source(
            textwrap.dedent(src), "m.py", [R.DonatedCarryRule()]
        )
        assert len(found) == 1 and found[0].suppressed

    def test_preceding_comment_line_suppression(self):
        src = textwrap.dedent(self.SRC).replace(
            "prog = jax.jit(step)",
            "# tpulint: disable=TPL001\nprog = jax.jit(step)",
        )
        found = lint_source(src, "m.py", [R.DonatedCarryRule()])
        assert found[0].suppressed

    def test_disable_all(self):
        src = self.SRC.replace(
            "prog = jax.jit(step)",
            "prog = jax.jit(step)  # tpulint: disable=all",
        )
        found = lint_source(
            textwrap.dedent(src), "m.py", [R.DonatedCarryRule()]
        )
        assert found[0].suppressed

    def test_other_rule_not_suppressed(self):
        src = self.SRC.replace(
            "prog = jax.jit(step)",
            "prog = jax.jit(step)  # tpulint: disable=TPL002",
        )
        found = lint_source(
            textwrap.dedent(src), "m.py", [R.DonatedCarryRule()]
        )
        assert not found[0].suppressed


class TestBaseline:
    def _finding(self, line=5):
        return Finding(
            rule="TPL001", path="a.py", line=line, col=0,
            message="carry not donated", scope="make",
        )

    def test_fingerprint_ignores_line_drift(self):
        assert self._finding(5).fingerprint == self._finding(50).fingerprint

    def test_fingerprint_distinguishes_scope(self):
        other = self._finding()
        other.scope = "other_factory"
        assert other.fingerprint != self._finding().fingerprint

    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        f = self._finding()
        Baseline.write(path, [f], notes={f.fingerprint: "why"})
        loaded = Baseline.load(path)
        fresh = self._finding(line=99)  # drifted
        loaded.apply([fresh])
        assert fresh.baselined and fresh.note == "why"
        assert loaded.stale([fresh]) == []

    def test_stale_detection(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.write(path, [self._finding()])
        loaded = Baseline.load(path)
        stale = loaded.stale([])  # the finding was fixed
        assert len(stale) == 1 and stale[0]["rule"] == "TPL001"

    def test_missing_file_is_empty(self, tmp_path):
        b = Baseline.load(str(tmp_path / "nope.json"))
        assert b.entries == {}


# ---------------------------------------------------------------------------
# the meta-test: lint the live package exactly like CI does


class TestLivePackage:
    @pytest.fixture(scope="class")
    def live(self):
        paths = [os.path.join(REPO, p)
                 for p in ("spark_rapids_ml_tpu", "tools", "bench.py")]
        findings, errors = lint_paths(paths, R.all_rules(), root=REPO)
        assert errors == [], errors
        return findings

    def test_repo_is_clean_modulo_baseline(self, live):
        baseline = Baseline.load(
            os.path.join(REPO, "tools", "tpulint_baseline.json")
        )
        unsuppressed = [f for f in live if not f.suppressed]
        baseline.apply(unsuppressed)
        live_findings = [f for f in unsuppressed if not f.baselined]
        assert live_findings == [], "\n".join(
            f.render() for f in live_findings
        )
        stale = baseline.stale(unsuppressed)
        assert stale == [], f"stale baseline entries: {stale}"

    def test_every_baseline_entry_has_real_note(self):
        doc = json.load(
            open(os.path.join(REPO, "tools", "tpulint_baseline.json"))
        )
        for e in doc["entries"]:
            assert e["note"] and "blessed without note" not in e["note"], (
                f"baseline entry for {e['path']} lacks a justification"
            )

    def test_cli_strict_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--strict"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_nonzero_on_finding(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax
            def step(carry, x):
                return carry + x
            prog = jax.jit(step)
        """))
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--strict",
             "--baseline", "", str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "TPL001" in proc.stdout

    def test_cli_json_output(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\n\ndef f(backoff):\n    time.sleep(backoff)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--json",
             "--baseline", "", str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        doc = json.loads(proc.stdout)
        assert doc["live"] == 1
        assert doc["findings"][0]["rule"] == "TPL004"
        assert doc["findings"][0]["fingerprint"]

    def test_readme_knob_table_in_sync(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--check-readme"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_list_knobs_lists_every_declaration(self):
        from spark_rapids_ml_tpu.utils import knobs

        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--list-knobs"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        for name in knobs.KNOBS:
            assert name in proc.stdout


def test_each_rule_fixture_fails_strict(tmp_path):
    """Acceptance: the CLI exits nonzero on a true positive of EVERY rule."""
    fixtures = {
        "TPL001": """
            import jax
            def step(carry, x):
                return carry + x
            prog = jax.jit(step)
        """,
        "TPL002": """
            import jax
            @jax.jit
            def f(x):
                return float(x)
        """,
        "TPL003": """
            import jax
            def f(fn, xs):
                return [jax.jit(fn)(x) for x in xs]
        """,
        "TPL004": """
            import time
            def f(fn):
                while True:
                    try:
                        return fn()
                    except OSError:
                        time.sleep(1)
        """,
        "TPL005": """
            def f(reg):
                reg.counter_inc("not.a.real.metric", 1)
        """,
        "TPL006": """
            import os
            v = os.environ.get("TPU_ML_NOT_DECLARED_ANYWHERE")
        """,
        "TPL008": """
            def f(fn):
                try:
                    fn()
                except Exception:
                    pass
        """,
    }
    # TPL007 needs a telemetry/ path, exercised separately below
    for rule_id, src in fixtures.items():
        p = tmp_path / f"{rule_id.lower()}.py"
        p.write_text(textwrap.dedent(src))
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--strict",
             "--baseline", "", str(p)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode != 0, f"{rule_id} fixture did not fail strict"
        assert rule_id in proc.stdout, proc.stdout


def test_tpl007_fixture_fails_strict(tmp_path):
    pkg = tmp_path / "telemetry"
    pkg.mkdir()
    p = pkg / "mod.py"
    p.write_text("_events = []\n\ndef record(e):\n    _events.append(e)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "--strict",
         "--baseline", "", str(p)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "TPL007" in proc.stdout
