"""NearestNeighbors differential tests — NumPy full-matrix oracle.

Strategy per SURVEY.md §4: differential against an exhaustive host oracle
(full [q, rows] distance matrix + argsort), the same role CPU Spark MLlib
plays for PCA. Random float data makes distance ties measure-zero, so
index equality is exact.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.models.neighbors import (
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.ops import neighbors as NN


def _oracle(queries, corpus, k, metric):
    """Exhaustive k-NN on the host: (distances, indices), best-first."""
    if metric == "cosine":
        qn = queries / np.maximum(np.linalg.norm(queries, axis=1, keepdims=True), 1e-300)
        cn = corpus / np.maximum(np.linalg.norm(corpus, axis=1, keepdims=True), 1e-300)
        d = 1.0 - qn @ cn.T
        order = np.argsort(d, axis=1)[:, :k]
    elif metric == "inner_product":
        d = queries @ corpus.T
        order = np.argsort(-d, axis=1)[:, :k]
    else:
        d = ((queries[:, None, :] - corpus[None, :, :]) ** 2).sum(-1)
        if metric == "euclidean":
            d = np.sqrt(d)
        order = np.argsort(d, axis=1)[:, :k]
    return np.take_along_axis(d, order, axis=1), order


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    corpus = rng.normal(size=(500, 24))
    queries = rng.normal(size=(73, 24))
    return corpus, queries


@pytest.mark.parametrize("metric", ["euclidean", "sqeuclidean", "cosine", "inner_product"])
def test_kneighbors_matches_oracle(data, metric):
    corpus, queries = data
    k = 9
    model = (
        NearestNeighbors().setK(k).setMetric(metric).fit(corpus)
    )
    dists, idx = model.kneighbors(queries)
    ref_d, ref_i = _oracle(queries, corpus, k, metric)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(dists, ref_d, rtol=1e-8, atol=1e-10)


def test_kernel_blocked_scan_matches_single_block(data):
    """The streaming tournament must be block-size invariant."""
    corpus, queries = data
    valid = np.ones(corpus.shape[0], dtype=bool)
    s1, i1 = NN.knn_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 7,
        block_rows=64,
    )
    s2, i2 = NN.knn_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 7,
        block_rows=500,
    )
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-12)


def test_kernel_valid_mask_excludes_rows(data):
    corpus, queries = data
    valid = np.ones(corpus.shape[0], dtype=bool)
    valid[::2] = False  # half the corpus is padding/excluded
    _, idx = NN.knn_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 5,
    )
    assert np.all(np.asarray(idx) % 2 == 1)


def test_cosine_anticorrelated_and_zero_rows():
    """Cosine edge semantics: anti-parallel → 2, zero row → exactly 1 from
    everything (ranked behind orthogonal-but-nonzero only by tie order)."""
    corpus = np.array([[1.0, 0.0], [-1.0, 0.0], [0.0, 1.0], [0.0, 0.0]])
    model = NearestNeighbors().setMetric("cosine").setK(4).fit(corpus)
    d, i = model.kneighbors(np.array([[2.0, 0.0]]))
    by_item = dict(zip(i[0], d[0]))
    assert by_item[0] == pytest.approx(0.0)
    assert by_item[1] == pytest.approx(2.0)
    assert by_item[2] == pytest.approx(1.0)
    assert by_item[3] == pytest.approx(1.0)
    # ordering is best-first: parallel, then the two at 1, then anti-parallel
    assert i[0, 0] == 0 and i[0, 3] == 1


def test_id_col_with_partition_list():
    """idCol extraction must work for the list-of-Arrow-partitions input
    form that PartitionedDataset.from_any supports."""
    pa = pytest.importorskip("pyarrow")
    rng = np.random.default_rng(5)
    x = rng.normal(size=(30, 4))
    ids = np.arange(30) + 100
    parts = [
        pa.table({"features": list(x[:17]), "id": ids[:17]}),
        pa.table({"features": list(x[17:]), "id": ids[17:]}),
    ]
    model = (
        NearestNeighbors()
        .setInputCol("features")
        .setIdCol("id")
        .setK(1)
        .fit(parts)
    )
    _, got = model.kneighbors(x + 1e-9)
    np.testing.assert_array_equal(got[:, 0], ids)


def test_kneighbors_k_override_and_validation(data):
    corpus, queries = data
    model = NearestNeighbors().setK(3).fit(corpus)
    d5, i5 = model.kneighbors(queries, k=5)
    assert d5.shape == (len(queries), 5)
    d3, _ = model.kneighbors(queries)
    np.testing.assert_allclose(d3, d5[:, :3])
    with pytest.raises(ValueError, match="k="):
        model.kneighbors(queries, k=len(corpus) + 1)
    with pytest.raises(ValueError, match="features"):
        model.kneighbors(np.zeros((2, 3)))
    with pytest.raises(ValueError, match="exceeds the fitted item count"):
        NearestNeighbors().setK(10).fit(corpus[:4])


def test_id_col_maps_indices():
    rng = np.random.default_rng(3)
    pd = pytest.importorskip("pandas")
    corpus = rng.normal(size=(40, 8))
    ids = rng.permutation(1000)[:40]
    df = pd.DataFrame(
        {"features": list(corpus), "item_id": ids}
    )
    model = (
        NearestNeighbors()
        .setInputCol("features")
        .setIdCol("item_id")
        .setK(4)
        .fit(df)
    )
    queries = corpus[:6] + 1e-9
    _, got = model.kneighbors(pd.DataFrame({"features": list(queries)}))
    assert got.dtype == np.int64
    assert np.array_equal(got[:, 0], ids[:6])  # self is its own 1-NN


def test_transform_appends_arrays(data):
    pd = pytest.importorskip("pandas")
    corpus, queries = data
    model = NearestNeighbors().setInputCol("features").setK(4).fit(
        pd.DataFrame({"features": list(corpus)})
    )
    out = model.transform(pd.DataFrame({"features": list(queries)}))
    assert "indices" in out.columns and "distances" in out.columns
    ref_d, ref_i = _oracle(queries, corpus, 4, "euclidean")
    np.testing.assert_array_equal(np.stack(out["indices"]), ref_i)
    np.testing.assert_allclose(np.stack(out["distances"]), ref_d, rtol=1e-8)


def test_persistence_roundtrip(tmp_path, data):
    corpus, queries = data
    model = NearestNeighbors().setK(6).setMetric("cosine").fit(corpus)
    path = str(tmp_path / "nn")
    model.save(path)
    loaded = NearestNeighborsModel.load(path)
    assert loaded.getMetric() == "cosine"
    d0, i0 = model.kneighbors(queries)
    d1, i1 = loaded.kneighbors(queries)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_allclose(d0, d1)


def test_sharded_knn_matches_local(data):
    """Mesh-sharded corpus (8 virtual devices) must agree with the
    single-device kernel exactly — the distributed top-k merge is lossless."""
    import jax
    from spark_rapids_ml_tpu.parallel.mesh import create_mesh
    from spark_rapids_ml_tpu.parallel.neighbors import make_sharded_knn

    corpus, queries = data
    k = 11
    ndev = len(jax.devices())
    mesh = create_mesh(data=ndev)
    # equal shards with per-shard pad rows (valid=0) — the wrapper's layout
    per = -(-corpus.shape[0] // ndev)
    padded = np.zeros((per * ndev, corpus.shape[1]))
    padded[: corpus.shape[0]] = corpus
    valid = np.zeros(per * ndev, dtype=bool)
    valid[: corpus.shape[0]] = True
    # interleave so every shard holds a contiguous slice of the padded array
    run = make_sharded_knn(mesh, k)
    scores, idx = run(
        jnp.asarray(padded), jnp.asarray(valid), jnp.asarray(queries)
    )
    ref_d, ref_i = _oracle(queries, corpus, k, "sqeuclidean")
    np.testing.assert_array_equal(np.asarray(idx), ref_i)
    np.testing.assert_allclose(-np.asarray(scores), ref_d, rtol=1e-9, atol=1e-12)


def test_sharded_knn_k_larger_than_shard():
    """k greater than any single shard's rows: shards pad candidates with
    −inf and the merge still returns the global exact set."""
    import jax
    from spark_rapids_ml_tpu.parallel.mesh import create_mesh
    from spark_rapids_ml_tpu.parallel.neighbors import make_sharded_knn

    rng = np.random.default_rng(11)
    ndev = len(jax.devices())
    corpus = rng.normal(size=(ndev * 3, 5))  # 3 rows per shard
    queries = rng.normal(size=(9, 5))
    k = 7  # > 3 per-shard rows
    mesh = create_mesh(data=ndev)
    run = make_sharded_knn(mesh, k)
    scores, idx = run(
        jnp.asarray(corpus),
        jnp.asarray(np.ones(len(corpus), dtype=bool)),
        jnp.asarray(queries),
    )
    ref_d, ref_i = _oracle(queries, corpus, k, "sqeuclidean")
    np.testing.assert_array_equal(np.asarray(idx), ref_i)
    np.testing.assert_allclose(-np.asarray(scores), ref_d, rtol=1e-9, atol=1e-12)
