"""Live-DataFrame integration suite for every Spark-facing estimator.

The analog of the reference's only test suite (PCASuite.scala:42-88 on the
harness RapidsMLTest.scala:22-33): run fit AND transform through the real
DataFrame execution surface — multi-partition data, plan functions shipped
to worker processes, results collected back — and compare against the
core-path (non-Spark) results as the differential oracle, with the
reference's own sign-invariant abs-tol 1e-5 contract for PCA
(PCASuite.scala:80-87).

Backends: ``localspark`` always (the no-JVM engine whose mapInArrow runs in
separate worker processes — see localspark/worker.py for the fidelity
contract); ``pyspark`` additionally when installed (CI installs it), running
the SAME tests on a real local[4] SparkSession.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    LogisticRegression,
    PCA,
    StandardScaler,
)
from spark_rapids_ml_tpu.spark import (
    SparkKMeans,
    SparkLinearRegression,
    SparkLogisticRegression,
    SparkPCA,
    SparkStandardScaler,
)
from spark_rapids_ml_tpu.spark.estimators import SparkPCAModel


from pyspark_support import have_pyspark as _have_pyspark


if _have_pyspark():
    BACKENDS = ["localspark", "pyspark"]
else:
    # LOUD skip (r3 verdict weak #2): the pyspark half of this module is
    # not a couple of quiet skips — it is every Spark-boundary claim
    # running only against the bundled simulator. The real-Spark evidence
    # then lives in CI's pyspark 3.5/4.0 matrix (build-test.yml
    # `pyspark-integration`), which publishes a SPARK_IT.json artifact per
    # run; a parametrized skip per backend-test makes the gap visible in
    # the skip column instead of silently shrinking the matrix.
    BACKENDS = [
        "localspark",
        pytest.param(
            "pyspark",
            marks=pytest.mark.skip(
                reason="pyspark not installed: real-Spark boundary NOT "
                "exercised locally — see CI pyspark-integration matrix "
                "(SPARK_IT.json artifact) for the live-Spark evidence"
            ),
        ),
    ]


class Backend:
    """One handle bundling (session, types, functions, createDataFrame)."""

    def __init__(self, name, session, types_mod, functions_mod):
        self.name = name
        self.session = session
        self.T = types_mod
        self.F = functions_mod

    def df(self, rows, schema, partitions=4):
        if self.name == "localspark":
            return self.session.createDataFrame(
                rows, schema, numPartitions=partitions
            )
        return self.session.createDataFrame(rows, schema).repartition(partitions)

    def features_schema(self, extra=()):
        T = self.T
        fields = [T.StructField("features", T.ArrayType(T.DoubleType()))]
        for name, t in extra:
            fields.append(T.StructField(name, t))
        return T.StructType(fields)


@pytest.fixture(scope="module", params=BACKENDS)
def backend(request):
    if request.param == "localspark":
        from spark_rapids_ml_tpu import localspark
        from spark_rapids_ml_tpu.localspark import functions as LF
        from spark_rapids_ml_tpu.localspark import types as LT

        # x64 + shared compile cache in the workers so differential
        # tolerances hold tight and repeated sessions don't re-trace
        session = localspark.LocalSparkSession(
            parallelism=4,
            worker_env={
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "JAX_COMPILATION_CACHE_DIR": "/tmp/jax_test_cache",
            },
        )
        yield Backend("localspark", session, LT, LF)
        session.stop()
    else:
        from pyspark.sql import SparkSession
        from pyspark.sql import functions as PF
        from pyspark.sql import types as PT

        session = (
            SparkSession.builder.master("local[4]")
            .appName("spark-rapids-ml-tpu-it")
            .config("spark.sql.execution.arrow.pyspark.enabled", "true")
            .config("spark.default.parallelism", "4")
            .config("spark.sql.shuffle.partitions", "4")
            .getOrCreate()
        )
        yield Backend("pyspark", session, PT, PF)
        session.stop()


@pytest.fixture(scope="module")
def rng_m():
    return np.random.default_rng(11)


class TestSparkPCAIntegration:
    """fit + transform through live mapInArrow — PCASuite.scala:42-88."""

    def test_fit_transform_differential(self, backend, rng_m):
        x = rng_m.normal(size=(320, 10))
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        est = SparkPCA().setInputCol("features").setOutputCol("pca").setK(4)
        model = est.fit(df)
        core = PCA().setInputCol("features").setOutputCol("pca").setK(4).fit(x)
        # sign-invariant comparison, reference tolerance (PCASuite.scala:80-87)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-5)
        np.testing.assert_allclose(
            model.explainedVariance, core.explainedVariance, atol=1e-5
        )

        out = model.transform(df)
        rows = out.collect()
        assert len(rows) == 320
        got = np.asarray([r["pca"] for r in rows])
        want = np.asarray(core.transform_rows(x))
        np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-5)

    def test_transform_appends_column_and_keeps_input(self, backend, rng_m):
        x = rng_m.normal(size=(40, 6))
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=2
        )
        model = SparkPCA().setInputCol("features").setOutputCol("out").setK(2).fit(df)
        out_df = model.transform(df)
        assert [f.name for f in out_df.schema.fields] == ["features", "out"]
        row = out_df.first()
        assert len(row["features"]) == 6 and len(row["out"]) == 2

    def test_k_greater_than_n_fails_before_job(self, backend, rng_m):
        x = rng_m.normal(size=(12, 3))
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        with pytest.raises(ValueError, match="k=5 must be <="):
            SparkPCA().setInputCol("features").setK(5).fit(df)

    def test_null_feature_vector_rejected(self, backend, rng_m):
        df = backend.df(
            [(None,), ([1.0, 2.0],)], backend.features_schema(), partitions=1
        )
        with pytest.raises(ValueError, match="null feature"):
            SparkPCA().setInputCol("features").setK(1).fit(df)

    def test_persistence_round_trip(self, backend, rng_m, tmp_path):
        x = rng_m.normal(size=(60, 5))
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        model = SparkPCA().setInputCol("features").setK(3).fit(df)
        path = str(tmp_path / "pca_model")
        model.save(path)
        loaded = SparkPCAModel.load(path)
        np.testing.assert_allclose(loaded.pc, model.pc)
        got = np.asarray([r["pca_features"] for r in loaded.transform(df).collect()])
        want = np.asarray([r["pca_features"] for r in model.transform(df).collect()])
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_mean_centering_on_df(self, backend, rng_m):
        # capability-add vs the reference (whose meanCentering is a TODO
        # stub, RapidsRowMatrix.scala:111-117): verify it on the live path
        x = rng_m.normal(size=(200, 6)) + 7.0
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        model = (
            SparkPCA().setInputCol("features").setK(3).setMeanCentering(True).fit(df)
        )
        core = PCA().setInputCol("features").setK(3).setMeanCentering(True).fit(x)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-5)

    @pytest.mark.parametrize("solver", ["full", "randomized", "svd", "auto"])
    def test_all_solvers_differential(self, backend, solver):
        rng_m = np.random.default_rng(101)
        # VERDICT r2 weak #2: the Spark path advertised solver but crashed on
        # 'svd'. Every solver value must run the live DataFrame path and
        # match the core estimator with the same solver.
        x = rng_m.normal(size=(320, 12))
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        model = SparkPCA().setInputCol("features").setK(4).setSolver(solver).fit(df)
        core = PCA().setInputCol("features").setK(4).setSolver(solver).fit(x)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-5)
        np.testing.assert_allclose(
            model.explainedVariance, core.explainedVariance, atol=1e-5
        )

    def test_svd_solver_mean_centering(self, backend):
        rng_m = np.random.default_rng(102)
        x = rng_m.normal(size=(240, 8)) + 5.0
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        model = (
            SparkPCA()
            .setInputCol("features")
            .setK(3)
            .setSolver("svd")
            .setMeanCentering(True)
            .fit(df)
        )
        core = (
            PCA().setInputCol("features").setK(3).setSolver("svd")
            .setMeanCentering(True).fit(x)
        )
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-5)

    def test_svd_solver_mesh_local(self, backend):
        rng_m = np.random.default_rng(103)
        x = rng_m.normal(size=(200, 8))
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        model = (
            SparkPCA().setInputCol("features").setK(3).setSolver("svd")
            .setDistribution("mesh-local").fit(df)
        )
        core = PCA().setInputCol("features").setK(3).setSolver("svd").fit(x)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-4)

    @pytest.mark.parametrize(
        "distribution", ["driver-merge", "mesh-local", "mesh-barrier"]
    )
    def test_standardize_fused_on_df(self, backend, distribution):
        # BASELINE config 4: StandardScaler fused into the PCA fit — one
        # data pass on every distribution (the scaled covariance derives
        # from the same GramStats row/psum)
        from spark_rapids_ml_tpu import StandardScaler

        rng = np.random.default_rng(125)
        x = rng.normal(size=(240, 6)) * np.array(
            [1.0, 40.0, 0.02, 5.0, 100.0, 1.0]
        ) + 2.0
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        model = (
            SparkPCA().setInputCol("features").setK(3).setStandardize(True)
            .setDistribution(distribution).fit(df)
        )
        scaler = (
            StandardScaler().setInputCol("features").setWithMean(True)
            .setWithStd(True).fit(x)
        )
        xs = np.asarray(scaler.transform(x))
        staged = PCA().setInputCol("features").setK(3).setMeanCentering(True).fit(xs)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(staged.pc), atol=1e-6)
        out = np.asarray(
            [r["pca_features"] for r in model.transform(df).collect()]
        )
        np.testing.assert_allclose(
            np.abs(out), np.abs(np.asarray(staged.transform(xs))), atol=1e-6
        )

    def test_vector_udt_input(self, backend):
        # VERDICT r2 missing #5: pyspark.ml pipelines carry VectorUDT
        # columns; fit + transform must accept them unmodified.
        if backend.name != "pyspark":
            pytest.skip("VectorUDT is a pyspark.ml type")
        from pyspark.ml.linalg import Vectors

        rng = np.random.default_rng(108)
        x = rng.normal(size=(120, 6))
        rows = [
            (
                Vectors.sparse(6, list(range(6)), row.tolist())
                if i % 5 == 0
                else Vectors.dense(row.tolist()),
            )
            for i, row in enumerate(x)
        ]
        df = backend.session.createDataFrame(rows, ["features"]).repartition(3)
        model = SparkPCA().setInputCol("features").setK(3).fit(df)
        core = PCA().setInputCol("features").setK(3).fit(x)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-5)
        out = model.transform(df).collect()
        assert len(out) == 120 and len(out[0]["pca_features"]) == 3

    def test_spark_ml_persistence_interop(self, backend, tmp_path):
        # VERDICT r2 missing #6: a model saved here (layout='spark') must
        # load in STOCK pyspark.ml, and a stock pyspark.ml save must load
        # here — full round-trip through Spark's own reader/writer.
        if backend.name != "pyspark":
            pytest.skip("stock pyspark.ml required")
        from pyspark.ml.feature import PCA as SparkMLPCA
        from pyspark.ml.feature import PCAModel as SparkMLPCAModel
        from pyspark.ml.linalg import Vectors

        rng = np.random.default_rng(109)
        x = rng.normal(size=(100, 5))
        ours = SparkPCA().setInputCol("features").setOutputCol("o").setK(2).fit(x)

        # ours -> stock
        p1 = str(tmp_path / "ours_as_spark")
        ours.save(p1, layout="spark")
        stock = SparkMLPCAModel.load(p1)
        np.testing.assert_allclose(
            np.asarray(stock.pc.toArray()), ours.pc, atol=1e-12
        )
        assert stock.getK() == 2 and stock.getInputCol() == "features"

        # stock -> ours
        df = backend.session.createDataFrame(
            [(Vectors.dense(r.tolist()),) for r in x], ["features"]
        )
        stock2 = (
            SparkMLPCA(k=2, inputCol="features", outputCol="o").fit(df)
        )
        p2 = str(tmp_path / "stock_save")
        stock2.save(p2)
        from spark_rapids_ml_tpu.models.pca import PCAModel as OurPCAModel

        back = OurPCAModel.load(p2)
        np.testing.assert_allclose(
            back.pc, np.asarray(stock2.pc.toArray()), atol=1e-12
        )
        assert back.getK() == 2

    @pytest.mark.parametrize("centering", [False, True])
    def test_svd_solver_mesh_barrier_differential(self, backend, centering):
        # r3: the TSQR solver runs ACROSS the barrier mesh too — per-device
        # QR, butterfly R merge over the process group, replicated SVD(R);
        # centering happens in-program with the pad mask
        rng_m = np.random.default_rng(104)
        x = rng_m.normal(size=(260, 8)) + 4.0
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        base = (
            SparkPCA().setInputCol("features").setK(3).setSolver("svd")
            .setMeanCentering(centering)
        )
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(np.abs(mesh.pc), np.abs(merge.pc), atol=1e-8)
        np.testing.assert_allclose(
            mesh.explainedVariance, merge.explainedVariance, atol=1e-8
        )


class TestSparkGLMIntegration:
    def _labeled_df(self, backend, x, y, w=None, partitions=4):
        T = backend.T
        extra = [("label", T.DoubleType())]
        rows = [(row.tolist(), float(lbl)) for row, lbl in zip(x, y)]
        if w is not None:
            extra.append(("wt", T.DoubleType()))
            rows = [
                (row.tolist(), float(lbl), float(wi))
                for row, lbl, wi in zip(x, y, w)
            ]
        return backend.df(rows, backend.features_schema(extra), partitions)

    def test_linreg_fit_and_transform(self, backend, rng_m):
        x = rng_m.normal(size=(400, 5))
        coef = np.array([1.0, -2.0, 0.5, 3.0, 0.0])
        y = x @ coef + 1.5 + 0.01 * rng_m.normal(size=400)
        df = self._labeled_df(backend, x, y)
        model = SparkLinearRegression().fit(df)
        core = LinearRegression().fit((x, y))
        np.testing.assert_allclose(model.coefficients, core.coefficients, atol=1e-6)
        np.testing.assert_allclose(model.intercept, core.intercept, atol=1e-6)
        preds = np.asarray([r["prediction"] for r in model.transform(df).collect()])
        np.testing.assert_allclose(preds, x @ core.coefficients + core.intercept, atol=1e-6)

    def test_linreg_elastic_net(self, backend):
        # α>0 routes the driver-side solve through FISTA on the same
        # reduced stats; both distribution modes must agree with the core.
        # Local rng: consuming module-scoped rng_m here would shift the
        # data stream of every test that runs after this one
        rng = np.random.default_rng(55)
        x = rng.normal(size=(400, 6))
        coef = np.array([1.0, -2.0, 0.0, 3.0, 0.0, 0.5])
        y = x @ coef + 1.5 + 0.01 * rng.normal(size=400)
        df = self._labeled_df(backend, x, y)
        est = SparkLinearRegression(regParam=0.1, elasticNetParam=1.0)
        core = LinearRegression(regParam=0.1, elasticNetParam=1.0).fit((x, y))
        model = est.fit(df)
        np.testing.assert_allclose(model.coefficients, core.coefficients, atol=1e-6)
        assert np.sum(np.abs(np.asarray(model.coefficients)) < 1e-9) >= 1
        barrier = est.copy().setDistribution("mesh-barrier").fit(df)
        np.testing.assert_allclose(
            barrier.coefficients, core.coefficients, atol=1e-6
        )

    def test_linreg_weighted(self, backend, rng_m):
        x = rng_m.normal(size=(300, 3))
        y = x @ np.ones(3)
        y_bad = y.copy()
        y_bad[150:] += 50.0
        w = np.ones(300)
        w[150:] = 1e-12
        df = self._labeled_df(backend, x, y_bad, w)
        model = SparkLinearRegression().setWeightCol("wt").fit(df)
        np.testing.assert_allclose(model.coefficients, np.ones(3), atol=1e-4)

    def test_logreg_elastic_net(self, backend):
        # proximal-Newton L1 on the DataFrame paths must match the core fit.
        # Local rng on purpose: rng_m is module-scoped and consuming its
        # stream here would shift the data of every later test
        rng = np.random.default_rng(77)
        x = rng.normal(size=(400, 6))
        true_w = np.array([2.0, -1.0, 0.0, 0.0, 1.5, 0.0])
        p = 1.0 / (1.0 + np.exp(-(x @ true_w)))
        y = (rng.uniform(size=400) < p).astype(np.float64)
        df = self._labeled_df(backend, x, y)
        core = LogisticRegression(
            regParam=0.02, elasticNetParam=1.0, maxIter=60, tol=1e-10
        ).fit((x, y))
        est = SparkLogisticRegression(
            regParam=0.02, elasticNetParam=1.0, maxIter=60, tol=1e-10
        )
        model = est.fit(df)
        np.testing.assert_allclose(model.coefficients, core.coefficients, atol=1e-8)
        barrier = est.copy().setDistribution("mesh-barrier").fit(df)
        np.testing.assert_allclose(
            barrier.coefficients, core.coefficients, atol=1e-6
        )

    def test_logreg_probability_col(self, backend):
        rng = np.random.default_rng(31)
        x = rng.normal(size=(200, 4))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5, 0.0]))))
        y = (rng.random(200) < p).astype(float)
        df = self._labeled_df(backend, x, y)
        model = (
            SparkLogisticRegression().setRegParam(0.01)
            .setProbabilityCol("probability").fit(df)
        )
        rows = model.transform(df).collect()
        proba = np.asarray([r["probability"] for r in rows])
        preds = np.asarray([r["prediction"] for r in rows])
        assert proba.shape == (200, 2)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-12)
        want = model.predict_proba_matrix(x)
        np.testing.assert_allclose(proba[:, 1], want, atol=1e-9)
        np.testing.assert_allclose(preds, (want >= 0.5).astype(float))

    def test_multinomial_probability_col(self, backend):
        rng = np.random.default_rng(41)
        x = np.concatenate([
            rng.normal(size=(60, 3)) + off for off in ([0, 0, 0], [4, 0, 0], [0, 4, 0])
        ])
        y = np.repeat([0.0, 1.0, 2.0], 60)
        df = self._labeled_df(backend, x, y)
        model = (
            SparkLogisticRegression().setRegParam(0.01)
            .setProbabilityCol("probability").fit(df)
        )
        rows = model.transform(df).collect()
        proba = np.asarray([r["probability"] for r in rows])
        preds = np.asarray([r["prediction"] for r in rows])
        assert proba.shape == (180, 3)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-12)
        np.testing.assert_allclose(preds, np.argmax(proba, axis=1).astype(float))
        assert np.mean(preds == y) > 0.9

    def test_multinomial_elastic_net_paths_agree(self, backend):
        # softmax proximal Newton: driver-merge and mesh-barrier must match
        # the core fit
        rng = np.random.default_rng(67)
        x = np.concatenate(
            [rng.normal(size=(70, 4)) + off
             for off in ([0, 0, 0, 0], [3, 0, 0, 0], [0, 3, 0, 0])]
        )
        y = np.repeat([0.0, 1.0, 2.0], 70)
        df = self._labeled_df(backend, x, y)
        core = LogisticRegression(
            regParam=0.02, elasticNetParam=1.0, maxIter=60, tol=1e-10
        ).fit((x, y))
        est = SparkLogisticRegression(
            regParam=0.02, elasticNetParam=1.0, maxIter=60, tol=1e-10
        )
        model = est.fit(df)
        np.testing.assert_allclose(
            model.coefficientMatrix, core.coefficientMatrix, atol=1e-8
        )
        barrier = est.copy().setDistribution("mesh-barrier").fit(df)
        np.testing.assert_allclose(
            barrier.coefficientMatrix, core.coefficientMatrix, atol=1e-6
        )

    def test_logreg_newton_over_jobs(self, backend):
        # local rng: the train-accuracy threshold below is data-dependent,
        # so this test must see the SAME data regardless of which other
        # rng_m-consuming tests a -k selection ran before it
        rng = np.random.default_rng(23)
        x = rng.normal(size=(500, 4))
        true_w = np.array([2.0, -1.0, 0.5, 0.0])
        p = 1.0 / (1.0 + np.exp(-(x @ true_w - 0.3)))
        y = (rng.random(500) < p).astype(float)
        df = self._labeled_df(backend, x, y)
        est = SparkLogisticRegression().setRegParam(1e-4).setMaxIter(15)
        model = est.fit(df)
        core = LogisticRegression().setRegParam(1e-4).setMaxIter(15).fit((x, y))
        np.testing.assert_allclose(model.coefficients, core.coefficients, atol=1e-5)
        preds = np.asarray([r["prediction"] for r in model.transform(df).collect()])
        # sanity bound only (labels are sigmoid-noisy: Bayes accuracy for
        # this generator is ~0.8); the real check is the differential above
        assert np.mean(preds == y) > 0.72

    def test_logreg_checkpoint_resume_matches_uninterrupted(
        self, backend, tmp_path, monkeypatch
    ):
        # binary Newton: kill after 2 completed iterations, resume, compare
        from spark_rapids_ml_tpu.spark import estimators as E

        rng = np.random.default_rng(113)
        x = rng.normal(size=(400, 4))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5, 0.0]))))
        y = (rng.random(400) < p).astype(float)
        df = self._labeled_df(backend, x, y)
        ckdir = str(tmp_path / "lr_ck")

        def est():
            return SparkLogisticRegression().setRegParam(1e-3).setMaxIter(10)

        uninterrupted = est().fit(df)

        real = E._collect_stats
        calls = {"n": 0}

        def dying(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("simulated preemption")
            return real(*a, **kw)

        monkeypatch.setattr(E, "_collect_stats", dying)
        with pytest.raises(RuntimeError, match="preemption"):
            est().fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        monkeypatch.setattr(E, "_collect_stats", real)
        resumed = est().fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        np.testing.assert_allclose(
            resumed.coefficients, uninterrupted.coefficients, atol=1e-8
        )

    def test_multinomial_checkpoint_resume(self, backend, tmp_path):
        # softmax path: partial fit leaves a checkpoint; a resumed fit with
        # the same dir matches the uninterrupted one
        rng = np.random.default_rng(114)
        centers = np.array([[3.0, 0.0], [0.0, 3.0], [-3.0, -3.0]])
        x = np.vstack([rng.normal(size=(80, 2)) + c for c in centers])
        y = np.repeat([0.0, 1.0, 2.0], 80)
        perm = rng.permutation(len(y))
        x, y = x[perm], y[perm]
        df = self._labeled_df(backend, x, y)
        ckdir = str(tmp_path / "mn_ck")

        def est(iters):
            return SparkLogisticRegression().setRegParam(1e-2).setMaxIter(iters)

        uninterrupted = est(8).setTol(0.0).fit(df)
        est(3).setTol(0.0).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        resumed = est(8).setTol(0.0).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        np.testing.assert_allclose(
            resumed.coefficientMatrix, uninterrupted.coefficientMatrix, atol=1e-8
        )

    def test_logreg_bad_labels_rejected(self, backend):
        rng_m = np.random.default_rng(105)
        x = rng_m.normal(size=(40, 3))
        y = rng_m.random(40)  # non-integer labels
        df = self._labeled_df(backend, x, y)
        with pytest.raises(ValueError, match="integer class labels"):
            SparkLogisticRegression().fit(df)

    def test_logreg_multinomial_differential(self, backend):
        rng_m = np.random.default_rng(106)
        # VERDICT r2 missing #3: >=3-class DataFrame fit must train softmax
        # and match the core multinomial model
        centers = np.array([[3.0, 0.0, 0.0], [0.0, 3.0, 0.0], [0.0, 0.0, 3.0]])
        x = np.vstack(
            [rng_m.normal(size=(120, 3)) + c for c in centers]
        )
        y = np.repeat([0.0, 1.0, 2.0], 120)
        perm = rng_m.permutation(len(y))
        x, y = x[perm], y[perm]
        df = self._labeled_df(backend, x, y)
        est = SparkLogisticRegression().setRegParam(1e-3).setMaxIter(12)
        model = est.fit(df)
        core = LogisticRegression().setRegParam(1e-3).setMaxIter(12).fit((x, y))
        assert model.numClasses == 3
        np.testing.assert_allclose(
            model.coefficientMatrix, core.coefficientMatrix, atol=1e-5
        )
        np.testing.assert_allclose(
            model.interceptVector, core.interceptVector, atol=1e-5
        )
        preds = np.asarray(
            [r["prediction"] for r in model.transform(df).collect()]
        )
        assert np.mean(preds == y) > 0.9

    def test_logreg_multinomial_weighted(self, backend):
        rng_m = np.random.default_rng(107)
        # class-2 rows carry ~zero weight: the fitted model must match a
        # core fit on the other two classes' geometry (still 3-class shape)
        x = rng_m.normal(size=(300, 2))
        y = rng_m.integers(0, 3, size=300).astype(float)
        w = np.where(y == 2.0, 1e-12, 1.0)
        df = self._labeled_df(backend, x, y, w)
        model = (
            SparkLogisticRegression().setWeightCol("wt").setMaxIter(8)
            .setRegParam(1e-2).fit(df)
        )
        core = (
            LogisticRegression().setWeightCol("wt").setMaxIter(8)
            .setRegParam(1e-2).fit((x, y, w))
        )
        np.testing.assert_allclose(
            model.coefficientMatrix, core.coefficientMatrix, atol=1e-5
        )


class TestSparkTruncatedSVDIntegration:
    @pytest.mark.parametrize("solver", ["gram", "svd", "randomized", "auto"])
    def test_all_solvers_differential(self, backend, solver):
        from spark_rapids_ml_tpu import TruncatedSVD
        from spark_rapids_ml_tpu.spark import SparkTruncatedSVD

        rng = np.random.default_rng(120)
        x = rng.normal(size=(280, 10))
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        model = (
            SparkTruncatedSVD().setInputCol("features").setK(4)
            .setSolver(solver).fit(df)
        )
        core = TruncatedSVD().setInputCol("features").setK(4).setSolver(solver).fit(x)
        np.testing.assert_allclose(
            np.abs(model.components), np.abs(core.components), atol=1e-5
        )
        np.testing.assert_allclose(
            model.singularValues, core.singularValues, atol=1e-5
        )
        out = model.transform(df).collect()
        assert len(out) == 280 and len(out[0]["svd_features"]) == 4

    def test_k_validated_before_job(self, backend):
        from spark_rapids_ml_tpu.spark import SparkTruncatedSVD

        rng = np.random.default_rng(121)
        df = backend.df(
            [(r.tolist(),) for r in rng.normal(size=(10, 3))],
            backend.features_schema(),
        )
        with pytest.raises(ValueError, match="k=7 must be <="):
            SparkTruncatedSVD().setInputCol("features").setK(7).fit(df)

    @pytest.mark.parametrize("solver", ["gram", "svd"])
    def test_mesh_barrier_differential(self, backend, solver):
        from spark_rapids_ml_tpu.spark import SparkTruncatedSVD

        rng = np.random.default_rng(123)
        x = rng.normal(size=(240, 9))
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        base = SparkTruncatedSVD().setInputCol("features").setK(4).setSolver(solver)
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(
            np.abs(mesh.components), np.abs(merge.components), atol=1e-8
        )
        np.testing.assert_allclose(
            mesh.singularValues, merge.singularValues, atol=1e-8
        )


class TestSparkNormalizerIntegration:
    def test_transform_differential(self, backend):
        from spark_rapids_ml_tpu import Normalizer
        from spark_rapids_ml_tpu.spark import SparkNormalizer

        rng = np.random.default_rng(122)
        x = rng.normal(size=(120, 5)) * 4.0
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=3
        )
        for p in (1.0, 2.0, float("inf")):
            out = (
                SparkNormalizer().setInputCol("features").setP(p)
                .transform(df).collect()
            )
            got = np.asarray([r["normalized_features"] for r in out])
            want = Normalizer().setInputCol("features").setP(p).transform(x)
            np.testing.assert_allclose(got, np.asarray(want), atol=1e-12)


class TestSparkKMeansIntegration:
    def test_kmeans_parallel_init_over_jobs(self, backend):
        # VERDICT r2 weak #6: k-means|| as distributed DataFrame passes —
        # cost job + oversampling job per round, weighting job, weighted++.
        rng = np.random.default_rng(110)
        centers_true = rng.normal(size=(30, 6)) * 8.0
        x = np.concatenate(
            [rng.normal(size=(40, 6)) * 0.3 + c for c in centers_true]
        )
        rng.shuffle(x)
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        est = (
            SparkKMeans().setInputCol("features").setK(30)
            .setInitMode("k-means||").setSeed(0).setMaxIter(8)
        )
        model = est.fit(df)
        assert model.clusterCenters.shape == (30, 6)
        core = (
            KMeans().setK(30).setInitMode("k-means||").setSeed(0)
            .setMaxIter(8).fit(x, num_partitions=4)
        )
        # same algorithm, different partition sampling — costs comparable
        assert model.trainingCost <= core.trainingCost * 1.25
        # well-separated blobs: a good init finds essentially every cluster
        d = np.linalg.norm(
            model.clusterCenters[:, None, :] - centers_true[None, :, :], axis=2
        )
        assert (d.min(axis=0) < 1.5).mean() > 0.9

    def test_fit_matches_core(self, backend, rng_m):
        centers_true = np.array([[6.0, 6.0], [-6.0, 6.0], [0.0, -7.0]])
        x = np.vstack(
            [rng_m.normal(size=(80, 2)) * 0.4 + c for c in centers_true]
        )
        perm = rng_m.permutation(len(x))
        x = x[perm]
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        model = SparkKMeans().setK(3).setSeed(5).setMaxIter(20).fit(df)
        got = np.asarray(sorted(model.clusterCenters.tolist()))
        want = np.asarray(sorted(centers_true.tolist()))
        np.testing.assert_allclose(got, want, atol=0.3)
        preds = np.asarray([r["prediction"] for r in model.transform(df).collect()])
        assert preds.shape == (240,)
        assert len(np.unique(preds)) == 3

    def test_seeding_not_biased_by_row_order(self, backend, rng_m, monkeypatch):
        """Partition-ordered data where head-seeding demonstrably fails:
        the first _INIT_SAMPLE rows all sit in ONE cluster, and maxIter is
        too small for Lloyd to recover from seeding all centers there
        (ADVICE round 1; core KMeans samples correctly, kmeans.py:84-108)."""
        monkeypatch.setattr(SparkKMeans, "_INIT_SAMPLE", 64)
        centers_true = np.array(
            [[20.0, 0.0], [-20.0, 0.0], [0.0, 20.0], [0.0, -20.0]]
        )
        # ORDERED: all of cluster 0 first, then 1, 2, 3
        x = np.vstack(
            [rng_m.normal(size=(500, 2)) * 0.3 + c for c in centers_true]
        )
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        model = SparkKMeans().setK(4).setSeed(1).setMaxIter(2).fit(df)
        # match by NEAREST true center, not sorted() (which flips row order
        # when a near-zero coordinate changes sign across rng draws)
        d = np.linalg.norm(
            model.clusterCenters[:, None, :] - centers_true[None, :, :], axis=2
        )
        assert (d.min(axis=0) < 1.0).all()  # every true cluster recovered

    def test_kmeans_checkpoint_resume_matches_uninterrupted(
        self, backend, tmp_path, monkeypatch
    ):
        # VERDICT r2 missing #7: a killed-and-resumed Spark-path fit must
        # match the uninterrupted fit. Kill mid-Lloyd by making the stats
        # pass raise on its 3rd invocation, then re-run the same call.
        from spark_rapids_ml_tpu.spark import estimators as E

        rng = np.random.default_rng(111)
        centers_true = rng.normal(size=(6, 4)) * 6.0
        x = np.concatenate(
            [rng.normal(size=(60, 4)) * 0.4 + c for c in centers_true]
        )
        rng.shuffle(x)
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        ckdir = str(tmp_path / "km_ck")

        def est():
            return (
                SparkKMeans().setInputCol("features").setK(6).setSeed(0)
                .setMaxIter(8).setTol(0.0)  # run all 8 iterations
            )

        uninterrupted = est().fit(df)

        real = E._collect_stats
        calls = {"n": 0}

        def dying(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("simulated preemption")
            return real(*a, **kw)

        monkeypatch.setattr(E, "_collect_stats", dying)
        with pytest.raises(RuntimeError, match="preemption"):
            est().fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        monkeypatch.setattr(E, "_collect_stats", real)
        import os

        assert any(d.startswith("step-") for d in os.listdir(ckdir))
        resumed = est().fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        np.testing.assert_allclose(
            resumed.clusterCenters, uninterrupted.clusterCenters, atol=1e-6
        )
        np.testing.assert_allclose(
            resumed.trainingCost, uninterrupted.trainingCost, rtol=1e-6
        )

    def test_kmeans_stale_checkpoint_rejected(self, backend, tmp_path):
        from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer

        rng = np.random.default_rng(112)
        x = rng.normal(size=(80, 3))
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        ckdir = str(tmp_path / "stale")
        TrainingCheckpointer(ckdir).save(0, {"centers": np.zeros((9, 3))}, {})
        with pytest.raises(ValueError, match="9 centers but k=4"):
            SparkKMeans().setInputCol("features").setK(4).fit(
                df, checkpoint_dir=ckdir
            )
        # wrong feature dim fails with the clear stale-dir error, not a
        # shape crash inside the executor job
        ckdir2 = str(tmp_path / "stale_dim")
        TrainingCheckpointer(ckdir2).save(0, {"centers": np.zeros((4, 7))}, {})
        with pytest.raises(ValueError, match="checkpoint_dir stale"):
            SparkKMeans().setInputCol("features").setK(4).fit(
                df, checkpoint_dir=ckdir2
            )

    def test_kmeans_resume_at_max_iter_keeps_cost(self, backend, tmp_path):
        # review finding r3: a resume whose checkpoint is already at the
        # final iteration must report the checkpointed cost, not inf
        rng = np.random.default_rng(115)
        x = rng.normal(size=(90, 3))
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        ckdir = str(tmp_path / "full_ck")
        est = SparkKMeans().setInputCol("features").setK(3).setSeed(0).setMaxIter(4).setTol(0.0)
        full = est.fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        resumed = est.fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        assert np.isfinite(resumed.trainingCost)
        np.testing.assert_allclose(resumed.trainingCost, full.trainingCost, rtol=1e-9)
        np.testing.assert_allclose(resumed.clusterCenters, full.clusterCenters)

    def test_compute_cost_on_dataframe(self, backend):
        rng = np.random.default_rng(124)
        centers_true = np.array([[6.0, 0.0], [-6.0, 0.0]])
        x = np.concatenate(
            [rng.normal(size=(50, 2)) * 0.5 + c for c in centers_true]
        )
        df = backend.df([(row.tolist(),) for row in x], backend.features_schema())
        model = SparkKMeans().setInputCol("features").setK(2).setSeed(0).fit(df)
        df_cost = model.computeCost(df)
        core_cost = model.computeCost(x)  # core path on the same data
        np.testing.assert_allclose(df_cost, core_cost, rtol=1e-9)
        np.testing.assert_allclose(df_cost, model.trainingCost, rtol=1e-6)

    def test_weighted_kmeans_df(self, backend, rng_m):
        T = backend.T
        x = np.vstack(
            [
                rng_m.normal(size=(100, 2)) * 0.2 + [4, 4],
                rng_m.normal(size=(100, 2)) * 0.2 - [4, 4],
                rng_m.normal(size=(50, 2)) * 0.2 + [40, 40],  # zero-weight blob
            ]
        )
        w = np.concatenate([np.ones(200), np.zeros(50)])
        rows = [(row.tolist(), float(wi)) for row, wi in zip(x, w)]
        df = backend.df(
            rows, backend.features_schema([("wt", T.DoubleType())]), partitions=3
        )
        model = (
            SparkKMeans().setK(2).setSeed(0).setWeightCol("wt").setMaxIter(15).fit(df)
        )
        centers = np.asarray(sorted(model.clusterCenters.tolist()))
        np.testing.assert_allclose(
            centers, [[-4.0, -4.0], [4.0, 4.0]], atol=0.3
        )


class TestSparkScalerIntegration:
    def test_fit_transform(self, backend, rng_m):
        x = rng_m.normal(size=(250, 6)) * 3.0 + 5.0
        df = backend.df(
            [(row.tolist(),) for row in x], backend.features_schema(), partitions=4
        )
        model = (
            SparkStandardScaler()
            .setInputCol("features")
            .setOutputCol("scaled")
            .setWithMean(True)  # Spark default is withMean=False
            .fit(df)
        )
        core = StandardScaler().setInputCol("features").setWithMean(True).fit(x)
        np.testing.assert_allclose(model.mean, core.mean, atol=1e-9)
        np.testing.assert_allclose(model.std, core.std, atol=1e-9)
        out = np.asarray(
            [r["scaled"] for r in model.transform(df).collect()]
        )
        np.testing.assert_allclose(out.mean(0), np.zeros(6), atol=1e-9)
        np.testing.assert_allclose(out.std(0, ddof=1), np.ones(6), atol=1e-9)


class TestEmptyDataFrameCost:
    def test_compute_cost_empty_df_is_zero(self, backend):
        from spark_rapids_ml_tpu.spark import SparkKMeansModel

        model = SparkKMeansModel(
            clusterCenters=np.zeros((2, 3)), trainingCost=0.0
        ).setInputCol("features")
        T = backend.T
        empty = backend.df([], backend.features_schema(), partitions=2)
        assert model.computeCost(empty) == 0.0


class TestMeshLocalDistribution:
    """'mesh-local' (driver-mesh psum programs) must match the core fits —
    the r3 completion of the distribution x estimator matrix; PCA had it,
    now the whole family does."""

    def _fdf(self, backend, x, extra_cols=()):
        rows = [
            (xr.tolist(), *vals) for xr, *vals in zip(x, *extra_cols)
        ] if extra_cols else [(xr.tolist(),) for xr in x]
        T = backend.T
        schema_fields = [T.StructField("features", T.ArrayType(T.DoubleType()))]
        names = ["label", "wt"]
        for i, _ in enumerate(extra_cols):
            schema_fields.append(T.StructField(names[i], T.DoubleType()))
        return backend.df(rows, T.StructType(schema_fields), partitions=3)

    def test_linreg_mesh_local(self, backend):
        rng = np.random.default_rng(91)
        x = rng.normal(size=(300, 5))
        y = x @ np.array([1.0, -2.0, 0.0, 0.5, 3.0]) + 1.0
        df = self._fdf(backend, x, (y,))
        core = LinearRegression(regParam=0.05).fit((x, y))
        m = (
            SparkLinearRegression(regParam=0.05)
            .setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(m.coefficients, core.coefficients, atol=1e-9)
        np.testing.assert_allclose(m.intercept, core.intercept, atol=1e-9)

    def test_linreg_mesh_local_weighted_elastic(self, backend):
        rng = np.random.default_rng(92)
        x = rng.normal(size=(240, 4))
        y = x @ np.array([2.0, 0.0, -1.0, 0.0]) + 0.3
        w = rng.uniform(0.2, 2.0, size=240)
        df = self._fdf(backend, x, (y, w))
        core = LinearRegression(
            regParam=0.05, elasticNetParam=1.0, tol=1e-12
        ).fit((x, y, w))
        m = (
            SparkLinearRegression(
                regParam=0.05, elasticNetParam=1.0, tol=1e-12
            )
            .setWeightCol("wt").setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(m.coefficients, core.coefficients, atol=1e-9)

    def test_logreg_mesh_local_binary_and_multinomial(self, backend):
        rng = np.random.default_rng(93)
        x = rng.normal(size=(300, 4))
        p = 1 / (1 + np.exp(-(x @ np.array([2.0, -1.0, 0.5, 0.0]))))
        y = (rng.uniform(size=300) < p).astype(float)
        df = self._fdf(backend, x, (y,))
        core = LogisticRegression(regParam=0.01, maxIter=20, tol=1e-10).fit((x, y))
        m = (
            SparkLogisticRegression(regParam=0.01, maxIter=20, tol=1e-10)
            .setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(m.coefficients, core.coefficients, atol=1e-8)

        x3 = np.concatenate(
            [rng.normal(size=(60, 3)) + off
             for off in ([0, 0, 0], [3, 0, 0], [0, 3, 0])]
        )
        y3 = np.repeat([0.0, 1.0, 2.0], 60)
        df3 = self._fdf(backend, x3, (y3,))
        core3 = LogisticRegression(regParam=0.02, maxIter=30, tol=1e-10).fit((x3, y3))
        m3 = (
            SparkLogisticRegression(regParam=0.02, maxIter=30, tol=1e-10)
            .setDistribution("mesh-local").fit(df3)
        )
        np.testing.assert_allclose(
            m3.coefficientMatrix, core3.coefficientMatrix, atol=1e-7
        )

    def test_kmeans_mesh_local(self, backend):
        rng = np.random.default_rng(94)
        x = np.concatenate(
            [rng.normal(size=(80, 3)) + off
             for off in ([0, 0, 0], [6, 0, 0], [0, 6, 0])]
        )
        df = self._fdf(backend, x)
        core = KMeans(k=3, seed=5, maxIter=15).fit(x)
        m = (
            SparkKMeans(k=3, seed=5, maxIter=15)
            .setInputCol("features").setDistribution("mesh-local").fit(df)
        )
        # seeding differs between the core and DataFrame paths (different
        # samplers), but on well-separated clusters both Lloyd loops must
        # converge to the same three centroids
        a = np.asarray(sorted(np.asarray(core.clusterCenters).tolist()))
        b = np.asarray(sorted(np.asarray(m.clusterCenters).tolist()))
        np.testing.assert_allclose(a, b, atol=0.5)
        assert abs(float(m.trainingCost) - float(core.trainingCost)) < 0.05 * float(
            core.trainingCost
        )

    def test_scaler_mesh_local(self, backend):
        rng = np.random.default_rng(95)
        x = rng.normal(size=(200, 6)) * 3.0 + 1.0
        df = self._fdf(backend, x)
        core = StandardScaler().setInputCol("features").fit(x)
        m = (
            SparkStandardScaler().setInputCol("features")
            .setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(m.mean, core.mean, atol=1e-10)
        np.testing.assert_allclose(m.std, core.std, atol=1e-10)

    @pytest.mark.parametrize("solver", ["gram", "svd"])
    def test_tsvd_mesh_local(self, backend, solver):
        from spark_rapids_ml_tpu import TruncatedSVD
        from spark_rapids_ml_tpu.spark import SparkTruncatedSVD

        rng = np.random.default_rng(96)
        x = rng.normal(size=(200, 8)) @ rng.normal(size=(8, 8))
        df = self._fdf(backend, x)
        core = (
            TruncatedSVD(k=3).setInputCol("features").setSolver(solver).fit(x)
        )
        m = (
            SparkTruncatedSVD(k=3).setInputCol("features").setSolver(solver)
            .setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(
            np.abs(m.components), np.abs(core.components), atol=1e-8
        )
        np.testing.assert_allclose(
            m.singularValues, core.singularValues, rtol=1e-10
        )


class TestKMeansMeshLocalParallelInit:
    """k-means|| + mesh-local seeds IN-PROGRAM (r3 verdict #8): the whole
    fit — init rounds included — runs on the mesh with no candidate rows
    bouncing through driver jobs, and lands at driver-init-quality cost."""

    def test_mesh_init_quality_matches_driver_init(self, backend):
        rng = np.random.default_rng(77)
        k = 4
        anchors = rng.normal(size=(k, 5)) * 8
        x = np.vstack(
            [anchors[i] + 0.4 * rng.normal(size=(90, 5)) for i in range(k)]
        )
        schema = backend.features_schema()
        df = backend.df([(row.tolist(),) for row in x], schema)

        def est(distribution):
            return (
                SparkKMeans(inputCol="features", k=k, seed=3, maxIter=20)
                .setInitMode("k-means||")
                .setDistribution(distribution)
            )

        mesh_model = est("mesh-local").fit(df)
        driver_model = est("driver-merge").fit(df)
        assert mesh_model.clusterCenters.shape == (k, 5)
        # both inits recover the anchor structure: equal-cost ballpark
        assert (
            mesh_model.trainingCost < 1.3 * driver_model.trainingCost + 1e-9
        )
        # every anchor is represented by a nearby center
        d = np.linalg.norm(
            mesh_model.clusterCenters[:, None, :] - anchors[None, :, :], axis=2
        )
        assert d.min(axis=0).max() < 2.0


class TestRangeScalersIntegration:
    """MinMax/MaxAbs scalers through live mapInArrow — the min/max monoid
    rides the same stats-row plumbing but folds with its OWN driver merge
    (sum-merge would corrupt it)."""

    def test_minmax_fit_transform_differential(self, backend):
        from spark_rapids_ml_tpu.spark import SparkMinMaxScaler

        rng = np.random.default_rng(61)
        x = rng.uniform(3.0, 11.0, size=(240, 5))  # positive: pads would fake min=0
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=4,
        )
        model = (
            SparkMinMaxScaler()
            .setInputCol("features")
            .setOutputCol("scaled")
            .setMin(-1.0)
            .setMax(1.0)
            .fit(df)
        )
        np.testing.assert_allclose(model.originalMin, x.min(0), atol=1e-12)
        np.testing.assert_allclose(model.originalMax, x.max(0), atol=1e-12)
        rows = model.transform(df).collect()
        got = np.asarray([r["scaled"] for r in rows])
        span = x.max(0) - x.min(0)
        want = (x - x.min(0)) / span * 2.0 - 1.0
        np.testing.assert_allclose(np.sort(got, 0), np.sort(want, 0), atol=1e-9)

    def test_maxabs_fit_transform_differential(self, backend):
        from spark_rapids_ml_tpu.spark import SparkMaxAbsScaler

        rng = np.random.default_rng(62)
        x = rng.normal(size=(180, 4)) * 7
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=3,
        )
        model = (
            SparkMaxAbsScaler().setInputCol("features").setOutputCol("s").fit(df)
        )
        np.testing.assert_allclose(model.maxAbs, np.abs(x).max(0), atol=1e-12)
        rows = model.transform(df).collect()
        got = np.asarray([r["s"] for r in rows])
        np.testing.assert_allclose(
            np.sort(got, 0), np.sort(x / np.abs(x).max(0), 0), atol=1e-9
        )

    def test_robust_scaler_fit_transform_differential(self, backend):
        from sklearn.preprocessing import RobustScaler as SkRobust

        from spark_rapids_ml_tpu.spark import SparkRobustScaler

        rng = np.random.default_rng(63)
        x = rng.normal(size=(4_000, 3)) * np.array([1.0, 6.0, 0.5]) + 2.0
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=4,
        )
        model = (
            SparkRobustScaler()
            .setInputCol("features")
            .setOutputCol("r")
            .setWithCentering(True)
            .fit(df)
        )
        sk = SkRobust(with_centering=True).fit(x)
        span = x.max(0) - x.min(0)
        tol = 2 * (span / 4096).max()
        np.testing.assert_allclose(model.median, sk.center_, atol=tol)
        np.testing.assert_allclose(model.range, sk.scale_, atol=2 * tol)
        rows = model.transform(df).collect()
        got = np.asarray([r["r"] for r in rows])
        np.testing.assert_allclose(
            np.sort(got, 0), np.sort(sk.transform(x), 0), atol=0.05
        )

    def test_imputer_fit_transform_differential(self, backend):
        from sklearn.impute import SimpleImputer

        from spark_rapids_ml_tpu.spark import SparkImputer

        rng = np.random.default_rng(64)
        x = rng.normal(size=(2_000, 4)) * np.array([1, 5, 0.5, 3]) + 1
        x[rng.random(x.shape) < 0.15] = np.nan
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=4,
        )
        for strategy, atol in (("mean", 1e-9), ("median", None)):
            model = (
                SparkImputer()
                .setInputCol("features")
                .setOutputCol("i")
                .setStrategy(strategy)
                .fit(df)
            )
            sk = SimpleImputer(strategy=strategy).fit(x)
            if atol is None:  # sketch bound for the median
                span = np.nanmax(x, 0) - np.nanmin(x, 0)
                atol = (2 * span / 4096).max()
            np.testing.assert_allclose(
                model.surrogate, sk.statistics_, atol=atol
            )
            rows = model.transform(df).collect()
            got = np.asarray([r["i"] for r in rows])
            assert not np.isnan(got).any()

    def test_variance_selector_fit_transform(self, backend):
        from spark_rapids_ml_tpu.spark import SparkVarianceThresholdSelector

        rng = np.random.default_rng(65)
        x = rng.normal(size=(500, 5)) * np.array([0.01, 2, 0.5, 3, 1])
        x[:, 0] *= 0.0  # near-then-exactly-zero variance feature
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=3,
        )
        model = (
            SparkVarianceThresholdSelector()
            .setFeaturesCol("features")
            .setOutputCol("sel")
            .setVarianceThreshold(0.1)
            .fit(df)
        )
        want = np.flatnonzero(x.var(axis=0, ddof=1) > 0.1)
        np.testing.assert_array_equal(model.selectedFeatures, want)
        rows = model.transform(df).collect()
        got = np.asarray([r["sel"] for r in rows])
        assert got.shape == (500, len(want))

    def test_stateless_transformers_over_dataframes(self, backend):
        from scipy.fft import dct as scipy_dct

        from spark_rapids_ml_tpu.spark import (
            SparkBinarizer,
            SparkBucketizer,
            SparkDCT,
            SparkElementwiseProduct,
            SparkVectorSlicer,
        )

        rng = np.random.default_rng(66)
        x = rng.normal(size=(120, 8))
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=3,
        )

        def col(out_df, name):
            return np.asarray([r[name] for r in out_df.collect()])

        got = col(
            SparkDCT().setInputCol("features").setOutputCol("d").transform(df),
            "d",
        )
        np.testing.assert_allclose(
            np.sort(got, 0),
            np.sort(scipy_dct(x, type=2, norm="ortho", axis=1), 0),
            atol=1e-9,
        )
        got = col(
            SparkBinarizer().setInputCol("features").setOutputCol("b")
            .setThreshold(0.0).transform(df),
            "b",
        )
        assert set(np.unique(got)) <= {0.0, 1.0}
        w = np.arange(1.0, 9.0)
        got = col(
            SparkElementwiseProduct().setInputCol("features")
            .setOutputCol("e").setScalingVec(w).transform(df),
            "e",
        )
        np.testing.assert_allclose(
            np.sort(got, 0), np.sort(x * w, 0), atol=1e-9
        )
        got = col(
            SparkVectorSlicer().setInputCol("features").setOutputCol("s")
            .setIndices([5, 1]).transform(df),
            "s",
        )
        assert got.shape == (120, 2)
        got = col(
            SparkBucketizer().setInputCol("features").setOutputCol("k")
            .setSplits([-np.inf, 0.0, np.inf]).transform(df),
            "k",
        )
        np.testing.assert_allclose(np.sort(got, 0), np.sort((x >= 0).astype(float), 0))

    def test_quantile_discretizer_over_dataframes(self, backend):
        from spark_rapids_ml_tpu.spark import SparkQuantileDiscretizer

        rng = np.random.default_rng(67)
        x = rng.normal(size=(3_000, 3)) * np.array([1, 5, 0.3])
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=4,
        )
        model = (
            SparkQuantileDiscretizer()
            .setInputCol("features")
            .setOutputCol("q")
            .setNumBuckets(4)
            .fit(df)
        )
        rows = model.transform(df).collect()
        got = np.asarray([r["q"] for r in rows])
        for j in range(3):
            frac = np.bincount(got[:, j].astype(int), minlength=4) / len(x)
            np.testing.assert_allclose(frac, 0.25, atol=0.03)

    def test_range_scalers_mesh_local_equals_driver_merge(self, backend):
        from spark_rapids_ml_tpu.spark import (
            SparkMaxAbsScaler,
            SparkMinMaxScaler,
            SparkQuantileDiscretizer,
            SparkRobustScaler,
        )

        rng = np.random.default_rng(68)
        x = rng.uniform(3.0, 9.0, size=(700, 4))  # positive: pads would fake min=0
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=3,
        )

        mm_d = SparkMinMaxScaler().setInputCol("features").fit(df)
        mm_m = (
            SparkMinMaxScaler().setInputCol("features")
            .setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(mm_m.originalMin, mm_d.originalMin, atol=0)
        np.testing.assert_allclose(mm_m.originalMax, mm_d.originalMax, atol=0)

        ma_m = (
            SparkMaxAbsScaler().setInputCol("features")
            .setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(ma_m.maxAbs, np.abs(x).max(0), atol=1e-12)

        rs_d = (
            SparkRobustScaler().setInputCol("features")
            .setWithCentering(True).fit(df)
        )
        rs_m = (
            SparkRobustScaler().setInputCol("features")
            .setWithCentering(True).setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(rs_m.median, rs_d.median, atol=1e-9)
        np.testing.assert_allclose(rs_m.range, rs_d.range, atol=1e-9)

        qd_d = (
            SparkQuantileDiscretizer().setInputCol("features")
            .setNumBuckets(4).fit(df)
        )
        qd_m = (
            SparkQuantileDiscretizer().setInputCol("features")
            .setNumBuckets(4).setDistribution("mesh-local").fit(df)
        )
        np.testing.assert_allclose(qd_m.splits, qd_d.splits, atol=1e-9)

    def test_polynomial_expansion_matches_stock_mllib(self, backend):
        """The ordering oracle: on the pyspark backend this compares our
        expansion ELEMENTWISE (order included) against stock MLlib's
        PolynomialExpansion; on localspark it pins the documented order."""
        from spark_rapids_ml_tpu.spark import SparkPolynomialExpansion

        rng = np.random.default_rng(69)
        x = rng.normal(size=(60, 3))
        df = backend.df(
            [(row.tolist(),) for row in x],
            backend.features_schema(),
            partitions=2,
        )
        ours_df = (
            SparkPolynomialExpansion().setInputCol("features")
            .setOutputCol("poly").setDegree(3).transform(df)
        )
        ours = {
            tuple(np.round(r["features"], 9)): np.asarray(r["poly"])
            for r in ours_df.collect()
        }
        if backend.name == "pyspark":
            from pyspark.ml.feature import (
                PolynomialExpansion as StockPoly,
            )
            from pyspark.ml.functions import array_to_vector

            vdf = backend.session.createDataFrame(
                [(row.tolist(),) for row in x], ["arr"]
            ).select(array_to_vector("arr").alias("features"))
            stock = (
                StockPoly(degree=3, inputCol="features", outputCol="poly")
                .transform(vdf)
            )
            for r in stock.collect():
                key = tuple(np.round(np.asarray(r["features"].toArray()), 9))
                np.testing.assert_allclose(
                    ours[key], np.asarray(r["poly"].toArray()), atol=1e-9,
                    err_msg="ordering or values diverge from stock MLlib",
                )
        else:
            row0 = x[0]
            want = [row0[0], row0[0] ** 2, row0[0] ** 3]
            key = tuple(np.round(row0, 9))
            np.testing.assert_allclose(ours[key][:3], want, atol=1e-9)


class TestR5FamiliesIntegration:
    """The r5 model families (k-NN, DBSCAN, random forest) through the live
    DataFrame surface on both backends — differential vs the core paths."""

    def test_knn_kneighbors_live(self, backend, rng_m):
        from spark_rapids_ml_tpu.knn import NearestNeighbors
        from spark_rapids_ml_tpu.spark import SparkNearestNeighbors

        items = rng_m.normal(size=(150, 6))
        queries = rng_m.normal(size=(30, 6))
        schema = backend.features_schema()
        item_df = backend.df([(r.tolist(),) for r in items], schema)
        query_df = backend.df([(r.tolist(),) for r in queries], schema)
        model = (
            SparkNearestNeighbors().setInputCol("features").setK(5)
            .fit(item_df)
        )
        got = {
            tuple(np.round(r["features"], 9)): np.asarray(r["indices"])
            for r in model.kneighbors(query_df).collect()
        }
        d_ref, i_ref = NearestNeighbors().setK(5).fit(items).kneighbors(queries)
        for q, idx in zip(queries, i_ref):
            np.testing.assert_array_equal(got[tuple(np.round(q, 9))], idx)

    def test_dbscan_live(self, backend, rng_m):
        from spark_rapids_ml_tpu.clustering import DBSCAN
        from spark_rapids_ml_tpu.spark import SparkDBSCAN

        x = np.concatenate(
            [rng_m.normal(c, 0.2, size=(35, 3)) for c in (0.0, 5.0)]
            + [rng_m.uniform(-10, 10, size=(6, 3))]
        )
        df = backend.df([(r.tolist(),) for r in x], backend.features_schema())
        out = (
            SparkDBSCAN().setInputCol("features").setEps(1.0)
            .setMinSamples(4).fit(df).transform(df)
        )
        got = {
            tuple(np.round(r["features"], 9)): r["prediction"]
            for r in out.collect()
        }
        ref = DBSCAN().setEps(1.0).setMinSamples(4).fit().clusterLabels(x)
        for row, lab in zip(x, ref):
            assert got[tuple(np.round(row, 9))] == lab

    def test_random_forest_live(self, backend, rng_m):
        from spark_rapids_ml_tpu.spark import SparkRandomForestClassifier

        x = rng_m.normal(size=(300, 5))
        y = (x[:, 0] - 0.8 * x[:, 2] > 0).astype(float)
        T = backend.T
        schema = T.StructType(
            [
                T.StructField("features", T.ArrayType(T.DoubleType())),
                T.StructField("label", T.DoubleType()),
            ]
        )
        df = backend.df(
            [(r.tolist(), float(l)) for r, l in zip(x, y)], schema
        )
        est = (
            SparkRandomForestClassifier().setNumTrees(5).setMaxDepth(4)
            .setSeed(7)
        )
        model = est.fit(df)
        # the Spark fit equals the core fit on the same rows (collection
        # preserves content; forest build is deterministic by seed)
        core = est.copy().fit((x, y))
        np.testing.assert_array_equal(
            np.asarray(model.trees.feature), np.asarray(core.trees.feature)
        )
        rows = model.transform(df).collect()
        acc = np.mean([r["prediction"] == l for r, l in zip(rows, y)])
        assert acc > 0.85, acc

    def test_linear_svc_live(self, backend, rng_m):
        from spark_rapids_ml_tpu.classification import LinearSVC
        from spark_rapids_ml_tpu.spark import SparkLinearSVC

        x = rng_m.normal(size=(250, 4))
        y = (x[:, 0] - x[:, 2] > 0).astype(float)
        T = backend.T
        schema = T.StructType(
            [
                T.StructField("features", T.ArrayType(T.DoubleType())),
                T.StructField("label", T.DoubleType()),
            ]
        )
        df = backend.df(
            [(r.tolist(), float(l)) for r, l in zip(x, y)], schema
        )
        model = SparkLinearSVC().setRegParam(0.02).setMaxIter(40).fit(df)
        core = LinearSVC().setRegParam(0.02).setMaxIter(40).fit((x, y))
        np.testing.assert_allclose(
            model.coefficients, core.coefficients, rtol=1e-6, atol=1e-8
        )
        rows = model.transform(df).collect()
        acc = np.mean([r["prediction"] == l for r, l in zip(rows, y)])
        assert acc > 0.9, acc

    def test_ann_and_umap_live(self, backend, rng_m):
        from spark_rapids_ml_tpu.spark import (
            SparkApproximateNearestNeighbors,
            SparkUMAP,
        )

        centers = rng_m.normal(scale=8, size=(3, 5))
        x = np.concatenate(
            [c + rng_m.normal(scale=0.4, size=(50, 5)) for c in centers]
        )
        df = backend.df(
            [(r.tolist(),) for r in x], backend.features_schema()
        )
        ann = (
            SparkApproximateNearestNeighbors(k=3, nlist=9, nprobe=9)
            .setInputCol("features").fit(df)
        )
        row0 = ann.kneighbors(df).collect()[0]
        assert len(row0["indices"]) == 3 and row0["distances"][0] >= 0

        um = (
            SparkUMAP().setInputCol("features").setNNeighbors(8)
            .setNEpochs(60).setSeed(1).fit(df)
        )
        emb_rows = um.transform(df).collect()
        assert len(np.asarray(emb_rows[0]["embedding"])) == 2
