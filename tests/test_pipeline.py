"""Pipeline tests — BASELINE config 4: scaler + PCA fused end-to-end."""

import numpy as np
import pandas as pd

from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.models.pipeline import Pipeline, PipelineModel
from spark_rapids_ml_tpu.models.scaler import Normalizer, StandardScaler


def _df(rng, rows=200, n=10):
    x = rng.normal(size=(rows, n)) * rng.uniform(0.5, 4.0, size=n)[None, :]
    return pd.DataFrame({"features": list(x)}), x


class TestPipeline:
    def test_scaler_then_pca(self, rng):
        df, x = _df(rng)
        pipe = Pipeline(
            stages=[
                StandardScaler().setInputCol("features").setOutputCol("scaled").setWithMean(True),
                PCA().setInputCol("scaled").setOutputCol("pca").setK(3),
            ]
        )
        model = pipe.fit(df)
        out = model.transform(df)
        assert "pca" in out.columns

        # differential: same composition by hand
        xs = (x - x.mean(0)) / x.std(0, ddof=1)
        evals, evecs = np.linalg.eigh(xs.T @ xs)
        order = np.argsort(evals)[::-1]
        want = xs @ evecs[:, order[:3]]
        got = np.stack(out["pca"].to_numpy())
        np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-6)

    def test_transformer_stage_in_pipeline(self, rng):
        df, _ = _df(rng)
        pipe = Pipeline(
            stages=[
                Normalizer().setInputCol("features").setOutputCol("norm"),
                PCA().setInputCol("norm").setOutputCol("pca").setK(2),
            ]
        )
        out = pipe.fit(df).transform(df)
        assert {"norm", "pca"} <= set(out.columns)

    def test_pipeline_model_persistence(self, rng, tmp_path):
        df, _ = _df(rng)
        pipe = Pipeline(
            stages=[
                StandardScaler().setInputCol("features").setOutputCol("s"),
                PCA().setInputCol("s").setOutputCol("p").setK(2),
            ]
        )
        model = pipe.fit(df)
        model.save(tmp_path / "pm")
        loaded = PipelineModel.load(tmp_path / "pm")
        out1 = model.transform(df)
        out2 = loaded.transform(df)
        np.testing.assert_allclose(
            np.stack(out1["p"].to_numpy()), np.stack(out2["p"].to_numpy())
        )

    def test_pipeline_estimator_persistence(self, rng, tmp_path):
        pipe = Pipeline(
            stages=[
                StandardScaler().setInputCol("f").setOutputCol("s"),
                PCA().setInputCol("s").setK(2),
            ]
        )
        pipe.save(tmp_path / "pipe")
        loaded = Pipeline.load(tmp_path / "pipe")
        assert len(loaded.getStages()) == 2
        assert isinstance(loaded.getStages()[0], StandardScaler)
        assert isinstance(loaded.getStages()[1], PCA)
        assert loaded.getStages()[1].getK() == 2


class TestPreprocessingPipelinePersistence:
    def test_round_trip_with_r5_stages(self, rng, tmp_path):
        """The r5 preprocessing family inside one PipelineModel: every
        stage (stateful models AND params-only transformers) must
        save/load through the pipeline persistence layer and transform
        identically."""
        from spark_rapids_ml_tpu.models.discretizer import QuantileDiscretizer
        from spark_rapids_ml_tpu.models.pipeline import Pipeline, PipelineModel
        from spark_rapids_ml_tpu.models.scaler import (
            Binarizer,
            Imputer,
            MinMaxScaler,
            RobustScaler,
        )
        from spark_rapids_ml_tpu.models.selector import (
            VarianceThresholdSelector,
        )

        x = rng.normal(size=(500, 6)) * np.array([1, 4, 0.01, 2, 5, 3])
        x[rng.random(x.shape) < 0.1] = np.nan
        df = pd.DataFrame({"features": list(x)})
        pipe = Pipeline(stages=[
            Imputer(inputCol="features", outputCol="dense",
                    strategy="median"),
            VarianceThresholdSelector(featuresCol="dense",
                                      outputCol="kept",
                                      varianceThreshold=0.1),
            RobustScaler(inputCol="kept", outputCol="robust",
                         withCentering=True),
            MinMaxScaler(inputCol="robust", outputCol="unit"),
            QuantileDiscretizer(inputCol="unit", outputCol="binned",
                                numBuckets=3),
            Binarizer(inputCol="unit", outputCol="bits", threshold=0.5),
        ])
        model = pipe.fit(df)
        out1 = model.transform(df)
        model.save(tmp_path / "prep")
        loaded = PipelineModel.load(tmp_path / "prep")
        out2 = loaded.transform(df)
        for col in ("dense", "kept", "robust", "unit", "binned", "bits"):
            np.testing.assert_allclose(
                np.stack(out1[col].to_numpy()),
                np.stack(out2[col].to_numpy()),
                atol=0,
                err_msg=col,
            )
        binned = np.stack(out2["binned"].to_numpy())
        assert set(np.unique(binned)) <= {0.0, 1.0, 2.0}
        assert not np.isnan(np.stack(out2["dense"].to_numpy())).any()
