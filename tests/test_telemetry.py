"""Telemetry subsystem: registry math, span accounting, FitReport, JSONL.

Covers the ISSUE-2 satellite list: histogram percentile math against known
distributions, exception-path span accounting (the trace_range try/finally
fix), registry thread-safety under concurrent recording (the localspark
partition-executor load shape), FitReport presence on PCA / StandardScaler /
LinearRegression after both in-core and streamed fits, and the JSONL sink
round-trip.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu import telemetry as T
from spark_rapids_ml_tpu.models.linear import LinearRegression
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.models.scaler import StandardScaler
from spark_rapids_ml_tpu.utils.config import get_config, set_config
from spark_rapids_ml_tpu.telemetry import metrics, reset_metrics, trace_range


@pytest.fixture(autouse=True)
def clean_registry():
    T.reset_metrics()
    yield
    T.reset_metrics()


@pytest.fixture
def force_streamed(monkeypatch):
    old = get_config().stream_fit_max_resident_bytes
    monkeypatch.setenv("TPU_ML_STREAM_CHUNK_ROWS", "128")
    set_config(stream_fit_max_resident_bytes=1)
    yield
    set_config(stream_fit_max_resident_bytes=old)


@pytest.fixture
def data():
    rng = np.random.default_rng(23)
    x = np.asarray(rng.normal(size=(600, 8)), np.float64)
    y = x @ rng.normal(size=8) + 0.1 * rng.normal(size=600)
    return x, y


class TestHistogram:
    def test_exact_count_sum_min_max(self):
        h = T.Histogram()
        vals = [0.5, 1.5, 2.5, 10.0, 0.001]
        for v in vals:
            h.record(v)
        assert h.count == len(vals)
        assert h.total == pytest.approx(sum(vals))
        assert h.vmin == min(vals)
        assert h.vmax == max(vals)

    def test_percentiles_within_bucket_tolerance(self):
        # uniform 1..1000: log-bucket quantiles are within half a bucket
        # (GROWTH=2^0.25 ⇒ ~9.5%) of the exact order statistic
        h = T.Histogram()
        vals = np.linspace(1.0, 1000.0, 1000)
        for v in vals:
            h.record(float(v))
        for q in (50, 90, 99):
            exact = float(np.percentile(vals, q))
            got = h.percentile(q)
            assert got == pytest.approx(exact, rel=0.15), (q, got, exact)

    def test_percentile_extremes_are_clamped_exact(self):
        h = T.Histogram()
        for v in (3.0, 7.0, 42.0):
            h.record(v)
        assert h.percentile(0) >= h.vmin
        assert h.percentile(100) <= h.vmax

    def test_zero_and_negative_values_bucket_safely(self):
        h = T.Histogram()
        h.record(0.0)
        h.record(-1.0)
        h.record(5.0)
        assert h.count == 3
        assert h.percentile(1) == 0.0  # the zero bucket ranks first

    def test_empty_percentile_is_zero(self):
        assert T.Histogram().percentile(50) == 0.0

    def test_delta_subtracts_earlier_window(self):
        h = T.Histogram()
        for v in range(1, 11):
            h.record(float(v))
        snap = h.copy()
        for v in range(1, 11):
            h.record(float(v) * 100)
        d = h.delta(snap)
        assert d.count == 10
        assert d.total == pytest.approx(sum(range(1, 11)) * 100)

    def test_to_dict_shape(self):
        h = T.Histogram()
        h.record(1.0)
        d = h.to_dict()
        assert set(d) == {"count", "sum", "min", "max", "p50", "p90", "p99"}
        assert T.Histogram().to_dict() == {"count": 0, "sum": 0.0}


class TestSpans:
    def test_trace_range_books_elapsed_on_raise(self):
        # satellite (a): a body that raises must still account its time
        with pytest.raises(RuntimeError):
            with trace_range("boom.phase"):
                raise RuntimeError("body died")
        m = metrics()
        assert m["boom.phase"]["count"] == 1
        assert m["boom.phase"]["seconds"] >= 0.0

    def test_legacy_metrics_shape(self):
        with trace_range("p1"):
            pass
        with trace_range("p1"):
            pass
        m = metrics()
        assert m["p1"]["count"] == 2
        assert "seconds" in m["p1"]

    def test_estimator_label_groups_spans(self):
        token = T.set_current_estimator("DemoEst")
        try:
            with trace_range("labelled"):
                pass
        finally:
            T.reset_current_estimator(token)
        snap = T.REGISTRY.snapshot()
        h = snap.hist("span.seconds", phase="labelled", estimator="DemoEst")
        assert h.count == 1


class TestRegistryThreadSafety:
    def test_concurrent_counters_and_spans_exact(self):
        # the localspark partition-executor load shape: many threads, one
        # registry. Totals must be exact — the lock satellite.
        n_threads, per_thread = 8, 500
        start = threading.Barrier(n_threads)

        def work():
            start.wait()
            for _ in range(per_thread):
                T.counter_inc("t.count")
                T.counter_inc("t.bytes", 3, path="x")
                T.REGISTRY.histogram_record("t.h", 0.5)
                with trace_range("t.span"):
                    pass

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = T.REGISTRY.snapshot()
        total = n_threads * per_thread
        assert snap.counter("t.count") == total
        assert snap.counter("t.bytes") == 3 * total
        assert snap.hist("t.h").count == total
        assert metrics()["t.span"]["count"] == total


class TestFitReport:
    def test_in_core_pca(self, data):
        x, _ = data
        m = PCA().setInputCol("f").setK(3).fit(x)
        r = m.fit_report
        assert r is not None
        assert r.estimator == "PCA"
        assert r.wall_seconds > 0
        assert r.phases  # compute cov / eigh spans
        for p in r.phases.values():
            assert {"count", "sum"}.issubset(p)

    def test_in_core_scaler_and_linreg(self, data):
        x, y = data
        ms = StandardScaler().fit(x)
        assert ms.fit_report is not None
        assert ms.fit_report.estimator == "StandardScaler"
        ml = LinearRegression().fit((x, y))
        assert ml.fit_report is not None
        assert ml.fit_report.estimator == "LinearRegression"

    def test_streamed_fits_report_rows(self, data, force_streamed):
        x, y = data
        for est, arg in (
            (PCA().setInputCol("f").setK(3), x),
            (StandardScaler(), x),
            (LinearRegression(), (x, y)),
        ):
            T.reset_metrics()
            m = est.fit(arg, num_partitions=3)
            r = m.fit_report
            assert r is not None, type(est).__name__
            assert r.rows_ingested == len(x), type(est).__name__
            assert r.bytes_ingested > 0
            # the streamed pipeline's spans are attributed to this fit
            assert "fold.dispatch" in r.phases, r.phases.keys()
            assert "fold.wait" in r.phases

    def test_report_isolated_per_fit(self, data):
        x, _ = data
        m1 = StandardScaler().fit(x)
        m2 = StandardScaler().fit(x[:100])
        # each report is a snapshot delta, not the accumulated registry
        assert m2.fit_report.phases != {} or m1.fit_report.phases != {}
        c1 = sum(p["count"] for p in m1.fit_report.phases.values())
        c2 = sum(p["count"] for p in m2.fit_report.phases.values())
        assert c2 <= c1 * 2  # second fit didn't inherit the first's spans

    def test_report_roundtrips_via_dict(self, data):
        x, _ = data
        r = StandardScaler().fit(x).fit_report
        back = T.FitReport.from_dict(json.loads(json.dumps(r.to_dict())))
        assert back.estimator == r.estimator
        assert back.wall_seconds == pytest.approx(r.wall_seconds)
        assert back.phases.keys() == r.phases.keys()

    def test_loaded_model_has_no_report(self, data, tmp_path):
        x, _ = data
        from spark_rapids_ml_tpu.models.scaler import StandardScalerModel

        m = StandardScaler().fit(x)
        m.save(str(tmp_path / "m"))
        loaded = StandardScalerModel.load(str(tmp_path / "m"))
        assert loaded.fit_report is None


class TestJsonlSink:
    def test_round_trip(self, data, tmp_path):
        x, _ = data
        path = str(tmp_path / "telemetry.jsonl")
        old = get_config().telemetry_path
        set_config(telemetry_path=path)
        try:
            PCA().setInputCol("f").setK(3).fit(x)
            StandardScaler().fit(x)
        finally:
            set_config(telemetry_path=old)
        records = T.read_jsonl(path)
        assert [r["estimator"] for r in records] == ["PCA", "StandardScaler"]
        for r in records:
            assert r["type"] == "fit_report"
            assert r["schema"] == 6
            assert len(r["fit_id"]) == 12  # log<->report join key
            assert r["wall_seconds"] > 0
            assert isinstance(r["phases"], dict)
            assert "compile" in r and "device_memory" in r

    def test_disabled_by_default(self, data, tmp_path):
        x, _ = data
        assert get_config().telemetry_path == ""
        m = StandardScaler().fit(x)
        assert m.fit_report is not None  # report still attaches, no sink

    def test_export_failure_never_raises(self, data):
        x, _ = data
        old = get_config().telemetry_path
        set_config(telemetry_path="/nonexistent-dir/nope/t.jsonl")
        try:
            m = StandardScaler().fit(x)  # export fails, fit must not
            assert m.fit_report is not None
        finally:
            set_config(telemetry_path=old)

    def test_read_jsonl_skips_corrupt_lines(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"type":"fit_report","estimator":"A"}\n{oops\n\n')
        recs = T.read_jsonl(str(p))
        assert len(recs) == 1 and recs[0]["estimator"] == "A"


class TestConfigValidation:
    def test_telemetry_path_must_be_str(self):
        with pytest.raises(TypeError):
            set_config(telemetry_path=7)

    def test_int_keys_still_reject_str(self):
        with pytest.raises(TypeError):
            set_config(min_bucket="128")


class TestDeviceMemorySampling:
    def test_sample_never_raises(self):
        # CPU backend: memory_stats() is None — must return empty, not throw
        out = T.sample_device_memory()
        assert isinstance(out, dict)

    def test_install_monitoring_idempotent(self):
        assert T.install_monitoring() == T.install_monitoring()
