"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4) but fixes its biggest
gap: everything here runs WITHOUT accelerator hardware. We force the JAX CPU
backend with 8 virtual devices so the multi-chip sharding paths
(shard_map/psum over a Mesh) compile and execute in any environment —
the analog of the reference exercising "distributed" behavior with
2-partition local RDDs (PCASuite.scala:55-56).

This must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# barrier rendezvous races first-compile latency; on a loaded box (bench
# or a sibling suite sharing the host) the 120 s default can flake
os.environ.setdefault("TPU_ML_BARRIER_TIMEOUT_S", "300")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment may have force-registered an accelerator PJRT plugin at
# interpreter start (sitecustomize), latching JAX_PLATFORMS before this file
# runs — override through the config, which wins as long as no backend has
# been initialized yet.
jax.config.update("jax_platforms", "cpu")

# f64 on the CPU backend so differential tests can hold tight tolerances
# against NumPy oracles; the framework code itself is dtype-agnostic.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache so repeated test runs don't re-trace/compile.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
