"""Tests for the Spark integration's executor-side Arrow plan functions.

These run WITHOUT pyspark: the mapInArrow bodies consume plain pyarrow
RecordBatch iterators, so the whole executor-side computation is verified
here; the thin pyspark-facing wrappers add only plan wiring. (The reference
has no Spark-free test path at all — SURVEY.md §4.)
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu import PCA
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.spark import SparkPCA, SparkPCAModel, arrow_fns
from spark_rapids_ml_tpu.utils import columnar


def _batches(x, sizes, col="features"):
    """Split [rows, n] into Arrow record batches of the given row counts."""
    out, at = [], 0
    for s in sizes:
        chunk = x[at : at + s]
        at += s
        arr = pa.FixedSizeListArray.from_arrays(
            pa.array(chunk.reshape(-1)), x.shape[1]
        )
        out.append(pa.RecordBatch.from_arrays([arr], names=[col]))
    assert at == len(x)
    return out


@pytest.fixture
def x(rng):
    return rng.normal(size=(200, 12))


class TestStatsSerialization:
    def test_round_trip(self, x):
        stats = L.gram_stats(x)
        batch = arrow_fns.stats_to_batch(stats)
        back = arrow_fns.stats_from_batches([batch])
        np.testing.assert_allclose(back.xtx, np.asarray(stats.xtx), rtol=1e-12)
        np.testing.assert_allclose(back.col_sum, np.asarray(stats.col_sum), rtol=1e-12)
        assert float(back.count) == 200.0

    def test_merge_multiple_rows(self, x):
        halves = [L.gram_stats(x[:100]), L.gram_stats(x[100:])]
        merged = arrow_fns.stats_from_batches(
            [arrow_fns.stats_to_batch(s) for s in halves]
        )
        np.testing.assert_allclose(merged.xtx, x.T @ x, rtol=1e-10)
        assert float(merged.count) == 200.0

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="no partition statistics"):
            arrow_fns.stats_from_batches([])

    def test_stats_batch_uses_variable_lists(self, x):
        """Spark maps ArrayType to Arrow ListType — the emitted batch must
        use variable lists or the worker/JVM boundary rejects it."""
        batch = arrow_fns.stats_to_batch(L.gram_stats(x))
        assert batch.schema.field("xtx").type == pa.list_(pa.float64())
        assert batch.schema.field("col_sum").type == pa.list_(pa.float64())

    def test_stats_from_rows_collect_path(self, x):
        """The PySpark <4.0 fallback: merge from collect()-style row dicts."""
        halves = [L.gram_stats(x[:100]), L.gram_stats(x[100:])]
        rows = [
            {
                "xtx": np.asarray(s.xtx).reshape(-1).tolist(),
                "col_sum": np.asarray(s.col_sum).tolist(),
                "count": float(np.asarray(s.count)),
            }
            for s in halves
        ]
        merged = arrow_fns.stats_from_rows(rows)
        np.testing.assert_allclose(merged.xtx, x.T @ x, rtol=1e-10)
        assert float(merged.count) == 200.0


class TestFitPartitionFn:
    def test_stats_match_full_matrix(self, x):
        """Partition fn over streamed batches == GramStats of all rows —
        the property the cross-partition reduce relies on."""
        fn = arrow_fns.make_fit_partition_fn("features")
        out = list(fn(iter(_batches(x, [64, 100, 36]))))
        assert len(out) == 1  # one stats row per partition
        stats = arrow_fns.stats_from_batches(out)
        np.testing.assert_allclose(stats.xtx, x.T @ x, rtol=1e-8)
        np.testing.assert_allclose(stats.col_sum, x.sum(0), rtol=1e-8)
        assert float(stats.count) == 200.0

    def test_empty_partition_yields_nothing(self):
        fn = arrow_fns.make_fit_partition_fn("features")
        assert list(fn(iter([]))) == []

    def test_zero_row_batches_skipped(self, x):
        """Spark can deliver 0-row batches; they must be skipped, not crash
        the column extraction."""
        empty = pa.RecordBatch.from_arrays(
            [pa.array([], type=pa.list_(pa.float64()))], names=["features"]
        )
        fn = arrow_fns.make_fit_partition_fn("features")
        out = list(fn(iter([empty, *_batches(x, [200]), empty])))
        stats = arrow_fns.stats_from_batches(out)
        np.testing.assert_allclose(stats.xtx, x.T @ x, rtol=1e-8)
        tfn = arrow_fns.make_transform_partition_fn(
            "features", "out", np.eye(12)[:, :2]
        )
        assert len(list(tfn(iter([empty])))) == 0

    def test_two_partitions_equal_one(self, x):
        fn = arrow_fns.make_fit_partition_fn("features")
        p1 = list(fn(iter(_batches(x[:80], [80]))))
        p2 = list(fn(iter(_batches(x[80:], [70, 50]))))
        merged = arrow_fns.stats_from_batches(p1 + p2)
        np.testing.assert_allclose(merged.xtx, x.T @ x, rtol=1e-8)

    def test_end_to_end_matches_core_pca(self, x):
        """mapInArrow-plan fit == the core estimator's fit, exactly the
        equivalence the SparkPCA wrapper provides."""
        fn = arrow_fns.make_fit_partition_fn("features")
        stats_rows = []
        for part in ([0, 90], [90, 200]):
            stats_rows += list(fn(iter(_batches(x[part[0]:part[1]], [part[1] - part[0]]))))
        stats = arrow_fns.stats_from_batches(stats_rows)
        import jax.numpy as jnp

        cov = L.covariance_from_stats(
            L.GramStats(jnp.asarray(stats.xtx), jnp.asarray(stats.col_sum),
                        jnp.asarray(stats.count)),
            mean_centering=False,
        )
        pc, ev = L.pca_fit_from_cov(cov, 3)
        core = PCA().setInputCol("f").setK(3).fit(x)
        np.testing.assert_allclose(np.asarray(pc), core.pc, atol=1e-8)
        np.testing.assert_allclose(np.asarray(ev), core.explainedVariance, atol=1e-10)


class TestTransformPartitionFn:
    def test_appends_projection_column(self, x, rng):
        pc = rng.normal(size=(12, 4))
        fn = arrow_fns.make_transform_partition_fn("features", "out", pc)
        out = list(fn(iter(_batches(x, [128, 72]))))
        assert len(out) == 2
        got = np.concatenate(
            [
                np.asarray(b.column("out").values.to_numpy()).reshape(-1, 4)
                for b in out
            ]
        )
        np.testing.assert_allclose(got, x @ pc, atol=1e-8)
        # input columns preserved
        assert out[0].schema.names == ["features", "out"]

    def test_output_is_float64_variable_list(self, x, rng):
        pc = rng.normal(size=(12, 2))
        fn = arrow_fns.make_transform_partition_fn("features", "out", pc)
        (batch,) = list(fn(iter(_batches(x, [200]))))
        assert batch.column("out").type == pa.list_(pa.float64())

    def test_schema_helper(self):
        schema = pa.schema([pa.field("features", pa.list_(pa.float64(), 12))])
        out = arrow_fns.transform_output_schema(schema, "out")
        assert out.field("out").type == pa.list_(pa.float64())


class TestSparkWrappers:
    def test_non_spark_input_falls_through(self, x):
        """SparkPCA on non-Spark input behaves exactly like core PCA."""
        model = SparkPCA().setInputCol("f").setK(3).fit(x)
        assert isinstance(model, SparkPCAModel)
        core = PCA().setInputCol("f").setK(3).fit(x)
        np.testing.assert_allclose(model.pc, core.pc, atol=1e-12)
        out = model.transform(x)
        np.testing.assert_allclose(out, x @ model.pc, atol=1e-8)

    def test_spark_import_error_is_actionable(self):
        try:
            import pyspark  # noqa: F401

            pytest.skip("pyspark installed; gating not exercised")
        except ImportError:
            pass
        from spark_rapids_ml_tpu.spark.estimators import _require_pyspark

        with pytest.raises(ImportError, match="requires pyspark"):
            _require_pyspark()


def _vector_struct_array(rows, n, *, sparse_every=None):
    """Build a pyspark.ml-VectorUDT-shaped Arrow struct array.

    Layout per VectorUDT.sqlType: struct<type:int8, size:int32,
    indices:list<int32>, values:list<float64>>, type 0=sparse, 1=dense.
    """
    types, sizes, indices, values = [], [], [], []
    for i, row in enumerate(rows):
        if sparse_every and i % sparse_every == 0:
            nz = np.nonzero(row)[0]
            types.append(0)
            sizes.append(n)
            indices.append(nz.astype(np.int32).tolist())
            values.append(row[nz].tolist())
        else:
            types.append(1)
            sizes.append(None)
            indices.append(None)
            values.append(row.tolist())
    return pa.StructArray.from_arrays(
        [
            pa.array(types, pa.int8()),
            pa.array(sizes, pa.int32()),
            pa.array(indices, pa.list_(pa.int32())),
            pa.array(values, pa.list_(pa.float64())),
        ],
        names=["type", "size", "indices", "values"],
    )


class TestVectorUDTIngestion:
    """pyspark.ml pipelines carry VectorUDT columns (VERDICT r2 missing #5);
    the Arrow boundary ships them as their sqlType struct, accepted here
    alongside ArrayType."""

    def test_dense_struct_extracts(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(50, 7))
        batch = pa.RecordBatch.from_arrays(
            [_vector_struct_array(x, 7)], names=["features"]
        )
        got = columnar.extract_matrix(batch, "features")
        np.testing.assert_allclose(got, x, atol=1e-15)

    def test_mixed_dense_sparse_struct(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(40, 5))
        x[::3, 1:4] = 0.0  # sparse-ish rows
        batch = pa.RecordBatch.from_arrays(
            [_vector_struct_array(x, 5, sparse_every=3)], names=["features"]
        )
        got = columnar.extract_matrix(batch, "features")
        np.testing.assert_allclose(got, x, atol=1e-15)

    def test_fit_partition_fn_on_vector_structs(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(64, 6))
        batch = pa.RecordBatch.from_arrays(
            [_vector_struct_array(x, 6, sparse_every=4)], names=["features"]
        )
        fn = arrow_fns.make_fit_partition_fn("features")
        (out,) = list(fn(iter([batch])))
        stats = arrow_fns.stats_from_batches([out])
        np.testing.assert_allclose(
            np.asarray(stats.xtx), x.T @ x, atol=1e-4
        )

    def test_row_vector_to_ndarray_shapes(self):
        dense = {"type": 1, "size": None, "indices": None, "values": [1.0, 2.0]}
        np.testing.assert_allclose(
            columnar.row_vector_to_ndarray(dense), [1.0, 2.0]
        )
        sparse = {"type": 0, "size": 4, "indices": [1, 3], "values": [5.0, 7.0]}
        np.testing.assert_allclose(
            columnar.row_vector_to_ndarray(sparse), [0.0, 5.0, 0.0, 7.0]
        )
        np.testing.assert_allclose(
            columnar.row_vector_to_ndarray([1.0, 2.0]), [1.0, 2.0]
        )
        assert columnar.feature_dim(dense) == 2
        assert columnar.feature_dim(sparse) == 4
        assert columnar.feature_dim([1.0, 2.0, 3.0]) == 3

    def test_ragged_vector_rows_rejected(self):
        arr = pa.StructArray.from_arrays(
            [
                pa.array([1, 1], pa.int8()),
                pa.array([None, None], pa.int32()),
                pa.array([None, None], pa.list_(pa.int32())),
                pa.array([[1.0, 2.0], [1.0, 2.0, 3.0]], pa.list_(pa.float64())),
            ],
            names=["type", "size", "indices", "values"],
        )
        batch = pa.RecordBatch.from_arrays([arr], names=["features"])
        with pytest.raises(ValueError, match="ragged"):
            columnar.extract_matrix(batch, "features")
