"""TruncatedSVD estimator tests — differential vs a NumPy SVD oracle."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import TruncatedSVD, TruncatedSVDModel


def _oracle(x, k):
    _, s, vt = np.linalg.svd(x, full_matrices=False)
    v = vt.T[:, :k]
    idx = np.argmax(np.abs(v), axis=0)
    return v * np.where(v[idx, np.arange(k)] < 0, -1.0, 1.0), s


@pytest.fixture
def x(rng):
    return rng.normal(size=(500, 24)) @ rng.normal(size=(24, 24))


class TestFit:
    @pytest.mark.parametrize("solver", ["gram", "svd"])
    def test_matches_oracle(self, x, solver):
        m = (
            TruncatedSVD()
            .setInputCol("f")
            .setK(5)
            .setSolver(solver)
            .fit(x, num_partitions=3)
        )
        v, s = _oracle(x, 5)
        np.testing.assert_allclose(m.components, v, atol=1e-6)
        np.testing.assert_allclose(m.singularValues, s[:5], rtol=1e-8)

    def test_randomized_solver(self, rng):
        u, _ = np.linalg.qr(rng.normal(size=(600, 32)))
        w, _ = np.linalg.qr(rng.normal(size=(32, 32)))
        x = (u * np.logspace(1, -2, 32)) @ w.T
        m = TruncatedSVD().setInputCol("f").setK(4).setSolver("randomized").fit(x)
        v, s = _oracle(x, 4)
        cos = np.abs(np.sum(m.components * v, axis=0))
        assert cos.min() > 0.9999
        np.testing.assert_allclose(m.singularValues, s[:4], rtol=1e-6)

    def test_uncentered_semantics(self, rng):
        """TruncatedSVD decomposes raw X — a large mean offset must shift the
        leading component toward the mean direction (unlike centered PCA)."""
        x = rng.normal(size=(400, 16)) + 50.0
        m = TruncatedSVD().setInputCol("f").setK(1).fit(x)
        mean_dir = x.mean(0) / np.linalg.norm(x.mean(0))
        assert abs(float(m.components[:, 0] @ mean_dir)) > 0.999

    def test_matches_reference_pca_fit(self, x):
        """On uncentered data TruncatedSVD and the reference-parity PCA fit
        compute the same subspace (the reference's PCA never centers)."""
        from spark_rapids_ml_tpu import PCA

        tsvd = TruncatedSVD().setInputCol("f").setK(4).fit(x, num_partitions=2)
        pca = PCA().setInputCol("f").setK(4).fit(x, num_partitions=2)
        np.testing.assert_allclose(tsvd.components, pca.pc, atol=1e-6)

    def test_k_too_large(self, x):
        with pytest.raises(ValueError):
            TruncatedSVD().setInputCol("f").setK(100).fit(x)

    def test_bad_solver(self):
        with pytest.raises(ValueError):
            TruncatedSVD().setSolver("eig")

    def test_bad_solver_via_kwargs_rejected_at_ctor(self):
        # constructor kwargs route through setSolver, so validation happens
        # at construction time — same contract as the fluent setter
        with pytest.raises(ValueError, match="solver"):
            TruncatedSVD(solver="full")


class TestModel:
    def test_transform_projects(self, x):
        m = TruncatedSVD().setInputCol("f").setK(3).fit(x)
        out = np.asarray(m.transform(x))
        np.testing.assert_allclose(out, x @ m.components, atol=1e-8)

    def test_transform_rows_fallback(self, x):
        m = TruncatedSVD().setInputCol("f").setK(3).fit(x)
        rows = [x[i] for i in range(5)]
        outs = m.transform_rows(rows)
        np.testing.assert_allclose(
            np.stack(outs), x[:5] @ m.components, atol=1e-8
        )

    def test_explained_variance_ratio(self, x):
        m = TruncatedSVD().setInputCol("f").setK(4).fit(x)
        r = m.explained_variance_ratio()
        assert r.shape == (4,) and abs(r.sum() - 1.0) < 1e-9
        assert (np.diff(r) <= 1e-12).all()  # descending

    def test_persistence_roundtrip(self, x, tmp_path):
        m = TruncatedSVD().setInputCol("f").setK(3).fit(x)
        p = str(tmp_path / "tsvd")
        m.save(p)
        m2 = TruncatedSVDModel.load(p)
        np.testing.assert_array_equal(m.components, m2.components)
        np.testing.assert_array_equal(m.singularValues, m2.singularValues)
        assert m2.getK() == 3
