"""Multi-process serve fleet: replicas, consistent-hash router, rolling
restart.

Covers the scale-out half of the serve tail hunt: the model spec round-trips
fitted models into replica processes bitwise; the ``HashRing`` is
deterministic across processes and spreads keys over slots; ``ServeFleet``
spawns supervised replica servers behind one router socket that relays both
the JSON UDS wire and the fast lane verbatim; consistent routing pins a
``(model, bucket)`` key to its home replica (``serve.route_hits``) until
drain/death/saturation walks the ring; and a rolling drain/restart under
live load completes with ZERO failed requests while the respawned replica
re-AOTs entirely from the shared persistent compile cache
(``cache_misses == 0`` in its shutdown report).

The observability-plane tests pin the fleet aggregation contracts: the
STATS scrape frame, the merged registry whose replica-label partition
reproduces each replica's registry exactly, the exporter endpoints
(``/metrics``, ``/healthz``, ``/traces``, ``/traces/<id>``), and the
telemetry-trailer flush on supervised teardown.

Replica processes inherit ``JAX_PLATFORMS=cpu`` from the session env; the
fleet tests keep the bucket list minimal (one rung) so each replica's AOT
warmup is two executables, not the full ladder.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_rapids_ml_tpu.serving import fastlane
from spark_rapids_ml_tpu.serving import fleet as fleet_mod
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.telemetry.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def fitted_models():
    from spark_rapids_ml_tpu.models.linear import LinearRegression
    from spark_rapids_ml_tpu.models.pca import PCA

    rng = np.random.default_rng(11)
    x = rng.normal(size=(200, 6))
    y = x @ rng.normal(size=6) + 0.5
    pca = PCA().setInputCol("features").setK(3).fit(x)
    lin = LinearRegression().fit((x, y))
    return x, pca, lin


@pytest.fixture(scope="module")
def live_fleet(fitted_models, tmp_path_factory):
    """One 2-replica fleet shared by the e2e tests (replica spawn is the
    expensive part; every test gets its own connections)."""
    x, pca, lin = fitted_models
    cache_dir = str(tmp_path_factory.mktemp("fleet_cache"))
    fleet = fleet_mod.ServeFleet(
        {"pca": pca, "lin": lin},
        replicas=2,
        socket_dir=str(tmp_path_factory.mktemp("fleet_sock")),
        bucket_list=(8,),
        extra_env={"TPU_ML_SERVE_COMPILE_CACHE_DIR": cache_dir},
    ).start()
    yield x, fleet
    fleet.stop()


def _read_exact(rf, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = rf.read(n)
        assert chunk, "peer closed mid-frame"
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _fast_call(sock, rf, model: str, x32: np.ndarray) -> np.ndarray:
    sock.sendall(fastlane.pack_request(model, x32))
    return fastlane.read_response(lambda n: _read_exact(rf, n))


def _json_call(sock, rf, model: str, rows: np.ndarray):
    header = json.dumps(
        {"model": model, "wire": "json", "instances": rows.tolist()}
    ).encode()
    sock.sendall(len(header).to_bytes(4, "big") + header)
    n = int.from_bytes(_read_exact(rf, 4), "big")
    resp = json.loads(_read_exact(rf, n))
    if resp.get("payload_bytes"):
        _read_exact(rf, int(resp["payload_bytes"]))
    return resp


# -- model spec --------------------------------------------------------------


class TestModelSpec:
    def test_round_trip_preserves_predictions(
        self, fitted_models, tmp_path
    ):
        x, pca, lin = fitted_models
        path = str(tmp_path / "spec.npz")
        param_bytes = fleet_mod.write_spec(path, {"p": pca, "l": lin})
        assert set(param_bytes) == {"p", "l"}
        assert all(v > 0 for v in param_bytes.values())
        loaded = fleet_mod.load_spec(path)
        assert np.array_equal(
            np.asarray(loaded["p"].transform(x[:16])),
            np.asarray(pca.transform(x[:16])),
        )
        assert np.array_equal(
            np.asarray(loaded["l"].transform(x[:16])),
            np.asarray(lin.transform(x[:16])),
        )

    def test_unservable_model_is_a_type_error(self, tmp_path):
        with pytest.raises(TypeError, match="no fleet spec"):
            fleet_mod.write_spec(str(tmp_path / "bad.npz"), {"x": object()})

    def test_plan_placement_checks_budget(self):
        plan = fleet_mod.plan_placement(
            {"a": 1000, "b": 2000}, 2, budget_bytes=4000
        )
        assert plan["fits"] and plan["param_bytes_per_replica"] == 3000
        over = fleet_mod.plan_placement(
            {"a": 3000, "b": 2000}, 2, budget_bytes=4000
        )
        assert not over["fits"]
        # no budget (CPU hosts): everything fits
        assert fleet_mod.plan_placement(
            {"a": 10**12}, 1, budget_bytes=None
        )["fits"]


# -- consistent-hash ring ----------------------------------------------------


class TestHashRing:
    def test_deterministic_across_instances(self):
        a = fleet_mod.HashRing([0, 1, 2])
        b = fleet_mod.HashRing([0, 1, 2])
        for model in ("m1", "m2", "m3"):
            for bucket in (8, 16, 32):
                key = fleet_mod.HashRing.key(model, bucket)
                assert a.preference(key) == b.preference(key)

    def test_preference_walks_every_slot_once(self):
        ring = fleet_mod.HashRing([0, 1, 2, 3])
        prefs = ring.preference("m/8")
        assert sorted(prefs) == [0, 1, 2, 3]

    def test_keys_spread_over_slots(self):
        ring = fleet_mod.HashRing([0, 1, 2, 3])
        homes = {
            ring.preference(fleet_mod.HashRing.key(f"model{i}", 8))[0]
            for i in range(64)
        }
        # 64 keys over 4 slots with 32 vnodes each: every slot is home
        # to at least one key
        assert homes == {0, 1, 2, 3}

    def test_removing_a_slot_only_moves_its_keys(self):
        full = fleet_mod.HashRing([0, 1, 2])
        keys = [fleet_mod.HashRing.key(f"m{i}", 8) for i in range(48)]
        homes_full = {k: full.preference(k)[0] for k in keys}
        reduced = fleet_mod.HashRing([0, 1])
        for k in keys:
            if homes_full[k] != 2:
                # keys not homed on the removed slot stay put — the
                # consistent-hash property that keeps replica caches warm
                # across fleet resizes
                assert reduced.preference(k)[0] == homes_full[k]


# -- fleet observability plane -----------------------------------------------


class TestFleetObservability:
    """The unified observability plane over a live fleet: per-replica
    STATS scrapes, the merged fleet registry whose replica-label
    partition reproduces each replica's registry exactly, the exporter's
    ``/metrics`` / ``/healthz`` / ``/traces`` endpoints, and the trailer
    flush that keeps a restarted incarnation's telemetry in the fleet
    totals."""

    @staticmethod
    def _drive(fleet, x, n_fast: int = 4, n_json: int = 2) -> None:
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(fleet.router_path)
            rf = s.makefile("rb")
            for _ in range(n_fast):
                _fast_call(s, rf, "pca", x32)
            for _ in range(n_json):
                assert _json_call(s, rf, "lin", x32)["ok"]

    @staticmethod
    def _scrape_snapshot(fleet, slot: int):
        st = fleet.scrape_stats(slot)
        assert st is not None, f"replica {slot} not scrapable"
        reg = MetricsRegistry()
        reg.merge_wire(st["registry"])
        return st, reg.snapshot()

    @staticmethod
    def _series_by_replica(snap, name: str) -> dict:
        out: dict = {}
        for (n, labels), v in snap.counters.items():
            if n == name:
                rep = dict(labels).get("replica", "")
                out[rep] = out.get(rep, 0) + v
        return out

    def test_stats_frame_scrapes_registry_and_events(self, live_fleet):
        x, fleet = live_fleet
        self._drive(fleet, x)
        total = 0.0
        for slot in (0, 1):
            st, snap = self._scrape_snapshot(fleet, slot)
            assert st["ok"] and st["kind"] == "stats"
            assert st["pid"] > 0 and st["seq"] >= 0 and st["mono_us"] > 0
            assert isinstance(st["events"], list)
            total += snap.counter("serve.requests")
        # between them the two replica registries cover the traffic
        assert total >= 6
        offsets = fleet.stats()["clock_offsets_us"]
        assert sorted(offsets) == ["0", "1"]
        assert all(isinstance(v, int) for v in offsets.values())

    def test_fleet_metrics_are_the_sum_of_replica_registries(
        self, live_fleet
    ):
        """The ``/metrics`` contract: the merged fleet registry's total
        for any serve family equals the sum of the per-replica registries
        (live scrapes plus harvested final fragments), and the replica
        label partitions the merged registry back into exactly those
        per-replica values."""
        x, fleet = live_fleet
        self._drive(fleet, x)
        per_slot = {
            str(slot): self._scrape_snapshot(fleet, slot)[1]
            for slot in (0, 1)
        }
        harvested = fleet._final_registry.snapshot()
        merged = fleet.fleet_registry(include_router=False).snapshot()
        for name in ("serve.requests", "serve.rows", "serve.batches"):
            assert merged.counter(name) == pytest.approx(
                sum(s.counter(name) for s in per_slot.values())
                + harvested.counter(name)
            ), f"fleet total for {name} is not the sum of its replicas"
        merged_by_rep = self._series_by_replica(merged, "serve.requests")
        harv_by_rep = self._series_by_replica(harvested, "serve.requests")
        for slot, snap in per_slot.items():
            assert merged_by_rep.get(slot, 0) == pytest.approx(
                snap.counter("serve.requests") + harv_by_rep.get(slot, 0)
            )
        # the router's own registry joins under replica="router"
        full = fleet.fleet_registry().snapshot()
        hits = self._series_by_replica(full, "serve.route_hits")
        assert hits.get("router", 0) > 0

    def test_exporter_unified_observability_plane(self, live_fleet):
        x, fleet = live_fleet
        self._drive(fleet, x, n_fast=3, n_json=1)
        ex = fleet.start_exporter()
        assert fleet.start_exporter() is ex  # idempotent
        body = urllib.request.urlopen(
            ex.url("/metrics"), timeout=10
        ).read().decode()
        assert "# TYPE tpu_ml_serve_requests counter" in body
        assert 'replica="0"' in body and 'replica="1"' in body
        assert 'replica="router"' in body
        health = json.loads(
            urllib.request.urlopen(ex.url("/healthz"), timeout=10).read()
        )
        assert health["status"] == "ok"
        assert health["components"]["router"] == "ok"
        assert health["components"]["replica-0"] == "ok"
        cov = json.loads(
            urllib.request.urlopen(ex.url("/traces"), timeout=10).read()
        )
        assert cov["traces"] >= 1 and "coverage" in cov
        # one stitched cross-process tree: the last relayed request
        relays = [
            e for e in fleet.fleet_events()
            if e.get("name") == "serve.relay"
        ]
        assert relays, "router recorded no relay spans"
        tid = (relays[-1].get("args") or {}).get("trace_id")
        assert tid
        tree = json.loads(
            urllib.request.urlopen(
                ex.url(f"/traces/{tid}"), timeout=10
            ).read()
        )
        assert tree["trace_id"] == tid and tree["complete"]
        assert len(tree["roots"]) == 1
        root = tree["roots"][0]
        assert root["name"] == "serve.relay"
        child_names = {c["name"] for c in root["children"]}
        assert "serve.request" in child_names
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                ex.url("/traces/ffffffffffffffff"), timeout=10
            )
        assert err.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(ex.url("/nope"), timeout=10)
        assert err.value.code == 404
        # worst-of rollup: one draining replica degrades the fleet
        assert fleet.drain(1)
        try:
            health = json.loads(
                urllib.request.urlopen(
                    ex.url("/healthz"), timeout=10
                ).read()
            )
            assert health["status"] == "degraded"
            assert health["components"]["replica-1"] == "draining"
        finally:
            fleet.undrain(1)

    def test_supervised_teardown_flushes_the_telemetry_trailer(
        self, live_fleet
    ):
        """A restarted replica's final registry + flight-recorder
        fragment must land in the fleet plane: the incarnation's
        telemetry survives the process."""
        x, fleet = live_fleet
        self._drive(fleet, x)
        # pick the slot that served the most requests this incarnation
        victim, served = 0, -1.0
        for slot in (0, 1):
            n = self._scrape_snapshot(fleet, slot)[1].counter(
                "serve.requests"
            )
            if n > served:
                victim, served = slot, n
        assert served > 0
        old_pid = fleet._supervisor._slots[victim].worker.proc.pid
        before = fleet._final_registry.snapshot().counter("serve.requests")
        assert fleet.restart_replica(victim), "respawn never became READY"
        assert (victim, old_pid) in fleet._harvested
        harvested = fleet._final_registry.snapshot()
        assert harvested.counter("serve.requests") - before >= served
        # the dead incarnation's events ride the merged stream,
        # replica-stamped for the fleet trace merge
        ev = [
            e for e in fleet.fleet_events() if e.get("pid") == old_pid
        ]
        assert ev and all(
            (e.get("args") or {}).get("replica") == str(victim)
            for e in ev
        )
        # and the merged fleet registry still covers it
        merged = fleet.fleet_registry(include_router=False).snapshot()
        assert merged.counter("serve.requests") >= served


# -- fleet end-to-end --------------------------------------------------------


class TestFleetE2E:
    def test_both_wires_relay_with_parity(self, live_fleet):
        """The router relays the fast lane and the JSON lane verbatim;
        both lanes answer bitwise-identically for the same request (the
        home replica serves both, so this also proves the relay does not
        corrupt frames)."""
        x, fleet = live_fleet
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(fleet.router_path)
            rf = s.makefile("rb")
            fast_out = _fast_call(s, rf, "lin", x32)
            resp = _json_call(s, rf, "lin", x32)
        assert resp["ok"] and resp["rows"] == 4
        json_out = np.asarray(resp["predictions"], dtype="<f4")
        assert fast_out.tobytes() == json_out.reshape(
            fast_out.shape
        ).tobytes()

    def test_consistent_routing_books_home_hits(self, live_fleet):
        """Sequential traffic for one (model, bucket) key always lands on
        its home replica: all hits, zero misses."""
        x, fleet = live_fleet
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        snap = REGISTRY.snapshot()
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(fleet.router_path)
            rf = s.makefile("rb")
            for _ in range(6):
                _fast_call(s, rf, "pca", x32)
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.route_hits", model="pca") == 6
        assert delta.counter("serve.route_misses", model="pca") == 0

    def test_error_relays_without_killing_connection(self, live_fleet):
        x, fleet = live_fleet
        x32 = np.ascontiguousarray(x[:2], dtype="<f4")
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(fleet.router_path)
            rf = s.makefile("rb")
            with pytest.raises(fastlane.FastlaneError) as e:
                _fast_call(s, rf, "ghost", x32)
            assert e.value.status == 404
            out = _fast_call(s, rf, "lin", x32)
        assert out.shape[0] == 2

    def test_stats_and_gauge(self, live_fleet):
        _, fleet = live_fleet
        stats = fleet.stats()
        assert stats["replicas"] == 2
        assert stats["live_replicas"] == 2
        assert stats["placement"]["fits"]
        assert sorted(stats["in_flight"]) == ["0", "1"]

    def test_rolling_restart_under_live_load_zero_failures(
        self, live_fleet
    ):
        """The headline operational contract: drain + respawn one replica
        while a client hammers the router — zero failed requests, and the
        respawned replica's shutdown report shows it re-AOT'd entirely
        from the shared persistent compile cache (cache_misses == 0)."""
        x, fleet = live_fleet
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        stop = threading.Event()
        failures: list[Exception] = []
        completed = [0]

        def hammer():
            with socket.socket(socket.AF_UNIX) as s:
                s.connect(fleet.router_path)
                rf = s.makefile("rb")
                while not stop.is_set():
                    try:
                        _fast_call(s, rf, "lin", x32)
                        resp = _json_call(s, rf, "pca", x32)
                        assert resp["ok"]
                        completed[0] += 2
                    except Exception as e:  # noqa: BLE001 — collected
                        # and asserted empty below
                        failures.append(e)
                        return

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            snap = REGISTRY.snapshot()
            for slot in (0, 1):
                assert fleet.restart_replica(slot), (
                    f"replica {slot} respawn never became READY"
                )
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not failures, f"requests failed during rolling restart: {failures[:3]}"
        assert completed[0] > 0
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.drain_events") == 2
        assert delta.counter("serve.replica_restarts") == 2
        # both live replicas are now respawns; traffic still flows
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(fleet.router_path)
            rf = s.makefile("rb")
            out = _fast_call(s, rf, "lin", x32)
        assert out.shape == (4, 1)
        # the warm-respawn proof: stop the fleet and read each replica's
        # shutdown report — every compile on the respawned replicas was a
        # persistent-cache load, zero fresh XLA compiles after restart
        workers = [fleet._supervisor._slots[s].worker for s in (0, 1)]
        fleet.stop()
        for w in workers:
            assert w is not None and w.cache_misses == 0, (
                f"respawned replica paid {w and w.cache_misses} fresh "
                "compile(s); expected a fully warm AOT-cache respawn"
            )
            assert w.cache_hits and w.cache_hits > 0
