"""NaiveBayes tests — sklearn PARAMETER-level differentials (the closed
forms are identical, so theta/pi must agree to float precision)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import NaiveBayes, NaiveBayesModel


@pytest.fixture(scope="module")
def count_data():
    rng = np.random.default_rng(0)
    x = rng.poisson(3.0, size=(900, 12)).astype(float)
    y = rng.integers(0, 3, size=900).astype(float)
    # make classes separable-ish: class c inflates features [4c, 4c+4)
    for c in range(3):
        x[y == c, 4 * c : 4 * c + 4] += rng.poisson(6.0, size=(int((y == c).sum()), 4))
    return x, y


def _spark_priors(y, n_classes, lam):
    """Spark's smoothed class priors (NaiveBayes.scala piLogDenom):
    (n_i + λ)/(N + λ·C) — probability space, for sklearn's class_prior."""
    counts = np.array([(y == c).sum() for c in range(n_classes)], float)
    return (counts + lam) / (counts.sum() + lam * n_classes)


def test_multinomial_matches_sklearn_parameters(count_data):
    sk_nb = pytest.importorskip("sklearn.naive_bayes")
    x, y = count_data
    m = NaiveBayes().setSmoothing(1.0).fit((x, y))
    # sklearn's default prior is the unsmoothed log(n_i/N); Spark smooths
    # the prior with the same λ as the likelihoods, so hand sklearn the
    # smoothed prior explicitly and the two models must agree exactly
    sk = sk_nb.MultinomialNB(
        alpha=1.0, class_prior=_spark_priors(y, 3, 1.0)
    ).fit(x, y)
    np.testing.assert_allclose(m.pi, sk.class_log_prior_, rtol=1e-12)
    np.testing.assert_allclose(m.theta, sk.feature_log_prob_, rtol=1e-12)
    np.testing.assert_array_equal(m._predict_matrix(x), sk.predict(x))
    proba, _ = m.proba_and_predictions(x[:50])
    np.testing.assert_allclose(proba, sk.predict_proba(x[:50]), atol=1e-10)


def test_class_priors_match_spark_smoothing_formula(count_data):
    """Documented Spark parity: π_i = log((n_i + λ)/(N + λ·C)) — including
    a class with zero observed rows, whose prior stays finite."""
    x, y = count_data
    lam = 0.7
    m = NaiveBayes().setSmoothing(lam).fit((x, y))
    counts = np.array([(y == c).sum() for c in range(3)], float)
    expected = np.log((counts + lam) / (counts.sum() + lam * 3))
    np.testing.assert_allclose(m.pi, expected, rtol=1e-12)

    # empty class: relabel class 1 into 0; label 2 keeps the 3-class space
    y2 = np.where(y == 1, 0.0, y)
    m2 = NaiveBayes().setSmoothing(lam).fit((x, y2))
    assert np.isfinite(m2.pi).all()
    counts2 = np.array([(y2 == c).sum() for c in range(3)], float)
    expected2 = np.log((counts2 + lam) / (counts2.sum() + lam * 3))
    np.testing.assert_allclose(m2.pi, expected2, rtol=1e-12)


def test_bernoulli_matches_sklearn(count_data):
    sk_nb = pytest.importorskip("sklearn.naive_bayes")
    x, y = count_data
    xb = (x > 3).astype(float)
    m = NaiveBayes().setModelType("bernoulli").setSmoothing(1.0).fit((xb, y))
    sk = sk_nb.BernoulliNB(
        alpha=1.0, class_prior=_spark_priors(y, 3, 1.0)
    ).fit(xb, y)
    np.testing.assert_allclose(m.theta, sk.feature_log_prob_, rtol=1e-12)
    np.testing.assert_array_equal(m._predict_matrix(xb), sk.predict(xb))


def test_gaussian_matches_sklearn(count_data):
    sk_nb = pytest.importorskip("sklearn.naive_bayes")
    rng = np.random.default_rng(1)
    x = rng.normal(size=(600, 5)) + rng.integers(0, 2, size=600)[:, None] * 3
    y = (x[:, 0] > 1.5).astype(float)
    m = NaiveBayes().setModelType("gaussian").fit((x, y))
    sk = sk_nb.GaussianNB(
        var_smoothing=0.0, priors=_spark_priors(y, 2, 1.0)
    ).fit(x, y)
    np.testing.assert_allclose(m.theta, sk.theta_, rtol=1e-10)
    np.testing.assert_allclose(m.sigma, sk.var_, rtol=1e-8)
    agree = (m._predict_matrix(x) == sk.predict(x)).mean()
    assert agree > 0.999, agree


def test_weighted_equals_duplication(count_data):
    x, y = count_data
    dup = np.arange(0, len(x), 5)
    w = np.ones(len(x)); w[dup] = 2.0
    m_w = NaiveBayes().fit((x, y, w))
    m_d = NaiveBayes().fit(
        (np.concatenate([x, x[dup]]), np.concatenate([y, y[dup]]))
    )
    np.testing.assert_allclose(m_w.theta, m_d.theta, rtol=1e-10)
    np.testing.assert_allclose(m_w.pi, m_d.pi, rtol=1e-10)


def test_validation_and_columns(count_data):
    pd = pytest.importorskip("pandas")
    x, y = count_data
    with pytest.raises(ValueError, match="non-negative"):
        NaiveBayes().fit((x - 100.0, y))
    with pytest.raises(ValueError, match="0/1 features"):
        NaiveBayes().setModelType("bernoulli").fit((x, y))
    with pytest.raises(ValueError, match="modelType"):
        NaiveBayes().setModelType("poisson")
    m = NaiveBayes().fit(pd.DataFrame({"features": list(x), "label": y}))
    out = m.transform(pd.DataFrame({"features": list(x[:20])}))
    assert {"rawPrediction", "probability", "prediction"} <= set(out.columns)
    p = np.stack(out["probability"])
    np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-12)


def test_persistence_and_partitions(tmp_path, count_data):
    x, y = count_data
    m1 = NaiveBayes().fit((x, y), num_partitions=1)
    m4 = NaiveBayes().fit((x, y), num_partitions=4)
    np.testing.assert_allclose(m1.theta, m4.theta, rtol=1e-10)  # monoid
    path = str(tmp_path / "nb")
    m1.save(path)
    loaded = NaiveBayesModel.load(path)
    assert loaded.getModelType() == "multinomial"
    np.testing.assert_array_equal(
        loaded._predict_matrix(x[:50]), m1._predict_matrix(x[:50])
    )


def test_gaussian_stable_on_offset_features():
    """Epoch-timestamp-style features (offset 1e8, spread 1): the centered
    second pass keeps variances exact where Sq/N − mu^2 cancels to junk."""
    sk_nb = pytest.importorskip("sklearn.naive_bayes")
    rng = np.random.default_rng(3)
    y = rng.integers(0, 2, size=500).astype(float)
    x = 1e8 + rng.normal(size=(500, 4)) + y[:, None] * 2.0
    m = NaiveBayes().setModelType("gaussian").fit((x, y))
    sk = sk_nb.GaussianNB(var_smoothing=0.0).fit(x, y)
    np.testing.assert_allclose(m.sigma, sk.var_, rtol=1e-6)
    assert (m._predict_matrix(x) == sk.predict(x)).mean() > 0.999


def test_bernoulli_rejects_nonbinary_at_predict(count_data):
    x, y = count_data
    xb = (x > 3).astype(float)
    m = NaiveBayes().setModelType("bernoulli").fit((xb, y))
    with pytest.raises(ValueError, match="0 or 1 feature values"):
        m._predict_matrix(x)  # raw counts, not binarized


def test_mesh_local_fit_equals_driver_merge(count_data):
    """distribution='mesh-local' produces the identical model (psum of an
    integer-valued monoid), for the one-pass multinomial AND the
    two-pass gaussian."""
    x, y = count_data
    m_d = NaiveBayes().fit((x, y))
    m_m = NaiveBayes().setDistribution("mesh-local").fit((x, y))
    np.testing.assert_allclose(m_m.theta, m_d.theta, rtol=1e-12)
    np.testing.assert_allclose(m_m.pi, m_d.pi, rtol=1e-12)

    rng = np.random.default_rng(7)
    xg = rng.normal(size=(500, 4)) + 1e6  # offset: exercises the stable pass
    yg = rng.integers(0, 2, size=500).astype(float)
    g_d = NaiveBayes().setModelType("gaussian").fit((xg, yg))
    g_m = (
        NaiveBayes().setModelType("gaussian").setDistribution("mesh-local")
        .fit((xg, yg))
    )
    np.testing.assert_allclose(g_m.sigma, g_d.sigma, rtol=1e-9)
    np.testing.assert_allclose(g_m.theta, g_d.theta, rtol=1e-12)


def test_sharded_stats_match_tree_reduce(count_data):
    """The NBStats monoid over the mesh psum equals the host tree-reduce
    exactly (integer-valued sums in f64)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops import naive_bayes as NBops
    from spark_rapids_ml_tpu.parallel.mesh import create_mesh
    from spark_rapids_ml_tpu.parallel.naive_bayes import sharded_nb_stats

    x, y = count_data
    ndev = len(jax.devices())
    rows = (len(x) // ndev) * ndev
    xs, ys = x[:rows], y[:rows]
    w = np.ones(rows)
    mesh = create_mesh(data=ndev)
    got = sharded_nb_stats(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(w), 3, mesh
    )
    ref = NBops.nb_stats(jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(w), 3)
    np.testing.assert_array_equal(np.asarray(got.counts), np.asarray(ref.counts))
    np.testing.assert_allclose(
        np.asarray(got.feat_sum), np.asarray(ref.feat_sum), rtol=1e-12
    )
