"""Unit tests for the resilience package: fault-plan parsing, error
classification, the shared retry policy, and the executor's migration onto
it (including the sleep-after-final-attempt fix)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.parallel import executor
from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.resilience import retry as R
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY


@pytest.fixture(autouse=True)
def clean_faults(monkeypatch):
    monkeypatch.delenv(faults.FAULT_PLAN_VAR, raising=False)
    faults.reset_faults()
    yield
    faults.reset_faults()


class TestPlanParsing:
    def test_parses_entries_and_args(self):
        plan = faults.parse_plan("fold.dispatch:oom:3, ingest.chunk:io:1,fold.wait:hang:2:0.5")
        assert plan == (
            faults.FaultSpec("fold.dispatch", "oom", 3),
            faults.FaultSpec("ingest.chunk", "io", 1),
            faults.FaultSpec("fold.wait", "hang", 2, 0.5),
        )

    def test_empty_plan(self):
        assert faults.parse_plan("") == ()
        assert faults.parse_plan(" , ") == ()

    @pytest.mark.parametrize(
        "raw,msg",
        [
            ("fold.dispatch:oom", "site:kind:nth"),
            ("a:frobnicate:1", "not one of"),
            ("a:io:x", "not an int"),
            ("a:io:0", ">= 1"),
        ],
    )
    def test_rejects_malformed(self, raw, msg):
        with pytest.raises(ValueError, match=msg):
            faults.parse_plan(raw)

    def test_nth_occurrence_fires_once(self, monkeypatch):
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "s:io:2")
        faults.inject("s")  # occurrence 1: clean
        with pytest.raises(faults.InjectedTransientIOError):
            faults.inject("s")  # occurrence 2: fires
        faults.inject("s")  # occurrence 3: clean again (transient clears)

    def test_nonfinite_corrupts_data(self, monkeypatch):
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "s:nonfinite:1")
        x = np.ones((4, 3))
        out = faults.inject("s", x)
        assert np.isnan(out.reshape(-1)[0])
        assert np.isfinite(x).all(), "input must not be mutated in place"

    def test_no_plan_is_passthrough(self):
        x = np.ones(3)
        assert faults.inject("anything", x) is x


class _FakeXlaRuntimeError(Exception):
    pass


# classify() recognizes XlaRuntimeError structurally by class name
_FakeXlaRuntimeError.__name__ = "XlaRuntimeError"


class TestClassify:
    @pytest.mark.parametrize(
        "exc,want",
        [
            (OSError("disk"), R.ErrorClass.TRANSIENT),
            (ConnectionResetError("peer"), R.ErrorClass.TRANSIENT),
            (TimeoutError(), R.ErrorClass.TRANSIENT),
            (EOFError(), R.ErrorClass.TRANSIENT),
            (MemoryError(), R.ErrorClass.RESOURCE_EXHAUSTED),
            (ValueError("shape"), R.ErrorClass.FATAL),
            (R.FoldHangTimeout("hung"), R.ErrorClass.POISONED),
            (faults.InjectedResourceExhausted("x"), R.ErrorClass.RESOURCE_EXHAUSTED),
            (faults.InjectedTransientIOError("x"), R.ErrorClass.TRANSIENT),
            (faults.InjectedPreemption("x"), R.ErrorClass.FATAL),
        ],
    )
    def test_basic(self, exc, want):
        assert R.classify(exc) is want

    @pytest.mark.parametrize(
        "msg,want",
        [
            ("RESOURCE_EXHAUSTED: out of memory allocating 2G", R.ErrorClass.RESOURCE_EXHAUSTED),
            ("Out of memory while trying to allocate", R.ErrorClass.RESOURCE_EXHAUSTED),
            ("UNAVAILABLE: connection reset by peer", R.ErrorClass.TRANSIENT),
            ("DEADLINE_EXCEEDED: collective timed out", R.ErrorClass.TRANSIENT),
            ("FAILED_PRECONDITION: PJRT client is dead", R.ErrorClass.POISONED),
            ("INVALID_ARGUMENT: mismatched shapes", R.ErrorClass.FATAL),
        ],
    )
    def test_xla_status_families(self, msg, want):
        assert R.classify(_FakeXlaRuntimeError(msg)) is want


class TestRetryPolicy:
    def test_backoff_deterministic_and_capped(self):
        pol = R.RetryPolicy(backoff_s=0.1, multiplier=2.0, max_backoff_s=0.3, jitter=0.1, seed=7)
        assert pol.sleep_s(1) == pol.sleep_s(1)  # deterministic per attempt
        for k in range(1, 8):
            assert pol.sleep_s(k) <= 0.3 * 1.1 + 1e-12
        nojit = R.RetryPolicy(backoff_s=0.1, multiplier=2.0, max_backoff_s=10.0, jitter=0.0)
        assert nojit.sleep_s(1) == pytest.approx(0.1)
        assert nojit.sleep_s(3) == pytest.approx(0.4)

    def test_from_config_reads_env_knobs(self, monkeypatch):
        from spark_rapids_ml_tpu.utils.config import set_config

        old_att, old_dl = None, None
        from spark_rapids_ml_tpu.utils.config import get_config

        cfg = get_config()
        old_att, old_dl = cfg.retry_max_attempts, cfg.retry_deadline_s
        try:
            set_config(retry_max_attempts=7, retry_deadline_s=0)
            pol = R.RetryPolicy.from_config()
            assert pol.max_attempts == 7
            assert pol.deadline_s is None  # 0 = unbounded
        finally:
            set_config(retry_max_attempts=old_att, retry_deadline_s=old_dl)

    def test_transient_clears_after_retries(self):
        calls = {"n": 0}
        sleeps = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("blip")
            return "ok"

        out = R.call_with_retry(
            flaky, site="t", policy=R.RetryPolicy(max_attempts=4, backoff_s=0.01),
            sleep=sleeps.append,
        )
        assert out == "ok"
        assert calls["n"] == 3
        assert len(sleeps) == 2

    def test_never_sleeps_after_final_attempt(self):
        sleeps = []

        def always():
            raise OSError("down")

        with pytest.raises(OSError):
            R.call_with_retry(
                always, site="t", policy=R.RetryPolicy(max_attempts=3, backoff_s=0.01),
                sleep=sleeps.append,
            )
        # 3 attempts -> 2 sleeps between them, NONE after the last failure
        assert len(sleeps) == 2

    def test_fatal_not_retried(self):
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise ValueError("shape")

        with pytest.raises(ValueError):
            R.call_with_retry(bad, policy=R.RetryPolicy(max_attempts=5))
        assert calls["n"] == 1

    def test_deadline_stops_retrying(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            raise OSError("blip")

        with pytest.raises(OSError):
            R.call_with_retry(
                flaky,
                policy=R.RetryPolicy(max_attempts=100, backoff_s=0.0, deadline_s=-1.0),
                sleep=lambda s: None,
            )
        assert calls["n"] == 1

    def test_retry_counted_in_telemetry(self):
        snap0 = REGISTRY.snapshot()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 2:
                raise OSError("blip")
            return 1

        R.call_with_retry(
            flaky, site="unit.test", policy=R.RetryPolicy(max_attempts=3),
            sleep=lambda s: None,
        )
        delta = REGISTRY.snapshot().delta(snap0)
        assert delta.counter("retry.attempts", site="unit.test") == 1


class TestExecutorMigration:
    def test_succeeds_after_injected_transient(self, monkeypatch):
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "worker.task:io:1")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        snap0 = REGISTRY.snapshot()
        out = executor.run_partition_tasks(
            lambda v: v * 2, [5], max_retries=2, retry_backoff_s=0.0
        )
        assert out == [10]
        delta = REGISTRY.snapshot().delta(snap0)
        assert delta.counter("fault.injected", site="worker.task", kind="io") == 1
        assert delta.counter("retry.attempts", site="worker.task") == 1

    def test_exhaustion_raises_without_trailing_sleep(self, monkeypatch):
        # the pre-migration loop slept retry_backoff_s * 2**att AFTER the
        # final failed attempt before raising; the shared policy must not
        monkeypatch.setenv(
            faults.FAULT_PLAN_VAR,
            "worker.task:io:1,worker.task:io:2,worker.task:io:3",
        )
        sleeps = []
        monkeypatch.setattr(R.time, "sleep", sleeps.append)
        with pytest.raises(executor.TaskFailedError, match="failed after 3 attempts"):
            executor.run_partition_tasks(
                lambda v: v, [1], max_retries=2, retry_backoff_s=0.01
            )
        assert len(sleeps) == 2, f"slept after the final attempt: {sleeps}"

    def test_log_format_preserved(self, monkeypatch, caplog):
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "worker.task:io:1")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        with caplog.at_level("WARNING", logger="spark_rapids_ml_tpu"):
            executor.run_partition_tasks(
                lambda v: v, [1], max_retries=1, retry_backoff_s=0.0
            )
        assert any(
            "partition task 0 attempt 1/2 failed" in r.message for r in caplog.records
        )

    def test_results_stay_ordered_under_faults(self, monkeypatch):
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "worker.task:io:2,worker.task:io:5")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        out = executor.run_partition_tasks(
            lambda v: v, list(range(6)), max_retries=3, max_workers=1,
            retry_backoff_s=0.0,
        )
        assert out == list(range(6))


class TestExecutorHedging:
    """Speculative duplicates for stragglers: retry answers 'it failed',
    hedging answers 'it is taking too long' — a wedged attempt never fails,
    so only a duplicate can rescue the task's wall-clock."""

    def test_straggler_hedged_first_result_wins(self, monkeypatch):
        import threading
        import time

        monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "2.0")
        monkeypatch.setenv("TPU_ML_HEDGE_FLOOR_S", "0.05")
        lock = threading.Lock()
        calls = {"slow": 0}

        def fn(v):
            if v == 2:
                with lock:
                    calls["slow"] += 1
                    wedged = calls["slow"] == 1
                if wedged:  # only the FIRST attempt of item 2 is stuck
                    time.sleep(1.0)
            return v * 10

        snap0 = REGISTRY.snapshot()
        out = executor.run_partition_tasks(
            fn, list(range(4)), max_workers=4, max_retries=0
        )
        assert out == [0, 10, 20, 30]
        d = REGISTRY.snapshot().delta(snap0)
        assert d.counter("scheduler.hedge", task="2") == 1
        assert calls["slow"] == 2  # the hedge twin really ran

    def test_factor_zero_disables_hedging(self, monkeypatch):
        import time

        monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "0")
        monkeypatch.setenv("TPU_ML_HEDGE_FLOOR_S", "0.0")

        def fn(v):
            if v == 1:
                time.sleep(0.2)
            return v

        snap0 = REGISTRY.snapshot()
        out = executor.run_partition_tasks(
            fn, list(range(3)), max_workers=3, max_retries=0
        )
        assert out == [0, 1, 2]
        assert REGISTRY.snapshot().delta(snap0).counter("scheduler.hedge") == 0
