"""Sketched (no-n×n) PCA tests on the virtual 8-device mesh.

This path is the capability the reference structurally lacks: its fit
allocates n×n per task (RapidsRowMatrix.scala:50-52). Here neither X nor any
intermediate is ever replicated or n×n — verified below via output shardings.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.parallel import mesh as M
from spark_rapids_ml_tpu.parallel import sketched as SK


def _decaying(rng, rows, n, decay_to=-3):
    u, _ = np.linalg.qr(rng.normal(size=(rows, n)))
    v, _ = np.linalg.qr(rng.normal(size=(n, n)))
    s = np.logspace(1, decay_to, n)
    return (u * s) @ v.T


def _oracle(x, k, center=False):
    xc = x - x.mean(0, keepdims=True) if center else x
    _, s, vt = np.linalg.svd(xc, full_matrices=False)
    v = vt.T[:, :k]
    idx = np.argmax(np.abs(v), axis=0)
    return v * np.where(v[idx, np.arange(k)] < 0, -1.0, 1.0), s


@pytest.fixture(scope="module")
def mesh42():
    return M.create_mesh(data=4, feat=2)


class TestSketchedPCA:
    def test_matches_oracle_on_decaying_spectrum(self, mesh42, rng):
        x = _decaying(rng, 512, 64)
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, ev = SK.sketched_pca_fit(xs, 8, mesh42)
        v, s = _oracle(x, 8)
        cos = np.abs(np.sum(np.asarray(pc) * v, axis=0))
        assert cos.min() > 0.9999
        # reference ev definition: s_i / sum(s) over the full spectrum. The
        # tail estimate is documented-conservative (concavity upper bound on
        # the unseen tail ⇒ ratios shrink): never above truth, near it.
        truth = (s / s.sum())[:8]
        assert (np.asarray(ev) <= truth + 1e-9).all()
        np.testing.assert_allclose(np.asarray(ev), truth, rtol=0.10)

    def test_components_are_feature_sharded(self, mesh42, rng):
        x = _decaying(rng, 256, 64)
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        fit = SK.make_sketched_fit(mesh42, 4)
        pc, _ = fit(xs)
        # [n, k] sharded by block-row over feat: each shard [n/2, k]
        shard_shapes = {sh.data.shape for sh in pc.addressable_shards}
        assert shard_shapes == {(32, 4)}

    def test_sign_convention_matches_reference(self, mesh42, rng):
        x = _decaying(rng, 512, 64)
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, _ = SK.sketched_pca_fit(xs, 6, mesh42)
        pc = np.asarray(pc)
        # per column: the max-|element| must be positive (rapidsml_jni.cu:40-60)
        anchors = pc[np.argmax(np.abs(pc), axis=0), np.arange(6)]
        assert (anchors > 0).all()

    def test_centered(self, mesh42, rng):
        x = _decaying(rng, 512, 64) + 5.0
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, _ = SK.sketched_pca_fit(xs, 5, mesh42, mean_centering=True)
        v, _ = _oracle(x, 5, center=True)
        cos = np.abs(np.sum(np.asarray(pc) * v, axis=0))
        assert cos.min() > 0.9999

    def test_wider_feat_axis(self, rng):
        mesh = M.create_mesh(data=2, feat=4)
        x = _decaying(rng, 256, 64)
        xs = jax.device_put(x, M.data_sharding(mesh, feature_sharded=True))
        pc, ev = SK.sketched_pca_fit(xs, 4, mesh)
        v, _ = _oracle(x, 4)
        cos = np.abs(np.sum(np.asarray(pc) * v, axis=0))
        assert cos.min() > 0.9999

    def test_more_power_iters_help_flat_spectrum(self, mesh42, rng):
        x = _decaying(rng, 512, 64, decay_to=-0.5)  # slow decay: hard case
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        v, _ = _oracle(x, 4)

        def cos_min(iters):
            pc, _ = SK.sketched_pca_fit(xs, 4, mesh42, power_iters=iters)
            return np.abs(np.sum(np.asarray(pc) * v, axis=0)).min()

        assert cos_min(6) >= cos_min(0) - 1e-9
        assert cos_min(6) > 0.999

    def test_rank_deficient_input(self, mesh42, rng):
        """rank(X) < l = k + oversample must not poison the fit: the TSQR R
        is singular there, and the pinv-based orthonormalization maps null
        directions to zero Ritz values instead of dividing by ~0."""
        n, rank, k = 64, 8, 4
        x = rng.normal(size=(512, rank)) @ rng.normal(size=(rank, n))
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, ev = SK.sketched_pca_fit(xs, k, mesh42)
        v, _ = _oracle(x, k)
        cos = np.abs(np.sum(np.asarray(pc) * v, axis=0))
        assert cos.min() > 0.9999
        assert np.isfinite(np.asarray(ev)).all()

    def test_exact_rank_equals_k(self, mesh42, rng):
        n, k = 64, 4
        x = rng.normal(size=(512, k)) @ rng.normal(size=(k, n))
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, _ = SK.sketched_pca_fit(xs, k, mesh42)
        v, _ = _oracle(x, k)
        cos = np.abs(np.sum(np.asarray(pc) * v, axis=0))
        assert cos.min() > 0.9999

    def test_sharded_project_end_to_end(self, mesh42, rng):
        """fit + transform with NOTHING n-sized replicated anywhere."""
        x = _decaying(rng, 512, 64)
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, _ = SK.make_sketched_fit(mesh42, 5)(xs)
        out = SK.make_sharded_project(mesh42)(xs, pc)
        # oracle: dense projection with the gathered components
        np.testing.assert_allclose(
            np.asarray(out), x @ np.asarray(pc), atol=1e-8
        )
        # output is data-sharded [rows/4, k] per shard
        assert {s.data.shape for s in out.addressable_shards} == {(128, 5)}

    def test_sharded_project_centered(self, mesh42, rng):
        """Components from a centered fit must project (X−μ)·V, with μ
        feature-sharded — never replicated."""
        x = _decaying(rng, 512, 64) + 7.0
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc, _ = SK.sketched_pca_fit(xs, 4, mesh42, mean_centering=True)
        mu = SK.sharded_column_means(xs, mesh42)
        np.testing.assert_allclose(np.asarray(mu), x.mean(0), rtol=1e-12)
        out = SK.make_sharded_project(mesh42, centered=True)(xs, pc, mu)
        expect = (x - x.mean(0)) @ np.asarray(pc)
        np.testing.assert_allclose(np.asarray(out), expect, atol=1e-7)

    def test_sharded_project_matches_dense(self, mesh42, rng):
        x = rng.normal(size=(256, 64))
        v = rng.normal(size=(64, 7))
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        from jax.sharding import NamedSharding, PartitionSpec as P

        vs = jax.device_put(v, NamedSharding(mesh42, P(M.FEAT_AXIS, None)))
        out = SK.sharded_project(xs, vs, mesh42)
        np.testing.assert_allclose(np.asarray(out), x @ v, atol=1e-9)

    def test_seed_determinism(self, mesh42, rng):
        x = _decaying(rng, 256, 64)
        xs = jax.device_put(x, M.data_sharding(mesh42, feature_sharded=True))
        pc1, _ = SK.sketched_pca_fit(xs, 4, mesh42, seed=3)
        pc2, _ = SK.sketched_pca_fit(xs, 4, mesh42, seed=3)
        np.testing.assert_array_equal(np.asarray(pc1), np.asarray(pc2))
