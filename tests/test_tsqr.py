"""TSQR + direct-SVD fit path tests.

The capability under test has no reference analog (the reference's only fit
route is Gram + cuSolver eig, SURVEY.md §3.1): a communication-avoiding QR
whose R factors merge across partitions/devices, giving principal components
at cond(X) instead of cond(X)² accuracy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.parallel import mesh as M
from spark_rapids_ml_tpu.parallel import tsqr as T


def _oracle_components(x, k, center=False):
    """NumPy f64 oracle: right singular vectors, reference sign convention."""
    xc = x - x.mean(0, keepdims=True) if center else x
    _, s, vt = np.linalg.svd(xc, full_matrices=False)
    v = vt.T[:, :k]
    idx = np.argmax(np.abs(v), axis=0)
    signs = np.where(v[idx, np.arange(k)] < 0, -1.0, 1.0)
    return v * signs, s


@pytest.fixture(scope="module")
def mesh_flat():
    return M.create_mesh(data=8, feat=1)


class TestLocalKernels:
    def test_qr_r_sufficient_statistic(self, rng):
        x = rng.normal(size=(128, 16))
        r = np.asarray(L.qr_r(jnp.asarray(x)))
        assert r.shape == (16, 16)
        np.testing.assert_allclose(r.T @ r, x.T @ x, rtol=1e-10, atol=1e-10)

    def test_qr_r_short_block_padded(self, rng):
        x = rng.normal(size=(5, 16))  # fewer rows than features
        r = np.asarray(L.qr_r(jnp.asarray(x)))
        assert r.shape == (16, 16)
        np.testing.assert_allclose(r.T @ r, x.T @ x, rtol=1e-9, atol=1e-10)

    def test_combine_r_associative_semigroup(self, rng):
        a, b, c = (rng.normal(size=(64, 8)) for _ in range(3))
        ra, rb, rc = (L.qr_r(jnp.asarray(m)) for m in (a, b, c))
        left = L.combine_r(L.combine_r(ra, rb), rc)
        right = L.combine_r(ra, L.combine_r(rb, rc))
        full = np.vstack([a, b, c])
        for r in (left, right):
            np.testing.assert_allclose(
                np.asarray(r).T @ np.asarray(r), full.T @ full, rtol=1e-9, atol=1e-9
            )

    def test_local_svd_fit_matches_oracle(self, rng):
        x = rng.normal(size=(300, 12))
        pc, ev = L.pca_fit_local_svd(jnp.asarray(x), 4)
        v, s = _oracle_components(x, 4)
        np.testing.assert_allclose(np.asarray(pc), v, atol=1e-8)
        np.testing.assert_allclose(np.asarray(ev), (s / s.sum())[:4], atol=1e-10)

    def test_local_svd_fit_centered(self, rng):
        x = rng.normal(size=(300, 12)) + 7.0  # big offset: centering matters
        pc, ev = L.pca_fit_local_svd(jnp.asarray(x), 3, mean_centering=True)
        v, s = _oracle_components(x, 3, center=True)
        np.testing.assert_allclose(np.asarray(pc), v, atol=1e-8)
        np.testing.assert_allclose(np.asarray(ev), (s / s.sum())[:3], atol=1e-10)


class TestDistributedTSQR:
    def test_butterfly_r_matches_gram(self, mesh_flat, rng):
        x = rng.normal(size=(256, 24))
        xs = jax.device_put(x, M.data_sharding(mesh_flat))
        r = np.asarray(T.tsqr_r(xs, mesh_flat))
        assert r.shape == (24, 24)
        np.testing.assert_allclose(r.T @ r, x.T @ x, rtol=1e-9, atol=1e-9)

    def test_non_power_of_two_gather_path(self, rng):
        mesh = M.create_mesh(data=6, feat=1, devices=jax.devices()[:6])
        x = rng.normal(size=(240, 16))
        xs = jax.device_put(x, M.data_sharding(mesh))
        r = np.asarray(T.tsqr_r(xs, mesh))
        np.testing.assert_allclose(r.T @ r, x.T @ x, rtol=1e-9, atol=1e-9)

    def test_distributed_fit_matches_local(self, mesh_flat, rng):
        x = rng.normal(size=(512, 20))
        xs = jax.device_put(x, M.data_sharding(mesh_flat))
        pc_d, ev_d = T.distributed_pca_fit_svd(xs, 5, mesh_flat)
        pc_l, ev_l = L.pca_fit_local_svd(jnp.asarray(x), 5)
        np.testing.assert_allclose(np.asarray(pc_d), np.asarray(pc_l), atol=1e-8)
        np.testing.assert_allclose(np.asarray(ev_d), np.asarray(ev_l), atol=1e-10)

    def test_distributed_fit_centered(self, mesh_flat, rng):
        x = rng.normal(size=(512, 20)) + 3.0
        xs = jax.device_put(x, M.data_sharding(mesh_flat))
        pc_d, ev_d = T.distributed_pca_fit_svd(
            xs, 4, mesh_flat, mean_centering=True
        )
        v, s = _oracle_components(x, 4, center=True)
        np.testing.assert_allclose(np.asarray(pc_d), v, atol=1e-7)
        np.testing.assert_allclose(np.asarray(ev_d), (s / s.sum())[:4], atol=1e-9)

    def test_jitted_entry(self, mesh_flat, rng):
        x = rng.normal(size=(256, 16))
        xs = jax.device_put(x, M.data_sharding(mesh_flat))
        fit = T.make_distributed_fit_svd(mesh_flat, 3)
        pc, ev = fit(xs)
        v, _ = _oracle_components(x, 3)
        np.testing.assert_allclose(np.asarray(pc), v, atol=1e-7)


class TestEstimatorSolverSVD:
    def test_multi_partition_fit(self, rng):
        x = rng.normal(size=(400, 10))
        model = (
            PCA()
            .setInputCol("features")
            .setK(3)
            .setSolver("svd")
            .fit(x, num_partitions=3)
        )
        v, s = _oracle_components(x, 3)
        np.testing.assert_allclose(model.pc, v, atol=1e-7)
        np.testing.assert_allclose(
            model.explainedVariance, (s / s.sum())[:3], atol=1e-9
        )

    def test_matches_full_solver(self, rng):
        x = rng.normal(size=(300, 8))
        kw = dict(num_partitions=2)
        m_svd = PCA().setInputCol("f").setK(4).setSolver("svd").fit(x, **kw)
        m_full = PCA().setInputCol("f").setK(4).setSolver("full").fit(x, **kw)
        np.testing.assert_allclose(m_svd.pc, m_full.pc, atol=1e-6)
        np.testing.assert_allclose(
            m_svd.explainedVariance, m_full.explainedVariance, atol=1e-8
        )

    def test_centered_fit(self, rng):
        x = rng.normal(size=(300, 8)) + 5.0
        model = (
            PCA()
            .setInputCol("f")
            .setK(2)
            .setSolver("svd")
            .setMeanCentering(True)
            .fit(x, num_partitions=4)
        )
        v, _ = _oracle_components(x, 2, center=True)
        np.testing.assert_allclose(model.pc, v, atol=1e-7)

    def test_bad_solver_rejected(self):
        with pytest.raises(ValueError):
            PCA().setSolver("qr")


class TestConditioning:
    def test_svd_beats_gram_on_ill_conditioned(self, rng):
        """The headline numerical property: on a matrix with cond(X) ~ 1e6,
        the Gram route works at cond ~ 1e12 — at the edge of f64 and far
        beyond f32 — while TSQR works at 1e6. Verify the direct path stays
        accurate in the regime where squaring hurts."""
        n = 16
        u, _ = np.linalg.qr(rng.normal(size=(512, n)))
        v, _ = np.linalg.qr(rng.normal(size=(n, n)))
        s = np.logspace(0, -6, n)  # cond = 1e6
        x = (u * s) @ v.T
        pc, _ = L.pca_fit_local_svd(jnp.asarray(x), n)
        v_o, _ = _oracle_components(x, n)
        # every component recovered, including the tiny-σ tail
        cos = np.abs(np.sum(np.asarray(pc) * v_o, axis=0))
        assert cos.min() > 0.99999
