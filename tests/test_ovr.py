"""OneVsRest tests — multiclass via binary margins, sklearn differential."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import (
    GBTClassifier,
    LinearSVC,
    LogisticRegression,
    OneVsRest,
    OneVsRestModel,
)


@pytest.fixture(scope="module")
def multiclass():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4, size=(4, 6))
    x = np.concatenate(
        [c + rng.normal(size=(200, 6)) for c in centers]
    )
    y = np.repeat(np.arange(4.0), 200)
    perm = rng.permutation(len(x))
    return x[perm], y[perm]


def test_ovr_svc_matches_sklearn_quality(multiclass):
    sk_svm = pytest.importorskip("sklearn.svm")
    from sklearn.multiclass import OneVsRestClassifier

    x, y = multiclass
    ours = (
        OneVsRest(classifier=LinearSVC().setRegParam(0.01).setMaxIter(50))
        .fit((x, y))
    )
    acc = (ours._predict_matrix(x) == y).mean()
    sk = OneVsRestClassifier(
        sk_svm.LinearSVC(C=1.0 / (0.01 * len(x)), max_iter=5000)
    ).fit(x, y)
    assert acc >= sk.score(x, y) - 0.02, acc


def test_ovr_composes_with_gbt_and_logreg(multiclass):
    x, y = multiclass
    for base in (
        GBTClassifier().setMaxIter(10).setMaxDepth(3),
        LogisticRegression().setRegParam(0.01),
    ):
        m = OneVsRest(classifier=base).fit((x, y))
        assert m.numClasses == 4
        acc = (m._predict_matrix(x) == y).mean()
        assert acc > 0.9, (type(base).__name__, acc)


def test_ovr_binary_logreg_scores_are_probabilities(multiclass):
    """The binary LogisticRegression surface routes through
    predict_proba_matrix — exercised explicitly because OneVsRest trains
    each sub-model as binary even for multi-class input."""
    x, y = multiclass
    m = OneVsRest(
        classifier=LogisticRegression().setRegParam(0.05)
    ).fit((x, y))
    from spark_rapids_ml_tpu.models.ovr import _positive_score

    s = _positive_score(m.models[0], x[:10])
    assert np.all((s >= 0) & (s <= 1))


def test_ovr_transform_and_persistence(tmp_path, multiclass):
    pd = pytest.importorskip("pandas")
    x, y = multiclass
    m = OneVsRest(
        classifier=LinearSVC().setRegParam(0.01)
    ).fit(pd.DataFrame({"features": list(x), "label": y}))
    out = m.transform(pd.DataFrame({"features": list(x[:50])}))
    assert "prediction" in out.columns
    path = str(tmp_path / "ovr")
    m.save(path)
    loaded = OneVsRestModel.load(path)
    assert loaded.numClasses == 4
    np.testing.assert_array_equal(
        loaded._predict_matrix(x[:100]), m._predict_matrix(x[:100])
    )


def test_ovr_validation(multiclass):
    x, y = multiclass
    with pytest.raises(ValueError, match="setClassifier"):
        OneVsRest().fit((x, y))
    with pytest.raises(ValueError, match="integer class labels"):
        OneVsRest(classifier=LinearSVC()).fit((x, y + 0.5))


def test_ovr_inside_pipeline_persistence(tmp_path, multiclass):
    """The composite-load delegation (models/base.py): a PipelineModel
    holding a fitted OneVsRestModel must round-trip — the generic stage
    loader used to return an EMPTY OVR model."""
    from spark_rapids_ml_tpu.models.pipeline import Pipeline, PipelineModel
    from spark_rapids_ml_tpu.models.scaler import StandardScaler

    x, y = multiclass
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({"features": list(x), "label": y})
    pipe = Pipeline(
        stages=[
            StandardScaler().setInputCol("features").setOutputCol("scaled"),
            OneVsRest(
                classifier=LinearSVC().setRegParam(0.01)
            ).setFeaturesCol("scaled"),
        ]
    )
    model = pipe.fit(df)
    path = str(tmp_path / "pipe_ovr")
    model.save(path)
    loaded = PipelineModel.load(path)
    ovr = loaded.stages[-1]
    assert isinstance(ovr, OneVsRestModel) and ovr.numClasses == 4
    out0 = model.transform(df)["prediction"].to_numpy()
    out1 = loaded.transform(df)["prediction"].to_numpy()
    np.testing.assert_array_equal(out0, out1)


def test_ovr_estimator_persists_classifier(tmp_path):
    est = OneVsRest(classifier=LinearSVC().setRegParam(0.07))
    path = str(tmp_path / "ovr_est")
    est.save(path)
    loaded = OneVsRest.load(path)
    assert isinstance(loaded.classifier, LinearSVC)
    assert loaded.classifier.getRegParam() == 0.07
    with pytest.raises(ValueError, match="no classifier"):
        OneVsRest().save(str(tmp_path / "empty"))
