"""Sharded KMeans / scaler-moments mesh tests on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.ops import kmeans as KM
from spark_rapids_ml_tpu.parallel import gram as G
from spark_rapids_ml_tpu.parallel import kmeans as PK
from spark_rapids_ml_tpu.parallel import mesh as M


@pytest.fixture(scope="module")
def mesh():
    return M.create_mesh(data=8, feat=1)


class TestShardedKMeans:
    def test_stats_match_local(self, mesh, rng):
        x = rng.normal(size=(512, 8))
        c = rng.normal(size=(5, 8))
        xs = jax.device_put(jnp.asarray(x), M.data_sharding(mesh))
        got = PK.sharded_kmeans_stats(xs, jnp.asarray(c), mesh, block_rows=64)
        want = KM.kmeans_stats(jnp.asarray(x), jnp.asarray(c), block_rows=64)
        np.testing.assert_allclose(np.asarray(got.sums), np.asarray(want.sums), atol=1e-8)
        np.testing.assert_allclose(np.asarray(got.counts), np.asarray(want.counts))
        np.testing.assert_allclose(float(got.cost), float(want.cost), rtol=1e-10)

    def test_lloyd_step_converges_on_blobs(self, mesh, rng):
        centers0 = np.array([[0.0, 0.0], [8.0, 8.0]])
        x = np.concatenate(
            [c + rng.normal(scale=0.3, size=(128, 2)) for c in centers0]
        )
        rng.shuffle(x)
        step = PK.make_distributed_lloyd(mesh)
        c = jnp.asarray(centers0 + rng.normal(scale=0.5, size=(2, 2)))
        xs = jnp.asarray(x)
        for _ in range(5):
            c, cost = step(xs, c)
        got = np.asarray(c)[np.lexsort(np.asarray(c).T)]
        np.testing.assert_allclose(got, centers0, atol=0.15)
        assert float(cost) < 2 * len(x) * 0.3**2 * 2

    def test_outputs_replicated(self, mesh, rng):
        step = PK.make_distributed_lloyd(mesh)
        c, _ = step(
            jnp.asarray(rng.normal(size=(256, 4))), jnp.asarray(rng.normal(size=(3, 4)))
        )
        assert c.sharding.is_fully_replicated


class TestShardedMoments:
    def test_match_local(self, mesh, rng):
        from spark_rapids_ml_tpu.ops import scaler as S

        x = rng.normal(size=(256, 16))
        xs = jax.device_put(jnp.asarray(x), M.data_sharding(mesh))
        got = G.sharded_moment_stats(xs, mesh)
        np.testing.assert_allclose(np.asarray(got.total), x.sum(0), rtol=1e-10)
        np.testing.assert_allclose(np.asarray(got.total_sq), (x**2).sum(0), rtol=1e-10)
        assert int(got.count) == 256
        mean, std = S.finalize_moments(got)
        np.testing.assert_allclose(np.asarray(mean), x.mean(0), rtol=1e-10)
        np.testing.assert_allclose(np.asarray(std), x.std(0, ddof=1), rtol=1e-8)


class TestMeshKMeansParallelInit:
    """k-means|| oversampling as one SPMD program (r3 verdict #8)."""

    def _sharded(self, mesh, x, w):
        from jax.sharding import NamedSharding, PartitionSpec as P

        xs = jax.device_put(jnp.asarray(x), M.data_sharding(mesh))
        ws = jax.device_put(
            jnp.asarray(w), NamedSharding(mesh, P(M.DATA_AXIS))
        )
        return xs, ws

    def test_counts_partition_the_weight_and_exclude_zero_weight(self, mesh, rng):
        k = 6
        anchors = rng.normal(size=(k, 8)) * 6
        x = np.vstack(
            [anchors[i] + 0.4 * rng.normal(size=(200, 8)) for i in range(k)]
        )
        poison = np.full((48, 8), 50.0)  # w=0: must never be sampled
        xa = np.vstack([x, poison])
        w = np.concatenate([np.ones(len(x)), np.zeros(48)])
        xs, ws = self._sharded(mesh, xa, w)
        init_fn = PK.make_distributed_kmeans_parallel_init(mesh, k, init_steps=2)
        cand, counts = init_fn(xs, ws, jax.random.PRNGKey(3))
        cand, counts = np.asarray(cand), np.asarray(counts)
        # ownership counts partition the total instance weight exactly
        assert counts.sum() == len(x)
        assert (counts > 0).sum() > k  # oversampled
        assert not (np.abs(cand - 50.0) < 1.0).all(axis=1).any()

    def test_seeds_reach_driver_init_quality(self, mesh, rng):
        k = 5
        anchors = rng.normal(size=(k, 6)) * 8
        x = np.vstack(
            [anchors[i] + 0.3 * rng.normal(size=(160, 6)) for i in range(k)]
        )
        w = np.ones(len(x))
        xs, ws = self._sharded(mesh, x, w)
        init_fn = PK.make_distributed_kmeans_parallel_init(mesh, k, init_steps=2)
        cand, counts = init_fn(xs, ws, jax.random.PRNGKey(9))
        centers0 = KM.weighted_kmeans_plus_plus_init(
            jax.random.PRNGKey(10), cand, counts, k
        )
        fit = PK.make_distributed_kmeans_fit(mesh, max_iter=25, tol=1e-8)
        _, cost_mesh, _ = fit(xs, ws, centers0)
        ref0 = KM.kmeans_plus_plus_init(jax.random.PRNGKey(10), jnp.asarray(x), k)
        _, cost_ref, _ = fit(xs, ws, jnp.asarray(ref0))
        # same final-cost ballpark as a full-data k-means++ seeding
        assert float(cost_mesh) < 1.5 * float(cost_ref) + 1e-9

    def test_replicated_outputs(self, mesh, rng):
        x = rng.normal(size=(256, 4))
        xs, ws = self._sharded(mesh, x, np.ones(256))
        init_fn = PK.make_distributed_kmeans_parallel_init(mesh, 3, init_steps=1)
        cand, counts = init_fn(xs, ws, jax.random.PRNGKey(0))
        assert cand.sharding.is_fully_replicated
        assert counts.sharding.is_fully_replicated
