"""Factorization machines — the pairwise-interaction oracle: data whose
signal is PURE x_i·x_j products, where any linear model is at chance.
FM's own generative form is the differential (sklearn has no FM)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import FMClassificationModel, FMClassifier
from spark_rapids_ml_tpu.regression import FMRegressionModel, FMRegressor


@pytest.fixture(scope="module")
def interaction_reg():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2000, 6))
    y = 2.0 * x[:, 0] * x[:, 1] - 1.5 * x[:, 2] * x[:, 4] + 0.1 * rng.normal(
        size=2000
    )
    return x[:1500], y[:1500], x[1500:], y[1500:]


@pytest.fixture(scope="module")
def interaction_clf():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2000, 4))
    y = ((x[:, 0] * x[:, 1] + 0.5 * x[:, 2] * x[:, 3]) > 0).astype(float)
    return x[:1500], y[:1500], x[1500:], y[1500:]


def test_regressor_captures_interactions(interaction_reg):
    from spark_rapids_ml_tpu.regression import LinearRegression

    xtr, ytr, xte, yte = interaction_reg
    fm = (
        FMRegressor().setFactorSize(4).setMaxIter(800).setStepSize(0.05)
        .setSeed(2).fit((xtr, ytr))
    )
    pred = fm._predict_matrix(xte)
    r2 = 1 - ((pred - yte) ** 2).mean() / yte.var()
    assert r2 > 0.9, r2
    # the linear baseline is at chance on pure interactions
    lin = LinearRegression().fit((xtr, ytr))
    lin_r2 = 1 - ((lin._predict_matrix(xte) - yte) ** 2).mean() / yte.var()
    assert lin_r2 < 0.1, lin_r2


def test_classifier_captures_interactions(interaction_clf):
    xtr, ytr, xte, yte = interaction_clf
    fm = (
        FMClassifier().setFactorSize(4).setMaxIter(600).setStepSize(0.05)
        .setSeed(3).fit((xtr, ytr))
    )
    acc = (fm._predict_matrix(xte) == yte).mean()
    assert acc > 0.9, acc  # logistic regression caps near 0.5 here


def test_fit_linear_and_intercept_switches(interaction_reg):
    xtr, ytr, _, _ = interaction_reg
    m = (
        FMRegressor().setFitLinear(False).setFitIntercept(False)
        .setMaxIter(50).fit((xtr, ytr))
    )
    assert m.intercept == 0.0
    assert (m.linear == 0.0).all()
    assert m.factors.shape == (6, 8)


def test_columns_determinism_validation(interaction_clf):
    pd = pytest.importorskip("pandas")
    xtr, ytr, _, _ = interaction_clf
    kw = dict(maxIter=80, seed=5, stepSize=0.05)
    m1 = FMClassifier(**kw).fit((xtr, ytr))
    m2 = FMClassifier(**kw).fit((xtr, ytr))
    np.testing.assert_array_equal(m1.flatWeights, m2.flatWeights)
    out = m1.transform(pd.DataFrame({"features": list(xtr[:20])}))
    assert {"rawPrediction", "probability", "prediction"} <= set(out.columns)
    raw = np.stack(out["rawPrediction"])
    p = np.stack(out["probability"])
    np.testing.assert_allclose(p[:, 1], 1 / (1 + np.exp(-raw[:, 1])), rtol=1e-9)
    with pytest.raises(ValueError, match="binary 0/1"):
        FMClassifier().fit((xtr, np.arange(len(xtr), dtype=float)))
    with pytest.raises(ValueError, match="solver"):
        FMRegressor().setSolver("lbfgs")


def test_persistence_roundtrip(tmp_path, interaction_reg, interaction_clf):
    xtr, ytr, xte, _ = interaction_reg
    m = FMRegressor().setFactorSize(3).setMaxIter(60).fit((xtr, ytr))
    m.save(str(tmp_path / "fmr"))
    loaded = FMRegressionModel.load(str(tmp_path / "fmr"))
    assert loaded.getFactorSize() == 3 and loaded.numFeatures == 6
    np.testing.assert_allclose(
        loaded._predict_matrix(xte), m._predict_matrix(xte)
    )

    xc, yc, xq, _ = interaction_clf
    mc = FMClassifier().setMaxIter(60).fit((xc, yc))
    mc.save(str(tmp_path / "fmc"))
    lc = FMClassificationModel.load(str(tmp_path / "fmc"))
    p0, _ = mc.proba_and_predictions(xq[:40])
    p1, _ = lc.proba_and_predictions(xq[:40])
    np.testing.assert_allclose(p0, p1)
