"""UMAP tests — structure-preservation oracles.

UMAP has no unique correct output, so the oracles are the metrics the
field uses: sklearn's trustworthiness (local neighborhoods preserved) and
cluster separability in the embedding. Kernel-level pieces (calibration,
fuzzy union, ab fit) get exact differential checks against their specs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.models.umap import UMAP, UMAPModel
from spark_rapids_ml_tpu.ops import umap as UM


@pytest.fixture(scope="module")
def blobs():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=12, size=(4, 12))
    x = np.concatenate(
        [c + rng.normal(scale=0.6, size=(120, 12)) for c in centers]
    )
    labels = np.repeat(np.arange(4), 120)
    perm = rng.permutation(len(x))
    return x[perm], labels[perm]


def test_smooth_knn_calibration_solves_target():
    rng = np.random.default_rng(1)
    d = np.sort(np.abs(rng.normal(size=(50, 15))), axis=1)
    rho, sigma = UM.smooth_knn_calibration(jnp.asarray(d))
    rho, sigma = np.asarray(rho), np.asarray(sigma)
    np.testing.assert_allclose(rho, d.min(axis=1), atol=1e-12)
    mass = np.exp(
        -np.maximum(d - rho[:, None], 0.0) / sigma[:, None]
    ).sum(axis=1)
    np.testing.assert_allclose(mass, np.log2(15), rtol=1e-6)


def test_fuzzy_union_is_symmetric_probabilistic_or():
    knn_i = np.array([[1, 2], [0, 2], [0, 3], [2, 0]])
    w = np.array([[0.9, 0.5], [0.8, 0.2], [0.6, 0.7], [0.4, 0.1]])
    heads, tails, vals = UM.fuzzy_union_edges(knn_i, w)
    edges = {(h, t): v for h, t, v in zip(heads, tails, vals)}
    # (0,1): directed 0.9 and 0.8 → 0.9+0.8−0.72
    assert edges[(0, 1)] == pytest.approx(0.9 + 0.8 - 0.72)
    # (2,3): directed 0.7 and (3,2) 0.4 → 0.7+0.4−0.28
    assert edges[(2, 3)] == pytest.approx(0.82)
    # (1,2): 0.2 one-way ∪ 0 → 0.2
    assert edges[(1, 2)] == pytest.approx(0.2)
    assert all(h < t for h, t in edges)  # undirected, no self edges


def test_find_ab_params_matches_curve():
    a, b = UM.find_ab_params(1.0, 0.1)
    # umap-learn's canonical values for spread=1, min_dist=0.1
    assert a == pytest.approx(1.577, abs=0.05)
    assert b == pytest.approx(0.895, abs=0.05)


def test_fit_preserves_cluster_structure(blobs):
    from sklearn.manifold import trustworthiness

    x, labels = blobs
    model = UMAP().setNNeighbors(12).setNEpochs(200).setSeed(3).fit(x)
    emb = model.embedding_
    assert emb.shape == (len(x), 2)
    tw = trustworthiness(x, emb, n_neighbors=10)
    assert tw > 0.9, tw
    # embedded clusters stay separable: intra-cluster mean distance well
    # below inter-cluster mean distance
    intra = np.mean(
        [
            np.linalg.norm(
                emb[labels == c] - emb[labels == c].mean(0), axis=1
            ).mean()
            for c in range(4)
        ]
    )
    cmeans = np.stack([emb[labels == c].mean(0) for c in range(4)])
    inter = np.mean(
        [
            np.linalg.norm(cmeans[i] - cmeans[j])
            for i in range(4)
            for j in range(i + 1, 4)
        ]
    )
    assert inter > 3 * intra, (intra, inter)


def test_fit_deterministic_by_seed(blobs):
    x, _ = blobs
    m1 = UMAP().setNEpochs(50).setSeed(7).fit(x[:150])
    m2 = UMAP().setNEpochs(50).setSeed(7).fit(x[:150])
    np.testing.assert_allclose(m1.embedding_, m2.embedding_)


def test_transform_places_new_points_near_their_cluster(blobs):
    x, labels = blobs
    model = UMAP().setNNeighbors(12).setNEpochs(150).setSeed(5).fit(x[:400])
    emb_train = model.embedding_
    new = x[400:420]
    new_labels = labels[400:420]
    out = model._embed_matrix(new)
    # each transformed point lands nearer its own cluster's centroid than
    # any other cluster's
    train_labels = labels[:400]
    cmeans = np.stack(
        [emb_train[train_labels == c].mean(0) for c in range(4)]
    )
    d = np.linalg.norm(out[:, None, :] - cmeans[None, :, :], axis=2)
    assigned = d.argmin(1)
    assert (assigned == new_labels).mean() >= 0.9


def test_random_init_and_persistence(tmp_path, blobs):
    x, _ = blobs
    model = (
        UMAP().setInit("random").setNEpochs(50).setSeed(2).fit(x[:150])
    )
    path = str(tmp_path / "umap")
    model.save(path)
    loaded = UMAPModel.load(path)
    np.testing.assert_allclose(loaded.embedding_, model.embedding_)
    np.testing.assert_allclose(
        loaded._embed_matrix(x[150:160]), model._embed_matrix(x[150:160])
    )


def test_validation():
    x = np.random.default_rng(0).normal(size=(10, 4))
    with pytest.raises(ValueError, match="nNeighbors"):
        UMAP().setNNeighbors(15).fit(x)
    with pytest.raises(ValueError, match="init"):
        UMAP().setInit("pca")
