"""PCA estimator tests — the PCASuite.scala analog plus what it lacked.

Strategy mirror (SURVEY.md §4): a golden differential test against an
independent CPU implementation comparing |transformed values| (sign-invariant,
abs-tol 1e-5 like PCASuite.scala:80-87), multi-partition fits to force the
cross-partition reduce path (their ``sc.parallelize(data, 2)``), params
conformance, and persistence round-trips.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu import PCA, PCAModel


def _make_data(rng, rows=400, n=20):
    # correlated data so the spectrum is interesting
    base = rng.normal(size=(rows, 5))
    mix = rng.normal(size=(5, n))
    return base @ mix + 0.01 * rng.normal(size=(rows, n))


def _numpy_pca(x, k, center):
    xe = x - x.mean(axis=0) if center else x
    evals, evecs = np.linalg.eigh(xe.T @ xe)
    order = np.argsort(evals)[::-1]
    return evecs[:, order[:k]]


@pytest.fixture
def data(rng):
    return _make_data(rng)


class TestFit:
    @pytest.mark.parametrize("center", [False, True])
    @pytest.mark.parametrize("partitions", [1, 3])
    def test_differential_vs_numpy(self, data, center, partitions):
        """The PCASuite golden test: |X·PC| must match an independent CPU
        implementation to abs-tol 1e-5 regardless of partitioning."""
        k = 4
        model = (
            PCA()
            .setInputCol("features")
            .setK(k)
            .setMeanCentering(center)
            .fit(data, num_partitions=partitions)
        )
        got = model.transform(data)
        # model projects raw X (parity: reference never centers at transform)
        want = data @ _numpy_pca(data, k, center)
        np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-5)

    def test_multi_partition_equals_single(self, data):
        m1 = PCA().setInputCol("f").setK(3).fit(data, num_partitions=1)
        m4 = PCA().setInputCol("f").setK(3).fit(data, num_partitions=4)
        np.testing.assert_allclose(m1.pc, m4.pc, atol=1e-8)
        np.testing.assert_allclose(
            m1.explainedVariance, m4.explainedVariance, atol=1e-10
        )

    def test_explained_variance_reference_semantics(self, data):
        """√λ proportions over full spectrum, truncated (RapidsRowMatrix.scala:92-99)."""
        model = PCA().setInputCol("f").setK(3).fit(data)
        evals = np.linalg.eigvalsh(data.T @ data)
        s = np.sqrt(np.clip(np.sort(evals)[::-1], 0, None))
        np.testing.assert_allclose(model.explainedVariance, (s / s.sum())[:3], rtol=1e-6)

    def test_sign_flip_orientation(self, data):
        model = PCA().setInputCol("f").setK(5).fit(data)
        for j in range(5):
            col = model.pc[:, j]
            assert col[np.argmax(np.abs(col))] > 0

    def test_k_too_large_raises(self, data):
        with pytest.raises(ValueError, match="k=21"):
            PCA().setInputCol("f").setK(21).fit(data)

    def test_randomized_solver_matches_full(self, data):
        """The data has a rank-5 signal + noise, so the top-4 subspace is
        well-separated — the randomized solver must agree with the exact
        one there (sign-invariant transform comparison, PCASuite-style)."""
        k = 4
        full = PCA().setInputCol("f").setK(k).fit(data)
        rand = PCA().setInputCol("f").setK(k).setSolver("randomized").fit(data)
        np.testing.assert_allclose(
            np.abs(rand.transform(data)), np.abs(full.transform(data)), atol=1e-5
        )
        # trace-based tail estimate keeps ratios in the right ballpark
        np.testing.assert_allclose(
            rand.explainedVariance, full.explainedVariance, rtol=0.15
        )

    def test_solver_validation(self):
        with pytest.raises(ValueError, match="solver"):
            PCA().setSolver("qr")


class TestContainers:
    """The input-format surface: ArrayType-shaped columns in every container."""

    def test_pandas_roundtrip(self, data):
        df = pd.DataFrame({"features": list(data), "id": np.arange(len(data))})
        model = PCA().setInputCol("features").setOutputCol("out").setK(3).fit(df)
        out = model.transform(df)
        assert "out" in out.columns
        mat = np.stack(out["out"].to_numpy())
        assert mat.shape == (len(data), 3)
        np.testing.assert_allclose(mat, data @ model.pc, atol=1e-8)

    def test_arrow_table_fixed_size_list(self, data):
        col = pa.FixedSizeListArray.from_arrays(
            pa.array(data.reshape(-1)), data.shape[1]
        )
        table = pa.table({"features": col})
        model = PCA().setInputCol("features").setOutputCol("out").setK(3).fit(table)
        out = model.transform(table)
        assert out.column_names == ["features", "out"]
        got = np.asarray(out.column("out").chunk(0).values.to_numpy()).reshape(-1, 3)
        np.testing.assert_allclose(got, data @ model.pc, atol=1e-8)

    def test_arrow_variable_list(self, data):
        col = pa.array([list(r) for r in data])  # ListArray with uniform lengths
        table = pa.table({"features": col})
        model = PCA().setInputCol("features").setK(2).fit(table)
        assert model.pc.shape == (data.shape[1], 2)

    def test_row_fallback_matches_columnar(self, data):
        """Dual-path contract (RapidsPCA.scala:128-161): CPU per-row path and
        device columnar path must agree."""
        model = PCA().setInputCol("f").setK(3).fit(data)
        columnar_out = model.transform(data)
        row_out = np.stack(model.transform_rows(list(data)))
        np.testing.assert_allclose(row_out, columnar_out, atol=1e-8)


class TestParams:
    def test_defaults_and_fluent_setters(self):
        p = PCA().setInputCol("a").setOutputCol("b").setK(7)
        assert p.getInputCol() == "a"
        assert p.getOutputCol() == "b"
        assert p.getK() == 7
        assert p.getMeanCentering() is False  # reference observable behavior
        assert "meanCentering" in p.explainParams()

    def test_copy_preserves_uid_and_params(self):
        p = PCA().setK(5)
        q = p.copy()
        assert q.uid == p.uid and q.getK() == 5
        q._set(k=9)
        assert p.getK() == 5  # maps are independent

    def test_model_inherits_estimator_params(self, data):
        est = PCA().setInputCol("f").setOutputCol("o").setK(2)
        model = est.fit(data)
        assert model.getInputCol() == "f"
        assert model.getOutputCol() == "o"
        assert model.getK() == 2
        assert model.uid == est.uid  # copyValues keeps the uid lineage


class TestPersistence:
    def test_estimator_roundtrip(self, tmp_path):
        est = PCA().setInputCol("f").setK(5).setMeanCentering(True)
        est.save(tmp_path / "est")
        loaded = PCA.load(tmp_path / "est")
        assert isinstance(loaded, PCA)
        assert loaded.uid == est.uid
        assert loaded.getK() == 5
        assert loaded.getMeanCentering() is True

    def test_model_roundtrip(self, data, tmp_path):
        model = PCA().setInputCol("f").setOutputCol("o").setK(3).fit(data)
        model.save(tmp_path / "m")
        loaded = PCAModel.load(tmp_path / "m")
        np.testing.assert_array_equal(loaded.pc, model.pc)
        np.testing.assert_array_equal(loaded.explainedVariance, model.explainedVariance)
        assert loaded.getInputCol() == "f"
        np.testing.assert_allclose(loaded.transform(data), model.transform(data))

    def test_overwrite_guard(self, data, tmp_path):
        model = PCA().setInputCol("f").setK(2).fit(data)
        model.save(tmp_path / "m")
        with pytest.raises(FileExistsError):
            model.save(tmp_path / "m")
        model.save(tmp_path / "m", overwrite=True)
