"""The Python half of the JVM shim contract (spark_rapids_ml_tpu/jvm_bridge):
parquet handoff in → TPU fit → stock-Spark-ML-layout model out. The Scala
half (jvm/) consumes exactly this via ``PCAModel.load``.
"""

import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_ml_tpu import PCA
from spark_rapids_ml_tpu.jvm_bridge import main
from spark_rapids_ml_tpu.models.pca import PCAModel
from spark_rapids_ml_tpu.utils import persistence as P


@pytest.fixture
def x():
    return np.random.default_rng(0).normal(size=(240, 6))


def _write_parquet(path, x, col="features"):
    flat = pa.array(x.reshape(-1))
    offsets = pa.array(np.arange(0, x.size + 1, x.shape[1], dtype=np.int32))
    table = pa.table({col: pa.ListArray.from_arrays(offsets, flat)})
    path.mkdir(parents=True, exist_ok=True)
    # Spark writes a multi-part dir + _SUCCESS; mimic that shape
    pq.write_table(table.slice(0, 120), path / "part-00000.snappy.parquet")
    pq.write_table(table.slice(120), path / "part-00001.snappy.parquet")
    (path / "_SUCCESS").write_text("")


class TestJvmBridgeFitPCA:
    def test_fit_writes_stock_spark_layout(self, x, tmp_path):
        inp = tmp_path / "in"
        out = tmp_path / "model"
        _write_parquet(inp, x)
        main([
            "fit-pca", "--input", str(inp), "--output", str(out),
            "--input-col", "features", "--k", "3",
        ])
        # the Scala side's whole contract: stock Spark ML layout
        assert P.is_spark_ml_layout(str(out))
        assert (out / "metadata" / "part-00000").exists()
        assert (out / "data" / "_SUCCESS").exists()
        loaded = PCAModel.load(str(out))
        core = PCA().setInputCol("features").setK(3).fit(x)
        np.testing.assert_allclose(np.abs(loaded.pc), np.abs(core.pc), atol=1e-7)

    def test_solver_and_centering_flags(self, x, tmp_path):
        inp = tmp_path / "in"
        _write_parquet(inp, x + 3.0)
        out = tmp_path / "model"
        main([
            "fit-pca", "--input", str(inp), "--output", str(out),
            "--k", "2", "--solver", "svd", "--mean-centering",
        ])
        loaded = PCAModel.load(str(out))
        core = (
            PCA().setInputCol("features").setK(2).setSolver("svd")
            .setMeanCentering(True).fit(x + 3.0)
        )
        np.testing.assert_allclose(np.abs(loaded.pc), np.abs(core.pc), atol=1e-7)

    def test_vector_udt_parquet_input(self, x, tmp_path):
        # a parquet dir written from a Spark VectorUDT column carries the
        # sqlType struct; the bridge must accept it like the estimators do
        inp = tmp_path / "in"
        inp.mkdir()
        struct = pa.StructArray.from_arrays(
            [
                pa.array([1] * len(x), pa.int8()),
                pa.array([None] * len(x), pa.int32()),
                pa.array([None] * len(x), pa.list_(pa.int32())),
                pa.array([row.tolist() for row in x], pa.list_(pa.float64())),
            ],
            names=["type", "size", "indices", "values"],
        )
        pq.write_table(
            pa.table({"features": struct}), inp / "part-00000.parquet"
        )
        out = tmp_path / "model"
        main(["fit-pca", "--input", str(inp), "--output", str(out), "--k", "2"])
        core = PCA().setInputCol("features").setK(2).fit(x)
        np.testing.assert_allclose(
            np.abs(PCAModel.load(str(out)).pc), np.abs(core.pc), atol=1e-7
        )

    def test_missing_column_is_actionable(self, x, tmp_path):
        inp = tmp_path / "in"
        _write_parquet(inp, x, col="other")
        with pytest.raises(SystemExit, match="'features' not in"):
            main(["fit-pca", "--input", str(inp), "--output",
                  str(tmp_path / "m"), "--k", "2"])

    def test_transform_round_trip_matches_stock_projection(self, x, tmp_path):
        # VERDICT r4 Next #3: the accelerated batch transform for the JVM
        # path. fit-pca writes the stock-layout model; transform-pca must
        # project a staged dataset to within 1e-6 of the stock pcᵀ·x
        # projection, preserving every passthrough column in row order.
        inp = tmp_path / "in"
        out = tmp_path / "model"
        _write_parquet(inp, x)
        main(["fit-pca", "--input", str(inp), "--output", str(out), "--k", "3"])

        staged = tmp_path / "staged"
        staged.mkdir()
        ids = np.arange(len(x), dtype=np.int64)
        flat = pa.array(x.reshape(-1))
        offsets = pa.array(
            np.arange(0, x.size + 1, x.shape[1], dtype=np.int32)
        )
        pq.write_table(
            pa.table({
                "id": pa.array(ids),
                "features": pa.ListArray.from_arrays(offsets, flat),
            }),
            staged / "part-00000.parquet",
        )
        result = tmp_path / "result"
        main([
            "transform-pca", "--input", str(staged), "--model", str(out),
            "--output", str(result), "--input-col", "features",
            "--output-col", "pca_features", "--batch-rows", "100",
        ])
        got = pq.read_table(result)
        assert got.column_names == ["id", "features", "pca_features"]
        np.testing.assert_array_equal(got.column("id").to_numpy(), ids)
        proj = np.stack(got.column("pca_features").to_pylist())
        model = PCAModel.load(str(out))
        np.testing.assert_allclose(proj, x @ model.pc, atol=1e-6)

    def test_transform_rejects_existing_output_col(self, x, tmp_path):
        inp = tmp_path / "in"
        out = tmp_path / "model"
        _write_parquet(inp, x)
        main(["fit-pca", "--input", str(inp), "--output", str(out), "--k", "2"])
        with pytest.raises(SystemExit, match="already exists"):
            main([
                "transform-pca", "--input", str(inp), "--model", str(out),
                "--output", str(tmp_path / "r"), "--output-col", "features",
            ])

    def test_cli_subprocess_exactly_as_scala_invokes(self, x, tmp_path):
        # the Scala shim's literal invocation: python -m ... fit-pca ...
        inp = tmp_path / "in"
        out = tmp_path / "model"
        _write_parquet(inp, x)
        r = subprocess.run(
            [
                sys.executable, "-m", "spark_rapids_ml_tpu.jvm_bridge",
                "fit-pca", "--input", str(inp), "--output", str(out),
                "--input-col", "features", "--output-col", "pca_features",
                "--k", "3", "--solver", "full", "--layout", "spark",
            ],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert r.returncode == 0, r.stderr
        assert "fit-pca ok rows=240" in r.stderr
        assert P.is_spark_ml_layout(str(out))
