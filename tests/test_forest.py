"""Random-forest differential tests.

Three oracle layers (SURVEY.md §4 strategy):
1. an exact-spec NumPy mirror of the histogram tree builder — node-for-node
   equality (stats are integer-valued, so f64 arithmetic is exact and even
   argmax tie-breaks match);
2. sklearn as a QUALITY oracle — our binned forest must land within a few
   points of sklearn's exact-split forest on held-out synthetic data;
3. invariances: seed determinism, weight≡duplication, mesh≡local.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.models.forest import (
    RandomForestClassificationModel,
    RandomForestClassifier,
    RandomForestRegressor,
    bin_features,
    quantile_bin_edges,
    subset_size,
)
from spark_rapids_ml_tpu.ops import forest as FO


# ---------------------------------------------------------------------------
# exact-spec NumPy mirror (all features per node — no subset randomness)
# ---------------------------------------------------------------------------


def _imp_n(stats, impurity):
    if impurity == "variance":
        w = stats[..., 0]
        safe = np.where(w > 0, w, 1.0)
        return np.where(w > 0, np.maximum(stats[..., 2] - stats[..., 1] ** 2 / safe, 0.0), 0.0)
    n = stats.sum(-1)
    safe = np.where(n > 0, n, 1.0)
    if impurity == "gini":
        return np.where(n > 0, n - (stats * stats).sum(-1) / safe, 0.0)
    ratio = np.where(stats > 0, stats / safe[..., None], 1.0)
    return np.where(n > 0, -safe * (ratio * np.log(ratio)).sum(-1), 0.0)


def _count(stats, impurity):
    return stats[..., 0] if impurity == "variance" else stats.sum(-1)


def numpy_tree(binned, row_stats, w, *, max_depth, n_bins, min_inst, min_gain, impurity):
    rows, F = binned.shape
    S = row_stats.shape[1]
    max_nodes = 2 ** (max_depth + 1) - 1
    feature = np.full(max_nodes, -1, np.int32)
    split_bin = np.zeros(max_nodes, np.int32)
    is_leaf = np.ones(max_nodes, bool)
    leaf_stats = np.zeros((max_nodes, S))
    node = np.zeros(rows, np.int32)
    active = np.ones(rows, bool)

    for d in range(max_depth + 1):
        nodes_d = 2 ** d
        offset = nodes_d - 1
        local = np.clip(node - offset, 0, nodes_d - 1)
        wa = np.where(active, w, 0.0)
        hist = np.zeros((F, nodes_d, n_bins, S))
        for f in range(F):
            np.add.at(hist[f], (local, binned[:, f]), row_stats * wa[:, None])
        total = hist[0].sum(1)
        leaf_stats[offset : offset + nodes_d] = total
        if d == max_depth:
            break
        left = np.cumsum(hist, axis=2)
        right = total[None, :, None, :] - left
        gain = _imp_n(total, impurity)[None, :, None] - _imp_n(left, impurity) - _imp_n(right, impurity)
        n_tot = _count(total, impurity)
        ok = (
            (_count(left, impurity) >= min_inst)
            & (_count(right, impurity) >= min_inst)
            & (gain / np.where(n_tot > 0, n_tot, 1.0)[None, :, None] >= min_gain)
            & (gain > 1e-12)
            & (np.arange(n_bins)[None, None, :] < n_bins - 1)
        )
        masked = np.where(ok, gain, -np.inf)
        flat = masked.transpose(1, 0, 2).reshape(nodes_d, F * n_bins)
        best = flat.argmax(1)
        best_gain = flat[np.arange(nodes_d), best]
        bf, bb = best // n_bins, best % n_bins
        do = best_gain > -np.inf
        feature[offset : offset + nodes_d] = np.where(do, bf, -1)
        split_bin[offset : offset + nodes_d] = np.where(do, bb, 0)
        is_leaf[offset : offset + nodes_d] = ~do
        row_split = active & do[local]
        rb = binned[np.arange(rows), np.clip(bf[local], 0, F - 1)]
        node = np.where(row_split, 2 * node + 1 + (rb > bb[local]), node)
        active = active & row_split
    return feature, split_bin, is_leaf, leaf_stats


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(3000, 8))
    logits = 1.5 * x[:, 0] - 2.0 * x[:, 3] + x[:, 5] * x[:, 0]
    y = (logits + rng.normal(scale=0.5, size=3000) > 0).astype(float)
    return x[:2000], y[:2000], x[2000:], y[2000:]


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(3000, 6))
    y = np.sin(x[:, 0]) * 3 + x[:, 2] ** 2 + rng.normal(scale=0.2, size=3000)
    return x[:2000], y[:2000], x[2000:], y[2000:]


@pytest.mark.parametrize("impurity,S", [("gini", 3), ("entropy", 3), ("variance", 3)])
def test_tree_matches_numpy_oracle(impurity, S):
    rng = np.random.default_rng(7)
    rows, F, B = 600, 5, 8
    binned = rng.integers(0, B, size=(rows, F)).astype(np.int32)
    if impurity == "variance":
        yv = rng.normal(size=rows)
        row_stats = np.stack([np.ones(rows), yv, yv * yv], axis=1)
    else:
        y = rng.integers(0, 3, size=rows)
        row_stats = np.eye(3)[y]
    w = rng.poisson(1.0, size=rows).astype(float)

    got = FO.build_tree(
        jax.random.PRNGKey(0),
        jnp.asarray(binned), jnp.asarray(row_stats), jnp.asarray(w),
        jnp.asarray(2.0), jnp.asarray(0.0),
        max_depth=4, n_bins=B, k_features=F, impurity=impurity,
    )
    ref_f, ref_b, ref_l, ref_s = numpy_tree(
        binned, row_stats, w,
        max_depth=4, n_bins=B, min_inst=2.0, min_gain=0.0, impurity=impurity,
    )
    np.testing.assert_array_equal(np.asarray(got.feature), ref_f)
    np.testing.assert_array_equal(np.asarray(got.split_bin), ref_b)
    np.testing.assert_array_equal(np.asarray(got.is_leaf), ref_l)
    np.testing.assert_allclose(np.asarray(got.leaf_stats), ref_s, rtol=1e-12)


def test_classifier_quality_vs_sklearn(clf_data):
    sklearn = pytest.importorskip("sklearn.ensemble")
    xtr, ytr, xte, yte = clf_data
    model = (
        RandomForestClassifier().setNumTrees(30).setMaxDepth(7).setSeed(3)
        .fit((xtr, ytr))
    )
    ours = (model._predict_matrix(xte) == yte).mean()
    sk = sklearn.RandomForestClassifier(
        n_estimators=30, max_depth=7, random_state=3
    ).fit(xtr, ytr)
    theirs = sk.score(xte, yte)
    assert ours >= theirs - 0.04, (ours, theirs)


def test_regressor_quality_vs_sklearn(reg_data):
    sklearn = pytest.importorskip("sklearn.ensemble")
    xtr, ytr, xte, yte = reg_data
    # sklearn's regressor default is max_features=1.0 (ALL features per
    # split) where Spark's 'auto' means F/3 — compare like-for-like, and
    # give the histogram trade (global bins vs exact splits) 128 bins
    model = (
        RandomForestRegressor().setNumTrees(30).setMaxDepth(8).setSeed(3)
        .setFeatureSubsetStrategy("all").setMaxBins(128)
        .fit((xtr, ytr))
    )
    pred = model._predict_matrix(xte)
    ours = 1 - ((pred - yte) ** 2).mean() / yte.var()
    sk = sklearn.RandomForestRegressor(
        n_estimators=30, max_depth=8, random_state=3
    ).fit(xtr, ytr)
    theirs = sk.score(xte, yte)
    assert ours >= theirs - 0.03, (ours, theirs)


def test_probability_columns_and_determinism(clf_data):
    pd = pytest.importorskip("pandas")
    xtr, ytr, xte, _ = clf_data
    df = pd.DataFrame({"features": list(xtr), "label": ytr})
    m1 = RandomForestClassifier().setNumTrees(9).setSeed(5).fit(df)
    m2 = RandomForestClassifier().setNumTrees(9).setSeed(5).fit(df)
    out = m1.transform(pd.DataFrame({"features": list(xte)}))
    assert {"probability", "rawPrediction", "prediction"} <= set(out.columns)
    p = np.stack(out["probability"])
    assert np.allclose(p.sum(1), 1.0)
    raw = np.stack(out["rawPrediction"])
    np.testing.assert_allclose(raw, p * 9, rtol=1e-12)
    np.testing.assert_array_equal(
        m1._predict_matrix(xte), m2._predict_matrix(xte)
    )
    m3 = RandomForestClassifier().setNumTrees(9).setSeed(6).fit(df)
    assert not np.array_equal(
        np.asarray(m1.trees.feature), np.asarray(m3.trees.feature)
    )


def test_weight_equals_duplication():
    """Kernel invariant: doubling a row's weight builds the identical tree
    as physically duplicating the row (same binning by construction)."""
    rng = np.random.default_rng(11)
    rows, F, B = 300, 4, 8
    binned = rng.integers(0, B, size=(rows, F)).astype(np.int32)
    y = rng.integers(0, 2, size=rows)
    row_stats = np.eye(2)[y]
    dup_idx = np.arange(0, rows, 3)
    w = np.ones(rows)
    w[dup_idx] = 2.0

    static = dict(max_depth=4, n_bins=B, k_features=F, impurity="gini")
    key = jax.random.PRNGKey(0)
    t_w = FO.build_tree(
        key, jnp.asarray(binned), jnp.asarray(row_stats), jnp.asarray(w),
        jnp.asarray(1.0), jnp.asarray(0.0), **static,
    )
    b_dup = np.concatenate([binned, binned[dup_idx]])
    s_dup = np.concatenate([row_stats, row_stats[dup_idx]])
    t_d = FO.build_tree(
        key, jnp.asarray(b_dup), jnp.asarray(s_dup),
        jnp.asarray(np.ones(len(b_dup))),
        jnp.asarray(1.0), jnp.asarray(0.0), **static,
    )
    np.testing.assert_array_equal(np.asarray(t_w.feature), np.asarray(t_d.feature))
    np.testing.assert_array_equal(np.asarray(t_w.split_bin), np.asarray(t_d.split_bin))
    np.testing.assert_allclose(
        np.asarray(t_w.leaf_stats), np.asarray(t_d.leaf_stats), rtol=1e-12
    )


def test_min_info_gain_and_depth_zero(clf_data):
    xtr, ytr, _, _ = clf_data
    stump = (
        RandomForestClassifier().setNumTrees(3).setMaxDepth(0)
        .setBootstrap(False)  # exact prior needs every tree on all rows
        .fit((xtr, ytr))
    )
    assert np.all(np.asarray(stump.trees.is_leaf[:, 0]))
    prior = ytr.mean()
    p, _ = stump.proba_and_predictions(xtr[:5])
    np.testing.assert_allclose(p[:, 1], prior, rtol=1e-6)

    huge_gain = (
        RandomForestClassifier().setNumTrees(3).setMinInfoGain(10.0)
        .fit((xtr, ytr))
    )
    assert np.all(np.asarray(huge_gain.trees.is_leaf[:, 0]))


def test_pure_labels_single_leaf():
    x = np.random.default_rng(2).normal(size=(100, 3))
    y = np.ones(100)
    m = RandomForestClassifier().setNumTrees(2).fit((x, y))
    assert np.all(np.asarray(m.trees.is_leaf[:, 0]))


def test_persistence_roundtrip(tmp_path, clf_data, reg_data):
    xtr, ytr, xte, _ = clf_data
    m = RandomForestClassifier().setNumTrees(5).setMaxDepth(4).fit((xtr, ytr))
    path = str(tmp_path / "rfc")
    m.save(path)
    loaded = RandomForestClassificationModel.load(path)
    assert loaded.numClasses == 2
    np.testing.assert_array_equal(
        loaded._predict_matrix(xte), m._predict_matrix(xte)
    )
    p0, _ = m.proba_and_predictions(xte)
    p1, _ = loaded.proba_and_predictions(xte)
    np.testing.assert_allclose(p0, p1)

    xr, yr, xq, _ = reg_data
    mr = RandomForestRegressor().setNumTrees(4).fit((xr, yr))
    rpath = str(tmp_path / "rfr")
    mr.save(rpath)
    from spark_rapids_ml_tpu.models.forest import RandomForestRegressionModel

    lr = RandomForestRegressionModel.load(rpath)
    np.testing.assert_allclose(lr._predict_matrix(xq), mr._predict_matrix(xq))


def test_feature_importances_identify_signal(clf_data):
    """Impurity importances concentrate on the informative features and
    correlate with sklearn's (same weighted-impurity-decrease family)."""
    sklearn = pytest.importorskip("sklearn.ensemble")
    xtr, ytr, _, _ = clf_data
    m = (
        RandomForestClassifier().setNumTrees(20).setMaxDepth(6).setSeed(1)
        .fit((xtr, ytr))
    )
    imp = m.featureImportances
    assert imp.shape == (xtr.shape[1],)
    np.testing.assert_allclose(imp.sum(), 1.0, rtol=1e-9)
    # the generative model uses features 0, 3, 5 — they must dominate
    top3 = set(np.argsort(imp)[-3:])
    assert top3 == {0, 3, 5}, (top3, imp)
    sk = sklearn.RandomForestClassifier(
        n_estimators=20, max_depth=6, random_state=1
    ).fit(xtr, ytr)
    corr = np.corrcoef(imp, sk.feature_importances_)[0, 1]
    assert corr > 0.9, (corr, imp, sk.feature_importances_)


def test_feature_importances_survive_persistence(tmp_path, clf_data):
    xtr, ytr, _, _ = clf_data
    m = RandomForestClassifier().setNumTrees(4).setMaxDepth(3).fit((xtr, ytr))
    path = str(tmp_path / "rf_imp")
    m.save(path)
    loaded = RandomForestClassificationModel.load(path)
    np.testing.assert_allclose(
        loaded.featureImportances, m.featureImportances, rtol=1e-12
    )


def test_subset_size_strategies():
    assert subset_size("auto", 100, classification=True) == 10
    assert subset_size("auto", 99, classification=False) == 33
    assert subset_size("all", 7, classification=True) == 7
    assert subset_size("log2", 64, classification=True) == 6
    assert subset_size("0.5", 10, classification=True) == 5
    assert subset_size("0.15", 10, classification=True) == 2  # Spark ceils
    assert subset_size("4", 10, classification=True) == 4
    # Spark ceils the named strategies too (RandomForestParams):
    # ceil(√10)=4 not 3, ceil(log₂10)=4 not 3, ceil(10/3)=4 not 3
    assert subset_size("sqrt", 10, classification=True) == 4
    assert subset_size("log2", 10, classification=True) == 4
    assert subset_size("onethird", 10, classification=False) == 4
    assert subset_size("auto", 10, classification=True) == 4
    with pytest.raises(ValueError):
        subset_size("bogus", 10, classification=True)


def test_num_features_and_no_bootstrap_subsampling(clf_data):
    xtr, ytr, _, _ = clf_data
    m = RandomForestClassifier().setNumTrees(2).setMaxDepth(2).fit((xtr, ytr))
    assert m.numFeatures == xtr.shape[1]
    # numFeatures survives persistence even for all-stump forests
    stump = RandomForestClassifier().setNumTrees(1).fit((xtr[:50], np.ones(50)))
    assert stump.numFeatures == xtr.shape[1]

    # bootstrap=False + subsamplingRate<1 = Bernoulli without-replacement
    # sampling (Spark BaggedPoint): trees must differ
    m2 = (
        RandomForestClassifier().setNumTrees(2).setBootstrap(False)
        .setSubsamplingRate(0.5).setFeatureSubsetStrategy("all").setSeed(1)
        .fit((xtr, ytr))
    )
    t = np.asarray(m2.trees.feature)
    assert not np.array_equal(t[0], t[1])


def test_sharded_forest_matches_local():
    from spark_rapids_ml_tpu.parallel.mesh import create_mesh
    from spark_rapids_ml_tpu.parallel.forest import make_sharded_forest

    rng = np.random.default_rng(13)
    ndev = len(jax.devices())
    rows = 1000
    per = -(-rows // ndev)
    F, B, T = 6, 16, 4
    x = rng.normal(size=(rows, F))
    y = rng.integers(0, 2, size=rows)
    edges = quantile_bin_edges(x, B, 0)
    binned = np.zeros((per * ndev, F), np.int32)
    binned[:rows] = bin_features(x, edges)
    row_stats = np.zeros((per * ndev, 2))
    row_stats[:rows] = np.eye(2)[y]
    w = np.zeros((T, per * ndev))
    w[:, :rows] = rng.poisson(1.0, size=(T, rows))
    keys = jax.random.split(jax.random.PRNGKey(0), T)

    static = dict(max_depth=4, n_bins=B, k_features=F, impurity="gini")
    local = FO.build_forest(
        keys, jnp.asarray(binned), jnp.asarray(row_stats), jnp.asarray(w),
        jnp.asarray(1.0), jnp.asarray(0.0), **static,
    )
    run = make_sharded_forest(create_mesh(data=ndev), **static)
    sharded = run(
        keys, jnp.asarray(binned), jnp.asarray(row_stats), jnp.asarray(w),
        jnp.asarray(1.0), jnp.asarray(0.0),
    )
    for a, b in zip(local, sharded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_decision_tree_matches_sklearn_quality(clf_data):
    """A single deterministic CART: close to sklearn's DecisionTree and
    exactly reproducible (no bootstrap, all features)."""
    sk_tree = pytest.importorskip("sklearn.tree")
    from spark_rapids_ml_tpu.classification import DecisionTreeClassifier
    from spark_rapids_ml_tpu.regression import DecisionTreeRegressor

    xtr, ytr, xte, yte = clf_data
    m = DecisionTreeClassifier().setMaxDepth(6).setMaxBins(64).fit((xtr, ytr))
    assert m.trees.feature.shape[0] == 1  # a forest of one
    ours = (m._predict_matrix(xte) == yte).mean()
    sk = sk_tree.DecisionTreeClassifier(max_depth=6, random_state=0).fit(xtr, ytr)
    assert ours >= sk.score(xte, yte) - 0.05, (ours, sk.score(xte, yte))
    assert 1 <= m.depth <= 6
    # deterministic: two fits agree exactly
    m2 = DecisionTreeClassifier().setMaxDepth(6).setMaxBins(64).fit((xtr, ytr))
    np.testing.assert_array_equal(
        np.asarray(m.trees.feature), np.asarray(m2.trees.feature)
    )
    with pytest.raises(AttributeError, match="exactly one tree"):
        DecisionTreeClassifier().setNumTrees(5)

    reg = DecisionTreeRegressor().setMaxDepth(5).fit((xtr, xtr[:, 0] * 2))
    pred = reg._predict_matrix(xte)
    r2 = 1 - ((pred - xte[:, 0] * 2) ** 2).mean() / (xte[:, 0] * 2).var()
    assert r2 > 0.85, r2


def test_decision_tree_persistence(tmp_path, clf_data):
    from spark_rapids_ml_tpu.classification import (
        DecisionTreeClassificationModel,
        DecisionTreeClassifier,
    )

    xtr, ytr, xte, _ = clf_data
    m = DecisionTreeClassifier().setMaxDepth(4).fit((xtr, ytr))
    path = str(tmp_path / "dt")
    m.save(path)
    loaded = DecisionTreeClassificationModel.load(path)
    assert isinstance(loaded, DecisionTreeClassificationModel)
    np.testing.assert_array_equal(
        loaded._predict_matrix(xte), m._predict_matrix(xte)
    )
    assert loaded.depth == m.depth


def test_decision_tree_load_rejects_forest_saves(tmp_path, clf_data):
    """The richer-subclass upgrade rule must not let a 5-tree forest pose
    as a decision tree."""
    from spark_rapids_ml_tpu.classification import (
        DecisionTreeClassificationModel,
    )

    xtr, ytr, _, _ = clf_data
    rf = RandomForestClassifier().setNumTrees(5).setMaxDepth(2).fit((xtr, ytr))
    path = str(tmp_path / "rf5")
    rf.save(path)
    with pytest.raises(TypeError, match="5 trees"):
        DecisionTreeClassificationModel.load(path)
