"""DBSCAN differential tests — NumPy BFS oracle.

The oracle replicates the kernel's deterministic spec exactly (cluster =
connected component of the core graph, id by smallest member core index
relabeled ascending; border → smallest core-neighbor cluster; noise −1),
so label equality is exact — stronger than a partition-equivalence check.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.models.dbscan import DBSCAN
from spark_rapids_ml_tpu.ops import dbscan as DB


def _oracle(x, eps, min_samples, w=None):
    n = len(x)
    w = np.ones(n) if w is None else np.asarray(w, float)
    d = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    nbr = d <= eps * eps
    # sklearn sample_weight: weights gate CORE status only — a zero-weight
    # point is core when its neighbors' mass suffices, and still gets labels
    core = nbr @ w >= min_samples
    labels = np.full(n, -1, dtype=np.int64)
    comp_min = {}
    for i in range(n):
        if core[i] and labels[i] < 0:
            stack, members = [i], []
            labels[i] = i
            while stack:
                j = stack.pop()
                members.append(j)
                for m in np.flatnonzero(nbr[j] & core):
                    if labels[m] < 0:
                        labels[m] = i
                        stack.append(m)
            comp_min[i] = min(members)
    for seed, mn in comp_min.items():
        labels[labels == seed] = mn
    # border: smallest core-neighbor component id
    for i in range(n):
        if not core[i]:
            cands = labels[np.flatnonzero(nbr[i] & core)]
            labels[i] = cands.min() if len(cands) else -1
    ids = np.unique(labels[labels >= 0])
    remap = {v: k for k, v in enumerate(ids)}
    return np.array([remap.get(v, -1) for v in labels], dtype=np.int32)


def _blobs(seed=0, n_out=25):
    rng = np.random.default_rng(seed)
    blobs = [
        rng.normal(loc, 0.25, size=(60, 3))
        for loc in ([0, 0, 0], [5, 5, 5], [-5, 5, 0])
    ]
    outliers = rng.uniform(-10, 10, size=(n_out, 3))
    x = np.concatenate(blobs + [outliers])
    return x[rng.permutation(len(x))]


def test_blobs_match_oracle():
    x = _blobs()
    got = DBSCAN().setEps(1.0).setMinSamples(5).fit().clusterLabels(x)
    np.testing.assert_array_equal(got, _oracle(x, 1.0, 5))
    assert len(np.unique(got[got >= 0])) == 3


def test_chain_cluster_long_diameter():
    """A 400-point line spaced under eps: one cluster, graph diameter 399 —
    the pointer-jumping shortcut must converge it (plain propagation would
    need 399 sweeps; the test would time out without the jumps)."""
    x = np.stack([np.arange(400) * 0.5, np.zeros(400)], axis=1)
    got = DBSCAN().setEps(0.6).setMinSamples(2).fit().clusterLabels(x)
    assert np.all(got == 0)


def test_weighted_core_points():
    """A weight-5 point makes its sparse neighborhood core (sklearn
    sample_weight semantics)."""
    pd = pytest.importorskip("pandas")
    x = np.array([[0.0, 0.0], [0.4, 0.0], [10.0, 10.0]])
    w = np.array([5.0, 1.0, 1.0])
    df = pd.DataFrame({"features": list(x), "w": w})
    model = (
        DBSCAN().setInputCol("features").setWeightCol("w")
        .setEps(0.5).setMinSamples(5).fit()
    )
    got = model.clusterLabels(df)
    np.testing.assert_array_equal(got, _oracle(x, 0.5, 5, w))
    assert got[0] == 0 and got[1] == 0 and got[2] == -1


def test_zero_weight_point_still_labeled():
    """Weights gate core status only: a zero-weight point inside a cluster
    is labeled border, not noise — and contributes nothing to core mass."""
    pd = pytest.importorskip("pandas")
    rng = np.random.default_rng(2)
    blob = rng.normal(0, 0.2, size=(20, 2))
    x = np.concatenate([blob, [[0.05, 0.0]], [[9.0, 9.0]]])
    w = np.ones(len(x))
    w[20] = 0.0  # zero-weight point sitting inside the blob
    df = pd.DataFrame({"features": list(x), "w": w})
    got = (
        DBSCAN().setInputCol("features").setWeightCol("w")
        .setEps(0.5).setMinSamples(5).fit().clusterLabels(df)
    )
    np.testing.assert_array_equal(got, _oracle(x, 0.5, 5, w))
    assert got[20] == 0  # labeled, despite zero weight
    assert got[21] == -1


def test_block_rows_invariance():
    x = _blobs(seed=3)
    ones = jnp.asarray(np.ones(len(x)))
    valid = jnp.asarray(np.ones(len(x), bool))
    ref = np.asarray(
        DB.dbscan_labels(jnp.asarray(x), ones, valid, jnp.asarray(1.0), jnp.asarray(5.0))
    )
    small = np.asarray(
        DB.dbscan_labels(
            jnp.asarray(x), ones, valid,
            jnp.asarray(1.0), jnp.asarray(5.0), block_rows=17,
        )
    )
    np.testing.assert_array_equal(ref, small)


def test_sqeuclidean_metric():
    """eps=0.7 euclidean ≡ eps=0.49 sqeuclidean — values chosen so a broken
    eps² branch cannot pass by coincidence (0.7² ≠ 0.7)."""
    x = _blobs(seed=5)
    e = DBSCAN().setEps(0.7).setMinSamples(5).fit().clusterLabels(x)
    sq = (
        DBSCAN().setEps(0.49).setMetric("sqeuclidean").setMinSamples(5)
        .fit().clusterLabels(x)
    )
    np.testing.assert_array_equal(e, sq)
    np.testing.assert_array_equal(e, _oracle(x, 0.7, 5))


def test_transform_appends_prediction():
    pd = pytest.importorskip("pandas")
    x = _blobs(seed=7)
    df = pd.DataFrame({"features": list(x)})
    out = (
        DBSCAN().setInputCol("features").setEps(1.0).setMinSamples(5)
        .setPredictionCol("cluster").fit(df).transform(df)
    )
    np.testing.assert_array_equal(
        out["cluster"].to_numpy(), _oracle(x, 1.0, 5)
    )


def test_persistence_roundtrip(tmp_path):
    from spark_rapids_ml_tpu.models.dbscan import DBSCANModel

    x = _blobs(seed=9)
    model = DBSCAN().setEps(1.0).setMinSamples(4).fit()
    path = str(tmp_path / "db")
    model.save(path)
    loaded = DBSCANModel.load(path)
    assert loaded.getEps() == 1.0 and loaded.getMinSamples() == 4.0
    np.testing.assert_array_equal(loaded.clusterLabels(x), model.clusterLabels(x))


def test_sharded_matches_local():
    import jax
    from spark_rapids_ml_tpu.parallel.mesh import create_mesh
    from spark_rapids_ml_tpu.parallel.dbscan import make_sharded_dbscan

    x = _blobs(seed=11)
    ndev = len(jax.devices())
    per = -(-len(x) // ndev)
    padded = np.zeros((per * ndev, x.shape[1]))
    padded[: len(x)] = x
    w = np.zeros(per * ndev)
    w[: len(x)] = 1.0

    valid = w > 0

    mesh = create_mesh(data=ndev)
    run = make_sharded_dbscan(mesh)
    got = np.asarray(
        run(
            jnp.asarray(padded), jnp.asarray(w), jnp.asarray(valid),
            jnp.asarray(1.0), jnp.asarray(5.0),
        )
    )[: len(x)]
    ref = np.asarray(
        DB.dbscan_labels(
            jnp.asarray(padded), jnp.asarray(w), jnp.asarray(valid),
            jnp.asarray(1.0), jnp.asarray(5.0),
        )
    )[: len(x)]
    np.testing.assert_array_equal(got, ref)
    np.testing.assert_array_equal(
        np.asarray(DBSCAN().setEps(1.0).setMinSamples(5).fit().clusterLabels(x)),
        _oracle(x, 1.0, 5),
    )
