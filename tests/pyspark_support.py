"""Shared gating helper for the real-pyspark test legs (the modules CI's
pyspark-integration matrix selects). One definition so a future change —
e.g. a version floor — edits one place."""


def have_pyspark() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except Exception:
        return False
