"""Constructor param-kwargs (pyspark.ml style) + compiled-program caches.

pyspark.ml allows ``PCA(k=3, inputCol="features")`` as sugar for the fluent
setters; every estimator here accepts the same form uniformly (the r3 verify
pass caught ``KMeans(k=3)`` raising while ``PCA(k=4)`` worked). The cache
tests pin the r3 perf fix: repeated fits must reuse one compiled executable
(maker identity), not re-trace a fresh closure per call.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    KMeans,
    LinearRegression,
    LogisticRegression,
    PCA,
    StandardScaler,
    TruncatedSVD,
)


@pytest.mark.parametrize(
    "cls,kwargs,getter,expected",
    [
        (PCA, {"k": 3, "inputCol": "f"}, "getK", 3),
        (TruncatedSVD, {"k": 5}, "getK", 5),
        (KMeans, {"k": 4, "seed": 9, "maxIter": 7}, "getK", 4),
        (LinearRegression, {"regParam": 0.5}, "getRegParam", 0.5),
        (LogisticRegression, {"maxIter": 11}, "getMaxIter", 11),
        (StandardScaler, {"withMean": True}, "getWithMean", True),
    ],
)
def test_ctor_kwargs_match_setters(cls, kwargs, getter, expected):
    est = cls(**kwargs)
    assert getattr(est, getter)() == expected
    # explicit ctor values shadow defaults exactly like setters
    for name, value in kwargs.items():
        assert est.getOrDefault(name) == value


def test_ctor_kwargs_unknown_param_rejected():
    with pytest.raises(KeyError, match="nosuch"):
        KMeans(nosuch=1)


def test_ctor_kwargs_run_setter_validation():
    # ctor kwargs must hit the SAME validation as the fluent setters
    with pytest.raises(ValueError, match="initMode"):
        KMeans(initMode="kmeans||")  # typo of k-means||
    with pytest.raises(ValueError, match="initSteps"):
        KMeans(initSteps=0)
    with pytest.raises(ValueError, match="precision"):
        TruncatedSVD(precision="double")
    from spark_rapids_ml_tpu.models.tuning import RegressionEvaluator

    with pytest.raises(ValueError, match="metricName"):
        RegressionEvaluator(metricName="rmsle")


def test_ctor_kwargs_none_means_unset():
    est = KMeans(k=None)
    assert not est.isSet("k")


def test_ctor_kwargs_fit_equivalence(rng):
    x = rng.normal(size=(200, 8))
    a = KMeans(k=3, seed=2, maxIter=5).fit(x)
    b = KMeans().setK(3).setSeed(2).setMaxIter(5).fit(x)
    np.testing.assert_allclose(
        np.asarray(a.clusterCenters), np.asarray(b.clusterCenters)
    )


# ---------------------------------------------------------------------------
# compiled-program caches
# ---------------------------------------------------------------------------


def test_maker_caches_return_same_executable():
    from spark_rapids_ml_tpu.parallel import gram as G
    from spark_rapids_ml_tpu.parallel import kmeans as PK
    from spark_rapids_ml_tpu.parallel import linear as PL
    from spark_rapids_ml_tpu.parallel import mesh as M

    mesh = M.create_mesh()
    # two create_mesh() calls produce equal/hash-equal meshes, so every
    # maker must hand back the SAME jitted callable for the same config
    mesh2 = M.create_mesh()
    assert hash(mesh) == hash(mesh2) and mesh == mesh2
    assert G.make_distributed_fit(mesh, 4) is G.make_distributed_fit(mesh2, 4)
    assert G.make_distributed_fit(mesh, 4) is not G.make_distributed_fit(mesh, 5)
    assert PK.make_distributed_lloyd(mesh) is PK.make_distributed_lloyd(mesh2)
    assert PL.make_distributed_linreg_fit(
        mesh, reg_param=0.1
    ) is PL.make_distributed_linreg_fit(mesh2, reg_param=0.1)


def test_hyperparameter_sweep_reuses_one_program(rng):
    # reg_param/max_iter/tol are traced (not static) in the jitted solver,
    # so a CV sweep over λ compiles ONE executable — the design that keeps
    # hyperparameter search cheap (models/linear.py jit wrapper comment)
    import numpy as np

    from spark_rapids_ml_tpu.models import linear as ML
    from spark_rapids_ml_tpu.models.linear import LinearRegression

    x = rng.normal(size=(200, 6))
    y = x @ np.ones(6) + rng.normal(size=200)
    before = ML._solve_from_stats._cache_size()
    for lam in (0.011, 0.052, 0.13, 0.54):
        LinearRegression(regParam=lam, elasticNetParam=1.0).fit((x, y))
    assert ML._solve_from_stats._cache_size() - before <= 1


def test_sharded_stats_program_cached(rng):
    import jax

    from spark_rapids_ml_tpu.ops import linalg as L
    from spark_rapids_ml_tpu.parallel import gram as G
    from spark_rapids_ml_tpu.parallel import mesh as M

    before = G._gram_stats_prog.cache_info().currsize
    mesh = M.create_mesh()
    x = jax.device_put(
        rng.normal(size=(64 * mesh.size, 8)), M.data_sharding(mesh)
    )
    s1 = G.sharded_gram_stats(x, mesh)
    s2 = G.sharded_gram_stats(x, M.create_mesh())
    np.testing.assert_allclose(np.asarray(s1.xtx), np.asarray(s2.xtx))
    info = G._gram_stats_prog.cache_info()
    assert info.currsize <= before + 1  # one program for both fits
    assert info.hits >= 1
    # and the program agrees with the local kernel
    np.testing.assert_allclose(
        np.asarray(s1.xtx),
        np.asarray(L.gram_stats(jax.device_get(x)).xtx),
        rtol=1e-10,
    )
