"""QuantileDiscretizer / Bucketizer — equal-frequency binning vs NumPy
quantile oracles, Spark edge semantics (top-edge inclusive, handleInvalid)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.discretizer import (
    Bucketizer,
    QuantileDiscretizer,
    QuantileDiscretizerModel,
)


class TestBucketizer:
    def test_spark_edge_semantics(self):
        b = (
            Bucketizer()
            .setInputCol("f")
            .setSplits([0.0, 1.0, 2.0])
        )
        x = np.array([[0.0, 0.5, 1.0, 1.5, 2.0]]).T
        out = b.transform(x).reshape(-1)
        # [0,1) -> 0; [1,2] -> 1 with the TOP EDGE INCLUSIVE (2.0 -> 1)
        np.testing.assert_array_equal(out, [0, 0, 1, 1, 1])

    def test_error_then_keep_on_out_of_range(self):
        x = np.array([[-1.0], [0.5], [3.0]])
        b = Bucketizer().setInputCol("f").setSplits([0.0, 1.0, 2.0])
        with pytest.raises(ValueError, match="outside"):
            b.transform(x)
        out = b.setHandleInvalid("keep").transform(x).reshape(-1)
        np.testing.assert_array_equal(out, [2, 0, 2])  # invalid bucket id 2

    def test_inf_endpoints_accept_everything(self, rng):
        x = rng.normal(size=(200, 3)) * 100
        b = (
            Bucketizer()
            .setInputCol("f")
            .setSplits([-np.inf, 0.0, np.inf])
        )
        out = b.transform(x)
        np.testing.assert_array_equal(out, (x >= 0).astype(float))

    def test_validation(self):
        with pytest.raises(ValueError, match="at least 3"):
            Bucketizer().setSplits([0.0, 1.0])
        with pytest.raises(ValueError, match="strictly increasing"):
            Bucketizer().setSplits([0.0, 0.0, 1.0])
        with pytest.raises(ValueError, match="'skip' would"):
            Bucketizer().setHandleInvalid("skip")
        with pytest.raises(ValueError, match="must be set"):
            Bucketizer().setInputCol("f").transform(np.ones((2, 2)))


class TestQuantileDiscretizer:
    def test_equal_frequency_buckets(self, rng):
        x = rng.normal(size=(20_000, 3)) * np.array([1.0, 5.0, 0.2])
        model = (
            QuantileDiscretizer()
            .setInputCol("f")
            .setNumBuckets(4)
            .fit(x, num_partitions=3)
        )
        out = model.transform(x)
        assert set(np.unique(out)) == {0.0, 1.0, 2.0, 3.0}
        # equal-frequency: each bucket holds ~25% per feature
        for j in range(3):
            frac = np.bincount(out[:, j].astype(int), minlength=4) / len(x)
            np.testing.assert_allclose(frac, 0.25, atol=0.02)

    def test_splits_match_numpy_quantiles(self, rng):
        x = rng.uniform(0.0, 10.0, size=(50_000, 2))
        model = (
            QuantileDiscretizer().setInputCol("f").setNumBuckets(5).fit(x)
        )
        want = np.quantile(x, [0.2, 0.4, 0.6, 0.8], axis=0).T
        got = model.splits[:, 1:5]
        np.testing.assert_allclose(got, want, atol=2 * 10.0 / 4096)
        assert np.isneginf(model.splits[:, 0]).all()
        assert np.isposinf(model.splits[:, -1]).all()

    def test_multi_partition_parity(self, rng):
        x = rng.normal(size=(999, 3))
        m1 = QuantileDiscretizer().setInputCol("f").setNumBuckets(3).fit(
            x, num_partitions=1
        )
        m4 = QuantileDiscretizer().setInputCol("f").setNumBuckets(3).fit(
            x, num_partitions=4
        )
        np.testing.assert_allclose(m1.splits, m4.splits, atol=1e-12)

    def test_skewed_duplicate_splits_stay_valid(self):
        # 90% of mass at one value: adjacent quantiles collapse
        x = np.concatenate([np.full(900, 5.0), np.arange(100, dtype=float)])
        x = x[:, None]
        model = (
            QuantileDiscretizer().setInputCol("f").setNumBuckets(4).fit(x)
        )
        out = model.transform(x)
        assert out.min() >= 0 and out.max() <= 3
        # every row with the modal value lands in ONE bucket
        assert len(np.unique(out[:900])) == 1

    def test_feature_count_mismatch_rejected(self, rng):
        x = rng.normal(size=(100, 3))
        model = QuantileDiscretizer().setInputCol("f").fit(x)
        with pytest.raises(ValueError, match="learned 3 features"):
            model.transform(rng.normal(size=(10, 5)))

    def test_persistence_native_roundtrip(self, rng, tmp_path):
        x = rng.normal(size=(500, 2))
        model = (
            QuantileDiscretizer().setInputCol("f").setNumBuckets(3).fit(x)
        )
        model.save(tmp_path / "qd")
        loaded = QuantileDiscretizerModel.load(tmp_path / "qd")
        np.testing.assert_array_equal(loaded.splits, model.splits)
        np.testing.assert_array_equal(
            loaded.transform(x), model.transform(x)
        )
        with pytest.raises(NotImplementedError, match="native layout"):
            model.save(tmp_path / "sp", layout="spark")

    def test_validation(self):
        with pytest.raises(ValueError, match="numBuckets"):
            QuantileDiscretizer().setNumBuckets(1)


class TestNaNHandling:
    def test_bucketizer_nan_error_and_keep(self):
        x = np.array([[0.5], [np.nan]])
        b = Bucketizer().setInputCol("f").setSplits([0.0, 1.0, 2.0])
        with pytest.raises(ValueError, match="NaN"):
            b.transform(x)
        out = b.setHandleInvalid("keep").transform(x).reshape(-1)
        np.testing.assert_array_equal(out, [0.0, 2.0])  # NaN -> invalid bucket

    def test_discretizer_rejects_nan_with_imputer_hint(self, rng):
        x = rng.normal(size=(100, 3))
        x[5, 1] = np.nan
        with pytest.raises(ValueError, match="impute first"):
            QuantileDiscretizer().setInputCol("f").fit(x)

    def test_model_transform_rejects_nan(self, rng):
        x = rng.normal(size=(100, 2))
        model = QuantileDiscretizer().setInputCol("f").setNumBuckets(4).fit(x)
        xb = x.copy()
        xb[7, 1] = np.nan
        with pytest.raises(ValueError, match="impute first"):
            model.transform(xb)
