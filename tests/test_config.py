"""Runtime config tests."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.utils.config import get_config, set_config


@pytest.fixture(autouse=True)
def restore_config():
    cfg = get_config()
    saved = cfg.__dict__.copy()
    yield
    cfg.__dict__.update(saved)


def test_defaults(monkeypatch):
    # assert built-in defaults, immune to TPU_ML_* set in the outer env
    for var in (
        "TPU_ML_MIN_BUCKET",
        "TPU_ML_MAX_WORKERS",
        "TPU_ML_TASK_RETRIES",
        "TPU_ML_DEFAULT_PRECISION",
    ):
        monkeypatch.delenv(var, raising=False)
    from spark_rapids_ml_tpu.utils.config import RuntimeConfig

    cfg = RuntimeConfig()
    assert cfg.min_bucket == 128
    assert cfg.task_retries == 3
    assert cfg.default_precision == "highest"


def test_invalid_env_rejected(monkeypatch):
    from spark_rapids_ml_tpu.utils.config import RuntimeConfig

    monkeypatch.setenv("TPU_ML_DEFAULT_PRECISION", "hi")
    with pytest.raises(ValueError, match="TPU_ML_DEFAULT_PRECISION"):
        RuntimeConfig()
    monkeypatch.delenv("TPU_ML_DEFAULT_PRECISION")
    monkeypatch.setenv("TPU_ML_MIN_BUCKET", "tiny")
    with pytest.raises(ValueError, match="TPU_ML_MIN_BUCKET"):
        RuntimeConfig()


def test_set_config_validates_values():
    with pytest.raises(ValueError):
        set_config(default_precision="hi")
    with pytest.raises(TypeError):
        set_config(min_bucket="64")


def test_set_config_overrides():
    set_config(min_bucket=32)
    assert columnar.bucket_rows(5) == 32
    set_config(min_bucket=256)
    assert columnar.bucket_rows(5) == 256


def test_unknown_key_rejected():
    with pytest.raises(KeyError):
        set_config(bogus=1)


def test_pca_precision_default_follows_config():
    set_config(default_precision="high")
    from spark_rapids_ml_tpu.models.pca import PCA

    assert PCA().getOrDefault("precision") == "high"


def test_bucket_rows_powers_of_two():
    assert columnar.bucket_rows(128) == 128
    assert columnar.bucket_rows(129) == 256
    assert columnar.bucket_rows(1) == 128
