"""Closed-loop model refresh: durable carry state, guarded hot-swap,
probation.

Covers the ISSUE-18 acceptance list: every incremental estimator's
``to_state``/``from_state`` round-trips its exact sufficient statistics so
an interrupted fold stream finalizes **bitwise** the same model; the
registry's versioned swap publishes atomically (version bump, blackout
sample, zero post-swap compiles), refuses divergent candidates at the
shadow gate, and rolls back bitwise to the HBM-retained prior; and the
:class:`~spark_rapids_ml_tpu.refresh.RefreshDaemon` drives the whole
fold → checkpoint → finalize → swap → probation loop, including the
SLO-burn rollback and the resume-from-durable-checkpoint path.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from spark_rapids_ml_tpu.models import incremental as inc
from spark_rapids_ml_tpu.refresh import RefreshDaemon
from spark_rapids_ml_tpu.serving import client as client_mod
from spark_rapids_ml_tpu.serving import registry as registry_mod
from spark_rapids_ml_tpu.serving import server as server_mod
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

BUCKETS = (8, 16, 32)


@pytest.fixture(autouse=True)
def serve_clean():
    yield
    client_mod.reset_client()
    server_mod.stop_serving(stop_monitor=False)
    registry_mod.reset_for_tests()


@pytest.fixture
def snap():
    s0 = REGISTRY.snapshot()

    class _Snap:
        @staticmethod
        def delta():
            return REGISTRY.snapshot().delta(s0)

    return _Snap


def _xy(n: int, seed: int, n_cols: int = 6):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, n_cols))
    y = x @ np.arange(1.0, n_cols + 1.0)
    return x, y


# -- durable carry state -----------------------------------------------------


ESTIMATORS = [
    pytest.param(lambda: inc.IncrementalPCA(k=3), False, id="pca"),
    pytest.param(lambda: inc.IncrementalTruncatedSVD(k=3), False, id="svd"),
    pytest.param(lambda: inc.IncrementalStandardScaler(), False, id="scaler"),
    pytest.param(lambda: inc.IncrementalLinearRegression(), True, id="linear"),
    # seedRows=16 so the first batch seeds: the checkpoint carries live
    # centers + cumulative weights, not just the pre-seed buffer
    pytest.param(
        lambda: inc.IncrementalKMeans(k=3).setSeedRows(16), False, id="kmeans"
    ),
]


def _model_arrays(model) -> list[np.ndarray]:
    """Every public array the finalized model exposes — the parity probe."""
    out = []
    for attr in (
        "components_", "components", "pc", "mean", "coefficients",
        "intercept", "clusterCenters", "scale", "std", "singularValues",
        "explainedVariance",
    ):
        v = getattr(model, attr, None)
        if v is None or callable(v) or isinstance(v, (str, bool)):
            continue
        out.append(np.asarray(v))
    assert out, f"no comparable arrays on {type(model).__name__}"
    return out


def _assert_models_bitwise(a, b):
    for va, vb in zip(_model_arrays(a), _model_arrays(b)):
        assert va.dtype == vb.dtype and np.array_equal(va, vb)


class TestStateRoundTrip:
    @pytest.mark.parametrize("make,labeled", ESTIMATORS)
    def test_resume_finalizes_bitwise(self, make, labeled):
        """partial_fit(a) → save/restore → partial_fit(b) must finalize
        bitwise-identical to the uninterrupted fold stream."""
        a_batch = _xy(64, 0) if labeled else _xy(64, 0)[0]
        b_batch = _xy(48, 1) if labeled else _xy(48, 1)[0]
        cont = make().partial_fit(a_batch)
        arrays, state = cont.to_state()
        # simulate the durable hop: round-trip through host numpy copies
        arrays = {k: np.array(v, copy=True) for k, v in arrays.items()}
        resumed = make().from_state(arrays, state)
        cont.partial_fit(b_batch)
        resumed.partial_fit(b_batch)
        _assert_models_bitwise(cont.finalize(), resumed.finalize())

    @pytest.mark.parametrize("make,labeled", ESTIMATORS)
    def test_empty_estimator_round_trips(self, make, labeled):
        arrays, state = make().to_state()
        resumed = make().from_state(arrays, state)
        batch = _xy(40, 3) if labeled else _xy(40, 3)[0]
        cont = make().partial_fit(batch)
        resumed.partial_fit(batch)
        _assert_models_bitwise(cont.finalize(), resumed.finalize())

    def test_kind_mismatch_raises(self):
        arrays, state = inc.IncrementalPCA(k=3).partial_fit(
            _xy(32, 2)[0]
        ).to_state()
        with pytest.raises(ValueError, match="state"):
            inc.IncrementalStandardScaler().from_state(arrays, state)

    def test_checkpointer_round_trip_is_durable(self, tmp_path):
        """Through the atomic TrainingCheckpointer (npz+json on disk, not
        in-memory dicts) the restored stream still finalizes bitwise."""
        from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer

        ck = TrainingCheckpointer(str(tmp_path), keep=2)
        cont = inc.IncrementalLinearRegression().partial_fit(_xy(64, 0))
        arrays, state = cont.to_state()
        ck.save(1, arrays, state)
        step, arrays2, state2 = ck.latest()
        assert step == 1
        resumed = inc.IncrementalLinearRegression().from_state(
            arrays2, state2
        )
        cont.partial_fit(_xy(48, 1))
        resumed.partial_fit(_xy(48, 1))
        _assert_models_bitwise(cont.finalize(), resumed.finalize())


# -- versioned registry swap -------------------------------------------------


def _fit_lin(n: int, seed: int):
    from spark_rapids_ml_tpu.models.linear import LinearRegression

    x, y = _xy(n, seed)
    return LinearRegression().fit((x, y))


class TestRegistrySwap:
    def test_swap_bumps_version_and_serves_candidate(self, snap):
        reg = registry_mod.get_registry()
        old = _fit_lin(128, 0)
        new = _fit_lin(128, 1)
        reg.register("lin", old, bucket_list=BUCKETS)
        x = _xy(8, 9)[0]
        out_old = reg.predict("lin", x)
        entry = reg.swap("lin", new, shadow_sample=x, tolerance=100.0)
        assert entry.version == 2
        assert reg.current_version("lin") == 2
        out_new = reg.predict("lin", x)
        assert np.array_equal(out_new, np.asarray(new.transform(x)))
        assert not np.array_equal(out_old, out_new)
        d = snap.delta()
        assert d.counter("serve.swaps") == 1
        assert d.hist("serve.swap_blackout_seconds").count == 1
        assert d.gauges[("serve.model_version", (("model", "lin"),))] == 2

    def test_swap_causes_zero_post_swap_compiles(self):
        """The swap pre-compiles the candidate over the live entry's warm
        ladder; dispatches after the publish never compile."""
        reg = registry_mod.get_registry()
        reg.register("lin", _fit_lin(128, 0), bucket_list=BUCKETS)
        reg.swap("lin", _fit_lin(128, 1), tolerance=100.0)
        s0 = REGISTRY.snapshot()
        for rows in (3, 8, 11, 16, 30):
            reg.predict("lin", _xy(rows, rows)[0])
        d = REGISTRY.snapshot().delta(s0)
        assert d.hist("compile.seconds").count == 0
        assert d.counter("serve.cold_compiles") == 0

    def test_shadow_gate_refuses_divergent_candidate(self, snap):
        reg = registry_mod.get_registry()
        old = _fit_lin(128, 0)
        reg.register("lin", old, bucket_list=BUCKETS)
        x = _xy(16, 9)[0]
        out_old = reg.predict("lin", x)
        from spark_rapids_ml_tpu.models.linear import LinearRegression

        xd, yd = _xy(128, 31)
        divergent = LinearRegression().fit((xd, -2.0 * yd))
        with pytest.raises(registry_mod.SwapRefused, match="shadow gate"):
            reg.swap("lin", divergent, shadow_sample=x, tolerance=1e-3)
        # the refusal leaves version 1 serving, bitwise untouched
        assert reg.current_version("lin") == 1
        assert np.array_equal(reg.predict("lin", x), out_old)
        d = snap.delta()
        assert d.counter("serve.swap_refused", model="lin", reason="shadow") == 1
        assert d.counter("serve.swaps") == 0

    def test_shape_mismatch_refused(self, snap):
        reg = registry_mod.get_registry()
        reg.register("lin", _fit_lin(128, 0), bucket_list=BUCKETS)
        from spark_rapids_ml_tpu.models.linear import LinearRegression

        x4, _ = _xy(128, 0, n_cols=4)
        y4 = x4 @ np.arange(1.0, 5.0)
        narrow = LinearRegression().fit((x4, y4))
        with pytest.raises(registry_mod.SwapRefused, match="n_features"):
            reg.swap("lin", narrow)
        d = snap.delta()
        assert d.counter("serve.swap_refused", model="lin", reason="shape") == 1
        assert reg.current_version("lin") == 1

    def test_rollback_restores_prior_bitwise(self, snap):
        reg = registry_mod.get_registry()
        old = _fit_lin(128, 0)
        reg.register("lin", old, bucket_list=BUCKETS)
        x = _xy(8, 9)[0]
        out_old = reg.predict("lin", x)
        reg.swap("lin", _fit_lin(128, 1), tolerance=100.0)
        prior = reg.rollback("lin")
        assert prior.version == 1
        assert reg.current_version("lin") == 1
        assert np.array_equal(reg.predict("lin", x), out_old)
        d = snap.delta()
        assert d.counter("serve.rollback") == 1
        # a second rollback has nothing retained to restore
        with pytest.raises(KeyError):
            reg.rollback("lin")

    def test_prune_prior_releases_retained_version(self):
        reg = registry_mod.get_registry()
        reg.register("lin", _fit_lin(128, 0), bucket_list=BUCKETS)
        reg.swap("lin", _fit_lin(128, 1), tolerance=100.0)
        assert reg.prior_entry("lin") is not None
        assert reg.prune_prior("lin") is True
        assert reg.prior_entry("lin") is None
        assert reg.prune_prior("lin") is False
        with pytest.raises(KeyError):
            reg.rollback("lin")

    def test_swap_of_unknown_model_is_key_error(self):
        with pytest.raises(KeyError):
            registry_mod.get_registry().swap("ghost", _fit_lin(64, 0))


# -- the refresh daemon ------------------------------------------------------


class TestRefreshDaemon:
    def test_full_lifecycle_promotes(self, tmp_path, snap):
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            checkpoint_dir=str(tmp_path), min_rows=32, shadow_rows=16,
            tolerance=100.0, probation_s=0.0,
            probation_slo="serve.latency:p99:10",
        )
        d.fold(_xy(64, 0))
        d.checkpoint()
        assert d.try_swap() == {"status": "registered", "version": 1}
        d.fold(_xy(64, 1))
        d.checkpoint()
        res = d.try_swap()
        assert res["status"] == "swapped" and res["version"] == 2
        assert res["refresh_lag_s"] >= 0.0
        assert d.in_probation
        # probation_s=0 -> the deadline has passed; next check promotes
        assert d.probation_check()["status"] == "promoted"
        assert not d.in_probation
        reg = registry_mod.get_registry()
        assert reg.current_version("lr") == 2
        assert reg.prior_entry("lr") is None
        dlt = snap.delta()
        assert dlt.counter("refresh.folds") == 2
        assert dlt.counter("refresh.rows") == 128
        assert dlt.counter("refresh.checkpoints") == 2
        assert dlt.counter("refresh.finalizes") == 2
        assert dlt.counter("serve.swaps") == 1

    def test_min_rows_floor_blocks_swap(self):
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            min_rows=100, shadow_rows=0,
        )
        d.fold(_xy(64, 0))
        res = d.try_swap()
        assert res["status"] == "waiting"
        assert res["rows_pending"] == 64

    def test_slo_burn_rolls_back_and_counts(self, snap):
        """A confirmed SLO burn during probation restores the prior
        version (bitwise) and books serve.rollback."""
        reg = registry_mod.get_registry()
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            min_rows=1, shadow_rows=0, tolerance=100.0,
            probation_s=3600.0, probation_burn=1,
            probation_slo="serve.latency:p99:0.001",
        )
        d.fold(_xy(64, 0))
        assert d.try_swap()["status"] == "registered"
        x = _xy(8, 9)[0]
        out_v1 = reg.predict("lr", x)
        d.fold(_xy(64, 1))
        assert d.try_swap()["status"] == "swapped"
        # post-swap traffic burns the probation SLO (p99 >> 1ms)
        for _ in range(8):
            REGISTRY.histogram_record("serve.latency", 0.5, model="lr")
        res = d.probation_check()
        assert res["status"] == "rolled_back"
        assert res["version"] == 1 and res["from_version"] == 2
        assert not d.in_probation
        assert reg.current_version("lr") == 1
        assert np.array_equal(reg.predict("lr", x), out_v1)
        assert snap.delta().counter("serve.rollback") == 1

    def test_healthy_probation_promotes_after_deadline(self):
        reg = registry_mod.get_registry()
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            min_rows=1, shadow_rows=0, tolerance=100.0,
            probation_s=0.0, probation_slo="serve.latency:p99:10",
        )
        d.fold(_xy(64, 0))
        d.try_swap()
        d.fold(_xy(64, 1))
        assert d.try_swap()["status"] == "swapped"
        # while in probation, try_swap defers to the probation check
        assert d.try_swap()["status"] == "promoted"
        assert reg.current_version("lr") == 2

    def test_resume_restores_pending_rows_and_finalizes_bitwise(
        self, tmp_path
    ):
        """Kill-between-folds survival: a fresh daemon resumed from the
        durable checkpoint swaps in the SAME candidate the dead one
        would have."""
        ckdir = str(tmp_path)
        d1 = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            checkpoint_dir=ckdir, min_rows=1, shadow_rows=8,
        )
        d1.fold(_xy(64, 0))
        d1.checkpoint()
        # the continuation the dead daemon never made
        oracle = inc.IncrementalLinearRegression().partial_fit(_xy(64, 0))
        oracle.partial_fit(_xy(32, 1))

        d2 = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            checkpoint_dir=ckdir, min_rows=1, shadow_rows=8,
        )
        assert d2.resume() is True
        assert d2.rows_pending == 64
        assert d2._shadow is not None and len(d2._shadow) == 8
        d2.fold(_xy(32, 1))
        _assert_models_bitwise(d2.estimator.finalize(), oracle.finalize())

    def test_resume_with_nothing_durable_is_false(self, tmp_path):
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            checkpoint_dir=str(tmp_path),
        )
        assert d.resume() is False
        assert RefreshDaemon(
            "lr2", inc.IncrementalLinearRegression(), checkpoint_dir=None
        ).resume() is False

    def test_feed_run_once_background_verbs(self, tmp_path):
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            checkpoint_dir=str(tmp_path), min_rows=1, shadow_rows=0,
            tolerance=100.0, probation_s=0.0,
            probation_slo="serve.latency:p99:10",
        )
        d.feed(_xy(32, 0))
        d.feed(_xy(32, 1))
        res = d.run_once()  # drains both, checkpoints, registers
        assert res == {"status": "registered", "version": 1}
        assert d.rows_pending == 0
        ck = d.checkpointer.latest()
        assert ck is not None and ck[2]["rows_pending"] == 64
        d.feed(_xy(32, 2))
        assert d.run_once()["status"] == "swapped"
        assert d.run_once()["status"] == "promoted"

    def test_refused_swap_keeps_pending_rows(self):
        """A shadow-gate refusal must not drop the folded deltas — the
        daemon retries after the next fold."""
        reg = registry_mod.get_registry()
        d = RefreshDaemon(
            "lr", inc.IncrementalLinearRegression(),
            min_rows=1, shadow_rows=16, tolerance=100.0,
        )
        d.fold(_xy(64, 0))
        assert d.try_swap()["status"] == "registered"
        d.tolerance = 1e-3
        xd, yd = _xy(256, 5)
        d.fold((xd, -yd))  # the delta flips the target: candidate diverges
        res = d.try_swap()
        assert res["status"] == "refused"
        assert d.rows_pending == 256
        assert reg.current_version("lr") == 1
        d.tolerance = 100.0
        assert d.try_swap()["status"] == "swapped"
