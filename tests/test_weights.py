"""Instance-weight (weightCol) support — differential vs weighted oracles.

Spark ML's weightCol contract: a non-negative per-row weight column scales
each instance's contribution to the loss. The equivalence oracle used
throughout: integer weight w ≡ replicating the row w times.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import KMeans, LinearRegression, LogisticRegression


@pytest.fixture
def xyw(rng):
    x = rng.normal(size=(300, 5))
    coef = rng.normal(size=5)
    y = x @ coef + 0.01 * rng.normal(size=300)
    w = rng.integers(1, 4, 300).astype(np.float64)
    return x, y, w


def _replicate(x, y, w):
    reps = w.astype(int)
    return np.repeat(x, reps, axis=0), np.repeat(y, reps)


class TestWeightedLinearRegression:
    def test_matches_replication_oracle(self, xyw):
        x, y, w = xyw
        m_w = LinearRegression().fit((x, y, w), num_partitions=3)
        xr, yr = _replicate(x, y, w)
        m_r = LinearRegression().fit((xr, yr), num_partitions=3)
        np.testing.assert_allclose(m_w.coefficients, m_r.coefficients, atol=1e-8)
        np.testing.assert_allclose(m_w.intercept, m_r.intercept, atol=1e-8)

    def test_unit_weights_noop(self, xyw):
        x, y, _ = xyw
        m_w = LinearRegression().fit((x, y, np.ones(len(y))))
        m_u = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m_w.coefficients, m_u.coefficients, atol=1e-12)

    def test_zero_weight_excludes_rows(self, rng):
        x = rng.normal(size=(100, 3))
        y = x @ np.ones(3)
        # poison the tail rows, then weight them out
        y2 = y.copy()
        y2[80:] += 100.0
        w = np.ones(100)
        w[80:] = 0.0
        m = LinearRegression().fit((x, y2, w))
        np.testing.assert_allclose(m.coefficients, np.ones(3), atol=1e-6)

    def test_weight_col_from_dataframe(self, xyw):
        pd = pytest.importorskip("pandas")
        x, y, w = xyw
        df = pd.DataFrame({"features": list(x), "label": y, "w": w})
        m_w = (
            LinearRegression()
            .setFeaturesCol("features")
            .setLabelCol("label")
            .setWeightCol("w")
            .fit(df, num_partitions=2)
        )
        xr, yr = _replicate(x, y, w)
        m_r = LinearRegression().fit((xr, yr))
        np.testing.assert_allclose(m_w.coefficients, m_r.coefficients, atol=1e-8)

    def test_negative_weights_rejected(self, xyw):
        x, y, w = xyw
        with pytest.raises(ValueError, match="non-negative"):
            LinearRegression().fit((x, y, -w))

    def test_length_mismatch_rejected(self, xyw):
        x, y, w = xyw
        with pytest.raises(ValueError, match="weights"):
            LinearRegression().fit((x, y, w[:-5]))


class TestWeightedLogisticRegression:
    def test_matches_replication_oracle(self, rng):
        x = rng.normal(size=(400, 4))
        y = (x[:, 0] + 0.5 * rng.normal(size=400) > 0).astype(float)
        w = rng.integers(1, 4, 400).astype(np.float64)
        m_w = LogisticRegression().setRegParam(0.01).fit((x, y, w))
        xr, yr = _replicate(x, y, w)
        m_r = LogisticRegression().setRegParam(0.01).fit((xr, yr))
        np.testing.assert_allclose(m_w.coefficients, m_r.coefficients, rtol=1e-5)
        np.testing.assert_allclose(m_w.intercept, m_r.intercept, atol=1e-5)

    def test_zero_weight_excludes_rows(self, rng):
        x = rng.normal(size=(200, 3))
        y = (x[:, 0] > 0).astype(float)
        y2 = y.copy()
        y2[150:] = 1.0 - y2[150:]  # flip labels on the tail
        w = np.ones(200)
        w[150:] = 0.0
        m_w = LogisticRegression().setRegParam(0.01).fit((x, y2, w))
        m_clean = LogisticRegression().setRegParam(0.01).fit((x[:150], y[:150]))
        np.testing.assert_allclose(m_w.coefficients, m_clean.coefficients, rtol=1e-5)


class TestWeightedKMeans:
    def test_matches_replication_oracle(self, rng):
        a = rng.normal(size=(60, 3)) + 6
        b = rng.normal(size=(60, 3)) - 6
        x = np.vstack([a, b])
        w = rng.integers(1, 4, 120).astype(np.float64)
        km = lambda: KMeans().setK(2).setSeed(3).setMaxIter(30)
        m_w = km().fit(x, sample_weight=w)
        m_r = km().fit(np.repeat(x, w.astype(int), axis=0))
        # same cluster structure: compare sorted centers
        cw = m_w.clusterCenters[np.argsort(m_w.clusterCenters[:, 0])]
        cr = m_r.clusterCenters[np.argsort(m_r.clusterCenters[:, 0])]
        np.testing.assert_allclose(cw, cr, atol=1e-4)

    def test_zero_weight_ignores_outliers(self, rng):
        x = np.vstack(
            [rng.normal(size=(50, 2)) + 5, rng.normal(size=(50, 2)) - 5,
             np.full((5, 2), 100.0)]  # far outliers
        )
        w = np.ones(105)
        w[100:] = 0.0
        m = KMeans().setK(2).setSeed(0).fit(x, sample_weight=w)
        assert np.abs(m.clusterCenters).max() < 10  # outliers never pull a center

    def test_weight_col_from_dataframe(self, rng):
        pd = pytest.importorskip("pandas")
        x = np.vstack([rng.normal(size=(40, 2)) + 4, rng.normal(size=(40, 2)) - 4])
        w = rng.integers(1, 3, 80).astype(np.float64)
        df = pd.DataFrame({"features": list(x), "w": w})
        m = (
            KMeans().setK(2).setSeed(1).setInputCol("features").setWeightCol("w")
            .fit(df)
        )
        m_r = KMeans().setK(2).setSeed(1).fit(np.repeat(x, w.astype(int), axis=0))
        cw = m.clusterCenters[np.argsort(m.clusterCenters[:, 0])]
        cr = m_r.clusterCenters[np.argsort(m_r.clusterCenters[:, 0])]
        np.testing.assert_allclose(cw, cr, atol=1e-4)

    def test_negative_sample_weight_rejected(self, rng):
        x = rng.normal(size=(20, 2))
        with pytest.raises(ValueError, match="non-negative"):
            KMeans().setK(2).fit(x, sample_weight=-np.ones(20))

    def test_all_zero_weights_rejected(self, rng):
        x = rng.normal(size=(20, 2))
        with pytest.raises(ValueError, match="all instance weights are zero"):
            KMeans().setK(2).fit(x, sample_weight=np.zeros(20))
        with pytest.raises(ValueError, match="all instance weights are zero"):
            LinearRegression().fit((x, np.zeros(20), np.zeros(20)))

    def test_fractional_weights_on_integer_features(self, rng):
        """Integer-dtype X must not floor fractional weights (or labels) —
        side vectors get a float dtype."""
        x_int = rng.integers(-5, 6, size=(100, 3))
        y = x_int @ np.array([1.0, 2.0, 3.0]) + 0.5
        w = np.full(100, 0.5)
        m = LinearRegression().fit((x_int, y, w))
        # uniform weights = unweighted fit
        m_u = LinearRegression().fit((x_int.astype(float), y))
        np.testing.assert_allclose(m.coefficients, m_u.coefficients, atol=1e-8)

        km = KMeans().setK(2).setSeed(0)
        model = km.fit(x_int.astype(np.float64), sample_weight=np.full(100, 0.5))
        assert np.isfinite(model.trainingCost)
