"""Streamed mesh-local ingestion (spark/ingest.py).

The reference never lands data on the driver (ColumnarRdd hands fit()
device-resident tables, RapidsRowMatrix.scala:118); the mesh-local
deployment must, and the contract here is that it does so at O(shard) peak
host memory — not O(dataset) like a collect-then-pad implementation.
"""

import os
import tracemalloc

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_ml_tpu.parallel import mesh as M
from spark_rapids_ml_tpu.spark import ingest


def _features_batch(mat: np.ndarray, extra: dict | None = None) -> pa.RecordBatch:
    n = mat.shape[1]
    flat = pa.array(mat.reshape(-1))
    offsets = pa.array(np.arange(0, mat.size + 1, n, dtype=np.int32))
    arrays = [pa.ListArray.from_arrays(offsets, flat)]
    names = ["features"]
    for name, col in (extra or {}).items():
        arrays.append(pa.array(col))
        names.append(name)
    return pa.RecordBatch.from_arrays(arrays, names=names)


class _LazyFrame:
    """localspark-shaped source whose partitions are GENERATED on demand —
    the whole dataset never exists at once on the host."""

    def __init__(self, rows: int, n: int, n_parts: int = 16, labeled: bool = False):
        self.rows, self.n, self.n_parts, self.labeled = rows, n, n_parts, labeled

    def count(self) -> int:
        return self.rows

    def _part_arrays(self, p: int):
        lo = self.rows * p // self.n_parts
        hi = self.rows * (p + 1) // self.n_parts
        idx = np.arange(lo, hi, dtype=np.float64)
        mat = idx[:, None] * 0.001 + np.arange(self.n)[None, :]
        return idx, mat

    def _parts(self):
        for p in range(self.n_parts):
            idx, mat = self._part_arrays(p)
            extra = {"label": idx * 0.5, "w": 1.0 + (idx % 3)} if self.labeled else None
            yield [_features_batch(mat, extra)]

    def dense(self):
        return np.concatenate(
            [self._part_arrays(p)[1] for p in range(self.n_parts)]
        )

    def dense_rows(self, lo: int, hi: int) -> np.ndarray:
        """Oracle for a row range without materializing the dataset."""
        idx = np.arange(lo, hi, dtype=np.float64)
        return idx[:, None] * 0.001 + np.arange(self.n)[None, :]


def test_stream_matches_collect_then_pad():
    rows, n = 1000, 8
    df = _LazyFrame(rows, n)
    mesh = M.create_mesh()
    ing = ingest.stream_to_mesh(df, features_col="features", n=n, mesh=mesh)
    assert ing.rows == rows
    assert ing.padded_rows % mesh.size == 0
    got = np.asarray(ing.xs)
    assert got.shape == (ing.padded_rows, n)
    np.testing.assert_array_equal(got[:rows], df.dense())
    assert not got[rows:].any()  # zero pads


def test_stream_labeled_weighted_and_intercept():
    rows, n = 700, 5
    df = _LazyFrame(rows, n, labeled=True)
    mesh = M.create_mesh()
    ing = ingest.stream_to_mesh(
        df, features_col="features", n=n, label_col="label", weight_col="w",
        with_weights=True, augment_intercept=True, mesh=mesh,
    )
    x = np.asarray(ing.xs)
    assert x.shape[1] == n + 1
    np.testing.assert_array_equal(x[:rows, :n], df.dense())
    np.testing.assert_array_equal(x[:rows, n], np.ones(rows))  # intercept col
    assert not x[rows:].any()  # pads: zero INCLUDING the intercept column
    idx = np.arange(rows, dtype=np.float64)
    np.testing.assert_array_equal(np.asarray(ing.ys)[:rows], idx * 0.5)
    np.testing.assert_array_equal(np.asarray(ing.ws)[:rows], 1.0 + (idx % 3))
    assert not np.asarray(ing.ws)[rows:].any()  # pad mask


def test_with_weights_without_weight_col_is_pad_mask():
    df = _LazyFrame(300, 4)
    ing = ingest.stream_to_mesh(
        df, features_col="features", n=4, with_weights=True
    )
    w = np.asarray(ing.ws)
    np.testing.assert_array_equal(w[:300], np.ones(300))
    assert not w[300:].any()


def test_negative_weights_raise():
    rows, n = 64, 3
    mat = np.ones((rows, n))
    w = np.ones(rows)
    w[10] = -1.0

    class Neg(_LazyFrame):
        def _parts(self):
            yield [_features_batch(mat, {"w": w})]

    with pytest.raises(ValueError, match="non-negative"):
        ingest.stream_to_mesh(
            Neg(rows, n), features_col="features", n=n, weight_col="w"
        )


def test_row_count_mismatch_raises():
    class Lying(_LazyFrame):
        def count(self):
            return self.rows + 5

    with pytest.raises(ValueError, match="cache"):
        ingest.stream_to_mesh(
            Lying(128, 4), features_col="features", n=4
        )


def test_size_guard_names_alternatives(monkeypatch):
    monkeypatch.setenv(ingest.MAX_BYTES_VAR, "1024")
    with pytest.raises(ValueError, match="mesh-barrier"):
        ingest.stream_to_mesh(
            _LazyFrame(4096, 16), features_col="features", n=16
        )


def test_wire_dtype_float32(monkeypatch):
    monkeypatch.setenv(ingest.WIRE_DTYPE_VAR, "float32")
    df = _LazyFrame(200, 4)
    ing = ingest.stream_to_mesh(df, features_col="features", n=4)
    assert np.asarray(ing.xs).dtype == np.float32
    np.testing.assert_allclose(
        np.asarray(ing.xs)[:200], df.dense(), rtol=1e-6
    )


def test_wire_dtype_rejects_unknown(monkeypatch):
    monkeypatch.setenv(ingest.WIRE_DTYPE_VAR, "bfloat16")
    with pytest.raises(ValueError, match="float32 or float64"):
        ingest.wire_dtype()


class _PysparkLike:
    """toArrow/toLocalIterator surface without _parts (a real-Spark stand-in):
    records which ingest strategy ran."""

    def __init__(self, rows, n):
        self.rows, self.n = rows, n
        self.used = None

    def count(self):
        return self.rows

    def _mat(self):
        return np.arange(self.rows * self.n, dtype=np.float64).reshape(
            self.rows, self.n
        )

    def toArrow(self):
        self.used = "arrow"
        return pa.Table.from_batches([_features_batch(self._mat())])

    def toLocalIterator(self):
        self.used = "rows"
        for r in self._mat():
            yield (list(r),)


def test_pyspark_small_dataset_takes_arrow_fast_path():
    df = _PysparkLike(500, 6)
    ing = ingest.stream_to_mesh(df, features_col="features", n=6)
    assert df.used == "arrow"
    np.testing.assert_array_equal(np.asarray(ing.xs)[:500], df._mat())


def test_pyspark_large_dataset_streams_rows(monkeypatch):
    monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "1000")  # force cutover
    df = _PysparkLike(500, 6)
    ing = ingest.stream_to_mesh(df, features_col="features", n=6)
    assert df.used == "rows"
    np.testing.assert_array_equal(np.asarray(ing.xs)[:500], df._mat())


class _Pyspark3Like(_PysparkLike):
    """pyspark 3.x surface: toPandas + toLocalIterator, NO toArrow —
    shaped like a properly-configured session (arrow transfer on,
    ArrayType features), which is what the pandas fast path requires."""

    toArrow = None  # not callable — the 4.0 probe must skip it

    @property
    def schema(self):
        return {"features": type("Field", (), {
            "dataType": type("ArrayType", (), {})()
        })()}

    @property
    def sparkSession(self):
        conf = type("Conf", (), {"get": staticmethod(lambda k: "true")})()
        return type("Session", (), {"conf": conf})()

    def toPandas(self):
        import pandas as pd

        self.used = "pandas"
        return pd.DataFrame({"features": [list(r) for r in self._mat()]})


def test_pyspark3_vector_udt_column_streams_rows_not_pandas():
    # VectorUDT is not arrow-convertible: toPandas would silently degrade
    # to a pickled full collect, so the guard must route to the iterator
    class VecUDT(_Pyspark3Like):
        @property
        def schema(self):
            return {"features": type("Field", (), {
                "dataType": type("VectorUDT", (), {})()
            })()}

        def toLocalIterator(self):
            self.used = "rows"
            for r in self._mat():
                yield (list(r),)

    df = VecUDT(200, 4)
    ing = ingest.stream_to_mesh(df, features_col="features", n=4)
    assert df.used == "rows"
    np.testing.assert_array_equal(np.asarray(ing.xs)[:200], df._mat())


def test_pyspark3_arrow_disabled_streams_rows():
    class ArrowOff(_Pyspark3Like):
        @property
        def sparkSession(self):
            conf = type("Conf", (), {"get": staticmethod(lambda k: "false")})()
            return type("Session", (), {"conf": conf})()

        def toLocalIterator(self):
            self.used = "rows"
            for r in self._mat():
                yield (list(r),)

    df = ArrowOff(200, 4)
    ingest.stream_to_mesh(df, features_col="features", n=4)
    assert df.used == "rows"


def test_pyspark3_small_dataset_takes_pandas_columnar_path():
    # pyspark 3.x has no toArrow; small datasets must still get a columnar
    # one-job collect (arrow-enabled toPandas), not the row iterator
    df = _Pyspark3Like(400, 6)
    ing = ingest.stream_to_mesh(df, features_col="features", n=6)
    assert df.used == "pandas"
    np.testing.assert_array_equal(np.asarray(ing.xs)[:400], df._mat())


def test_pyspark3_large_dataset_still_streams_rows(monkeypatch):
    monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "1000")
    df = _Pyspark3Like(400, 6)
    ing = ingest.stream_to_mesh(df, features_col="features", n=6)
    assert df.used == "rows"
    np.testing.assert_array_equal(np.asarray(ing.xs)[:400], df._mat())


class _PysparkLikeWeighted(_PysparkLike):
    """Row-iterator source with [features, weight] columns and NO label —
    the positional layout KMeans selects (weight at index 1, not 2)."""

    def toLocalIterator(self):
        self.used = "rows"
        for i, r in enumerate(self._mat()):
            yield (list(r), float(1 + i % 3))


def test_row_path_weight_position_without_label(monkeypatch):
    monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "1")  # force the row path
    rows = 200
    df = _PysparkLikeWeighted(rows, 4)
    ing = ingest.stream_to_mesh(
        df, features_col="features", n=4, weight_col="w"
    )
    assert df.used == "rows"
    w = np.asarray(ing.ws)
    np.testing.assert_array_equal(
        w[:rows], 1.0 + (np.arange(rows) % 3)
    )
    assert not w[rows:].any()


from pyspark_support import have_pyspark as _have_pyspark


@pytest.mark.skipif(
    not _have_pyspark(),
    reason="pyspark not installed: the REAL toArrow/toLocalIterator ingest "
    "branches NOT exercised locally — see CI pyspark-integration matrix "
    "(build-test.yml), which selects this module",
)
class TestLivePysparkIngestBranches:
    """VERDICT r4 Next #4: the pyspark-specific strategy code — toArrow
    cutover and toLocalIterator row streaming (spark/ingest.py) — against a
    live session, not monkeypatched fakes."""

    @pytest.fixture(scope="class")
    def spark(self):
        from pyspark.sql import SparkSession

        s = (
            SparkSession.builder.master("local[2]")
            .appName("tpu-ml-ingest-it")
            .config("spark.sql.execution.arrow.pyspark.enabled", "true")
            .getOrCreate()
        )
        yield s
        s.stop()

    def _df(self, spark, x):
        from pyspark.sql import types as PT

        schema = PT.StructType(
            [PT.StructField("features", PT.ArrayType(PT.DoubleType()))]
        )
        return spark.createDataFrame(
            [(row.tolist(),) for row in x], schema
        ).repartition(3)

    def test_row_iterator_path_equals_arrow_path(self, spark, monkeypatch):
        import time

        x = np.random.default_rng(5).normal(size=(5000, 16))
        df = self._df(spark, x).select("features")
        arrow = ingest.stream_to_mesh(df, features_col="features", n=16)
        monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "0")  # force rows
        t0 = time.perf_counter()
        rowed = ingest.stream_to_mesh(df, features_col="features", n=16)
        took = time.perf_counter() - t0
        print(
            f"\nlive toLocalIterator ingest: {5000 / took:,.0f} rows/s "
            "(5000 x 16 f64, local[2])"
        )
        # same rows, same order, both strategies (sorting not required:
        # both passes run the same deterministic plan)
        np.testing.assert_array_equal(
            np.asarray(arrow.xs), np.asarray(rowed.xs)
        )
        got = np.sort(np.asarray(rowed.xs)[:5000, 0])
        np.testing.assert_allclose(got, np.sort(x[:, 0]), atol=0)

    def test_vector_udt_rows_through_both_paths(self, spark, monkeypatch):
        from pyspark.ml.linalg import Vectors
        from pyspark.sql import types as PT
        from pyspark.ml.linalg import VectorUDT

        x = np.random.default_rng(6).normal(size=(400, 5))
        schema = PT.StructType([PT.StructField("features", VectorUDT())])
        df = spark.createDataFrame(
            [(Vectors.dense(row),) for row in x], schema
        ).select("features")
        arrow = ingest.stream_to_mesh(df, features_col="features", n=5)
        monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "0")
        rowed = ingest.stream_to_mesh(df, features_col="features", n=5)
        np.testing.assert_array_equal(
            np.asarray(arrow.xs), np.asarray(rowed.xs)
        )


class _FakeDenseVector:
    """pyspark.ml DenseVector shape: a ``values`` ndarray, no ``indices``."""

    def __init__(self, values):
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self):
        return self.values


class _FakeSparseVector:
    """pyspark.ml SparseVector shape: values + indices + size + toArray."""

    def __init__(self, size, indices, values):
        self.size = size
        self.indices = np.asarray(indices, dtype=np.int32)
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self):
        out = np.zeros(self.size)
        out[self.indices] = self.values
        return out


class _PysparkLikeVectors(_PysparkLike):
    """Row-iterator source whose features are pyspark.ml-style vectors —
    the dtype real VectorUDT DataFrames hand toLocalIterator."""

    def __init__(self, rows, n, sparse_every: int = 0):
        super().__init__(rows, n)
        self.sparse_every = sparse_every

    def toLocalIterator(self):
        self.used = "rows"
        for i, r in enumerate(self._mat()):
            if self.sparse_every and i % self.sparse_every == 0:
                nz = [0, self.n - 1]
                yield (_FakeSparseVector(self.n, nz, r[nz]),)
            else:
                yield (_FakeDenseVector(r),)


def test_row_path_densevector_bulk_conversion(monkeypatch):
    # the bulk branch: DenseVector rows stack their backing ndarrays
    monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "1")
    df = _PysparkLikeVectors(300, 5)
    ing = ingest.stream_to_mesh(df, features_col="features", n=5)
    assert df.used == "rows"
    np.testing.assert_array_equal(np.asarray(ing.xs)[:300], df._mat())


def test_row_path_mixed_sparse_rows_fall_back_exactly(monkeypatch):
    # sparse rows interleaved with dense: the bulk attempt must fall back
    # to the exact per-row converter, not silently mis-shape
    monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "1")
    rows, n = 120, 6
    df = _PysparkLikeVectors(rows, n, sparse_every=7)
    ing = ingest.stream_to_mesh(df, features_col="features", n=n)
    want = df._mat()
    for i in range(0, rows, 7):
        dense = np.zeros(n)
        dense[[0, n - 1]] = want[i, [0, n - 1]]
        want[i] = dense
    np.testing.assert_array_equal(np.asarray(ing.xs)[:rows], want)


def test_row_path_throughput_is_measured(monkeypatch, capsys):
    """Weak #5 (r4): the row-iterator conversion cost as a NUMBER. The
    end-to-end rate prints into the test log for the record (an absolute
    floor would flake with machine load — observed 19k-70k rows/s on the
    same box); the regression GATE is relative: the bulk chunk converter
    must beat the exact per-row fallback on identical data, min-of-3,
    which no amount of load inverts."""
    import time

    from spark_rapids_ml_tpu.utils import columnar

    monkeypatch.setenv(ingest.ARROW_CUTOVER_VAR, "1")
    rows, n = 200_000, 32
    df = _PysparkLike(rows, n)
    t0 = time.perf_counter()
    ing = ingest.stream_to_mesh(df, features_col="features", n=n)
    took = time.perf_counter() - t0
    print(
        f"\nrow-iterator ingest: {rows / took:,.0f} rows/s ({rows} x {n} f64)"
    )
    assert ing.rows == rows

    chunk = [
        (list(r),)
        for r in np.random.default_rng(0).normal(size=(20_000, n))
    ]

    def timed(fn):
        best, out = float("inf"), None
        for _ in range(3):
            s = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - s)
        return best, out

    bulk_t, (bulk_x, _, _) = timed(
        lambda: ingest._chunk_from_rows(chunk, None, None)
    )
    row_t, row_x = timed(
        lambda: np.stack(
            [columnar.row_vector_to_ndarray(r[0]) for r in chunk]
        )
    )
    np.testing.assert_array_equal(bulk_x, row_x)
    print(
        f"chunk converter: bulk {20_000 / bulk_t:,.0f} rows/s vs per-row "
        f"{20_000 / row_t:,.0f} rows/s"
    )
    assert bulk_t < row_t, (
        f"bulk converter ({bulk_t:.3f}s) no faster than per-row fallback "
        f"({row_t:.3f}s) — did the bulk path regress to per-row?"
    )


@pytest.mark.slow
def test_streamed_ingest_8gb_scale():
    """VERDICT r4 Next #6: the O(shard) bound at a shape the old
    concatenate+pad implementation could not survive. 16M×128 float32 wire
    is ~8.2 GB device-resident; the old path would have peaked at ~2×
    dataset in EXTRA host copies (f64 concatenate + padded copy ≈ 33 GB).
    tracemalloc tracks the host numpy allocations; on the CPU test backend
    device_put aliases the shard buffers, so the bound is on the transient
    footprint ABOVE device residency — one inbound chunk + one fill buffer.
    """
    rows, n = 16_000_000, 128
    dataset_bytes = rows * n * 4
    os.environ[ingest.WIRE_DTYPE_VAR] = "float32"
    try:
        df = _LazyFrame(rows, n, n_parts=128)
        mesh = M.create_mesh()
        tracemalloc.start()
        try:
            ing = ingest.stream_to_mesh(
                df, features_col="features", n=n, mesh=mesh
            )
            _, peak = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
    finally:
        del os.environ[ingest.WIRE_DTYPE_VAR]
    device_resident = ing.padded_rows * n * 4
    transient = peak - device_resident
    shard_bytes = (ing.padded_rows // mesh.size) * n * 4
    # generator chunk (f64, rows/128 × n) + one f32 fill buffer + slack
    chunk_bytes = (rows // 128) * n * 8
    assert transient < 2 * (shard_bytes + chunk_bytes), (
        f"transient {transient / 1e9:.2f} GB vs shard {shard_bytes / 1e9:.2f}"
        f" GB + chunk {chunk_bytes / 1e9:.2f} GB (dataset "
        f"{dataset_bytes / 1e9:.2f} GB)"
    )
    # the headline bound: nothing remotely like the old 2x-dataset copies
    assert transient < 0.5 * dataset_bytes
    # spot-check correctness at both ends of the stream, reading PER-SHARD
    # device buffers: a global slice (ing.xs[:64]) would make XLA gather
    # the full 8 GB array onto every device — observed 66 GB RSS
    shards = sorted(
        ing.xs.addressable_shards, key=lambda s: s.index[0].start or 0
    )

    def shard_holding(global_row):
        for s in shards:
            start = s.index[0].start or 0
            if start <= global_row < start + s.data.shape[0]:
                return s, start
        raise AssertionError(f"no shard holds row {global_row}")

    head = np.asarray(shards[0].data)[:64]
    np.testing.assert_allclose(head, df.dense_rows(0, 64), rtol=1e-6)
    # the LAST TRUE rows may sit before an all-padding tail shard on some
    # device counts — address the shard that actually holds them
    t_shard, t_start = shard_holding(rows - 64)
    lo = rows - 64 - t_start
    hi = min(rows - t_start, t_shard.data.shape[0])
    tail = np.asarray(t_shard.data)[lo:hi]
    np.testing.assert_allclose(
        tail, df.dense_rows(rows - 64, rows - 64 + len(tail)), rtol=1e-6
    )


def test_host_memory_is_o_shard_not_o_dataset():
    """The r3 verdict's bound: peak host allocation during a mesh-local
    ingest must scale with ONE shard, not the dataset. 200k×64 f64 is
    ~100 MB of data; with 8 devices a shard buffer is ~16 MB. tracemalloc
    sees numpy/python host allocations (the ones the old concatenate+pad
    implementation blew up) and not XLA device buffers — exactly the
    boundary we are bounding."""
    rows, n = 200_000, 64
    df = _LazyFrame(rows, n, n_parts=16)
    mesh = M.create_mesh()
    dataset_bytes = rows * n * 8
    tracemalloc.start()
    try:
        ing = ingest.stream_to_mesh(
            df, features_col="features", n=n, mesh=mesh
        )
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    shard_bytes = (ing.padded_rows // mesh.size) * n * 8
    # On the CPU test backend device_put ALIASES the numpy shard buffers
    # (zero-copy), so tracemalloc's peak includes the full device-resident
    # padded dataset — bytes that live in HBM on a real TPU. The host-side
    # bound is therefore on the TRANSIENT footprint above device residency:
    # one inbound partition + the fill buffers + slack, O(shard).
    device_resident = ing.padded_rows * n * 8
    transient = peak - device_resident
    assert transient < 4 * shard_bytes, (
        f"transient host alloc {transient / 1e6:.1f} MB vs shard "
        f"{shard_bytes / 1e6:.1f} MB, dataset {dataset_bytes / 1e6:.1f} MB"
    )
    # and nothing like the ≥2×dataset of the old concatenate+pad path
    assert peak < 1.5 * dataset_bytes
    np.testing.assert_array_equal(
        np.asarray(ing.xs)[: rows // 100], df.dense()[: rows // 100]
    )


@pytest.mark.slow
def test_mesh_local_training_at_gb_scale():
    """The training-side sibling of the 8 GB ingest proof: stream a ~2 GB
    float32 dataset onto the mesh and run the WHOLE-LOOP Lloyd program on
    it — the full mesh-local deployment path (ingest + in-program k-means++
    reduction + while_loop Lloyd) at a scale the old concatenate path
    could not stage."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.parallel import kmeans as PK

    rows, n, k = 8_000_000, 64, 16
    os.environ[ingest.WIRE_DTYPE_VAR] = "float32"
    try:
        df = _LazyFrame(rows, n, n_parts=64)
        mesh = M.create_mesh()
        ing = ingest.stream_to_mesh(
            df, features_col="features", n=n, with_weights=True, mesh=mesh
        )
    finally:
        del os.environ[ingest.WIRE_DTYPE_VAR]
    # deterministic seeds from the first shard (seeding quality is not the
    # subject here; the whole-loop program at scale is)
    shard0 = ing.xs.addressable_shards[0].data
    centers0 = jnp.asarray(np.asarray(shard0[:k]))
    cfit, cost, iters = PK.make_distributed_kmeans_fit(
        mesh, max_iter=5, tol=1e-6
    )(ing.xs, ing.ws, centers0)
    jax.block_until_ready(cfit)
    assert cfit.shape == (k, n)
    assert np.isfinite(np.asarray(cfit)).all()
    assert float(cost) > 0.0 and int(iters) >= 1
    # the data is a linear ramp (row*0.001 + arange(n)): centers must land
    # inside the data's bounding box, not at pads/zeros
    lo, hi = 0.0, (rows - 1) * 0.001 + (n - 1)
    c = np.asarray(cfit)
    assert (c >= lo - 1e-3).all() and (c <= hi + 1e-3).all()
    # pads carry zero weight, so no center collapses onto the zero pad rows
    # unless the data actually lives there (feature j floor is j)
    assert (c[:, -1] >= (n - 1) - 1e-3).all()
