"""Flight recorder (telemetry/timeline.py) + the worker→driver telemetry
trailer + the fit timeline export pipeline.

Covers the ISSUE-4 list: ring bounding and event ordering under concurrent
recording, Chrome trace-event export validity, the localspark task
protocol's telemetry trailer round-trip (worker events land driver-side
labeled by partition), the streamed-SparkPCA acceptance path (driver
spans + injected-fault/retry instants + overlap_fraction on the report,
rendered/exported by tools/trace_timeline.py), the TPU_ML_PROGRESS
heartbeat, the fit_id log filter, and the Prometheus exposition +
tools/metrics_dump.py satellite.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu import telemetry as T
from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from spark_rapids_ml_tpu.telemetry.timeline import (
    TIMELINE,
    Timeline,
    chrome_trace,
    timeline_capacity,
)
from spark_rapids_ml_tpu.utils.config import get_config, set_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TL_CLI = os.path.join(REPO, "tools", "trace_timeline.py")
MD_CLI = os.path.join(REPO, "tools", "metrics_dump.py")


@pytest.fixture(autouse=True)
def clean():
    T.reset_metrics()
    TIMELINE.clear()
    faults.reset_faults()
    yield
    T.reset_metrics()
    TIMELINE.clear()
    faults.reset_faults()


@pytest.fixture
def force_streamed(monkeypatch):
    old = get_config().stream_fit_max_resident_bytes
    monkeypatch.setenv("TPU_ML_STREAM_CHUNK_ROWS", "128")
    set_config(stream_fit_max_resident_bytes=1)
    yield
    set_config(stream_fit_max_resident_bytes=old)


class TestTimelineUnit:
    def test_span_and_instant_event_shape(self):
        tl = Timeline(capacity=16)
        tl.record_span("fold", 1.0, 1.5, estimator="PCA", empty="")
        tl.record_instant("retry", site="fold.dispatch", attempt=1)
        spans = [e for e in tl.events() if e["ph"] == "X"]
        instants = [e for e in tl.events() if e["ph"] == "i"]
        assert len(spans) == 1 and len(instants) == 1
        s = spans[0]
        assert s["name"] == "fold"
        assert s["ts"] == 1_000_000 and s["dur"] == 500_000
        assert s["pid"] == os.getpid()
        assert s["args"] == {"estimator": "PCA"}  # falsy labels dropped
        i = instants[0]
        assert i["s"] == "t"
        assert i["args"] == {"site": "fold.dispatch", "attempt": 1}

    def test_ring_stays_within_bound(self):
        tl = Timeline(capacity=64)
        for k in range(1000):
            tl.record_instant("e", k=k + 1)
        assert len(tl) == 64
        evs = tl.events()
        # oldest fell off; the survivors are exactly the LAST 64, in order
        assert [e["args"]["k"] for e in evs] == list(range(937, 1001))
        assert evs[-1]["seq"] == 1000

    def test_zero_capacity_disables_recording(self):
        tl = Timeline(capacity=0)
        tl.record_span("x", 0.0, 1.0)
        tl.record_instant("y")
        tl.merge([{"name": "z", "ts": 1}])
        assert len(tl) == 0

    def test_capacity_env(self, monkeypatch):
        monkeypatch.setenv("TPU_ML_TIMELINE_EVENTS", "128")
        assert timeline_capacity() == 128
        assert Timeline().capacity == 128
        monkeypatch.setenv("TPU_ML_TIMELINE_EVENTS", "banana")
        with pytest.raises(ValueError, match="not an integer"):
            timeline_capacity()
        monkeypatch.setenv("TPU_ML_TIMELINE_EVENTS", "-1")
        with pytest.raises(ValueError, match=">= 0"):
            timeline_capacity()

    def test_since_seq_window(self):
        tl = Timeline(capacity=16)
        tl.record_instant("a")
        mark = tl.seq()
        tl.record_instant("b")
        tl.record_instant("c")
        assert [e["name"] for e in tl.events(since_seq=mark)] == ["b", "c"]

    def test_concurrent_recording_bounded_and_ordered(self):
        """The localspark load shape: many threads record concurrently. No
        lost updates (every append got a distinct seq), the ring bound
        holds, and events() comes out seq-ordered."""
        tl = Timeline(capacity=256)
        n_threads, per_thread = 8, 500

        def work(t):
            for k in range(per_thread):
                tl.record_instant("e", thread=t + 1, k=k + 1)

        threads = [
            threading.Thread(target=work, args=(t,)) for t in range(n_threads)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(tl) == 256
        evs = tl.events()
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == len(seqs)
        assert tl.seq() == n_threads * per_thread  # no lost seq update

    def test_merge_stamps_labels_and_drops_malformed(self):
        tl = Timeline(capacity=16)
        foreign = [
            {"name": "worker.task", "ph": "X", "ts": 5, "dur": 2,
             "pid": 99999, "tid": 1, "args": {"x": 1}},
            "not-a-dict",
            {"ph": "i", "ts": 7},  # no name
            {"name": "noline"},  # no ts
        ]
        tl.merge(foreign, partition="3", empty="")
        evs = tl.events()
        assert len(evs) == 1
        e = evs[0]
        assert e["pid"] == 99999 and e["ts"] == 5  # foreign clock preserved
        assert e["args"] == {"x": 1, "partition": "3"}

    def test_chrome_trace_valid_and_named(self):
        tl = Timeline(capacity=16)
        tl.record_span("driver.span", 0.0, 1.0)
        tl.merge(
            [{"name": "worker.task", "ph": "X", "ts": 1, "dur": 1,
              "pid": 4242, "tid": 1, "args": {}}],
            partition="7",
        )
        trace = json.loads(json.dumps(chrome_trace(tl.events())))
        evs = trace["traceEvents"]
        assert all("seq" not in e for e in evs)
        meta = {e["pid"]: e["args"]["name"] for e in evs if e["ph"] == "M"}
        assert meta[4242] == "worker partition 7"
        assert meta[os.getpid()].startswith("driver")


class TestWorkerTrailer:
    def test_mapinarrow_round_trip_labels_partitions(self):
        """The tentpole protocol change: worker registry deltas and timeline
        events ship on the success frame and merge driver-side labeled by
        partition."""
        from spark_rapids_ml_tpu.localspark.session import LocalSparkSession

        with LocalSparkSession(parallelism=3, num_workers=2) as spark:
            df = spark.createDataFrame(
                [(float(i), float(2 * i)) for i in range(30)], ["a", "b"]
            )

            def fn(it):
                yield from it

            assert len(df.mapInArrow(fn, df.schema).collect()) == 30

        snap = REGISTRY.snapshot()
        # worker-side span histogram arrived, one series per partition
        assert snap.hist("span.seconds", phase="worker.task").count == 3
        for p in ("0", "1", "2"):
            assert (
                snap.hist("span.seconds", phase="worker.task", partition=p).count
                == 1
            )
        # timeline events arrived with the foreign pid preserved
        tasks = [
            e for e in TIMELINE.events() if e["name"] == "worker.task"
        ]
        assert sorted(e["args"]["partition"] for e in tasks) == ["0", "1", "2"]
        assert all(e["pid"] != os.getpid() for e in tasks)

    def test_worker_counters_merge_with_partition_label(self):
        """A counter a plan function records inside the worker becomes
        visible in the driver registry, labeled by its partition."""
        from spark_rapids_ml_tpu.localspark.session import LocalSparkSession

        def fn(it):
            from spark_rapids_ml_tpu.telemetry.registry import REGISTRY as R

            for b in it:
                R.counter_inc("test.worker_rows", b.num_rows)
                yield b

        with LocalSparkSession(parallelism=2, num_workers=2) as spark:
            df = spark.createDataFrame(
                [(float(i),) for i in range(20)], ["a"]
            )
            df.mapInArrow(fn, df.schema).collect()
        snap = REGISTRY.snapshot()
        assert snap.counter("test.worker_rows") == 20
        assert snap.counter("test.worker_rows", partition="0") == 10
        assert snap.counter("test.worker_rows", partition="1") == 10

    def test_failed_task_ships_no_telemetry(self):
        from spark_rapids_ml_tpu.localspark.session import (
            LocalSparkSession,
            WorkerException,
        )

        def bad(it):
            raise ValueError("boom")
            yield  # pragma: no cover

        with LocalSparkSession(parallelism=2, num_workers=1) as spark:
            df = spark.createDataFrame([(1.0,), (2.0,)], ["a"])
            with pytest.raises(WorkerException, match="boom"):
                df.mapInArrow(bad, df.schema).collect()
            # the protocol stream stayed in sync: the SAME worker runs the
            # next task fine (an unread trailer would desynchronize it)
            def ok(it):
                yield from it

            assert len(df.mapInArrow(ok, df.schema).collect()) == 2
        assert [e for e in TIMELINE.events() if e["name"] == "worker.task"]


class TestFitTimelineExport:
    def test_streamed_sparkpca_exports_loadable_chrome_trace(
        self, force_streamed, monkeypatch, tmp_path
    ):
        """The acceptance path: a streamed SparkPCA.fit (mesh-local, with
        one injected-then-retried fault) plus a worker-path fit, exported
        via TPU_ML_TIMELINE_PATH and rendered by tools/trace_timeline.py
        into Chrome trace JSON holding driver spans, partition-labeled
        worker spans and the fault/retry instants."""
        from spark_rapids_ml_tpu.localspark.session import LocalSparkSession
        from spark_rapids_ml_tpu.localspark import types as LT
        from spark_rapids_ml_tpu.spark import SparkPCA

        tl_path = str(tmp_path / "timeline.jsonl")
        old = get_config().timeline_path
        set_config(timeline_path=tl_path)
        # first fold dispatch fails with a transient I/O error, the shared
        # retry recovers it — the flight recorder must show both instants
        monkeypatch.setenv("TPU_ML_FAULT_PLAN", "fold.dispatch:io:1")
        try:
            rng = np.random.default_rng(7)
            x = rng.normal(size=(600, 8))
            schema = LT.StructType(
                [LT.StructField("features", LT.ArrayType(LT.DoubleType()))]
            )
            with LocalSparkSession(parallelism=2, num_workers=1) as spark:
                df = spark.createDataFrame([(r.tolist(),) for r in x], schema)
                model = (
                    SparkPCA().setInputCol("features").setK(3)
                    .setDistribution("mesh-local").fit(df)
                )
                monkeypatch.delenv("TPU_ML_FAULT_PLAN")
                faults.reset_faults()
                # worker-path fit: driver-merge runs partition stats through
                # mapInArrow workers, contributing partition-labeled spans
                SparkPCA().setInputCol("features").setK(3).fit(df)
        finally:
            set_config(timeline_path=old)

        rep = model.fit_report
        assert rep is not None and len(rep.fit_id) == 12
        assert rep.overlap_fraction is not None
        assert 0.0 <= rep.overlap_fraction <= 1.0

        records = [
            json.loads(line)
            for line in open(tl_path, encoding="utf-8")
            if line.strip()
        ]
        assert [r["type"] for r in records] == ["timeline", "timeline"]
        assert records[0]["fit_id"] == rep.fit_id
        assert records[0]["overlap_fraction"] == rep.overlap_fraction

        out_json = str(tmp_path / "trace.json")
        proc = subprocess.run(
            [sys.executable, TL_CLI, tl_path, "--out", out_json],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "overlap fraction" in proc.stdout
        with open(out_json, encoding="utf-8") as f:
            trace = json.load(f)  # valid Chrome trace JSON
        evs = trace["traceEvents"]
        phases = {e.get("ph") for e in evs}
        assert {"X", "i", "M"} <= phases
        driver_spans = [
            e for e in evs
            if e.get("ph") == "X" and e.get("pid") == os.getpid()
        ]
        assert driver_spans  # fold.dispatch / fold.wait etc.
        worker_spans = [
            e for e in evs
            if e.get("ph") == "X" and (e.get("args") or {}).get("partition")
        ]
        assert worker_spans  # partition-labeled, from the trailer
        instants = {e["name"] for e in evs if e.get("ph") == "i"}
        assert "fault.injected" in instants
        assert "retry" in instants
        assert "stream.chunk" in instants

    def test_no_export_without_timeline_path(self, tmp_path):
        from spark_rapids_ml_tpu.models.pca import PCA

        assert get_config().timeline_path == ""
        x = np.random.default_rng(0).normal(size=(128, 4))
        model = PCA().setInputCol("f").setK(2).fit(x)
        assert model.fit_report.fit_id  # fit identity minted regardless

    def test_in_core_fit_has_no_overlap_fraction(self):
        from spark_rapids_ml_tpu.models.pca import PCA

        x = np.random.default_rng(0).normal(size=(128, 4))
        model = PCA().setInputCol("f").setK(2).fit(x)
        assert model.fit_report.overlap_fraction is None


class TestProgressHeartbeat:
    def test_heartbeat_line_on_stderr(self, monkeypatch, capsys):
        from spark_rapids_ml_tpu.ops import linalg as L
        from spark_rapids_ml_tpu.spark import ingest

        monkeypatch.setenv("TPU_ML_PROGRESS", "1e-9")
        rng = np.random.default_rng(3)
        x = np.asarray(rng.normal(size=(1024, 16)), ingest.wire_dtype())
        res = ingest.stream_fold(
            iter(np.array_split(x, 8)),
            L.gram_fold_step(),
            n=16,
            init=L.init_gram_carry(16, x.dtype),
            chunk_rows=128,
        )
        assert res.chunks == 8
        err = capsys.readouterr().err
        assert "[tpu-ml progress" in err
        assert "rows=" in err and "rows/s" in err and "retries=" in err

    def test_heartbeat_off_by_default(self, capsys):
        from spark_rapids_ml_tpu.ops import linalg as L
        from spark_rapids_ml_tpu.spark import ingest

        assert ingest.progress_interval() == 0.0
        x = np.asarray(
            np.random.default_rng(3).normal(size=(256, 8)),
            ingest.wire_dtype(),
        )
        ingest.stream_fold(
            iter(np.array_split(x, 2)),
            L.gram_fold_step(),
            n=8,
            init=L.init_gram_carry(8, x.dtype),
            chunk_rows=128,
        )
        assert "[tpu-ml progress" not in capsys.readouterr().err

    def test_bad_interval_rejected(self, monkeypatch):
        from spark_rapids_ml_tpu.spark import ingest

        monkeypatch.setenv("TPU_ML_PROGRESS", "often")
        with pytest.raises(ValueError, match="TPU_ML_PROGRESS"):
            ingest.progress_interval()


class TestFitIdFilter:
    def test_package_log_records_carry_fit_id(self, caplog):
        from spark_rapids_ml_tpu.models.pca import PCA

        x = np.random.default_rng(0).normal(size=(128, 4))
        with caplog.at_level(logging.DEBUG, logger="spark_rapids_ml_tpu"):
            model = PCA().setInputCol("f").setK(2).fit(x)
        fid = model.fit_report.fit_id
        stamped = [
            r for r in caplog.records if getattr(r, "fit_id", "-") == fid
        ]
        assert stamped  # span debug lines inside the fit window
        # outside any fit, records still format: the filter stamps "-"
        logging.getLogger("spark_rapids_ml_tpu").warning("outside")
        assert caplog.records[-1].fit_id == "-"


class TestPrometheusExposition:
    def test_text_format(self):
        reg = MetricsRegistry()
        reg.counter_inc("retry.attempts", 3, site="fold.dispatch")
        reg.gauge_set("chunk.rows", 512)
        reg.histogram_record("span.seconds", 0.5, phase="fit")
        reg.histogram_record("span.seconds", 2.0, phase="fit")
        text = reg.to_prometheus()
        assert "# TYPE tpu_ml_retry_attempts counter" in text
        assert 'tpu_ml_retry_attempts{site="fold.dispatch"} 3' in text
        assert "# TYPE tpu_ml_chunk_rows gauge" in text
        assert "# TYPE tpu_ml_span_seconds histogram" in text
        assert 'le="+Inf"' in text
        assert 'tpu_ml_span_seconds_count{phase="fit"} 2' in text
        assert 'tpu_ml_span_seconds_sum{phase="fit"} 2.5' in text
        # cumulative buckets: the +Inf bucket equals the count
        assert text.endswith("\n")

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter_inc("c", 1, site='we"ird\\x')
        assert 'site="we\\"ird\\\\x"' in reg.to_prometheus()

    def test_empty_registry_renders_empty(self):
        assert MetricsRegistry().to_prometheus() == ""

    def test_metrics_dump_cli(self, tmp_path):
        from spark_rapids_ml_tpu.models.pca import PCA
        from spark_rapids_ml_tpu.telemetry.export import export_fit_report

        x = np.random.default_rng(0).normal(size=(256, 6))
        model = PCA().setInputCol("f").setK(2).fit(x)
        path = str(tmp_path / "telemetry.jsonl")
        assert export_fit_report(model.fit_report, path=path)
        proc = subprocess.run(
            [sys.executable, MD_CLI, path],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert '# TYPE tpu_ml_fits counter' in proc.stdout
        assert 'tpu_ml_fits{estimator="PCA"} 1' in proc.stdout
        assert "# TYPE tpu_ml_fit_wall_seconds histogram" in proc.stdout

    def test_metrics_dump_cli_no_records(self, tmp_path):
        path = tmp_path / "empty.jsonl"
        path.write_text("")
        proc = subprocess.run(
            [sys.executable, MD_CLI, str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1

    def test_metrics_dump_renders_every_names_family(self, tmp_path, capsys):
        """Meta-check: every metric family declared in telemetry.names
        survives the report→dump→Prometheus pipeline. A family the dump
        silently drops (filters, sanitization, renames) would otherwise
        vanish from dashboards without any test noticing."""
        import importlib.util

        from spark_rapids_ml_tpu.telemetry import names

        spec = importlib.util.spec_from_file_location("metrics_dump", MD_CLI)
        md = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(md)

        rec = {
            "type": "fit_report",
            "schema": 5,
            "estimator": "Meta",
            "wall_seconds": 1.0,
            "rows_ingested": 10,
            "bytes_ingested": 80,
            "h2d_bytes": 80,
            "overlap_fraction": 0.5,
            "collectives": {"count": 1, "bytes": 8, "tree_combines": 1},
            "compile": {
                "count": 1, "seconds": 0.1, "trace_seconds": 0.05,
                "lower_seconds": 0.02, "cache_hits": 1, "cache_misses": 1,
                "cache_time_saved_s": 0.1,
            },
            "cost_model": {
                "analytical_flops": 100, "analytical_bytes": 100,
                "roofline_utilization": 0.1,
            },
            "tuning": {
                "decisions": [
                    {"kernel": "stream.fold_step", "source": "cache",
                     "cache_hit": True, "config": {}},
                ],
            },
            # every declared family as a raw window counter: the generic
            # pass-through must re-emit ALL of them
            "counters": {name: 1.0 for name in sorted(names.METRICS)},
        }
        path = tmp_path / "t.jsonl"
        path.write_text(json.dumps(rec) + "\n")
        assert md.main([str(path)]) == 0
        out = capsys.readouterr().out

        def prom_name(name):
            return "tpu_ml_" + "".join(
                c if c.isalnum() or c == "_" else "_" for c in name
            )

        missing, wrong_kind = [], []
        for n in sorted(names.METRICS):
            pn = prom_name(n)
            if n in names.HISTOGRAMS:
                kind, probe = "histogram", pn + "_count"
            elif n in names.GAUGES:
                kind, probe = "gauge", pn
            else:
                kind, probe = "counter", pn
            if probe + "{" not in out and probe + " " not in out:
                missing.append(n)
            # the declared kind must be the rendered TYPE: a histogram
            # family silently rendering as a counter would rate() into
            # garbage on a dashboard without any test noticing
            elif f"# TYPE {pn} {kind}" not in out:
                wrong_kind.append(f"{n} (want {kind})")
        assert not missing, f"families dropped by metrics_dump: {missing}"
        assert not wrong_kind, (
            f"families rendered under the wrong TYPE: {wrong_kind} — "
            "declare the kind in telemetry.names HISTOGRAMS/GAUGES"
        )
        # the dedicated autotune decision family carries its labels
        assert (
            'tpu_ml_autotune_decisions{estimator="Meta",'
            'kernel="stream.fold_step",source="cache"} 1' in out
        )

    def test_metrics_dump_renders_perf_ledger_serving(self, tmp_path, capsys):
        """A perf_ledger record's serving/refresh/fleet evidence renders
        the serve.*/refresh.* families — queue_delay_us as a histogram,
        transports labeled, swap/fold counters, version gauges."""
        import importlib.util

        spec = importlib.util.spec_from_file_location("metrics_dump", MD_CLI)
        md = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(md)

        rec = {
            "type": "perf_ledger",
            "serving": {
                "requests": 52, "errors": 1, "rows": 400, "batches": 9,
                "hedges": 2, "shed": 0,
                "transport_mix": {"http/json": 20, "uds/fast": 32},
                "bucket_hits": {"8": 40, "16": 12},
                "json_codec": {"encode": 3, "decode": 3},
                "trace": {"minted": 52, "latency_exemplars": []},
                "latency": {"count": 52, "sum": 1.0, "p50": 0.01,
                            "p99": 0.08},
                "queue_delay_us": {"count": 52, "sum": 900.0, "p50": 10.0,
                                   "p99": 120.0},
                "hbm_bytes": 1024,
            },
            "refresh": {
                "refresh": {
                    "swaps": 1, "swap_refused": 0, "rollbacks": 0,
                    "folds": 2, "rows": 8192, "finalizes": 1,
                    "checkpoints": 2, "resumes": 0,
                    "swap_blackout": {"count": 1, "sum": 0.002,
                                      "p50": 0.002, "p99": 0.002},
                    "lag_seconds": 0.5,
                    "versions": {"bench_refresh": 2},
                },
            },
            "fleet": {
                "replicas": 2,
                "routing": {"hits": 90, "misses": 4},
                "rolling_restart": {"drain_events": 1,
                                    "replica_restarts": 1},
            },
        }
        path = tmp_path / "ledger.jsonl"
        path.write_text(json.dumps(rec) + "\n")
        assert md.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "# TYPE tpu_ml_serve_queue_delay_us histogram" in out
        assert "tpu_ml_serve_queue_delay_us_count 2" in out
        assert "tpu_ml_serve_requests 52" in out
        assert 'tpu_ml_serve_transport{transport="uds",wire="fast"} 32' in out
        assert "tpu_ml_serve_traces 52" in out
        assert "# TYPE tpu_ml_serve_latency histogram" in out
        assert "tpu_ml_serve_swaps 1" in out
        assert "tpu_ml_refresh_folds 2" in out
        assert "# TYPE tpu_ml_refresh_lag_seconds gauge" in out
        assert 'tpu_ml_serve_model_version{model="bench_refresh"} 2' in out
        assert "# TYPE tpu_ml_serve_fleet_replicas gauge" in out
        assert "tpu_ml_serve_route_hits 90" in out
        assert "tpu_ml_serve_drain_events 1" in out


class TestTraceTimelineCli:
    def _record(self, **over):
        events = [
            {"name": "fold.dispatch", "ph": "X", "ts": 1_000_000,
             "dur": 100_000, "pid": 10, "tid": 1, "args": {}},
            {"name": "fold.dispatch", "ph": "X", "ts": 4_000_000,
             "dur": 100_000, "pid": 10, "tid": 1, "args": {}},
            {"name": "worker.task", "ph": "X", "ts": 1_100_000,
             "dur": 50_000, "pid": 11, "tid": 1,
             "args": {"partition": "0"}},
            {"name": "retry", "ph": "i", "ts": 1_200_000, "pid": 10,
             "tid": 1, "s": "t", "args": {"site": "fold.dispatch"}},
        ]
        rec = {
            "type": "timeline", "schema": 1, "fit_id": "feedc0ffee12",
            "estimator": "SparkPCA", "uid": "", "overlap_fraction": 0.5,
            "events": events,
        }
        rec.update(over)
        return rec

    def test_summary_and_strict_gap_gate(self, tmp_path):
        import importlib.util

        spec = importlib.util.spec_from_file_location("trace_timeline", TL_CLI)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(self._record()) + "\n")
        # the driver track has a 2.9 s gap between its two spans
        assert mod.main([str(p)]) == 0  # default threshold 1.0, not strict
        assert mod.main([str(p), "--strict", "--gap-threshold", "1.0"]) == 2
        assert mod.main([str(p), "--strict", "--gap-threshold", "10"]) == 0
        assert mod.main([str(p), "--fit", "nope"]) == 1

    def test_out_roundtrips_through_itself(self, tmp_path):
        """--out writes a Chrome trace the tool itself accepts as input."""
        import importlib.util

        spec = importlib.util.spec_from_file_location("trace_timeline", TL_CLI)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(self._record()) + "\n")
        out = str(tmp_path / "trace.json")
        assert mod.main([str(p), "--out", out]) == 0
        trace = json.load(open(out, encoding="utf-8"))
        names = {
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e["ph"] == "M"
        }
        assert "worker partition 0" in names
        assert mod.main([out]) == 0  # chrome-trace input mode
