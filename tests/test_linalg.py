"""Unit tests for the kernel core — differential against NumPy f64 oracles.

The reference has no unit tests of its native layer (SURVEY.md §4); these are
the pure-math tests it lacked, runnable on the CPU backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.ops import linalg as L


def _random(rng, rows=200, n=16):
    return rng.normal(size=(rows, n)).astype(np.float64)


class TestGram:
    def test_matches_numpy(self, rng):
        x = _random(rng)
        got = np.asarray(L.gram(jnp.asarray(x)))
        np.testing.assert_allclose(got, x.T @ x, rtol=1e-12)

    def test_stats_combine_is_concat(self, rng):
        """Summing per-partition GramStats == stats of the concatenated data.

        This is the property the cross-partition reduction relies on
        (reference: breeze reduce at RapidsRowMatrix.scala:139).
        """
        parts = [_random(rng, rows=r) for r in (50, 70, 30)]
        stats = [L.gram_stats(jnp.asarray(p)) for p in parts]
        combined = stats[0]
        for s in stats[1:]:
            combined = L.combine_gram_stats(combined, s)
        full = L.gram_stats(jnp.asarray(np.concatenate(parts)))
        np.testing.assert_allclose(combined.xtx, full.xtx, rtol=1e-10)
        np.testing.assert_allclose(combined.col_sum, full.col_sum, rtol=1e-10)
        assert int(combined.count) == 150

    def test_centered_covariance(self, rng):
        x = _random(rng)
        stats = L.gram_stats(jnp.asarray(x))
        cov = np.asarray(L.covariance_from_stats(stats, mean_centering=True))
        xc = x - x.mean(axis=0)
        np.testing.assert_allclose(cov, xc.T @ xc, rtol=1e-8, atol=1e-8)

    def test_uncentered_is_raw_gram(self, rng):
        x = _random(rng)
        stats = L.gram_stats(jnp.asarray(x))
        cov = np.asarray(L.covariance_from_stats(stats, mean_centering=False))
        np.testing.assert_allclose(cov, x.T @ x, rtol=1e-12)


class TestSignFlip:
    def test_max_abs_element_positive(self, rng):
        u = rng.normal(size=(12, 8))
        flipped = np.asarray(L.sign_flip(jnp.asarray(u)))
        for j in range(8):
            col = flipped[:, j]
            assert col[np.argmax(np.abs(col))] > 0

    def test_only_sign_changes(self, rng):
        u = rng.normal(size=(12, 8))
        flipped = np.asarray(L.sign_flip(jnp.asarray(u)))
        np.testing.assert_allclose(np.abs(flipped), np.abs(u), rtol=1e-12)

    def test_already_positive_unchanged(self):
        u = np.array([[1.0, -0.5], [0.5, 2.0]])
        # col0 max-abs elem is +1 → unchanged; col1 max-abs is +2 → unchanged
        np.testing.assert_array_equal(np.asarray(L.sign_flip(jnp.asarray(u))), u)

    def test_negative_anchor_flips(self):
        u = np.array([[-3.0], [1.0]])
        np.testing.assert_array_equal(
            np.asarray(L.sign_flip(jnp.asarray(u))), np.array([[3.0], [-1.0]])
        )


class TestEighDescending:
    def test_against_numpy(self, rng):
        x = _random(rng, rows=500, n=24)
        cov = x.T @ x
        comps, s = L.eigh_descending(jnp.asarray(cov))
        comps, s = np.asarray(comps), np.asarray(s)

        evals, evecs = np.linalg.eigh(cov)
        order = np.argsort(evals)[::-1]
        np.testing.assert_allclose(s, np.sqrt(evals[order]), rtol=1e-9)
        # eigenvectors up to sign → compare abs values
        np.testing.assert_allclose(
            np.abs(comps), np.abs(evecs[:, order]), rtol=1e-7, atol=1e-9
        )

    def test_descending_order(self, rng):
        x = _random(rng)
        _, s = L.eigh_descending(jnp.asarray(x.T @ x))
        s = np.asarray(s)
        assert np.all(np.diff(s) <= 1e-12)

    def test_negative_eigenvalues_clipped(self):
        # indefinite symmetric matrix: λ = ±1 → singular values [1, 0]
        a = jnp.asarray(np.array([[0.0, 1.0], [1.0, 0.0]]))
        _, s = L.eigh_descending(a)
        np.testing.assert_allclose(np.asarray(s), [1.0, 0.0], atol=1e-12)


class TestExplainedVariance:
    def test_full_spectrum_normalization_before_truncation(self):
        """Reference semantics: normalize over ALL singular values, then cut
        to k (RapidsRowMatrix.scala:92-99) — NOT eigenvalue proportions."""
        s = jnp.asarray(np.array([4.0, 3.0, 2.0, 1.0]))
        ev = np.asarray(L.explained_variance(s, 2))
        np.testing.assert_allclose(ev, [0.4, 0.3], rtol=1e-12)

    def test_zero_spectrum_safe(self):
        ev = np.asarray(L.explained_variance(jnp.zeros(4), 2))
        np.testing.assert_array_equal(ev, [0.0, 0.0])


class TestRandomizedSolver:
    def test_matches_exact_on_decaying_spectrum(self, rng):
        """Top-k subspace and singular values agree with the exact eigh on a
        spectrum with decay (the regime randomized SVD targets)."""
        n, k = 64, 5
        # Construct a PSD matrix with geometric spectral decay.
        q, _ = np.linalg.qr(rng.normal(size=(n, n)))
        evals = 100.0 * (0.5 ** np.arange(n))
        cov = (q * evals) @ q.T
        u, s, tail = L.randomized_eigh_descending(
            jnp.asarray(cov), k, power_iters=3
        )
        u, s = np.asarray(u), np.asarray(s)
        assert s.shape == (k + 10,)  # full oversampled Ritz spectrum
        np.testing.assert_allclose(s[:k] ** 2, evals[:k], rtol=1e-6)
        # subspace check, sign-invariant
        np.testing.assert_allclose(np.abs(u), np.abs(q[:, :k]), atol=1e-5)
        assert int(tail) == n - k - 10

    def test_sign_flip_orientation(self, rng):
        n, k = 32, 4
        x = _random(rng, rows=200, n=n)
        u, _, _ = L.randomized_eigh_descending(jnp.asarray(x.T @ x), k)
        u = np.asarray(u)
        for j in range(k):
            assert u[np.argmax(np.abs(u[:, j])), j] > 0

    def test_pca_fit_from_cov_solver_dispatch(self, rng):
        # rank-structured data: randomized solvers need spectral separation
        # between the kept components (near-degenerate Wishart spectra mix
        # eigenvectors — inherent to the method, not a bug).
        base = rng.normal(size=(300, 6))
        x = base @ rng.normal(size=(6, 24)) + 1e-3 * _random(rng, rows=300, n=24)
        cov = jnp.asarray(x.T @ x)
        pc_full, ev_full = L.pca_fit_from_cov(cov, 3, solver="full")
        pc_rand, ev_rand = L.pca_fit_from_cov(cov, 3, solver="randomized")
        np.testing.assert_allclose(
            np.abs(np.asarray(pc_rand)), np.abs(np.asarray(pc_full)), atol=1e-6
        )
        # ev uses the tail estimate → looser agreement, same ordering
        np.testing.assert_allclose(
            np.asarray(ev_rand), np.asarray(ev_full), rtol=0.1
        )
        with pytest.raises(ValueError):
            L.pca_fit_from_cov(cov, 3, solver="bogus")

    def test_auto_picks_full_for_small_n(self, rng):
        """auto == full below the measured profitability threshold —
        bit-identical output."""
        x = _random(rng, rows=100, n=16)
        cov = jnp.asarray(x.T @ x)
        pc_a, ev_a = L.pca_fit_from_cov(cov, 3, solver="auto")
        pc_f, ev_f = L.pca_fit_from_cov(cov, 3, solver="full")
        np.testing.assert_array_equal(np.asarray(pc_a), np.asarray(pc_f))
        np.testing.assert_array_equal(np.asarray(ev_a), np.asarray(ev_f))

    def test_profitability_rule_covers_bench_shape(self):
        """The measured win (v5e, n=512, k=50, oversample=20) must be inside
        the shared auto rule, else solver='auto' leaves it on the table."""
        assert L.randomized_profitable(512, 50, oversample=20)
        assert not L.randomized_profitable(128, 50)  # l > n/4
        assert not L.randomized_profitable(100, 10)  # n below floor

    def test_jittable_with_static_solver(self, rng):
        x = _random(rng, rows=100, n=16)
        fit = jax.jit(L.pca_fit_from_cov, static_argnums=(1,), static_argnames=("solver",))
        pc, ev = fit(jnp.asarray(x.T @ x), 3, solver="randomized")
        assert pc.shape == (16, 3) and ev.shape == (3,)

    def test_tail_estimate_flat_spectrum_exact(self):
        """The √(m·trace_tail) tail estimate is exact for a flat tail."""
        n, k = 40, 4
        evals = np.concatenate([[100.0, 90.0, 80.0, 70.0], np.full(n - k, 2.0)])
        s_top = jnp.asarray(np.sqrt(evals[:k]))
        ev = np.asarray(
            L.explained_variance_from_partial(
                s_top, jnp.asarray(evals.sum()), jnp.asarray(float(n - k))
            )
        )
        s_all = np.sqrt(evals)
        np.testing.assert_allclose(ev, (s_all / s_all.sum())[:k], rtol=1e-10)


class TestEndToEnd:
    @pytest.mark.parametrize("mean_centering", [False, True])
    def test_projection_matches_sklearn_subspace(self, rng, mean_centering):
        """Differential oracle in the style of PCASuite.scala:42-88: compare
        |transformed| against an independent implementation (sign-invariant)."""
        x = _random(rng, rows=300, n=20)
        k = 5
        pc, ev = L.pca_fit_local(jnp.asarray(x), k, mean_centering=mean_centering)
        pc = np.asarray(pc)

        xe = x - x.mean(axis=0) if mean_centering else x
        evals, evecs = np.linalg.eigh(xe.T @ xe)
        order = np.argsort(evals)[::-1]
        expected_pc = evecs[:, order[:k]]

        got = xe @ pc
        want = xe @ expected_pc
        np.testing.assert_allclose(np.abs(got), np.abs(want), rtol=1e-6, atol=1e-8)

        # explainedVariance: √λ proportions over full spectrum, truncated
        s = np.sqrt(np.clip(evals[order], 0, None))
        np.testing.assert_allclose(np.asarray(ev), (s / s.sum())[:k], rtol=1e-7)

    def test_fit_kernel_is_jittable(self, rng):
        x = jnp.asarray(_random(rng, rows=64, n=8))
        fit = jax.jit(lambda a: L.pca_fit_local(a, 3))
        pc, ev = fit(x)
        assert pc.shape == (8, 3)
        assert ev.shape == (3,)

    def test_project_matches_numpy(self, rng):
        x = _random(rng, rows=100, n=16)
        pc = rng.normal(size=(16, 4))
        got = np.asarray(L.project(jnp.asarray(x), jnp.asarray(pc)))
        np.testing.assert_allclose(got, x @ pc, rtol=1e-12)
