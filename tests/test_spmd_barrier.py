"""Barrier-stage SPMD mesh execution through the DataFrame API.

The north-star test (VERDICT r2 #1): a multi-worker DataFrame fit whose
cross-partition Gram reduction happens as a psum collective inside ONE XLA
program spanning the barrier stage's jax.distributed process group — the
driver receives a single pre-reduced statistics row (never per-partition
xtx), and the result is differential-equal to the portable driver-merge
path (which is itself differential-tested against NumPy oracles).
"""

import json

import numpy as np
import pytest

from spark_rapids_ml_tpu.localspark import LocalSparkSession
from spark_rapids_ml_tpu.localspark import types as LT
from spark_rapids_ml_tpu.spark import SparkPCA
from spark_rapids_ml_tpu.spark import spmd


@pytest.fixture(scope="module")
def session():
    s = LocalSparkSession(
        parallelism=4,
        worker_env={
            "JAX_ENABLE_X64": "1",
            "JAX_COMPILATION_CACHE_DIR": "/tmp/jax_test_cache",
        },
    )
    yield s
    s.stop()


def _features_df(session, x, partitions=4):
    schema = LT.StructType(
        [LT.StructField("features", LT.ArrayType(LT.DoubleType()))]
    )
    return session.createDataFrame(
        [(row.tolist(),) for row in x], schema, numPartitions=partitions
    )


class TestBarrierTaskContext:
    def test_all_gather_orders_by_rank(self, session):
        df = _features_df(session, np.eye(4), partitions=4)

        def fn(batches):
            import pyarrow as pa

            from spark_rapids_ml_tpu.localspark.taskcontext import (
                BarrierTaskContext,
            )

            list(batches)
            ctx = BarrierTaskContext.get()
            ctx.barrier()  # plain rendezvous round first
            gathered = ctx.allGather(json.dumps({"rank": ctx.partitionId()}))
            ranks = [json.loads(g)["rank"] for g in gathered]
            yield pa.RecordBatch.from_arrays(
                [
                    pa.array([ctx.partitionId()]),
                    pa.array([json.dumps(ranks)]),
                ],
                names=["rank", "ranks"],
            )

        out_schema = LT.StructType(
            [
                LT.StructField("rank", LT.LongType()),
                LT.StructField("ranks", LT.StringType()),
            ]
        )
        rows = df.mapInArrow(fn, out_schema, barrier=True).collect()
        assert sorted(r["rank"] for r in rows) == [0, 1, 2, 3]
        for r in rows:
            assert json.loads(r["ranks"]) == [0, 1, 2, 3]

    def test_outside_barrier_task_raises(self):
        from spark_rapids_ml_tpu.localspark.taskcontext import BarrierTaskContext

        with pytest.raises(RuntimeError, match="not inside a barrier task"):
            BarrierTaskContext.get()


class TestMeshGramStage:
    def test_single_prereduced_row_with_full_mesh(self, session, rng):
        """4 barrier tasks -> one jax.distributed group -> ONE stats row whose
        mesh_size proves the psum spanned all 4 processes."""
        x = rng.normal(size=(320, 6))
        df = _features_df(session, x, partitions=4)
        fn = spmd.MeshGramPartitionFn("features", precision="highest")
        schema = LT.StructType(
            [
                LT.StructField(f, LT.ArrayType(LT.DoubleType()))
                for f in spmd.MESH_FIELDS
            ]
        )
        batches = df.mapInArrow(fn, schema, barrier=True).toArrow().to_batches()
        stats, mesh_size = spmd.single_stats_from_batches(batches, 6)
        assert mesh_size == 4
        # the driver-visible payload is ALREADY globally reduced:
        np.testing.assert_allclose(stats.xtx, x.T @ x, rtol=1e-10)
        np.testing.assert_allclose(stats.col_sum, x.sum(axis=0), rtol=1e-10)
        assert float(stats.count) == 320.0

    def test_multiple_rows_rejected(self, rng):
        from spark_rapids_ml_tpu.spark import arrow_fns

        row = arrow_fns.arrays_to_batch(
            {
                "xtx": np.eye(2),
                "col_sum": np.zeros(2),
                "count": np.float64(1),
                "mesh_size": np.float64(1),
            }
        )
        with pytest.raises(AssertionError, match="exactly ONE pre-reduced"):
            spmd.single_stats_from_batches([row, row], 2)


class TestSparkPCAMeshBarrier:
    def test_differential_vs_driver_merge(self, session, rng):
        x = rng.normal(size=(320, 8)) + 2.0
        df = _features_df(session, x, partitions=4)
        base = SparkPCA().setInputCol("features").setK(3).setMeanCentering(True)
        mesh_model = base.copy().setDistribution("mesh-barrier").fit(df)
        merge_model = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(
            np.abs(mesh_model.pc), np.abs(merge_model.pc), atol=1e-8
        )
        np.testing.assert_allclose(
            mesh_model.explainedVariance,
            merge_model.explainedVariance,
            atol=1e-8,
        )

    def test_mesh_local_differential(self, session, rng):
        """'mesh-local': the driver's own (virtual 8-device) mesh runs the
        psum program on rows streamed through the DataFrame API."""
        x = rng.normal(size=(300, 7))
        df = _features_df(session, x, partitions=4)
        base = SparkPCA().setInputCol("features").setK(3)
        local_model = base.copy().setDistribution("mesh-local").fit(df)
        merge_model = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(
            np.abs(local_model.pc), np.abs(merge_model.pc), atol=1e-8
        )
        np.testing.assert_allclose(
            local_model.explainedVariance,
            merge_model.explainedVariance,
            atol=1e-8,
        )

    def test_bad_distribution_rejected(self):
        with pytest.raises(ValueError, match="distribution"):
            SparkPCA().setDistribution("gossip")


class TestMeshBarrierBeyondPCA:
    """The SPMD barrier machinery is estimator-generic (r3): every
    stats-monoid estimator reduces through one psum program."""

    def test_linreg_mesh_barrier_differential(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkLinearRegression

        x = rng.normal(size=(400, 5))
        coef = np.array([1.0, -2.0, 0.5, 3.0, 0.0])
        y = x @ coef + 1.5 + 0.01 * rng.normal(size=400)
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [(row.tolist(), float(lbl)) for row, lbl in zip(x, y)],
            schema,
            numPartitions=4,
        )
        base = SparkLinearRegression().setRegParam(1e-6)
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(mesh.coefficients, merge.coefficients, atol=1e-8)
        np.testing.assert_allclose(mesh.intercept, merge.intercept, atol=1e-8)

    def test_linreg_mesh_barrier_weighted(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkLinearRegression

        x = rng.normal(size=(300, 3))
        y = x @ np.ones(3)
        y_bad = y.copy()
        y_bad[150:] += 50.0
        w = np.ones(300)
        w[150:] = 1e-12
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
                LT.StructField("wt", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [
                (row.tolist(), float(lbl), float(wi))
                for row, lbl, wi in zip(x, y_bad, w)
            ],
            schema,
            numPartitions=4,
        )
        model = (
            SparkLinearRegression().setWeightCol("wt")
            .setDistribution("mesh-barrier").fit(df)
        )
        np.testing.assert_allclose(model.coefficients, np.ones(3), atol=1e-4)

    def test_scaler_mesh_barrier_differential(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkStandardScaler

        x = rng.normal(size=(350, 6)) * 3.0 + 5.0
        df = _features_df(session, x, partitions=4)
        base = SparkStandardScaler().setInputCol("features")
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(mesh.mean, merge.mean, atol=1e-10)
        np.testing.assert_allclose(mesh.std, merge.std, atol=1e-10)

    def test_bad_distribution_rejected(self):
        from spark_rapids_ml_tpu.spark import (
            SparkLinearRegression,
            SparkStandardScaler,
        )

        # mesh-local became family-wide in r3 — it must be ACCEPTED now
        est = SparkLinearRegression().setDistribution("mesh-local")
        assert est.getOrDefault("distribution") == "mesh-local"
        with pytest.raises(ValueError, match="distribution"):
            SparkStandardScaler().setDistribution("gossip")


class TestFullLoopBarrierFits:
    """The r3 capstone: ENTIRE iterative fits as one XLA program across the
    barrier stage's process mesh — the driver sees only the final model."""

    def test_logreg_full_fit_differential(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkLogisticRegression

        x = rng.normal(size=(480, 4))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5, 0.0]) - 0.3)))
        y = (rng.random(480) < p).astype(float)
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [(row.tolist(), float(lbl)) for row, lbl in zip(x, y)],
            schema,
            numPartitions=4,
        )
        base = SparkLogisticRegression().setRegParam(1e-3).setMaxIter(12)
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(
            mesh.coefficients, merge.coefficients, atol=1e-8
        )
        np.testing.assert_allclose(mesh.intercept, merge.intercept, atol=1e-8)

    def test_kmeans_full_fit_differential(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkKMeans

        centers_true = rng.normal(size=(5, 3)) * 7.0
        x = np.concatenate(
            [rng.normal(size=(60, 3)) * 0.4 + c for c in centers_true]
        )
        rng.shuffle(x)
        df = _features_df(session, x, partitions=4)
        base = (
            SparkKMeans().setInputCol("features").setK(5).setSeed(3)
            .setMaxIter(10).setTol(0.0)
        )
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        # same driver-side seeding, same Lloyd math -> identical trajectory
        np.testing.assert_allclose(
            mesh.clusterCenters, merge.clusterCenters, atol=1e-8
        )
        np.testing.assert_allclose(
            mesh.trainingCost, merge.trainingCost, rtol=1e-8
        )

    def test_multinomial_full_fit_differential(self, session, rng):
        # r3: >=3-class fits ALSO run the whole softmax loop on the mesh
        from spark_rapids_ml_tpu.spark import SparkLogisticRegression

        centers = np.array([[3.0, 0.0], [0.0, 3.0], [-3.0, -3.0]])
        x = np.vstack([rng.normal(size=(70, 2)) + c for c in centers])
        y = np.repeat([0.0, 1.0, 2.0], 70)
        perm = rng.permutation(len(y))
        x, y = x[perm], y[perm]
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [(row.tolist(), float(lbl)) for row, lbl in zip(x, y)],
            schema,
            numPartitions=4,
        )
        base = SparkLogisticRegression().setRegParam(1e-2).setMaxIter(8)
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        assert mesh.numClasses == 3
        # softmax has a flat class-shift direction that amplifies float
        # summation-order differences between the 8-device mesh psum and the
        # 4-partition driver merge; 1e-6 is still far inside model noise
        np.testing.assert_allclose(
            mesh.coefficientMatrix, merge.coefficientMatrix, atol=1e-6
        )
        np.testing.assert_allclose(
            mesh.interceptVector, merge.interceptVector, atol=1e-6
        )

    def test_checkpoint_on_mesh_barrier_writes_durable_steps(
        self, session, rng, tmp_path
    ):
        # r4: mesh-barrier ACCEPTS checkpoint_dir (rank-0 chunked saves on
        # a shared filesystem). Verify the stage leaves durable step dirs
        # and the resulting model is intact; trajectory-equality is covered
        # by tests/test_mesh_checkpoint.py's barrier resume tests.
        import os

        from spark_rapids_ml_tpu.spark import SparkKMeans

        x = np.vstack(
            [rng.normal(size=(30, 3)) + 4, rng.normal(size=(30, 3)) - 4]
        )
        df = _features_df(session, x)
        ckdir = str(tmp_path / "ck")
        m = (
            SparkKMeans().setInputCol("features").setK(2).setSeed(1)
            .setMaxIter(4).setTol(0.0).setDistribution("mesh-barrier")
            .fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        )
        assert m.clusterCenters.shape == (2, 3)
        steps = [d for d in os.listdir(ckdir) if d.startswith("step-")]
        assert steps, "rank-0 worker wrote no durable checkpoints"

    def test_all_zero_weights_rejected_on_mesh_barrier(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkLogisticRegression

        x = rng.normal(size=(40, 3))
        y = (rng.random(40) < 0.5).astype(float)
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
                LT.StructField("wt", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [(r.tolist(), float(l), 0.0) for r, l in zip(x, y)], schema
        )
        est = (
            SparkLogisticRegression().setWeightCol("wt")
            .setDistribution("mesh-barrier").setMaxIter(3)
        )
        with pytest.raises(ValueError, match="all instance weights are zero"):
            est.fit(df)

    def test_weighted_logreg_mesh_barrier_differential(self, session, rng):
        from spark_rapids_ml_tpu.spark import SparkLogisticRegression

        x = rng.normal(size=(300, 3))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([1.5, -1.0, 0.5]))))
        y = (rng.random(300) < p).astype(float)
        w = rng.uniform(0.1, 2.0, size=300)
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
                LT.StructField("wt", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [
                (r.tolist(), float(l), float(wi))
                for r, l, wi in zip(x, y, w)
            ],
            schema,
            numPartitions=4,
        )
        base = (
            SparkLogisticRegression().setWeightCol("wt")
            .setRegParam(1e-3).setMaxIter(10)
        )
        mesh = base.copy().setDistribution("mesh-barrier").fit(df)
        merge = base.copy().setDistribution("driver-merge").fit(df)
        np.testing.assert_allclose(
            mesh.coefficients, merge.coefficients, atol=1e-8
        )


class TestBarrierEdgeCases:
    def test_empty_partition_in_barrier_stage(self, session, rng):
        # a partition with zero rows must adopt the group's column count and
        # contribute nothing (zero shard) — not crash the rendezvous
        x = rng.normal(size=(3, 5))  # 3 rows over 4 partitions -> one empty
        df = _features_df(session, x, partitions=4)
        model = (
            SparkPCA().setInputCol("features").setK(2)
            .setDistribution("mesh-barrier").fit(df)
        )
        core = SparkPCA().setInputCol("features").setK(2).fit(x)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(core.pc), atol=1e-8)
