"""Chaos fault-matrix: every injected fault class against the streamed fit,
asserting BOTH recovery (parity with the clean result) and the telemetry
trail (injection + recovery counters). Deterministic — the TPU_ML_FAULT_PLAN
nth-occurrence grammar always fails the same call — so these run in tier-1,
not behind the slow marker.

Matrix: device OOM (chunk bisection), transient I/O (retry-in-place), hang
(bounded fold.wait + FoldHangTimeout diagnosis), preemption (durable
checkpoint + bitwise resume), non-finite rows (raise/skip policy),
collective blips (finalize retry), device-init failure (CPU degradation).
"""

import os

import jax
import numpy as np
import pytest

from spark_rapids_ml_tpu.localspark import LocalSparkSession
from spark_rapids_ml_tpu.localspark import types as LT
from spark_rapids_ml_tpu.models.linear import LinearRegression
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.resilience import retry as R
from spark_rapids_ml_tpu.spark import ingest
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer
from spark_rapids_ml_tpu.utils.config import get_config, set_config

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_faults(monkeypatch):
    """No plan leaks in (from the env) or out (to later tests)."""
    monkeypatch.delenv(faults.FAULT_PLAN_VAR, raising=False)
    faults.reset_faults()
    yield
    faults.reset_faults()


@pytest.fixture
def snap():
    """Telemetry delta for the test body: ``snap.delta()`` -> counters."""
    s0 = REGISTRY.snapshot()

    class _Snap:
        @staticmethod
        def delta():
            return REGISTRY.snapshot().delta(s0)

    return _Snap


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(23)
    x = np.asarray(rng.normal(size=(1100, 12)), np.float64)
    coef = rng.normal(size=12)
    y = x @ coef + 0.05 * rng.normal(size=1100)
    return x, y


def _gram_stream(x, plan=None, monkeypatch=None, **kw):
    if plan is not None:
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, plan)
    return ingest.stream_fold(
        iter(np.array_split(x, 4)),
        L.gram_fold_step(),
        n=x.shape[1],
        init=L.init_gram_carry(x.shape[1], x.dtype),
        rows=len(x),
        chunk_rows=128,
        **kw,
    )


def _assert_gram_equal(carry, x):
    import jax.numpy as jnp

    want = L.gram_stats(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(carry.xtx), np.asarray(want.xtx), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(carry.col_sum), np.asarray(want.col_sum), rtol=1e-12
    )
    assert float(carry.count) == float(len(x))


class TestOOMBisection:
    def test_oom_bisects_chunk_and_stays_exact(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(x, "fold.dispatch:oom:3", monkeypatch)
        assert res.bisections >= 1
        assert res.rows == 1100
        _assert_gram_equal(res.carry, x)
        d = snap.delta()
        assert d.counter("fault.injected", site="fold.dispatch", kind="oom") == 1
        assert d.counter("chunk.bisections") == res.bisections

    def test_bisection_respects_floor(self, data, monkeypatch):
        """Every dispatch OOMs: the bisection floor turns an un-shrinkable
        OOM into the original error instead of an infinite loop."""
        x, _ = data
        plan = ",".join(f"fold.dispatch:oom:{i}" for i in range(1, 40))
        with pytest.raises(faults.InjectedResourceExhausted):
            _gram_stream(x, plan, monkeypatch, min_chunk_rows=64)


class TestTransientRetry:
    def test_ingest_io_retried(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(x, "ingest.chunk:io:2", monkeypatch)
        _assert_gram_equal(res.carry, x)
        d = snap.delta()
        assert d.counter("fault.injected", site="ingest.chunk", kind="io") == 1
        assert d.counter("retry.attempts", site="ingest.chunk") == 1

    def test_dispatch_io_retried(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(x, "fold.dispatch:io:4", monkeypatch)
        _assert_gram_equal(res.carry, x)
        assert snap.delta().counter("retry.attempts", site="fold.dispatch") == 1

    def test_transient_budget_exhaustion_raises(self, data, monkeypatch):
        plan = ",".join(f"ingest.chunk:io:{i}" for i in range(1, 30))
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        with pytest.raises(faults.InjectedTransientIOError):
            _gram_stream(x := data[0], plan, monkeypatch)


class TestHangBound:
    def test_hang_within_bound_completes(self, data, monkeypatch):
        x, _ = data
        res = _gram_stream(
            x, "fold.wait:hang:1:0.1", monkeypatch, fold_wait_timeout_s=30.0
        )
        _assert_gram_equal(res.carry, x)

    def test_hang_beyond_bound_diagnosed(self, data, monkeypatch):
        x, _ = data
        with pytest.raises(R.FoldHangTimeout, match="hung, not slow"):
            _gram_stream(
                x, "fold.wait:hang:1:3.0", monkeypatch, fold_wait_timeout_s=0.3
            )

    def test_hang_timeout_classified_poisoned(self):
        assert R.classify(R.FoldHangTimeout("x")) is R.ErrorClass.POISONED


class TestPreemptResume:
    def test_preempted_stream_resumes_bitwise(self, data, monkeypatch, tmp_path, snap):
        x, _ = data
        clean = _gram_stream(x)
        ckpt = TrainingCheckpointer(tmp_path / "ck")
        # chunks 1-5 fold; checkpoints land after chunks 2 and 4; the 6th
        # dispatch dies like a preempted process would
        with pytest.raises(faults.InjectedPreemption):
            _gram_stream(
                x, "fold.dispatch:preempt:6", monkeypatch,
                checkpointer=ckpt, checkpoint_every=2,
            )
        assert snap.delta().counter("stream.checkpoints") == 2
        monkeypatch.delenv(faults.FAULT_PLAN_VAR)
        res = _gram_stream(x, checkpointer=ckpt, checkpoint_every=2)
        assert res.resumed
        assert res.chunks == clean.chunks
        assert snap.delta().counter("stream.resumes") == 1
        # bitwise: the resumed accumulator path must reproduce the clean run
        np.testing.assert_array_equal(
            np.asarray(res.carry.xtx), np.asarray(clean.carry.xtx)
        )
        np.testing.assert_array_equal(
            np.asarray(res.carry.col_sum), np.asarray(clean.carry.col_sum)
        )
        assert float(res.carry.count) == float(clean.carry.count)

    def test_preemption_never_retried_in_process(self):
        calls = {"n": 0}

        def die():
            calls["n"] += 1
            raise faults.InjectedPreemption("gone")

        with pytest.raises(faults.InjectedPreemption):
            R.call_with_retry(die, policy=R.RetryPolicy(max_attempts=5))
        assert calls["n"] == 1


class TestNonFinitePolicy:
    def test_raise_policy_fails_loudly(self, data, monkeypatch):
        x = data[0].copy()
        x[7, 3] = np.nan
        with pytest.raises(ValueError, match="non-finite"):
            _gram_stream(x, nonfinite="raise")

    def test_skip_policy_drops_counts_and_matches(self, data, snap):
        x = data[0].copy()
        bad_rows = [7, 500, 1099]
        for i in bad_rows:
            x[i, i % 12] = np.inf if i % 2 else np.nan
        res = _gram_stream(x, nonfinite="skip")
        assert res.skipped_rows == len(bad_rows)
        assert res.rows == len(x) - len(bad_rows)
        _assert_gram_equal(res.carry, np.delete(x, bad_rows, axis=0))
        assert snap.delta().counter("rows.nonfinite_skipped") == len(bad_rows)

    def test_injected_corruption_skipped(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(
            x, "ingest.chunk:nonfinite:1", monkeypatch, nonfinite="skip"
        )
        assert res.skipped_rows == 1
        _assert_gram_equal(res.carry, x[1:])  # first row of first pull corrupted
        d = snap.delta()
        assert d.counter("fault.injected", site="ingest.chunk", kind="nonfinite") == 1

    def test_allow_policy_skips_the_scan(self, data):
        x = data[0].copy()
        x[3, 3] = np.nan
        res = _gram_stream(x, nonfinite="allow")
        assert res.skipped_rows == 0
        assert not np.isfinite(np.asarray(res.carry.xtx)).all()


class TestCollectiveRetry:
    def test_finalize_retries_transient(self, data, monkeypatch, snap):
        from spark_rapids_ml_tpu.parallel import gram as G
        from spark_rapids_ml_tpu.parallel import mesh as M

        x, _ = data
        mesh = M.create_mesh()
        example = L.GramStats(
            xtx=jax.ShapeDtypeStruct((12, 12), np.float64),
            col_sum=jax.ShapeDtypeStruct((12,), np.float64),
            count=jax.ShapeDtypeStruct((), np.float64),
        )
        res = ingest.stream_fold(
            iter(np.array_split(x, 4)),
            lambda c, xd, wd: G.sharded_gram_fold(c, xd, wd, mesh),
            n=12,
            init=G.init_chunk_carry(example, mesh),
            chunk_rows=G.stream_chunk_rows_for_mesh(mesh),
            put_fn=G.chunk_put(mesh),
        )
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "collective:io:1")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        stats = G.finalize_chunk_fold(res.carry, mesh)
        _assert_gram_equal(stats, x)
        d = snap.delta()
        assert d.counter("fault.injected", site="collective", kind="io") == 1
        assert d.counter("retry.attempts", site="collective") == 1


class TestDeviceInitDegradation:
    def test_nonfatal_init_failure_degrades(self, monkeypatch, snap):
        from spark_rapids_ml_tpu.spark import estimators as E

        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "device.init:io:1")
        assert E._mesh_or_fallback() is None
        assert snap.delta().counter("degraded.cpu_fallback") == 1

    def test_fatal_init_failure_propagates(self, monkeypatch):
        from spark_rapids_ml_tpu.spark import estimators as E

        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "device.init:preempt:1")
        with pytest.raises(faults.InjectedPreemption):
            E._mesh_or_fallback()

    def test_healthy_init_returns_mesh(self):
        from spark_rapids_ml_tpu.spark import estimators as E

        assert E._mesh_or_fallback() is not None


@pytest.fixture
def force_streamed(monkeypatch):
    old = get_config().stream_fit_max_resident_bytes
    monkeypatch.setenv("TPU_ML_STREAM_CHUNK_ROWS", "128")
    set_config(stream_fit_max_resident_bytes=1)
    yield
    set_config(stream_fit_max_resident_bytes=old)


class TestEstimatorChaosParity:
    """Whole-fit chaos: streamed PCA / LinearRegression under injection
    complete with parity against the clean model, and the per-fit telemetry
    records the injection and the recovery."""

    def test_pca_streamed_fit_under_faults(self, data, monkeypatch, force_streamed, snap):
        x, _ = data
        est = PCA().setInputCol("f").setK(4)
        clean = est.fit(x, num_partitions=3)
        monkeypatch.setenv(
            faults.FAULT_PLAN_VAR, "ingest.chunk:io:1,fold.dispatch:oom:5"
        )
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        m = est.fit(x, num_partitions=3)
        cos = np.abs(np.sum(clean.pc * m.pc, axis=0))
        assert cos.min() >= 0.9999, cos
        d = snap.delta()
        assert d.counter("fault.injected") == 2
        assert d.counter("retry.attempts") >= 1
        assert d.counter("chunk.bisections") >= 1

    def test_linreg_streamed_fit_under_faults(self, data, monkeypatch, force_streamed, snap):
        x, y = data
        clean = LinearRegression().fit((x, y), num_partitions=3)
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "fold.dispatch:io:2")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        m = LinearRegression().fit((x, y), num_partitions=3)
        np.testing.assert_allclose(m.coefficients, clean.coefficients, atol=1e-9)
        assert abs(m.intercept - clean.intercept) <= 1e-9
        d = snap.delta()
        assert d.counter("fault.injected", site="fold.dispatch", kind="io") == 1
        assert d.counter("retry.attempts", site="fold.dispatch") == 1

    def test_no_plan_means_zero_injections(self, data, force_streamed, snap):
        x, _ = data
        PCA().setInputCol("f").setK(3).fit(x, num_partitions=3)
        assert snap.delta().counter("fault.injected") == 0


# -- elastic stage scheduler: supervision, reassignment, hedging, barriers ----


def _ls_features_df(session, rows=36, dim=4, partitions=None, seed=5):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, dim))
    schema = LT.StructType(
        [
            LT.StructField("features", LT.ArrayType(LT.DoubleType())),
            LT.StructField("idx", LT.LongType()),
        ]
    )
    df = session.createDataFrame(
        [(row.tolist(), i) for i, row in enumerate(x)],
        schema,
        numPartitions=partitions,
    )
    return df, x


def _rows_key(rows):
    """Order-independent exact row content (the floats are bit-identical
    across runs: same source array, no arithmetic in the plan fn)."""
    return sorted((r.idx, tuple(r.features)) for r in rows)


def _local_ident():
    # defined per-call so cloudpickle ships it BY VALUE: a module-level
    # function would pickle by reference to this test module, which is not
    # importable inside a worker process
    def ident(batches):
        yield from batches

    return ident


class TestElasticScheduler:
    def test_worker_kill_mid_stage_reassigns(self, tmp_path, snap):
        """One worker SIGKILLs itself mid-stage: the supervisor respawns
        the slot, the dead attempt's partition migrates, and the output is
        identical to a clean run."""
        marker = str(tmp_path / "died_once")

        def die_once(batches):
            import os as wos

            data = list(batches)
            try:
                # O_EXCL: exactly one worker across the stage takes the hit
                wos.close(
                    wos.open(marker, wos.O_CREAT | wos.O_EXCL | wos.O_WRONLY)
                )
                wos.kill(wos.getpid(), 9)
            except FileExistsError:
                pass
            yield from data

        with LocalSparkSession(parallelism=6, num_workers=2) as s:
            df, _ = _ls_features_df(s, rows=36)
            clean = _rows_key(df.mapInArrow(_local_ident(), df.schema).collect())
            out = _rows_key(df.mapInArrow(die_once, df.schema).collect())
        assert out == clean
        d = snap.delta()
        assert d.counter("scheduler.reassign") >= 1
        assert d.counter("worker.respawn") >= 1
        assert d.counter("worker.quarantine") == 0

    def test_crash_loop_slot_quarantined_stage_completes(
        self, monkeypatch, snap
    ):
        """A slot whose every worker dies on arrival trips the circuit
        breaker; the stage finishes (degraded) on the surviving slot
        instead of respawning forever."""
        monkeypatch.setenv("TPU_ML_WORKER_BREAKER_THRESHOLD", "2")
        monkeypatch.setenv("TPU_ML_WORKER_RESPAWN_BACKOFF_S", "0.01")

        def die_on_slot0(batches):
            import os as wos

            data = list(batches)
            if wos.environ.get("TPU_ML_WORKER_SLOT") == "0":
                wos._exit(113)
            yield from data

        with LocalSparkSession(parallelism=6, num_workers=2) as s:
            df, _ = _ls_features_df(s, rows=36)
            out = _rows_key(df.mapInArrow(die_on_slot0, df.schema).collect())
            clean = _rows_key(df.mapInArrow(_local_ident(), df.schema).collect())
            assert out == clean
            assert s._supervisor.quarantined_slots() == [0]
            assert s._supervisor.summary()["leases"]["0"]["quarantined"]
        d = snap.delta()
        assert d.counter("worker.quarantine", slot="0") == 1
        assert d.counter("scheduler.reassign") >= 2

    def test_straggler_hedge_is_deterministic(self, monkeypatch, snap):
        """Each worker's 2nd task hangs 1s: with hedging on, an idle slot
        duplicates the straggler and the first result wins; results are
        bit-identical with hedging on, off, and with no fault at all."""
        # each worker process hangs on its 3rd task: occurrence 1 is the
        # warm-up below, 2 is the stage's seeded partition, 3 is the
        # straggler (primary on one worker, its hedge twin on the other)
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "worker.task:hang:3:1.0")
        monkeypatch.setenv("TPU_ML_HEDGE_FLOOR_S", "0.05")

        def run(factor):
            with LocalSparkSession(parallelism=3, num_workers=2) as s:
                # warm both workers first (hedging off, one seeded task
                # each) so the measured p50 reflects task time, not the
                # 1s worker spawn — the hedge threshold must see the hang
                # as a straggler, not as a normal first-task latency
                monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "0")
                warm, _ = _ls_features_df(s, rows=8, partitions=2)
                warm.mapInArrow(_local_ident(), warm.schema).collect()
                monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", factor)
                df, _ = _ls_features_df(s, rows=30)
                return _rows_key(
                    df.mapInArrow(_local_ident(), df.schema).collect()
                )

        hedged = run("2.0")
        assert snap.delta().counter("scheduler.hedge") >= 1

        s1 = REGISTRY.snapshot()
        unhedged = run("0")
        assert REGISTRY.snapshot().delta(s1).counter("scheduler.hedge") == 0

        monkeypatch.delenv(faults.FAULT_PLAN_VAR)
        clean = run("0")
        assert hedged == unhedged == clean

    def test_barrier_epoch_retry_after_rank_preemption(
        self, monkeypatch, snap
    ):
        """A preempted rank dooms the barrier epoch; the stage retries the
        WHOLE round with fresh workers and matches the clean result."""
        with LocalSparkSession(parallelism=3) as s:
            df, _ = _ls_features_df(s, rows=30, partitions=3)
            clean = _rows_key(
                df.mapInArrow(_local_ident(), df.schema, barrier=True).collect()
            )
            monkeypatch.setenv(faults.FAULT_PLAN_VAR, "scheduler.rank:preempt:2")
            retried = _rows_key(
                df.mapInArrow(_local_ident(), df.schema, barrier=True).collect()
            )
        assert retried == clean
        d = snap.delta()
        assert d.counter("scheduler.barrier_retry") == 1
        assert (
            d.counter("fault.injected", site="scheduler.rank", kind="preempt")
            == 1
        )

    def test_barrier_failure_leaves_no_workers_or_dirs(self, monkeypatch):
        """Retries exhausted: the epoch's failure must still tear down every
        rank worker and remove the rendezvous scratch dir (try/finally —
        the old path leaked both on a failed rank)."""
        import tempfile

        def _barrier_dirs():
            return {
                n
                for n in os.listdir(tempfile.gettempdir())
                if n.startswith("localspark-barrier-")
            }

        def _live_children():
            me, kids = str(os.getpid()), set()
            for pid in os.listdir("/proc"):
                if not pid.isdigit():
                    continue
                try:
                    with open(f"/proc/{pid}/stat", "rb") as f:
                        raw = f.read()
                    # parse after the parenthesized comm (may hold spaces)
                    state, ppid = raw[raw.rindex(b")") + 2:].split()[:2]
                    if ppid == me.encode() and state != b"Z":
                        kids.add(int(pid))
                except (OSError, ValueError):
                    continue
            return kids

        monkeypatch.setenv("TPU_ML_BARRIER_RETRIES", "0")
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "scheduler.rank:preempt:1")
        dirs0, kids0 = _barrier_dirs(), _live_children()
        with LocalSparkSession(parallelism=3) as s:
            df, _ = _ls_features_df(s, rows=12, partitions=3)
            with pytest.raises(faults.InjectedPreemption):
                df.mapInArrow(_local_ident(), df.schema, barrier=True).collect()
        assert _barrier_dirs() == dirs0
        assert _live_children() - kids0 == set()


class TestAdmissionControl:
    """begin_fit consults the health monitor: a FAILING component refuses
    the fit under the default policy, or admits it CPU-degraded under
    ``TPU_ML_ADMISSION_POLICY=degrade`` — decision stamped on the report."""

    @pytest.fixture(autouse=True)
    def _monitor_lifecycle(self):
        from spark_rapids_ml_tpu.telemetry import health

        health.stop_monitor(timeout=10.0)
        yield
        health.stop_monitor(timeout=10.0)

    def _wedge_monitor(self):
        from spark_rapids_ml_tpu.telemetry import health

        health.start_monitor(
            interval_s=3600.0,
            probe_mode="inline",
            probe_fn=lambda: (False, "injected transport wedge"),
            failing_after=1,
        ).poll_once()

    def test_failing_health_refuses_fit_by_default(self, data, snap):
        from spark_rapids_ml_tpu.telemetry import health

        self._wedge_monitor()
        x, _ = data
        with pytest.raises(
            health.AdmissionRefused, match="refused by admission control"
        ):
            PCA().setInputCol("f").setK(3).fit(x)
        assert snap.delta().counter("scheduler.admission", action="refuse") == 1

    def test_degrade_policy_admits_and_stamps_report(
        self, data, monkeypatch, snap
    ):
        monkeypatch.setenv("TPU_ML_ADMISSION_POLICY", "degrade")
        self._wedge_monitor()
        x, _ = data
        model = PCA().setInputCol("f").setK(3).fit(x)
        rep = model.fit_report
        assert rep.admission["action"] == "degrade"
        assert rep.admission["health_state"] == "FAILING"
        assert "injected transport wedge" in rep.admission["reason"]
        assert snap.delta().counter("scheduler.admission", action="degrade") == 1

    def test_healthy_monitor_admits_plainly(self, data):
        from spark_rapids_ml_tpu.telemetry import health

        health.start_monitor(
            interval_s=3600.0,
            probe_mode="inline",
            probe_fn=lambda: (True, "ok"),
        ).poll_once()
        x, _ = data
        model = PCA().setInputCol("f").setK(3).fit(x)
        assert model.fit_report.admission["action"] == "admit"

    def test_no_monitor_means_no_gatekeeping(self, data):
        x, _ = data
        model = PCA().setInputCol("f").setK(3).fit(x)
        adm = model.fit_report.admission
        assert adm["action"] == "admit"
        assert "no health evidence" in adm["reason"]


# -- serving plane: hot-swap, refresh, rollback (ISSUE-18) -------------------
# invariant under every fault below: the registry ends on exactly ONE
# consistent serving version — never a torn slot, never a client-visible
# wrong answer


def _fit_lin_pair():
    """Live model + a genuinely different candidate (flipped target)."""
    rng = np.random.default_rng(41)
    x = rng.normal(size=(128, 6))
    y = x @ np.arange(1.0, 7.0)
    return (
        x,
        LinearRegression().fit((x, y)),
        LinearRegression().fit((x, -y)),
    )


class TestServingSwapChaos:
    @pytest.fixture(autouse=True)
    def serve_clean(self):
        yield
        from spark_rapids_ml_tpu.serving import client as client_mod
        from spark_rapids_ml_tpu.serving import registry as registry_mod
        from spark_rapids_ml_tpu.serving import server as server_mod

        client_mod.reset_client()
        server_mod.stop_serving(stop_monitor=False)
        registry_mod.reset_for_tests()

    def test_swap_barrier_fault_never_tears_the_slot(self, monkeypatch, snap):
        """An I/O fault at the serve.swap barrier lands strictly before
        the publish: the old version keeps serving bitwise, and the
        retried swap completes cleanly."""
        from spark_rapids_ml_tpu.serving import registry as registry_mod

        x, old, new = _fit_lin_pair()
        reg = registry_mod.get_registry()
        reg.register("lin", old, bucket_list=(8, 16))
        out_old = reg.predict("lin", x[:8])
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "serve.swap:io:1")
        faults.reset_faults()
        with pytest.raises(faults.InjectedTransientIOError):
            reg.swap("lin", new, tolerance=100.0)
        assert reg.current_version("lin") == 1
        assert np.array_equal(reg.predict("lin", x[:8]), out_old)
        d = snap.delta()
        assert d.counter("fault.injected", site="serve.swap", kind="io") == 1
        assert d.counter("serve.swaps") == 0
        assert d.hist("serve.swap_blackout_seconds").count == 0
        # the nth-occurrence plan is spent: the retry publishes v2
        entry = reg.swap("lin", new, tolerance=100.0)
        assert entry.version == 2
        d = snap.delta()
        assert d.counter("serve.swaps") == 1
        assert d.hist("serve.swap_blackout_seconds").count == 1

    def test_swap_hang_does_not_extend_the_blackout(self, monkeypatch, snap):
        """A hang at the barrier delays the swap, not the serving plane:
        the blackout (lock-hold) stays tiny because every slow step sits
        outside the atomic section."""
        from spark_rapids_ml_tpu.serving import registry as registry_mod

        x, old, new = _fit_lin_pair()
        reg = registry_mod.get_registry()
        reg.register("lin", old, bucket_list=(8,))
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "serve.swap:hang:1:0.3")
        faults.reset_faults()
        entry = reg.swap("lin", new, tolerance=100.0)
        assert entry.version == 2
        d = snap.delta()
        assert d.counter("fault.injected", site="serve.swap", kind="hang") == 1
        black = d.hist("serve.swap_blackout_seconds")
        assert black.count == 1
        # the 0.3s hang fired pre-publish; the publish itself stayed fast
        assert black.total < 0.25

    def test_dispatch_fault_is_one_request_not_a_torn_slot(
        self, monkeypatch, snap
    ):
        from spark_rapids_ml_tpu.serving import registry as registry_mod

        x, old, _ = _fit_lin_pair()
        reg = registry_mod.get_registry()
        reg.register("lin", old, bucket_list=(8,))
        out = reg.predict("lin", x[:8])
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "serve.dispatch:io:1")
        faults.reset_faults()
        with pytest.raises(faults.InjectedTransientIOError):
            reg.predict("lin", x[:8])
        # the very next request serves the same consistent version
        assert np.array_equal(reg.predict("lin", x[:8]), out)
        assert reg.current_version("lin") == 1
        d = snap.delta()
        assert d.counter("fault.injected", site="serve.dispatch", kind="io") == 1


class TestRefreshChaos:
    @pytest.fixture(autouse=True)
    def serve_clean(self):
        yield
        from spark_rapids_ml_tpu.serving import client as client_mod
        from spark_rapids_ml_tpu.serving import registry as registry_mod
        from spark_rapids_ml_tpu.serving import server as server_mod

        client_mod.reset_client()
        server_mod.stop_serving(stop_monitor=False)
        registry_mod.reset_for_tests()

    @staticmethod
    def _delta(n: int, seed: int, flip: float = 1.0):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(n, 6))
        return x, flip * (x @ np.arange(1.0, 7.0))

    def test_fold_fault_leaves_carry_retryable(self, monkeypatch, snap):
        """An injected fold failure consumes nothing: the carry and the
        pending-row count are untouched, and refolding the same delta
        finalizes bitwise with the never-faulted oracle."""
        from spark_rapids_ml_tpu.models.incremental import (
            IncrementalLinearRegression,
        )
        from spark_rapids_ml_tpu.refresh import RefreshDaemon

        d = RefreshDaemon(
            "lr", IncrementalLinearRegression(), min_rows=1, shadow_rows=0
        )
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "refresh.fold:io:1")
        faults.reset_faults()
        with pytest.raises(faults.InjectedTransientIOError):
            d.fold(self._delta(64, 0))
        assert d.rows_pending == 0
        d.fold(self._delta(64, 0))
        oracle = IncrementalLinearRegression().partial_fit(self._delta(64, 0))
        assert np.array_equal(
            np.asarray(d.estimator.finalize().coefficients),
            np.asarray(oracle.finalize().coefficients),
        )
        dlt = snap.delta()
        assert dlt.counter("fault.injected", site="refresh.fold", kind="io") == 1
        assert dlt.counter("refresh.folds") == 1

    def test_checkpoint_fault_keeps_previous_durable_step(
        self, monkeypatch, tmp_path, snap
    ):
        from spark_rapids_ml_tpu.models.incremental import (
            IncrementalLinearRegression,
        )
        from spark_rapids_ml_tpu.refresh import RefreshDaemon

        d = RefreshDaemon(
            "lr", IncrementalLinearRegression(),
            checkpoint_dir=str(tmp_path), min_rows=1, shadow_rows=0,
        )
        d.fold(self._delta(64, 0))
        assert d.checkpoint() == 1
        d.fold(self._delta(32, 1))
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "refresh.checkpoint:io:1")
        faults.reset_faults()
        with pytest.raises(faults.InjectedTransientIOError):
            d.checkpoint()
        # step 1 is still the durable truth, readable and complete
        step, arrays, state = d.checkpointer.latest()
        assert step == 1 and state["rows_pending"] == 64
        # and the spent plan lets the next checkpoint land as step 2
        assert d.checkpoint() == 2
        assert d.checkpointer.latest()[2]["rows_pending"] == 96
        assert snap.delta().counter(
            "fault.injected", site="refresh.checkpoint", kind="io"
        ) == 1

    def test_corrupt_checkpoint_refuses_swap_old_keeps_serving(
        self, tmp_path, snap
    ):
        """A truncated checkpoint must not produce a candidate: resume
        skips the unreadable step, the min-rows floor refuses the swap,
        and the registered version keeps serving untouched."""
        from spark_rapids_ml_tpu.models.incremental import (
            IncrementalLinearRegression,
        )
        from spark_rapids_ml_tpu.refresh import RefreshDaemon
        from spark_rapids_ml_tpu.serving import registry as registry_mod

        reg = registry_mod.get_registry()
        ckdir = str(tmp_path)
        d1 = RefreshDaemon(
            "lr", IncrementalLinearRegression(),
            checkpoint_dir=ckdir, min_rows=32, shadow_rows=0,
        )
        d1.fold(self._delta(64, 0))
        assert d1.try_swap()["status"] == "registered"
        x_probe = self._delta(8, 9)[0]
        out_v1 = reg.predict("lr", x_probe)
        # the delta folds and checkpoints... then the file is truncated
        d1.fold(self._delta(64, 1))
        step = d1.checkpoint()
        npz = os.path.join(
            ckdir, f"step-{step:09d}", "arrays.npz"
        )
        with open(npz, "r+b") as f:
            f.truncate(16)
        # the daemon restarts: nothing durable is readable, so it comes
        # back empty and the swap gate refuses on the min-rows floor
        d2 = RefreshDaemon(
            "lr", IncrementalLinearRegression(),
            checkpoint_dir=ckdir, min_rows=32, shadow_rows=0,
        )
        assert d2.resume() is False
        res = d2.try_swap()
        assert res["status"] == "waiting" and res["rows_pending"] == 0
        assert reg.current_version("lr") == 1
        assert np.array_equal(reg.predict("lr", x_probe), out_v1)
        dlt = snap.delta()
        assert dlt.counter("serve.swaps") == 0
        assert dlt.counter("refresh.resumes") == 0

    def test_post_swap_latency_burn_rolls_back(self, monkeypatch, snap):
        """The headline closed-loop contract: a latency burn on live
        post-swap traffic fires the probation SLO, the daemon rolls back
        to the HBM-retained prior, and serving resumes bitwise on the old
        version — all under load, no process restart."""
        from spark_rapids_ml_tpu.models.incremental import (
            IncrementalLinearRegression,
        )
        from spark_rapids_ml_tpu.refresh import RefreshDaemon
        from spark_rapids_ml_tpu.serving import client as client_mod
        from spark_rapids_ml_tpu.serving import registry as registry_mod

        reg = registry_mod.get_registry()
        d = RefreshDaemon(
            "lr", IncrementalLinearRegression(),
            min_rows=1, shadow_rows=0, tolerance=100.0,
            probation_s=3600.0, probation_burn=1,
            probation_slo="serve.latency:p99:0.05",
        )
        d.fold(self._delta(64, 0))
        assert d.try_swap()["status"] == "registered"
        x_probe = self._delta(8, 9)[0]
        out_v1 = reg.predict("lr", x_probe)
        d.fold(self._delta(64, 1, flip=-1.0))
        assert d.try_swap()["status"] == "swapped"
        assert reg.current_version("lr") == 2
        # live post-swap traffic through the in-process serve path, with
        # an injected hang on every dispatch: the p99 burns the 50ms SLO
        monkeypatch.setenv(
            faults.FAULT_PLAN_VAR,
            ",".join(f"serve.dispatch:hang:{i}:0.12" for i in range(1, 4)),
        )
        faults.reset_faults()
        for _ in range(3):
            client_mod.predict("lr", x_probe)
        res = d.probation_check()
        assert res["status"] == "rolled_back"
        assert res["from_version"] == 2 and res["version"] == 1
        assert reg.current_version("lr") == 1
        assert np.array_equal(reg.predict("lr", x_probe), out_v1)
        dlt = snap.delta()
        assert dlt.counter("serve.rollback") == 1
        assert dlt.counter(
            "fault.injected", site="serve.dispatch", kind="hang"
        ) == 3

    def test_healthy_probation_promotes_under_load(self, snap):
        """The control case for the burn test: identical swap, healthy
        latency, the deadline promotes and the prior is released."""
        from spark_rapids_ml_tpu.models.incremental import (
            IncrementalLinearRegression,
        )
        from spark_rapids_ml_tpu.refresh import RefreshDaemon
        from spark_rapids_ml_tpu.serving import client as client_mod
        from spark_rapids_ml_tpu.serving import registry as registry_mod

        reg = registry_mod.get_registry()
        d = RefreshDaemon(
            "lr", IncrementalLinearRegression(),
            min_rows=1, shadow_rows=0, tolerance=100.0,
            probation_s=0.0, probation_slo="serve.latency:p99:10",
        )
        d.fold(self._delta(64, 0))
        d.try_swap()
        d.fold(self._delta(64, 1))
        assert d.try_swap()["status"] == "swapped"
        x_probe = self._delta(8, 9)[0]
        for _ in range(3):
            client_mod.predict("lr", x_probe)
        assert d.probation_check()["status"] == "promoted"
        assert reg.current_version("lr") == 2
        assert reg.prior_entry("lr") is None
        assert snap.delta().counter("serve.rollback") == 0


class TestFleetSwapChaos:
    """Fleet-wide hot-swap propagation under a replica kill: the rolling
    walk converges every replica to the new version with ZERO failed
    client requests, and every response is attributable to exactly one
    version (old or new) — never a torn mix."""

    @staticmethod
    def _read_exact(rf, n: int) -> bytes:
        chunks = []
        while n > 0:
            chunk = rf.read(n)
            assert chunk, "peer closed mid-frame"
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _fast_call(self, sock, rf, model, x32):
        from spark_rapids_ml_tpu.serving import fastlane

        sock.sendall(fastlane.pack_request(model, x32))
        return fastlane.read_response(lambda n: self._read_exact(rf, n))

    def test_replica_killed_mid_swap_zero_failed_requests(
        self, tmp_path, snap
    ):
        import socket
        import threading

        from spark_rapids_ml_tpu.serving import fleet as fleet_mod

        rng = np.random.default_rng(41)
        xf = rng.normal(size=(128, 6))
        yf = xf @ np.arange(1.0, 7.0)
        old = LinearRegression().fit((xf, yf))
        new = LinearRegression().fit((xf, -yf))
        x32 = np.ascontiguousarray(xf[:4], dtype="<f4")
        want_old = np.asarray(old.transform(x32)).ravel()
        want_new = np.asarray(new.transform(x32)).ravel()

        fleet = fleet_mod.ServeFleet(
            {"lin": old},
            replicas=3,
            socket_dir=str(tmp_path / "sock"),
            bucket_list=(8,),
            extra_env={
                "TPU_ML_SERVE_COMPILE_CACHE_DIR": str(tmp_path / "cache")
            },
        ).start()
        stop = threading.Event()
        failures: list[Exception] = []
        responses: list[np.ndarray] = []

        def hammer():
            try:
                with socket.socket(socket.AF_UNIX) as s:
                    s.connect(fleet.router_path)
                    rf = s.makefile("rb")
                    while not stop.is_set():
                        responses.append(
                            self._fast_call(s, rf, "lin", x32)
                        )
            except Exception as e:  # noqa: BLE001 — collected + asserted
                failures.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(2)]
        try:
            for t in threads:
                t.start()
            # SIGKILL the last-walked slot 0.15s into the rolling swap:
            # the walk is still respawning slot 0 (seconds), so the kill
            # lands squarely mid-swap on a not-yet-swapped replica
            victim = fleet._supervisor._slots[2].worker
            killer = threading.Timer(0.15, victim.proc.kill)
            killer.start()
            ok = fleet.swap_models({"lin": new})
            killer.join()
            assert ok, "a replica never came back READY on the new spec"
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        try:
            assert victim.proc.poll() is not None, "the kill never landed"
            assert not failures, (
                f"client requests failed during the killed swap: "
                f"{failures[:3]}"
            )
            assert len(responses) > 0
            # every response is exactly one version's answer — never torn
            n_old = n_new = 0
            for r in responses:
                flat = np.asarray(r, dtype=np.float64).ravel()
                if np.allclose(flat, want_old, rtol=1e-4, atol=1e-4):
                    n_old += 1
                elif np.allclose(flat, want_new, rtol=1e-4, atol=1e-4):
                    n_new += 1
                else:
                    raise AssertionError(
                        f"response matches neither version: {flat[:4]}"
                    )
            assert n_old > 0, "no pre-swap traffic observed"
            # after the walk every replica serves the NEW version only
            assert fleet.live_replicas() == 3
            with socket.socket(socket.AF_UNIX) as s:
                s.connect(fleet.router_path)
                rf = s.makefile("rb")
                for _ in range(6):
                    final = np.asarray(
                        self._fast_call(s, rf, "lin", x32), np.float64
                    ).ravel()
                    assert np.allclose(
                        final, want_new, rtol=1e-4, atol=1e-4
                    )
            d = snap.delta()
            assert d.counter("serve.replica_restarts") >= 3
            assert d.counter("serve.drain_events") >= 3
        finally:
            fleet.stop()


class TestFleetTraceChaos:
    """Trace stitching under fleet chaos: a replica SIGKILLed with
    requests in flight yields exactly one complete trace per retried
    request — carrying the router's silent-retry marker — and a rolling
    restart mid-window loses no spans: the merged fleet stream stitches
    with zero orphans."""

    @staticmethod
    def _read_exact(rf, n: int) -> bytes:
        chunks = []
        while n > 0:
            chunk = rf.read(n)
            assert chunk, "peer closed mid-frame"
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _fast_call(self, sock, rf, model, x32):
        from spark_rapids_ml_tpu.serving import fastlane

        sock.sendall(fastlane.pack_request(model, x32))
        return fastlane.read_response(lambda n: self._read_exact(rf, n))

    def _spawn_fleet(self, tmp_path, sample: str):
        from spark_rapids_ml_tpu.serving import fleet as fleet_mod

        rng = np.random.default_rng(43)
        xf = rng.normal(size=(96, 6))
        lin = LinearRegression().fit((xf, xf @ np.arange(1.0, 7.0)))
        fleet = fleet_mod.ServeFleet(
            {"lin": lin},
            replicas=2,
            socket_dir=str(tmp_path / "sock"),
            bucket_list=(8,),
            extra_env={
                "TPU_ML_SERVE_COMPILE_CACHE_DIR": str(tmp_path / "cache"),
                "TPU_ML_TRACE_SAMPLE": sample,
            },
        ).start()
        x32 = np.ascontiguousarray(xf[:4], dtype="<f4")
        return fleet, x32

    def _hammer(self, fleet, x32, stop, failures, done):
        import socket

        try:
            with socket.socket(socket.AF_UNIX) as s:
                s.connect(fleet.router_path)
                rf = s.makefile("rb")
                while not stop.is_set():
                    self._fast_call(s, rf, "lin", x32)
                    done[0] += 1
        except Exception as e:  # noqa: BLE001 — collected + asserted
            failures.append(e)

    def test_replica_kill_mid_request_one_complete_trace_with_retry(
        self, tmp_path, monkeypatch
    ):
        import threading
        import time

        from spark_rapids_ml_tpu.serving import fleet as fleet_mod
        from spark_rapids_ml_tpu.telemetry import tracectx

        monkeypatch.setenv("TPU_ML_TRACE_SAMPLE", "1.0")
        fleet, x32 = self._spawn_fleet(tmp_path, "1.0")
        stop = threading.Event()
        failures: list[Exception] = []
        done = [0]
        threads = [
            threading.Thread(
                target=self._hammer, args=(fleet, x32, stop, failures, done)
            )
            for _ in range(3)
        ]
        try:
            for t in threads:
                t.start()
            # let traffic flow, then SIGKILL the home replica — the
            # hammer keeps requests in flight, so the kill lands
            # mid-request and the router's silent retry must re-route
            deadline = time.monotonic() + 10
            while done[0] < 20 and time.monotonic() < deadline:
                time.sleep(0.01)
            home = fleet.ring.preference(
                fleet_mod.HashRing.key("lin", 8)
            )[0]
            fleet._supervisor._slots[home].worker.proc.kill()
            want = done[0] + 50
            deadline = time.monotonic() + 10
            while done[0] < want and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        try:
            assert not failures, (
                f"clients saw failures across the kill: {failures[:3]}"
            )
            events = fleet.fleet_events()
            retries = [
                e for e in events
                if e.get("name") == "retry"
                and (e.get("args") or {}).get("trace_id")
            ]
            assert retries, (
                "the kill never exercised the router's silent retry"
            )
            traces = tracectx.stitch_all(events)
            for inst in retries:
                tid = inst["args"]["trace_id"]
                t = traces.get(tid)
                assert t is not None and t["complete"], (
                    f"retried trace {tid} did not stitch complete"
                )
                relays = [
                    s for s in t["spans"]
                    if s.get("name") == "serve.relay"
                ]
                reqs = [
                    s for s in t["spans"]
                    if s.get("name") == "serve.request"
                ]
                # exactly one client-visible relay — the retry re-routed
                # inside it, it did not fork a second trace
                assert len(relays) == 1
                assert reqs, (
                    "retried trace has no replica-side request span"
                )
                assert any(
                    i.get("name") == "retry" for i in t["instants"]
                )
            # the un-respawned victim leaves the fleet rollup down
            assert fleet.healthz()["status"] == "down"
        finally:
            fleet.stop()

    def test_rolling_restart_mid_window_stitches_zero_orphans(
        self, tmp_path, monkeypatch
    ):
        import threading

        from spark_rapids_ml_tpu.telemetry import tracectx
        from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE

        # sample down so a multi-thousand-request window cannot evict a
        # trace's parent spans from the bounded flight-recorder rings —
        # the same discipline the bench fleet stage uses
        monkeypatch.setenv("TPU_ML_TRACE_SAMPLE", "0.02")
        fleet, x32 = self._spawn_fleet(tmp_path, "0.02")
        seq0 = TIMELINE.seq()
        stop = threading.Event()
        failures: list[Exception] = []
        done = [0]
        threads = [
            threading.Thread(
                target=self._hammer, args=(fleet, x32, stop, failures, done)
            )
            for _ in range(3)
        ]
        try:
            for t in threads:
                t.start()
            try:
                for slot in (0, 1):
                    assert fleet.restart_replica(slot), (
                        f"replica {slot} respawn never became READY"
                    )
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=30)
            assert not failures, (
                f"requests failed during rolling restart: {failures[:3]}"
            )
            assert done[0] > 0
            # scope the router's bounded ring to this window; replica
            # processes (and their harvested trailers) are all fresh
            pid_self = os.getpid()
            events = [
                e for e in fleet.fleet_events()
                if e.get("pid") != pid_self or e.get("seq", 0) > seq0
            ]
            cov = tracectx.coverage(events)
            assert cov["traces"] > 0, "no sampled traces in the window"
            assert cov["orphan_spans"] == 0, (
                f"rolling restart orphaned spans: {cov}"
            )
            assert cov["coverage"] >= 0.99, (
                f"stitching coverage regressed across the restart: {cov}"
            )
        finally:
            fleet.stop()
