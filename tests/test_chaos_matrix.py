"""Chaos fault-matrix: every injected fault class against the streamed fit,
asserting BOTH recovery (parity with the clean result) and the telemetry
trail (injection + recovery counters). Deterministic — the TPU_ML_FAULT_PLAN
nth-occurrence grammar always fails the same call — so these run in tier-1,
not behind the slow marker.

Matrix: device OOM (chunk bisection), transient I/O (retry-in-place), hang
(bounded fold.wait + FoldHangTimeout diagnosis), preemption (durable
checkpoint + bitwise resume), non-finite rows (raise/skip policy),
collective blips (finalize retry), device-init failure (CPU degradation).
"""

import jax
import numpy as np
import pytest

from spark_rapids_ml_tpu.models.linear import LinearRegression
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.resilience import retry as R
from spark_rapids_ml_tpu.spark import ingest
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer
from spark_rapids_ml_tpu.utils.config import get_config, set_config

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_faults(monkeypatch):
    """No plan leaks in (from the env) or out (to later tests)."""
    monkeypatch.delenv(faults.FAULT_PLAN_VAR, raising=False)
    faults.reset_faults()
    yield
    faults.reset_faults()


@pytest.fixture
def snap():
    """Telemetry delta for the test body: ``snap.delta()`` -> counters."""
    s0 = REGISTRY.snapshot()

    class _Snap:
        @staticmethod
        def delta():
            return REGISTRY.snapshot().delta(s0)

    return _Snap


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(23)
    x = np.asarray(rng.normal(size=(1100, 12)), np.float64)
    coef = rng.normal(size=12)
    y = x @ coef + 0.05 * rng.normal(size=1100)
    return x, y


def _gram_stream(x, plan=None, monkeypatch=None, **kw):
    if plan is not None:
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, plan)
    return ingest.stream_fold(
        iter(np.array_split(x, 4)),
        L.gram_fold_step(),
        n=x.shape[1],
        init=L.init_gram_carry(x.shape[1], x.dtype),
        rows=len(x),
        chunk_rows=128,
        **kw,
    )


def _assert_gram_equal(carry, x):
    import jax.numpy as jnp

    want = L.gram_stats(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(carry.xtx), np.asarray(want.xtx), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(carry.col_sum), np.asarray(want.col_sum), rtol=1e-12
    )
    assert float(carry.count) == float(len(x))


class TestOOMBisection:
    def test_oom_bisects_chunk_and_stays_exact(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(x, "fold.dispatch:oom:3", monkeypatch)
        assert res.bisections >= 1
        assert res.rows == 1100
        _assert_gram_equal(res.carry, x)
        d = snap.delta()
        assert d.counter("fault.injected", site="fold.dispatch", kind="oom") == 1
        assert d.counter("chunk.bisections") == res.bisections

    def test_bisection_respects_floor(self, data, monkeypatch):
        """Every dispatch OOMs: the bisection floor turns an un-shrinkable
        OOM into the original error instead of an infinite loop."""
        x, _ = data
        plan = ",".join(f"fold.dispatch:oom:{i}" for i in range(1, 40))
        with pytest.raises(faults.InjectedResourceExhausted):
            _gram_stream(x, plan, monkeypatch, min_chunk_rows=64)


class TestTransientRetry:
    def test_ingest_io_retried(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(x, "ingest.chunk:io:2", monkeypatch)
        _assert_gram_equal(res.carry, x)
        d = snap.delta()
        assert d.counter("fault.injected", site="ingest.chunk", kind="io") == 1
        assert d.counter("retry.attempts", site="ingest.chunk") == 1

    def test_dispatch_io_retried(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(x, "fold.dispatch:io:4", monkeypatch)
        _assert_gram_equal(res.carry, x)
        assert snap.delta().counter("retry.attempts", site="fold.dispatch") == 1

    def test_transient_budget_exhaustion_raises(self, data, monkeypatch):
        plan = ",".join(f"ingest.chunk:io:{i}" for i in range(1, 30))
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        with pytest.raises(faults.InjectedTransientIOError):
            _gram_stream(x := data[0], plan, monkeypatch)


class TestHangBound:
    def test_hang_within_bound_completes(self, data, monkeypatch):
        x, _ = data
        res = _gram_stream(
            x, "fold.wait:hang:1:0.1", monkeypatch, fold_wait_timeout_s=30.0
        )
        _assert_gram_equal(res.carry, x)

    def test_hang_beyond_bound_diagnosed(self, data, monkeypatch):
        x, _ = data
        with pytest.raises(R.FoldHangTimeout, match="hung, not slow"):
            _gram_stream(
                x, "fold.wait:hang:1:3.0", monkeypatch, fold_wait_timeout_s=0.3
            )

    def test_hang_timeout_classified_poisoned(self):
        assert R.classify(R.FoldHangTimeout("x")) is R.ErrorClass.POISONED


class TestPreemptResume:
    def test_preempted_stream_resumes_bitwise(self, data, monkeypatch, tmp_path, snap):
        x, _ = data
        clean = _gram_stream(x)
        ckpt = TrainingCheckpointer(tmp_path / "ck")
        # chunks 1-5 fold; checkpoints land after chunks 2 and 4; the 6th
        # dispatch dies like a preempted process would
        with pytest.raises(faults.InjectedPreemption):
            _gram_stream(
                x, "fold.dispatch:preempt:6", monkeypatch,
                checkpointer=ckpt, checkpoint_every=2,
            )
        assert snap.delta().counter("stream.checkpoints") == 2
        monkeypatch.delenv(faults.FAULT_PLAN_VAR)
        res = _gram_stream(x, checkpointer=ckpt, checkpoint_every=2)
        assert res.resumed
        assert res.chunks == clean.chunks
        assert snap.delta().counter("stream.resumes") == 1
        # bitwise: the resumed accumulator path must reproduce the clean run
        np.testing.assert_array_equal(
            np.asarray(res.carry.xtx), np.asarray(clean.carry.xtx)
        )
        np.testing.assert_array_equal(
            np.asarray(res.carry.col_sum), np.asarray(clean.carry.col_sum)
        )
        assert float(res.carry.count) == float(clean.carry.count)

    def test_preemption_never_retried_in_process(self):
        calls = {"n": 0}

        def die():
            calls["n"] += 1
            raise faults.InjectedPreemption("gone")

        with pytest.raises(faults.InjectedPreemption):
            R.call_with_retry(die, policy=R.RetryPolicy(max_attempts=5))
        assert calls["n"] == 1


class TestNonFinitePolicy:
    def test_raise_policy_fails_loudly(self, data, monkeypatch):
        x = data[0].copy()
        x[7, 3] = np.nan
        with pytest.raises(ValueError, match="non-finite"):
            _gram_stream(x, nonfinite="raise")

    def test_skip_policy_drops_counts_and_matches(self, data, snap):
        x = data[0].copy()
        bad_rows = [7, 500, 1099]
        for i in bad_rows:
            x[i, i % 12] = np.inf if i % 2 else np.nan
        res = _gram_stream(x, nonfinite="skip")
        assert res.skipped_rows == len(bad_rows)
        assert res.rows == len(x) - len(bad_rows)
        _assert_gram_equal(res.carry, np.delete(x, bad_rows, axis=0))
        assert snap.delta().counter("rows.nonfinite_skipped") == len(bad_rows)

    def test_injected_corruption_skipped(self, data, monkeypatch, snap):
        x, _ = data
        res = _gram_stream(
            x, "ingest.chunk:nonfinite:1", monkeypatch, nonfinite="skip"
        )
        assert res.skipped_rows == 1
        _assert_gram_equal(res.carry, x[1:])  # first row of first pull corrupted
        d = snap.delta()
        assert d.counter("fault.injected", site="ingest.chunk", kind="nonfinite") == 1

    def test_allow_policy_skips_the_scan(self, data):
        x = data[0].copy()
        x[3, 3] = np.nan
        res = _gram_stream(x, nonfinite="allow")
        assert res.skipped_rows == 0
        assert not np.isfinite(np.asarray(res.carry.xtx)).all()


class TestCollectiveRetry:
    def test_finalize_retries_transient(self, data, monkeypatch, snap):
        from spark_rapids_ml_tpu.parallel import gram as G
        from spark_rapids_ml_tpu.parallel import mesh as M

        x, _ = data
        mesh = M.create_mesh()
        example = L.GramStats(
            xtx=jax.ShapeDtypeStruct((12, 12), np.float64),
            col_sum=jax.ShapeDtypeStruct((12,), np.float64),
            count=jax.ShapeDtypeStruct((), np.float64),
        )
        res = ingest.stream_fold(
            iter(np.array_split(x, 4)),
            lambda c, xd, wd: G.sharded_gram_fold(c, xd, wd, mesh),
            n=12,
            init=G.init_chunk_carry(example, mesh),
            chunk_rows=G.stream_chunk_rows_for_mesh(mesh),
            put_fn=G.chunk_put(mesh),
        )
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "collective:io:1")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        stats = G.finalize_chunk_fold(res.carry, mesh)
        _assert_gram_equal(stats, x)
        d = snap.delta()
        assert d.counter("fault.injected", site="collective", kind="io") == 1
        assert d.counter("retry.attempts", site="collective") == 1


class TestDeviceInitDegradation:
    def test_nonfatal_init_failure_degrades(self, monkeypatch, snap):
        from spark_rapids_ml_tpu.spark import estimators as E

        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "device.init:io:1")
        assert E._mesh_or_fallback() is None
        assert snap.delta().counter("degraded.cpu_fallback") == 1

    def test_fatal_init_failure_propagates(self, monkeypatch):
        from spark_rapids_ml_tpu.spark import estimators as E

        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "device.init:preempt:1")
        with pytest.raises(faults.InjectedPreemption):
            E._mesh_or_fallback()

    def test_healthy_init_returns_mesh(self):
        from spark_rapids_ml_tpu.spark import estimators as E

        assert E._mesh_or_fallback() is not None


@pytest.fixture
def force_streamed(monkeypatch):
    old = get_config().stream_fit_max_resident_bytes
    monkeypatch.setenv("TPU_ML_STREAM_CHUNK_ROWS", "128")
    set_config(stream_fit_max_resident_bytes=1)
    yield
    set_config(stream_fit_max_resident_bytes=old)


class TestEstimatorChaosParity:
    """Whole-fit chaos: streamed PCA / LinearRegression under injection
    complete with parity against the clean model, and the per-fit telemetry
    records the injection and the recovery."""

    def test_pca_streamed_fit_under_faults(self, data, monkeypatch, force_streamed, snap):
        x, _ = data
        est = PCA().setInputCol("f").setK(4)
        clean = est.fit(x, num_partitions=3)
        monkeypatch.setenv(
            faults.FAULT_PLAN_VAR, "ingest.chunk:io:1,fold.dispatch:oom:5"
        )
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        m = est.fit(x, num_partitions=3)
        cos = np.abs(np.sum(clean.pc * m.pc, axis=0))
        assert cos.min() >= 0.9999, cos
        d = snap.delta()
        assert d.counter("fault.injected") == 2
        assert d.counter("retry.attempts") >= 1
        assert d.counter("chunk.bisections") >= 1

    def test_linreg_streamed_fit_under_faults(self, data, monkeypatch, force_streamed, snap):
        x, y = data
        clean = LinearRegression().fit((x, y), num_partitions=3)
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "fold.dispatch:io:2")
        monkeypatch.setattr(R.time, "sleep", lambda s: None)
        m = LinearRegression().fit((x, y), num_partitions=3)
        np.testing.assert_allclose(m.coefficients, clean.coefficients, atol=1e-9)
        assert abs(m.intercept - clean.intercept) <= 1e-9
        d = snap.delta()
        assert d.counter("fault.injected", site="fold.dispatch", kind="io") == 1
        assert d.counter("retry.attempts", site="fold.dispatch") == 1

    def test_no_plan_means_zero_injections(self, data, force_streamed, snap):
        x, _ = data
        PCA().setInputCol("f").setK(3).fit(x, num_partitions=3)
        assert snap.delta().counter("fault.injected") == 0
