"""Warm-path serving runtime: AOT registry, shape buckets, micro-batching.

Covers the ISSUE-10 acceptance list: after a 2-request warmup per bucket,
50 mixed-size concurrent requests across 2 models produce ZERO new compiles
(asserted via the telemetry compile counters) and every response is bitwise
equal to the eager ``transform()`` result on the unpadded rows; a fresh
process re-registering the same model warms from the persistent XLA cache
(``compile.cache_hits > 0``, no slow lowering); the bucket ladder rounds,
pads and rejects correctly; the micro-batcher coalesces concurrent
same-(model,bucket) requests into one device dispatch; and the HTTP
front-end serves ``/v1/models`` + ``:predict`` with the documented error
codes while keeping the exporter's ``/metrics`` surface alive.
"""

from __future__ import annotations

import importlib.util
import json
import os
import socket
import subprocess
import sys
import time
import types
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from spark_rapids_ml_tpu.serving import buckets
from spark_rapids_ml_tpu.serving import client as client_mod
from spark_rapids_ml_tpu.serving import fastlane
from spark_rapids_ml_tpu.serving import hbm as hbm_mod
from spark_rapids_ml_tpu.serving import registry as registry_mod
from spark_rapids_ml_tpu.serving import server as server_mod
from spark_rapids_ml_tpu.serving.batcher import MicroBatcher
from spark_rapids_ml_tpu.telemetry import tracectx
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BUCKETS = (8, 16, 32, 64)


@pytest.fixture(autouse=True)
def serve_clean():
    yield
    client_mod.reset_client()
    server_mod.stop_serving(stop_monitor=False)
    registry_mod.reset_for_tests()


@pytest.fixture(scope="module")
def fitted_models():
    """One dataset and two fitted models (PCA + linear) shared across the
    serving tests; registration happens per-test against a fresh registry."""
    from spark_rapids_ml_tpu.models.linear import LinearRegression
    from spark_rapids_ml_tpu.models.pca import PCA

    rng = np.random.default_rng(5)
    x = rng.normal(size=(200, 6))
    y = x @ rng.normal(size=6) + 0.5
    pca = PCA().setInputCol("features").setK(3).fit(x)
    lin = LinearRegression().fit((x, y))
    return x, pca, lin


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(port: int, path: str, payload) -> tuple[int, dict]:
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=data,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# -- bucket ladder ----------------------------------------------------------


class TestBuckets:
    @pytest.fixture(autouse=True)
    def _ladder_env(self, monkeypatch):
        monkeypatch.setenv("TPU_ML_SERVE_MIN_BUCKET", "8")
        monkeypatch.setenv("TPU_ML_SERVE_MAX_BATCH_ROWS", "64")

    def test_serve_bucket_rounds_up_to_power_of_two(self):
        assert buckets.serve_bucket(1) == 8
        assert buckets.serve_bucket(8) == 8
        assert buckets.serve_bucket(9) == 16
        assert buckets.serve_bucket(33) == 64
        assert buckets.serve_bucket(64) == 64

    def test_empty_and_oversized_requests_rejected(self):
        with pytest.raises(ValueError, match="at least one row"):
            buckets.serve_bucket(0)
        with pytest.raises(ValueError, match="ladder cap"):
            buckets.serve_bucket(65)

    def test_ladder_enumerates_every_rung(self):
        assert buckets.bucket_ladder() == (8, 16, 32, 64)

    def test_non_power_of_two_knobs_round_up(self, monkeypatch):
        monkeypatch.setenv("TPU_ML_SERVE_MIN_BUCKET", "6")
        monkeypatch.setenv("TPU_ML_SERVE_MAX_BATCH_ROWS", "100")
        assert buckets.min_bucket() == 8
        assert buckets.max_batch_rows() == 128
        assert buckets.bucket_ladder() == (8, 16, 32, 64, 128)

    def test_pad_to_bucket_zero_fills_and_reports_true_rows(self):
        x = np.arange(12.0).reshape(3, 4)
        padded, true_rows = buckets.pad_to_bucket(x)
        assert padded.shape == (8, 4)
        assert true_rows == 3
        assert np.array_equal(padded[:3], x)
        assert not padded[3:].any()
        # exact fit returns the block untouched
        full = np.ones((8, 4))
        same, rows = buckets.pad_to_bucket(full)
        assert same is full and rows == 8
        with pytest.raises(ValueError, match="do not fit"):
            buckets.pad_to_bucket(x, bucket=2)


# -- registry: kernel extraction + eager parity -----------------------------


class TestRegistryParity:
    SIZES = (1, 3, 8, 17, 40, 60)

    def _assert_parity(self, name, model, x):
        reg = registry_mod.get_registry()
        reg.register(name, model, bucket_list=BUCKETS)
        for n in self.SIZES:
            got = reg.predict(name, x[:n])
            expected = np.asarray(model.transform(x[:n]))
            assert got.shape == expected.shape, n
            assert np.array_equal(got, expected), (
                f"serve/eager mismatch for {name} at {n} rows"
            )

    def test_pca_bitwise_parity(self, fitted_models):
        x, pca, _ = fitted_models
        self._assert_parity("pca", pca, x)

    def test_linear_bitwise_parity(self, fitted_models):
        x, _, lin = fitted_models
        self._assert_parity("linear", lin, x)

    def test_scaler_bitwise_parity(self, rng):
        from spark_rapids_ml_tpu.models.scaler import StandardScaler

        x = rng.normal(loc=3.0, scale=2.0, size=(120, 5))
        scaler = (
            StandardScaler()
            .setInputCol("features")
            .setWithMean(True)
            .setWithStd(True)
            .fit(x)
        )
        self._assert_parity("scaler", scaler, x)

    def test_forest_bitwise_parity(self, rng):
        from spark_rapids_ml_tpu.models.forest import RandomForestClassifier

        x = rng.normal(size=(150, 4))
        yc = (x[:, 0] + x[:, 1] > 0).astype(np.int64)
        forest = (
            RandomForestClassifier().setNumTrees(5).setSeed(3).fit((x, yc))
        )
        self._assert_parity("forest", forest, x)

    def test_unservable_model_raises_type_error(self):
        with pytest.raises(TypeError, match="no serve contract"):
            registry_mod.get_registry().register("bad", object())

    def test_unknown_model_raises_key_error(self):
        with pytest.raises(KeyError, match="no servable model"):
            registry_mod.get_registry().predict("ghost", [[1.0]])

    def test_describe_reports_warm_buckets(self, fitted_models):
        _, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8, 16))
        (desc,) = reg.describe()
        assert desc["name"] == "p"
        assert desc["family"] == "pca"
        assert desc["n_features"] == 6
        assert desc["buckets"] == [8, 16]

    def test_unwarmed_bucket_books_cold_compile(self, fitted_models):
        """A bucket outside the registered list still serves — but books
        serve.cold_compiles, the steady-state anomaly the report flags."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        snap = REGISTRY.snapshot()
        got = reg.predict("p", x[:9])  # rounds to 16: never AOT-compiled
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.cold_compiles") == 1
        assert np.array_equal(got, np.asarray(pca.transform(x[:9])))
        # the miss is now warm: a second hit does not re-book
        snap = REGISTRY.snapshot()
        reg.predict("p", x[:9])
        assert REGISTRY.snapshot().delta(snap).counter("serve.cold_compiles") == 0


# -- micro-batcher ----------------------------------------------------------


class TestMicroBatcher:
    def test_concurrent_requests_share_one_dispatch(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8, 16))
        batcher = MicroBatcher(reg, max_delay_s=0.2).start()
        try:
            snap = REGISTRY.snapshot()
            futures = [batcher.submit("p", x[i : i + 1]) for i in range(8)]
            outs = [f.result(timeout=30.0) for f in futures]
        finally:
            batcher.stop()
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.batches") == 1  # 8 requests, 1 dispatch
        assert delta.counter("serve.rows") == 8
        assert delta.hist("serve.queue_delay_seconds").count == 8
        expected = np.asarray(pca.transform(x[:8]))
        for i, out in enumerate(outs):
            assert np.array_equal(np.asarray(out), expected[i : i + 1])

    def test_coalescing_never_exceeds_the_warm_bucket_set(self, fitted_models):
        """Requests that would combine past the model's largest AOT-warm
        bucket split into multiple warm dispatches instead of coalescing
        into an unwarmed (cold-compiling) one."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8, 16))
        batcher = MicroBatcher(reg, max_delay_s=0.2).start()
        try:
            snap = REGISTRY.snapshot()
            # 4 x 8 rows inside one window: 32 combined would round to an
            # unwarmed 32-bucket — must dispatch as 2 x 16 instead
            futures = [batcher.submit("p", x[8 * i : 8 * i + 8]) for i in range(4)]
            for f in futures:
                f.result(timeout=30.0)
        finally:
            batcher.stop()
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.cold_compiles") == 0
        assert delta.counter("serve.batches") == 2
        assert delta.counter("serve.rows") == 32

    def test_submit_validates_before_queueing(self, fitted_models, monkeypatch):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        batcher = MicroBatcher(reg)  # not started: all paths raise at submit
        with pytest.raises(KeyError):
            batcher.submit("ghost", x[:1])
        with pytest.raises(ValueError, match="expected"):
            batcher.submit("p", np.ones((2, 4)))
        monkeypatch.setenv("TPU_ML_SERVE_MAX_BATCH_ROWS", "16")
        with pytest.raises(ValueError, match="ladder cap"):
            batcher.submit("p", np.ones((17, 6)))

    def test_stop_fans_error_to_waiting_requests(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        batcher = MicroBatcher(reg, max_delay_s=60.0).start()
        future = batcher.submit("p", x[:1])
        batcher.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            future.result(timeout=5.0)


# -- HTTP front-end ---------------------------------------------------------


class TestServeHTTP:
    def test_models_listing_and_predict(self, fitted_models):
        x, pca, _ = fitted_models
        registry_mod.get_registry().register("pca_http", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        code, raw = _get(srv.port, "/v1/models")
        assert code == 200
        (desc,) = json.loads(raw)["models"]
        assert desc["name"] == "pca_http" and desc["family"] == "pca"

        code, body = _post(
            srv.port, "/v1/models/pca_http:predict", {"instances": x[:3].tolist()}
        )
        assert code == 200
        assert body["model"] == "pca_http" and body["rows"] == 3
        assert body["latency_ms"] >= 0
        expected = np.asarray(pca.transform(x[:3]))
        assert np.array_equal(
            np.asarray(body["predictions"], dtype=expected.dtype), expected
        )

    def test_error_codes(self, fitted_models, monkeypatch):
        x, pca, _ = fitted_models
        registry_mod.get_registry().register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        # unknown model with a valid body -> 404
        code, body = _post(
            srv.port, "/v1/models/ghost:predict", {"instances": [[1.0] * 6]}
        )
        assert code == 404 and "ghost" in body["error"]
        # malformed body (no instances) -> 400
        code, body = _post(srv.port, "/v1/models/p:predict", {})
        assert code == 400
        # oversized request -> 413 at admission
        monkeypatch.setenv("TPU_ML_SERVE_MAX_BATCH_ROWS", "16")
        code, body = _post(
            srv.port,
            "/v1/models/p:predict",
            {"instances": np.ones((17, 6)).tolist()},
        )
        assert code == 413 and "ladder cap" in body["error"]
        # wrong endpoint -> 404
        code, _ = _post(srv.port, "/v1/nonsense", {"instances": []})
        assert code == 404

    def test_exporter_surface_still_served(self, fitted_models):
        """The serve front-end extends the telemetry exporter: /metrics on
        the SAME port carries the serve.* series the SLO engine watches."""
        x, pca, _ = fitted_models
        registry_mod.get_registry().register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        _post(srv.port, "/v1/models/p:predict", {"instances": x[:2].tolist()})
        code, raw = _get(srv.port, "/metrics")
        assert code == 200
        text = raw.decode()
        assert "tpu_ml_serve_requests" in text
        assert "tpu_ml_serve_latency" in text


# -- the acceptance test ----------------------------------------------------


class TestWarmPathAcceptance:
    def test_zero_recompiles_and_bitwise_parity_under_concurrency(
        self, fitted_models
    ):
        """2-request warmup per (model, bucket), then 50 mixed-size
        concurrent requests across 2 models: zero new compiles (telemetry
        compile counters) and every response bitwise-equal to the eager
        transform() on the unpadded rows."""
        x, pca, lin = fitted_models
        reg = registry_mod.get_registry()
        reg.register("pca_a", pca, bucket_list=BUCKETS)
        reg.register("lin_b", lin, bucket_list=BUCKETS)
        srv = server_mod.start_serving(0, with_monitor=False)

        for name in ("pca_a", "lin_b"):
            for bucket in BUCKETS:
                for _ in range(2):
                    code, _ = _post(
                        srv.port,
                        f"/v1/models/{name}:predict",
                        {"instances": x[:bucket].tolist()},
                    )
                    assert code == 200

        snap_warm = REGISTRY.snapshot()

        sizes = (1, 2, 3, 5, 8, 12, 17, 30, 40, 60)
        requests = []
        for i in range(50):
            n = sizes[i % len(sizes)]
            name, model = ("pca_a", pca) if i % 2 == 0 else ("lin_b", lin)
            start = (i * 3) % (len(x) - n)
            requests.append((name, model, x[start : start + n]))

        def call(req):
            name, model, xs = req
            code, body = _post(
                srv.port,
                f"/v1/models/{name}:predict",
                {"instances": xs.tolist()},
            )
            return code, body, model, xs

        with ThreadPoolExecutor(max_workers=8) as pool:
            results = list(pool.map(call, requests))

        window = REGISTRY.snapshot().delta(snap_warm)
        # the hard gate: nothing compiled after warmup
        assert window.hist("compile.seconds").count == 0
        assert window.counter("serve.cold_compiles") == 0
        assert window.counter("serve.requests") >= 50
        assert window.counter("serve.errors") == 0
        assert window.hist("serve.latency").count == 50
        # every response bitwise-equal to the eager transform (JSON carries
        # float64 exactly via repr round-trip)
        for code, body, model, xs in results:
            assert code == 200
            expected = np.asarray(model.transform(xs))
            got = np.asarray(body["predictions"], dtype=expected.dtype)
            assert got.shape == expected.shape
            assert np.array_equal(got, expected)
        # the evidence blob bench rides on the ledger renders from this window
        summary = server_mod.serve_summary(window)
        assert summary["requests"] >= 50
        assert summary["cold_compiles"] == 0
        assert summary["latency"]["count"] == 50
        assert sum(summary["bucket_hits"].values()) > 0


# -- persistent compile-cache warm start (subprocess) -----------------------


_WARM_SCRIPT = """
import json
import numpy as np
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.serving import registry as serve_registry
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

x = np.linspace(0.0, 1.0, 64 * 6).reshape(64, 6)
model = PCA().setInputCol("features").setK(3).fit(x)
snap = REGISTRY.snapshot()
serve_registry.get_registry().register("warm_pca", model, bucket_list=(8, 16))
delta = REGISTRY.snapshot().delta(snap)
lower = delta.hist("compile.lower_seconds")
print(json.dumps({
    "cache_hits": delta.counter("compile.cache_hits"),
    "cache_misses": delta.counter("compile.cache_misses"),
    "lower_max_s": float(lower.vmax) if lower.count else 0.0,
    "aot_compiles": delta.counter("serve.aot_compiles"),
}))
"""


class TestCompileCacheWarmStart:
    def test_second_process_warms_from_disk(self, tmp_path):
        """Two fresh processes register the same model against the same
        TPU_ML_SERVE_COMPILE_CACHE_DIR: the second reports cache hits and
        no slow lowering — the registration-time compiles were loads."""
        cache_dir = tmp_path / "serve_cache"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["TPU_ML_SERVE_COMPILE_CACHE_DIR"] = str(cache_dir)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)

        def run_once():
            proc = subprocess.run(
                [sys.executable, "-c", _WARM_SCRIPT],
                capture_output=True,
                text=True,
                env=env,
                cwd=REPO,
                timeout=300,
            )
            assert proc.returncode == 0, proc.stderr
            return json.loads(proc.stdout.strip().splitlines()[-1])

        first = run_once()
        assert first["aot_compiles"] == 2
        assert first["cache_misses"] > 0, first
        cached = [p for p in cache_dir.rglob("*") if p.is_file()]
        assert cached, "registration wrote nothing to the serve cache dir"

        second = run_once()
        assert second["aot_compiles"] == 2
        assert second["cache_hits"] > 0, second
        # a warm start never re-lowers slowly: the AOT .lower() still runs
        # (tracing is not cached) but stays far under a cold XLA compile
        assert second["lower_max_s"] < 2.0, second


# -- serve_report CLI -------------------------------------------------------


def _load_serve_report():
    spec = importlib.util.spec_from_file_location(
        "serve_report", os.path.join(REPO, "tools", "serve_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _summary_blob(**over):
    blob = {
        "type": "serve_summary",
        "coalesce_window_s": 0.002,
        "requests": 50.0,
        "errors": 0.0,
        "rows": 400.0,
        "batches": 30.0,
        "aot_compiles": 8.0,
        "cold_compiles": 0.0,
        "bucket_hits": {"8": 20.0, "16": 6.0, "32": 4.0},
        "latency": {
            "count": 50, "p50": 0.004, "p90": 0.006, "p99": 0.009,
            "max": 0.012,
        },
        "queue_delay": {
            "count": 50, "p50": 0.001, "p90": 0.0015, "p99": 0.002,
            "max": 0.004,
        },
        "batch_rows": {"count": 30, "p50": 8, "p90": 16, "p99": 32, "max": 32},
    }
    blob.update(over)
    return blob


class TestServeReport:
    def _write(self, tmp_path, records):
        path = tmp_path / "perf.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        return str(path)

    def test_clean_ledger_entry_renders_and_passes_strict(self, tmp_path, capsys):
        sr = _load_serve_report()
        path = self._write(
            tmp_path,
            [
                {"bench": "smoke", "other": 1},  # no serving evidence: ignored
                {
                    "bench": "smoke",
                    "timestamp": "2026-08-05T00:00:00Z",
                    "serving": _summary_blob(),
                    "metrics": {
                        "serve_recompiles_after_warmup": {"value": 0}
                    },
                },
            ],
        )
        assert sr.main([path, "--strict"]) == 0
        out = capsys.readouterr().out
        assert "requests/dispatch" in out
        assert "anomaly checks: ok" in out
        assert "bucket" in out and "share" in out

    def test_cold_compile_anomaly_fails_strict(self, tmp_path, capsys):
        sr = _load_serve_report()
        path = self._write(
            tmp_path, [{"serving": _summary_blob(cold_compiles=2.0)}]
        )
        assert sr.main([path]) == 0  # render-only stays green
        assert sr.main([path, "--strict"]) == 2
        assert "cold-start-compile-in-steady-state" in capsys.readouterr().out

    def test_wrapper_recompile_metric_fails_strict(self, tmp_path):
        sr = _load_serve_report()
        path = self._write(
            tmp_path,
            [{
                "serving": _summary_blob(),
                "metrics": {"serve_recompiles_after_warmup": {"value": 1}},
            }],
        )
        assert sr.main([path, "--strict"]) == 2

    def test_queue_delay_and_error_anomalies(self, tmp_path, capsys):
        sr = _load_serve_report()
        blob = _summary_blob(
            errors=3.0,
            queue_delay={
                "count": 50, "p50": 0.01, "p90": 0.02, "p99": 0.05,
                "max": 0.06,
            },
        )
        path = self._write(tmp_path, [blob])  # bare blob, no wrapper
        assert sr.main([path, "--strict"]) == 2
        out = capsys.readouterr().out
        assert "serve-errors" in out
        assert "queue-delay-above-window" in out

    def test_no_evidence_is_an_error(self, tmp_path):
        sr = _load_serve_report()
        path = self._write(tmp_path, [{"bench": "smoke"}])
        assert sr.main([path]) == 1


# -- zero-copy ingest: dtype preservation + binary wire ----------------------


class TestZeroCopyIngest:
    def test_validate_request_preserves_dtype(self):
        f32 = registry_mod.validate_request(
            np.ones((2, 6), dtype=np.float32), 6, "m"
        )
        assert f32.dtype == np.float32
        f64 = registry_mod.validate_request(np.ones((2, 6)), 6, "m")
        assert f64.dtype == np.float64
        # JSON integers/bools widen to exact float64, like the eager path
        ints = registry_mod.validate_request(
            np.ones((2, 6), dtype=np.int64), 6, "m"
        )
        assert ints.dtype == np.float64

    def test_unsupported_dtype_names_accepted_set(self):
        with pytest.raises(ValueError) as ei:
            registry_mod.validate_request(
                np.ones((2, 6), dtype=np.float16), 6, "m"
            )
        msg = str(ei.value)
        assert "float16" in msg
        assert "float32" in msg and "float64" in msg

    def test_float32_never_round_trips_through_float64(self, fitted_models):
        """The batcher queues the request block in the device dtype: a f32
        payload must reach the staging block as f32, not as a f64 copy."""
        x, _, lin = fitted_models
        reg = registry_mod.get_registry()
        entry = reg.register("lin32", lin, bucket_list=(8,))
        x32 = np.asarray(x[:3], dtype=np.float32)
        prepared = entry.prepare(
            registry_mod.validate_request(x32, entry.n_features, "lin32")
        )
        assert prepared.dtype == np.float32

    def test_binary_http_round_trip_bitwise(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        x32 = np.ascontiguousarray(x[:5], dtype="<f4")
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/models/p:predict",
            data=x32.tobytes(),
            headers={
                "Content-Type": server_mod.BINARY_CONTENT_TYPE,
                server_mod.SHAPE_HEADER: "5,6",
                "Accept": server_mod.BINARY_CONTENT_TYPE,
            },
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == server_mod.BINARY_CONTENT_TYPE
            shape = tuple(
                int(d) for d in r.headers[server_mod.SHAPE_HEADER].split(",")
            )
            got = np.frombuffer(r.read(), dtype="<f4").reshape(shape)
        expected = np.asarray(
            reg.predict("p", x32), dtype="<f4"
        )
        assert np.array_equal(got, expected)

    def test_binary_request_json_response(self, fitted_models):
        """No binary Accept header: a binary request still answers JSON."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        x32 = np.ascontiguousarray(x[:2], dtype="<f4")
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/models/p:predict",
            data=x32.tobytes(),
            headers={
                "Content-Type": server_mod.BINARY_CONTENT_TYPE,
                server_mod.SHAPE_HEADER: "2,6",
            },
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            body = json.loads(r.read())
        assert body["rows"] == 2
        expected = reg.predict("p", x32)
        assert np.allclose(body["predictions"], expected)

    def test_binary_payload_validation_is_400(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)

        def binary_post(data, shape_header):
            headers = {"Content-Type": server_mod.BINARY_CONTENT_TYPE}
            if shape_header is not None:
                headers[server_mod.SHAPE_HEADER] = shape_header
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/models/p:predict",
                data=data,
                headers=headers,
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        x32 = np.ones((2, 6), dtype="<f4")
        # byte length does not match the declared shape
        code, body = binary_post(x32.tobytes()[:-4], "2,6")
        assert code == 400 and "expected" in body["error"]
        # missing shape header
        code, body = binary_post(x32.tobytes(), None)
        assert code == 400 and server_mod.SHAPE_HEADER in body["error"]

    def test_dtype_error_body_names_accepted_dtypes(self, fitted_models):
        x, pca, _ = fitted_models
        registry_mod.get_registry().register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        code, body = _post(
            srv.port,
            "/v1/models/p:predict",
            {"instances": [["not", "a", "number", "x", "y", "z"]]},
        )
        assert code == 400
        assert "accepted dtypes" in body["error"]
        assert "float32" in body["error"] and "float64" in body["error"]


# -- UDS transport -----------------------------------------------------------


def _uds_read_exact(rf, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = rf.read(n)
        assert chunk, "peer closed mid-frame"
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _uds_exchange(sock, header: dict, payload: bytes = b""):
    raw = json.dumps(header).encode()
    sock.sendall(len(raw).to_bytes(4, "big") + raw + payload)
    rf = sock.makefile("rb")
    n = int.from_bytes(_uds_read_exact(rf, 4), "big")
    resp = json.loads(_uds_read_exact(rf, n))
    body = (
        _uds_read_exact(rf, int(resp["payload_bytes"]))
        if resp.get("payload_bytes")
        else b""
    )
    return resp, body


class TestUDSTransport:
    def _serve(self, tmp_path, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        path = str(tmp_path / "serve.sock")
        server_mod.start_serving(0, with_monitor=False, uds_path=path)
        return x, reg, path

    def test_json_round_trip(self, tmp_path, fitted_models):
        x, reg, path = self._serve(tmp_path, fitted_models)
        snap = REGISTRY.snapshot()
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            resp, _ = _uds_exchange(
                s, {"model": "p", "wire": "json", "instances": x[:3].tolist()}
            )
        assert resp["ok"] and resp["code"] == 200 and resp["rows"] == 3
        expected = reg.predict("p", x[:3])
        assert np.array_equal(np.asarray(resp["predictions"]), expected)
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter(
            "serve.transport", transport="uds", wire="json"
        ) == 1
        assert delta.hist("serve.latency").count == 1

    def test_binary_round_trip_bitwise(self, tmp_path, fitted_models):
        x, reg, path = self._serve(tmp_path, fitted_models)
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            resp, body = _uds_exchange(
                s,
                {
                    "model": "p",
                    "wire": "binary",
                    "accept": "binary",
                    "shape": [4, 6],
                    "payload_bytes": x32.nbytes,
                },
                x32.tobytes(),
            )
        assert resp["ok"] and resp["wire"] == "binary"
        got = np.frombuffer(body, dtype="<f4").reshape(resp["shape"])
        expected = np.asarray(reg.predict("p", x32), dtype="<f4")
        assert np.array_equal(got, expected)

    def test_one_connection_many_requests_and_errors(
        self, tmp_path, fitted_models
    ):
        x, _, path = self._serve(tmp_path, fitted_models)
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            # an error frame answers without killing the connection
            resp, _ = _uds_exchange(
                s,
                {"model": "ghost", "wire": "json",
                 "instances": x[:1].tolist()},
            )
            assert not resp["ok"] and resp["code"] == 404
            resp, _ = _uds_exchange(
                s, {"model": "p", "wire": "json", "instances": x[:2].tolist()}
            )
            assert resp["ok"] and resp["rows"] == 2

    def test_stop_serving_unlinks_socket(self, tmp_path, fitted_models):
        _, _, path = self._serve(tmp_path, fitted_models)
        assert os.path.exists(path)
        server_mod.stop_serving(stop_monitor=False)
        assert not os.path.exists(path)


# -- in-process client -------------------------------------------------------


class TestInprocClient:
    def test_client_shares_server_batcher(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        srv = server_mod.start_serving(0, with_monitor=False)
        snap = REGISTRY.snapshot()
        out = client_mod.predict("p", x[:3])
        assert np.array_equal(out, reg.predict("p", x[:3]))
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter(
            "serve.transport", transport="inproc", wire="array"
        ) == 1
        # bound to the front-end's batcher, not a private one
        assert client_mod.get_client()._batcher() is srv.batcher

    def test_client_without_server_starts_private_batcher(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        client = client_mod.ServeClient()
        try:
            out = client.predict("p", x[:2])
            assert np.array_equal(out, reg.predict("p", x[:2]))
        finally:
            client.close()

    def test_client_error_books_status_code(self, fitted_models):
        x, pca, _ = fitted_models
        registry_mod.get_registry().register("p", pca, bucket_list=(8,))
        client = client_mod.ServeClient()
        snap = REGISTRY.snapshot()
        try:
            with pytest.raises(KeyError):
                client.predict("ghost", x[:1])
        finally:
            client.close()
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.errors", model="ghost", code=404) == 1


# -- continuous batching -----------------------------------------------------


class TestContinuousBatching:
    def test_full_bucket_leaves_immediately(self, fitted_models):
        """The window is a ceiling, not a tax: a full min-bucket dispatches
        without waiting out a 60 s window."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        batcher = MicroBatcher(reg, max_delay_s=60.0).start()
        try:
            out = batcher.submit("p", x[:8]).result(timeout=10.0)
        finally:
            batcher.stop()
        assert np.array_equal(out, np.asarray(pca.transform(x[:8])))

    def test_late_request_joins_in_flight_dispatch(self, fitted_models):
        """A request arriving after the batch was taken but before the
        padded block is built rides the in-flight dispatch's pad slack —
        and its result is bitwise what a solo dispatch would produce."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        batcher = MicroBatcher(reg, max_delay_s=60.0, adaptive=False)
        # worker not started: drive the take/dispatch sequence by hand so
        # the "late" arrival is deterministic
        fut_a = batcher.submit("p", x[:1])
        key = ("p", 8)
        with batcher._cond:
            taken = batcher._groups.pop(key)
        fut_b = batcher.submit("p", x[1:3])  # arrives after the take
        snap = REGISTRY.snapshot()
        batcher._dispatch(key, taken, 0.0)
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.batches") == 1
        assert delta.counter("serve.joined_in_flight", model="p") == 1
        assert delta.hist("serve.queue_delay_seconds").count == 2
        out_a = fut_a.result(timeout=5.0)
        out_b = fut_b.result(timeout=5.0)
        assert np.array_equal(out_a, np.asarray(pca.transform(x[:1])))
        assert np.array_equal(out_b, np.asarray(pca.transform(x[1:3])))

    def test_late_join_never_overflows_the_bucket(self, fitted_models):
        """Riders only join up to the chosen bucket's pad slack; the rest
        stay queued for their own window."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8, 16))
        batcher = MicroBatcher(reg, max_delay_s=60.0, adaptive=False)
        batcher.submit("p", x[:6])
        key = ("p", 8)
        with batcher._cond:
            taken = batcher._groups.pop(key)
        fut_fits = batcher.submit("p", x[6:8])    # 6+2 = 8: fits
        fut_next = batcher.submit("p", x[8:16])   # would overflow: stays
        batcher._dispatch(key, taken, 0.0)
        assert fut_fits.result(timeout=5.0).shape[0] == 2
        with batcher._cond:
            assert sum(
                p.rows for g in batcher._groups.values() for p in g
            ) == 8
        # drain the leftover so no future leaks
        with batcher._cond:
            leftover = batcher._groups.pop(("p", 8))
        batcher._dispatch(("p", 8), leftover, 0.0)
        assert fut_next.result(timeout=5.0).shape[0] == 8

    def test_adaptive_window_tracks_device_time(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        fixed = MicroBatcher(reg, max_delay_s=0.2, adaptive=False)
        assert fixed.effective_window_s("p") == 0.2
        adaptive = MicroBatcher(reg, max_delay_s=0.2, adaptive=True).start()
        try:
            # no device observation yet: the ceiling is the window
            assert adaptive.effective_window_s("p") == 0.2
            adaptive.submit("p", x[:8]).result(timeout=30.0)
            # one dispatch seeded the EWMA: the window left the ceiling
            assert adaptive.effective_window_s("p") < 0.2
            assert adaptive.effective_window_s("p") >= 25e-6
        finally:
            adaptive.stop()

    def test_adaptive_window_cuts_queue_delay_under_burst(self, fitted_models):
        """The ISSUE acceptance: under a burst that does NOT fill the
        bucket, the adaptive window drains at ~device time while the fixed
        window idles out its full ceiling — queue-delay p99 drops by well
        over 3x."""
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8, 16))
        ceiling = 0.12

        def burst(batcher):
            snap = REGISTRY.snapshot()
            with ThreadPoolExecutor(max_workers=4) as pool:
                futs = list(
                    pool.map(
                        lambda i: batcher.submit("p", x[i : i + 1]), range(4)
                    )
                )
            outs = [f.result(timeout=30.0) for f in futs]
            for i, out in enumerate(outs):
                assert np.array_equal(
                    out, np.asarray(pca.transform(x[i : i + 1]))
                )
            delta = REGISTRY.snapshot().delta(snap)
            return delta.hist("serve.queue_delay_seconds").percentile(99)

        fixed = MicroBatcher(reg, max_delay_s=ceiling, adaptive=False).start()
        try:
            p99_fixed = burst(fixed)
        finally:
            fixed.stop()

        adaptive = MicroBatcher(
            reg, max_delay_s=ceiling, adaptive=True
        ).start()
        try:
            # seed the device EWMA with one full-bucket dispatch
            adaptive.submit("p", x[:8]).result(timeout=30.0)
            p99_adaptive = burst(adaptive)
        finally:
            adaptive.stop()

        assert p99_fixed >= 0.8 * ceiling
        assert p99_adaptive < p99_fixed / 3

    def test_every_dispatch_books_effective_window(self, fitted_models):
        x, pca, _ = fitted_models
        reg = registry_mod.get_registry()
        reg.register("p", pca, bucket_list=(8,))
        batcher = MicroBatcher(reg, max_delay_s=0.01).start()
        try:
            snap = REGISTRY.snapshot()
            batcher.submit("p", x[:8]).result(timeout=30.0)
            delta = REGISTRY.snapshot().delta(snap)
            assert delta.hist(
                "serve.window_effective_seconds", model="p"
            ).count == 1
        finally:
            batcher.stop()


# -- HBM fleet manager -------------------------------------------------------


class TestHbmFleet:
    def test_lru_paging_order_counters_and_repaged_parity(
        self, fitted_models, monkeypatch
    ):
        x, _, lin = fitted_models
        reg = registry_mod.get_registry()
        e1 = reg.register("m1", lin, bucket_list=(8,))
        per_model = hbm_mod.param_bytes(e1.params)
        assert per_model > 0
        # budget fits exactly two models
        monkeypatch.setenv(
            hbm_mod.SERVE_HBM_BUDGET_BYTES_VAR, str(2 * per_model)
        )
        reg.register("m2", lin, bucket_list=(8,))
        reg.predict("m1", x[:2])  # touch m1: m2 becomes LRU
        snap = REGISTRY.snapshot()
        reg.register("m3", lin, bucket_list=(8,))
        delta = REGISTRY.snapshot().delta(snap)
        # true LRU: the un-touched m2 was evicted, not the older m1
        assert delta.counter("serve.page_out", model="m2") == 1
        assert delta.counter("serve.page_out", model="m1") == 0
        fleet = hbm_mod.get_fleet()
        stats = fleet.stats()
        assert stats["budget_bytes"] == 2 * per_model
        assert stats["resident_bytes"] == 2 * per_model
        assert not stats["models"]["m2"]["resident"]
        assert stats["models"]["m1"]["resident"]
        assert stats["models"]["m3"]["resident"]

        # predicting the paged-out model repages it (evicting the new LRU,
        # m1) and its predictions are bitwise what they were when resident
        expected = np.asarray(lin.transform(x[:3]))
        snap = REGISTRY.snapshot()
        got = reg.predict("m2", x[:3])
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.page_in", model="m2") == 1
        assert delta.counter("serve.page_out", model="m1") == 1
        assert np.array_equal(got, expected)
        stats = fleet.stats()
        assert stats["models"]["m2"]["resident"]
        assert not stats["models"]["m1"]["resident"]

    def test_no_budget_means_no_paging(self, fitted_models, monkeypatch):
        """CPU backends expose no memory stats and set no override: every
        model stays resident and nothing pages."""
        monkeypatch.delenv(
            hbm_mod.SERVE_HBM_BUDGET_BYTES_VAR, raising=False
        )
        monkeypatch.setattr(hbm_mod, "budget_bytes", lambda: None)
        x, _, lin = fitted_models
        reg = registry_mod.get_registry()
        snap = REGISTRY.snapshot()
        for name in ("a", "b", "c"):
            reg.register(name, lin, bucket_list=(8,))
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.page_out") == 0
        assert all(
            r["resident"]
            for r in hbm_mod.get_fleet().stats()["models"].values()
        )

    def test_hbm_bytes_gauge_tracks_residency(self, fitted_models, monkeypatch):
        x, _, lin = fitted_models
        reg = registry_mod.get_registry()
        e1 = reg.register("g1", lin, bucket_list=(8,))
        per_model = hbm_mod.param_bytes(e1.params)
        monkeypatch.setenv(
            hbm_mod.SERVE_HBM_BUDGET_BYTES_VAR, str(per_model)
        )
        reg.register("g2", lin, bucket_list=(8,))
        snap = REGISTRY.snapshot()
        gauge = [
            v for (n, _), v in snap.gauges.items() if n == "serve.hbm_bytes"
        ]
        assert gauge == [per_model]

    def test_shed_on_slo_burn(self, monkeypatch):
        from spark_rapids_ml_tpu.telemetry import health

        breaches = [0]
        fake = types.SimpleNamespace(
            slo=types.SimpleNamespace(total_breaches=lambda: breaches[0])
        )
        monkeypatch.setattr(health, "get_monitor", lambda: fake)
        monkeypatch.setenv("TPU_ML_ADMISSION_POLICY", "refuse")
        fleet = hbm_mod.get_fleet()
        fleet.check_admission("m")  # no burn yet: admits
        breaches[0] = 2
        snap = REGISTRY.snapshot()
        with pytest.raises(hbm_mod.ServeShed):
            fleet.check_admission("m")
        # the shed surfaces as 503 at every transport
        assert server_mod.status_for_error(hbm_mod.ServeShed("x")) == 503
        # one shed per newly observed breach: the same burn does not
        # re-shed the next request
        fleet.check_admission("m")
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.shed", model="m", policy="refuse") == 1

    def test_degrade_policy_counts_but_admits(self, monkeypatch):
        from spark_rapids_ml_tpu.telemetry import health

        fake = types.SimpleNamespace(
            slo=types.SimpleNamespace(total_breaches=lambda: 1)
        )
        monkeypatch.setattr(health, "get_monitor", lambda: fake)
        monkeypatch.setenv("TPU_ML_ADMISSION_POLICY", "degrade")
        fleet = hbm_mod.get_fleet()
        snap = REGISTRY.snapshot()
        fleet.check_admission("m")  # burns, but admits
        delta = REGISTRY.snapshot().delta(snap)
        assert delta.counter("serve.shed", model="m", policy="degrade") == 1

    def test_off_policy_disables_shedding(self, monkeypatch):
        from spark_rapids_ml_tpu.telemetry import health

        fake = types.SimpleNamespace(
            slo=types.SimpleNamespace(total_breaches=lambda: 99)
        )
        monkeypatch.setattr(health, "get_monitor", lambda: fake)
        monkeypatch.setenv("TPU_ML_ADMISSION_POLICY", "off")
        snap = REGISTRY.snapshot()
        hbm_mod.get_fleet().check_admission("m")
        assert REGISTRY.snapshot().delta(snap).counter("serve.shed") == 0


# -- serve_report: fast-path additions ---------------------------------------


class TestServeReportFastPath:
    def _write(self, tmp_path, records):
        path = tmp_path / "perf.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        return str(path)

    def test_transport_mix_paging_and_window_render(self, tmp_path, capsys):
        sr = _load_serve_report()
        blob = _summary_blob(
            transport_mix={
                "http/json": 20.0, "http/binary": 10.0,
                "uds/binary": 15.0, "inproc/array": 5.0,
            },
            joined_in_flight=7.0,
            page_in=1.0,
            page_out=2.0,
            hbm_bytes=4096.0,
            adaptive_window=True,
            window_effective={
                "count": 30, "p50": 0.0004, "p90": 0.001, "p99": 0.002,
                "max": 0.002,
            },
        )
        path = self._write(tmp_path, [blob])
        assert sr.main([path, "--strict"]) == 0
        out = capsys.readouterr().out
        assert "transport/wire" in out and "uds/binary" in out
        assert "7 rider(s) joined in-flight" in out
        assert "hbm paging: 1 page-in(s), 2 page-out(s)" in out
        assert "adaptive window" in out and "ceiling" in out

    def test_page_thrash_anomaly_fails_strict(self, tmp_path, capsys):
        sr = _load_serve_report()
        blob = _summary_blob(page_in=20.0, requests=50.0)
        path = self._write(tmp_path, [blob])
        assert sr.main([path, "--strict"]) == 2
        assert "page-thrash" in capsys.readouterr().out

    def test_window_never_adapts_anomaly(self, tmp_path, capsys):
        sr = _load_serve_report()
        blob = _summary_blob(
            adaptive_window=True,
            window_effective={
                "count": 12, "p50": 0.002, "p90": 0.002, "p99": 0.002,
                "max": 0.002,
            },
        )
        path = self._write(tmp_path, [blob])
        assert sr.main([path, "--strict"]) == 2
        assert "window-never-adapts" in capsys.readouterr().out

    def test_sparse_window_traffic_is_not_an_anomaly(self, tmp_path):
        """Too few dispatches to judge adaptation: no anomaly."""
        sr = _load_serve_report()
        blob = _summary_blob(
            adaptive_window=True,
            window_effective={
                "count": 4, "p50": 0.002, "p90": 0.002, "p99": 0.002,
                "max": 0.002,
            },
        )
        path = self._write(tmp_path, [blob])
        assert sr.main([path, "--strict"]) == 0


# -- fast lane: JSON-free dispatch -------------------------------------------


class TestFastlaneProtocol:
    def test_request_round_trip_zero_copy(self):
        x = np.arange(12, dtype="<f4").reshape(4, 3)
        frame = fastlane.pack_request("m", x)
        assert fastlane.is_fastlane_head(frame[:4])
        buf = memoryview(frame[4:])
        pos = [0]

        def read_exact(n):
            out = buf[pos[0]:pos[0] + n]
            pos[0] += n
            return out

        model, mat, is_query, trace = fastlane.read_request(read_exact)
        assert model == "m" and not is_query
        assert trace is None  # all-zero trace tail = untraced request
        assert np.array_equal(mat, x) and mat.dtype == np.dtype("<f4")

    def test_peek_matches_read(self):
        x = np.zeros((8, 5), dtype="<f4")
        frame = fastlane.pack_request("abc", x)
        struct_raw = frame[4:4 + fastlane.request_struct_size()]
        assert fastlane.peek_request(struct_raw) == (3, 8, 5)

    def test_trace_context_rides_the_struct(self):
        """v2 wire: a packed trace context round-trips through the binary
        request struct — no JSON anywhere on the path."""
        x = np.zeros((2, 3), dtype="<f4")
        ctx = tracectx.TraceContext(
            trace_id=0x1122334455667788, span_id=0x9ABCDEF0,
            origin_us=123456789,
        )
        frame = fastlane.pack_request("m", x, trace=ctx)
        buf, pos = memoryview(frame[4:]), [0]

        def read_exact(n):
            out = buf[pos[0]:pos[0] + n]
            pos[0] += n
            return out

        model, _mat, _q, got = fastlane.read_request(read_exact)
        assert model == "m" and got == ctx

    def test_peek_and_rewrite_trace_are_byte_surgery(self):
        """The router's relay path peeks the inbound context and rewrites
        its own child span id into the forwarded struct without touching
        name or payload bytes."""
        x = np.zeros((8, 5), dtype="<f4")
        parent = tracectx.TraceContext(
            trace_id=0xDEAD, span_id=0xBEEF, origin_us=42,
        )
        frame = fastlane.pack_request("abc", x, trace=parent)
        struct_raw = bytes(frame[4:4 + fastlane.request_struct_size()])
        assert fastlane.peek_trace(struct_raw) == parent
        # rows/cols/name_len untouched by the trace tail
        assert fastlane.peek_request(struct_raw) == (3, 8, 5)
        child = parent.child()
        rewritten = fastlane.rewrite_trace(struct_raw, child)
        assert len(rewritten) == len(struct_raw)
        assert fastlane.peek_trace(rewritten) == child
        assert fastlane.peek_request(rewritten) == (3, 8, 5)
        # untraced peek: all-zero tail reads back as None
        bare = bytes(fastlane.pack_request("abc", x)[
            4:4 + fastlane.request_struct_size()
        ])
        assert fastlane.peek_trace(bare) is None

    def test_error_frame_raises_with_status(self):
        frame = fastlane.pack_error_response(404, "model 'x' not found")
        buf, pos = memoryview(frame), [0]

        def read_exact(n):
            out = buf[pos[0]:pos[0] + n]
            pos[0] += n
            return bytes(out)

        with pytest.raises(fastlane.FastlaneError) as e:
            fastlane.read_response(read_exact)
        assert e.value.status == 404 and "not found" in e.value.message

    def test_magic_unreachable_as_json_header_length(self):
        # the discriminator rides in place of the 4-byte header length;
        # a real JSON header can never be ~4.1 GB long
        assert fastlane.FASTLANE_MAGIC > 2**31

    def test_response_pool_recycles_buffers(self):
        pool = fastlane.ResponseBufferPool()
        with pool.lease("m", 8, 64) as view:
            first = view.obj
            assert len(view) == 64
        with pool.lease("m", 8, 64) as view:
            assert view.obj is first  # recycled, not reallocated
        with pool.lease("m", 8, 32) as view:
            assert view.obj is first and len(view) == 32  # shrunk lease
        stats = pool.stats()
        assert stats == {"leases": 3, "allocations": 1, "keys": 1}

    def test_fill_f32_casts_into_leased_buffer(self):
        pool = fastlane.ResponseBufferPool()
        out = np.arange(6, dtype=np.float64).reshape(3, 2)
        with pool.lease("m", 8, out.size * 4) as view:
            rows, cols = fastlane.fill_f32(view, out)
            assert (rows, cols) == (3, 2)
            got = np.frombuffer(view, dtype="<f4").reshape(3, 2)
            assert np.array_equal(got, out.astype("<f4"))


class TestFastlaneE2E:
    def _serve(self, tmp_path, fitted_models):
        x, _, lin = fitted_models
        reg = registry_mod.get_registry()
        reg.register("lin", lin, bucket_list=(8,))
        path = str(tmp_path / "serve.sock")
        server_mod.start_serving(0, with_monitor=False, uds_path=path)
        return x, reg, path

    def test_zero_json_on_hot_path_and_bitwise_parity(
        self, tmp_path, fitted_models
    ):
        """The fast lane books ZERO serve.json_codec activity (the counted
        codec proves the no-dict-churn claim) and its f32 payload is
        bitwise identical to the JSON lane's predictions for the same
        f32-representable request (linear model: identity prepare, so
        both lanes run the exact same f32 kernel)."""
        x, reg, path = self._serve(tmp_path, fitted_models)
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            rf = s.makefile("rb")
            snap = REGISTRY.snapshot()
            s.sendall(fastlane.pack_request("lin", x32))
            fast_out = fastlane.read_response(
                lambda n: _uds_read_exact(rf, n)
            )
            delta = REGISTRY.snapshot().delta(snap)
            assert delta.counter("serve.json_codec") == 0
            assert delta.counter(
                "serve.transport", transport="uds", wire="fast"
            ) == 1
            assert delta.hist(
                "serve.latency", transport="uds", wire="fast"
            ).count == 1

            # same request on the JSON lane of the same connection
            resp, _ = _uds_exchange(
                s,
                {"model": "lin", "wire": "json",
                 "instances": x32.tolist()},
            )
        assert resp["ok"]
        json_out = np.asarray(resp["predictions"], dtype="<f4")
        assert fast_out.tobytes() == json_out.reshape(fast_out.shape).tobytes()
        # ...and the JSON lane DID run the counted codec
        post = REGISTRY.snapshot().delta(snap)
        assert post.counter("serve.json_codec", op="decode") >= 1
        assert post.counter("serve.json_codec", op="encode") >= 1

    def test_fastlane_pooled_response_buffers_recycle(
        self, tmp_path, fitted_models
    ):
        x, _, path = self._serve(tmp_path, fitted_models)
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        before = fastlane.RESPONSE_POOL.stats()
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            rf = s.makefile("rb")
            for _ in range(5):
                s.sendall(fastlane.pack_request("lin", x32))
                fastlane.read_response(lambda n: _uds_read_exact(rf, n))
        after = fastlane.RESPONSE_POOL.stats()
        assert after["leases"] - before["leases"] == 5
        # steady state allocates at most once for this (model, bucket)
        assert after["allocations"] - before["allocations"] <= 1

    def test_error_frame_keeps_connection_alive(
        self, tmp_path, fitted_models
    ):
        x, _, path = self._serve(tmp_path, fitted_models)
        x32 = np.ascontiguousarray(x[:2], dtype="<f4")
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            rf = s.makefile("rb")
            s.sendall(fastlane.pack_request("ghost", x32))
            with pytest.raises(fastlane.FastlaneError) as e:
                fastlane.read_response(lambda n: _uds_read_exact(rf, n))
            assert e.value.status == 404
            # the connection survives the error frame
            s.sendall(fastlane.pack_request("lin", x32))
            out = fastlane.read_response(lambda n: _uds_read_exact(rf, n))
        assert out.shape[0] == 2


# -- deterministic teardown (no leaked threads / sockets) --------------------


def _serve_threads() -> list[str]:
    import threading as _threading

    return sorted(
        t.name for t in _threading.enumerate()
        if t.name.startswith(("tpu-ml-serve", "tpu-ml-fleet"))
    )


class TestTeardownLeak:
    def test_repeated_start_stop_cycles_leak_nothing(
        self, tmp_path, fitted_models
    ):
        """stop_serving/reset_client must deterministically join every
        worker thread and unlink the UDS socket: after each of several
        start/serve/stop cycles the process has zero tpu-ml serve threads
        and no stray socket file."""
        x, _, lin = fitted_models
        x32 = np.ascontiguousarray(x[:4], dtype="<f4")
        for cycle in range(3):
            reg = registry_mod.get_registry()
            if "lin" not in {d["name"] for d in reg.describe()}:
                reg.register("lin", lin, bucket_list=(8,))
            path = str(tmp_path / f"serve-{cycle}.sock")
            server_mod.start_serving(0, with_monitor=False, uds_path=path)
            with socket.socket(socket.AF_UNIX) as s:
                s.connect(path)
                rf = s.makefile("rb")
                s.sendall(fastlane.pack_request("lin", x32))
                fastlane.read_response(lambda n: _uds_read_exact(rf, n))
            client_mod.predict("lin", x32)
            server_mod.stop_serving(stop_monitor=False)
            client_mod.reset_client()
            assert _serve_threads() == [], (
                f"cycle {cycle} leaked threads: {_serve_threads()}"
            )
            assert not os.path.exists(path), (
                f"cycle {cycle} left the UDS socket behind"
            )

    def test_private_client_batcher_joins_on_reset(self, fitted_models):
        _, _, lin = fitted_models
        reg = registry_mod.get_registry()
        reg.register("lin", lin, bucket_list=(8,))
        # no server running: the client lazily starts a private batcher
        out = client_mod.predict("lin", np.zeros((2, 6), dtype="<f4"))
        assert out.shape[0] == 2
        assert "tpu-ml-serve-batcher" in _serve_threads()
        client_mod.reset_client()
        assert _serve_threads() == []


# -- tail-aware hedged dispatch ----------------------------------------------


class TestHedgedDispatch:
    def test_hedge_fires_past_threshold_and_first_result_wins(
        self, fitted_models, monkeypatch
    ):
        """A stalled primary dispatch past the hedge threshold re-issues
        the batch; the hedge's result answers the request and the
        telemetry books the hedge + the winner (the loser's device time
        never reaches the adaptive-window EWMA)."""
        _, _, lin = fitted_models
        monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "1.5")
        monkeypatch.setenv("TPU_ML_SERVE_HEDGE_FLOOR_US", "1000")
        reg = registry_mod.get_registry()
        reg.register("lin", lin, bucket_list=(8,))
        mb = MicroBatcher(reg).start()
        try:
            x32 = np.ascontiguousarray(
                np.linspace(0.0, 1.0, 12).reshape(2, 6), dtype="<f4"
            )
            # seed the device-time EWMA (no hedging while it is unknown:
            # "never hedge blind")
            expected = mb.submit("lin", x32).result(timeout=30)

            real_dispatch = reg.dispatch_padded
            stalls = iter([0.4])

            def stalling_dispatch(entry, padded, bucket):
                delay = next(stalls, 0.0)
                if delay:
                    time.sleep(delay)
                return real_dispatch(entry, padded, bucket)

            monkeypatch.setattr(reg, "dispatch_padded", stalling_dispatch)
            snap = REGISTRY.snapshot()
            out = mb.submit("lin", x32).result(timeout=30)
            delta = REGISTRY.snapshot().delta(snap)
            assert np.array_equal(np.asarray(out), np.asarray(expected))
            assert delta.counter("serve.hedges", model="lin") == 1
            assert delta.counter(
                "serve.hedge_wins", model="lin", winner="hedge"
            ) == 1
        finally:
            mb.stop()

    def test_no_hedge_without_observed_device_time(
        self, fitted_models, monkeypatch
    ):
        from spark_rapids_ml_tpu.resilience import supervisor

        monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "2.0")
        # observed == 0 -> never hedge blind
        assert supervisor.hedge_threshold_s(0.0, floor_s=0.001) is None
        # factor <= 0 -> hedging disabled outright
        monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "0")
        assert supervisor.hedge_threshold_s(0.5, floor_s=0.001) is None

    def test_threshold_respects_serve_floor(self, monkeypatch):
        from spark_rapids_ml_tpu.resilience import supervisor
        from spark_rapids_ml_tpu.serving import batcher as batcher_mod

        monkeypatch.setenv("TPU_ML_HEDGE_FACTOR", "2.0")
        monkeypatch.setenv("TPU_ML_SERVE_HEDGE_FLOOR_US", "5000")
        floor = batcher_mod.serve_hedge_floor_s()
        assert floor == pytest.approx(0.005)
        # tiny observed latency: the floor wins (no microsecond hedges)
        assert supervisor.hedge_threshold_s(
            1e-5, floor_s=floor
        ) == pytest.approx(0.005)
        # big observed latency: factor x observed wins
        assert supervisor.hedge_threshold_s(
            0.1, floor_s=floor
        ) == pytest.approx(0.2)


# -- hot-swap under concurrent load (ISSUE-18) -------------------------------


class TestSwapUnderConcurrentLoad:
    def test_every_response_is_bitwise_one_version(self, fitted_models):
        """Hammer the registry from worker threads while the main thread
        hot-swaps the model: zero errors, and every single response is
        bitwise-identical to exactly one version's eager ``transform()``
        — in-flight dispatches finish on the old kernel, new admissions
        land on the new one, nothing ever serves a torn mix."""
        from spark_rapids_ml_tpu.models.linear import LinearRegression

        x, _, _ = fitted_models
        rng = np.random.default_rng(13)
        y = x @ rng.normal(size=6) + 0.25
        old = LinearRegression().fit((x, y))
        new = LinearRegression().fit((x, -y))
        reg = registry_mod.get_registry()
        reg.register("hot", old, bucket_list=(8, 16))
        probe = x[:8]
        want_old = np.asarray(old.transform(probe))
        want_new = np.asarray(new.transform(probe))
        assert not np.array_equal(want_old, want_new)

        stop = False
        errors: list[Exception] = []
        outs: list[np.ndarray] = []

        def hammer():
            while not stop:
                try:
                    outs.append(reg.predict("hot", probe))
                except Exception as e:  # noqa: BLE001 — asserted empty
                    errors.append(e)
                    return

        import threading

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.1)  # guaranteed pre-swap traffic
            entry = reg.swap(
                "hot", new, shadow_sample=probe, tolerance=100.0
            )
            assert entry.version == 2
            time.sleep(0.1)  # guaranteed post-swap traffic
        finally:
            stop = True
            for t in threads:
                t.join(timeout=30)
        assert not errors, f"requests failed during swap: {errors[:3]}"
        n_old = sum(1 for o in outs if np.array_equal(o, want_old))
        n_new = sum(1 for o in outs if np.array_equal(o, want_new))
        assert n_old + n_new == len(outs), (
            "a response matched neither version bitwise — torn swap"
        )
        assert n_old > 0 and n_new > 0
        # post-swap steady state: the new version, bitwise, every time
        assert np.array_equal(reg.predict("hot", probe), want_new)
