"""Model selection and pipelines over LIVE DataFrames (VERDICT r2 missing
#4): CrossValidator/TrainValidationSplit split with randomSplit/union (no
row leaves the cluster for the split), and Pipeline chains Spark-wrapped
stages end to end.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.localspark import LocalSparkSession
from spark_rapids_ml_tpu.localspark import types as LT
from spark_rapids_ml_tpu.models.pipeline import Pipeline
from spark_rapids_ml_tpu.models.tuning import (
    BinaryClassificationEvaluator,
    ClusteringEvaluator,
    CrossValidator,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
    RegressionEvaluator,
    TrainValidationSplit,
)
from spark_rapids_ml_tpu.spark import (
    SparkKMeans,
    SparkLinearRegression,
    SparkLogisticRegression,
    SparkPCA,
    SparkStandardScaler,
)


@pytest.fixture(scope="module")
def session():
    s = LocalSparkSession(
        parallelism=4,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "JAX_ENABLE_X64": "1",
            "JAX_COMPILATION_CACHE_DIR": "/tmp/jax_test_cache",
        },
    )
    yield s
    s.stop()


def _labeled_df(session, x, y, partitions=4):
    schema = LT.StructType(
        [
            LT.StructField("features", LT.ArrayType(LT.DoubleType())),
            LT.StructField("label", LT.DoubleType()),
        ]
    )
    return session.createDataFrame(
        [(row.tolist(), float(lbl)) for row, lbl in zip(x, y)],
        schema,
        numPartitions=partitions,
    )


def _features_df(session, x, partitions=4):
    schema = LT.StructType(
        [LT.StructField("features", LT.ArrayType(LT.DoubleType()))]
    )
    return session.createDataFrame(
        [(row.tolist(),) for row in x], schema, numPartitions=partitions
    )


class TestCrossValidatorOverDataFrames:
    def test_cv_picks_the_right_reg_param(self, session):
        rng = np.random.default_rng(30)
        x = rng.normal(size=(400, 6))
        coef = np.array([2.0, -1.0, 0.5, 0.0, 1.0, -0.5])
        y = x @ coef + 0.05 * rng.normal(size=400)
        df = _labeled_df(session, x, y)
        grid = ParamGridBuilder().addGrid("regParam", [0.0, 10.0]).build()
        cv = CrossValidator(
            estimator=SparkLinearRegression(),
            estimatorParamMaps=grid,
            evaluator=RegressionEvaluator(),
            numFolds=3,
            seed=1,
        )
        fitted = cv.fit(df)
        # near-noiseless linear data: lambda=0 must beat heavy shrinkage
        assert fitted.bestIndex == 0
        assert len(fitted.avgMetrics) == 2
        assert fitted.avgMetrics[0] < fitted.avgMetrics[1]  # rmse lower better
        np.testing.assert_allclose(
            fitted.bestModel.coefficients, coef, atol=0.05
        )
        preds = np.asarray(
            [r["prediction"] for r in fitted.transform(df).collect()]
        )
        assert preds.shape == (400,)

    def test_cv_multinomial_f1_over_dataframes(self, session):
        # the r3 verdict's gap: CV over a >=3-class problem had no metric
        # to optimize — the multinomial softmax estimator is now tunable
        rng = np.random.default_rng(31)
        rows = 360
        centers = np.array(
            [[2.0, 0.0, 0.0], [0.0, 2.0, 0.0], [0.0, 0.0, 2.0]]
        )
        y = np.arange(rows, dtype=float) % 3
        x = centers[y.astype(int)] + 0.6 * rng.normal(size=(rows, 3))
        df = _labeled_df(session, x, y)
        grid = ParamGridBuilder().addGrid("regParam", [0.001, 100.0]).build()
        cv = CrossValidator(
            estimator=SparkLogisticRegression(maxIter=40),
            estimatorParamMaps=grid,
            evaluator=MulticlassClassificationEvaluator(),
            numFolds=3,
            seed=2,
        )
        fitted = cv.fit(df)
        assert fitted.bestIndex == 0  # crushing L2 loses on weighted f1
        assert fitted.avgMetrics[0] > fitted.avgMetrics[1]
        assert fitted.bestModel.coefficientMatrix.shape == (3, 3)

    def test_multiclass_log_loss_reads_probability_col(self, session):
        rng = np.random.default_rng(32)
        rows = 240
        centers = np.array([[2.5, 0.0], [0.0, 2.5], [-2.5, -2.5]])
        y = np.arange(rows, dtype=float) % 3
        x = centers[y.astype(int)] + 0.5 * rng.normal(size=(rows, 2))
        df = _labeled_df(session, x, y)
        # regParam>0: separable clusters have no finite unregularized MLE
        model = (
            SparkLogisticRegression(maxIter=40, regParam=1e-3)
            .setProbabilityCol("probability")
            .fit(df)
        )
        out = model.transform(df)
        ll = MulticlassClassificationEvaluator(metricName="logLoss").evaluate(out)
        assert 0.0 < ll < 0.5  # well-separated clusters: confident fit
        # degenerate evaluator misuse surfaces a descriptive error
        with pytest.raises(ValueError, match="probability column"):
            MulticlassClassificationEvaluator(
                metricName="logLoss", probabilityCol="nope"
            ).evaluate(out)

    def test_weighted_evaluator_reads_weight_column(self, session):
        # weightCol on the evaluator: the DF carries per-row weights; the
        # duplication oracle runs on an expanded unweighted DF
        rng = np.random.default_rng(33)
        rows = 120
        x = rng.normal(size=(rows, 3))
        y = x @ np.array([1.0, -1.0, 0.5]) + 0.1 * rng.normal(size=rows)
        pred = y + 0.3 * rng.normal(size=rows)
        w = rng.integers(1, 4, size=rows).astype(float)
        schema = LT.StructType(
            [
                LT.StructField("label", LT.DoubleType()),
                LT.StructField("prediction", LT.DoubleType()),
                LT.StructField("w", LT.DoubleType()),
            ]
        )
        df = session.createDataFrame(
            [(float(a), float(b), float(c)) for a, b, c in zip(y, pred, w)],
            schema,
            numPartitions=3,
        )
        got = RegressionEvaluator(weightCol="w").evaluate(df)
        rep = np.repeat(np.arange(rows), w.astype(int))
        want = RegressionEvaluator().evaluate(
            (None, y[rep]), predictions=pred[rep]
        )
        assert abs(got - want) < 1e-12

    def test_cv_auc_over_dataframes(self, session):
        rng = np.random.default_rng(31)
        x = rng.normal(size=(300, 3))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5]))))
        y = (rng.random(300) < p).astype(float)
        df = _labeled_df(session, x, y)
        cv = CrossValidator(
            estimator=SparkLogisticRegression().setMaxIter(8),
            estimatorParamMaps=[{"regParam": 1e-3}],
            evaluator=BinaryClassificationEvaluator(),
            numFolds=2,
            seed=2,
        )
        fitted = cv.fit(df)
        assert fitted.avgMetrics[0] > 0.8  # AUC on ranked probabilities

    def test_weighted_df_cv_ranks_on_probability_surface(self, session):
        # ADVICE r4: with weightCol set and a DataFrame validation set,
        # _fit_and_eval must still rank AUC on the probability surface —
        # weighted and unweighted CV score the same surface, and no
        # degradation warning fires.
        import warnings

        from spark_rapids_ml_tpu.models.tuning import _fit_and_eval

        rng = np.random.default_rng(38)
        x = rng.normal(size=(300, 3))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5]))))
        y = (rng.random(300) < p).astype(float)
        w = rng.uniform(0.5, 3.0, size=300)
        schema = LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
                LT.StructField("w", LT.DoubleType()),
            ]
        )
        rows = [
            (row.tolist(), float(lbl), float(wt))
            for row, lbl, wt in zip(x, y, w)
        ]
        train = session.createDataFrame(rows[:200], schema, numPartitions=3)
        val = session.createDataFrame(rows[200:], schema, numPartitions=3)
        ev = BinaryClassificationEvaluator(weightCol="w")
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any degradation warning fails
            model, auc = _fit_and_eval(
                SparkLogisticRegression(regParam=1e-3), {}, ev, train, val
            )
        # oracle: weighted AUC of the SAME model's probabilities on val
        scores = model.predict_proba_matrix(x[200:])
        want = ev.evaluate((x[200:], y[200:], w[200:]), predictions=scores)
        assert abs(auc - want) < 1e-12
        # and the surface genuinely differs from hard-label ranking
        hard = (np.asarray(scores).reshape(len(scores), -1)[:, -1] >= 0.5).astype(float)
        auc_hard = ev.evaluate(
            (x[200:], y[200:], w[200:]), predictions=hard
        )
        assert auc > auc_hard

    def test_evaluator_reads_probability_col_on_dataframe(self, session):
        from sklearn.metrics import roc_auc_score

        rng = np.random.default_rng(37)
        x = rng.normal(size=(300, 3))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5]))))
        y = (rng.random(300) < p).astype(float)
        df = _labeled_df(session, x, y)
        model = (
            SparkLogisticRegression().setRegParam(1e-3)
            .setProbabilityCol("probability").fit(df)
        )
        out = model.transform(df)
        ev = BinaryClassificationEvaluator().setRawPredictionCol("probability")
        auc = ev.evaluate(out)
        rows = out.collect()
        got_y = np.asarray([r["label"] for r in rows])
        got_p = np.asarray([r["probability"][1] for r in rows])
        assert abs(auc - roc_auc_score(got_y, got_p)) < 1e-12


class TestTrainValidationSplitOverDataFrames:
    def test_tvs_selects_and_refits(self, session):
        rng = np.random.default_rng(32)
        x = rng.normal(size=(300, 4))
        y = x @ np.array([1.0, 2.0, -1.0, 0.5]) + 0.02 * rng.normal(size=300)
        df = _labeled_df(session, x, y)
        grid = ParamGridBuilder().addGrid("regParam", [0.0, 50.0]).build()
        tvs = TrainValidationSplit(
            estimator=SparkLinearRegression(),
            estimatorParamMaps=grid,
            evaluator=RegressionEvaluator(),
            trainRatio=0.7,
            seed=3,
        )
        fitted = tvs.fit(df)
        assert fitted.bestIndex == 0
        assert len(fitted.validationMetrics) == 2

    def test_clustering_evaluator_over_dataframe(self, session):
        rng = np.random.default_rng(33)
        centers = np.array([[5.0, 5.0], [-5.0, -5.0]])
        x = np.vstack([rng.normal(size=(60, 2)) * 0.4 + c for c in centers])
        df = _features_df(session, x)
        model = SparkKMeans().setInputCol("features").setK(2).setSeed(0).fit(df)
        out = model.transform(df)
        score = ClusteringEvaluator().evaluate(out)
        assert score > 0.8  # well-separated blobs


class TestPipelineOverDataFrames:
    def test_scaler_then_pca_pipeline(self, session):
        from spark_rapids_ml_tpu import PCA, StandardScaler

        rng = np.random.default_rng(34)
        x = rng.normal(size=(200, 6)) * np.array([1, 5, 10, 0.5, 2, 1]) + 3.0
        df = _features_df(session, x)
        pipe = Pipeline(
            stages=[
                SparkStandardScaler()
                .setInputCol("features")
                .setOutputCol("scaled"),
                SparkPCA().setInputCol("scaled").setOutputCol("pca").setK(3),
            ]
        )
        fitted = pipe.fit(df)
        out = fitted.transform(df).collect()
        assert len(out) == 200 and len(out[0]["pca"]) == 3
        # differential vs the core pipeline on the same data
        core_scaled = (
            StandardScaler().setInputCol("features").setOutputCol("scaled").fit(x)
        )
        xs = np.asarray(core_scaled.transform(x))
        core_pca = PCA().setInputCol("scaled").setK(3).fit(xs)
        got = np.asarray([r["pca"] for r in out])
        want = xs @ core_pca.pc
        np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-6)

    def test_union_round_trips_rows(self, session):
        rng = np.random.default_rng(35)
        x = rng.normal(size=(50, 3))
        df = _features_df(session, x, partitions=2)
        a, b = df.randomSplit([0.5, 0.5], seed=0)
        u = a.union(b)
        assert u.count() == 50
        got = np.sort(
            np.asarray([r[0] for r in u.collect()], dtype=np.float64), axis=0
        )
        np.testing.assert_allclose(got, np.sort(x, axis=0), atol=1e-12)

    def test_union_is_positional(self, session):
        # pyspark union semantics: columns map by POSITION, not name
        a = session.createDataFrame(
            [(1.0, 10.0)],
            LT.StructType(
                [
                    LT.StructField("x", LT.DoubleType()),
                    LT.StructField("y", LT.DoubleType()),
                ]
            ),
        )
        b = session.createDataFrame(
            [(2.0, 20.0)],
            LT.StructType(
                [
                    LT.StructField("y", LT.DoubleType()),
                    LT.StructField("x", LT.DoubleType()),
                ]
            ),
        )
        rows = a.union(b).select("x").collect()
        assert sorted(r[0] for r in rows) == [1.0, 2.0]
