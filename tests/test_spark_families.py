"""r5 family Spark wrappers over the bundled localspark engine.

The wrappers run the SAME plan code on localspark and real pyspark
(``_sql_mods`` dispatch), so these localspark-driven tests exercise the
actual mapInArrow bodies, schema handling, and collect paths; the pyspark
legs live in the CI integration matrix like every other wrapper family.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.localspark import LocalSparkSession
from spark_rapids_ml_tpu.localspark import types as LT
from spark_rapids_ml_tpu.spark import (
    SparkDBSCAN,
    SparkNearestNeighbors,
    SparkNearestNeighborsModel,
    SparkRandomForestClassifier,
    SparkRandomForestRegressor,
)


@pytest.fixture(scope="module")
def spark():
    with LocalSparkSession(parallelism=3) as s:
        yield s


def _features_df(s, x, extra=None, num_partitions=3):
    fields = [LT.StructField("features", LT.ArrayType(LT.DoubleType()))]
    rows = [(row.tolist(),) for row in x]
    if extra:
        for name, typ, vals in extra:
            fields.append(LT.StructField(name, typ))
        rows = [
            base + tuple(float(vals[i]) for _, _, vals in extra)
            for i, base in enumerate(rows)
        ]
    return s.createDataFrame(
        rows, LT.StructType(fields), numPartitions=num_partitions
    )


def test_spark_knn_matches_core(spark, rng):
    items = rng.normal(size=(200, 8))
    queries = rng.normal(size=(40, 8))
    item_df = _features_df(spark, items)
    query_df = _features_df(spark, queries)

    model = SparkNearestNeighbors().setInputCol("features").setK(6).fit(item_df)
    assert isinstance(model, SparkNearestNeighborsModel)
    out = model.kneighbors(query_df)
    rows = sorted(out.collect(), key=lambda r: tuple(r["features"]))

    from spark_rapids_ml_tpu.knn import NearestNeighbors

    core = NearestNeighbors().setK(6).fit(items)
    d_ref, i_ref = core.kneighbors(queries)
    by_query = {
        tuple(q): (d_ref[i], i_ref[i]) for i, q in enumerate(queries)
    }
    for r in rows:
        d, i = by_query[tuple(r["features"])]
        np.testing.assert_array_equal(np.asarray(r["indices"]), i)
        # worker subprocesses compute in f32 (production default);
        # the f64 core reference differs at float32 epsilon
        np.testing.assert_allclose(np.asarray(r["distances"]), d, rtol=1e-5)


def test_spark_knn_id_col(spark, rng):
    items = rng.normal(size=(60, 4))
    ids = (np.arange(60) * 7).astype(float)
    df = _features_df(
        spark, items, extra=[("item_id", LT.DoubleType(), ids)]
    )
    model = (
        SparkNearestNeighbors().setInputCol("features").setIdCol("item_id")
        .setK(1).fit(df)
    )
    out = model.transform(_features_df(spark, items + 1e-12))
    got = {tuple(r["features"]): r["indices"][0] for r in out.collect()}
    for i, row in enumerate(items + 1e-12):
        assert got[tuple(row)] == i * 7


def test_spark_knn_float_ids_schema(spark, rng):
    """Non-integral idCol values keep a DoubleType indices column — the
    declared schema and the worker's cast must agree (real pyspark rejects
    dtype-mismatched mapInArrow batches)."""
    items = rng.normal(size=(30, 3))
    ids = np.arange(30) + 0.5
    df = _features_df(spark, items, extra=[("item_id", LT.DoubleType(), ids)])
    model = (
        SparkNearestNeighbors().setInputCol("features").setIdCol("item_id")
        .setK(1).fit(df)
    )
    out = model.kneighbors(_features_df(spark, items))
    field = {f.name: f for f in out.schema.fields}["indices"]
    assert isinstance(field.dataType.elementType, LT.DoubleType)
    got = {tuple(r["features"]): r["indices"][0] for r in out.collect()}
    for i, row in enumerate(items):
        assert got[tuple(row)] == i + 0.5


def test_spark_dbscan_matches_core(spark, rng):
    blobs = np.concatenate(
        [rng.normal(c, 0.25, size=(40, 3)) for c in (0.0, 6.0, -6.0)]
    )
    noise = rng.uniform(-12, 12, size=(8, 3))
    x = np.concatenate([blobs, noise])
    df = _features_df(spark, x)

    model = (
        SparkDBSCAN().setInputCol("features").setEps(1.2).setMinSamples(5)
        .fit(df)
    )
    out = model.transform(df)
    assert "prediction" in out.schema.names
    got = np.array([r["prediction"] for r in out.collect()])

    from spark_rapids_ml_tpu.clustering import DBSCAN

    ref = DBSCAN().setEps(1.2).setMinSamples(5).fit().clusterLabels(x)
    np.testing.assert_array_equal(got, ref)
    # row order is preserved through the collect-and-rebuild path
    feats = np.stack([np.asarray(r["features"]) for r in out.collect()])
    np.testing.assert_allclose(feats, x)


def test_spark_rf_classifier_both_distributions(spark, rng):
    x = rng.normal(size=(400, 6))
    y = (1.2 * x[:, 0] - x[:, 4] > 0).astype(float)
    df = spark.createDataFrame(
        [(row.tolist(), float(lab)) for row, lab in zip(x, y)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=3,
    )
    est = SparkRandomForestClassifier().setNumTrees(6).setMaxDepth(4).setSeed(2)
    m_driver = est.copy().setDistribution("driver-merge").fit(df)
    m_mesh = est.copy().setDistribution("mesh-local").fit(df)
    # bit-identical trees regardless of distribution mode
    np.testing.assert_array_equal(
        np.asarray(m_driver.trees.feature), np.asarray(m_mesh.trees.feature)
    )
    np.testing.assert_allclose(
        np.asarray(m_driver.trees.leaf_stats),
        np.asarray(m_mesh.trees.leaf_stats),
        rtol=1e-12,
    )

    out = m_driver.transform(df)
    assert {"rawPrediction", "probability", "prediction"} <= set(out.schema.names)
    rows = out.collect()
    acc = np.mean(
        [r["prediction"] == lab for r, lab in zip(rows, y)]
    )
    assert acc > 0.85, acc
    p = np.stack([np.asarray(r["probability"]) for r in rows])
    np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-5)  # f32 workers
    raw = np.stack([np.asarray(r["rawPrediction"]) for r in rows])
    np.testing.assert_allclose(raw, p * 6, rtol=1e-5)


def test_spark_rf_regressor(spark, rng):
    x = rng.normal(size=(400, 5))
    y = 2.0 * x[:, 1] + np.sin(x[:, 3])
    df = spark.createDataFrame(
        [(row.tolist(), float(val)) for row, val in zip(x, y)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=2,
    )
    m = (
        SparkRandomForestRegressor().setNumTrees(8).setMaxDepth(6)
        .setFeatureSubsetStrategy("all").setSeed(4).fit(df)
    )
    preds = np.array([r["prediction"] for r in m.transform(df).collect()])
    r2 = 1 - ((preds - y) ** 2).mean() / y.var()
    assert r2 > 0.8, r2


def test_spark_linear_svc_both_distributions(spark, rng):
    from spark_rapids_ml_tpu.spark import SparkLinearSVC

    x = rng.normal(size=(400, 5))
    y = (x[:, 0] - 0.7 * x[:, 3] > 0).astype(float)
    df = spark.createDataFrame(
        [(r.tolist(), float(l)) for r, l in zip(x, y)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=3,
    )
    est = SparkLinearSVC().setRegParam(0.02).setMaxIter(50)
    m_driver = est.copy().setDistribution("driver-merge").fit(df)
    m_mesh = est.copy().setDistribution("mesh-local").fit(df)
    np.testing.assert_allclose(
        m_driver.coefficients, m_mesh.coefficients, rtol=1e-6, atol=1e-8
    )
    out = m_driver.transform(df)
    assert {"rawPrediction", "prediction"} <= set(out.schema.names)
    rows = out.collect()
    acc = np.mean([r["prediction"] == l for r, l in zip(rows, y)])
    assert acc > 0.9, acc
    raw = np.stack([np.asarray(r["rawPrediction"]) for r in rows])
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1], atol=1e-6)

    # checkpoint kwargs flow through; typos raise (the sibling contract)
    with pytest.raises(TypeError, match="unexpected fit"):
        est.copy().fit(df, checkpont_dir="/tmp/x")
    # mesh-local rejects non-binary labels loudly
    bad = spark.createDataFrame(
        [(r.tolist(), float(i % 3)) for i, r in enumerate(x)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=2,
    )
    with pytest.raises(ValueError, match="binary 0/1"):
        est.copy().setDistribution("mesh-local").fit(bad)


def test_spark_wrappers_fall_through_to_core(rng):
    """Non-Spark inputs keep the core contract on every r5 wrapper."""
    x = rng.normal(size=(50, 4))
    m = SparkNearestNeighbors().setK(3).fit(x)
    d, i = m.kneighbors(x[:5])
    assert d.shape == (5, 3)
    db = SparkDBSCAN().setEps(0.8).setMinSamples(3).fit()
    assert db.clusterLabels(x).shape == (50,)
    y = (x[:, 0] > 0).astype(float)
    rf = SparkRandomForestClassifier().setNumTrees(2).fit((x, y))
    assert rf._predict_matrix(x).shape == (50,)


def test_spark_ann_matches_core(spark, rng):
    from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors
    from spark_rapids_ml_tpu.spark import SparkApproximateNearestNeighbors

    centers = rng.normal(scale=8, size=(10, 6))
    items = np.concatenate(
        [c + rng.normal(scale=0.5, size=(40, 6)) for c in centers]
    )
    queries = items[::25] + 1e-9
    item_df = _features_df(spark, items)
    est_kw = dict(k=4, nlist=10, nprobe=10, seed=3)
    model = (
        SparkApproximateNearestNeighbors(**est_kw)
        .setInputCol("features").fit(item_df)
    )
    out = model.kneighbors(_features_df(spark, queries))
    got = {
        tuple(np.round(r["features"], 9)): np.asarray(r["indices"])
        for r in out.collect()
    }
    core = ApproximateNearestNeighbors(**est_kw).fit(items)
    _, i_ref = core.kneighbors(queries)
    for q, idx in zip(queries, i_ref):
        np.testing.assert_array_equal(got[tuple(np.round(q, 9))], idx)


def test_spark_umap_fit_and_distributed_transform(spark, rng):
    from spark_rapids_ml_tpu.spark import SparkUMAP, SparkUMAPModel

    centers = rng.normal(scale=10, size=(3, 8))
    x = np.concatenate(
        [c + rng.normal(scale=0.4, size=(60, 8)) for c in centers]
    )
    labels = np.repeat(np.arange(3), 60)
    df = _features_df(spark, x)
    model = (
        SparkUMAP().setInputCol("features").setNNeighbors(10)
        .setNEpochs(80).setSeed(2).fit(df)
    )
    assert isinstance(model, SparkUMAPModel)
    assert model.embedding_.shape == (len(x), 2)
    out = model.transform(_features_df(spark, x[:30]))
    emb = np.stack([np.asarray(r["embedding"]) for r in out.collect()])
    assert emb.shape == (30, 2)
    # transformed points land nearest their own cluster's embedded centroid
    cmeans = np.stack(
        [model.embedding_[labels == c].mean(0) for c in range(3)]
    )
    d = np.linalg.norm(emb[:, None, :] - cmeans[None, :, :], axis=2)
    assert (d.argmin(1) == labels[:30]).mean() >= 0.9


def test_spark_gbt_matches_core(spark, rng):
    from spark_rapids_ml_tpu.classification import GBTClassifier
    from spark_rapids_ml_tpu.spark import SparkGBTClassifier, SparkGBTRegressor

    x = rng.normal(size=(400, 5))
    y = (1.3 * x[:, 0] - x[:, 2] > 0).astype(float)
    df = spark.createDataFrame(
        [(r.tolist(), float(l)) for r, l in zip(x, y)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=3,
    )
    m = SparkGBTClassifier().setMaxIter(10).setSeed(2).fit(df)
    core = GBTClassifier().setMaxIter(10).setSeed(2).fit((x, y))
    np.testing.assert_array_equal(
        np.asarray(m.trees.feature), np.asarray(core.trees.feature)
    )
    out = m.transform(df)
    rows = out.collect()
    assert {"rawPrediction", "probability", "prediction"} <= set(
        out.schema.names
    )
    acc = np.mean([r["prediction"] == l for r, l in zip(rows, y)])
    assert acc > 0.9, acc
    p = np.stack([np.asarray(r["probability"]) for r in rows])
    raw = np.stack([np.asarray(r["rawPrediction"]) for r in rows])
    # raw recovers the margin: p1 = sigma(raw[:, 1])
    np.testing.assert_allclose(
        p[:, 1], 1 / (1 + np.exp(-raw[:, 1])), rtol=1e-4
    )

    yr = 2.0 * x[:, 1] + np.sin(x[:, 3])
    rdf = spark.createDataFrame(
        [(r.tolist(), float(v)) for r, v in zip(x, yr)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=2,
    )
    mr = SparkGBTRegressor().setMaxIter(15).setMaxBins(64).fit(rdf)
    preds = np.array([r["prediction"] for r in mr.transform(rdf).collect()])
    r2 = 1 - ((preds - yr) ** 2).mean() / yr.var()
    assert r2 > 0.85, r2


def test_spark_one_vs_rest(spark, rng):
    from spark_rapids_ml_tpu.classification import LinearSVC
    from spark_rapids_ml_tpu.spark import SparkOneVsRest

    centers = rng.normal(scale=8, size=(3, 4))
    x = np.concatenate([c + rng.normal(size=(80, 4)) for c in centers])
    y = np.repeat(np.arange(3.0), 80)
    df = spark.createDataFrame(
        [(r.tolist(), float(l)) for r, l in zip(x, y)],
        LT.StructType(
            [
                LT.StructField("features", LT.ArrayType(LT.DoubleType())),
                LT.StructField("label", LT.DoubleType()),
            ]
        ),
        numPartitions=3,
    )
    m = (
        SparkOneVsRest()
        .setClassifier(LinearSVC().setRegParam(0.01))
        .fit(df)
    )
    assert m.numClasses == 3
    rows = m.transform(df).collect()
    acc = np.mean([r["prediction"] == l for r, l in zip(rows, y)])
    assert acc > 0.95, acc


def test_wrapper_upgrade_loads(tmp_path, rng):
    """A core-model save opens through its Spark wrapper class (the
    richer-subclass upgrade rule, models/base._resolve_load_class) for
    every r5 family — the train-local / serve-on-Spark handoff."""
    from spark_rapids_ml_tpu.classification import (
        LinearSVC,
        RandomForestClassifier,
    )
    from spark_rapids_ml_tpu.knn import NearestNeighbors
    from spark_rapids_ml_tpu.spark import (
        SparkLinearSVCModel,
        SparkNearestNeighborsModel,
        SparkRandomForestClassificationModel,
    )

    x = rng.normal(size=(80, 4))
    y = (x[:, 0] > 0).astype(float)

    rf = RandomForestClassifier().setNumTrees(2).setMaxDepth(2).fit((x, y))
    rf.save(str(tmp_path / "rf"))
    rf_up = SparkRandomForestClassificationModel.load(str(tmp_path / "rf"))
    assert isinstance(rf_up, SparkRandomForestClassificationModel)
    np.testing.assert_array_equal(
        rf_up._predict_matrix(x), rf._predict_matrix(x)
    )

    svc = LinearSVC().setRegParam(0.1).fit((x, y))
    svc.save(str(tmp_path / "svc"))
    svc_up = SparkLinearSVCModel.load(str(tmp_path / "svc"))
    assert isinstance(svc_up, SparkLinearSVCModel)
    np.testing.assert_allclose(svc_up.coefficients, svc.coefficients)

    nn = NearestNeighbors().setK(3).fit(x)
    nn.save(str(tmp_path / "nn"))
    nn_up = SparkNearestNeighborsModel.load(str(tmp_path / "nn"))
    assert isinstance(nn_up, SparkNearestNeighborsModel)
    d0, i0 = nn.kneighbors(x[:5])
    d1, i1 = nn_up.kneighbors(x[:5])
    np.testing.assert_array_equal(i0, i1)

    # the composite family: a core OneVsRest save upgrades through the
    # wrapper class's inherited custom load (subdirectory sub-models)
    from spark_rapids_ml_tpu.classification import OneVsRest
    from spark_rapids_ml_tpu.spark import SparkOneVsRestModel

    ovr = OneVsRest(classifier=LinearSVC().setRegParam(0.05)).fit((x, y))
    ovr.save(str(tmp_path / "ovr"))
    ovr_up = SparkOneVsRestModel.load(str(tmp_path / "ovr"))
    assert isinstance(ovr_up, SparkOneVsRestModel)
    assert ovr_up.numClasses == ovr.numClasses
    np.testing.assert_array_equal(
        ovr_up._predict_matrix(x[:20]), ovr._predict_matrix(x[:20])
    )


def test_spark_close_family_wrappers(spark, rng):
    """The five r5-close supervised wrappers: DataFrame fit equals the
    core array fit; classifier transforms emit the three Spark columns."""
    from spark_rapids_ml_tpu.classification import NaiveBayes
    from spark_rapids_ml_tpu.spark import (
        SparkFMRegressor,
        SparkIsotonicRegression,
        SparkMultilayerPerceptronClassifier,
        SparkNaiveBayes,
    )

    x = np.abs(rng.normal(size=(240, 4))) * 3
    y = (x[:, 0] > x[:, 1]).astype(float)
    schema = LT.StructType(
        [
            LT.StructField("features", LT.ArrayType(LT.DoubleType())),
            LT.StructField("label", LT.DoubleType()),
        ]
    )
    df = spark.createDataFrame(
        [(r.tolist(), float(l)) for r, l in zip(x, y)], schema,
        numPartitions=3,
    )

    nb = SparkNaiveBayes().fit(df)
    core = NaiveBayes().fit((x, y))
    np.testing.assert_allclose(nb.theta, core.theta, rtol=1e-6)
    nb_out = nb.transform(df)
    assert {"rawPrediction", "probability", "prediction"} <= set(
        nb_out.schema.names
    )
    nacc = np.mean(
        [r["prediction"] == l for r, l in zip(nb_out.collect(), y)]
    )
    assert nacc > 0.7, nacc

    mlp = (
        SparkMultilayerPerceptronClassifier().setLayers([4, 8, 2])
        .setMaxIter(80).setSeed(1).fit(df)
    )
    macc = np.mean(
        [r["prediction"] == l for r, l in zip(mlp.transform(df).collect(), y)]
    )
    assert macc > 0.9, macc

    yr = x[:, 0] * x[:, 1]  # interaction target
    rdf = spark.createDataFrame(
        [(r.tolist(), float(v)) for r, v in zip(x, yr)], schema,
        numPartitions=2,
    )
    fm = (
        SparkFMRegressor().setFactorSize(3).setMaxIter(400)
        .setStepSize(0.05).fit(rdf)
    )
    preds = np.array([r["prediction"] for r in fm.transform(rdf).collect()])
    r2 = 1 - ((preds - yr) ** 2).mean() / yr.var()
    assert r2 > 0.85, r2

    iso = SparkIsotonicRegression().fit(rdf)  # monotone-ish in feature 0
    out = iso.transform(rdf).collect()
    assert all(np.isfinite(r["prediction"]) for r in out)
