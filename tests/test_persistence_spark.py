"""Persistence: Spark-ML-layout interop, fsspec URLs, and overwrite().

VERDICT r2 missing #6 / weak #7: models must round-trip with a Spark
cluster — the stock pyspark.ml on-disk layout (metadata/part-00000 JSON +
data/ parquet of UDT structs, RapidsPCA.scala:193-229), remote paths via
fsspec, and a ``write().overwrite().save()`` that actually overwrites.
"""

import json

import numpy as np
import pyarrow.parquet as pq
import pytest

from spark_rapids_ml_tpu import PCA, StandardScaler
from spark_rapids_ml_tpu.models.base import Saveable
from spark_rapids_ml_tpu.models.pca import PCAModel
from spark_rapids_ml_tpu.models.scaler import StandardScalerModel
from spark_rapids_ml_tpu.utils import persistence as P


@pytest.fixture
def pca_model(rng=np.random.default_rng(0)):
    x = rng.normal(size=(200, 8))
    return PCA().setInputCol("f").setOutputCol("out").setK(3).fit(x)


@pytest.fixture
def scaler_model(rng=np.random.default_rng(1)):
    x = rng.normal(size=(100, 5)) * 3.0 + 2.0
    return StandardScaler().setInputCol("f").fit(x)


class TestOverwrite:
    def test_save_refuses_existing_without_overwrite(self, pca_model, tmp_path):
        p = str(tmp_path / "m")
        pca_model.save(p)
        with pytest.raises(FileExistsError):
            pca_model.save(p)

    def test_writer_overwrite_actually_overwrites(self, pca_model, tmp_path):
        # VERDICT r2 weak #7: this was a stub nothing read
        p = str(tmp_path / "m")
        pca_model.save(p)
        pca_model.write().overwrite().save(p)
        loaded = PCAModel.load(p)
        np.testing.assert_allclose(loaded.pc, pca_model.pc)

    def test_overwrite_replaces_stale_contents(self, pca_model, tmp_path):
        # overwrite must REPLACE the directory, not merge into it: a stale
        # data.parquet from a differently-shaped model must not survive
        p = str(tmp_path / "m")
        pca_model.save(p)
        (tmp_path / "m" / "stale_file").write_text("junk")
        pca_model.write().overwrite().save(p)
        assert not (tmp_path / "m" / "stale_file").exists()


class TestSparkLayout:
    def test_pca_spark_layout_structure(self, pca_model, tmp_path):
        p = tmp_path / "spark_m"
        pca_model.save(str(p), layout="spark")
        # Spark's DefaultParamsReader shape: one-line JSON + _SUCCESS
        meta_text = (p / "metadata" / "part-00000").read_text()
        assert "\n" not in meta_text.strip()
        meta = json.loads(meta_text)
        assert meta["class"] == "org.apache.spark.ml.feature.PCAModel"
        assert meta["uid"] == pca_model.uid
        assert meta["paramMap"]["k"] == 3
        assert "sparkVersion" in meta
        assert (p / "metadata" / "_SUCCESS").exists()
        # data/: parquet with the Spark row-metadata schema key, values
        # column-major (DenseMatrix layout)
        parts = [
            f for f in (p / "data").iterdir() if f.name.endswith(".parquet")
        ]
        assert len(parts) == 1
        table = pq.read_table(parts[0])
        schema_json = json.loads(
            table.schema.metadata[P._SPARK_ROW_METADATA_KEY.encode()].decode()
        )
        assert schema_json["fields"][0]["type"]["class"].endswith("MatrixUDT")
        pc_row = table.column("pc")[0].as_py()
        assert pc_row["numRows"] == 8 and pc_row["numCols"] == 3
        np.testing.assert_allclose(
            np.asarray(pc_row["values"]), pca_model.pc.flatten(order="F")
        )

    def test_pca_spark_layout_round_trip(self, pca_model, tmp_path):
        p = str(tmp_path / "spark_m")
        pca_model.save(p, layout="spark")
        loaded = PCAModel.load(p)  # auto-detects the layout
        np.testing.assert_allclose(loaded.pc, pca_model.pc, atol=1e-12)
        np.testing.assert_allclose(
            loaded.explainedVariance, pca_model.explainedVariance, atol=1e-12
        )
        assert loaded.getK() == 3
        assert loaded.getInputCol() == "f"
        assert loaded.getOutputCol() == "out"

    def test_scaler_spark_layout_round_trip(self, scaler_model, tmp_path):
        p = str(tmp_path / "spark_s")
        scaler_model.save(p, layout="spark")
        loaded = StandardScalerModel.load(p)
        np.testing.assert_allclose(loaded.mean, scaler_model.mean, atol=1e-12)
        np.testing.assert_allclose(loaded.std, scaler_model.std, atol=1e-12)

    def test_writer_format_spark(self, pca_model, tmp_path):
        p = str(tmp_path / "m")
        pca_model.write().format("spark").save(p)
        assert P.is_spark_ml_layout(p)

    def test_unmapped_class_rejected(self, pca_model, tmp_path):
        p = tmp_path / "weird"
        P.save_spark_ml_metadata(
            str(p),
            class_name="org.apache.spark.ml.feature.Word2VecModel",
            uid="w2v",
            param_map={},
        )
        with pytest.raises(TypeError, match="no mapped implementation"):
            Saveable.load(str(p))

    def test_estimator_without_spark_twin_rejected(self, tmp_path):
        est = PCA().setK(2)
        with pytest.raises(NotImplementedError, match="no stock Spark ML twin"):
            est.save(str(tmp_path / "e"), layout="spark")


class TestStructDecoding:
    def test_matrix_transposed_layout(self):
        row = {
            "type": 1, "numRows": 2, "numCols": 3,
            "colPtrs": None, "rowIndices": None,
            "values": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0], "isTransposed": True,
        }
        np.testing.assert_allclose(
            P.struct_to_matrix(row), [[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]
        )

    def test_matrix_csc_sparse(self):
        # [[5, 0], [0, 7]] in CSC
        row = {
            "type": 0, "numRows": 2, "numCols": 2,
            "colPtrs": [0, 1, 2], "rowIndices": [0, 1],
            "values": [5.0, 7.0], "isTransposed": False,
        }
        np.testing.assert_allclose(P.struct_to_matrix(row), [[5.0, 0.0], [0.0, 7.0]])

    def test_sparse_vector(self):
        row = {"type": 0, "size": 4, "indices": [0, 3], "values": [1.0, 2.0]}
        np.testing.assert_allclose(P.struct_to_vector(row), [1.0, 0.0, 0.0, 2.0])


class TestFsspecPaths:
    """Remote-path persistence through fsspec's built-in memory:// filesystem
    — the same code path s3://, gs://, and hdfs:// take (fsspec is a
    declared test dependency)."""

    def test_native_layout_memory_url(self, pca_model):
        url = "memory://tpu-ml-test/native_m"
        pca_model.save(url, overwrite=True)
        loaded = PCAModel.load(url)
        np.testing.assert_allclose(loaded.pc, pca_model.pc, atol=1e-12)

    def test_spark_layout_memory_url(self, pca_model):
        url = "memory://tpu-ml-test/spark_m"
        pca_model.save(url, overwrite=True, layout="spark")
        loaded = PCAModel.load(url)
        np.testing.assert_allclose(loaded.pc, pca_model.pc, atol=1e-12)

    def test_overwrite_on_memory_url(self, pca_model):
        url = "memory://tpu-ml-test/ow_m"
        pca_model.save(url, overwrite=True)
        with pytest.raises(FileExistsError):
            pca_model.save(url)
        pca_model.write().overwrite().save(url)


class TestReviewRegressions:
    """Regression tests for the r3 review findings on this layer."""

    def test_bad_layout_does_not_destroy_existing_save(self, pca_model, tmp_path):
        p = str(tmp_path / "m")
        pca_model.save(p)
        with pytest.raises(ValueError, match="layout"):
            pca_model.save(p, overwrite=True, layout="parquet")
        # the old save must still load — validation precedes deletion
        np.testing.assert_allclose(PCAModel.load(p).pc, pca_model.pc)

    def test_spark_layout_on_unsupported_model_keeps_save(self, tmp_path):
        from spark_rapids_ml_tpu.models.scaler import Normalizer

        nm = Normalizer().setP(2.0)
        p = str(tmp_path / "n")
        nm.save(p)
        with pytest.raises(NotImplementedError):
            nm.save(p, overwrite=True, layout="spark")
        assert Normalizer.load(p).getP() == 2.0

    def test_subclass_wrapper_loads_spark_layout(self, pca_model, tmp_path):
        from spark_rapids_ml_tpu.spark import SparkPCAModel

        p = str(tmp_path / "m")
        pca_model.save(p, layout="spark")
        loaded = SparkPCAModel.load(p)
        assert isinstance(loaded, SparkPCAModel)
        np.testing.assert_allclose(loaded.pc, pca_model.pc, atol=1e-12)

    def test_csr_sparse_matrix_decodes(self):
        # SparseMatrix(isTransposed=True) is CSR: [[0, 9], [8, 0]]
        row = {
            "type": 0, "numRows": 2, "numCols": 2,
            "colPtrs": [0, 1, 2], "rowIndices": [1, 0],
            "values": [9.0, 8.0], "isTransposed": True,
        }
        np.testing.assert_allclose(P.struct_to_matrix(row), [[0.0, 9.0], [8.0, 0.0]])


from pyspark_support import have_pyspark as _have_pyspark


@pytest.mark.skipif(
    not _have_pyspark(),
    reason="pyspark not installed: STOCK Spark ML loading our spark-layout "
    "saves NOT exercised locally — this is the Scala shim's load contract "
    "(PCAModel.load); see CI pyspark-integration matrix, which selects "
    "this module",
)
class TestStockSparkMLLoadsOurSaves:
    """The interop claim behind the whole JVM story: a save produced by
    ``layout="spark"`` must load in STOCK Spark ML (the same JVM reader
    ``org.apache.spark.ml.feature.PCAModel.load`` the Scala shim calls,
    driven here through pyspark) and transform identically."""

    @pytest.fixture(scope="class")
    def spark(self):
        from pyspark.sql import SparkSession

        s = (
            SparkSession.builder.master("local[2]")
            .appName("tpu-ml-persistence-it")
            .getOrCreate()
        )
        yield s
        s.stop()

    def test_stock_pca_model_loads_and_transforms(self, spark, tmp_path):
        from pyspark.ml.feature import PCAModel as StockPCAModel
        from pyspark.ml.linalg import Vectors

        rng = np.random.default_rng(3)
        x = rng.normal(size=(150, 6))
        ours = (
            PCA().setInputCol("features").setOutputCol("pca").setK(3).fit(x)
        )
        p = str(tmp_path / "m")
        ours.save(p, layout="spark")

        stock = StockPCAModel.load(p)
        assert stock.getK() == 3
        np.testing.assert_allclose(
            np.asarray(stock.pc.toArray()), ours.pc, atol=1e-12
        )
        df = spark.createDataFrame(
            [(Vectors.dense(row),) for row in x], ["features"]
        )
        got = np.asarray(
            [r["pca"].toArray() for r in stock.transform(df).collect()]
        )
        want = x @ ours.pc
        np.testing.assert_allclose(got, want, atol=1e-9)

    def test_stock_save_loads_back_here(self, spark, tmp_path):
        # the reverse direction: a save written by STOCK Spark ML loads in
        # this framework (cluster-trained model, local inference)
        from pyspark.ml.feature import PCA as StockPCA
        from pyspark.ml.linalg import Vectors

        rng = np.random.default_rng(4)
        x = rng.normal(size=(120, 5))
        df = spark.createDataFrame(
            [(Vectors.dense(row),) for row in x], ["features"]
        )
        stock = (
            StockPCA()
            .setInputCol("features")
            .setOutputCol("pca")
            .setK(2)
            .fit(df)
        )
        p = str(tmp_path / "stock")
        stock.save(p)
        ours = PCAModel.load(p)
        np.testing.assert_allclose(
            ours.pc, np.asarray(stock.pc.toArray()), atol=1e-12
        )

    def test_stock_minmax_scaler_model_loads_ours(self, spark, tmp_path):
        from pyspark.ml.feature import MinMaxScalerModel as StockMinMax
        from pyspark.ml.linalg import Vectors

        from spark_rapids_ml_tpu.models.scaler import MinMaxScaler

        rng = np.random.default_rng(5)
        x = rng.uniform(1.0, 9.0, size=(80, 4))
        ours = (
            MinMaxScaler()
            .setInputCol("features")
            .setOutputCol("scaled")
            .setMax(2.0)
            .fit(x)
        )
        p = str(tmp_path / "mm")
        ours.save(p, layout="spark")
        stock = StockMinMax.load(p)
        np.testing.assert_allclose(
            np.asarray(stock.originalMin.toArray()), ours.originalMin, atol=1e-12
        )
        np.testing.assert_allclose(
            np.asarray(stock.originalMax.toArray()), ours.originalMax, atol=1e-12
        )
        assert stock.getMax() == 2.0
        df = spark.createDataFrame(
            [(Vectors.dense(row),) for row in x], ["features"]
        )
        got = np.asarray(
            [r["scaled"].toArray() for r in stock.transform(df).collect()]
        )
        np.testing.assert_allclose(
            np.sort(got, 0), np.sort(ours.transform(x), 0), atol=1e-9
        )

    def test_stock_maxabs_scaler_model_loads_ours(self, spark, tmp_path):
        from pyspark.ml.feature import MaxAbsScalerModel as StockMaxAbs

        from spark_rapids_ml_tpu.models.scaler import MaxAbsScaler

        rng = np.random.default_rng(6)
        x = rng.normal(size=(60, 3)) * 4
        ours = MaxAbsScaler().setInputCol("features").fit(x)
        p = str(tmp_path / "ma")
        ours.save(p, layout="spark")
        stock = StockMaxAbs.load(p)
        np.testing.assert_allclose(
            np.asarray(stock.maxAbs.toArray()), ours.maxAbs, atol=1e-12
        )

    def test_stock_robust_scaler_model_loads_ours(self, spark, tmp_path):
        from pyspark.ml.feature import RobustScalerModel as StockRobust

        from spark_rapids_ml_tpu.models.scaler import RobustScaler

        rng = np.random.default_rng(7)
        x = rng.normal(size=(3_000, 3)) * 2 + 1
        ours = (
            RobustScaler()
            .setInputCol("features")
            .setOutputCol("scaled")
            .setWithCentering(True)
            .fit(x)
        )
        p = str(tmp_path / "rs")
        ours.save(p, layout="spark")
        stock = StockRobust.load(p)
        np.testing.assert_allclose(
            np.asarray(stock.median.toArray()), ours.median, atol=1e-12
        )
        np.testing.assert_allclose(
            np.asarray(stock.range.toArray()), ours.range, atol=1e-12
        )
        assert stock.getWithCentering() is True

    def test_stock_variance_selector_model_loads_ours(self, spark, tmp_path):
        from pyspark.ml.feature import (
            VarianceThresholdSelectorModel as StockSel,
        )
        from pyspark.ml.linalg import Vectors

        from spark_rapids_ml_tpu.models.selector import (
            VarianceThresholdSelector,
        )

        rng = np.random.default_rng(8)
        x = rng.normal(size=(100, 4)) * np.array([0.01, 1.0, 2.0, 0.02])
        ours = (
            VarianceThresholdSelector()
            .setFeaturesCol("features")
            .setVarianceThreshold(0.1)
            .fit(x)
        )
        p = str(tmp_path / "sel")
        ours.save(p, layout="spark")
        stock = StockSel.load(p)
        np.testing.assert_array_equal(
            np.asarray(stock.selectedFeatures), ours.selectedFeatures
        )
        df = spark.createDataFrame(
            [(Vectors.dense(row),) for row in x], ["features"]
        )
        got = np.asarray(
            [
                r["selected_features"].toArray()
                for r in stock.transform(df).collect()
            ]
        )
        np.testing.assert_allclose(
            got, x[:, ours.selectedFeatures], atol=1e-12
        )


class TestWrapperUpgradeLoad:
    def test_core_native_save_loads_as_spark_wrapper(self, tmp_path, rng):
        """The train-local / serve-on-Spark handoff: a native save written
        by a CORE model must load through its Spark wrapper class (which
        only adds DataFrame behavior), across the whole family."""
        from spark_rapids_ml_tpu.models.discretizer import QuantileDiscretizer
        from spark_rapids_ml_tpu.models.scaler import (
            Imputer,
            MinMaxScaler,
            RobustScaler,
        )
        from spark_rapids_ml_tpu.spark import (
            SparkImputerModel,
            SparkMinMaxScalerModel,
            SparkPCAModel,
            SparkQuantileDiscretizerModel,
            SparkRobustScalerModel,
        )

        x = rng.uniform(1, 9, size=(150, 4))
        cases = [
            (PCA().setInputCol("f").setK(2).fit(x), SparkPCAModel),
            (MinMaxScaler().setInputCol("f").fit(x), SparkMinMaxScalerModel),
            (RobustScaler().setInputCol("f").fit(x), SparkRobustScalerModel),
            (Imputer().setInputCol("f").fit(x), SparkImputerModel),
            (
                QuantileDiscretizer().setInputCol("f").setNumBuckets(3).fit(x),
                SparkQuantileDiscretizerModel,
            ),
        ]
        for i, (model, SparkCls) in enumerate(cases):
            p = str(tmp_path / f"m{i}")
            model.save(p)  # native layout, core class recorded
            loaded = SparkCls.load(p)
            assert isinstance(loaded, SparkCls), SparkCls.__name__
            np.testing.assert_allclose(
                loaded.transform(x), model.transform(x), atol=0,
                err_msg=SparkCls.__name__,
            )

    def test_mismatched_class_still_rejected(self, pca_model, tmp_path, rng):
        from spark_rapids_ml_tpu.models.scaler import MinMaxScalerModel

        p = str(tmp_path / "pca")
        pca_model.save(p)
        with pytest.raises(TypeError, match="not a MinMaxScalerModel"):
            MinMaxScalerModel.load(p)
