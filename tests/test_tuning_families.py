"""Model-selection interop for the r5 families: CrossValidator composes
with RandomForest and LinearSVC exactly as with the GLMs — same Estimator
contract, same evaluators. (UMAP/DBSCAN/k-NN are unsupervised; CV's
labeled-data surface doesn't apply.)"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import LinearSVC, RandomForestClassifier
from spark_rapids_ml_tpu.models.tuning import (
    BinaryClassificationEvaluator,
    CrossValidator,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
)


@pytest.fixture(scope="module")
def labeled():
    rng = np.random.default_rng(8)
    x = rng.normal(size=(600, 6))
    y = (1.5 * x[:, 0] - x[:, 2] + 0.5 * rng.normal(size=600) > 0).astype(float)
    return x, y


def test_cv_over_random_forest_depth(labeled):
    x, y = labeled
    est = RandomForestClassifier().setNumTrees(8).setSeed(1)
    grid = (
        ParamGridBuilder()
        .addGrid(est.maxDepth, [1, 6])
        .build()
    )
    cv = CrossValidator(
        estimator=est,
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator().setMetricName("accuracy"),
        numFolds=3,
        seed=0,
    )
    model = cv.fit((x, y))
    # depth 6 must beat a depth-1 stump on this interaction-free but
    # 2-feature problem
    assert model.bestModel.getMaxDepth() == 6
    assert len(model.avgMetrics) == 2
    assert model.avgMetrics[1] > model.avgMetrics[0]
    preds = model.transform(x)
    assert (np.asarray(preds) == y).mean() > 0.85


def test_cv_over_svc_reg_param(labeled):
    x, y = labeled
    est = LinearSVC().setMaxIter(30)
    grid = ParamGridBuilder().addGrid(est.regParam, [100.0, 0.01]).build()
    cv = CrossValidator(
        estimator=est,
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator().setMetricName("accuracy"),
        numFolds=3,
        seed=0,
    )
    model = cv.fit((x, y))
    # a crushing L2 penalty must lose to a sane one
    assert model.bestModel.getRegParam() == 0.01
    assert (np.asarray(model.transform(x)) == y).mean() > 0.85


def test_binary_evaluator_on_svc_margins(labeled):
    """BinaryClassificationEvaluator ranks on the rawPrediction margin
    surface the SVC model emits — AUC near 1 on this separable-ish task."""
    pd = pytest.importorskip("pandas")
    x, y = labeled
    model = LinearSVC().setRegParam(0.01).fit((x, y))
    out = model.transform(pd.DataFrame({"features": list(x)}))
    scored = pd.DataFrame(
        {
            "label": y,
            "rawPrediction": list(np.stack(out["rawPrediction"])),
        }
    )
    auc = BinaryClassificationEvaluator().evaluate(scored)
    assert auc > 0.95, auc


def test_pipeline_composes_new_stages():
    """Pipeline chains the r5 stages like any Spark ML stage: scale →
    UMAP-embed → KMeans-cluster on the embedding, one fit/transform unit."""
    pd = pytest.importorskip("pandas")
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.models.pipeline import Pipeline
    from spark_rapids_ml_tpu.models.scaler import StandardScaler
    from spark_rapids_ml_tpu.umap import UMAP

    rng = np.random.default_rng(4)
    centers = rng.normal(scale=10, size=(3, 8))
    x = np.concatenate(
        [c + rng.normal(scale=0.4, size=(70, 8)) for c in centers]
    )
    labels = np.repeat(np.arange(3), 70)
    df = pd.DataFrame({"features": list(x)})

    pipe = Pipeline(
        stages=[
            StandardScaler().setInputCol("features").setOutputCol("scaled"),
            UMAP().setInputCol("scaled").setOutputCol("emb")
            .setNNeighbors(10).setNEpochs(80).setSeed(1),
            KMeans().setInputCol("emb").setOutputCol("cluster").setK(3)
            .setSeed(0),
        ]
    )
    model = pipe.fit(df)
    out = model.transform(df)
    assert {"scaled", "emb", "cluster"} <= set(out.columns)
    clusters = out["cluster"].to_numpy()
    # the pipeline's clusters recover the generative blobs (up to relabel)
    from itertools import permutations

    best = max(
        (np.mean(clusters == np.array(p)[labels]) for p in permutations(range(3)))
    )
    assert best > 0.95, best
