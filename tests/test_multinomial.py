"""Multinomial (softmax) logistic regression — differential vs a scipy oracle."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import LogisticRegression, LogisticRegressionModel


def _make_multiclass(rng, rows=600, n=4, c=3, noise=0.6):
    w_true = rng.normal(size=(c, n)) * 2
    x = rng.normal(size=(rows, n))
    logits = x @ w_true.T + noise * rng.normal(size=(rows, c))
    y = np.argmax(logits, axis=1).astype(float)
    return x, y, w_true


def _scipy_oracle(x, y, c, reg_param, fit_intercept=True):
    """Full-batch softmax NLL + 0.5·λ·m·‖W‖² minimized by scipy L-BFGS —
    the same objective the Newton loop optimizes."""
    from scipy.optimize import minimize
    from scipy.special import logsumexp

    m, n = x.shape
    xa = np.hstack([x, np.ones((m, 1))]) if fit_intercept else x
    d = xa.shape[1]
    onehot = np.eye(c)[y.astype(int)]

    def nll(w_flat):
        w = w_flat.reshape(c, d)
        logits = xa @ w.T
        lz = logsumexp(logits, axis=1)
        loss = np.sum(lz - np.sum(onehot * logits, axis=1))
        pen = w[:, :-1] if fit_intercept else w
        loss += 0.5 * reg_param * m * np.sum(pen**2)
        p = np.exp(logits - lz[:, None])
        g = (p - onehot).T @ xa
        if fit_intercept:
            g[:, :-1] += reg_param * m * w[:, :-1]
        else:
            g += reg_param * m * w
        return loss, g.reshape(-1)

    res = minimize(nll, np.zeros(c * d), jac=True, method="L-BFGS-B",
                   options={"maxiter": 500, "ftol": 1e-14, "gtol": 1e-10})
    return res.x.reshape(c, d)


class TestMultinomialFit:
    def test_matches_scipy_oracle(self, rng):
        x, y, _ = _make_multiclass(rng)
        m = LogisticRegression().setRegParam(0.05).fit((x, y), num_partitions=3)
        w_ref = _scipy_oracle(x, y, 3, 0.05)
        assert m.coefficientMatrix.shape == (3, 4)
        # softmax parameterization has a flat intercept-shift direction the
        # two optimizers may resolve differently; compare shift-invariantly
        cm = m.coefficientMatrix - m.coefficientMatrix.mean(0)
        cr = w_ref[:, :-1] - w_ref[:, :-1].mean(0)
        np.testing.assert_allclose(cm, cr, atol=1e-4)
        iv = m.interceptVector - m.interceptVector.mean()
        ir = w_ref[:, -1] - w_ref[:, -1].mean()
        np.testing.assert_allclose(iv, ir, atol=1e-4)

    def test_predictions_accurate_on_separable(self, rng):
        x, y, _ = _make_multiclass(rng, noise=0.05)
        m = LogisticRegression().setRegParam(0.001).fit((x, y))
        pred = m._predict_matrix(x)
        assert np.mean(pred == y) > 0.94

    def test_binary_path_unchanged_for_two_classes(self, rng):
        x = rng.normal(size=(200, 3))
        y = (x[:, 0] > 0).astype(float)
        m = LogisticRegression().setRegParam(0.1).fit((x, y))
        assert m.coefficientMatrix is None  # binary surface, not multinomial
        assert m.coefficients.shape == (3,)
        assert m.numClasses == 2

    def test_consistency_with_binary_on_two_class_data(self, rng):
        """A 2-class softmax fit must induce the same decision function as
        the binary sigmoid fit: w1 − w0 ≈ binary coefficients."""
        x = rng.normal(size=(400, 3))
        y = (x @ np.array([1.0, -2.0, 0.5]) > 0).astype(float)
        mb = LogisticRegression().setRegParam(0.1).fit((x, y))
        # force the multinomial route by relabeling to 3 classes where one
        # class never appears is NOT valid — instead fit softmax directly
        from spark_rapids_ml_tpu.ops import linear as LIN
        import jax.numpy as jnp

        xa = np.hstack([x, np.ones((400, 1))])
        w = jnp.zeros(2 * 4)
        for _ in range(25):
            stats = LIN.softmax_newton_stats(
                jnp.asarray(xa), jnp.asarray(y.astype(np.int32)), w, 2
            )
            w, step = LIN.softmax_newton_update(w, stats, 2, reg_param=0.05)
            if float(step) < 1e-9:
                break
        wm = np.asarray(w).reshape(2, 4)
        diff = wm[1] - wm[0]  # log-odds direction
        # decision directions agree (binary λ=0.1 vs softmax per-class λ=0.05
        # on ±w/2 symmetric solution gives the same penalized objective)
        cos = diff[:3] @ mb.coefficients / (
            np.linalg.norm(diff[:3]) * np.linalg.norm(mb.coefficients)
        )
        assert cos > 0.9999

    def test_weighted_multiclass(self, rng):
        x, y, _ = _make_multiclass(rng, rows=300)
        w = rng.integers(1, 4, 300).astype(np.float64)
        m_w = LogisticRegression().setRegParam(0.05).fit((x, y, w))
        xr = np.repeat(x, w.astype(int), axis=0)
        yr = np.repeat(y, w.astype(int))
        m_r = LogisticRegression().setRegParam(0.05).fit((xr, yr))
        np.testing.assert_allclose(
            m_w.coefficientMatrix, m_r.coefficientMatrix, rtol=1e-4, atol=1e-6
        )

    def test_separable_unregularized_stays_finite(self, rng):
        # Separable data with regParam=0 has no finite MLE: the Newton
        # iterates legitimately diverge, and the divergence guard must
        # return the LAST FINITE iterate (big weights, correct decisions)
        # — never NaN coefficients (ops/linear._regularized_newton_solve).
        centers = np.array(
            [[4.0, 0.0, 0.0], [0.0, 4.0, 0.0], [0.0, 0.0, 4.0]]
        )
        y = np.arange(240, dtype=float) % 3
        x = centers[y.astype(int)] + 0.1 * rng.normal(size=(240, 3))
        m = LogisticRegression(maxIter=60).fit((x, y))
        assert np.all(np.isfinite(m.coefficientMatrix))
        assert np.all(np.isfinite(m.interceptVector))
        assert np.mean(np.asarray(m.transform(x)) == y) > 0.99
        probs = m.predict_proba_matrix(x)
        assert np.all(np.isfinite(probs))

    def test_separable_unregularized_binary_stays_finite(self, rng):
        y = (np.arange(300) % 2).astype(float)
        x = np.where(y[:, None] > 0, 3.0, -3.0) + 0.1 * rng.normal(
            size=(300, 4)
        )
        m = LogisticRegression(maxIter=60).fit((x, y))
        assert np.all(np.isfinite(m.coefficients))
        assert np.isfinite(m.intercept)
        assert np.mean(np.asarray(m.transform(x)) == y) > 0.99

    def test_nan_features_raise_not_silent_zero_model(self, rng):
        # the divergence guard must NOT mask bad input data: a NaN feature
        # makes the FIRST Newton step non-finite from the zero init, which
        # check_newton_outcome turns into a diagnosable error rather than
        # an all-zero model that predicts one class everywhere
        x, y, _ = _make_multiclass(rng, rows=120)
        x[7, 2] = np.nan
        with pytest.raises(ValueError, match="NaN/Inf"):
            LogisticRegression(maxIter=10).fit((x, y))

    def test_nan_features_raise_binary(self, rng):
        x = rng.normal(size=(100, 3))
        y = (x[:, 0] > 0).astype(float)
        x[3, 1] = np.inf
        with pytest.raises(ValueError, match="NaN/Inf"):
            LogisticRegression(maxIter=10).fit((x, y))

    def test_non_integer_labels_rejected(self, rng):
        x = rng.normal(size=(50, 2))
        with pytest.raises(ValueError, match="integer class labels"):
            LogisticRegression().fit((x, np.full(50, 0.5)))

    def test_id_like_labels_rejected(self, rng):
        """One mislabeled/ID-like row must produce a clear error, not a
        [C·d, C·d] allocation attempt."""
        x = rng.normal(size=(50, 2))
        y = np.zeros(50)
        y[0] = 100000.0
        with pytest.raises(ValueError, match="classes"):
            LogisticRegression().fit((x, y))

    def test_proba_rows_sum_to_one(self, rng):
        x, y, _ = _make_multiclass(rng, rows=200)
        m = LogisticRegression().setRegParam(0.1).fit((x, y))
        p = m.predict_proba_matrix(x[:20])
        assert p.shape == (20, 3)
        np.testing.assert_allclose(p.sum(1), np.ones(20), atol=1e-6)

    def test_predict_single_row(self, rng):
        x, y, _ = _make_multiclass(rng, noise=0.05)
        m = LogisticRegression().setRegParam(0.001).fit((x, y))
        hits = sum(m.predict(x[i]) == y[i] for i in range(50))
        assert hits > 45

    def test_persistence_roundtrip(self, rng, tmp_path):
        x, y, _ = _make_multiclass(rng, rows=200)
        m = LogisticRegression().setRegParam(0.1).fit((x, y))
        p = str(tmp_path / "mlr")
        m.save(p)
        m2 = LogisticRegressionModel.load(p)
        np.testing.assert_array_equal(m.coefficientMatrix, m2.coefficientMatrix)
        np.testing.assert_array_equal(m.interceptVector, m2.interceptVector)
        assert m2.numClasses == 3

    def test_checkpoint_resume(self, rng, tmp_path):
        x, y, _ = _make_multiclass(rng, rows=300)
        ckpt = str(tmp_path / "ck")
        est = LogisticRegression().setRegParam(0.05).setMaxIter(3)
        m_partial = est.fit((x, y), checkpoint_dir=ckpt, checkpoint_every=1)
        est2 = LogisticRegression().setRegParam(0.05).setMaxIter(30)
        m_res = est2.fit((x, y), checkpoint_dir=ckpt, checkpoint_every=1)
        m_fresh = LogisticRegression().setRegParam(0.05).setMaxIter(30).fit((x, y))
        np.testing.assert_allclose(
            m_res.coefficientMatrix, m_fresh.coefficientMatrix, rtol=1e-5, atol=1e-7
        )
