"""Native bridge tests — differential against NumPy, plus the JAX-vs-native
cross-check the reference never had (its native layer was only ever tested
through the full Spark stack, SURVEY.md §4)."""

import numpy as np
import pytest

bridge = pytest.importorskip("spark_rapids_ml_tpu.bridge")

if not bridge.available():  # pragma: no cover
    pytest.skip("native toolchain unavailable", allow_module_level=True)


def test_version():
    assert bridge.version() == 12


class TestPacking:
    def test_pack_rows(self, rng):
        rows = [rng.normal(size=12) for _ in range(50)]
        out = bridge.pack_rows(rows)
        np.testing.assert_array_equal(out, np.stack(rows))

    def test_pack_list(self, rng):
        mat = rng.normal(size=(30, 8))
        values = mat.reshape(-1)
        offsets = np.arange(0, 31 * 8, 8, dtype=np.int32)
        out = bridge.pack_list(values, offsets, 8)
        np.testing.assert_array_equal(out, mat)

    def test_pack_list_ragged_rejected(self, rng):
        values = rng.normal(size=20)
        offsets = np.array([0, 8, 13, 20], dtype=np.int32)  # ragged
        with pytest.raises(bridge.NativeBridgeError):
            bridge.pack_list(values, offsets, 8)


class TestGram:
    def test_matches_numpy(self, rng):
        x = rng.normal(size=(300, 40))
        np.testing.assert_allclose(bridge.gram(x), x.T @ x, rtol=1e-12)

    def test_accumulation_across_batches(self, rng):
        """Repeated calls accumulate — the per-partition covariance loop
        semantics (RapidsRowMatrix.scala:122-137)."""
        a, b = rng.normal(size=(100, 16)), rng.normal(size=(64, 16))
        out = bridge.gram(a)
        out = bridge.gram(b, out=out)
        full = np.concatenate([a, b])
        np.testing.assert_allclose(out, full.T @ full, rtol=1e-12)

    def test_odd_sizes(self, rng):
        x = rng.normal(size=(7, 131))  # not multiples of the tile size
        np.testing.assert_allclose(bridge.gram(x), x.T @ x, rtol=1e-12)


class TestSignFlip:
    def test_semantics(self, rng):
        u = rng.normal(size=(20, 6))
        flipped = bridge.sign_flip(u)
        for j in range(6):
            col = flipped[:, j]
            assert col[np.argmax(np.abs(col))] > 0
        np.testing.assert_allclose(np.abs(flipped), np.abs(u), rtol=1e-15)

    def test_matches_jax_kernel(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops import linalg as L

        u = rng.normal(size=(15, 7))
        np.testing.assert_allclose(
            bridge.sign_flip(u), np.asarray(L.sign_flip(jnp.asarray(u))), rtol=1e-12
        )


class TestEigh:
    def test_against_numpy(self, rng):
        x = rng.normal(size=(200, 24))
        cov = x.T @ x
        comps, s = bridge.eigh_descending(cov)
        evals, evecs = np.linalg.eigh(cov)
        order = np.argsort(evals)[::-1]
        np.testing.assert_allclose(s, np.sqrt(evals[order]), rtol=1e-9)
        np.testing.assert_allclose(
            np.abs(comps), np.abs(evecs[:, order]), rtol=1e-6, atol=1e-9
        )
        # residual: Jacobi should be LAPACK-grade
        resid = np.max(np.abs(cov @ comps - comps * (s**2)[None, :]))
        assert resid < 1e-9 * np.max(np.abs(cov))

    def test_descending_and_flipped(self, rng):
        x = rng.normal(size=(100, 10))
        comps, s = bridge.eigh_descending(x.T @ x)
        assert np.all(np.diff(s) <= 1e-9)
        for j in range(10):
            col = comps[:, j]
            assert col[np.argmax(np.abs(col))] > 0


class TestProject:
    def test_matches_numpy(self, rng):
        x = rng.normal(size=(500, 32))
        pc = rng.normal(size=(32, 5))
        np.testing.assert_allclose(bridge.project(x, pc), x @ pc, rtol=1e-12)


class TestHostFit:
    @pytest.mark.parametrize("center", [False, True])
    def test_matches_jax_path(self, rng, center):
        """The native fallback and the JAX device path must produce the same
        model — the dual-backend contract."""
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops import linalg as L

        x = rng.normal(size=(300, 20))
        pc_n, ev_n = bridge.pca_fit_host(x, 5, mean_centering=center)
        pc_j, ev_j = L.pca_fit_local(jnp.asarray(x), 5, mean_centering=center)
        np.testing.assert_allclose(pc_n, np.asarray(pc_j), atol=1e-8)
        np.testing.assert_allclose(ev_n, np.asarray(ev_j), atol=1e-10)


class TestKMeansAssign:
    def test_matches_jax_kernel(self, rng):
        """Native threaded assignment vs the device kmeans_stats monoid —
        the dual-backend contract, weighted."""
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops import kmeans as KM

        x = rng.normal(size=(700, 12))
        centers = x[:: 700 // 5][:5].copy()
        w = rng.integers(0, 3, size=700).astype(float)  # incl. zero weights
        labels, sums, counts, cost = bridge.kmeans_assign(x, centers, w)
        ref = KM.kmeans_stats(jnp.asarray(x), jnp.asarray(centers), jnp.asarray(w))
        np.testing.assert_allclose(sums, np.asarray(ref.sums), atol=1e-9)
        np.testing.assert_allclose(counts, np.asarray(ref.counts), atol=1e-12)
        np.testing.assert_allclose(cost, float(ref.cost), rtol=1e-10)
        # labels match a NumPy argmin oracle
        d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d2.argmin(axis=1))

    def test_accumulates_across_batches(self, rng):
        x = rng.normal(size=(400, 8))
        centers = x[:4].copy()
        _, s1, c1, cost1 = bridge.kmeans_assign(x[:200], centers)
        _, s1, c1, cost2 = bridge.kmeans_assign(
            x[200:], centers, sums=s1, counts=c1
        )
        _, s_all, c_all, cost_all = bridge.kmeans_assign(x, centers)
        np.testing.assert_allclose(s1, s_all, atol=1e-10)
        np.testing.assert_allclose(c1, c_all)
        assert abs((cost1 + cost2) - cost_all) < 1e-9 * max(1.0, cost_all)

    def test_lloyd_host_matches_device_loop(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops import kmeans as KM

        anchors = rng.normal(size=(3, 6)) * 6
        x = np.vstack([anchors[i] + 0.4 * rng.normal(size=(150, 6)) for i in range(3)])
        centers0 = x[[0, 150, 300]].copy()
        c_native, cost_native, _ = bridge.kmeans_lloyd_host(
            x, centers0, max_iter=15, tol=1e-10
        )
        c = jnp.asarray(centers0)
        for _ in range(15):
            stats = KM.kmeans_stats(jnp.asarray(x), c)
            c = KM.update_centers(stats, c)
        np.testing.assert_allclose(c_native, np.asarray(c), atol=1e-8)
        assert cost_native > 0


class TestLinregNative:
    """Native normal-equations family vs NumPy oracles and the framework's
    LinearRegression (ops.linear.solve_normal semantics)."""

    def test_accumulate_matches_numpy(self, rng):
        x = rng.normal(size=(300, 7))
        y = rng.normal(size=300)
        w = rng.uniform(0.5, 2.0, size=300)
        xtx, xty, mom = bridge.linreg_accumulate(x, y, w)
        np.testing.assert_allclose(xtx, (x * w[:, None]).T @ x, atol=1e-9)
        np.testing.assert_allclose(xty, x.T @ (w * y), atol=1e-9)
        np.testing.assert_allclose(mom[:7], (x * w[:, None]).sum(0), atol=1e-9)
        assert abs(mom[7] - float(w @ y)) < 1e-9
        assert abs(mom[8] - w.sum()) < 1e-12

    def test_accumulate_batches_fold(self, rng):
        x = rng.normal(size=(200, 5))
        y = rng.normal(size=200)
        xtx, xty, mom = bridge.linreg_accumulate(x[:90], y[:90])
        bridge.linreg_accumulate(x[90:], y[90:], xtx=xtx, xty=xty, moments=mom)
        xtx_all, xty_all, mom_all = bridge.linreg_accumulate(x, y)
        np.testing.assert_allclose(xtx, xtx_all, atol=1e-10)
        np.testing.assert_allclose(xty, xty_all, atol=1e-10)
        np.testing.assert_allclose(mom, mom_all, atol=1e-10)

    def test_solve_spd_matches_numpy(self, rng):
        a = rng.normal(size=(10, 10))
        spd = a @ a.T + 10 * np.eye(10)
        b = rng.normal(size=10)
        np.testing.assert_allclose(
            bridge.solve_spd(spd, b), np.linalg.solve(spd, b), atol=1e-9
        )

    def test_solve_spd_rejects_indefinite(self):
        a = np.diag([1.0, -1.0])
        with pytest.raises(bridge.NativeBridgeError, match="code 4"):
            bridge.solve_spd(a, np.ones(2))

    def test_fit_matches_framework_estimator(self, rng):
        from spark_rapids_ml_tpu import LinearRegression

        x = rng.normal(size=(400, 6))
        coef_true = rng.normal(size=6)
        y = x @ coef_true + 1.5 + 0.05 * rng.normal(size=400)
        for reg in (0.0, 0.3):
            coef, intercept = bridge.linreg_fit_host(x, y, reg_param=reg)
            m = LinearRegression(regParam=reg).fit((x, y))
            np.testing.assert_allclose(coef, m.coefficients, atol=1e-7)
            assert abs(intercept - m.intercept) < 1e-7

    def test_weighted_fit_matches_duplication(self, rng):
        x = rng.normal(size=(120, 3))
        y = x @ np.array([1.0, -2.0, 0.5]) + 0.1 * rng.normal(size=120)
        w = rng.integers(1, 4, size=120).astype(float)
        coef_w, b_w = bridge.linreg_fit_host(x, y, w, reg_param=0.0)
        rep = np.repeat(np.arange(120), w.astype(int))
        coef_d, b_d = bridge.linreg_fit_host(x[rep], y[rep], reg_param=0.0)
        np.testing.assert_allclose(coef_w, coef_d, atol=1e-9)
        assert abs(b_w - b_d) < 1e-9

    def test_rank_deficient_falls_back(self, rng):
        x = rng.normal(size=(50, 2))
        x3 = np.hstack([x, x[:, :1]])  # exactly collinear third column
        y = x @ np.ones(2)
        coef, intercept = bridge.linreg_fit_host(x3, y, reg_param=0.0)
        # the min-norm solution still predicts exactly
        np.testing.assert_allclose(x3 @ coef + intercept, y, atol=1e-6)

    def test_nan_input_degrades_to_nan_like_device_path(self, rng):
        x = rng.normal(size=(50, 3))
        x[3, 1] = np.nan
        coef, _ = bridge.linreg_fit_host(x, np.ones(50))
        assert np.all(np.isnan(coef))


class TestLogregNative:
    def test_matches_framework_estimator(self, rng):
        from spark_rapids_ml_tpu import LogisticRegression

        x = rng.normal(size=(400, 5))
        p = 1 / (1 + np.exp(-(x @ rng.normal(size=5) + 0.5)))
        y = (rng.uniform(size=400) < p).astype(float)
        for reg in (0.01, 0.3):
            coef, b = bridge.logreg_fit_host(
                x, y, reg_param=reg, max_iter=50, tol=1e-10
            )
            m = LogisticRegression(
                regParam=reg, maxIter=50, tol=1e-10
            ).fit((x, y))
            np.testing.assert_allclose(coef, m.coefficients, atol=1e-7)
            assert abs(b - m.intercept) < 1e-7

    def test_weighted_matches_duplication(self, rng):
        x = rng.normal(size=(150, 3))
        y = (x[:, 0] + 0.3 * rng.normal(size=150) > 0).astype(float)
        w = rng.integers(1, 4, size=150).astype(float)
        cw, bw = bridge.logreg_fit_host(x, y, w, reg_param=0.01)
        rep = np.repeat(np.arange(150), w.astype(int))
        cd, bd = bridge.logreg_fit_host(x[rep], y[rep], reg_param=0.01)
        np.testing.assert_allclose(cw, cd, atol=1e-8)
        assert abs(bw - bd) < 1e-8

    def test_bad_labels_and_nan_rejected(self, rng):
        x = rng.normal(size=(50, 2))
        with pytest.raises(ValueError, match="0/1 labels"):
            bridge.logreg_fit_host(x, np.full(50, 2.0))
        xb = x.copy(); xb[0, 0] = np.nan
        with pytest.raises(ValueError, match="NaN/Inf"):
            bridge.logreg_fit_host(xb, (x[:, 0] > 0).astype(float))
