"""Comm backend + executor tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.parallel import backend as B
from spark_rapids_ml_tpu.parallel import mesh as M
from spark_rapids_ml_tpu.parallel.executor import TaskFailedError, run_partition_tasks


@pytest.fixture(scope="module")
def mesh():
    return M.create_mesh(data=8, feat=1)


class TestCollectives:
    def test_allreduce(self, mesh, rng):
        x = rng.normal(size=(8, 16, 16))
        got = B.allreduce(jnp.asarray(x), mesh, M.DATA_AXIS)
        np.testing.assert_allclose(np.asarray(got), x.sum(0), rtol=1e-12)

    def test_allreduce_uneven_stacking(self, mesh, rng):
        # 16 partials over 8 devices: 2 resident slices each
        x = rng.normal(size=(16, 4))
        got = B.allreduce(jnp.asarray(x), mesh, M.DATA_AXIS)
        np.testing.assert_allclose(np.asarray(got), x.sum(0), rtol=1e-12)

    def test_allgather(self, mesh, rng):
        x = rng.normal(size=(8, 4))
        got = B.allgather(jnp.asarray(x), mesh, M.DATA_AXIS)
        np.testing.assert_allclose(np.asarray(got), x, rtol=1e-15)

    def test_single_process_helpers(self):
        info = B.process_info()
        assert info["process_count"] == 1
        assert B.broadcast_host(42) == 42
        B.initialize()  # no-op single host

    def test_host_reduce(self, rng):
        parts = [rng.normal(size=(6, 6)) for _ in range(5)]
        got = B.host_reduce(parts, lambda a, b: a + b)
        np.testing.assert_allclose(got, sum(parts), rtol=1e-12)


class TestExecutor:
    def test_order_preserved(self):
        out = run_partition_tasks(lambda i: i * 2, list(range(20)), max_workers=8)
        assert out == [i * 2 for i in range(20)]

    def test_retries_transient_failure(self):
        attempts = {}

        def flaky(i):
            attempts[i] = attempts.get(i, 0) + 1
            if i == 3 and attempts[i] < 3:
                raise RuntimeError("transient")
            return i

        out = run_partition_tasks(flaky, list(range(5)), max_workers=2)
        assert out == list(range(5))
        assert attempts[3] == 3

    def test_exhausted_retries_raise(self):
        def always_fails(i):
            raise RuntimeError("permanent")

        with pytest.raises(TaskFailedError, match="after 3 attempts"):
            run_partition_tasks(
                always_fails, [1], max_retries=2, retry_backoff_s=0.0
            )

    def test_empty(self):
        assert run_partition_tasks(lambda i: i, []) == []
