"""ApproximateNearestNeighbors (IVF-Flat) tests.

Two oracle layers: with ``nprobe == nlist`` every cluster is scanned, so
results must equal exact brute force BIT-FOR-BIT (the strongest possible
check of the bucket/gather/merge plumbing); with a partial probe, recall
against the exact answer on clustered data must stay high.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.knn import (
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
    NearestNeighbors,
)


@pytest.fixture(scope="module")
def clustered():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=10, size=(25, 16))
    items = np.concatenate(
        [c + rng.normal(scale=0.8, size=(80, 16)) for c in centers]
    )
    queries = np.concatenate(
        [c + rng.normal(scale=0.8, size=(4, 16)) for c in centers]
    )
    return items, queries


def test_full_probe_equals_exact(clustered):
    items, queries = clustered
    k = 8
    exact_d, exact_i = NearestNeighbors().setK(k).fit(items).kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setNlist(16).setNprobe(16)
        .setSeed(1).fit(items)
    )
    d, i = ann.kneighbors(queries)
    np.testing.assert_array_equal(i, exact_i)
    np.testing.assert_allclose(d, exact_d, rtol=1e-9)


def test_partial_probe_recall(clustered):
    items, queries = clustered
    k = 10
    _, exact_i = NearestNeighbors().setK(k).fit(items).kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setNlist(25).setNprobe(5)
        .setSeed(1).fit(items)
    )
    _, i = ann.kneighbors(queries)
    recall = np.mean(
        [len(set(a) & set(b)) / k for a, b in zip(i, exact_i)]
    )
    assert recall >= 0.9, recall


def test_auto_nlist_and_persistence(tmp_path, clustered):
    items, queries = clustered
    ann = ApproximateNearestNeighbors().setK(5).setNprobe(50).fit(items)
    assert ann.centroids.shape[0] == int(np.sqrt(len(items)))
    path = str(tmp_path / "ann")
    ann.save(path)
    loaded = ApproximateNearestNeighborsModel.load(path)
    d0, i0 = ann.kneighbors(queries)
    d1, i1 = loaded.kneighbors(queries)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_allclose(d0, d1)


def test_cosine_metric_recall(clustered):
    items, queries = clustered
    k = 6
    exact = NearestNeighbors().setK(k).setMetric("cosine").fit(items)
    _, exact_i = exact.kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setMetric("cosine")
        .setNlist(20).setNprobe(20).setSeed(2).fit(items)
    )
    d, i = ann.kneighbors(queries)
    np.testing.assert_array_equal(i, exact_i)
    assert np.all((d >= 0) & (d <= 2))


def test_unfilled_slots_are_inf_not_two():
    """With fewer reachable candidates than k, phantom slots carry id −1
    and distance inf — never a legal finite distance (cosine's old clip
    mapped them to exactly 2.0)."""
    rng = np.random.default_rng(4)
    items = rng.normal(size=(40, 6))
    ann = (
        ApproximateNearestNeighbors().setK(10).setMetric("cosine")
        .setNlist(20).setNprobe(1).setSeed(0).fit(items)
    )
    d, i = ann.kneighbors(items[:8])
    phantom = i == -1
    assert phantom.any(), "expected some unfilled slots at nprobe=1"
    assert np.all(np.isinf(d[phantom]))
    assert np.all(np.isfinite(d[~phantom]))


def test_skewed_corpus_cap_and_spill():
    """100:1 cluster skew regression: the percentile cap keeps the dense
    tensor near the corpus footprint (the old pad-to-largest packing
    allocated ~nlist × hot-cluster-size), and NO item is dropped — every
    corpus row appears exactly once across buckets + spill."""
    from spark_rapids_ml_tpu.ops import ivf as IVF

    rng = np.random.default_rng(7)
    n, nlist = 8, 100
    hot = rng.normal(size=(5000, n))          # one hot cluster, 100:1 skew
    cold = rng.normal(size=(99, 50, n))       # 99 clusters of 50
    items = np.concatenate([hot, cold.reshape(-1, n)]).astype(np.float32)
    labels = np.concatenate(
        [np.zeros(5000, np.int64),
         np.repeat(np.arange(1, nlist), 50)]
    )
    b = IVF.build_ivf_buckets(items, labels, nlist)
    # memory: the 99th-percentile cap excludes the hot cluster, so the
    # dense tensor must be far under the old nlist*max_count*n packing
    assert b.cap < 5000
    old_bytes = nlist * 5000 * n * items.itemsize
    assert b.bucket_items.nbytes < old_bytes / 10
    # completeness: ids partition exactly into buckets + spill
    kept = np.concatenate(
        [b.bucket_ids[b.bucket_ids >= 0], b.spill_ids[b.spill_ids >= 0]]
    )
    np.testing.assert_array_equal(np.sort(kept), np.arange(len(items)))
    # and the spilled overflow stays searchable: full probe == exact
    exact_d, exact_i = (
        NearestNeighbors().setK(5).fit(items).kneighbors(items[:32])
    )
    ann = (
        ApproximateNearestNeighbors().setK(5).setNlist(nlist)
        .setNprobe(nlist).setSeed(0).fit(items)
    )
    d, i = ann.kneighbors(items[:32])
    np.testing.assert_array_equal(i, exact_i)


@pytest.mark.parametrize(
    "policy,tol",
    [
        # the tolerances ops/ivf.py documents for unit-scale data
        ("bf16_f32acc", 1e-2),
        ("int8_dist", 5e-2),
    ],
)
def test_quantized_full_probe_parity(policy, tol):
    """nprobe == nlist under the quantized scan variants: distances agree
    with the f32 kernel within the documented relative tolerance, and the
    neighbor sets stay essentially exact on separable data."""
    from spark_rapids_ml_tpu.ops import ivf as IVF

    rng = np.random.default_rng(11)
    items = rng.normal(size=(2000, 16)).astype(np.float32)
    queries = items[:64]
    k, nlist = 8, 16
    ann = (
        ApproximateNearestNeighbors().setK(k).setNlist(nlist)
        .setNprobe(nlist).setSeed(3).fit(items)
    )
    d_f, i_f = IVF.ivf_search(
        queries, ann.centroids, ann.bucketItems, ann.bucketIds, k, nlist,
        spill_items=ann.spillItems, spill_ids=ann.spillIds,
    )
    d_q, i_q = IVF.ivf_search(
        queries, ann.centroids, ann.bucketItems, ann.bucketIds, k, nlist,
        spill_items=ann.spillItems, spill_ids=ann.spillIds, policy=policy,
    )
    d_f, d_q = np.asarray(d_f), np.asarray(d_q)
    scale = np.abs(d_f).max()
    np.testing.assert_allclose(d_q, d_f, rtol=tol, atol=tol * scale)
    overlap = np.mean(
        [len(set(a.tolist()) & set(b.tolist())) / k
         for a, b in zip(np.asarray(i_f), np.asarray(i_q))]
    )
    assert overlap >= 0.95, overlap


def test_recall_monotone_in_nprobe(clustered):
    """recall@10 is non-decreasing in nprobe: the probe set at nprobe+1 is
    a strict superset of the one at nprobe (same coarse ranking), so the
    merged top-k can only improve."""
    items, queries = clustered
    k = 10
    _, exact_i = NearestNeighbors().setK(k).fit(items).kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setNlist(25).setNprobe(1)
        .setSeed(1).fit(items)
    )
    recalls = []
    for nprobe in (1, 2, 4, 8, 16, 25):
        ann._set(nprobe=nprobe)
        _, i = ann.kneighbors(queries)
        recalls.append(np.mean(
            [len(set(a) & set(b)) / k for a, b in zip(i, exact_i)]
        ))
    assert all(b >= a for a, b in zip(recalls, recalls[1:])), recalls
    assert recalls[-1] == 1.0, recalls


def test_id_col_and_validation(clustered):
    pd = pytest.importorskip("pandas")
    items, queries = clustered
    ids = np.arange(len(items)) * 3
    df = pd.DataFrame({"features": list(items), "id": ids})
    ann = (
        ApproximateNearestNeighbors().setInputCol("features").setIdCol("id")
        .setK(1).setNprobe(1000).fit(df)
    )
    _, i = ann.kneighbors(pd.DataFrame({"features": list(items[:10] + 1e-10)}))
    np.testing.assert_array_equal(i[:, 0], ids[:10])
    with pytest.raises(ValueError, match="k="):
        ann.kneighbors(queries, k=len(items) + 1)
    with pytest.raises(ValueError, match="metric"):
        ApproximateNearestNeighbors().setMetric("inner_product")
