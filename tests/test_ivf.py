"""ApproximateNearestNeighbors (IVF-Flat) tests.

Two oracle layers: with ``nprobe == nlist`` every cluster is scanned, so
results must equal exact brute force BIT-FOR-BIT (the strongest possible
check of the bucket/gather/merge plumbing); with a partial probe, recall
against the exact answer on clustered data must stay high.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.knn import (
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
    NearestNeighbors,
)


@pytest.fixture(scope="module")
def clustered():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=10, size=(25, 16))
    items = np.concatenate(
        [c + rng.normal(scale=0.8, size=(80, 16)) for c in centers]
    )
    queries = np.concatenate(
        [c + rng.normal(scale=0.8, size=(4, 16)) for c in centers]
    )
    return items, queries


def test_full_probe_equals_exact(clustered):
    items, queries = clustered
    k = 8
    exact_d, exact_i = NearestNeighbors().setK(k).fit(items).kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setNlist(16).setNprobe(16)
        .setSeed(1).fit(items)
    )
    d, i = ann.kneighbors(queries)
    np.testing.assert_array_equal(i, exact_i)
    np.testing.assert_allclose(d, exact_d, rtol=1e-9)


def test_partial_probe_recall(clustered):
    items, queries = clustered
    k = 10
    _, exact_i = NearestNeighbors().setK(k).fit(items).kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setNlist(25).setNprobe(5)
        .setSeed(1).fit(items)
    )
    _, i = ann.kneighbors(queries)
    recall = np.mean(
        [len(set(a) & set(b)) / k for a, b in zip(i, exact_i)]
    )
    assert recall >= 0.9, recall


def test_auto_nlist_and_persistence(tmp_path, clustered):
    items, queries = clustered
    ann = ApproximateNearestNeighbors().setK(5).setNprobe(50).fit(items)
    assert ann.centroids.shape[0] == int(np.sqrt(len(items)))
    path = str(tmp_path / "ann")
    ann.save(path)
    loaded = ApproximateNearestNeighborsModel.load(path)
    d0, i0 = ann.kneighbors(queries)
    d1, i1 = loaded.kneighbors(queries)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_allclose(d0, d1)


def test_cosine_metric_recall(clustered):
    items, queries = clustered
    k = 6
    exact = NearestNeighbors().setK(k).setMetric("cosine").fit(items)
    _, exact_i = exact.kneighbors(queries)
    ann = (
        ApproximateNearestNeighbors().setK(k).setMetric("cosine")
        .setNlist(20).setNprobe(20).setSeed(2).fit(items)
    )
    d, i = ann.kneighbors(queries)
    np.testing.assert_array_equal(i, exact_i)
    assert np.all((d >= 0) & (d <= 2))


def test_unfilled_slots_are_inf_not_two():
    """With fewer reachable candidates than k, phantom slots carry id −1
    and distance inf — never a legal finite distance (cosine's old clip
    mapped them to exactly 2.0)."""
    rng = np.random.default_rng(4)
    items = rng.normal(size=(40, 6))
    ann = (
        ApproximateNearestNeighbors().setK(10).setMetric("cosine")
        .setNlist(20).setNprobe(1).setSeed(0).fit(items)
    )
    d, i = ann.kneighbors(items[:8])
    phantom = i == -1
    assert phantom.any(), "expected some unfilled slots at nprobe=1"
    assert np.all(np.isinf(d[phantom]))
    assert np.all(np.isfinite(d[~phantom]))


def test_id_col_and_validation(clustered):
    pd = pytest.importorskip("pandas")
    items, queries = clustered
    ids = np.arange(len(items)) * 3
    df = pd.DataFrame({"features": list(items), "id": ids})
    ann = (
        ApproximateNearestNeighbors().setInputCol("features").setIdCol("id")
        .setK(1).setNprobe(1000).fit(df)
    )
    _, i = ann.kneighbors(pd.DataFrame({"features": list(items[:10] + 1e-10)}))
    np.testing.assert_array_equal(i[:, 0], ids[:10])
    with pytest.raises(ValueError, match="k="):
        ann.kneighbors(queries, k=len(items) + 1)
    with pytest.raises(ValueError, match="metric"):
        ApproximateNearestNeighbors().setMetric("inner_product")
