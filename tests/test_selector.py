"""VarianceThresholdSelector — differential vs sklearn VarianceThreshold
(with the sample-vs-population variance correction Spark uses)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.selector import (
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
)


@pytest.fixture
def data(rng):
    x = rng.normal(size=(300, 6)) * np.array([0.01, 2.0, 0.5, 3.0, 0.05, 1.0])
    x[:, 4] = 7.0  # constant feature: zero variance
    return x


class TestVarianceThresholdSelector:
    def test_matches_sample_variance_rule(self, data):
        model = (
            VarianceThresholdSelector()
            .setFeaturesCol("f")
            .setVarianceThreshold(0.1)
            .fit(data, num_partitions=3)
        )
        want = np.flatnonzero(data.var(axis=0, ddof=1) > 0.1)
        np.testing.assert_array_equal(model.selectedFeatures, want)
        out = model.transform(data)
        np.testing.assert_array_equal(out, data[:, want])

    def test_default_threshold_drops_constant_only(self, data):
        model = VarianceThresholdSelector().setFeaturesCol("f").fit(data)
        np.testing.assert_array_equal(
            model.selectedFeatures, [0, 1, 2, 3, 5]
        )

    def test_matches_sklearn(self, data):
        from sklearn.feature_selection import VarianceThreshold

        # sklearn thresholds POPULATION variance; feed it the equivalent
        # threshold so the selections agree
        thr = 0.1
        n = len(data)
        sk = VarianceThreshold(threshold=thr * (n - 1) / n).fit(data)
        model = (
            VarianceThresholdSelector()
            .setFeaturesCol("f")
            .setVarianceThreshold(thr)
            .fit(data)
        )
        np.testing.assert_array_equal(
            model.selectedFeatures, np.flatnonzero(sk.get_support())
        )

    def test_all_rejected_is_actionable(self, data):
        with pytest.raises(ValueError, match="rejects every feature"):
            VarianceThresholdSelector().setFeaturesCol("f").setVarianceThreshold(
                1e9
            ).fit(data)

    def test_multi_partition_parity(self, data):
        m1 = VarianceThresholdSelector().setFeaturesCol("f").fit(
            data, num_partitions=1
        )
        m4 = VarianceThresholdSelector().setFeaturesCol("f").fit(
            data, num_partitions=4
        )
        np.testing.assert_array_equal(m1.selectedFeatures, m4.selectedFeatures)

    def test_persistence_roundtrip_both_layouts(self, data, tmp_path):
        model = (
            VarianceThresholdSelector()
            .setFeaturesCol("f")
            .setVarianceThreshold(0.1)
            .fit(data)
        )
        model.save(tmp_path / "native")
        loaded = VarianceThresholdSelectorModel.load(tmp_path / "native")
        np.testing.assert_array_equal(
            loaded.selectedFeatures, model.selectedFeatures
        )
        assert loaded.getVarianceThreshold() == 0.1
        model.save(tmp_path / "spark", layout="spark")
        loaded2 = VarianceThresholdSelectorModel.load(str(tmp_path / "spark"))
        np.testing.assert_array_equal(
            loaded2.selectedFeatures, model.selectedFeatures
        )
        np.testing.assert_array_equal(
            loaded2.transform(data), model.transform(data)
        )
