"""ANN subsystem tests: streamed IVF build, serving-native queries.

The streamed ``IVFFlatIndex`` build must agree with the in-memory
``ApproximateNearestNeighbors`` packing (same kernels, exhaustive probe →
exact neighbors), survive persistence bitwise, drop nothing under skew,
and serve through the registry/batcher/HTTP stack as the ``"ann"`` family
with zero steady-state compiles. conftest forces 8 host devices, so every
build here exercises the mesh-sharded Lloyd fold.
"""

from __future__ import annotations

import importlib.util
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_rapids_ml_tpu.ann import (
    IVFFlatIndex,
    IVFFlatIndexModel,
    query,
    query_direct,
    register_index,
)
from spark_rapids_ml_tpu.serving import client as client_mod
from spark_rapids_ml_tpu.serving import registry as registry_mod
from spark_rapids_ml_tpu.serving import server as server_mod
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY


@pytest.fixture(autouse=True)
def serve_clean():
    yield
    client_mod.reset_client()
    server_mod.stop_serving(stop_monitor=False)
    registry_mod.reset_for_tests()


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=8, size=(24, 12))
    labels = rng.integers(0, 24, 6000)
    x = (centers[labels] + rng.normal(size=(6000, 12))).astype(np.float32)
    return x


def _chunks(x, rows=1500):
    return [x[i : i + rows] for i in range(0, len(x), rows)]


def _recall(ids, oracle_ids):
    k = oracle_ids.shape[1]
    return np.mean(
        [len(set(a.tolist()) & set(b.tolist())) / k
         for a, b in zip(ids, oracle_ids)]
    )


def test_streamed_build_matches_exact_at_full_probe(corpus):
    from spark_rapids_ml_tpu.knn import NearestNeighbors

    model = (
        IVFFlatIndex(k=8, nlist=24, nprobe=24, maxIter=3, seed=1)
        .fit(_chunks(corpus))
    )
    exact_d, exact_i = (
        NearestNeighbors().setK(8).fit(corpus).kneighbors(corpus[:128])
    )
    d, i = model.search(corpus[:128])
    np.testing.assert_array_equal(i, exact_i)
    # queries ARE corpus rows, so the exact self-distance is 0 and the
    # f32 q²+x²−2qx cancellation leaves ~√eps·scale after the sqrt —
    # atol must cover that; everything else agrees to ~1e-5 relative
    np.testing.assert_allclose(d, exact_d, rtol=1e-3, atol=0.05)


def test_streamed_build_source_forms(corpus):
    """ndarray, chunk list and chunk-factory sources build the same index."""
    kw = dict(k=5, nlist=16, nprobe=16, maxIter=2, seed=2)
    m_arr = IVFFlatIndex(**kw).fit(corpus)
    m_list = IVFFlatIndex(**kw).fit(_chunks(corpus))
    m_fact = IVFFlatIndex(**kw).fit(lambda: iter(_chunks(corpus)))
    np.testing.assert_array_equal(m_arr.bucketIds, m_list.bucketIds)
    np.testing.assert_array_equal(m_list.bucketIds, m_fact.bucketIds)
    np.testing.assert_array_equal(m_list.bucketItems, m_fact.bucketItems)


def test_streamed_build_drops_nothing_under_skew():
    """100:1-skewed stream: every corpus row lands in a bucket or the spill
    list, and the dense tensor stays percentile-capped."""
    rng = np.random.default_rng(3)
    hot = rng.normal(loc=0.0, scale=0.05, size=(5000, 8))
    cold = rng.normal(scale=20.0, size=(2500, 8))
    x = np.concatenate([hot, cold]).astype(np.float32)
    model = (
        IVFFlatIndex(k=5, nlist=64, nprobe=64, maxIter=3, seed=4)
        .fit(_chunks(x, 1024))
    )
    kept = np.concatenate([
        model.bucketIds[model.bucketIds >= 0],
        model.spillIds[model.spillIds >= 0],
    ])
    np.testing.assert_array_equal(np.sort(kept), np.arange(len(x)))
    assert model.bucketItems.shape[1] < 5000


def test_rebalance_reseeds_empty_cells_greedily():
    """Two empty cells and two uncovered clusters: greedy farthest-point
    reseeding must give each uncovered cluster its own cell (a plain
    top-k by distance would drop both seeds into the farthest cluster),
    and must leave live cells bitwise untouched."""
    from spark_rapids_ml_tpu.ann.index import _rebalance_cells

    rng = np.random.default_rng(11)
    true = np.array(
        [[0.0, 0.0], [30.0, 0.0], [0.0, 30.0], [30.0, 40.0]], np.float32
    )
    labels = np.arange(1200) % 4
    x = (true[labels] + rng.normal(scale=0.1, size=(1200, 2))).astype(
        np.float32
    )
    # init double-covered cluster 0; clusters 2 and 3 got no center
    centers = np.array(
        [[0.1, 0.0], [-0.1, 0.0], [30.0, 0.1], [0.2, 0.1]], np.float32
    )
    counts2 = np.array([150, 0, 300, 0])
    repaired2, n2 = _rebalance_cells(centers, counts2, x)
    assert n2 == 2
    d_to_true = np.linalg.norm(
        repaired2[[1, 3], None, :] - true[None, :, :], axis=2
    )
    nearest = set(np.argmin(d_to_true, axis=1).tolist())
    assert nearest == {2, 3}  # one seed per uncovered cluster, not two in one

    same, zero = _rebalance_cells(
        centers, np.array([300, 300, 300, 300]), x
    )
    assert zero == 0 and same is centers


def test_rebalance_splits_merged_cells():
    """The no-empty-cell local minimum: cluster 3 has no center, so its
    rows pile onto cluster 1's cell (doubling it) while two duplicate
    centers split cluster 0. Repair must move a duplicate (the smallest
    cell) into the absorbed cluster, leaving every cell near-balanced."""
    from spark_rapids_ml_tpu.ann.index import _rebalance_cells

    rng = np.random.default_rng(13)
    true = np.array(
        [[0.0, 0.0], [30.0, 0.0], [0.0, 30.0], [33.0, 3.0]], np.float32
    )
    labels = np.arange(1200) % 4
    x = (true[labels] + rng.normal(scale=0.1, size=(1200, 2))).astype(
        np.float32
    )
    centers = np.array(
        [[0.1, 0.0], [-0.1, 0.0], [30.5, 1.5], [0.0, 30.0]], np.float32
    )
    # stream counts: duplicates split cluster 0, cell 2 absorbed cluster 3
    counts = np.array([150, 130, 620, 300])
    repaired, n = _rebalance_cells(centers, counts, x)
    assert n == 1
    # the donated center (smallest cell, slot 1) lands inside cluster 3,
    # the farthest region of the overfull cell
    assert np.linalg.norm(repaired[1] - true[3]) < 1.0
    np.testing.assert_array_equal(repaired[[0, 2, 3]], centers[[0, 2, 3]])


def test_custom_ids_and_mismatch(corpus):
    ids = np.arange(len(corpus), dtype=np.int64) * 7 + 3
    model = (
        IVFFlatIndex(k=3, nlist=16, nprobe=16, maxIter=2, seed=5)
        .fit(_chunks(corpus), ids=ids)
    )
    _, i = model.search(corpus[:10])
    np.testing.assert_array_equal(i[:, 0], ids[:10])
    with pytest.raises(ValueError, match="ids has"):
        IVFFlatIndex(k=3, nlist=8, maxIter=1).fit(
            _chunks(corpus), ids=ids[:-1]
        )


def test_non_reiterable_source_is_detected(corpus):
    """A bare generator drains on the first pass; the build must fail
    loudly instead of packing an empty index."""
    gen = (c for c in _chunks(corpus))
    with pytest.raises(ValueError):
        IVFFlatIndex(k=3, nlist=8, maxIter=1).fit(lambda: gen)


def test_persistence_roundtrip(tmp_path, corpus):
    model = (
        IVFFlatIndex(k=6, nlist=16, nprobe=4, maxIter=2, seed=6)
        .fit(_chunks(corpus))
    )
    path = str(tmp_path / "ivf_index")
    model.save(path)
    loaded = IVFFlatIndexModel.load(path)
    assert isinstance(loaded, IVFFlatIndexModel)
    assert loaded.getNprobe() == 4
    d0, i0 = model.search(corpus[:32])
    d1, i1 = loaded.search(corpus[:32])
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(d0, d1)
    d2, i2 = loaded.search(corpus[:32], nprobe=16)
    assert loaded.getNprobe() == 4  # override is per-call


def test_serving_registration_and_query(corpus):
    from spark_rapids_ml_tpu.knn import NearestNeighbors

    model = (
        IVFFlatIndex(k=10, nlist=24, nprobe=8, maxIter=3, seed=7)
        .fit(_chunks(corpus))
    )
    entry = register_index("vecs", model, bucket_list=(8, 64, 256))
    assert entry.family == "ann"
    assert any(
        e["family"] == "ann" for e in registry_mod.get_registry().describe()
    )

    q = corpus[:200]
    cold_before = REGISTRY.snapshot().counter("serve.cold_compiles")
    d, i = query("vecs", q)
    d2, i2 = query("vecs", q)  # steady state: no new compiles
    cold_after = REGISTRY.snapshot().counter("serve.cold_compiles")
    assert cold_after == cold_before
    np.testing.assert_array_equal(i, i2)

    # parity with the model's own search at the registered operating point
    d_ref, i_ref = model.search(q)
    np.testing.assert_array_equal(i, i_ref)
    np.testing.assert_allclose(d, d_ref, rtol=1e-5, atol=1e-5)

    # recall vs the exact oracle at nprobe=8/24 on separable clusters
    _, oracle = NearestNeighbors().setK(10).fit(corpus).kneighbors(q)
    assert _recall(i, oracle) >= 0.95

    # query_direct sweeps nprobe without re-registering
    _, i_full = query_direct("vecs", q, nprobe=24)
    assert _recall(i_full, oracle) == 1.0


def test_serving_cosine_prepare_hook(corpus):
    """Cosine indexes normalize queries in the serve prepare hook — the
    served answer must match the model's own (normalizing) search path."""
    model = (
        IVFFlatIndex(k=5, metric="cosine", nlist=16, nprobe=16, maxIter=2,
                     seed=8)
        .fit(_chunks(corpus))
    )
    register_index("cos", model, bucket_list=(64,))
    q = corpus[:50] * 3.7  # scaling must not change cosine neighbors
    d, i = query("cos", q)
    d_ref, i_ref = model.search(q)
    np.testing.assert_array_equal(i, i_ref)
    np.testing.assert_allclose(d, d_ref, rtol=1e-5, atol=1e-5)
    assert np.all((d >= 0) & (d <= 2))


def test_http_index_endpoints(corpus):
    model = (
        IVFFlatIndex(k=4, nlist=16, nprobe=16, maxIter=2, seed=9)
        .fit(_chunks(corpus))
    )
    register_index("web", model, bucket_list=(8, 16))
    srv = server_mod.start_serving(0, with_monitor=False)
    port = srv._httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"

    with urllib.request.urlopen(f"{base}/v1/indexes") as r:
        listing = json.loads(r.read())
    assert [e["name"] for e in listing["indexes"]] == ["web"]

    body = json.dumps({"instances": corpus[:3].tolist()}).encode()
    req = urllib.request.Request(
        f"{base}/v1/indexes/web:query", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        resp = json.loads(r.read())
    assert resp["rows"] == 3
    assert resp["ids"][0][0] == 0  # self-match
    assert len(resp["distances"][0]) == 4

    # binary wire: packed [rows, 2k] + X-ANN-K
    raw = np.ascontiguousarray(corpus[:2], dtype="<f4").tobytes()
    req = urllib.request.Request(
        f"{base}/v1/indexes/web:query", data=raw,
        headers={
            "Content-Type": server_mod.BINARY_CONTENT_TYPE,
            server_mod.SHAPE_HEADER: "2,12",
            "Accept": server_mod.BINARY_CONTENT_TYPE,
        },
    )
    with urllib.request.urlopen(req) as r:
        k = int(r.headers[server_mod.ANN_K_HEADER])
        shape = [int(d) for d in r.headers[server_mod.SHAPE_HEADER].split(",")]
        packed = np.frombuffer(r.read(), dtype="<f4").reshape(shape)
    assert k == 4 and shape == [2, 8]
    np.testing.assert_array_equal(packed[:, k].astype(int), [0, 1])

    # :query against a non-ann servable is a 404
    from spark_rapids_ml_tpu.models.pca import PCA

    srv.registry.register("p", PCA(k=2).fit(corpus), bucket_list=(8,))
    req = urllib.request.Request(
        f"{base}/v1/indexes/p:query", data=body,
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req)
    assert err.value.code == 404


# -- ann_report CLI ----------------------------------------------------------


def _load_ann_report():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ann_report", os.path.join(repo, "tools", "ann_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ann_blob(**over):
    blob = {
        "rows": 1_048_576,
        "n_features": 32,
        "nlist": 2048,
        "nprobe": 1,
        "k": 10,
        "build_seconds": 75.0,
        "build_rows_per_s": 13981,
        "bucket_cap": 512,
        "bucket_fill": {"mean": 512.0, "p50": 512, "p99": 512, "max": 512},
        "spill_rows": 0,
        "spill_fraction": 0.0,
        "ann_qps": 36651,
        "knn_qps": 221,
        "qps_ratio": 165.7,
        "ann_recall_at_10": 0.9996,
        "recall_vs_nprobe": [
            {"nprobe": 1, "recall_at_10": 0.9996},
            {"nprobe": 2, "recall_at_10": 0.9996},
            {"nprobe": 4, "recall_at_10": 0.9996},
        ],
        "ann_recompiles_after_warmup": 0,
    }
    blob.update(over)
    return blob


class TestAnnReport:
    def _write(self, tmp_path, records):
        path = tmp_path / "perf.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        return str(path)

    def test_clean_ledger_entry_renders_and_passes_strict(
        self, tmp_path, capsys
    ):
        ar = _load_ann_report()
        path = self._write(
            tmp_path,
            [
                {"bench": "smoke", "other": 1},  # no ann evidence: ignored
                {
                    "bench": "smoke",
                    "timestamp": "2026-08-05T00:00:00Z",
                    "ann": _ann_blob(),
                },
            ],
        )
        assert ar.main([path, "--strict"]) == 0
        out = capsys.readouterr().out
        assert "anomaly checks: ok" in out
        assert "nprobe" in out and "recall@10" in out
        assert "registered operating point" in out

    def test_probe_skew_anomaly_fails_strict(self, tmp_path, capsys):
        ar = _load_ann_report()
        blob = _ann_blob(
            bucket_cap=1024,
            bucket_fill={"mean": 512.0, "p50": 512, "p99": 1088, "max": 1100},
        )
        path = self._write(tmp_path, [{"ann": blob}])
        assert ar.main([path]) == 0  # render-only stays green
        assert ar.main([path, "--strict"]) == 2
        assert "probe-skew" in capsys.readouterr().out

    def test_recall_cliff_anomaly(self, tmp_path, capsys):
        ar = _load_ann_report()
        blob = _ann_blob(
            ann_recall_at_10=0.93,
            recall_vs_nprobe=[
                {"nprobe": 1, "recall_at_10": 0.93},
                {"nprobe": 4, "recall_at_10": 0.999},
            ],
        )
        path = self._write(tmp_path, [blob])  # bare blob, no wrapper
        assert ar.main([path, "--strict"]) == 2
        out = capsys.readouterr().out
        assert "recall-cliff" in out and "nprobe=4" in out

    def test_monotonicity_and_recompile_anomalies(self, tmp_path, capsys):
        ar = _load_ann_report()
        blob = _ann_blob(
            ann_recompiles_after_warmup=2,
            recall_vs_nprobe=[
                {"nprobe": 1, "recall_at_10": 0.9996},
                {"nprobe": 2, "recall_at_10": 0.91},
            ],
        )
        path = self._write(tmp_path, [{"ann": blob}])
        assert ar.main([path, "--strict"]) == 2
        out = capsys.readouterr().out
        assert "recall-not-monotone" in out
        assert "query-path-recompile" in out

    def test_low_recall_ratio_and_spill_anomalies(self, tmp_path, capsys):
        ar = _load_ann_report()
        blob = _ann_blob(
            ann_recall_at_10=0.80,
            qps_ratio=19.4,
            spill_fraction=0.12,
            spill_rows=125_829,
            recall_vs_nprobe=[],
        )
        path = self._write(tmp_path, [{"ann": blob}])
        assert ar.main([path, "--strict"]) == 2
        out = capsys.readouterr().out
        assert "recall-below-bar" in out
        assert "index-no-speedup" in out
        assert "spill-heavy" in out

    def test_no_evidence_is_an_error(self, tmp_path):
        ar = _load_ann_report()
        path = self._write(tmp_path, [{"bench": "smoke"}])
        assert ar.main([path]) == 1
