"""Eigenvector-accuracy validation of the BENCH program — as a test.

VERDICT r2 weak #5: the 0.9999999980 cosine claim lived in a bench.py
comment. Now (a) bench.py measures it on the real chip every round and
records it in BENCH_r{N}.json (``eigvec_min_cosine...``, ``accuracy_ok``),
and (b) this test runs the bench's EXACT program configuration —
Precision.HIGH Gram + randomized solver (oversample=20), uncentered — on a
scaled slice of the same correlated-spectrum workload against an f64 host
oracle, so any change that degrades the measured program's accuracy fails
CI before it reaches the chip.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from spark_rapids_ml_tpu.ops import linalg as L  # noqa: E402

ROWS, N, K = 50_000, 512, 50
TARGET = 0.9999  # BASELINE.md north-star accuracy bar


def _bench_workload(rows: int) -> np.ndarray:
    """The bench's correlated-spectrum generator (rank-64 mix + noise),
    host-side and f32 like the device path sees it."""
    rng = np.random.default_rng(7)
    base = rng.normal(size=(rows, 64)).astype(np.float32)
    mix = rng.normal(size=(64, N)).astype(np.float32)
    return base @ mix + 0.1 * rng.normal(size=(rows, N)).astype(np.float32)


def test_bench_program_meets_cosine_bar():
    x = _bench_workload(ROWS)

    @jax.jit
    def fit(a):
        return L.pca_fit_from_cov(
            L.gram(a, precision=lax.Precision.HIGH),
            K,
            solver="randomized",
            oversample=20,
        )

    pc, _ = fit(jnp.asarray(x))
    min_cos = L.min_cosine_vs_f64_oracle(x, pc, K)
    assert min_cos >= TARGET, (
        f"min eigenvector cosine {min_cos:.10f} below the {TARGET} bar"
    )


def test_full_solver_meets_cosine_bar():
    # the reference-parity exact path must clear the same bar
    x = _bench_workload(20_000)

    @jax.jit
    def fit(a):
        return L.pca_fit_from_cov(
            L.gram(a, precision=lax.Precision.HIGH), K, solver="full"
        )

    pc = fit(jnp.asarray(x))[0]
    assert L.min_cosine_vs_f64_oracle(x, pc, K) >= TARGET
