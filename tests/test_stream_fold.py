"""Streamed-fit pipeline tests (spark.ingest.stream_fold + donated folds).

Three claims, each load-bearing for the out-of-core path:

1. PARITY — streamed fits equal resident fits on identical data (PCA
   per-component |cosine| >= 0.9999, linear coefficients atol <= 1e-5 —
   the ISSUE acceptance bars; in practice the {1,0} pad-mask convention
   makes the folds bit-for-bit so the margins are enormous), including
   weighted rows and a chunk size that does not divide the row count.
2. MEMORY — the full [rows, n] array is never materialized: the largest
   single host->device transfer stays O(chunk), and the carry is O(n**2).
3. OVERLAP — fold dispatch returns while the previous chunk's fold is
   still executing (double buffering via JAX async dispatch), observable
   via StreamFold.overlapped and the ingest.chunk/fold.dispatch/fold.wait
   trace spans.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.models.linear import LinearRegression
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.models.scaler import StandardScaler
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.spark import ingest
from spark_rapids_ml_tpu.utils.config import get_config, set_config
from spark_rapids_ml_tpu.telemetry import metrics, reset_metrics


@pytest.fixture
def force_streamed(monkeypatch):
    """Drop the cutover to 1 byte (every fit streams) and pin a chunk size
    that does NOT divide the test row counts; restore on exit."""
    old = get_config().stream_fit_max_resident_bytes
    monkeypatch.setenv("TPU_ML_STREAM_CHUNK_ROWS", "128")
    set_config(stream_fit_max_resident_bytes=1)
    yield
    set_config(stream_fit_max_resident_bytes=old)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(17)
    # 1100 rows: not a multiple of the 128-row chunk (ragged tail rides
    # the w=0 pad mask), nor of the 3 partitions
    x = np.asarray(rng.normal(size=(1100, 12)), np.float64)
    coef = rng.normal(size=12)
    y = x @ coef + 0.05 * rng.normal(size=1100)
    w = rng.uniform(0.5, 3.0, size=1100)
    return x, y, w


class TestStreamedParity:
    def test_pca_streamed_matches_resident(self, data, force_streamed):
        x, _, _ = data
        est = PCA().setInputCol("f").setK(5)
        resident_bytes = get_config().stream_fit_max_resident_bytes
        set_config(stream_fit_max_resident_bytes=1 << 31)
        m_res = est.fit(x, num_partitions=3)
        set_config(stream_fit_max_resident_bytes=resident_bytes)
        m_str = est.fit(x, num_partitions=3)
        cos = np.abs(np.sum(m_res.pc * m_str.pc, axis=0))
        assert cos.min() >= 0.9999, cos
        np.testing.assert_allclose(
            m_str.explainedVariance, m_res.explainedVariance, atol=1e-9
        )

    def test_scaler_streamed_matches_resident(self, data, force_streamed):
        x, _, _ = data
        set_config(stream_fit_max_resident_bytes=1 << 31)
        m_res = StandardScaler().fit(x, num_partitions=3)
        set_config(stream_fit_max_resident_bytes=1)
        m_str = StandardScaler().fit(x, num_partitions=3)
        np.testing.assert_allclose(m_str.mean, m_res.mean, atol=1e-12)
        np.testing.assert_allclose(m_str.std, m_res.std, atol=1e-12)

    def test_linreg_streamed_matches_resident_weighted(
        self, data, force_streamed
    ):
        x, y, w = data
        set_config(stream_fit_max_resident_bytes=1 << 31)
        m_res = LinearRegression().fit((x, y, w), num_partitions=3)
        set_config(stream_fit_max_resident_bytes=1)
        m_str = LinearRegression().fit((x, y, w), num_partitions=3)
        np.testing.assert_allclose(
            m_str.coefficients, m_res.coefficients, atol=1e-5
        )
        assert abs(m_str.intercept - m_res.intercept) <= 1e-5

    def test_sharded_chunk_fold_matches_one_shot(self, data):
        """parallel.gram: stacked per-device partials + single finalize
        allreduce == the one-shot GramStats of the concatenated data."""
        from spark_rapids_ml_tpu.parallel import gram as G
        from spark_rapids_ml_tpu.parallel import mesh as M

        x, _, _ = data
        mesh = M.create_mesh()
        ndev = len(jax.devices())
        chunk = 128 // ndev * ndev or ndev
        dt = np.float64
        example = L.GramStats(
            xtx=jax.ShapeDtypeStruct((12, 12), dt),
            col_sum=jax.ShapeDtypeStruct((12,), dt),
            count=jax.ShapeDtypeStruct((), dt),
        )
        res = ingest.stream_fold(
            iter([x]),
            lambda c, xd, wd: G.sharded_gram_fold(c, xd, wd, mesh),
            n=12,
            init=G.init_chunk_carry(example, mesh),
            chunk_rows=chunk,
            put_fn=G.chunk_put(mesh),
        )
        stats = G.finalize_chunk_fold(res.carry, mesh)
        want = L.gram_stats(jnp.asarray(x))
        np.testing.assert_allclose(stats.xtx, want.xtx, rtol=1e-12)
        np.testing.assert_allclose(stats.col_sum, want.col_sum, rtol=1e-12)
        assert float(stats.count) == 1100.0


class TestStreamedMemory:
    def test_peak_transfer_is_one_chunk_not_full_array(self, data):
        """O(chunk + n^2) evidence: the largest single device_put is one
        fixed-shape chunk (+ its weight vector), far below the [rows, n]
        resident array the old path shipped."""
        x, _, _ = data
        chunk = 128
        res = ingest.stream_fold(
            iter(np.array_split(x, 4)),
            L.gram_fold_step(),
            n=12,
            init=L.init_gram_carry(12, x.dtype),
            chunk_rows=chunk,
        )
        chunk_bytes = chunk * 12 * x.itemsize + chunk * x.itemsize
        assert res.max_put_bytes == chunk_bytes
        assert res.max_put_bytes < x.nbytes / 4
        assert res.rows == 1100
        # 1100 rows / 128-row chunks -> 8 full + 1 ragged = 9 dispatches
        assert res.chunks == 9
        # the carry itself is O(n^2), independent of rows
        assert res.carry.xtx.shape == (12, 12)

    def test_ragged_tail_and_count_exact(self, data):
        x, _, _ = data
        res = ingest.stream_fold(
            iter([x]),
            L.gram_fold_step(),
            n=12,
            init=L.init_gram_carry(12, x.dtype),
            chunk_rows=256,  # 1100 = 4*256 + 76: pad rows ride w=0
        )
        want = L.gram_stats(jnp.asarray(x))
        np.testing.assert_allclose(res.carry.xtx, want.xtx, rtol=1e-12)
        assert float(res.carry.count) == 1100.0


class TestStreamedOverlap:
    def test_dispatch_overlaps_previous_fold(self):
        """Double-buffering observable: with a fold heavy enough to still
        be executing when the host finishes staging the next chunk, at
        least one dispatch must find the carry not-ready."""
        rng = np.random.default_rng(5)
        x = np.asarray(rng.normal(size=(2048, 128)), np.float64)

        @partial(jax.jit, donate_argnums=0)
        def heavy_fold(carry, xc, wc):
            def body(_, c):
                return L.fold_gram_stats(c, xc, wc)

            return jax.lax.fori_loop(0, 50, body, carry)

        # the busy window is scheduler-dependent (CPU async dispatch may
        # finish a fold within the dispatch call itself), so sample a few
        # streams: a genuinely serialized pipeline yields 0 on every one
        for _ in range(8):
            res = ingest.stream_fold(
                iter(np.array_split(x, 8)),
                heavy_fold,
                n=128,
                init=L.init_gram_carry(128, x.dtype),
                chunk_rows=512,
            )
            assert res.chunks == 4
            if res.overlapped >= 1:
                break
        else:
            pytest.fail(
                "no fold dispatch observed the previous fold still executing "
                "in any of 8 streams — the pipeline is serialized"
            )

    def test_phase_spans_recorded(self, data):
        x, _, _ = data
        reset_metrics()
        res = ingest.stream_fold(
            iter(np.array_split(x, 3)),
            L.gram_fold_step(),
            n=12,
            init=L.init_gram_carry(12, x.dtype),
            chunk_rows=512,
        )
        m = metrics()
        assert m["fold.dispatch"]["count"] == res.chunks
        assert m["fold.wait"]["count"] == 1
        # one span per source pull (3 partitions) + the exhausting pull
        assert m["ingest.chunk"]["count"] == 4

    def test_empty_and_mismatched_inputs_raise(self):
        with pytest.raises(ValueError, match="empty dataset"):
            ingest.stream_fold(
                iter([]),
                L.gram_fold_step(),
                n=4,
                init=L.init_gram_carry(4, np.float64),
                chunk_rows=128,
            )
        with pytest.raises(ValueError, match="feature dimension"):
            ingest.stream_fold(
                iter([np.zeros((8, 4)), np.zeros((8, 5))]),
                L.gram_fold_step(),
                n=4,
                init=L.init_gram_carry(4, np.float64),
                chunk_rows=128,
            )
