"""Ledger-driven autotuner + mixed-precision policy tests.

Four claims, each load-bearing for the tuner PR:

1. MECHANISM — successive halving deterministically selects the fastest
   candidate under injected timings, respects the trial budget, and an
   all-trials-dead search returns None without poisoning the cache.
2. CACHE — winners round-trip through the persistent JSON tier, shape
   bucketing collapses nearby row counts into one entry, and a repeat
   resolve of the same bucket is a pure cache hit (zero new search trials,
   counter-asserted).
3. NUMERICS — the ``bf16_f32acc`` policy passes the f64-oracle gates at
   the documented tolerances (PCA min |cosine| >= 0.99, linear coef
   rel err <= 5e-2, gram rel err <= 2e-3) with accumulator dtype preserved,
   and ``int8_dist`` keeps kmeans assignments >= 0.99 in agreement with
   full precision on separated clusters.
4. INTEGRATION — stream_fold consults the cache for chunk geometry and
   staging layout, the FitReport stamps the decisions (schema v4), and a
   chaos plan killing trials degrades the search instead of the fit.
"""

import json

import numpy as np
import pytest

from spark_rapids_ml_tpu import autotune
from spark_rapids_ml_tpu.autotune import cache
from spark_rapids_ml_tpu.autotune import search
from spark_rapids_ml_tpu.autotune.policy import (
    FOLD_POLICIES,
    PrecisionPolicy,
    TuningConfig,
    resolve_policy,
)
from spark_rapids_ml_tpu.ops import kmeans as KM
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.ops import linear as LIN
from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.spark import ingest
from spark_rapids_ml_tpu.telemetry import report
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.utils import knobs

# documented mixed-precision tolerances (mirrored in README's policy table)
BF16_GRAM_REL_ERR = 2e-3
BF16_PCA_MIN_COSINE = 0.99
BF16_LINEAR_COEF_REL_ERR = 5e-2
INT8_KMEANS_AGREEMENT = 0.99


@pytest.fixture(autouse=True)
def clean_tuner(monkeypatch):
    """Every test starts with an empty tuner: no mode/cache/policy env, no
    in-process entries, no journal, no armed fault plan."""
    for knob in (knobs.AUTOTUNE, knobs.AUTOTUNE_TRIALS,
                 knobs.TUNING_CACHE_PATH, knobs.PRECISION_POLICY):
        monkeypatch.delenv(knob.name, raising=False)
    monkeypatch.delenv(faults.FAULT_PLAN_VAR, raising=False)
    faults.reset_faults()
    cache.reset()
    yield
    faults.reset_faults()
    cache.reset()


def _counters():
    return REGISTRY.snapshot()


class TestPolicyVocabulary:
    def test_tuning_config_round_trip(self):
        c = TuningConfig(chunk_rows=4096, layout="col",
                         policy="bf16_f32acc", donate_carry=True)
        assert TuningConfig.from_dict(c.to_dict()) == c
        assert "chunk=4096" in c.key() and "layout=col" in c.key()

    def test_tuning_config_validates(self):
        with pytest.raises(ValueError):
            TuningConfig(layout="diagonal")
        with pytest.raises(ValueError):
            TuningConfig(policy="fp8")
        with pytest.raises(ValueError):
            TuningConfig(chunk_rows=0)

    def test_resolve_policy_env_default(self, monkeypatch):
        assert resolve_policy(None) == "f32"
        monkeypatch.setenv(knobs.PRECISION_POLICY.name, "bf16_f32acc")
        assert resolve_policy(None) == "bf16_f32acc"
        # explicit beats env
        assert resolve_policy("f32") == "f32"

    def test_fold_policies_exclude_int8(self, monkeypatch):
        monkeypatch.setenv(knobs.PRECISION_POLICY.name, "int8_dist")
        with pytest.raises(ValueError):
            resolve_policy(None, allowed=FOLD_POLICIES)

    def test_candidate_grid(self):
        grid = search.candidate_grid(1024, floor=8)
        sizes = sorted({c.chunk_rows for c in grid})
        assert sizes == [512, 1024, 2048]
        assert {c.layout for c in grid} == {"row", "col"}
        # floor clamps the half-size candidate
        low = search.candidate_grid(8, floor=8)
        assert min(c.chunk_rows for c in low) == 8


class TestCache:
    def test_shape_bucketing(self):
        # nearby row counts share a bucket; widths never collapse
        k1 = cache.cache_key("k", n=16, rows=100_000, dtype="float64")
        k2 = cache.cache_key("k", n=16, rows=120_000, dtype="float64")
        k3 = cache.cache_key("k", n=32, rows=100_000, dtype="float64")
        assert k1 == k2
        assert k1 != k3
        assert cache.shape_bucket(16, None) == "n16/rowsANY"

    def test_persistent_round_trip(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tuning.json")
        monkeypatch.setenv(knobs.TUNING_CACHE_PATH.name, path)
        key = cache.cache_key("k", n=8, rows=1000, dtype="float64")
        cfg = TuningConfig(chunk_rows=256, layout="col")
        cache.store(key, cfg, trials=3)
        doc = json.loads(open(path).read())
        assert doc["type"] == "tuning_cache"
        # a fresh process (reset) reloads the blessed file lazily
        cache.reset()
        monkeypatch.setenv(knobs.TUNING_CACHE_PATH.name, path)
        assert cache.lookup(key) == cfg

    def test_lookup_books_counters(self):
        key = cache.cache_key("k", n=8, rows=1000, dtype="float64")
        before = _counters()
        assert cache.lookup(key) is None
        cache.store(key, TuningConfig(chunk_rows=64), persist=False)
        assert cache.lookup(key) is not None
        delta = _counters().delta(before)
        assert delta.counter("autotune.cache_misses") == 1
        assert delta.counter("autotune.cache_hits") == 1


class TestSuccessiveHalving:
    CONFIGS = [TuningConfig(chunk_rows=r) for r in (64, 128, 256, 512)]

    def test_selects_fastest_under_injected_timings(self):
        times = {64: 4.0, 128: 1.0, 256: 3.0, 512: 2.0}
        winner, trials = search.successive_halving(
            self.CONFIGS, lambda c: times[c.chunk_rows], budget=12
        )
        assert winner.chunk_rows == 128
        assert trials <= 12

    def test_budget_respected(self):
        calls = []
        winner, trials = search.successive_halving(
            self.CONFIGS,
            lambda c: calls.append(c) or 1.0,
            budget=3,
        )
        assert trials == 3 == len(calls)
        assert winner is not None

    def test_all_failures_yield_none_and_empty_cache(self):
        def boom(_config):
            raise RuntimeError("trial died")

        got = search.search(
            "k", cache.cache_key("k", n=8, rows=1000, dtype="f8"),
            self.CONFIGS, boom, budget=8,
        )
        assert got is None
        assert cache.entries() == {}

    def test_deterministic_tie_break(self):
        # equal timings: candidate order decides, every run identically
        winners = {
            search.successive_halving(
                self.CONFIGS, lambda c: 1.0, budget=8
            )[0].chunk_rows
            for _ in range(3)
        }
        assert len(winners) == 1


class TestResolveModes:
    KW = dict(n=8, rows=1000, dtype="float64")

    def test_off_mode_is_silent(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "off")
        assert search.resolve("k", **self.KW) is None
        assert cache.decisions_since(0) == []

    def test_cache_mode_never_searches(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "cache")
        got = search.resolve(
            "k", **self.KW,
            measure=lambda c: 1.0,
            candidates=[TuningConfig(chunk_rows=64)],
        )
        assert got is None  # miss -> static knobs, no search in cache mode
        (decision,) = cache.decisions_since(0)
        assert decision["source"] == "default"

    def test_search_then_pure_cache_hit(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "search")
        times = {64: 2.0, 128: 1.0}
        candidates = [TuningConfig(chunk_rows=r) for r in times]
        before = _counters()
        first = search.resolve(
            "k", **self.KW,
            measure=lambda c: times[c.chunk_rows],
            candidates=candidates, budget=6,
        )
        assert first is not None and first.chunk_rows == 128
        mid = _counters()
        assert mid.delta(before).counter("autotune.search_runs") == 1
        assert mid.delta(before).counter("autotune.trials") > 0

        # the repeat resolve must not measure at all: zero new trials
        again = search.resolve(
            "k", **self.KW,
            measure=lambda c: pytest.fail("measured on a cache hit"),
            candidates=candidates,
        )
        assert again == first
        delta = _counters().delta(mid)
        assert delta.counter("autotune.trials") == 0
        assert delta.counter("autotune.search_runs") == 0
        assert delta.counter("autotune.cache_hits") == 1
        sources = [d["source"] for d in cache.decisions_since(0)]
        assert sources == ["search", "cache"]


class TestChaos:
    def test_faulted_trial_drops_only_that_candidate(self, monkeypatch):
        # the FIRST trial (the would-be fastest candidate) dies; the search
        # must finish on the survivors
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "autotune.trial:io:1")
        faults.reset_faults()
        times = {64: 1.0, 128: 2.0, 256: 3.0}
        candidates = [TuningConfig(chunk_rows=r) for r in times]
        before = _counters()
        winner, _trials = search.successive_halving(
            candidates, lambda c: times[c.chunk_rows], budget=9
        )
        assert winner.chunk_rows == 128  # 64 died with its trial
        assert _counters().delta(before).counter(
            "autotune.trial_failures") == 1

    def test_all_trials_faulted_falls_back_to_defaults(self, monkeypatch):
        budget = 4
        plan = ",".join(f"autotune.trial:io:{i + 1}" for i in range(budget))
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, plan)
        monkeypatch.setenv(knobs.AUTOTUNE.name, "search")
        faults.reset_faults()
        got = search.resolve(
            "k", n=8, rows=1000, dtype="float64",
            measure=lambda c: 1.0,
            candidates=[TuningConfig(chunk_rows=r) for r in (64, 128)],
            budget=budget,
        )
        assert got is None  # fit proceeds on static knobs
        assert cache.entries() == {}  # a dead search never poisons the cache
        assert cache.decisions_since(0)[-1]["source"] == "default"


class TestMixedPrecisionNumerics:
    @pytest.fixture(scope="class")
    def spectral_data(self):
        rng = np.random.default_rng(7)
        n = 16
        # strongly decaying column scales: well-separated top eigenpairs so
        # the oracle comparison measures policy error, not eigengap noise
        x = rng.normal(size=(2000, n)) * (2.0 ** -np.arange(n))
        return np.asarray(x, np.float64)

    def _fold_gram(self, x, policy):
        import jax.numpy as jnp

        step = L.gram_fold_step(policy=policy)
        carry = L.init_gram_carry(x.shape[1], np.float64)
        for at in range(0, len(x), 500):
            chunk = jnp.asarray(x[at:at + 500])
            carry = step(carry, chunk, jnp.ones(len(chunk), jnp.float64))
        return carry

    def test_bf16_gram_rel_err_and_carry_dtype(self, spectral_data):
        x = spectral_data
        c = self._fold_gram(x, "bf16_f32acc")
        assert str(c.xtx.dtype) == "float64"  # accumulator NEVER narrows
        ref = x.T @ x
        rel = np.max(np.abs(np.asarray(c.xtx) - ref)) / np.max(np.abs(ref))
        assert 0 < rel <= BF16_GRAM_REL_ERR
        # count/col_sum stay exact: they never route through the matmul
        assert float(c.count) == len(x)
        np.testing.assert_allclose(np.asarray(c.col_sum), x.sum(axis=0))

    def test_bf16_pca_cosine_vs_f64_oracle(self, spectral_data):
        x = spectral_data
        k = 4
        c = self._fold_gram(x, "bf16_f32acc")
        pc, _ev = L.pca_fit_from_cov(c.xtx, k)
        assert L.min_cosine_vs_f64_oracle(x, pc, k) >= BF16_PCA_MIN_COSINE

    def test_bf16_linear_coef_vs_f64_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        n = 8
        x = rng.normal(size=(4000, n))
        coef = rng.normal(size=n)
        y = x @ coef + 0.01 * rng.normal(size=len(x))

        step = LIN.linear_fold_step(policy="bf16_f32acc")
        carry = LIN.init_linear_carry(n, np.float64)
        for at in range(0, len(x), 1000):
            xc = jnp.asarray(x[at:at + 1000])
            yc = jnp.asarray(y[at:at + 1000])
            carry = step(carry, xc, yc, jnp.ones(len(xc), jnp.float64))
        got = np.linalg.solve(np.asarray(carry.xtx), np.asarray(carry.xty))
        oracle = np.linalg.solve(x.T @ x, x.T @ y)
        rel = np.linalg.norm(got - oracle) / np.linalg.norm(oracle)
        assert rel <= BF16_LINEAR_COEF_REL_ERR

    @pytest.mark.parametrize("policy", ["bf16_f32acc", "int8_dist"])
    def test_distance_policy_assignment_agreement(self, policy):
        import jax.numpy as jnp

        rng = np.random.default_rng(5)
        k, n = 8, 16
        centers = rng.normal(size=(k, n)) * 6.0  # separated
        labels = rng.integers(0, k, size=3000)
        x = centers[labels] + rng.normal(size=(3000, n))
        xd, cd = jnp.asarray(x), jnp.asarray(centers)
        base, _ = KM.assign_clusters(xd, cd)
        got, _ = KM.assign_clusters(xd, cd, policy=policy)
        agreement = float(np.mean(np.asarray(base) == np.asarray(got)))
        assert agreement >= INT8_KMEANS_AGREEMENT

    def test_int8_rejected_for_fold_kernels(self):
        with pytest.raises(ValueError):
            L.gram_fold_step(policy="int8_dist")
        with pytest.raises(ValueError):
            LIN.linear_fold_step(policy="int8_dist")


class TestStreamFoldIntegration:
    N = 6

    def _chunks(self, rows=320):
        rng = np.random.default_rng(11)
        x = rng.normal(size=(rows, self.N))
        return x, [x[at:at + 80] for at in range(0, rows, 80)]

    def _fit(self):
        x, parts = self._chunks()
        res = ingest.stream_fold(
            iter(parts), L.gram_fold_step(), n=self.N,
            init=L.init_gram_carry(self.N, ingest.wire_dtype()),
        )
        return x, res

    def test_cached_geometry_drives_chunking(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "cache")
        key = cache.cache_key(
            "stream.fold_step", n=self.N, rows=None, dtype=ingest.wire_dtype()
        )
        # 128 is the TPU_ML_MIN_BUCKET floor: the tuned size lands as-is
        cache.store(
            key, TuningConfig(chunk_rows=128, layout="col"), persist=False
        )
        x, res = self._fit()
        # tuned geometry (2x128 + ragged tail), not the 65536-row knob
        assert res.chunks == -(-len(x) // 128) == 3
        np.testing.assert_allclose(
            np.asarray(res.carry.xtx), x.T @ x, rtol=1e-10, atol=1e-8
        )
        (decision,) = cache.decisions_since(0)
        assert decision["cache_hit"] is True

    def test_off_mode_keeps_static_knob(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "off")
        key = cache.cache_key(
            "stream.fold_step", n=self.N, rows=None, dtype=ingest.wire_dtype()
        )
        cache.store(key, TuningConfig(chunk_rows=64), persist=False)
        x, res = self._fit()
        assert res.chunks == 1  # 320 rows < the 65536-row default chunk
        assert cache.decisions_since(0) == []

    def test_caller_pinned_chunk_rows_bypasses_tuner(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "cache")
        x, parts = self._chunks()
        res = ingest.stream_fold(
            iter(parts), L.gram_fold_step(), n=self.N,
            init=L.init_gram_carry(self.N, ingest.wire_dtype()),
            chunk_rows=128,
        )
        assert res.chunks == len(x) // 128 + 1  # 320 = 2x128 + ragged tail
        assert cache.decisions_since(0) == []  # tuner never consulted


class TestFitReportStamp:
    def test_tuning_decisions_drain_into_report(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "cache")
        key = cache.cache_key("stream.fold_step", n=8, rows=1000,
                              dtype="float64")
        cache.store(key, TuningConfig(chunk_rows=256), persist=False)
        cap = report.begin_fit("TunedEstimator")
        got = search.resolve("stream.fold_step", n=8, rows=1000,
                             dtype="float64")
        rep = report.end_fit(cap)
        assert got is not None
        assert rep.schema == 6
        assert rep.tuning["cache_hit"] is True
        assert rep.tuning["source"] == "cache"
        assert rep.tuning["config"]["chunk_rows"] == 256
        d = rep.to_dict()
        assert d["schema"] == 6 and d["tuning"]["source"] == "cache"
        assert report.FitReport.from_dict(d).tuning == rep.tuning

    def test_untuned_fit_has_empty_stamp(self):
        cap = report.begin_fit("PlainEstimator")
        rep = report.end_fit(cap)
        assert rep.tuning == {}

    def test_decisions_outside_window_excluded(self, monkeypatch):
        monkeypatch.setenv(knobs.AUTOTUNE.name, "cache")
        search.resolve("k", n=8, rows=10, dtype="float64")  # before window
        cap = report.begin_fit("WindowedEstimator")
        rep = report.end_fit(cap)
        assert rep.tuning == {}


def test_package_exports():
    assert autotune.MODES == ("off", "cache", "search")
    assert autotune.PrecisionPolicy is PrecisionPolicy
    assert callable(autotune.resolve)
    assert callable(autotune.stream_fold_measure)
