"""KMeans tests — kernel differentials vs NumPy/sklearn and estimator behavior."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.cluster import KMeans as SkKMeans

from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.ops import kmeans as KM


@pytest.fixture
def blobs(rng):
    """Three well-separated clusters."""
    centers = np.array([[0.0, 0.0, 0.0], [10.0, 10.0, 0.0], [-10.0, 5.0, 5.0]])
    x = np.concatenate(
        [c + rng.normal(scale=0.5, size=(100, 3)) for c in centers]
    )
    rng.shuffle(x)
    return x, centers


class TestKernels:
    def test_pairwise_dists_match_numpy(self, rng):
        x = rng.normal(size=(50, 8))
        c = rng.normal(size=(5, 8))
        got = np.asarray(KM.pairwise_sq_dists(jnp.asarray(x), jnp.asarray(c)))
        want = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(got, want, atol=1e-8)

    def test_stats_match_manual_lloyd(self, rng):
        x = rng.normal(size=(200, 6))
        c = rng.normal(size=(4, 6))
        stats = KM.kmeans_stats(jnp.asarray(x), jnp.asarray(c), block_rows=64)
        labels = np.argmin(((x[:, None, :] - c[None, :, :]) ** 2).sum(-1), axis=1)
        for j in range(4):
            np.testing.assert_allclose(
                np.asarray(stats.sums)[j], x[labels == j].sum(axis=0), atol=1e-8
            )
            assert int(np.asarray(stats.counts)[j]) == int((labels == j).sum())

    def test_weights_mask_padding(self, rng):
        x = rng.normal(size=(100, 4))
        c = rng.normal(size=(3, 4))
        xp = np.concatenate([x, np.zeros((28, 4))])
        w = np.concatenate([np.ones(100), np.zeros(28)])
        s_full = KM.kmeans_stats(jnp.asarray(x), jnp.asarray(c), block_rows=32)
        s_pad = KM.kmeans_stats(
            jnp.asarray(xp), jnp.asarray(c), jnp.asarray(w), block_rows=32
        )
        np.testing.assert_allclose(np.asarray(s_pad.sums), np.asarray(s_full.sums), atol=1e-8)
        np.testing.assert_allclose(np.asarray(s_pad.counts), np.asarray(s_full.counts))
        np.testing.assert_allclose(
            float(s_pad.cost), float(s_full.cost), rtol=1e-10
        )

    def test_empty_cluster_keeps_old_center(self):
        stats = KM.KMeansStats(
            sums=jnp.zeros((2, 3)).at[0].set(jnp.ones(3) * 10),
            counts=jnp.asarray([5.0, 0.0]),
            cost=jnp.asarray(0.0),
        )
        old = jnp.asarray([[0.0, 0.0, 0.0], [1.0, 2.0, 3.0]])
        new = np.asarray(KM.update_centers(stats, old))
        np.testing.assert_allclose(new[0], [2.0, 2.0, 2.0])
        np.testing.assert_allclose(new[1], [1.0, 2.0, 3.0])  # untouched


class TestEstimator:
    def test_recovers_blobs(self, blobs):
        x, true_centers = blobs
        model = KMeans().setInputCol("f").setK(3).setSeed(1).fit(x, num_partitions=2)
        got = model.clusterCenters[np.lexsort(model.clusterCenters.T)]
        want = true_centers[np.lexsort(true_centers.T)]
        np.testing.assert_allclose(got, want, atol=0.3)

    def test_cost_close_to_sklearn(self, blobs):
        x, _ = blobs
        model = KMeans().setInputCol("f").setK(3).setSeed(1).fit(x)
        sk = SkKMeans(n_clusters=3, n_init=10, random_state=0).fit(x)
        assert model.trainingCost <= sk.inertia_ * 1.05

    def test_transform_prediction_column(self, blobs):
        import pandas as pd

        x, _ = blobs
        df = pd.DataFrame({"f": list(x)})
        model = KMeans().setInputCol("f").setK(3).setSeed(1).fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        labels = out["prediction"].to_numpy()
        # clusters are well separated: all points in a blob share a label
        d = ((x[:, None, :] - model.clusterCenters[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(axis=1))

    def test_predict_single_row(self, blobs):
        x, _ = blobs
        model = KMeans().setInputCol("f").setK(3).setSeed(1).fit(x)
        for i in [0, 50, 150]:
            assert model.predict(x[i]) == model._predict_matrix(x[i : i + 1])[0]

    def test_multi_partition_equals_single(self, blobs):
        x, _ = blobs
        m1 = KMeans().setInputCol("f").setK(3).setSeed(3).fit(x, num_partitions=1)
        m3 = KMeans().setInputCol("f").setK(3).setSeed(3).fit(x, num_partitions=3)
        # init sampling is partition-dependent, so compare as center SETS
        c1 = m1.clusterCenters[np.lexsort(m1.clusterCenters.T)]
        c3 = m3.clusterCenters[np.lexsort(m3.clusterCenters.T)]
        np.testing.assert_allclose(c1, c3, atol=1e-6)

    def test_random_init_mode(self, blobs):
        x, _ = blobs
        model = (
            KMeans().setInputCol("f").setK(3).setSeed(5).setInitMode("random").fit(x)
        )
        assert model.clusterCenters.shape == (3, 3)

    def test_persistence_roundtrip(self, blobs, tmp_path):
        x, _ = blobs
        model = KMeans().setInputCol("f").setK(3).setSeed(1).fit(x)
        model.save(tmp_path / "km")
        loaded = KMeansModel.load(tmp_path / "km")
        np.testing.assert_array_equal(loaded.clusterCenters, model.clusterCenters)
        assert loaded.trainingCost == model.trainingCost
        np.testing.assert_array_equal(loaded.transform(x), model.transform(x))

    def test_compute_cost(self, blobs):
        x, _ = blobs
        model = KMeans().setInputCol("f").setK(3).setSeed(1).fit(x)
        np.testing.assert_allclose(
            model.computeCost(x), model.trainingCost, rtol=0.05
        )


class TestKMeansParallelInit:
    """k-means|| distributed init (VERDICT r2 weak #6): candidate quality
    must not degrade with k the way a bounded driver sample does."""

    def _clustered(self, n_clusters=500, dim=16, per=40, seed=42):
        rng = np.random.default_rng(seed)
        centers_true = rng.normal(size=(n_clusters, dim)) * 10.0
        x = np.concatenate(
            [rng.normal(size=(per, dim)) * 0.3 + c for c in centers_true]
        )
        rng.shuffle(x)
        return x

    def _init_cost(self, x, centers):
        d2 = KM.min_sq_dists(jnp.asarray(x), jnp.asarray(centers, dtype=x.dtype))
        return float(np.asarray(d2).sum())

    def test_beats_sampled_kmeans_plus_plus_at_large_k(self):
        import jax

        k = 500
        x = self._clustered(n_clusters=k)
        # the r2 baseline: k-means++ on a 4096-row driver sample
        samp = x[np.random.default_rng(0).choice(len(x), 4096, replace=False)]
        pp = np.asarray(
            KM.kmeans_plus_plus_init(jax.random.PRNGKey(0), jnp.asarray(samp), k)
        )
        est = KMeans().setK(k).setInitMode("k-means||").setSeed(0)
        par = est._kmeans_parallel_init(list(np.array_split(x, 8)), None, k)
        assert par.shape == (k, x.shape[1])
        # measured ~19% better; assert a conservative 5% margin
        assert self._init_cost(x, par) < 0.95 * self._init_cost(x, pp)

    def test_full_fit_with_parallel_init(self):
        x = self._clustered(n_clusters=40, per=50)
        model = (
            KMeans().setK(40).setInitMode("k-means||").setSeed(1)
            .setMaxIter(10).setInputCol(None).fit(x, num_partitions=4)
        )
        ref = (
            KMeans().setK(40).setInitMode("k-means++").setSeed(1)
            .setMaxIter(10).fit(x, num_partitions=4)
        )
        assert model.trainingCost <= ref.trainingCost * 1.05

    def test_deterministic_given_seed(self):
        x = self._clustered(n_clusters=20, per=30, dim=4)
        est = KMeans().setK(20).setInitMode("k-means||").setSeed(7)
        a = est._kmeans_parallel_init([x], None, 20)
        b = est._kmeans_parallel_init([x], None, 20)
        np.testing.assert_allclose(a, b)

    def test_zero_weight_rows_never_seed(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(400, 4))
        outliers = np.full((20, 4), 100.0) + rng.normal(size=(20, 4))
        data = np.concatenate([x, outliers])
        w = np.concatenate([np.ones(400), np.zeros(20)])
        est = KMeans().setK(8).setInitMode("k-means||").setSeed(0)
        centers = est._kmeans_parallel_init(
            [data], [w], 8
        )
        assert np.abs(centers).max() < 50.0  # no center at the outlier blob

    def test_init_steps_validation(self):
        with pytest.raises(ValueError, match="initSteps"):
            KMeans().setInitSteps(0)
        with pytest.raises(ValueError, match="initMode"):
            KMeans().setInitMode("kmeanspp")

    def test_weighted_plus_plus_respects_weights(self):
        import jax

        rng = np.random.default_rng(5)
        cand = np.concatenate([rng.normal(size=(50, 3)), 100.0 + rng.normal(size=(5, 3))])
        w = np.concatenate([np.ones(50), np.zeros(5)])
        centers = np.asarray(
            KM.weighted_kmeans_plus_plus_init(
                jax.random.PRNGKey(0), jnp.asarray(cand), jnp.asarray(w), 4
            )
        )
        assert np.abs(centers).max() < 50.0
