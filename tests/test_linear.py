"""GLM family tests — differentials vs sklearn closed forms and estimator
behavior (multi-partition parity, persistence, checkpoint/resume)."""

import numpy as np
import pytest
from sklearn.linear_model import LinearRegression as SkLinear
from sklearn.linear_model import LogisticRegression as SkLogistic
from sklearn.linear_model import Ridge as SkRidge

from spark_rapids_ml_tpu.models.linear import (
    LinearRegression,
    LinearRegressionModel,
    LogisticRegression,
    LogisticRegressionModel,
)


@pytest.fixture
def reg_data(rng):
    x = rng.normal(size=(400, 6))
    true_w = np.array([1.5, -2.0, 0.0, 3.0, 0.5, -1.0])
    y = x @ true_w + 0.7 + 0.01 * rng.normal(size=400)
    return x, y


@pytest.fixture
def cls_data(rng):
    x = rng.normal(size=(600, 4))
    true_w = np.array([2.0, -1.0, 0.5, 0.0])
    p = 1 / (1 + np.exp(-(x @ true_w - 0.3)))
    y = (rng.uniform(size=600) < p).astype(np.float64)
    return x, y


class TestLinearRegression:
    def test_matches_sklearn_ols(self, reg_data):
        x, y = reg_data
        model = LinearRegression().fit((x, y))
        sk = SkLinear().fit(x, y)
        np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-8)
        np.testing.assert_allclose(model.intercept, sk.intercept_, atol=1e-8)

    def test_matches_sklearn_ridge(self, reg_data):
        x, y = reg_data
        lam = 0.1
        model = LinearRegression().setRegParam(lam).fit((x, y))
        sk = SkRidge(alpha=lam * len(x)).fit(x, y)
        np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-7)
        np.testing.assert_allclose(model.intercept, sk.intercept_, atol=1e-7)

    def test_no_intercept(self, reg_data):
        x, y = reg_data
        model = LinearRegression().setFitIntercept(False).fit((x, y))
        sk = SkLinear(fit_intercept=False).fit(x, y)
        np.testing.assert_allclose(model.coefficients, sk.coef_, atol=1e-8)
        assert model.intercept == 0.0

    def test_multi_partition_equals_single(self, reg_data):
        x, y = reg_data
        m1 = LinearRegression().fit((x, y), num_partitions=1)
        m3 = LinearRegression().fit((x, y), num_partitions=3)
        np.testing.assert_allclose(m3.coefficients, m1.coefficients, atol=1e-9)
        np.testing.assert_allclose(m3.intercept, m1.intercept, atol=1e-9)

    def test_transform_pandas(self, reg_data):
        import pandas as pd

        x, y = reg_data
        df = pd.DataFrame({"features": list(x), "label": y})
        model = LinearRegression().fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        np.testing.assert_allclose(
            out["prediction"].to_numpy(), x @ model.coefficients + model.intercept,
            atol=1e-8,
        )

    def test_predict_single_row(self, reg_data):
        x, y = reg_data
        model = LinearRegression().fit((x, y))
        np.testing.assert_allclose(
            model.predict(x[0]), model._predict_matrix(x[:1])[0], atol=1e-8
        )

    def test_persistence_roundtrip(self, reg_data, tmp_path):
        x, y = reg_data
        model = LinearRegression().setRegParam(0.05).fit((x, y))
        model.save(tmp_path / "lr")
        loaded = LinearRegressionModel.load(tmp_path / "lr")
        np.testing.assert_array_equal(loaded.coefficients, model.coefficients)
        assert loaded.intercept == model.intercept
        assert loaded.getRegParam() == 0.05

    def test_singular_design_finite(self, rng):
        # constant feature column + intercept => singular normal equations;
        # the lstsq fallback must produce finite coefficients, not NaN
        x = np.ones((50, 3))
        y = rng.normal(size=50)
        model = LinearRegression().fit((x, y))
        assert np.all(np.isfinite(model.coefficients))
        np.testing.assert_allclose(
            model._predict_matrix(x), np.full(50, y.mean()), atol=1e-6
        )

    def test_mismatched_rows_rejected(self, reg_data):
        x, y = reg_data
        with pytest.raises(ValueError, match="rows"):
            LinearRegression().fit((x, y[:-5]))


class TestElasticNet:
    """FISTA-on-reduced-stats elastic net vs sklearn coordinate descent.

    Convention check (models/linear.py docstring): our (regParam=λ,
    elasticNetParam=α) == sklearn ElasticNet(alpha=λ, l1_ratio=α)."""

    def test_lasso_matches_sklearn(self, reg_data):
        from sklearn.linear_model import Lasso as SkLasso

        x, y = reg_data
        lam = 0.1
        m = (
            LinearRegression(regParam=lam, elasticNetParam=1.0, tol=1e-12)
            .fit((x, y))
        )
        sk = SkLasso(alpha=lam, tol=1e-12, max_iter=50_000).fit(x, y)
        np.testing.assert_allclose(m.coefficients, sk.coef_, atol=1e-5)
        np.testing.assert_allclose(m.intercept, sk.intercept_, atol=1e-5)

    def test_elastic_net_matches_sklearn(self, reg_data):
        from sklearn.linear_model import ElasticNet as SkEN

        x, y = reg_data
        m = (
            LinearRegression(
                regParam=0.05, elasticNetParam=0.4, tol=1e-12, maxIter=5000
            ).fit((x, y))
        )
        sk = SkEN(alpha=0.05, l1_ratio=0.4, tol=1e-12, max_iter=50_000).fit(x, y)
        np.testing.assert_allclose(m.coefficients, sk.coef_, atol=1e-5)
        np.testing.assert_allclose(m.intercept, sk.intercept_, atol=1e-5)

    def test_lasso_sparsity_and_kkt(self, rng):
        # lasso at meaningful λ must zero some coefficients, and the
        # survivors must satisfy the KKT stationarity conditions:
        #   w_j != 0  ->  |g_j| == λα   (g = smooth gradient, sign opposes w)
        #   w_j == 0  ->  |g_j| <= λα
        x = rng.normal(size=(500, 10))
        w_true = np.zeros(10)
        w_true[[1, 4, 7]] = [2.0, -3.0, 1.5]
        y = x @ w_true + 0.05 * rng.normal(size=500)
        lam = 0.2
        m = LinearRegression(
            regParam=lam, elasticNetParam=1.0, tol=1e-12, maxIter=10_000
        ).fit((x, y))
        w = np.asarray(m.coefficients)
        assert np.sum(np.abs(w) < 1e-9) >= 5  # noise coords zeroed
        xc = x - x.mean(0)
        yc = y - y.mean()
        g = (xc.T @ (xc @ w - yc)) / len(y)
        on = np.abs(w) > 1e-9
        np.testing.assert_allclose(g[on], -lam * np.sign(w[on]), atol=1e-6)
        assert np.all(np.abs(g[~on]) <= lam + 1e-6)

    def test_alpha_zero_equals_closed_form(self, reg_data):
        x, y = reg_data
        a = LinearRegression(regParam=0.01).fit((x, y))
        b = LinearRegression(regParam=0.01, elasticNetParam=0.0).fit((x, y))
        np.testing.assert_allclose(a.coefficients, b.coefficients)

    def test_no_intercept(self, reg_data):
        from sklearn.linear_model import Lasso as SkLasso

        x, y = reg_data
        m = LinearRegression(
            regParam=0.1, elasticNetParam=1.0, fitIntercept=False, tol=1e-12
        ).fit((x, y))
        sk = SkLasso(alpha=0.1, fit_intercept=False, tol=1e-12, max_iter=50_000).fit(x, y)
        np.testing.assert_allclose(m.coefficients, sk.coef_, atol=1e-5)
        assert m.intercept == 0.0

    def test_multi_partition_equals_single(self, reg_data):
        x, y = reg_data
        a = LinearRegression(regParam=0.05, elasticNetParam=0.7).fit((x, y))
        b = LinearRegression(regParam=0.05, elasticNetParam=0.7).fit(
            (x, y), num_partitions=4
        )
        np.testing.assert_allclose(a.coefficients, b.coefficients, atol=1e-10)

    def test_bad_alpha_rejected(self):
        with pytest.raises(ValueError, match="elasticNetParam"):
            LinearRegression(elasticNetParam=1.5)

    def test_cancelling_columns_stay_finite(self, rng):
        # x2 = -x1 makes A·1 exactly zero, collapsing the power-iteration
        # Lipschitz estimate; the trace fallback must keep FISTA finite
        # (the failure mode is a SILENT divergence to ±inf)
        x1 = rng.normal(size=(300, 1))
        x = np.concatenate([x1, -x1], axis=1)
        y = x1[:, 0] + 0.01 * rng.normal(size=300)
        m = LinearRegression(
            regParam=0.1, elasticNetParam=1.0, fitIntercept=False
        ).fit((x, y))
        w = np.asarray(m.coefficients)
        assert np.all(np.isfinite(w))
        # KKT: the lasso subgradient bound must hold at the solution
        g = (x.T @ (x @ w - y)) / len(y)
        assert np.all(np.abs(g) <= 0.1 + 1e-6)

    def test_persistence_roundtrip(self, reg_data, tmp_path):
        x, y = reg_data
        m = LinearRegression(regParam=0.1, elasticNetParam=1.0).fit((x, y))
        m.write().save(str(tmp_path / "en"))
        m2 = LinearRegressionModel.load(str(tmp_path / "en"))
        np.testing.assert_allclose(m.coefficients, m2.coefficients)
        assert m2.getOrDefault("elasticNetParam") == 1.0

    def test_sharded_fit_matches_host(self, reg_data):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from spark_rapids_ml_tpu.parallel import linear as PL
        from spark_rapids_ml_tpu.parallel import mesh as M

        mesh8 = M.create_mesh()
        x, y = reg_data
        rows = (len(x) // mesh8.size) * mesh8.size
        x, y = x[:rows], y[:rows]
        host = LinearRegression(regParam=0.1, elasticNetParam=1.0).fit((x, y))
        fit = PL.make_distributed_linreg_fit(
            mesh8, reg_param=0.1, elastic_net_param=1.0
        )
        xs = jax.device_put(x, M.data_sharding(mesh8))
        ys = jax.device_put(y, NamedSharding(mesh8, P(M.DATA_AXIS)))
        coef, intercept = fit(xs, ys)
        np.testing.assert_allclose(host.coefficients, np.asarray(coef), atol=1e-7)
        np.testing.assert_allclose(host.intercept, float(intercept), atol=1e-7)


class TestLogisticRegression:
    def test_matches_sklearn(self, cls_data):
        x, y = cls_data
        lam = 0.01
        model = LogisticRegression().setRegParam(lam).fit((x, y))
        # sklearn minimizes sum-loss + 1/(2C)·|w|²; our λ scales with rows
        sk = SkLogistic(C=1.0 / (lam * len(x)), tol=1e-10).fit(x, y)
        np.testing.assert_allclose(model.coefficients, sk.coef_[0], atol=1e-4)
        np.testing.assert_allclose(model.intercept, sk.intercept_[0], atol=1e-4)

    def test_separable_data_regularized(self, rng):
        # perfectly separable: unregularized weights diverge; λ keeps it sane
        x = np.concatenate([rng.normal(-3, 0.5, (50, 2)), rng.normal(3, 0.5, (50, 2))])
        y = np.concatenate([np.zeros(50), np.ones(50)])
        model = LogisticRegression().setRegParam(0.1).fit((x, y))
        preds = model._predict_matrix(x)
        assert (preds == y).mean() == 1.0

    def test_multi_partition_equals_single(self, cls_data):
        x, y = cls_data
        m1 = LogisticRegression().setRegParam(0.01).fit((x, y), num_partitions=1)
        m3 = LogisticRegression().setRegParam(0.01).fit((x, y), num_partitions=3)
        np.testing.assert_allclose(m3.coefficients, m1.coefficients, atol=1e-8)

    def test_bad_labels_rejected(self, cls_data):
        # non-integer labels are invalid for any family
        x, _ = cls_data
        with pytest.raises(ValueError, match="integer class labels"):
            LogisticRegression().fit((x, np.full(len(x), 0.5)))
        with pytest.raises(ValueError, match="integer class labels"):
            LogisticRegression().fit((x, np.full(len(x), -1.0)))

    def test_proba_monotone_in_margin(self, cls_data):
        x, y = cls_data
        model = LogisticRegression().setRegParam(0.01).fit((x, y))
        proba = model.predict_proba_matrix(x)
        margin = x @ model.coefficients + model.intercept
        assert np.all((proba >= 0.5) == (margin >= 0))

    def test_checkpoint_resume_matches(self, cls_data, tmp_path):
        x, y = cls_data
        mk = lambda: LogisticRegression().setRegParam(0.01).setMaxIter(20)
        full = mk().fit((x, y))
        mk().setMaxIter(3).fit(
            (x, y), checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1
        )
        resumed = mk().fit((x, y), checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
        np.testing.assert_allclose(resumed.coefficients, full.coefficients, atol=1e-6)

    def test_nan_input_does_not_persist_junk_checkpoint(self, cls_data, tmp_path):
        # ADVICE r4: the NaN-input raise must run BEFORE the checkpoint
        # save (run_chunked_newton's order) — otherwise checkpoint_every=1
        # persists an all-zeros step-0 checkpoint and a post-cleanup re-fit
        # silently resumes one iteration in.
        x, y = cls_data
        x_bad = x.copy()
        x_bad[0, 0] = np.nan
        ck = str(tmp_path / "ck")
        mk = lambda: LogisticRegression().setRegParam(0.01).setMaxIter(20)
        with pytest.raises(ValueError, match="NaN/Inf"):
            mk().fit((x_bad, y), checkpoint_dir=ck, checkpoint_every=1)
        fresh = mk().fit((x, y))
        refit = mk().fit((x, y), checkpoint_dir=ck, checkpoint_every=1)
        np.testing.assert_allclose(
            refit.coefficients, fresh.coefficients, atol=1e-10
        )

    def test_persistence_roundtrip(self, cls_data, tmp_path):
        x, y = cls_data
        model = LogisticRegression().setRegParam(0.01).fit((x, y))
        model.save(tmp_path / "logit")
        loaded = LogisticRegressionModel.load(tmp_path / "logit")
        np.testing.assert_array_equal(loaded.coefficients, model.coefficients)
        np.testing.assert_array_equal(loaded._predict_matrix(x), model._predict_matrix(x))


class TestLogRegElasticNet:
    """Proximal-Newton L1/elastic-net logistic vs sklearn.

    Convention: objective (1/m)·Σ logloss + λ(α‖w‖₁ + (1−α)/2‖w‖²) — so
    sklearn LogisticRegression(penalty="l1", C=1/(λ·m)) at α=1."""

    def test_lasso_logistic_matches_sklearn(self, cls_data):
        x, y = cls_data
        lam = 0.01
        m = LogisticRegression(
            regParam=lam, elasticNetParam=1.0, maxIter=100, tol=1e-10
        ).fit((x, y))
        # saga, not liblinear: liblinear folds the intercept into the
        # penalized features, saga leaves it unpenalized like this repo
        sk = SkLogistic(
            l1_ratio=1.0, C=1.0 / (lam * len(y)), solver="saga",
            tol=1e-12, max_iter=100_000,
        ).fit(x, y)
        np.testing.assert_allclose(
            m.coefficients, sk.coef_.ravel(), atol=2e-4
        )
        np.testing.assert_allclose(m.intercept, sk.intercept_[0], atol=2e-3)

    def test_l1_zeroes_noise_features(self, rng):
        x = rng.normal(size=(800, 8))
        w_true = np.zeros(8)
        w_true[[0, 3]] = [2.0, -1.5]
        p = 1 / (1 + np.exp(-(x @ w_true)))
        y = (rng.uniform(size=800) < p).astype(np.float64)
        m = LogisticRegression(
            regParam=0.05, elasticNetParam=1.0, maxIter=100, tol=1e-10
        ).fit((x, y))
        w = np.asarray(m.coefficients)
        assert np.all(np.abs(w[[1, 2, 4, 5, 6, 7]]) < 1e-6)
        assert np.all(np.abs(w[[0, 3]]) > 0.1)

    def test_alpha_zero_unchanged(self, cls_data):
        x, y = cls_data
        a = LogisticRegression(regParam=0.01).fit((x, y))
        b = LogisticRegression(regParam=0.01, elasticNetParam=0.0).fit((x, y))
        np.testing.assert_allclose(a.coefficients, b.coefficients)

    def test_multinomial_lasso_matches_sklearn(self, rng):
        # proximal Newton on the [C·d, C·d] softmax Fisher model vs sklearn
        # saga multinomial L1 (same objective up to C = 1/(λ·m))
        x = rng.normal(size=(450, 5))
        w_true = np.zeros((3, 5))
        w_true[0, 0], w_true[1, 1], w_true[2, 2] = 3.0, 3.0, -3.0
        logits = x @ w_true.T
        y = np.argmax(
            logits + rng.gumbel(size=logits.shape), axis=1
        ).astype(float)
        lam = 0.01
        m = LogisticRegression(
            regParam=lam, elasticNetParam=1.0, maxIter=100, tol=1e-10
        ).fit((x, y))
        sk = SkLogistic(
            l1_ratio=1.0, C=1.0 / (lam * len(y)), solver="saga",
            tol=1e-12, max_iter=200_000,
        ).fit(x, y)
        # softmax has a per-coordinate-shift gauge freedom under L1 that
        # sklearn resolves differently; compare class-margin DIFFERENCES
        # via predicted probabilities instead of raw coefficients
        ours = m.predict_proba_matrix(x)
        theirs = sk.predict_proba(x)
        np.testing.assert_allclose(ours, theirs, atol=5e-3)
        # sparsity materialized: noise coordinates are exactly zero
        w = np.asarray(m.coefficientMatrix)
        assert np.sum(np.abs(w) < 1e-8) >= 6

    def test_multinomial_alpha_accepted_all_paths(self, rng):
        x = rng.normal(size=(90, 3))
        y = np.repeat([0.0, 1.0, 2.0], 30)
        m = LogisticRegression(
            regParam=0.05, elasticNetParam=0.5, maxIter=40
        ).fit((x, y))
        assert m.coefficientMatrix.shape == (3, 3)

    def test_whole_loop_mesh_matches_host(self, cls_data):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from spark_rapids_ml_tpu.ops import linear as LIN
        from spark_rapids_ml_tpu.parallel import linear as PL
        from spark_rapids_ml_tpu.parallel import mesh as M

        mesh = M.create_mesh()
        x, y = cls_data
        rows = (len(x) // mesh.size) * mesh.size
        x, y = x[:rows], y[:rows]
        host = LogisticRegression(
            regParam=0.01, elasticNetParam=1.0, maxIter=50, tol=1e-10
        ).fit((x, y))
        fit = PL.make_distributed_logreg_fit(
            mesh, reg_param=0.01, elastic_net_param=1.0,
            max_iter=50, tol=1e-10,
        )
        xa = LIN.augment(jax.numpy.asarray(x))
        xs = jax.device_put(np.asarray(xa), M.data_sharding(mesh))
        ys = jax.device_put(y, NamedSharding(mesh, P(M.DATA_AXIS)))
        ws = jax.device_put(np.ones(rows), NamedSharding(mesh, P(M.DATA_AXIS)))
        w_fit, iters, _ = fit(xs, ys, ws)
        w_fit = np.asarray(w_fit)
        np.testing.assert_allclose(host.coefficients, w_fit[:-1], atol=1e-6)
        np.testing.assert_allclose(host.intercept, w_fit[-1], atol=1e-6)


class TestProbabilityCol:
    def test_pandas_emits_both_columns(self, cls_data):
        import pandas as pd

        x, y = cls_data
        df = pd.DataFrame({"features": list(x), "label": y})
        m = (
            LogisticRegression().setRegParam(0.01)
            .setProbabilityCol("probability").fit(df)
        )
        out = m.transform(df)
        assert "probability" in out.columns and "prediction" in out.columns
        proba = np.stack(out["probability"].to_numpy())
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-12)
        np.testing.assert_allclose(
            out["prediction"].to_numpy(), (proba[:, 1] >= 0.5).astype(float)
        )

    def test_matrix_input_keeps_prediction_only_contract(self, cls_data):
        x, y = cls_data
        m = (
            LogisticRegression().setProbabilityCol("probability").fit((x, y))
        )
        out = m.transform(x)  # ndarray in, prediction vector out
        assert isinstance(out, np.ndarray) and out.shape == (len(x),)


class TestShardedGLM:
    @pytest.fixture
    def mesh8(self):
        from spark_rapids_ml_tpu.parallel import mesh as M

        return M.create_mesh(data=8)

    def test_sharded_linreg_matches_host(self, reg_data, mesh8):
        import jax

        from spark_rapids_ml_tpu.parallel import linear as PL
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS
        from jax.sharding import NamedSharding, PartitionSpec as P

        x, y = reg_data
        fit = PL.make_distributed_linreg_fit(mesh8, reg_param=0.05)
        xs = jax.device_put(x, NamedSharding(mesh8, P(DATA_AXIS, None)))
        ys = jax.device_put(y, NamedSharding(mesh8, P(DATA_AXIS)))
        coef, intercept = fit(xs, ys)
        host = LinearRegression().setRegParam(0.05).fit((x, y))
        np.testing.assert_allclose(np.asarray(coef), host.coefficients, atol=1e-7)
        np.testing.assert_allclose(float(intercept), host.intercept, atol=1e-7)

    def test_sharded_newton_matches_host_stats(self, cls_data, mesh8):
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops import linear as LIN
        from spark_rapids_ml_tpu.parallel import linear as PL
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS
        from jax.sharding import NamedSharding, PartitionSpec as P

        x, y = cls_data
        x_aug = np.concatenate([x, np.ones((len(x), 1))], axis=1)
        w0 = jnp.zeros(x_aug.shape[1])
        step = PL.make_distributed_newton_step(mesh8, reg_param=0.01)
        xs = jax.device_put(x_aug, NamedSharding(mesh8, P(DATA_AXIS, None)))
        ys = jax.device_put(y, NamedSharding(mesh8, P(DATA_AXIS)))
        w1, norm1 = step(xs, ys, w0)
        stats = LIN.logistic_newton_stats(jnp.asarray(x_aug), jnp.asarray(y), w0)
        w1_host, norm1_host = LIN.newton_update(w0, stats, reg_param=0.01)
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w1_host), atol=1e-8)
        np.testing.assert_allclose(float(norm1), float(norm1_host), atol=1e-8)


def test_dropin_namespaces():
    from spark_rapids_ml_tpu.classification import LogisticRegression as L1
    from spark_rapids_ml_tpu.regression import LinearRegression as R1
    import spark_rapids_ml_tpu as pkg

    assert pkg.LinearRegression is R1
    assert pkg.LogisticRegression is L1
