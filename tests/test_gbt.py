"""GBT tests — sklearn GradientBoosting differentials + boosting invariants."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import GBTClassificationModel, GBTClassifier
from spark_rapids_ml_tpu.regression import GBTRegressionModel, GBTRegressor


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2400, 6))
    y = np.sin(x[:, 0]) * 3 + x[:, 2] ** 2 + 0.5 * x[:, 4] + rng.normal(
        scale=0.2, size=2400
    )
    return x[:1800], y[:1800], x[1800:], y[1800:]


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2400, 6))
    logit = 1.5 * x[:, 0] - x[:, 3] + x[:, 0] * x[:, 5]
    y = (logit + rng.normal(scale=0.7, size=2400) > 0).astype(float)
    return x[:1800], y[:1800], x[1800:], y[1800:]


def test_regressor_quality_vs_sklearn(reg_data):
    sk_ens = pytest.importorskip("sklearn.ensemble")
    xtr, ytr, xte, yte = reg_data
    m = (
        GBTRegressor().setMaxIter(60).setMaxDepth(4).setStepSize(0.2)
        .setMaxBins(64).setSeed(2).fit((xtr, ytr))
    )
    pred = m._predict_matrix(xte)
    ours = 1 - ((pred - yte) ** 2).mean() / yte.var()
    sk = sk_ens.GradientBoostingRegressor(
        n_estimators=60, max_depth=4, learning_rate=0.2, random_state=2
    ).fit(xtr, ytr)
    theirs = sk.score(xte, yte)
    assert ours >= theirs - 0.04, (ours, theirs)


def test_classifier_quality_vs_sklearn(clf_data):
    sk_ens = pytest.importorskip("sklearn.ensemble")
    xtr, ytr, xte, yte = clf_data
    m = (
        GBTClassifier().setMaxIter(60).setMaxDepth(3).setStepSize(0.2)
        .setMaxBins(64).setSeed(2).fit((xtr, ytr))
    )
    ours = (m._predict_matrix(xte) == yte).mean()
    sk = sk_ens.GradientBoostingClassifier(
        n_estimators=60, max_depth=3, learning_rate=0.2, random_state=2
    ).fit(xtr, ytr)
    theirs = sk.score(xte, yte)
    assert ours >= theirs - 0.04, (ours, theirs)


def test_training_loss_decreases(reg_data, clf_data):
    """Boosting's defining invariant: each stage reduces training loss."""
    xtr, ytr, _, _ = reg_data
    m = GBTRegressor().setMaxIter(25).setStepSize(0.3).fit((xtr, ytr))
    losses = m.trainLosses
    assert len(losses) == 25
    assert losses[-1] < losses[0] * 0.5
    assert np.all(np.diff(losses) <= 1e-9)  # squared loss: monotone

    xc, yc, _, _ = clf_data
    mc = GBTClassifier().setMaxIter(25).setStepSize(0.3).fit((xc, yc))
    assert mc.trainLosses[-1] < mc.trainLosses[0]


def test_classifier_output_columns_and_margin_consistency(clf_data):
    pd = pytest.importorskip("pandas")
    xtr, ytr, xte, _ = clf_data
    m = GBTClassifier().setMaxIter(15).fit(
        pd.DataFrame({"features": list(xtr), "label": ytr})
    )
    out = m.transform(pd.DataFrame({"features": list(xte[:40])}))
    assert {"rawPrediction", "probability", "prediction"} <= set(out.columns)
    raw = np.stack(out["rawPrediction"])
    p = np.stack(out["probability"])
    np.testing.assert_allclose(raw[:, 1], -raw[:, 0])
    # probability is the sigmoid of the margin: σ(2F) with raw = [−2F, 2F]
    np.testing.assert_allclose(p[:, 1], 1 / (1 + np.exp(-raw[:, 1])), rtol=1e-9)
    np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-12)
    np.testing.assert_array_equal(
        out["prediction"].to_numpy(), (raw[:, 1] > 0).astype(float)
    )


def test_determinism_and_subsampling(clf_data):
    xtr, ytr, _, _ = clf_data
    kw = dict(numTrees=10, seed=5, subsamplingRate=0.7)
    m1 = GBTClassifier(**kw).fit((xtr, ytr))
    m2 = GBTClassifier(**kw).fit((xtr, ytr))
    np.testing.assert_array_equal(
        np.asarray(m1.trees.feature), np.asarray(m2.trees.feature)
    )
    m3 = GBTClassifier(numTrees=10, seed=6, subsamplingRate=0.7).fit((xtr, ytr))
    assert not np.array_equal(
        np.asarray(m1.trees.feature), np.asarray(m3.trees.feature)
    )


def test_weighted_fit(reg_data):
    """Zero-weight rows must not influence the fit at all."""
    xtr, ytr, _, _ = reg_data
    x2 = np.concatenate([xtr, xtr[:200] + 100.0])  # junk rows far away
    y2 = np.concatenate([ytr, np.full(200, 1e6)])
    w2 = np.concatenate([np.ones(len(xtr)), np.zeros(200)])
    m_w = GBTRegressor().setMaxIter(10).setSeed(0).fit((x2, y2, w2))
    m_ref = GBTRegressor().setMaxIter(10).setSeed(0).fit((xtr, ytr))
    # zero-weight rows are excluded from the quantile grid AND carry zero
    # histogram mass, so the fits are numerically identical
    np.testing.assert_array_equal(
        np.asarray(m_w.trees.feature), np.asarray(m_ref.trees.feature)
    )
    np.testing.assert_allclose(
        m_w._predict_matrix(xtr[:100]),
        m_ref._predict_matrix(xtr[:100]),
        rtol=1e-10,
    )


def test_persistence_roundtrip(tmp_path, reg_data, clf_data):
    xtr, ytr, xte, _ = reg_data
    m = GBTRegressor().setMaxIter(8).fit((xtr, ytr))
    path = str(tmp_path / "gbtr")
    m.save(path)
    loaded = GBTRegressionModel.load(path)
    np.testing.assert_allclose(
        loaded._predict_matrix(xte), m._predict_matrix(xte)
    )
    np.testing.assert_allclose(loaded.trainLosses, m.trainLosses)

    xc, yc, xq, _ = clf_data
    mc = GBTClassifier().setMaxIter(8).fit((xc, yc))
    cpath = str(tmp_path / "gbtc")
    mc.save(cpath)
    lc = GBTClassificationModel.load(cpath)
    p0, _ = mc.proba_and_predictions(xq[:50])
    p1, _ = lc.proba_and_predictions(xq[:50])
    np.testing.assert_allclose(p0, p1)


def test_label_validation():
    x = np.random.default_rng(2).normal(size=(30, 3))
    with pytest.raises(ValueError, match="binary 0/1"):
        GBTClassifier().fit((x, np.arange(30, dtype=float)))
    with pytest.raises(ValueError, match="variance"):
        GBTRegressor().setImpurity("gini")


def test_spark_api_surface(clf_data):
    """Spark-parity knobs: configurable output columns, treeWeights with
    the MLlib boost schedule (first tree 1.0, later stages stepSize),
    'auto' strategy resolving to all features."""
    pd = pytest.importorskip("pandas")
    xtr, ytr, _, _ = clf_data
    m = (
        GBTClassifier().setMaxIter(5).setStepSize(0.25)
        .setProbabilityCol("p").setRawPredictionCol("rawr")
        .setFeatureSubsetStrategy("auto")
        .fit((xtr, ytr))
    )
    np.testing.assert_allclose(m.treeWeights, [1.0, 0.25, 0.25, 0.25, 0.25])
    out = m.transform(pd.DataFrame({"features": list(xtr[:10])}))
    assert {"p", "rawr", "prediction"} <= set(out.columns)


def test_feature_importances(clf_data):
    xtr, ytr, _, _ = clf_data
    m = GBTClassifier().setMaxIter(20).setMaxDepth(3).fit((xtr, ytr))
    imp = m.featureImportances
    np.testing.assert_allclose(imp.sum(), 1.0, rtol=1e-9)
    # the generative logit uses features 0, 3, 5
    assert set(np.argsort(imp)[-3:]) == {0, 3, 5}, imp
