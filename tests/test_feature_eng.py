"""VectorAssembler / StringIndexer / OneHotEncoder — pyspark.ml column
semantics on pandas/Arrow containers, feeding a full raw-columns pipeline."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.feature import (
    OneHotEncoder,
    OneHotEncoderModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)

pd = pytest.importorskip("pandas")


@pytest.fixture()
def df():
    rng = np.random.default_rng(0)
    return pd.DataFrame(
        {
            "age": rng.uniform(20, 60, size=8),
            "income": rng.uniform(1e4, 1e5, size=8),
            "scores": list(rng.normal(size=(8, 3))),
            "city": ["ber", "nyc", "nyc", "sfo", "nyc", "ber", "sfo", "nyc"],
        }
    )


def test_vector_assembler_concatenates_in_order(df):
    out = (
        VectorAssembler()
        .setInputCols(["age", "scores", "income"])
        .setOutputCol("features")
        .transform(df)
    )
    feats = np.stack(out["features"])
    assert feats.shape == (8, 5)
    np.testing.assert_allclose(feats[:, 0], df["age"])
    np.testing.assert_allclose(feats[:, 1:4], np.stack(df["scores"]))
    np.testing.assert_allclose(feats[:, 4], df["income"])


def test_vector_assembler_invalid_handling(df):
    df2 = df.copy()
    df2.loc[3, "age"] = np.nan
    va = VectorAssembler().setInputCols(["age", "income"])
    with pytest.raises(ValueError, match="age"):
        va.transform(df2)
    out = va.setHandleInvalid("keep").transform(df2)
    assert np.isnan(np.stack(out["features"])[3, 0])


def test_string_indexer_frequency_desc_with_alpha_ties(df):
    # counts: nyc=4, ber=2, sfo=2 → nyc:0, then tie broken alphabetically:
    # ber:1, sfo:2 (Spark's rule)
    model = StringIndexer().setInputCol("city").setOutputCol("ci").fit(df)
    assert model.labels == ["nyc", "ber", "sfo"]
    out = model.transform(df)
    expect = {"nyc": 0.0, "ber": 1.0, "sfo": 2.0}
    np.testing.assert_array_equal(
        out["ci"].to_numpy(), [expect[c] for c in df["city"]]
    )


def test_string_indexer_order_types_and_unseen(df):
    m = (
        StringIndexer().setInputCol("city").setOutputCol("ci")
        .setStringOrderType("alphabetAsc").fit(df)
    )
    assert m.labels == ["ber", "nyc", "sfo"]
    new = pd.DataFrame({"city": ["nyc", "tok"]})
    with pytest.raises(ValueError, match="unseen label 'tok'"):
        m.transform(new)
    out = m.setHandleInvalid("keep").transform(new)
    np.testing.assert_array_equal(out["ci"].to_numpy(), [1.0, 3.0])


def test_one_hot_encoder_drop_last_and_invalid(df):
    si = StringIndexer().setInputCol("city").setOutputCol("ci").fit(df)
    indexed = si.transform(df)
    ohe = OneHotEncoder().setInputCol("ci").setOutputCol("onehot").fit(indexed)
    out = ohe.transform(indexed)
    oh = np.stack(out["onehot"])
    assert oh.shape == (8, 2)  # 3 categories, dropLast
    # category 2 (sfo) encodes as all-zeros under dropLast
    sfo_rows = indexed["ci"].to_numpy() == 2.0
    assert (oh[sfo_rows] == 0).all()
    nyc_rows = indexed["ci"].to_numpy() == 0.0
    np.testing.assert_array_equal(oh[nyc_rows, 0], 1.0)

    full = (
        OneHotEncoder().setInputCol("ci").setOutputCol("onehot")
        .setDropLast(False).fit(indexed).transform(indexed)
    )
    np.testing.assert_allclose(np.stack(full["onehot"]).sum(1), 1.0)

    bad = pd.DataFrame({"ci": [5.0]})
    with pytest.raises(ValueError, match="outside"):
        ohe.transform(bad)
    kept = ohe.setHandleInvalid("keep").transform(bad)
    assert (np.stack(kept["onehot"]) == 0).all()  # extra slot is dropLast'd? no:
    # keep adds an extra slot; with dropLast the invalid slot is the last → dropped


def test_persistence(tmp_path, df):
    si = StringIndexer().setInputCol("city").setOutputCol("ci").fit(df)
    si.save(str(tmp_path / "si"))
    si2 = StringIndexerModel.load(str(tmp_path / "si"))
    assert si2.labels == si.labels
    ohe = OneHotEncoder().setInputCol("ci").fit(si.transform(df))
    ohe.save(str(tmp_path / "ohe"))
    ohe2 = OneHotEncoderModel.load(str(tmp_path / "ohe"))
    assert ohe2.categorySize == 3


def test_raw_columns_pipeline(df):
    """The real point: raw tabular columns → assembled features →
    estimator, as one Pipeline."""
    from spark_rapids_ml_tpu.models.pipeline import Pipeline
    from spark_rapids_ml_tpu.models.scaler import StandardScaler

    pipe = Pipeline(
        stages=[
            StringIndexer().setInputCol("city").setOutputCol("ci"),
            OneHotEncoder().setInputCol("ci").setOutputCol("cityv"),
            VectorAssembler()
            .setInputCols(["age", "income", "cityv", "scores"])
            .setOutputCol("features"),
            StandardScaler().setInputCol("features").setOutputCol("scaled")
            .setWithMean(True),
        ]
    )
    out = pipe.fit(df).transform(df)
    scaled = np.stack(out["scaled"])
    assert scaled.shape == (8, 7)
    np.testing.assert_allclose(scaled.mean(0), 0.0, atol=1e-9)


def test_string_indexer_unicode_labels_roundtrip(tmp_path):
    df2 = pd.DataFrame({"city": ["münchen", "nyc", "münchen", "køge"]})
    m = StringIndexer().setInputCol("city").setOutputCol("ci").fit(df2)
    path = str(tmp_path / "si_u")
    m.save(path)
    loaded = StringIndexerModel.load(path)
    assert loaded.labels == m.labels == ["münchen", "køge", "nyc"]
    np.testing.assert_array_equal(
        loaded.transform(df2)["ci"].to_numpy(), [0.0, 2.0, 0.0, 1.0]
    )


def test_vector_assembler_allows_inf(df):
    """Spark errors on NaN only — Infinity is a legal Double."""
    df2 = df.copy()
    df2.loc[0, "age"] = np.inf
    out = VectorAssembler().setInputCols(["age", "income"]).transform(df2)
    assert np.isinf(np.stack(out["features"])[0, 0])


def test_index_to_string_round_trips(df):
    from spark_rapids_ml_tpu.feature import IndexToString

    si = StringIndexer().setInputCol("city").setOutputCol("ci").fit(df)
    indexed = si.transform(df)
    back = (
        IndexToString().setInputCol("ci").setOutputCol("city2")
        .setLabels(si.labels).transform(indexed)
    )
    assert list(back["city2"]) == list(df["city"])
    with pytest.raises(ValueError, match="outside the label table"):
        IndexToString().setInputCol("ci").setLabels(["only-one"]).transform(
            indexed
        )
    with pytest.raises(ValueError, match="setLabels"):
        IndexToString().setInputCol("ci").transform(indexed)
