"""The health daemon's opportunistic harvest glue (tools/healthd.py).

The harvest path (ported from the retired-and-deleted
tools/transport_monitor_r5.py) only executes when the accelerator
transport heals — which may
never happen in a round. These tests drive the glue with a stubbed bench
runner so the file contracts (drift log lines, the stamped
BENCH_OPPORTUNISTIC payload bench.py's fallback consumes, the re-wedge
retreat) are verified without a chip, plus the --once exit-code contract
CI gates on.
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

_TOOLS = Path(__file__).resolve().parent.parent / "tools"


@pytest.fixture
def monitor(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "healthd_under_test", _TOOLS / "healthd.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LOG_PATH", str(tmp_path / "log.jsonl"))
    monkeypatch.setattr(mod, "BENCH_OUT", str(tmp_path / "opportunistic.json"))
    monkeypatch.setattr(mod, "DRIFT_OUT", str(tmp_path / "drift.jsonl"))
    monkeypatch.setattr(mod, "N_BENCH_RUNS", 3)
    yield mod
    del sys.modules[spec.name]


def _fake_record(run, rc, value=0.0171):
    payload = None
    if rc == 0:
        payload = {
            "metric": "pca_fit_uncentered_device_wall_clock_2Mx512_k50",
            "value": value,
            "unit": "seconds",
            "vs_baseline": 5.38,
        }
    return {
        "t": "2026-01-01T00:00:00+00:00",
        "elapsed_s": 1.0,
        "run": run,
        "rc": rc,
        "took_s": 12.3,
        "json": payload,
    }


class TestHarvestGlue:
    def test_harvest_writes_stamped_primary_and_drift_series(
        self, monitor, monkeypatch
    ):
        values = iter([0.017, 0.018, 0.016])
        monkeypatch.setattr(
            monitor,
            "run_bench",
            lambda i: _fake_record(i, 0, next(values)),
        )
        assert monitor.harvest() is True
        primary = json.loads(Path(monitor.BENCH_OUT).read_text())
        # the FIRST complete run is the primary, stamped for bench.py's
        # snapshot-time fallback age gate
        assert primary["value"] == 0.017
        assert isinstance(primary["harvested_at_unix"], float)
        assert "harvested_at" in primary
        drift = [
            json.loads(line)
            for line in Path(monitor.DRIFT_OUT).read_text().splitlines()
        ]
        assert [d["run"] for d in drift] == [1, 2, 3]
        assert [d["json"]["value"] for d in drift] == [0.017, 0.018, 0.016]

    def test_rewedge_mid_harvest_retreats_without_primary(
        self, monitor, monkeypatch
    ):
        rcs = iter([1, 1, 1])
        monkeypatch.setattr(
            monitor, "run_bench", lambda i: _fake_record(i, next(rcs))
        )
        assert monitor.harvest() is False
        assert not Path(monitor.BENCH_OUT).exists()
        drift = Path(monitor.DRIFT_OUT).read_text().splitlines()
        assert len(drift) == 2  # gave up after the second failure

    def test_first_failure_then_success_still_lands_primary(
        self, monitor, monkeypatch
    ):
        seq = iter([(1, 1), (2, 0), (3, 0)])

        def fake(i):
            run, rc = next(seq)
            return _fake_record(run, rc)

        monkeypatch.setattr(monitor, "run_bench", fake)
        assert monitor.harvest() is True
        assert json.loads(Path(monitor.BENCH_OUT).read_text())["value"] == 0.0171


class TestExitCodes:
    """The --once/--strict CI-gate contract (healthd._exit_code)."""

    def test_ok_is_zero_even_strict(self, monitor):
        rollup = {"state": "OK", "slo": {"total_breaches": 0}}
        assert monitor._exit_code(rollup, strict=False) == 0
        assert monitor._exit_code(rollup, strict=True) == 0

    def test_failing_is_two_regardless(self, monitor):
        rollup = {"state": "FAILING", "slo": {}}
        assert monitor._exit_code(rollup, strict=False) == 2
        assert monitor._exit_code(rollup, strict=True) == 2

    def test_degraded_and_breaches_only_fail_strict(self, monitor):
        degraded = {"state": "DEGRADED", "slo": {}}
        assert monitor._exit_code(degraded, strict=False) == 0
        assert monitor._exit_code(degraded, strict=True) == 1
        breached = {"state": "OK", "slo": {"total_breaches": 2}}
        assert monitor._exit_code(breached, strict=False) == 0
        assert monitor._exit_code(breached, strict=True) == 1


def test_transport_monitor_shim_is_retired():
    """The deprecation shim had one release of grace and is now deleted;
    only healthd remains. (Resurrecting the old entry point would hide
    the migration from anyone still scripting against it.)"""
    assert not (_TOOLS / "transport_monitor_r5.py").exists()
    assert (_TOOLS / "healthd.py").exists()
