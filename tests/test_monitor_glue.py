"""The round-5 transport monitor's harvest glue (tools/transport_monitor_r5).

The monitor is evidence-critical (VERDICT r4 Next #1) but its harvest path
only executes when the accelerator transport heals — which may never happen
in a round. These tests drive the glue with a stubbed bench runner so the
file contracts (drift log lines, the stamped BENCH_OPPORTUNISTIC payload
bench.py's fallback consumes, the re-wedge retreat) are verified without a
chip.
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

_TOOLS = Path(__file__).resolve().parent.parent / "tools"


@pytest.fixture
def monitor(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "transport_monitor_r5_under_test", _TOOLS / "transport_monitor_r5.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LOG_PATH", str(tmp_path / "log.jsonl"))
    monkeypatch.setattr(mod, "BENCH_OUT", str(tmp_path / "opportunistic.json"))
    monkeypatch.setattr(mod, "DRIFT_OUT", str(tmp_path / "drift.jsonl"))
    monkeypatch.setattr(mod, "N_BENCH_RUNS", 3)
    yield mod
    del sys.modules[spec.name]


def _fake_record(run, rc, value=0.0171):
    payload = None
    if rc == 0:
        payload = {
            "metric": "pca_fit_uncentered_device_wall_clock_2Mx512_k50",
            "value": value,
            "unit": "seconds",
            "vs_baseline": 5.38,
        }
    return {
        "t": "2026-01-01T00:00:00+00:00",
        "elapsed_s": 1.0,
        "run": run,
        "rc": rc,
        "took_s": 12.3,
        "json": payload,
    }


class TestHarvestGlue:
    def test_harvest_writes_stamped_primary_and_drift_series(
        self, monitor, monkeypatch
    ):
        values = iter([0.017, 0.018, 0.016])
        monkeypatch.setattr(
            monitor,
            "run_bench",
            lambda i: _fake_record(i, 0, next(values)),
        )
        assert monitor.harvest() is True
        primary = json.loads(Path(monitor.BENCH_OUT).read_text())
        # the FIRST complete run is the primary, stamped for bench.py's
        # snapshot-time fallback age gate
        assert primary["value"] == 0.017
        assert isinstance(primary["harvested_at_unix"], float)
        assert "harvested_at" in primary
        drift = [
            json.loads(line)
            for line in Path(monitor.DRIFT_OUT).read_text().splitlines()
        ]
        assert [d["run"] for d in drift] == [1, 2, 3]
        assert [d["json"]["value"] for d in drift] == [0.017, 0.018, 0.016]

    def test_rewedge_mid_harvest_retreats_without_primary(
        self, monitor, monkeypatch
    ):
        rcs = iter([1, 1, 1])
        monkeypatch.setattr(
            monitor, "run_bench", lambda i: _fake_record(i, next(rcs))
        )
        assert monitor.harvest() is False
        assert not Path(monitor.BENCH_OUT).exists()
        drift = Path(monitor.DRIFT_OUT).read_text().splitlines()
        assert len(drift) == 2  # gave up after the second failure

    def test_first_failure_then_success_still_lands_primary(
        self, monitor, monkeypatch
    ):
        seq = iter([(1, 1), (2, 0), (3, 0)])

        def fake(i):
            run, rc = next(seq)
            return _fake_record(run, rc)

        monkeypatch.setattr(monitor, "run_bench", fake)
        assert monitor.harvest() is True
        assert json.loads(Path(monitor.BENCH_OUT).read_text())["value"] == 0.0171
