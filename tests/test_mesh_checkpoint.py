"""Chunked-loop checkpointing for mesh-local fits (r3 verdict #6).

The whole-loop mesh programs used to reject ``checkpoint_dir`` outright —
a preempted 2-hour pod fit restarted from zero. The chunked variants run K
iterations per cached XLA program with a durable host checkpoint between
chunks; these tests assert the contract that matters: a partial fit plus a
resumed fit produces EXACTLY the model an uninterrupted fit produces
(same iteration trajectory, same programs), and mesh-barrier still rejects
with a pointer to the supported modes.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.localspark import LocalSparkSession
from spark_rapids_ml_tpu.localspark import types as LT
from spark_rapids_ml_tpu.spark import SparkKMeans, SparkLogisticRegression


@pytest.fixture(scope="module")
def session():
    s = LocalSparkSession(
        parallelism=2,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "JAX_ENABLE_X64": "1",
            "JAX_COMPILATION_CACHE_DIR": "/tmp/jax_test_cache",
        },
    )
    yield s
    s.stop()


def _labeled_df(session, x, y):
    schema = LT.StructType(
        [
            LT.StructField("features", LT.ArrayType(LT.DoubleType())),
            LT.StructField("label", LT.DoubleType()),
        ]
    )
    return session.createDataFrame(
        [(r.tolist(), float(l)) for r, l in zip(x, y)], schema, numPartitions=2
    )


def _features_df(session, x):
    schema = LT.StructType(
        [LT.StructField("features", LT.ArrayType(LT.DoubleType()))]
    )
    return session.createDataFrame(
        [(r.tolist(),) for r in x], schema, numPartitions=2
    )


class TestLogRegMeshChunkedCheckpoint:
    def _data(self):
        rng = np.random.default_rng(41)
        x = rng.normal(size=(300, 4))
        p = 1.0 / (1.0 + np.exp(-(x @ np.array([2.0, -1.0, 0.5, 0.0]))))
        y = (rng.random(300) < p).astype(float)
        return x, y

    def _est(self, iters):
        return (
            SparkLogisticRegression(maxIter=iters, regParam=1e-3)
            .setTol(0.0)  # fixed-iteration trajectory: exact comparison
            .setDistribution("mesh-local")
        )

    def test_partial_then_resume_matches_uninterrupted(self, session, tmp_path):
        x, y = self._data()
        df = _labeled_df(session, x, y)
        ckdir = str(tmp_path / "lr_mesh_ck")
        uninterrupted = self._est(8).fit(df)
        # "preemption": a fit stopped after 3 iterations left checkpoints
        self._est(3).fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        resumed = self._est(8).fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        np.testing.assert_allclose(
            resumed.coefficients, uninterrupted.coefficients, atol=1e-10
        )
        np.testing.assert_allclose(
            resumed.intercept, uninterrupted.intercept, atol=1e-10
        )

    def test_chunked_equals_whole_loop_without_checkpoint(self, session, tmp_path):
        x, y = self._data()
        df = _labeled_df(session, x, y)
        ckdir = str(tmp_path / "lr_mesh_ck2")
        whole = self._est(6).fit(df)
        chunked = self._est(6).fit(df, checkpoint_dir=ckdir, checkpoint_every=4)
        np.testing.assert_allclose(
            chunked.coefficients, whole.coefficients, atol=1e-10
        )

    def test_softmax_partial_then_resume(self, session, tmp_path):
        rng = np.random.default_rng(42)
        centers = np.array([[3.0, 0.0], [0.0, 3.0], [-3.0, -3.0]])
        x = np.vstack([rng.normal(size=(60, 2)) + c for c in centers])
        y = np.repeat([0.0, 1.0, 2.0], 60)
        df = _labeled_df(session, x, y)
        ckdir = str(tmp_path / "mn_mesh_ck")

        def est(iters):
            return (
                SparkLogisticRegression(maxIter=iters, regParam=1e-2)
                .setTol(0.0)
                .setDistribution("mesh-local")
            )

        uninterrupted = est(6).fit(df)
        est(2).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        resumed = est(6).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        np.testing.assert_allclose(
            resumed.coefficientMatrix,
            uninterrupted.coefficientMatrix,
            atol=1e-10,
        )

    def test_barrier_partial_then_resume_matches_uninterrupted(
        self, session, tmp_path
    ):
        # mesh-barrier edition: rank 0 of the jax.distributed group saves
        # between chunks (shared filesystem — one host here), the DRIVER
        # resolves the resume before launching the next stage
        x, y = self._data()
        df = _labeled_df(session, x, y)
        ckdir = str(tmp_path / "lr_barrier_ck")

        def est(iters):
            return (
                SparkLogisticRegression(maxIter=iters, regParam=1e-3)
                .setTol(0.0)
                .setDistribution("mesh-barrier")
            )

        uninterrupted = est(6).fit(df)
        est(2).fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        resumed = est(6).fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        np.testing.assert_allclose(
            resumed.coefficients, uninterrupted.coefficients, atol=1e-10
        )

    def test_barrier_resume_at_max_iter_skips_the_stage(self, session, tmp_path):
        x, y = self._data()
        df = _labeled_df(session, x, y)
        ckdir = str(tmp_path / "lr_barrier_ck2")
        full = self._est_barrier(4).fit(
            df, checkpoint_dir=ckdir, checkpoint_every=1
        )
        resumed = self._est_barrier(4).fit(
            df, checkpoint_dir=ckdir, checkpoint_every=1
        )
        np.testing.assert_allclose(
            resumed.coefficients, full.coefficients, atol=1e-12
        )

    def _est_barrier(self, iters):
        return (
            SparkLogisticRegression(maxIter=iters, regParam=1e-3)
            .setTol(0.0)
            .setDistribution("mesh-barrier")
        )


class TestKMeansMeshChunkedCheckpoint:
    def _data(self):
        rng = np.random.default_rng(43)
        anchors = np.array([[4.0, 0.0, 0.0], [0.0, 4.0, 0.0], [0.0, 0.0, 4.0]])
        return np.vstack([rng.normal(size=(70, 3)) * 0.5 + a for a in anchors])

    def _est(self, iters):
        return (
            SparkKMeans(k=3, seed=7, maxIter=iters)
            .setTol(0.0)
            .setDistribution("mesh-local")
        )

    def test_partial_then_resume_matches_uninterrupted(self, session, tmp_path):
        x = self._data()
        df = _features_df(session, x)
        ckdir = str(tmp_path / "km_mesh_ck")
        uninterrupted = self._est(8).fit(df)
        self._est(3).fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        resumed = self._est(8).fit(df, checkpoint_dir=ckdir, checkpoint_every=2)
        np.testing.assert_allclose(
            resumed.clusterCenters, uninterrupted.clusterCenters, atol=1e-10
        )
        np.testing.assert_allclose(
            resumed.trainingCost, uninterrupted.trainingCost, rtol=1e-10
        )

    def test_resume_at_max_iter_reports_checkpointed_cost(self, session, tmp_path):
        x = self._data()
        df = _features_df(session, x)
        ckdir = str(tmp_path / "km_mesh_ck2")
        full = self._est(5).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        resumed = self._est(5).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        assert np.isfinite(resumed.trainingCost)
        np.testing.assert_allclose(
            resumed.clusterCenters, full.clusterCenters, atol=1e-12
        )

    def test_barrier_partial_then_resume_matches_uninterrupted(
        self, session, tmp_path
    ):
        x = self._data()
        df = _features_df(session, x)
        ckdir = str(tmp_path / "km_barrier_ck")

        def est(iters):
            return (
                SparkKMeans(k=3, seed=7, maxIter=iters)
                .setTol(0.0)
                .setDistribution("mesh-barrier")
            )

        uninterrupted = est(6).fit(df)
        est(2).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        resumed = est(6).fit(df, checkpoint_dir=ckdir, checkpoint_every=1)
        np.testing.assert_allclose(
            resumed.clusterCenters, uninterrupted.clusterCenters, atol=1e-10
        )
