"""Fused Pallas Gram kernel — interpret-mode differentials on CPU.

On hardware the same kernel is exercised by bench.py; here the interpreter
validates the math (split-bf16 accumulation, moment fusion, padding)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.ops.pallas_gram import (
    fused_gram_moments,
    symmetric_gram_moments,
)


def _check(x, **kw):
    g, cs, sq = fused_gram_moments(jnp.asarray(x, jnp.float32), interpret=True, **kw)
    xf = x.astype(np.float64)
    # split-bf16 carries ~16 mantissa bits -> ~1e-5 relative
    rows = x.shape[0]
    scale = np.abs(xf.T @ xf).max() + 1e-12
    np.testing.assert_allclose(np.asarray(g), xf.T @ xf, atol=3e-5 * scale)
    # moments are reconstructed from hi+lo (~2^-17 relative per element);
    # with cancellation the error is absolute, ~sqrt(rows)·2^-17·|x|
    np.testing.assert_allclose(
        np.asarray(cs), xf.sum(0), rtol=1e-4, atol=2e-4 * np.sqrt(rows)
    )
    np.testing.assert_allclose(
        np.asarray(sq), (xf**2).sum(0), rtol=1e-4, atol=2e-4 * np.sqrt(rows)
    )


class TestFusedGram:
    def test_block_aligned(self, rng):
        _check(rng.normal(size=(2048, 256)), block_rows=512, block_cols=128)

    def test_row_padding(self, rng):
        _check(rng.normal(size=(700, 128)), block_rows=512, block_cols=128)

    def test_col_padding(self, rng):
        _check(rng.normal(size=(512, 200)), block_rows=256, block_cols=128)

    def test_multi_col_blocks(self, rng):
        # exercises the off-diagonal (i != j) tiles and the i==0 moment wave
        _check(rng.normal(size=(512, 384)), block_rows=256, block_cols=128)

    def test_symmetric_variant_matches(self, rng):
        """The upper-triangle-skip kernel must agree with the full one and
        produce an exactly symmetric Gram (mirrored, not recomputed)."""
        x = rng.normal(size=(700, 300)).astype(np.float32)
        g, cs, sq = symmetric_gram_moments(
            jnp.asarray(x), block_rows=256, block_cols=128, interpret=True
        )
        g = np.asarray(g)
        xf = x.astype(np.float64)
        scale = np.abs(xf.T @ xf).max()
        # off-diagonal blocks are mirrored bit-exactly; diagonal blocks are
        # computed directly and the hi·lo / lo·hi accumulation orders differ
        # by f32 rounding, so symmetry there is to rounding only
        np.testing.assert_allclose(g, g.T, atol=1e-5 * scale)
        np.testing.assert_array_equal(g[128:, :128], g[:128, 128:].T)
        np.testing.assert_allclose(g, xf.T @ xf, atol=3e-5 * scale)
        np.testing.assert_allclose(np.asarray(cs), xf.sum(0), rtol=1e-4, atol=6e-3)
        np.testing.assert_allclose(
            np.asarray(sq), (xf**2).sum(0), rtol=1e-4, atol=6e-3
        )

    def test_symmetric_single_tile(self, rng):
        x = rng.normal(size=(512, 128)).astype(np.float32)
        g, _, _ = symmetric_gram_moments(
            jnp.asarray(x), block_rows=256, block_cols=128, interpret=True
        )
        xf = x.astype(np.float64)
        scale = np.abs(xf.T @ xf).max()
        np.testing.assert_allclose(np.asarray(g), xf.T @ xf, atol=3e-5 * scale)

    def test_split_precision_beats_bf16(self, rng):
        """The hi+lo split must be far more accurate than plain bf16."""
        x = rng.normal(size=(1024, 128)).astype(np.float32)
        g, _, _ = fused_gram_moments(jnp.asarray(x), interpret=True,
                                     block_rows=512, block_cols=128)
        exact = x.astype(np.float64).T @ x.astype(np.float64)
        bf = np.asarray(jnp.asarray(x, jnp.bfloat16), np.float64)
        err_split = np.abs(np.asarray(g) - exact).max()
        err_bf16 = np.abs(bf.T @ bf - exact).max()
        assert err_split < err_bf16 / 20
