"""Live health & SLO engine (telemetry.health / telemetry.slo /
telemetry.httpd).

Covers the ISSUE-8 acceptance scenarios without hardware:

- an injected ``device.init`` hang wedges the inline transport probe past
  its deadline → the component escalates to FAILING and ``/healthz``
  flips 200 → 503;
- the sliding-window SLO engine breaches only after the burn streak and
  books ``slo.breach`` counter + timeline instant;
- the HTTP exporter scraped MID-STREAM (from inside a streamed fold's
  source iterator) returns parse-clean Prometheus text including the
  live ``stream.active`` gauge and rolling SLO percentiles;
- the monitor thread (and any straggling probe thread) shuts down
  cleanly — no dangling named threads after ``stop()``;
- FitReport schema 6 carries the monitor's ``health`` summary.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.telemetry import health, httpd
from spark_rapids_ml_tpu.telemetry import slo as slo_mod
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from spark_rapids_ml_tpu.telemetry import reset_metrics
from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    """Isolated registry/faults/singletons per test; always tear down any
    monitor or exporter a test started."""
    monkeypatch.delenv(faults.FAULT_PLAN_VAR, raising=False)
    faults.reset_faults()
    reset_metrics()
    yield
    httpd.stop_http_server(timeout=10.0)
    health.stop_monitor(timeout=10.0)
    faults.reset_faults()
    reset_metrics()


def _get(url: str) -> tuple[int, str]:
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# one Prometheus sample line: name{labels} value  (labels optional)
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+$"
)


def _assert_parse_clean_prometheus(text: str) -> None:
    assert text, "empty exposition"
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE.match(line), f"malformed exposition line: {line!r}"
        value = line.rsplit(" ", 1)[1]
        float(value)  # must parse (inf/nan spellings included)


# -- SLO engine --------------------------------------------------------------


class TestSloEngine:
    def test_parse_objectives_grammar(self):
        objs = slo_mod.parse_objectives(
            " fold.wait:p99:2.0, ingest.rows:min_rate:50000 "
        )
        assert [o.key for o in objs] == [
            "fold.wait:p99", "ingest.rows:min_rate",
        ]
        assert objs[0].target == 2.0
        assert slo_mod.parse_objectives("") == ()
        with pytest.raises(ValueError, match="series:kind:target"):
            slo_mod.parse_objectives("fold.wait:p99")
        with pytest.raises(ValueError, match="neither"):
            slo_mod.parse_objectives("fold.wait:mean:2.0")
        with pytest.raises(ValueError, match="not a"):
            slo_mod.parse_objectives("fold.wait:p99:fast")

    def test_latency_breach_fires_only_after_burn_streak(self):
        reg = MetricsRegistry()
        eng = slo_mod.SloEngine(
            slo_mod.parse_objectives("fold.wait:p95:0.001"),
            window_s=60.0, burn=2, registry=reg,
        )
        tl0 = TIMELINE.seq()
        reg.histogram_record("span.seconds", 0.5, phase="fold.wait")
        r1 = eng.evaluate()
        (o1,) = r1["objectives"]
        assert o1["breached"] is True and o1["streak"] == 1
        assert r1["total_breaches"] == 0  # burn not reached yet

        reg.histogram_record("span.seconds", 0.6, phase="fold.wait")
        r2 = eng.evaluate()
        (o2,) = r2["objectives"]
        assert o2["streak"] == 2 and o2["breaches"] == 1
        assert r2["total_breaches"] == 1
        snap = reg.snapshot()
        assert snap.counter("slo.breach") == 1
        breach_events = [
            e for e in TIMELINE.events(tl0) if e.get("name") == "slo.breach"
        ]
        assert breach_events, "slo.breach timeline instant missing"
        assert breach_events[0]["args"]["objective"] == "fold.wait:p95"

    def test_min_rate_floor_needs_traffic_to_judge(self):
        reg = MetricsRegistry()
        eng = slo_mod.SloEngine(
            slo_mod.parse_objectives("ingest.rows:min_rate:1000000"),
            window_s=60.0, burn=1, registry=reg,
        )
        r = eng.evaluate()
        (o,) = r["objectives"]
        assert o["value"] is None and o["breached"] is False
        # moving but far below the floor → breach
        reg.counter_inc("ingest.rows", 5)
        r = eng.evaluate()
        (o,) = r["objectives"]
        assert o["value"] is not None and o["breached"] is True
        assert r["total_breaches"] == 1

    def test_rolling_percentiles_published_without_objectives(self):
        reg = MetricsRegistry()
        eng = slo_mod.SloEngine((), window_s=60.0, registry=reg)
        reg.histogram_record("span.seconds", 0.1, phase="ingest.chunk")
        reg.histogram_record("span.seconds", 0.3, phase="ingest.chunk")
        r = eng.evaluate()
        assert "ingest.chunk" in r["rolling"]
        assert set(r["rolling"]["ingest.chunk"]) == {"p50", "p95", "p99"}
        snap = reg.snapshot()
        keys = {
            snap_key for (name, snap_key) in snap.gauges
            if name == "slo.rolling"
        }
        assert any("ingest.chunk" in str(k) for k in keys)


# -- health monitor ----------------------------------------------------------


class TestHealthMonitor:
    def test_all_ok_rollup(self):
        mon = health.HealthMonitor(
            interval_s=60.0, probe_mode="inline",
            probe_fn=lambda: (True, "stub ok"),
        )
        r = mon.poll_once()
        assert r["state"] == "OK"
        assert set(r["components"]) == set(health.COMPONENTS)
        assert r["polls"] == 1 and r["transitions"] == 0
        mon.stop()

    def test_injected_device_init_hang_times_out_probe_to_failing(
        self, monkeypatch
    ):
        """The acceptance scenario: a chaos-plan hang on device.init wedges
        the default inline probe past its deadline; with failing_after=1
        the transport component goes straight to FAILING and the
        transition is counted + recorded on the timeline."""
        monkeypatch.setenv(faults.FAULT_PLAN_VAR, "device.init:hang:1:1.0")
        faults.reset_faults()
        mon = health.HealthMonitor(
            interval_s=60.0, probe_mode="inline",
            probe_timeout_s=0.1, failing_after=1,
        )
        tl0 = TIMELINE.seq()
        r = mon.poll_once()
        transport = r["components"]["transport"]
        assert transport["state"] == "FAILING"
        assert "did not complete" in transport["detail"]
        assert r["state"] == "FAILING"
        snap = REGISTRY.snapshot()
        assert snap.counter(
            "health.transitions", component="transport", to="FAILING"
        ) == 1
        assert any(
            e.get("name") == "health.transition"
            and e["args"].get("component") == "transport"
            for e in TIMELINE.events(tl0)
        )
        # the wedged probe thread is joined (bounded) by stop()
        mon.stop(timeout=5.0)
        assert "tpu-ml-health-probe" not in {
            t.name for t in threading.enumerate() if t.is_alive()
        }

    def test_probe_failure_streak_escalates_degraded_then_failing(self):
        mon = health.HealthMonitor(
            interval_s=60.0, probe_mode="inline", probe_timeout_s=1.0,
            failing_after=2, probe_fn=lambda: (False, "synthetic down"),
        )
        r1 = mon.poll_once()
        assert r1["components"]["transport"]["state"] == "DEGRADED"
        r2 = mon.poll_once()
        assert r2["components"]["transport"]["state"] == "FAILING"
        mon.stop()

    def test_stream_heartbeat_staleness(self):
        mon = health.HealthMonitor(
            interval_s=60.0, probe_mode="off", stale_s=60.0, failing_after=2,
        )
        # no active stream → OK regardless of beats
        assert mon.poll_once()["components"]["stream"]["state"] == "OK"
        REGISTRY.gauge_set("stream.active", 1)
        REGISTRY.gauge_set("stream.last_beat", time.monotonic() - 120.0)
        assert mon.poll_once()["components"]["stream"]["state"] == "DEGRADED"
        assert mon.poll_once()["components"]["stream"]["state"] == "FAILING"
        # stream ends (ingest clears the gauge in its finally) → back to OK
        REGISTRY.gauge_set("stream.active", 0)
        assert mon.poll_once()["components"]["stream"]["state"] == "OK"
        # fresh beat while active → OK
        REGISTRY.gauge_set("stream.active", 1)
        REGISTRY.gauge_set("stream.last_beat", time.monotonic())
        assert mon.poll_once()["components"]["stream"]["state"] == "OK"
        mon.stop()

    def test_worker_trailer_recency(self):
        mon = health.HealthMonitor(
            interval_s=60.0, probe_mode="off", stale_s=60.0,
        )
        assert mon.poll_once()["components"]["workers"]["state"] == "OK"
        REGISTRY.gauge_set("worker.last_trailer", time.monotonic() - 300.0)
        assert mon.poll_once()["components"]["workers"]["state"] == "DEGRADED"
        REGISTRY.gauge_set("worker.last_trailer", time.monotonic())
        assert mon.poll_once()["components"]["workers"]["state"] == "OK"
        mon.stop()

    def test_resilience_signals_window(self):
        mon = health.HealthMonitor(
            interval_s=60.0, probe_mode="off", retry_storm=8,
        )
        assert mon.poll_once()["components"]["resilience"]["state"] == "OK"
        REGISTRY.counter_inc("retry.attempts", 10, site="fold.dispatch")
        r = mon.poll_once()
        assert r["components"]["resilience"]["state"] == "DEGRADED"
        assert "retry storm" in r["components"]["resilience"]["detail"]
        # storm passed: the NEXT window is quiet again
        assert mon.poll_once()["components"]["resilience"]["state"] == "OK"
        # cpu fallback is cumulative, not windowed: it marks the whole run
        REGISTRY.counter_inc("degraded.cpu_fallback")
        r = mon.poll_once()
        assert r["components"]["resilience"]["state"] == "DEGRADED"
        assert "cpu fallback" in r["components"]["resilience"]["detail"]
        mon.stop()

    def test_monitor_thread_starts_polls_and_stops_cleanly(self):
        mon = health.HealthMonitor(
            interval_s=0.05, probe_mode="inline",
            probe_fn=lambda: (True, "ok"),
        )
        mon.start()
        assert mon.running
        deadline = time.monotonic() + 10.0
        while mon.polls < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.polls >= 2
        mon.stop(timeout=10.0)
        assert not mon.running
        assert "tpu-ml-health-monitor" not in {
            t.name for t in threading.enumerate() if t.is_alive()
        }

    def test_singleton_start_get_stop(self):
        assert health.get_monitor() is None
        mon = health.start_monitor(
            interval_s=3600.0, probe_mode="inline",
            probe_fn=lambda: (True, "ok"),
        )
        assert health.get_monitor() is mon
        assert health.start_monitor() is mon  # idempotent
        health.stop_monitor()
        assert health.get_monitor() is None
        assert health.current_summary() == {}


# -- HTTP exporter -----------------------------------------------------------


class TestHttpExporter:
    def test_healthz_flips_200_to_503_when_probe_wedges(self):
        state = {"ok": True}

        def probe():
            return state["ok"], "stub"

        mon = health.start_monitor(
            interval_s=3600.0, probe_mode="inline", probe_timeout_s=1.0,
            failing_after=1, probe_fn=probe,
        )
        server = httpd.start_http_server(0, with_monitor=False)
        code, body = _get(server.url + "/healthz")
        assert code == 200
        payload = json.loads(body)
        assert payload["state"] == "OK"
        assert payload["components"]["transport"]["state"] == "OK"

        state["ok"] = False
        mon.poll_once()
        code, body = _get(server.url + "/healthz")
        assert code == 503
        payload = json.loads(body)
        assert payload["state"] == "FAILING"
        assert payload["components"]["transport"]["state"] == "FAILING"

    def test_healthz_unknown_without_monitor(self):
        server = httpd.start_http_server(0, with_monitor=False)
        code, body = _get(server.url + "/healthz")
        assert code == 200
        assert json.loads(body)["state"] == "UNKNOWN"

    def test_metrics_scraped_mid_stream_is_parse_clean(self):
        """Scrape /metrics and /healthz from INSIDE a streamed fold's
        source iterator — the live-watchability acceptance check."""
        from spark_rapids_ml_tpu.ops import linalg as L
        from spark_rapids_ml_tpu.spark import ingest

        server = httpd.start_http_server(0)  # also starts the monitor
        mon = health.get_monitor()
        scraped: dict = {}
        rng = np.random.default_rng(3)

        def source():
            for i in range(3):
                if i == 2:
                    mon.poll_once()  # force a fresh SLO/rolling publish
                    scraped["metrics"] = _get(server.url + "/metrics")
                    scraped["healthz"] = _get(server.url + "/healthz")
                yield np.asarray(rng.normal(size=(128, 6)), np.float64)

        ingest.stream_fold(
            source(), L.gram_fold_step(), n=6,
            init=L.init_gram_carry(6, np.float64), chunk_rows=128,
        )
        code, text = scraped["metrics"]
        assert code == 200
        _assert_parse_clean_prometheus(text)
        # the stream was live at scrape time
        assert "tpu_ml_stream_active 1" in text
        assert "tpu_ml_stream_last_beat" in text
        assert "tpu_ml_ingest_rows" in text
        assert "tpu_ml_health_state" in text
        # rolling SLO percentile gauges for the default watchlist
        assert 'tpu_ml_slo_rolling{q="p99",series="ingest.chunk"}' in text
        hcode, hbody = scraped["healthz"]
        assert hcode == 200 and json.loads(hbody)["state"] == "OK"
        # after the stream, the active gauge is cleared
        code, text = _get(server.url + "/metrics")
        assert code == 200
        assert "tpu_ml_stream_active 0" in text

    def test_slo_report_and_404_endpoints(self):
        health.start_monitor(
            interval_s=3600.0, probe_mode="inline",
            probe_fn=lambda: (True, "ok"),
        ).poll_once()
        server = httpd.start_http_server(0, with_monitor=False)
        code, body = _get(server.url + "/slo")
        assert code == 200
        payload = json.loads(body)
        assert "window_s" in payload and "objectives" in payload
        code, body = _get(server.url + "/report")
        assert code == 200
        assert "reports" in json.loads(body)
        code, body = _get(server.url + "/nope")
        assert code == 404
        # request counters are booked per path
        snap = REGISTRY.snapshot()
        assert snap.counter("http.requests", path="/slo") == 1
        assert snap.counter("http.requests", path="/nope") == 1

    def test_ensure_started_is_off_without_port_env(self, monkeypatch):
        monkeypatch.delenv(httpd.HTTP_PORT_VAR, raising=False)
        assert httpd.ensure_started() is None
        assert httpd.get_http_server() is None

    def test_ensure_started_with_env_port_is_idempotent(self, monkeypatch):
        monkeypatch.setenv(httpd.HTTP_PORT_VAR, "0")
        server = httpd.ensure_started()
        assert server is not None
        assert httpd.ensure_started() is server
        assert httpd.get_http_server() is server
        assert health.get_monitor() is not None  # monitor came up alongside

    def test_stop_http_server_joins_threads(self):
        server = httpd.start_http_server(0)
        assert _get(server.url + "/healthz")[0] in (200, 503)
        httpd.stop_http_server(timeout=10.0)
        assert httpd.get_http_server() is None
        assert health.get_monitor() is None
        alive = {t.name for t in threading.enumerate() if t.is_alive()}
        assert "tpu-ml-httpd" not in alive
        assert "tpu-ml-health-monitor" not in alive


# -- FitReport schema 6 stamping ---------------------------------------------


class TestFitReportHealthStamp:
    def test_fit_report_carries_health_summary(self):
        from spark_rapids_ml_tpu.models.pca import PCA
        from spark_rapids_ml_tpu.telemetry.report import SCHEMA_VERSION

        assert SCHEMA_VERSION == 6
        health.start_monitor(
            interval_s=3600.0, probe_mode="inline",
            probe_fn=lambda: (True, "ok"),
        ).poll_once()
        x = np.random.default_rng(0).normal(size=(128, 4))
        model = PCA().setInputCol("f").setK(2).fit(x)
        rep = model.fit_report
        assert rep.health["state"] in ("OK", "DEGRADED", "FAILING")
        assert set(rep.health["components"]) == set(health.COMPONENTS)
        assert rep.health["polls"] >= 1
        assert "slo_breaches" in rep.health
        d = rep.to_dict()
        assert d["schema"] == 6 and d["health"] == rep.health

    def test_fit_report_health_empty_without_monitor(self):
        from spark_rapids_ml_tpu.models.pca import PCA
        from spark_rapids_ml_tpu.telemetry.report import FitReport

        x = np.random.default_rng(1).normal(size=(128, 4))
        model = PCA().setInputCol("f").setK(2).fit(x)
        assert model.fit_report.health == {}
        # older records load with an empty default
        assert FitReport.from_dict({"estimator": "X"}).health == {}
