"""Perf ledger + regression sentinel (tools/perf_sentinel.py).

Covers the ISSUE-5 sentinel list: a fresh ledger always passes, a
synthetic 2x slowdown (and a 2x throughput drop) is flagged and exits 2
under --strict, improvements and within-threshold noise pass, smoke and
full-shape entries are never compared with each other, unit-derived
direction (seconds up = bad, rows/s down = bad), and --bless truncates
the ledger to the new baseline.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "perf_sentinel.py")

spec = importlib.util.spec_from_file_location("perf_sentinel", CLI)
sentinel = importlib.util.module_from_spec(spec)
spec.loader.exec_module(sentinel)


def entry(wall=1.0, rows_s=1000.0, smoke=True, **extra_metrics):
    metrics = {
        "fit.wall": {"value": wall, "unit": "seconds"},
        "fit.throughput": {"value": rows_s, "unit": "rows/s"},
    }
    for name, (value, unit) in extra_metrics.items():
        metrics[name] = {"value": value, "unit": unit}
    return {
        "type": "perf_ledger",
        "schema": 1,
        "timestamp_unix": 0.0,
        "smoke": smoke,
        "metrics": metrics,
        "cost_model": {},
    }


def write_ledger(path, entries):
    with open(path, "w", encoding="utf-8") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    return str(path)


class TestCompare:
    def test_clean_run_has_no_regressions(self):
        history = [entry(wall=1.0, rows_s=1000.0) for _ in range(5)]
        regs, notes = sentinel.compare(entry(1.05, 980.0), history, 0.35)
        assert regs == [] and notes == []

    def test_2x_slowdown_flagged(self):
        history = [entry(wall=1.0) for _ in range(5)]
        regs, _ = sentinel.compare(entry(wall=2.0), history, 0.35)
        assert [r["metric"] for r in regs] == ["fit.wall"]
        assert regs[0]["ratio"] == pytest.approx(2.0)
        assert regs[0]["baseline_median"] == 1.0

    def test_2x_throughput_drop_flagged(self):
        history = [entry(rows_s=1000.0) for _ in range(5)]
        regs, _ = sentinel.compare(entry(rows_s=500.0), history, 0.35)
        assert [r["metric"] for r in regs] == ["fit.throughput"]

    def test_improvement_is_not_a_regression(self):
        history = [entry(wall=1.0, rows_s=1000.0) for _ in range(5)]
        regs, _ = sentinel.compare(entry(wall=0.4, rows_s=2500.0), history, 0.35)
        assert regs == []

    def test_direction_comes_from_unit(self):
        assert sentinel.lower_is_better("seconds")
        assert sentinel.lower_is_better("bytes")
        assert sentinel.lower_is_better("ms")
        assert not sentinel.lower_is_better("rows/s")
        assert not sentinel.lower_is_better("cosine")

    def test_new_metric_and_zero_baseline_are_notes(self):
        history = [entry(extra=(0.0, "seconds")) for _ in range(3)]
        cur = entry(extra=(1.0, "seconds"), brand_new=(5.0, "widgets"))
        regs, notes = sentinel.compare(cur, history, 0.35)
        assert regs == []
        assert any("brand_new" in n and "no history" in n for n in notes)
        assert any("extra" in n and "zero baseline" in n for n in notes)

    def test_median_absorbs_one_outlier_run(self):
        history = [entry(wall=w) for w in (1.0, 1.0, 1.0, 1.0, 30.0)]
        regs, _ = sentinel.compare(entry(wall=1.1), history, 0.35)
        assert regs == []


class TestCli:
    def test_fresh_ledger_passes_strict(self, tmp_path):
        p = write_ledger(tmp_path / "l.jsonl", [entry()])
        assert sentinel.main([p, "--strict"]) == 0

    def test_empty_ledger_passes(self, tmp_path):
        p = tmp_path / "l.jsonl"
        p.write_text("")
        assert sentinel.main([str(p), "--strict"]) == 0

    def test_missing_ledger_is_an_error(self, tmp_path):
        assert sentinel.main([str(tmp_path / "nope.jsonl")]) == 1

    def test_strict_exits_2_on_synthetic_regression(self, tmp_path, capsys):
        entries = [entry(wall=1.0) for _ in range(5)] + [entry(wall=2.0)]
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p]) == 0  # report-only mode never gates
        assert sentinel.main([p, "--strict"]) == 2
        out = capsys.readouterr().out
        assert "REGRESSION fit.wall" in out
        assert "--bless" in out  # points at the intentional-change workflow

    def test_threshold_is_respected(self, tmp_path):
        entries = [entry(wall=1.0) for _ in range(5)] + [entry(wall=1.25)]
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict", "--threshold", "0.35"]) == 0
        assert sentinel.main([p, "--strict", "--threshold", "0.2"]) == 2

    def test_smoke_and_full_runs_never_compared(self, tmp_path):
        # slow full-shape history must not judge a fast smoke run (or the
        # reverse) — the current smoke entry only sees smoke history
        entries = [entry(wall=10.0, smoke=False) for _ in range(5)]
        entries.append(entry(wall=1.0, smoke=True))
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict"]) == 0  # fresh for smoke
        entries.append(entry(wall=2.0, smoke=True))
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict"]) == 2  # judged vs smoke only

    def test_last_window_bounds_history(self, tmp_path):
        # ancient fast history beyond --last must not flag today's steady
        # state: 2 slow entries in the window, current matches them
        entries = [entry(wall=1.0) for _ in range(5)]
        entries += [entry(wall=3.0), entry(wall=3.0), entry(wall=3.1)]
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict", "--last", "2"]) == 0
        assert sentinel.main([p, "--strict", "--last", "0"]) == 2

    def test_bless_truncates_to_new_baseline(self, tmp_path):
        entries = [entry(wall=1.0) for _ in range(5)] + [entry(wall=2.0)]
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict"]) == 2
        assert sentinel.main([p, "--bless"]) == 0
        remaining = sentinel.load_ledger(p)
        assert len(remaining) == 1
        assert remaining[0]["metrics"]["fit.wall"]["value"] == 2.0
        # after blessing, the once-regressed numbers are the baseline
        assert sentinel.main([p, "--strict"]) == 0

    def test_corrupt_lines_are_skipped(self, tmp_path):
        p = tmp_path / "l.jsonl"
        lines = [json.dumps(entry(wall=1.0)) for _ in range(3)]
        lines.insert(1, "{torn line")
        lines.append(json.dumps({"type": "other"}))
        p.write_text("\n".join(lines) + "\n")
        assert len(sentinel.load_ledger(str(p))) == 3
        assert sentinel.main([str(p), "--strict"]) == 0


class TestVanishedMetrics:
    def test_metric_present_in_all_history_must_not_vanish(self):
        history = [entry(serve_p99_ms=(4.0, "ms")) for _ in range(4)]
        regs, _ = sentinel.compare(entry(), history, 0.35)
        assert [r["metric"] for r in regs] == ["serve_p99_ms"]
        assert regs[0]["vanished"] is True

    def test_metric_absent_from_some_history_may_vanish(self):
        # a metric that was never in EVERY comparable entry (e.g. gated
        # behind an opt-in stage) is not a gated series
        history = [entry(serve_p99_ms=(4.0, "ms")), entry(), entry()]
        regs, _ = sentinel.compare(entry(), history, 0.35)
        assert regs == []

    def test_vanished_metric_gates_strict(self, tmp_path, capsys):
        entries = [entry(serve_p99_ms=(4.0, "ms")) for _ in range(4)]
        entries.append(entry())
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict"]) == 2
        out = capsys.readouterr().out
        assert "REGRESSION serve_p99_ms" in out
        assert "missing from the current entry" in out


class TestAbsoluteCeilings:
    def ceiled(self, value, ceiling, unit="ms"):
        e = entry()
        e["metrics"]["serve_p99_ms"] = {
            "value": value, "unit": unit, "ceiling": ceiling,
        }
        return e

    def test_crossed_ceiling_is_a_regression_despite_flat_history(self):
        # history sits at the same value, so the ratio gate would pass —
        # the declared absolute bound still fails it
        history = [self.ceiled(6.0, 5.0) for _ in range(4)]
        regs, _ = sentinel.compare(self.ceiled(6.0, 5.0), history, 0.35)
        assert [r["metric"] for r in regs] == ["serve_p99_ms"]
        assert regs[0]["ceiling"] is True
        assert regs[0]["baseline_median"] == 5.0

    def test_within_ceiling_passes(self):
        history = [self.ceiled(4.0, 5.0) for _ in range(4)]
        regs, _ = sentinel.compare(self.ceiled(4.9, 5.0), history, 0.35)
        assert regs == []

    def test_higher_is_better_units_read_ceiling_as_floor(self):
        e = entry()
        e["metrics"]["fit.throughput"] = {
            "value": 90.0, "unit": "rows/s", "ceiling": 100.0,
        }
        regs, _ = sentinel.compare(e, [entry()], 0.35)
        assert [r["metric"] for r in regs] == ["fit.throughput"]
        assert regs[0]["ceiling"] is True

    def test_ceiling_gates_even_a_fresh_ledger(self, tmp_path, capsys):
        # the bound rides the entry itself, so neither an empty history
        # nor --bless waves it through
        p = write_ledger(tmp_path / "l.jsonl", [self.ceiled(9.0, 5.0)])
        assert sentinel.main([p, "--strict"]) == 2
        assert "absolute ceiling" in capsys.readouterr().out
        assert sentinel.main([p, "--bless"]) == 0
        assert sentinel.main([p, "--strict"]) == 2  # still over after bless

    def test_serve_p99_history_regression_and_bless_workflow(
        self, tmp_path
    ):
        # the serving gate end to end: ms unit derives lower-is-better, a
        # p99 jump fails --strict, blessing accepts the new baseline
        entries = [entry(serve_p99_ms=(4.0, "ms")) for _ in range(5)]
        entries.append(entry(serve_p99_ms=(9.0, "ms")))
        p = write_ledger(tmp_path / "l.jsonl", entries)
        assert sentinel.main([p, "--strict"]) == 2
        assert sentinel.main([p, "--bless"]) == 0
        assert sentinel.main([p, "--strict"]) == 0
