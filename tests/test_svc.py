"""LinearSVC tests — sklearn LinearSVC differential + mesh equality.

sklearn's LinearSVC(loss='squared_hinge', penalty='l2') minimizes
C·Σ max(0, 1−y·m)² + ½‖w‖² — the same objective up to the λ↔C
reparameterization (λ·m = 1/C), so coefficient-level agreement (not just
accuracy) is checkable on non-separable data.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.classification import LinearSVC, LinearSVCModel
from spark_rapids_ml_tpu.ops import linear as LIN


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(1500, 8))
    w_true = rng.normal(size=8)
    margin = x @ w_true + 0.3
    y = (margin + rng.normal(scale=2.0, size=1500) > 0).astype(float)
    return x, y


def test_coefficients_match_sklearn(xy):
    svm = pytest.importorskip("sklearn.svm")
    x, y = xy
    reg = 0.1
    model = LinearSVC().setRegParam(reg).setMaxIter(50).fit((x, y))
    # λ·m·Σmax² ↔ sklearn C·Σmax² + ½‖w‖²: C = 1/(λ·m)
    sk = svm.LinearSVC(
        loss="squared_hinge", C=1.0 / (reg * len(x)), max_iter=20000,
        tol=1e-10,
    ).fit(x, y)
    np.testing.assert_allclose(
        model.coefficients, sk.coef_[0], rtol=0.02, atol=5e-3
    )
    np.testing.assert_allclose(
        model.intercept, sk.intercept_[0], rtol=0.05, atol=5e-3
    )


def test_accuracy_and_threshold(xy):
    x, y = xy
    model = LinearSVC().setRegParam(0.01).fit((x, y))
    acc = (model._predict_matrix(x) == y).mean()
    # the noise level caps the Bayes rate at ~0.81; the fit reaches it
    assert acc > 0.79, acc
    # a huge threshold predicts all 0
    model.setThreshold(1e6)
    assert not model._predict_matrix(x).any()


def test_transform_raw_prediction_columns(xy):
    pd = pytest.importorskip("pandas")
    x, y = xy
    df = pd.DataFrame({"features": list(x), "label": y})
    model = LinearSVC().setRegParam(0.01).fit(df)
    out = model.transform(pd.DataFrame({"features": list(x[:50])}))
    assert {"rawPrediction", "prediction"} <= set(out.columns)
    raw = np.stack(out["rawPrediction"])
    np.testing.assert_allclose(raw[:, 1], -raw[:, 0])
    np.testing.assert_array_equal(
        out["prediction"].to_numpy(), (raw[:, 1] > 0).astype(float)
    )


def test_weighted_fit_equals_duplication(xy):
    x, y = xy
    x, y = x[:200], y[:200]
    dup = np.arange(0, 200, 4)
    w = np.ones(200)
    w[dup] = 2.0
    # both fits see identical Σc (m = 250) and identical loss sums, so the
    # SAME regParam yields the same objective — weight ≡ duplication exactly
    m_w = LinearSVC().setRegParam(0.05).fit((x, y, w))
    m_d = LinearSVC().setRegParam(0.05).fit(
        (np.concatenate([x, x[dup]]), np.concatenate([y, y[dup]]))
    )
    np.testing.assert_allclose(
        m_w.coefficients, m_d.coefficients, rtol=1e-6, atol=1e-9
    )


def test_label_validation():
    x = np.random.default_rng(1).normal(size=(20, 3))
    with pytest.raises(ValueError, match="binary 0/1"):
        LinearSVC().fit((x, np.arange(20, dtype=float)))


def test_persistence_roundtrip(tmp_path, xy):
    x, y = xy
    model = LinearSVC().setRegParam(0.02).fit((x[:300], y[:300]))
    path = str(tmp_path / "svc")
    model.save(path)
    loaded = LinearSVCModel.load(path)
    np.testing.assert_allclose(loaded.coefficients, model.coefficients)
    np.testing.assert_array_equal(
        loaded._predict_matrix(x[:50]), model._predict_matrix(x[:50])
    )


def test_mesh_svc_matches_driver_merge(xy):
    """The squared-hinge whole-loop mesh program lands where the
    driver-merge loop lands."""
    from spark_rapids_ml_tpu.parallel.linear import make_distributed_logreg_fit
    from spark_rapids_ml_tpu.parallel.mesh import create_mesh

    x, y = xy
    ndev = len(jax.devices())
    rows = (len(x) // ndev) * ndev
    x, y = x[:rows], y[:rows]
    mesh = create_mesh(data=ndev)
    xa = LIN.augment(jnp.asarray(x))
    fit = make_distributed_logreg_fit(
        mesh, reg_param=0.05, max_iter=50, tol=1e-9, loss="squared_hinge"
    )
    w_mesh, iters, _ = fit(
        xa, jnp.asarray(y), jnp.asarray(np.ones(rows))
    )
    core = LinearSVC().setRegParam(0.05).setMaxIter(50).setTol(1e-9).fit((x, y))
    np.testing.assert_allclose(
        np.asarray(w_mesh)[:-1], core.coefficients, rtol=1e-8, atol=1e-10
    )
    assert int(iters) >= 2
