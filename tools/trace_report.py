#!/usr/bin/env python
"""Render per-fit/transform telemetry JSONL (TPU_ML_TELEMETRY_PATH).

Usage::

    python tools/trace_report.py /path/to/telemetry.jsonl [--last N] [--strict]

For each ``fit_report`` or ``transform_report`` record (newest last;
``--last N`` keeps only the final N): a per-phase latency table (count /
total / p50 / p90 / p99 / max), throughput and collective/compile
summaries, the analytical cost-model line (FLOPs, bytes accessed, roofline
utilization vs TPU_ML_PEAK_TFLOPS), per-partition breakdowns for
transforms, peak device memory, and a set of anomaly checks — heuristics
that turn the numbers into a diagnosis:

- ``fold.wait`` total > 2× ``fold.dispatch`` total ⇒ the streamed-fit
  pipeline is NOT overlapping H2D with compute (the terminal block is
  eating what double-buffering should hide).
- compile seconds > 50% of fit wall ⇒ compile-dominated fit (check the
  persistent cache, TPU_ML_COMPILE_CACHE, and shape-bucketing).
- zero rows ingested with nonzero wall ⇒ the fit never saw the data path
  this report instruments (fine for array fits fed device arrays; worth a
  look for DataFrame fits).
- nonzero ``retry.attempts`` / ``chunk.bisections`` counters ⇒ the fit
  completed but only by recovering (transient retries, OOM chunk
  bisection) — healthy output, unhealthy ride; worth investigating
  before it becomes a hard failure.
- nonzero ``fault.injected`` ⇒ a TPU_ML_FAULT_PLAN was active; expected
  only in chaos tests, never in a production report.
- nonzero ``slo.breach`` counted during the fit window ⇒ a declared
  ``TPU_ML_SLO`` latency ceiling or throughput floor burned through its
  tolerance while the fit ran (``slo-breach-during-fit``).
- backend compiles far exceeding the distinct cost-model kernel count ⇒
  recompile storm: static-shape bucketing is not holding, so the same
  logical kernels keep recompiling per shape (check TPU_ML_MIN_BUCKET and
  TPU_ML_COMPILE_CACHE).
- ``scheduler.hedge`` count > 20% of ``scheduler.tasks`` ⇒ hedge storm:
  speculative duplicates are no longer the exception — the hedge
  threshold is mis-tuned for this workload or most partitions are
  stragglers (check TPU_ML_HEDGE_FACTOR / TPU_ML_HEDGE_FLOOR_S and the
  partition sizing).
- nonzero ``worker.quarantine`` ⇒ a worker slot crash-looped until its
  circuit breaker opened; the fit finished on the surviving slots with
  reduced parallelism.
- transform reports: slowest partition > 3× the median partition ⇒
  partition skew; one straggler sets the wall clock.

The reader is tolerant by design: a record from a newer schema than this
tool understands, or one missing the fields a renderer needs, is skipped
with a note — never a KeyError traceback — so one odd record cannot hide
the rest of the file.

Exit status: 0 normally; with ``--strict``, 2 when any anomaly fired OR
any record had to be skipped (CI gate). Stdlib-only on the read path —
the report must render on hosts without jax installed.
"""

from __future__ import annotations

import argparse
import json
import sys

# highest fit_report schema this renderer understands (telemetry.report
# .SCHEMA_VERSION); newer records are skipped with a note, older ones
# render with defaults for the fields they predate
SUPPORTED_SCHEMA = 6

# highest transform_report schema understood
# (telemetry.report.TRANSFORM_SCHEMA_VERSION)
SUPPORTED_TRANSFORM_SCHEMA = 1


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.0f}us"


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024.0
    return f"{v:.1f}TiB"


def _table(rows: list[list[str]], header: list[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(header), sep] + [line(r) for r in rows])


def check_anomalies(rec: dict) -> list[str]:
    """The heuristic diagnoses for one fit_report record."""
    out: list[str] = []
    phases = rec.get("phases", {})
    wait = phases.get("fold.wait", {}).get("sum", 0.0)
    dispatch = phases.get("fold.dispatch", {}).get("sum", 0.0)
    if dispatch > 0 and wait > 2.0 * dispatch:
        out.append(
            f"pipeline not overlapping: fold.wait total {_fmt_s(wait)} > 2x "
            f"fold.dispatch total {_fmt_s(dispatch)} — the terminal block is "
            "absorbing the fold work; H2D is not hiding behind compute "
            "(check donate_argnums on the fold step and chunk sizing)"
        )
    wall = rec.get("wall_seconds", 0.0)
    compile_s = rec.get("compile", {}).get("seconds", 0.0)
    if wall > 0 and compile_s > 0.5 * wall:
        out.append(
            f"compile-dominated fit: {_fmt_s(compile_s)} of {_fmt_s(wall)} "
            "wall went to XLA compiles (check TPU_ML_COMPILE_CACHE and that "
            "input shapes hit the row buckets)"
        )
    if wall > 0 and not rec.get("rows_ingested"):
        out.append(
            "no rows counted: the fit bypassed the instrumented ingest/"
            "columnar path (expected for fits fed pre-built device arrays)"
        )
    retries = _counter_total(rec, "retry.attempts")
    bisections = _counter_total(rec, "chunk.bisections")
    if retries or bisections:
        out.append(
            f"recovered-but-degraded fit: {retries:g} retried attempt(s), "
            f"{bisections:g} chunk bisection(s) — the fit finished only by "
            "recovering; investigate the flaking transport / device memory "
            "headroom before it becomes a hard failure"
        )
    injected = _counter_total(rec, "fault.injected")
    if injected:
        out.append(
            f"fault injection active: {injected:g} synthetic fault(s) fired "
            "— TPU_ML_FAULT_PLAN is set; expected only in chaos tests, "
            "never in production"
        )
    breaches = _counter_total(rec, "slo.breach")
    if breaches:
        out.append(
            f"slo-breach-during-fit: {breaches:g} windowed SLO breach(es) "
            "fired while this fit ran — a declared TPU_ML_SLO target "
            "(latency ceiling or throughput floor) burned through its "
            "tolerance; see the slo.breach timeline instants and the "
            "/slo endpoint for which objective"
        )
    storm = _recompile_storm(rec)
    if storm:
        out.append(storm)
    hedges = _counter_total(rec, "scheduler.hedge")
    tasks = _counter_total(rec, "scheduler.tasks")
    if tasks > 0 and hedges > 0.2 * tasks:
        out.append(
            f"hedge-storm: {hedges:g} speculative hedge(s) for {tasks:g} "
            "scheduled task(s) (> 20%) — hedging should be the exception, "
            "not the norm; the straggler threshold is mis-tuned for this "
            "workload (check TPU_ML_HEDGE_FACTOR / TPU_ML_HEDGE_FLOOR_S "
            "and the partition sizing)"
        )
    quarantined = _counter_total(rec, "worker.quarantine")
    if quarantined:
        out.append(
            f"worker-quarantined: {quarantined:g} worker slot(s) crash-"
            "looped until the circuit breaker opened — the fit finished on "
            "the surviving slots with reduced parallelism; inspect the "
            "worker.quarantine timeline instants and the slot's last error "
            "in /healthz before the next run"
        )
    return out


def _recompile_storm(rec: dict) -> str | None:
    """Backend compiles >> distinct cost-model kernels ⇒ recompile storm.

    Each captured kernel legitimately costs up to two compiles (the AOT
    cost-analysis lowering plus the real dispatch), and a fit also runs a
    few auxiliary jitted helpers the cost model does not capture — hence
    the 2x + slack budget before the check fires.
    """
    kernels = (rec.get("cost_model") or {}).get("kernels") or {}
    count = (rec.get("compile") or {}).get("count", 0)
    if kernels and count > 2 * len(kernels) + 2:
        return (
            f"recompile storm: {count:g} backend compiles for "
            f"{len(kernels)} distinct cost-model kernel(s) — the same "
            "logical kernels are recompiling per input shape (check "
            "TPU_ML_MIN_BUCKET row-bucketing and TPU_ML_COMPILE_CACHE; "
            "if a code path builds jax.jit programs per call, "
            "`python -m tools.tpulint` rule TPL003 finds it statically)"
        )
    return None


def check_transform_anomalies(rec: dict) -> list[str]:
    """The heuristic diagnoses for one transform_report record."""
    out: list[str] = []
    wall = rec.get("wall_seconds", 0.0)
    if wall > 0 and not rec.get("rows"):
        out.append(
            "no rows counted: the transform plan was built but never "
            "materialized through the instrumented arrow path (lazy plans "
            "only report after an action consumes them)"
        )
    parts = rec.get("partitions") or {}
    secs = sorted(
        p.get("seconds", 0.0) for p in parts.values() if p.get("seconds")
    )
    if len(secs) >= 2:
        median = secs[len(secs) // 2]
        if median > 0 and secs[-1] > 3.0 * median:
            out.append(
                f"partition skew: slowest partition took {_fmt_s(secs[-1])} "
                f"vs median {_fmt_s(median)} — one straggler is setting the "
                "wall clock (check the input partitioning)"
            )
    retries = _counter_total(rec, "retry.attempts")
    if retries:
        out.append(
            f"recovered-but-degraded transform: {retries:g} retried "
            "attempt(s) — the transform finished only by recovering"
        )
    injected = _counter_total(rec, "fault.injected")
    if injected:
        out.append(
            f"fault injection active: {injected:g} synthetic fault(s) fired "
            "— TPU_ML_FAULT_PLAN is set; expected only in chaos tests, "
            "never in production"
        )
    storm = _recompile_storm(rec)
    if storm:
        out.append(storm)
    return out


def _counter_total(rec: dict, name: str) -> float:
    """Sum a counter across its label sets: report counters are keyed
    ``name`` or ``name{label=value,...}`` (telemetry.registry.render_key)."""
    total = 0.0
    for key, val in (rec.get("counters") or {}).items():
        if key == name or key.startswith(name + "{"):
            total += val
    return total


def _print_phase_table(rec: dict, out) -> None:
    phases = rec.get("phases", {})
    if not phases:
        print("(no spans recorded)", file=out)
        return
    rows = []
    for name, p in sorted(
        phases.items(), key=lambda kv: -kv[1].get("sum", 0.0)
    ):
        rows.append([
            name,
            int(p.get("count", 0)),
            _fmt_s(p.get("sum", 0.0)),
            _fmt_s(p.get("p50", 0.0)),
            _fmt_s(p.get("p90", 0.0)),
            _fmt_s(p.get("p99", 0.0)),
            _fmt_s(p.get("max", 0.0)),
        ])
    print(
        _table(rows, ["phase", "count", "total", "p50", "p90", "p99", "max"]),
        file=out,
    )


def _print_cost_model(rec: dict, out) -> None:
    """The analytical FLOPs/bytes + roofline line (telemetry.costmodel)."""
    cm = rec.get("cost_model") or {}
    kernels = cm.get("kernels") or {}
    if not kernels and not cm.get("analytical_flops"):
        return
    line = (
        f"cost model: {cm.get('analytical_flops', 0):,.0f} analytical FLOPs, "
        f"{_fmt_bytes(cm.get('analytical_bytes', 0))} accessed, "
        f"{len(kernels)} kernel(s)"
    )
    util = cm.get("roofline_utilization")
    if util is not None:
        line += (
            f"; roofline {util:.3%} of "
            f"{cm.get('peak_flops', 0) / 1e12:.0f} TFLOP/s peak"
        )
    print(line, file=out)
    for name, k in sorted(kernels.items()):
        calls = k.get("calls", 0)
        detail = (
            f"  kernel {name}: {calls:g} call(s), "
            f"{k.get('flops', 0):,.0f} FLOPs/call, "
            f"{_fmt_bytes(k.get('bytes_accessed', 0))}/call"
        )
        if k.get("temp_bytes"):
            detail += f", temp {_fmt_bytes(k['temp_bytes'])}"
        print(detail, file=out)


def _print_tuning(rec: dict, out) -> None:
    """The autotuner decision line (fit_report schema >= 4): which
    TuningConfig the fit actually ran with and where it came from."""
    tuning = rec.get("tuning") or {}
    if not tuning:
        return
    source = tuning.get("source", "?")
    config = tuning.get("config")
    if config:
        desc = (
            f"chunk_rows={config.get('chunk_rows')}, "
            f"layout={config.get('layout')}, policy={config.get('policy')}"
        )
    else:
        desc = "static knobs (no tuned config)"
    n_dec = len(tuning.get("decisions") or [])
    hit = "cache hit" if tuning.get("cache_hit") else f"source={source}"
    print(
        f"autotune: {desc} ({hit}; {n_dec} decision(s) this fit)",
        file=out,
    )


def _print_admission(rec: dict, out) -> None:
    """The admission-control decision stamped at fit start (fit_report
    schema >= 6): which policy ran and what it decided. Only non-plain
    admits are printed — a healthy admit under the default policy is the
    uninteresting common case."""
    adm = rec.get("admission") or {}
    if not adm:
        return
    action = adm.get("action", "?")
    policy = adm.get("policy", "?")
    if action == "admit" and policy in ("refuse", "degrade"):
        return  # healthy-path admit: no news is good news
    print(
        f"admission: action={action} policy={policy} "
        f"health={adm.get('health_state', '?')} — {adm.get('reason', '')}",
        file=out,
    )


def _print_health(rec: dict, out) -> None:
    """The live-monitor rollup stamped at fit end (fit_report schema >= 5):
    worst component state, any non-OK components, and counted SLO
    breaches. Absent (empty) when no monitor ran — nothing is printed."""
    health = rec.get("health") or {}
    if not health:
        return
    components = health.get("components") or {}
    bad = ", ".join(
        f"{c}={s}" for c, s in sorted(components.items()) if s != "OK"
    )
    line = f"health: {health.get('state', '?')}"
    if bad:
        line += f" ({bad})"
    line += (
        f"; {health.get('polls', 0)} poll(s), "
        f"{health.get('transitions', 0)} transition(s), "
        f"{health.get('slo_breaches', 0)} SLO breach(es)"
    )
    print(line, file=out)


def render_record(rec: dict, out=sys.stdout) -> list[str]:
    """Print one fit_report; returns its anomaly list."""
    est = rec.get("estimator", "?")
    uid = rec.get("uid", "")
    wall = rec.get("wall_seconds", 0.0)
    fit_id = rec.get("fit_id", "")
    tag = f" [{uid}]" if uid else ""
    tag += f" fit={fit_id}" if fit_id else ""
    print(f"\n=== {est}{tag} — {_fmt_s(wall)} ===", file=out)
    ov = rec.get("overlap_fraction")
    if ov is not None:
        print(
            f"streamed H2D<->compute overlap: {ov:.2f} "
            f"({'overlapped' if ov > 0 else 'NOT overlapped'}; "
            "see tools/trace_timeline.py for the event view)",
            file=out,
        )

    _print_phase_table(rec, out)

    rows_in = rec.get("rows_ingested", 0)
    if rows_in:
        line = (
            f"ingest: {rows_in} rows, {_fmt_bytes(rec.get('bytes_ingested', 0))}"
        )
        if wall > 0:
            line += f" ({rows_in / wall:,.0f} rows/s)"
        if rec.get("h2d_bytes"):
            line += f"; h2d {_fmt_bytes(rec['h2d_bytes'])}"
        print(line, file=out)
    coll = rec.get("collectives", {})
    if coll.get("count") or coll.get("tree_combines"):
        print(
            f"collectives: {coll.get('count', 0):g} launches, "
            f"{_fmt_bytes(coll.get('bytes', 0))} payload, "
            f"{coll.get('tree_combines', 0):g} tree combines",
            file=out,
        )
    comp = rec.get("compile", {})
    if comp.get("count"):
        print(
            f"compile: {comp['count']:g} backend compiles, "
            f"{_fmt_s(comp.get('seconds', 0.0))} "
            f"(trace {_fmt_s(comp.get('trace_seconds', 0.0))}; "
            f"cache {comp.get('cache_hits', 0):g} hits / "
            f"{comp.get('cache_misses', 0):g} misses)",
            file=out,
        )
    _print_cost_model(rec, out)
    _print_tuning(rec, out)
    _print_health(rec, out)
    _print_admission(rec, out)
    peak = rec.get("peak_device_bytes", 0)
    if peak:
        print(f"peak device memory: {_fmt_bytes(peak)}", file=out)

    anomalies = check_anomalies(rec)
    for a in anomalies:
        print(f"  !! {a}", file=out)
    if not anomalies:
        print("  anomaly checks: ok", file=out)
    return anomalies


def render_transform_record(rec: dict, out=sys.stdout) -> list[str]:
    """Print one transform_report; returns its anomaly list."""
    name = rec.get("transformer", "?")
    uid = rec.get("uid", "")
    wall = rec.get("wall_seconds", 0.0)
    transform_id = rec.get("transform_id", "")
    tag = f" [{uid}]" if uid else ""
    tag += f" transform={transform_id}" if transform_id else ""
    print(f"\n=== {name}{tag} — {_fmt_s(wall)} (transform) ===", file=out)

    _print_phase_table(rec, out)

    rows_out = rec.get("rows", 0)
    if rows_out:
        line = f"output: {rows_out} rows, {_fmt_bytes(rec.get('bytes', 0))}"
        if wall > 0:
            line += f" ({rows_out / wall:,.0f} rows/s)"
        print(line, file=out)

    parts = rec.get("partitions") or {}
    if parts:
        def _pkey(kv):
            pid = kv[0]
            return (0, int(pid)) if pid.isdigit() else (1, 0)
        rows = []
        for pid, p in sorted(parts.items(), key=_pkey):
            rows.append([
                pid,
                int(p.get("rows", 0)),
                _fmt_bytes(p.get("bytes", 0)),
                int(p.get("batches", 0)),
                _fmt_s(p.get("seconds", 0.0)),
            ])
        print(
            _table(rows, ["partition", "rows", "bytes", "batches", "seconds"]),
            file=out,
        )
    lat = rec.get("partition_latency") or {}
    if lat.get("count"):
        print(
            f"partition latency: {lat['count']:g} partition(s), "
            f"p50 {_fmt_s(lat.get('p50', 0.0))} / "
            f"p90 {_fmt_s(lat.get('p90', 0.0))} / "
            f"p99 {_fmt_s(lat.get('p99', 0.0))}, "
            f"max {_fmt_s(lat.get('max', 0.0))}",
            file=out,
        )

    _print_cost_model(rec, out)

    anomalies = check_transform_anomalies(rec)
    for a in anomalies:
        print(f"  !! {a}", file=out)
    if not anomalies:
        print("  anomaly checks: ok", file=out)
    return anomalies


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render spark_rapids_ml_tpu telemetry JSONL"
    )
    ap.add_argument("path", help="telemetry JSONL file (TPU_ML_TELEMETRY_PATH)")
    ap.add_argument(
        "--last", type=int, default=0, metavar="N",
        help="only render the last N fit reports",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit 2 when any anomaly check fires",
    )
    args = ap.parse_args(argv)

    records = []
    try:
        with open(args.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    print(f"# skipping corrupt line", file=sys.stderr)
                    continue
                if rec.get("type") in ("fit_report", "transform_report"):
                    records.append(rec)
    except OSError as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    if not records:
        print(
            f"no fit_report/transform_report records in {args.path}",
            file=sys.stderr,
        )
        return 1
    if args.last > 0:
        records = records[-args.last:]

    n_fit = sum(1 for r in records if r.get("type") == "fit_report")
    print(
        f"{n_fit} fit report(s), {len(records) - n_fit} transform "
        f"report(s) from {args.path}"
    )
    any_anomaly = False
    skipped = 0
    for i, rec in enumerate(records):
        is_transform = rec.get("type") == "transform_report"
        supported = (
            SUPPORTED_TRANSFORM_SCHEMA if is_transform else SUPPORTED_SCHEMA
        )
        schema = rec.get("schema", 1)
        if isinstance(schema, (int, float)) and schema > supported:
            print(
                f"# skipping record {i}: schema {schema} is newer than this "
                f"tool understands (<= {supported}) — upgrade "
                "tools/trace_report.py",
                file=sys.stderr,
            )
            skipped += 1
            continue
        try:
            renderer = render_transform_record if is_transform else render_record
            if renderer(rec):
                any_anomaly = True
        except Exception as e:  # noqa: BLE001 — a bad record must not
            # hide the rest of the file
            print(
                f"# skipping unrenderable record {i} "
                f"({type(e).__name__}: {e})",
                file=sys.stderr,
            )
            skipped += 1
    if skipped:
        print(f"# {skipped} record(s) skipped", file=sys.stderr)
    return 2 if (args.strict and (any_anomaly or skipped)) else 0


if __name__ == "__main__":
    raise SystemExit(main())
