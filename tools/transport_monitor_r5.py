"""Round-long opportunistic on-chip evidence harvester (VERDICT r4 Next #1/#5).

Runs detached for the whole round (``setsid nohup python tools/transport_monitor_r5.py``).
Every PROBE_INTERVAL_S it probes the accelerator transport in a THROWAWAY
subprocess (`devicepolicy.probe_transport_subprocess` — an in-process timed-out
probe poisons the interpreter, see utils/devicepolicy.py:267) and appends one
JSON line to ``TRANSPORT_LOG_r05.jsonl``.  The moment a probe succeeds it runs
the full benchmark N_BENCH_RUNS times back-to-back:

* the first complete rc=0 JSON line becomes ``BENCH_OPPORTUNISTIC_r05.json``
  (primary + spread + derived + extras + accuracy gate — the full contract);
* every run (rc, duration, JSON line or stderr tail) is appended to
  ``BENCH_DRIFT_r05.jsonl`` so the r1→r2 27% drift question (VERDICT r4
  Weak #1 tail) gets an answer from runs executed minutes apart on one
  transport session.

After harvesting it keeps probing on the coarse interval so the committed log
is a round-long health timeline either way: if the chip never heals, the log
itself is the evidence the round asks for.

Safety: bench children get a generous 1 h bound and are stopped with SIGTERM
(60 s grace) — never an immediate SIGKILL — because hard-killing a JAX process
mid-compile is what wedges the tunnel for every later process.
"""

from __future__ import annotations

import datetime
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_ml_tpu.utils import devicepolicy, knobs  # noqa: E402

LOG_PATH = os.path.join(REPO, "TRANSPORT_LOG_r05.jsonl")
# Output names are env-overridable so a SUPPLEMENTAL harvest instance can
# run after the primary landed (e.g. when new bench extras are added
# mid-round and deserve their own on-chip values: point BENCH_OUT at a
# _r05b file and the main-loop "already harvested?" check follows it).
BENCH_OUT = os.path.join(
    REPO,
    os.environ.get(
        knobs.MONITOR_BENCH_OUT.name, "BENCH_OPPORTUNISTIC_r05.json"
    ),
)
DRIFT_OUT = os.path.join(
    REPO, os.environ.get(knobs.MONITOR_DRIFT_OUT.name, "BENCH_DRIFT_r05.jsonl")
)

PROBE_INTERVAL_S = float(os.environ.get(knobs.MONITOR_INTERVAL_S.name, "600"))
PROBE_TIMEOUT_S = float(
    os.environ.get(knobs.MONITOR_PROBE_TIMEOUT_S.name, "120")
)
ROUND_WINDOW_S = float(
    os.environ.get(knobs.MONITOR_WINDOW_S.name, str(11.5 * 3600))
)
N_BENCH_RUNS = int(os.environ.get(knobs.MONITOR_BENCH_RUNS.name, "5"))
BENCH_TIMEOUT_S = float(
    os.environ.get(knobs.MONITOR_BENCH_TIMEOUT_S.name, "3600")
)

START = time.time()


def now_iso() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(timespec="seconds")


def append(path: str, record: dict) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
        os.fsync(f.fileno())


def run_bench(run_idx: int) -> dict:
    """One full bench run; returns the drift-log record."""
    env = dict(os.environ)
    # The monitor just proved the transport healthy; the bench's own
    # preamble only needs a short re-confirmation window.
    env[knobs.BENCH_PROBE_WINDOW_S.name] = "300"
    start = time.time()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=BENCH_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        # SIGTERM the whole process group, generous grace, never jump
        # straight to SIGKILL (a hard kill mid-compile wedges the tunnel).
        os.killpg(proc.pid, signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            out, err = proc.communicate()
    took = time.time() - start
    json_line = None
    for line in (out or "").splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            json_line = line
    record = {
        "t": now_iso(),
        "elapsed_s": round(time.time() - START, 1),
        "run": run_idx,
        "rc": proc.returncode,
        "took_s": round(took, 1),
        "json": json.loads(json_line) if json_line else None,
    }
    if proc.returncode != 0 or json_line is None:
        record["stderr_tail"] = (err or "")[-2000:]
    return record


def harvest() -> bool:
    """Run the bench N times; write BENCH_OPPORTUNISTIC on first full rc=0."""
    wrote_primary = False
    for i in range(1, N_BENCH_RUNS + 1):
        rec = run_bench(i)
        append(DRIFT_OUT, rec)
        print(f"[monitor] bench run {i}/{N_BENCH_RUNS}: rc={rec['rc']} "
              f"took={rec['took_s']}s", flush=True)
        if not wrote_primary and rec["rc"] == 0 and rec["json"] is not None:
            payload = dict(rec["json"])
            # bench.py's snapshot-time fallback only trusts a harvest
            # stamped fresh enough to be from the CURRENT round — a
            # committed harvest from a past round must never be re-emitted
            # as this round's measurement
            payload["harvested_at_unix"] = round(time.time(), 1)
            payload["harvested_at"] = now_iso()
            with open(BENCH_OUT, "w") as f:
                json.dump(payload, f, indent=2)
                f.write("\n")
            wrote_primary = True
        if rec["rc"] != 0 and rec["json"] is None and i >= 2 and not wrote_primary:
            # Transport re-wedged mid-harvest; go back to probing.
            return False
    return wrote_primary


def main() -> None:
    harvested = os.path.exists(BENCH_OUT)
    attempt = 0
    print(f"[monitor] start {now_iso()} interval={PROBE_INTERVAL_S}s "
          f"window={ROUND_WINDOW_S}s harvested={harvested}", flush=True)
    while time.time() - START < ROUND_WINDOW_S:
        attempt += 1
        t0 = time.time()
        ok, detail = devicepolicy.probe_transport_subprocess(timeout=PROBE_TIMEOUT_S)
        # last non-empty line: the child's stderr opens with harmless
        # platform warnings; the diagnostic is at the end
        lines = [l for l in (detail or "").splitlines() if l.strip()]
        append(LOG_PATH, {
            "t": now_iso(),
            "elapsed_s": round(time.time() - START, 1),
            "attempt": attempt,
            "ok": ok,
            "took_s": round(time.time() - t0, 1),
            "detail": (lines[-1] if lines else "")[:200],
        })
        print(f"[monitor] probe {attempt}: ok={ok} ({detail.splitlines()[0][:120] if detail else ''})",
              flush=True)
        if ok and not harvested:
            append(LOG_PATH, {"t": now_iso(), "event": "harvest_start"})
            harvested = harvest()
            append(LOG_PATH, {
                "t": now_iso(),
                "event": "harvest_done",
                "complete": harvested,
            })
        time.sleep(PROBE_INTERVAL_S)
    print(f"[monitor] window exhausted at {now_iso()}", flush=True)


if __name__ == "__main__":
    main()
