"""Deprecated: superseded by ``tools/healthd.py`` (same knobs, same
harvest outputs, plus component health + SLOs + the HTTP exporter)."""

import os
import runpy
import sys

sys.stderr.write(
    "[transport_monitor_r5] deprecated — use tools/healthd.py; forwarding\n"
)
runpy.run_path(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "healthd.py"),
    run_name="__main__",
)
