#!/usr/bin/env python
"""Flight-recorder timeline: export to Chrome trace JSON + overlap/gap report.

Usage::

    python tools/trace_timeline.py /path/to/timeline.jsonl
    python tools/trace_timeline.py timeline.jsonl --out trace.json
    python tools/trace_timeline.py timeline.jsonl --last 1 --strict \\
        --gap-threshold 0.5
    python tools/trace_timeline.py router.jsonl /tmp/r0.sock.trailer \\
        /tmp/r1.sock.trailer --offsets fleet_stats.json --out fleet.json

Input is either the JSONL file written by ``TPU_ML_TIMELINE_PATH``
(``timeline`` records, one per outermost fit or transform — see
``telemetry/export.py``) or an already-exported Chrome trace JSON object.
Transform timelines carry a ``transform_id`` instead of (or alongside) a
``fit_id``; both show in the record header and both have a filter flag.

**Fleet merge.** More than one path merges per-process fragments into
one fleet trace: each extra path may be another timeline JSONL, a
replica telemetry trailer (the ``<socket>.trailer`` JSON the fleet
supervisor flushes at READY and on teardown) or a fleet event dump
(any JSON object with an ``events`` list). ``--offsets`` supplies the
monotonic-clock correction from the READY handshake — either the fleet
router's ``stats()`` JSON (its ``clock_offsets_us`` is keyed by replica
slot and matched against each event's ``replica`` label) or a flat
``{basename-or-pid: offset_us}`` mapping; offsets are *added* to event
timestamps (offset = router clock minus replica clock), so all
processes land on the router's clock. On a single host
CLOCK_MONOTONIC is already system-wide and offsets are ~handshake
latency; cross-host fragments need them. When the package is
importable the merged stream also gets a trace-stitching coverage
line (complete traces / orphan spans).

The default output is a per-fit summary: event counts, per-track (one
track = one ``(pid, partition)``) span busy time and the largest idle gap
between consecutive spans, straggler tracks (busy time well above the
median — the partition everyone else waited on), instant-event tallies
(retries, bisections, checkpoints, faults) and the recorded H2D↔compute
overlap fraction.

``--out trace.json`` merges the selected records into one Chrome
trace-event JSON file that loads directly in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``. Driver and worker
events share a clock (CLOCK_MONOTONIC is system-wide on Linux) so they
interleave correctly; each pid renders as its own named process track.

Exit status: 0 normally; with ``--strict``, 2 when any track's largest
gap exceeds ``--gap-threshold`` seconds (default 1.0) — the CI gate for
"the pipeline stalled". Stdlib-only: renders on hosts without jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.0f}us"


def load_records(path: str) -> list[dict]:
    """Timeline records from JSONL (``type == "timeline"``), a raw Chrome
    trace object, a replica telemetry trailer (``{"pid", "events", ...}``)
    or a fleet event dump — single JSON objects are wrapped as one
    synthetic record. Corrupt JSONL lines are skipped with a note — a torn
    line from a crashed process must not hide the rest of the file."""
    import os

    with open(path, encoding="utf-8") as f:
        text = f.read()
    source = os.path.basename(path)
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict) and "traceEvents" in obj:
            events = [
                e for e in obj.get("traceEvents", []) if e.get("ph") != "M"
            ]
            return [{"type": "timeline", "fit_id": "", "events": events,
                     "source": source}]
        if isinstance(obj, dict) and isinstance(obj.get("events"), list):
            # a replica trailer or fleet event dump: one flat event list,
            # possibly with the writer's pid alongside
            return [{"type": "timeline", "fit_id": "",
                     "events": obj["events"], "pid": obj.get("pid"),
                     "source": source}]
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            print("# skipping corrupt line", file=sys.stderr)
            continue
        if rec.get("type") == "timeline":
            rec.setdefault("source", source)
            records.append(rec)
    return records


def load_offsets(spec: str) -> dict:
    """Clock-offset spec: a JSON file path or inline JSON. Accepts either
    the fleet router's ``stats()`` dump (``clock_offsets_us`` keyed by
    replica slot, applied per event via its ``replica`` label) or a flat
    ``{basename-or-pid: offset_us}`` mapping applied per input file."""
    if not spec:
        return {}
    text = spec
    if not spec.lstrip().startswith("{"):
        with open(spec, encoding="utf-8") as f:
            text = f.read()
    obj = json.loads(text)
    if not isinstance(obj, dict):
        raise ValueError("offsets must be a JSON object")
    if isinstance(obj.get("clock_offsets_us"), dict):
        return {"clock_offsets_us": {
            str(k): int(v) for k, v in obj["clock_offsets_us"].items()
        }}
    return {str(k): int(v) for k, v in obj.items()}


def apply_offsets(records: list[dict], offsets: dict) -> int:
    """Shift event timestamps onto the router's clock; returns how many
    events moved. Slot-keyed offsets (``clock_offsets_us``) match each
    event's ``replica`` arg; flat offsets match a record's source
    basename or writer pid."""
    by_replica = offsets.get("clock_offsets_us")
    shifted = 0
    for rec in records:
        rec_off = 0
        if by_replica is None:
            for key in (rec.get("source"), str(rec.get("pid"))):
                if key is not None and key in offsets:
                    rec_off = offsets[key]
                    break
        for e in rec.get("events", []):
            if not isinstance(e, dict) or "ts" not in e:
                continue
            off = rec_off
            if by_replica is not None:
                replica = (e.get("args") or {}).get("replica")
                off = by_replica.get(str(replica), 0) if replica is not None else 0
            if off:
                e["ts"] = e["ts"] + off
                shifted += 1
    return shifted


def chrome_trace(events: list[dict]) -> dict:
    """Events → Chrome trace-event JSON (mirrors
    ``telemetry.timeline.chrome_trace``, re-implemented here so the tool
    stays importable without the package installed)."""
    pids: list = []
    out = []
    for e in events:
        e = {k: v for k, v in e.items() if k != "seq"}
        pid = e.get("pid", 0)
        if pid not in pids:
            pids.append(pid)
        out.append(e)
    meta = []
    for pid in pids:
        part = next(
            (
                e["args"]["partition"]
                for e in out
                if e.get("pid") == pid and (e.get("args") or {}).get("partition")
            ),
            None,
        )
        name = f"worker partition {part}" if part is not None else f"pid {pid}"
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        )
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def _track_key(e: dict) -> tuple:
    return (e.get("pid", 0), (e.get("args") or {}).get("partition", ""))


def summarize_record(rec: dict, gap_threshold_s: float, out=sys.stdout) -> bool:
    """Print one timeline record's report; returns True when a track's
    largest inter-span gap exceeds the threshold (the --strict trigger)."""
    events = [e for e in rec.get("events", []) if isinstance(e, dict)]
    fit_id = rec.get("fit_id", "")
    transform_id = rec.get("transform_id", "")
    est = rec.get("estimator", "")
    head = " ".join(
        x
        for x in (
            est,
            f"[fit={fit_id}]" if fit_id else "",
            f"[transform={transform_id}]" if transform_id else "",
        )
        if x
    )
    print(f"\n=== timeline {head or '(unlabeled)'}: {len(events)} events ===",
          file=out)
    ov = rec.get("overlap_fraction")
    if ov is not None:
        print(f"H2D<->compute overlap fraction: {ov:.2f}", file=out)

    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]

    tally: dict[str, int] = {}
    for e in instants:
        tally[e.get("name", "?")] = tally.get(e.get("name", "?"), 0) + 1
    if tally:
        print(
            "instants: "
            + ", ".join(f"{n} x{c}" for n, c in sorted(tally.items())),
            file=out,
        )

    exceeded = False
    if spans:
        tracks: dict[tuple, list[dict]] = {}
        for e in spans:
            tracks.setdefault(_track_key(e), []).append(e)
        rows = []
        busies = {}
        for key, evs in tracks.items():
            evs.sort(key=lambda e: e.get("ts", 0))
            busy = sum(e.get("dur", 0) for e in evs) / 1e6
            extent = (
                evs[-1].get("ts", 0) + evs[-1].get("dur", 0) - evs[0].get("ts", 0)
            ) / 1e6
            max_gap = 0.0
            end = None
            for e in evs:
                ts = e.get("ts", 0)
                if end is not None and ts > end:
                    max_gap = max(max_gap, (ts - end) / 1e6)
                end = max(end or 0, ts + e.get("dur", 0))
            busies[key] = busy
            if max_gap > gap_threshold_s:
                exceeded = True
            pid, part = key
            rows.append([
                f"partition {part}" if part else f"driver pid {pid}",
                len(evs),
                _fmt_s(busy),
                _fmt_s(extent),
                _fmt_s(max_gap) + (" !!" if max_gap > gap_threshold_s else ""),
            ])
        rows.sort(key=lambda r: r[0])
        widths = [max(len(str(r[i])) for r in rows + [["track", "spans", "busy", "extent", "max gap"]]) for i in range(5)]
        header = ["track", "spans", "busy", "extent", "max gap"]
        print("  ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip(), file=out)
        print("  ".join("-" * w for w in widths), file=out)
        for r in rows:
            print("  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip(), file=out)

        # straggler: a track busy > 2x the median busy time means the rest
        # of the stage sat waiting on it
        if len(busies) >= 3:
            vals = sorted(busies.values())
            median = vals[len(vals) // 2]
            for key, busy in sorted(busies.items()):
                if median > 0 and busy > 2.0 * median:
                    pid, part = key
                    label = f"partition {part}" if part else f"driver pid {pid}"
                    print(
                        f"  !! straggler: {label} busy {_fmt_s(busy)} > 2x "
                        f"median {_fmt_s(median)}",
                        file=out,
                    )
    else:
        print("(no spans)", file=out)
    return exceeded


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize/export flight-recorder timeline JSONL"
    )
    ap.add_argument(
        "paths", nargs="+", metavar="PATH",
        help="timeline JSONL (TPU_ML_TIMELINE_PATH), Chrome trace JSON, "
             "replica .trailer JSON or fleet event dump; several paths "
             "merge into one fleet trace",
    )
    ap.add_argument(
        "--out", metavar="TRACE_JSON", default="",
        help="write the selected records merged as Chrome trace JSON "
             "(load in Perfetto / chrome://tracing)",
    )
    ap.add_argument(
        "--offsets", metavar="JSON", default="",
        help="per-replica clock offsets (us) from the READY handshake: a "
             "fleet stats() JSON (clock_offsets_us) or a flat "
             "{basename-or-pid: offset_us} mapping, as a file or inline",
    )
    ap.add_argument(
        "--last", type=int, default=0, metavar="N",
        help="only use the last N timeline records",
    )
    ap.add_argument(
        "--fit", default="", metavar="FIT_ID",
        help="only use records with this fit_id",
    )
    ap.add_argument(
        "--transform", default="", metavar="TRANSFORM_ID",
        help="only use records with this transform_id",
    )
    ap.add_argument(
        "--gap-threshold", type=float, default=1.0, metavar="SECONDS",
        help="largest tolerated idle gap within a track (default 1.0)",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit 2 when any track's max gap exceeds --gap-threshold",
    )
    args = ap.parse_args(argv)

    records = []
    for path in args.paths:
        try:
            records.extend(load_records(path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 1
    if args.offsets:
        try:
            offsets = load_offsets(args.offsets)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: bad --offsets: {e}", file=sys.stderr)
            return 1
        shifted = apply_offsets(records, offsets)
        if shifted:
            print(f"clock-corrected {shifted} events onto the router clock")
    if args.fit:
        records = [r for r in records if r.get("fit_id") == args.fit]
    if args.transform:
        records = [
            r for r in records if r.get("transform_id") == args.transform
        ]
    if args.last > 0:
        records = records[-args.last:]
    if not records:
        print(f"no timeline records in {', '.join(args.paths)}", file=sys.stderr)
        return 1

    print(f"{len(records)} timeline record(s) from {', '.join(args.paths)}")
    any_exceeded = False
    for rec in records:
        if summarize_record(rec, args.gap_threshold):
            any_exceeded = True

    merged: list[dict] = []
    for rec in records:
        merged.extend(e for e in rec.get("events", []) if isinstance(e, dict))

    if len(args.paths) > 1 or args.out:
        # fleet view: trace-stitching coverage over the merged stream —
        # best-effort, the tool stays usable without the package installed
        try:
            sys.path.insert(
                0,
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
            from spark_rapids_ml_tpu.telemetry import tracectx

            cov = tracectx.coverage(merged)
            if cov["traces"]:
                print(
                    f"\ntrace stitching: {cov['complete']}/{cov['traces']} "
                    f"complete ({cov['coverage']:.2%}), "
                    f"{cov['orphan_spans']} orphan spans, "
                    f"{cov['multi_root']} multi-root"
                )
        except ImportError:
            pass

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(chrome_trace(merged), f)
        print(f"\nwrote Chrome trace: {args.out} ({len(merged)} events)")

    return 2 if (args.strict and any_exceeded) else 0


if __name__ == "__main__":
    raise SystemExit(main())
