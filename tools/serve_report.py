#!/usr/bin/env python
"""Render serving-runtime evidence: bucket hit rates, queue delay, compiles.

Usage::

    python tools/serve_report.py /path/to/perf.jsonl [--last N] [--strict]

Reads JSONL (or a single JSON document) and renders every record that
carries serving evidence — either a perf-ledger entry whose ``serving``
key holds the blob ``bench.py --smoke`` embeds
(``spark_rapids_ml_tpu.serving.server.serve_summary``), or a bare
``serve_summary`` record written directly. For each:

- the per-bucket hit table — which rungs of the serve ladder actually
  absorbed traffic, and each rung's share. A healthy warm path
  concentrates hits on a few small buckets; a flat spread means request
  sizes straddle rungs and the ladder constants
  (``TPU_ML_SERVE_MIN_BUCKET`` / ``TPU_ML_SERVE_MAX_BATCH_ROWS``) are
  mis-sized for the workload.
- micro-batcher queue-delay percentiles (p50/p90/p99/max) against the
  configured coalescing window — p99 well above
  ``TPU_ML_SERVE_MAX_DELAY_US`` means the batcher worker, not the window,
  is the bottleneck.
- the transport mix (http/uds/inproc x json/binary) — how much traffic
  still pays HTTP+JSON framing vs the fast paths — and the per-lane
  latency breakdown (p50/p99 per transport/wire pair), which is where a
  regression in one lane shows up before it moves the blended tail.
- fleet evidence when the record carries it: replica count, routing
  hit-rate (consistent-hash affinity vs spill/fallback), drain events and
  rolling restarts, hedged dispatches and which side won.
- the adaptive-window trace (``serve.window_effective_seconds``
  percentiles vs the configured ceiling) and continuous-batching riders
  (``serve.joined_in_flight``).
- HBM fleet paging: ``serve.page_in``/``serve.page_out`` counts and the
  page-in rate per request.
- request latency percentiles and the batching ratio
  (requests per device dispatch).
- anomaly checks:

  - ``cold-start-compile-in-steady-state`` — nonzero
    ``serve.cold_compiles``: a request landed on a bucket the registry
    never AOT-compiled and paid a synchronous XLA compile on the serve
    path. Registration is supposed to make the compiled-signature set
    total (serving.registry); a cold compile in steady state means a
    model was registered with a truncated ``bucket_list`` or the ladder
    knobs changed after registration.
  - ``serve-errors`` — nonzero ``serve.errors`` booked in the window.
  - ``queue-delay-above-window`` — queue-delay p99 exceeded 5x the
    coalescing window (when the record carries the window).
  - ``page-thrash`` — the HBM fleet paged weights in on a quarter or
    more of the window's requests: the resident working set does not fit
    ``TPU_ML_SERVE_HBM_BUDGET_BYTES`` and models are ping-ponging
    between host and device on the hot path.
  - ``window-never-adapts`` — adaptive windowing is on and the window
    saw sustained dispatch traffic, yet its p50 never left the
    ``TPU_ML_SERVE_MAX_DELAY_US`` ceiling: the device-time feedback is
    not reaching the batcher (or every dispatch is slower than the
    ceiling, which is its own problem).
  - ``binary-wire-slower-than-json`` — a transport's binary/fast lane
    posted a higher p99 than its JSON lane. The binary lanes exist to
    delete codec work; when they lose to JSON the fast path has picked
    up a regression (pool contention, framing bug) that the blended
    latency histogram would hide.
  - ``torn-swap`` — the window's ``serve.swaps`` count disagrees with
    its ``serve.swap_blackout_seconds`` sample count. Every completed
    hot-swap publish records exactly one blackout sample from inside
    the atomic section; a mismatch means a swap died mid-publish (a
    torn serving slot — the one state the refresh subsystem promises
    can never exist) or the swap telemetry is lying.
  - ``rollback-exceeds-swaps`` — more rollbacks than swaps in one
    window: a prior was restored that this window never displaced
    (crash-looping probation, duplicated rollback calls).
  - ``refresh-failed-requests`` / ``refresh-post-swap-compiles`` — the
    refresh bench stage saw a client-visible failure or a backend
    compile after the publish; both are hard swap-contract violations.
  - ``orphan-spans`` — the record's ``trace_coverage`` blob (bench's
    stitched-trace audit over the measured window, serving or fleet
    stage) reports orphan spans or <99% of sampled requests stitching
    into a complete trace. An orphan span names a parent that no merged
    event stream contains: a replica fragment was never harvested (lost
    trailer), a hop dropped the trace context on a wire, or the
    flight-recorder ring evicted a parent mid-window (lower
    ``TPU_ML_TRACE_SAMPLE`` or raise ``TPU_ML_TIMELINE_EVENTS``).

The record's tracing evidence also renders: traces minted in the
window, stitching coverage, and the slowest latency exemplars (trace
ids — pull any of them up with ``/traces/<id>`` or decompose the tail
with ``tools/tail_report.py``).

Exit status: 0 normally; with ``--strict``, 2 when any anomaly fired OR
any record had to be skipped (CI gate). Stdlib-only — renders on hosts
without jax installed.
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.0f}us"


def _table(rows: list[list[str]], header: list[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(header), sep] + [line(r) for r in rows])


def extract_summary(rec: dict) -> dict | None:
    """Pull the serve_summary blob out of a record, whatever wrapper it
    arrived in: a perf-ledger entry (``serving`` key), or the bare blob."""
    if isinstance(rec.get("serving"), dict):
        return rec["serving"]
    if rec.get("type") == "serve_summary" or "bucket_hits" in rec:
        return rec
    return None


def check_anomalies(summary: dict, wrapper: dict) -> list[str]:
    out: list[str] = []
    cold = summary.get("cold_compiles", 0) or 0
    recompiles = _wrapper_metric(wrapper, "serve_recompiles_after_warmup")
    if cold or (recompiles or 0) > 0:
        n = cold or recompiles
        out.append(
            f"cold-start-compile-in-steady-state: {n:g} serve dispatch(es) "
            "paid a synchronous XLA compile — a request landed on a bucket "
            "the registry never AOT-compiled at registration (truncated "
            "bucket_list, or the ladder knobs TPU_ML_SERVE_MIN_BUCKET/"
            "TPU_ML_SERVE_MAX_BATCH_ROWS changed after registration)"
        )
    errors = summary.get("errors", 0) or 0
    if errors:
        out.append(
            f"serve-errors: {errors:g} request(s) failed in the window — "
            "see the serve.errors label sets on /metrics for the model "
            "and status code"
        )
    qd = summary.get("queue_delay") or {}
    window = summary.get("coalesce_window_s")
    if window and qd.get("p99", 0) > 5.0 * window:
        out.append(
            f"queue-delay-above-window: batcher queue-delay p99 "
            f"{_fmt_s(qd['p99'])} is >5x the {_fmt_s(window)} coalescing "
            "window — the batcher worker (or the device dispatch it wraps) "
            "is the bottleneck, not the window; check device contention "
            "and TPU_ML_SERVE_MAX_BATCH_ROWS"
        )
    requests = summary.get("requests", 0) or 0
    page_in = summary.get("page_in", 0) or 0
    if page_in >= 4 and requests and page_in >= 0.25 * requests:
        out.append(
            f"page-thrash: {page_in:g} HBM page-in(s) across {requests:g} "
            "request(s) — the resident model working set does not fit the "
            "fleet budget and weights are ping-ponging between host and "
            "device on the hot path; raise TPU_ML_SERVE_HBM_BUDGET_BYTES "
            "or shrink the fleet"
        )
    by_lane = summary.get("latency_by_transport") or {}
    for lane, hist in sorted(by_lane.items()):
        transport, _, lane_wire = lane.partition("/")
        if lane_wire not in ("fast", "binary") or hist.get("count", 0) < 8:
            continue
        json_hist = by_lane.get(f"{transport}/json") or {}
        if json_hist.get("count", 0) < 8:
            continue
        if hist.get("p99", 0.0) > json_hist.get("p99", 0.0):
            out.append(
                f"binary-wire-slower-than-json: {lane} p99 "
                f"{_fmt_s(hist['p99'])} exceeds {transport}/json p99 "
                f"{_fmt_s(json_hist['p99'])} — the codec-free lane lost "
                "to the lane it exists to beat; look for response-pool "
                "contention or framing overhead on the fast path"
            )
    win_hist = summary.get("window_effective") or {}
    if (
        summary.get("adaptive_window")
        and window
        and win_hist.get("count", 0) >= 8
        and win_hist.get("p50", 0) >= 0.95 * window
    ):
        out.append(
            f"window-never-adapts: adaptive windowing is on but the "
            f"effective-window p50 ({_fmt_s(win_hist['p50'])}) sat at the "
            f"{_fmt_s(window)} TPU_ML_SERVE_MAX_DELAY_US ceiling across "
            f"{win_hist['count']:g} dispatch(es) — the device-time "
            "feedback never shrank the window (or every dispatch outran "
            "the ceiling)"
        )
    out.extend(
        check_trace_anomalies(summary.get("trace_coverage"), "serving")
    )
    return out


def check_trace_anomalies(cov: dict | None, where: str) -> list[str]:
    """One ``trace_coverage`` blob (telemetry.tracectx.coverage) against
    the stitching contract: zero orphan spans, >=99% of sampled requests
    forming one complete trace."""
    if not isinstance(cov, dict) or not cov.get("traces"):
        return []
    out: list[str] = []
    orphans = cov.get("orphan_spans", 0) or 0
    coverage = cov.get("coverage", 1.0)
    if orphans or coverage < 0.99:
        out.append(
            f"orphan-spans: {where} window stitched "
            f"{cov.get('complete', 0):g}/{cov['traces']:g} trace(s) "
            f"complete ({coverage:.1%}) with {orphans:g} orphan span(s) — "
            "a span names a parent no merged stream contains: a replica "
            "fragment was never harvested, a hop dropped the trace "
            "context, or the flight-recorder ring evicted a parent "
            "mid-window (lower TPU_ML_TRACE_SAMPLE or raise "
            "TPU_ML_TIMELINE_EVENTS)"
        )
    return out


def check_refresh_anomalies(refresh: dict) -> list[str]:
    """Consistency checks on one window's swap/rollback counters (the
    ``refresh`` block of ``serve_summary``)."""
    out: list[str] = []
    swaps = refresh.get("swaps", 0) or 0
    blackout = (refresh.get("swap_blackout") or {}).get("count", 0) or 0
    if swaps != blackout:
        out.append(
            f"torn-swap: {swaps:g} swap(s) published but {blackout:g} "
            "blackout sample(s) booked — every completed publish records "
            "exactly one serve.swap_blackout_seconds sample from inside "
            "the atomic section; a mismatch means a swap died mid-publish "
            "(a torn serving slot) or the swap telemetry is lying"
        )
    rollbacks = refresh.get("rollbacks", 0) or 0
    if rollbacks > swaps:
        out.append(
            f"rollback-exceeds-swaps: {rollbacks:g} rollback(s) vs "
            f"{swaps:g} swap(s) in the same window — a prior was restored "
            "that this window never displaced; look for a crash-looping "
            "probation or duplicated rollback calls"
        )
    return out


def _wrapper_metric(wrapper: dict, name: str) -> float | None:
    m = (wrapper.get("metrics") or {}).get(name)
    if isinstance(m, dict):
        return m.get("value")
    return m if isinstance(m, (int, float)) else None


def _render_refresh(refresh: dict, out) -> None:
    """Print one window's model-refresh counters (swap/rollback plane)."""
    swaps = refresh.get("swaps", 0) or 0
    refused = refresh.get("swap_refused", 0) or 0
    rollbacks = refresh.get("rollbacks", 0) or 0
    folds = refresh.get("folds", 0) or 0
    checkpoints = refresh.get("checkpoints", 0) or 0
    if not (swaps or refused or rollbacks or folds or checkpoints):
        return
    line = (
        f"model refresh: {swaps:g} swap(s), {refused:g} refused, "
        f"{rollbacks:g} rollback(s)"
    )
    blackout = refresh.get("swap_blackout") or {}
    if blackout.get("count"):
        line += (
            f", blackout p99 {_fmt_s(blackout.get('p99', 0.0))} / "
            f"max {_fmt_s(blackout.get('max', 0.0))}"
        )
    print(line, file=out)
    if folds or checkpoints:
        line = (
            f"  delta plane: {folds:g} fold(s) over "
            f"{refresh.get('rows', 0) or 0:g} row(s), "
            f"{refresh.get('finalizes', 0) or 0:g} finalize(s), "
            f"{checkpoints:g} checkpoint(s), "
            f"{refresh.get('resumes', 0) or 0:g} resume(s)"
        )
        lag = refresh.get("lag_seconds")
        if lag is not None:
            line += f", lag {_fmt_s(lag)}"
        print(line, file=out)
    versions = refresh.get("versions") or {}
    if versions:
        print(
            "  serving versions: " + ", ".join(
                f"{m} v{v:g}" for m, v in sorted(versions.items())
            ),
            file=out,
        )


def _render_refresh_stage(stage: dict, out) -> list[str]:
    """Render the bench ``refresh`` stage evidence (the hot-swap-under-load
    proof) and return its anomaly list."""
    anomalies: list[str] = []
    probation = stage.get("probation")
    if isinstance(probation, dict):
        probation = probation.get("status", "?")
    print(
        f"refresh stage: model {stage.get('model', '?')} swapped to "
        f"v{stage.get('swap_version', 0):g} under load — blackout "
        f"{stage.get('swap_blackout_ms', 0.0):g}ms, refresh lag "
        f"{stage.get('refresh_lag_s', 0.0):g}s, probation {probation}",
        file=out,
    )
    requests = stage.get("requests_during_swap", 0) or 0
    failed = stage.get("failed_requests", 0) or 0
    recompiles = stage.get("post_swap_recompiles", 0) or 0
    print(
        f"  swap-window traffic: {requests:g} request(s), {failed:g} "
        f"failed, {recompiles:g} post-swap compile(s)",
        file=out,
    )
    if failed:
        anomalies.append(
            f"refresh-failed-requests: {failed:g} request(s) failed while "
            "a hot-swap was in flight — the atomic publish leaked onto the "
            "request path"
        )
    if recompiles:
        anomalies.append(
            f"refresh-post-swap-compiles: {recompiles:g} backend compile(s) "
            "after the publish — the candidate was not AOT-compiled over "
            "the live bucket ladder before the swap"
        )
    anomalies.extend(check_refresh_anomalies(stage.get("refresh") or {}))
    return anomalies


def render_record(rec: dict, out=sys.stdout) -> list[str] | None:
    """Render one record's serving evidence; returns its anomaly list, or
    None when the record carries no serving evidence."""
    summary = extract_summary(rec)
    if summary is None:
        return None
    tag = rec.get("bench") or rec.get("name") or "serving"
    when = rec.get("timestamp") or rec.get("time") or ""
    head = f"\n=== {tag} serving window"
    if when:
        head += f" @ {when}"
    print(head + " ===", file=out)

    requests = summary.get("requests", 0) or 0
    batches = summary.get("batches", 0) or 0
    line = (
        f"traffic: {requests:g} request(s), {summary.get('rows', 0):g} "
        f"row(s), {batches:g} device dispatch(es)"
    )
    if batches:
        line += f" ({requests / batches:.2f} requests/dispatch)"
    joined = summary.get("joined_in_flight", 0) or 0
    if joined:
        line += f", {joined:g} rider(s) joined in-flight"
    shed = summary.get("shed", 0) or 0
    if shed:
        line += f", {shed:g} shed"
    print(line, file=out)

    mix = summary.get("transport_mix") or {}
    total_mix = sum(mix.values())
    if mix:
        rows = [
            [t, f"{v:g}", f"{v / total_mix:.1%}" if total_mix else "-"]
            for t, v in sorted(mix.items())
        ]
        print(_table(rows, ["transport/wire", "requests", "share"]), file=out)

    by_lane = summary.get("latency_by_transport") or {}
    lane_rows = [
        [
            lane, f"{h.get('count', 0):g}",
            _fmt_s(h.get("p50", 0.0)), _fmt_s(h.get("p99", 0.0)),
            _fmt_s(h.get("max", 0.0)),
        ]
        for lane, h in sorted(by_lane.items())
        if h.get("count")
    ]
    if lane_rows:
        print(
            _table(lane_rows, ["lane", "requests", "p50", "p99", "max"]),
            file=out,
        )

    fleet = summary.get("fleet") or {}
    if fleet.get("replicas"):
        hits = fleet.get("route_hits", 0) or 0
        misses = fleet.get("route_misses", 0) or 0
        routed = hits + misses
        line = f"fleet: {fleet['replicas']:g} replica(s)"
        if routed:
            line += (
                f", routing hit-rate {hits / routed:.1%} "
                f"({hits:g} home / {misses:g} spill-or-fallback)"
            )
        drains = fleet.get("drain_events", 0) or 0
        restarts = fleet.get("replica_restarts", 0) or 0
        if drains or restarts:
            line += f", {drains:g} drain(s), {restarts:g} rolling restart(s)"
        print(line, file=out)

    refresh = summary.get("refresh") or {}
    _render_refresh(refresh, out)

    hedges = summary.get("hedges", 0) or 0
    if hedges:
        wins = summary.get("hedge_wins") or {}
        line = f"hedged dispatches: {hedges:g} issued"
        if wins:
            line += " (" + ", ".join(
                f"{k} won {v:g}" for k, v in sorted(wins.items())
            ) + ")"
        print(line, file=out)

    page_in = summary.get("page_in", 0) or 0
    page_out = summary.get("page_out", 0) or 0
    if page_in or page_out:
        line = (
            f"hbm paging: {page_in:g} page-in(s), {page_out:g} page-out(s)"
        )
        if requests:
            line += f" ({page_in / requests:.3f} page-ins/request)"
        hbm_bytes = summary.get("hbm_bytes", 0) or 0
        if hbm_bytes:
            line += f", {hbm_bytes:g} resident byte(s)"
        print(line, file=out)

    win = summary.get("window_effective") or {}
    if win.get("count"):
        line = (
            f"adaptive window: p50 {_fmt_s(win.get('p50', 0.0))} / "
            f"p90 {_fmt_s(win.get('p90', 0.0))} / "
            f"p99 {_fmt_s(win.get('p99', 0.0))} across "
            f"{win['count']:g} dispatch(es)"
        )
        ceiling = summary.get("coalesce_window_s")
        if ceiling:
            line += f" (ceiling {_fmt_s(ceiling)})"
        print(line, file=out)

    hits = summary.get("bucket_hits") or {}
    total_hits = sum(hits.values())
    if hits:
        def _bkey(kv):
            return (0, int(kv[0])) if str(kv[0]).isdigit() else (1, 0)
        rows = [
            [b, f"{v:g}", f"{v / total_hits:.1%}" if total_hits else "-"]
            for b, v in sorted(hits.items(), key=_bkey)
        ]
        print(_table(rows, ["bucket", "hits", "share"]), file=out)

    lat = summary.get("latency") or {}
    if lat.get("count"):
        print(
            f"request latency: {lat['count']:g} sample(s), "
            f"p50 {_fmt_s(lat.get('p50', 0.0))} / "
            f"p90 {_fmt_s(lat.get('p90', 0.0))} / "
            f"p99 {_fmt_s(lat.get('p99', 0.0))}, "
            f"max {_fmt_s(lat.get('max', 0.0))}",
            file=out,
        )
    qd = summary.get("queue_delay") or {}
    if qd.get("count"):
        line = (
            f"batcher queue delay: p50 {_fmt_s(qd.get('p50', 0.0))} / "
            f"p90 {_fmt_s(qd.get('p90', 0.0))} / "
            f"p99 {_fmt_s(qd.get('p99', 0.0))}, "
            f"max {_fmt_s(qd.get('max', 0.0))}"
        )
        window = summary.get("coalesce_window_s")
        if window:
            line += f" (window {_fmt_s(window)})"
        print(line, file=out)
    qd_us = summary.get("queue_delay_us") or {}
    if qd_us.get("count"):
        # the µs-resolution series (values are microseconds, not seconds)
        print(
            f"batcher queue delay (us series): "
            f"p50 {qd_us.get('p50', 0.0):.1f}us / "
            f"p90 {qd_us.get('p90', 0.0):.1f}us / "
            f"p99 {qd_us.get('p99', 0.0):.1f}us, "
            f"max {qd_us.get('max', 0.0):.1f}us",
            file=out,
        )
    comp_line = (
        f"compiles: {summary.get('aot_compiles', 0):g} AOT at "
        f"registration, {summary.get('cold_compiles', 0):g} cold in "
        "steady state"
    )
    print(comp_line, file=out)

    trace = summary.get("trace") or {}
    cov = summary.get("trace_coverage") or {}
    if trace.get("minted") or cov.get("traces"):
        line = f"tracing: {trace.get('minted', 0):g} trace(s) minted"
        if cov.get("traces"):
            line += (
                f", {cov.get('complete', 0):g}/{cov['traces']:g} stitched "
                f"complete ({cov.get('coverage', 1.0):.1%}), "
                f"{cov.get('orphan_spans', 0):g} orphan span(s)"
            )
        print(line, file=out)
        exemplars = trace.get("latency_exemplars") or []
        if exemplars:
            print(
                "  slowest exemplars: " + ", ".join(
                    f"{tid} ({_fmt_s(v)})" for v, tid in exemplars[:4]
                ),
                file=out,
            )

    anomalies = check_anomalies(summary, rec)
    anomalies.extend(check_refresh_anomalies(refresh))
    stage = rec.get("refresh")
    if isinstance(stage, dict) and "swap_blackout_ms" in stage:
        anomalies.extend(_render_refresh_stage(stage, out))
    fleet_stage = rec.get("fleet")
    if isinstance(fleet_stage, dict):
        fleet_cov = fleet_stage.get("trace_coverage") or {}
        if fleet_cov.get("traces"):
            print(
                f"fleet tracing: {fleet_cov.get('complete', 0):g}/"
                f"{fleet_cov['traces']:g} cross-process trace(s) stitched "
                f"complete ({fleet_cov.get('coverage', 1.0):.1%}), "
                f"{fleet_cov.get('orphan_spans', 0):g} orphan span(s)",
                file=out,
            )
        anomalies.extend(check_trace_anomalies(fleet_cov, "fleet"))
    for a in anomalies:
        print(f"  !! {a}", file=out)
    if not anomalies:
        print("  anomaly checks: ok", file=out)
    return anomalies


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render spark_rapids_ml_tpu serving evidence"
    )
    ap.add_argument(
        "path",
        help="perf-ledger JSONL (bench.py --smoke) or serve_summary JSON",
    )
    ap.add_argument(
        "--last", type=int, default=0, metavar="N",
        help="only render the last N serving records",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit 2 when any anomaly check fires or a record is skipped",
    )
    args = ap.parse_args(argv)

    records = []
    skipped = 0
    try:
        with open(args.path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            print("# skipping corrupt line", file=sys.stderr)
            skipped += 1
            continue
        if isinstance(rec, dict) and extract_summary(rec) is not None:
            records.append(rec)
    if not records:
        print(f"no serving evidence in {args.path}", file=sys.stderr)
        return 1
    if args.last > 0:
        records = records[-args.last:]

    print(f"{len(records)} serving record(s) from {args.path}")
    any_anomaly = False
    for i, rec in enumerate(records):
        try:
            anomalies = render_record(rec)
        except Exception as e:  # noqa: BLE001 — a bad record must not
            # hide the rest of the file
            print(
                f"# skipping unrenderable record {i} "
                f"({type(e).__name__}: {e})",
                file=sys.stderr,
            )
            skipped += 1
            continue
        if anomalies:
            any_anomaly = True
    if skipped:
        print(f"# {skipped} record(s) skipped", file=sys.stderr)
    return 2 if (args.strict and (any_anomaly or skipped)) else 0


if __name__ == "__main__":
    raise SystemExit(main())
