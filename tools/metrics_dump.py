#!/usr/bin/env python
"""One-shot Prometheus text exposition from per-fit telemetry JSONL.

Usage::

    python tools/metrics_dump.py /path/to/telemetry.jsonl [--last N]

There is no long-lived server process to scrape — fits run inside batch
jobs — so this re-aggregates the ``fit_report`` and ``transform_report``
records of a JSONL sink (``TPU_ML_TELEMETRY_PATH``) into a fresh
:class:`~spark_rapids_ml_tpu.telemetry.registry.MetricsRegistry` and
prints :meth:`to_prometheus` text, suitable for a node-exporter textfile
collector or a pushgateway::

    python tools/metrics_dump.py telemetry.jsonl \\
        > /var/lib/node_exporter/textfile/tpu_ml.prom

Counter keys are parsed back from their rendered ``name{k=v,...}`` form
and re-emitted through their *declared kind*: every family listed in
``telemetry.names.HISTOGRAMS`` records a histogram sample, every family
in ``names.GAUGES`` sets a gauge, everything else increments a counter —
so ``serve.queue_delay_us`` renders with ``# TYPE ... histogram``, not as
a counter that a dashboard would rate(). The names-family meta-check in
tests/test_timeline.py asserts the TYPE line matches the declared kind
for every family, so a new family added to names.py without a kind
declaration (or a dump renderer) fails CI.

The report's dedicated fields re-emit as counters (``rows_ingested``,
``h2d_bytes``, ``collective.count``, the full ``compile.*`` family from
``telemetry.compilemon`` — count / cache hits+misses / cache time saved —
and the cost model's ``costmodel.flops`` / ``costmodel.bytes``; the
autotuner decision trail re-emits as ``autotune.decisions`` labeled by
kernel and source) and
per-record scalars (``fit.wall_seconds``, ``transform.wall_seconds``,
``compile.seconds`` / ``trace_seconds`` / ``lower_seconds``) as
one-sample-per-record histograms, all labeled by estimator/transformer.

``perf_ledger`` records (bench's JSONL) render too: their serving /
refresh / fleet evidence blobs re-emit the ``serve.*`` and ``refresh.*``
families — request/error/transport counters, the latency and
µs-queue-delay digests as representative histogram samples (p50/p99 per
window, the transform-latency idiom), swap/rollback/fold counters and
the version/replica gauges — so a scrape of the ledger shows the serving
plane, not just fits. Importing the registry does not pull in jax, so
this runs on telemetry-collection hosts without it.
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable straight from a checkout: the registry import needs the repo
# root, which `python tools/metrics_dump.py` does not put on sys.path
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def parse_rendered_key(key: str) -> tuple[str, dict[str, str]]:
    """Invert ``telemetry.registry.render_key``: ``name{k=v,...}`` →
    ``(name, labels)``. Values never contain ``,`` or ``=`` (label values
    are estimator/site/phase identifiers)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


def _record_by_kind(reg, name: str, value: float, **labels) -> None:
    """Route one sample through the family's declared kind
    (``telemetry.names`` HISTOGRAMS / GAUGES; counters otherwise), so the
    re-aggregated registry renders the same Prometheus TYPE as the live
    one."""
    from spark_rapids_ml_tpu.telemetry import names

    if name in names.HISTOGRAMS or name.startswith(
        "transform.partition_seconds_"
    ):
        reg.histogram_record(name, value, **labels)
    elif name in names.GAUGES:
        reg.gauge_set(name, value, **labels)
    else:
        reg.counter_inc(name, value, **labels)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Dump telemetry JSONL as Prometheus exposition text"
    )
    ap.add_argument("path", help="telemetry JSONL file (TPU_ML_TELEMETRY_PATH)")
    ap.add_argument(
        "--last", type=int, default=0, metavar="N",
        help="only aggregate the last N fit reports",
    )
    args = ap.parse_args(argv)

    from spark_rapids_ml_tpu.telemetry.export import read_jsonl
    from spark_rapids_ml_tpu.telemetry.registry import MetricsRegistry

    try:
        records = [
            r for r in read_jsonl(args.path)
            if r.get("type")
            in ("fit_report", "transform_report", "perf_ledger")
        ]
    except OSError as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    if not records:
        print(
            f"no fit_report/transform_report/perf_ledger records in "
            f"{args.path}",
            file=sys.stderr,
        )
        return 1
    if args.last > 0:
        records = records[-args.last:]

    reg = MetricsRegistry()
    for rec in records:
        if rec.get("type") == "transform_report":
            _aggregate_transform(reg, rec)
            continue
        if rec.get("type") == "perf_ledger":
            _aggregate_serving(reg, rec)
            continue
        est = rec.get("estimator", "")
        for key, v in (rec.get("counters") or {}).items():
            name, labels = parse_rendered_key(key)
            _record_by_kind(reg, name, v, **labels)
        for name, v in (
            ("rows_ingested", rec.get("rows_ingested", 0)),
            ("bytes_ingested", rec.get("bytes_ingested", 0)),
            ("h2d_bytes", rec.get("h2d_bytes", 0)),
        ):
            if v:
                reg.counter_inc(name, v, estimator=est)
        coll = rec.get("collectives") or {}
        for name, k in (
            ("collective.count", "count"),
            ("collective.bytes", "bytes"),
            ("collective.tree_combines", "tree_combines"),
        ):
            if coll.get(k):
                reg.counter_inc(name, coll[k], estimator=est)
        comp = rec.get("compile") or {}
        for name, k in (
            ("compile.count", "count"),
            ("compile.cache_hits", "cache_hits"),
            ("compile.cache_misses", "cache_misses"),
            ("compile.cache_time_saved_s", "cache_time_saved_s"),
        ):
            if comp.get(k):
                reg.counter_inc(name, comp[k], estimator=est)
        reg.counter_inc("fits", 1, estimator=est)
        reg.histogram_record(
            "fit.wall_seconds", rec.get("wall_seconds", 0.0), estimator=est
        )
        for name, k in (
            ("compile.seconds", "seconds"),
            ("compile.trace_seconds", "trace_seconds"),
            ("compile.lower_seconds", "lower_seconds"),
        ):
            if comp.get(k):
                reg.histogram_record(name, comp[k], estimator=est)
        _aggregate_cost_model(reg, rec, estimator=est)
        _aggregate_tuning(reg, rec, estimator=est)
        ov = rec.get("overlap_fraction")
        if ov is not None:
            reg.histogram_record("stream.overlap_fraction", ov, estimator=est)

    sys.stdout.write(reg.to_prometheus())
    return 0


def _aggregate_tuning(reg, rec: dict, **labels) -> None:
    """Re-emit the autotuner decision trail (fit_report schema >= 4
    ``tuning`` field) as an ``autotune.decisions`` counter labeled by
    kernel and source. The raw window counters already pass the unlabeled
    ``autotune.cache_hits``/``cache_misses``/``trials`` family through the
    generic loop above; this adds the per-kernel attribution those lack."""
    for d in (rec.get("tuning") or {}).get("decisions") or []:
        reg.counter_inc(
            "autotune.decisions", 1,
            kernel=d.get("kernel", ""), source=d.get("source", ""),
            **labels,
        )


def _aggregate_cost_model(reg, rec: dict, **labels) -> None:
    """Re-emit a record's analytical cost-model totals as counters."""
    cm = rec.get("cost_model") or {}
    if cm.get("analytical_flops"):
        reg.counter_inc("costmodel.flops", cm["analytical_flops"], **labels)
    if cm.get("analytical_bytes"):
        reg.counter_inc("costmodel.bytes", cm["analytical_bytes"], **labels)
    util = cm.get("roofline_utilization")
    if util is not None:
        reg.histogram_record("costmodel.roofline_utilization", util, **labels)


def _aggregate_serving(reg, rec: dict) -> None:
    """Fold one perf_ledger record's serving/refresh/fleet evidence into
    the registry: the ``serve.*`` / ``refresh.*`` families a scrape of the
    bench ledger should show. Histogram digests re-emit as representative
    samples (p50/p99 of the measured window — the transform-latency
    idiom), counters and gauges verbatim."""

    def digest(name: str, d: dict | None, **labels) -> None:
        for q in ("p50", "p99"):
            if d and d.get("count") and d.get(q) is not None:
                reg.histogram_record(name, d[q], **labels)

    serving = rec.get("serving")
    if isinstance(serving, dict):
        for name, key in (
            ("serve.requests", "requests"),
            ("serve.errors", "errors"),
            ("serve.rows", "rows"),
            ("serve.batches", "batches"),
            ("serve.aot_compiles", "aot_compiles"),
            ("serve.cold_compiles", "cold_compiles"),
            ("serve.joined_in_flight", "joined_in_flight"),
            ("serve.shed", "shed"),
            ("serve.page_in", "page_in"),
            ("serve.page_out", "page_out"),
            ("serve.hedges", "hedges"),
        ):
            if serving.get(key):
                reg.counter_inc(name, serving[key])
        if serving.get("hbm_bytes"):
            reg.gauge_set("serve.hbm_bytes", serving["hbm_bytes"])
        for lane, count in (serving.get("transport_mix") or {}).items():
            transport, _, wire = str(lane).partition("/")
            reg.counter_inc(
                "serve.transport", count, transport=transport, wire=wire
            )
        for bucket, hits in (serving.get("bucket_hits") or {}).items():
            reg.counter_inc("serve.bucket_hits", hits, bucket=str(bucket))
        for op in ("encode", "decode"):
            if (serving.get("json_codec") or {}).get(op):
                reg.counter_inc(
                    "serve.json_codec", serving["json_codec"][op], op=op
                )
        if (serving.get("trace") or {}).get("minted"):
            reg.counter_inc("serve.traces", serving["trace"]["minted"])
        digest("serve.latency", serving.get("latency"))
        digest("serve.queue_delay_seconds", serving.get("queue_delay"))
        digest("serve.queue_delay_us", serving.get("queue_delay_us"))
        digest(
            "serve.window_effective_seconds",
            serving.get("window_effective"),
        )
        digest("serve.batch_rows", serving.get("batch_rows"))

    # the serving blob's nested refresh view and the dedicated refresh
    # evidence share a schema; render whichever the record carries
    refresh = rec.get("refresh")
    refresh_view = (
        (refresh.get("refresh") if isinstance(refresh, dict) else None)
        or (serving.get("refresh") if isinstance(serving, dict) else None)
    )
    if isinstance(refresh_view, dict):
        for name, key in (
            ("serve.swaps", "swaps"),
            ("serve.swap_refused", "swap_refused"),
            ("serve.rollback", "rollbacks"),
            ("refresh.folds", "folds"),
            ("refresh.rows", "rows"),
            ("refresh.finalizes", "finalizes"),
            ("refresh.checkpoints", "checkpoints"),
            ("refresh.resumes", "resumes"),
        ):
            if refresh_view.get(key):
                reg.counter_inc(name, refresh_view[key])
        digest(
            "serve.swap_blackout_seconds", refresh_view.get("swap_blackout")
        )
        if refresh_view.get("lag_seconds"):
            reg.gauge_set("refresh.lag_seconds", refresh_view["lag_seconds"])
        for model, version in (refresh_view.get("versions") or {}).items():
            reg.gauge_set("serve.model_version", version, model=str(model))

    fleet = rec.get("fleet")
    fleet_view = (
        fleet
        if isinstance(fleet, dict)
        else (serving.get("fleet") if isinstance(serving, dict) else None)
    )
    if isinstance(fleet_view, dict):
        if fleet_view.get("replicas"):
            reg.gauge_set("serve.fleet_replicas", fleet_view["replicas"])
        # two shapes: the serving blob's flat fleet sub-dict vs the bench
        # fleet evidence (routing + rolling_restart sub-dicts)
        routing = fleet_view.get("routing") or {}
        restart = fleet_view.get("rolling_restart") or {}
        for name, value in (
            ("serve.route_hits",
             routing.get("hits", fleet_view.get("route_hits"))),
            ("serve.route_misses",
             routing.get("misses", fleet_view.get("route_misses"))),
            ("serve.drain_events",
             restart.get("drain_events", fleet_view.get("drain_events"))),
            ("serve.replica_restarts",
             restart.get(
                 "replica_restarts", fleet_view.get("replica_restarts")
             )),
        ):
            if value:
                reg.counter_inc(name, value)


def _aggregate_transform(reg, rec: dict) -> None:
    """Fold one transform_report into the registry (transformer-labeled)."""
    tr = rec.get("transformer", "")
    for key, v in (rec.get("counters") or {}).items():
        name, labels = parse_rendered_key(key)
        _record_by_kind(reg, name, v, **labels)
    for name, v in (
        ("transform.rows", rec.get("rows", 0)),
        ("transform.bytes", rec.get("bytes", 0)),
        ("transform.partitions", len(rec.get("partitions") or {})),
    ):
        if v:
            reg.counter_inc(name, v, transformer=tr)
    reg.counter_inc("transforms", 1, transformer=tr)
    reg.histogram_record(
        "transform.wall_seconds", rec.get("wall_seconds", 0.0), transformer=tr
    )
    # one sample per partition is gone by now; re-emit the report's own
    # latency digest as representative samples so the hist survives export
    lat = rec.get("partition_latency") or {}
    for q in ("p50", "p99"):
        if lat.get(q) is not None and lat.get("count"):
            reg.histogram_record(
                f"transform.partition_seconds_{q}", lat[q], transformer=tr
            )
    _aggregate_cost_model(reg, rec, transformer=tr)


if __name__ == "__main__":
    raise SystemExit(main())
