"""tpulint CLI — run the project-native JAX/TPU invariant linter.

Usage (from the repo root):

    python -m tools.tpulint                  # lint the default surface
    python -m tools.tpulint --strict         # CI mode: nonzero on findings
    python -m tools.tpulint --json           # machine-readable findings
    python -m tools.tpulint --bless          # grandfather current findings
    python -m tools.tpulint --list-rules     # rule IDs + docs
    python -m tools.tpulint --list-knobs     # TPU_ML_* inventory
    python -m tools.tpulint --list-knobs --markdown   # README table body
    python -m tools.tpulint --check-readme   # README knob-table drift gate

Default lint surface: the package, tools/, and bench.py (tests/ hold rule
fixtures on purpose and are linted only by their own meta-test). Exit code
0 means clean (suppressed/baselined findings do not count); with
``--strict``, stale baseline entries and unparseable files also fail.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_ml_tpu.analysis.engine import Baseline, lint_paths
from spark_rapids_ml_tpu.analysis.rules import ALL_RULES
from spark_rapids_ml_tpu.utils import knobs

DEFAULT_PATHS = ("spark_rapids_ml_tpu", "tools", "bench.py")
DEFAULT_BASELINE = os.path.join("tools", "tpulint_baseline.json")

README_BEGIN = "<!-- tpulint:knob-table:begin -->"
README_END = "<!-- tpulint:knob-table:end -->"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _list_rules() -> str:
    out = []
    for r in ALL_RULES:
        out.append(f"{r.id} ({r.name})")
        out.append(f"    {r.doc}")
    return "\n".join(out)


def _list_knobs(markdown: bool) -> str:
    if markdown:
        return knobs.markdown_table()
    out = []
    for k in knobs.KNOBS.values():
        default = k.default if k.default else "<unset>"
        out.append(f"{k.name}  [{k.type}, default {default}]  ({k.module})")
        out.append(f"    {k.doc}")
    return "\n".join(out)


def _check_readme(root: str) -> int:
    """0 iff the README's generated knob table matches the declarations."""
    path = os.path.join(root, "README.md")
    with open(path, encoding="utf-8") as f:
        readme = f.read()
    try:
        head, rest = readme.split(README_BEGIN, 1)
        table, _ = rest.split(README_END, 1)
    except ValueError:
        print(
            f"README.md: missing {README_BEGIN}/{README_END} markers",
            file=sys.stderr,
        )
        return 1
    if table.strip() != knobs.markdown_table().strip():
        print(
            "README.md knob table is stale — regenerate the block between "
            "the tpulint:knob-table markers with:\n"
            "    python -m tools.tpulint --list-knobs --markdown",
            file=sys.stderr,
        )
        return 1
    print("README.md knob table matches utils.knobs declarations")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint "
                    f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on live findings, stale baseline "
                    "entries, or unparseable files (the CI gate)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path ('' disables)")
    ap.add_argument("--bless", action="store_true",
                    help="write all current live findings into the "
                    "baseline (existing notes survive; new entries get a "
                    "placeholder note to fill in)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print baselined/suppressed findings")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule IDs and docs, then exit")
    ap.add_argument("--list-knobs", action="store_true",
                    help="print the declared TPU_ML_* knob inventory")
    ap.add_argument("--markdown", action="store_true",
                    help="with --list-knobs: emit the README markdown table")
    ap.add_argument("--check-readme", action="store_true",
                    help="verify the README knob table matches the "
                    "declarations (drift gate)")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if args.list_knobs:
        print(_list_knobs(args.markdown))
        return 0

    root = _repo_root()
    if args.check_readme:
        return _check_readme(root)

    paths = args.paths or [os.path.join(root, p) for p in DEFAULT_PATHS]
    findings, errors = lint_paths(paths, ALL_RULES, root=root)

    baseline_path = (
        os.path.join(root, args.baseline) if args.baseline
        and not os.path.isabs(args.baseline) else args.baseline
    )
    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()
    unsuppressed = [f for f in findings if not f.suppressed]
    baseline.apply(unsuppressed)
    live = [f for f in unsuppressed if not f.baselined]
    stale = baseline.stale(unsuppressed)

    if args.bless:
        if not baseline_path:
            print("--bless needs a --baseline path", file=sys.stderr)
            return 2
        n = Baseline.write(baseline_path, unsuppressed)
        print(f"blessed {n} finding(s) into {os.path.relpath(baseline_path, root)}")
        return 0

    if args.as_json:
        doc = {
            "findings": [f.to_dict() for f in findings],
            "live": len(live),
            "baselined": sum(1 for f in findings if f.baselined),
            "suppressed": sum(1 for f in findings if f.suppressed),
            "stale_baseline": stale,
            "errors": errors,
        }
        print(json.dumps(doc, indent=2))
    else:
        shown = findings if args.show_baselined else live
        for f in shown:
            print(f.render())
        for e in errors:
            print(f"error: {e}", file=sys.stderr)
        for s in stale:
            print(
                f"stale baseline entry (finding no longer fires — remove "
                f"it): {s['rule']} {s['path']} {s['message']!r}",
                file=sys.stderr,
            )
        counts = (
            f"{len(live)} live finding(s), "
            f"{sum(1 for f in findings if f.baselined)} baselined, "
            f"{sum(1 for f in findings if f.suppressed)} suppressed"
        )
        print(counts if shown or stale or errors else f"clean — {counts}")

    if live:
        return 1
    if args.strict and (stale or errors):
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `tpulint --list-rules | head`
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
