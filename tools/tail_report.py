#!/usr/bin/env python
"""Tail attribution: decompose serve p99 into per-segment budgets from
stitched traces and flag the dominant segment.

Usage::

    python -m tools.tail_report fleet_events.json
    python -m tools.tail_report router.jsonl /tmp/r0.sock.trailer \\
        /tmp/r1.sock.trailer --percentile 99 --top 5
    python -m tools.tail_report events.json --ledger perf.jsonl --json

Input is any mix of event streams: a fleet event dump (JSON object with
an ``events`` list, e.g. ``ServeFleet.fleet_events()`` written to a
file), replica telemetry trailers (``<socket>.trailer``), Chrome trace
JSON or the ``TPU_ML_TIMELINE_PATH`` timeline JSONL. Streams are merged
and stitched with :func:`telemetry.tracectx.stitch_all`; every complete
trace that carries a ``serve.request`` span is decomposed into:

``queue``
    the micro-batcher admission wait (``serve.queue`` span),
``route``/``relay``
    router-side time (``serve.relay`` span minus the replica's
    ``serve.request`` span): ring walk, trace injection, the UDS hop to
    the replica and any silent crash retries. The current
    instrumentation cannot split the routing decision from the relay
    wire, so ``route`` reads 0 and both ride the ``relay`` row;
    single-process traces have neither,
``device``
    the coalesced device dispatch the request rode (the
    ``serve.dispatch`` span link-joined to this trace; hedge losers are
    excluded — the loser is off the critical path),
``response``
    the residual inside the serving process: decode, finalize, framing
    the reply (``serve.request`` minus queue minus device).

The report prints the fleet percentile, the mean per-segment budget over
the tail (every trace at or above the percentile), the dominant segment,
and the top-N slowest stitched traces. ``--ledger`` cross-references the
latest perf-ledger record's serving/fleet evidence: trace ids that ride
the ledger's latency exemplars are marked ``*`` in the top table, so the
slow requests the registry sampled can be pulled up by id
(``/traces/<id>``). ``--json`` emits the same payload for machines.

Exit status: 0 normally, 1 when no stitched ``serve.request`` trace is
found (nothing to attribute).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# the stitching primitives live in the package, which must be importable
# from the repo root — `python tools/tail_report.py` does not put it on
# sys.path
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from spark_rapids_ml_tpu.telemetry import tracectx  # noqa: E402

SEGMENTS = ("queue", "route", "relay", "device", "response")


def load_events(path: str) -> list[dict]:
    """Merged event list from one file: fleet event dump / trailer
    (``{"events": [...]}``), Chrome trace JSON, or timeline JSONL."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        obj = json.loads(text)
        if isinstance(obj, dict) and "traceEvents" in obj:
            return [
                e for e in obj["traceEvents"]
                if isinstance(e, dict) and e.get("ph") != "M"
            ]
        if isinstance(obj, dict) and isinstance(obj.get("events"), list):
            return [e for e in obj["events"] if isinstance(e, dict)]
        return []
    events: list[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("type") == "timeline":
            events.extend(
                e for e in rec.get("events", []) if isinstance(e, dict)
            )
    return events


def _span(trace: dict, name: str) -> dict | None:
    """The longest span named ``name`` in a stitched trace (a retried
    request can legitimately carry two; the longest is the critical
    path)."""
    best = None
    for s in trace["spans"]:
        if s.get("name") == name and (
            best is None or s.get("dur", 0) > best.get("dur", 0)
        ):
            best = s
    return best


def decompose(trace: dict) -> dict | None:
    """One stitched trace → per-segment budget dict (µs), or None when it
    carries no ``serve.request`` span (refresh chains etc.)."""
    request = _span(trace, "serve.request")
    if request is None:
        return None
    relay = _span(trace, "serve.relay")
    queue = _span(trace, "serve.queue")
    # the winning dispatch joined by span link; hedge losers excluded
    device_us = 0
    for link in trace["links"]:
        e = link["event"]
        if e.get("name") != "serve.dispatch":
            continue
        if (e.get("args") or {}).get("hedge_lost"):
            continue
        device_us = max(device_us, e.get("dur", 0))
    req_us = request.get("dur", 0)
    queue_us = min(queue.get("dur", 0) if queue else 0, req_us)
    device_us = min(device_us, max(req_us - queue_us, 0))
    total_us = relay.get("dur", 0) if relay else req_us
    segments = {
        "queue": queue_us,
        "route": 0,
        "relay": max(total_us - req_us, 0) if relay else 0,
        "device": device_us,
        "response": max(req_us - queue_us - device_us, 0),
    }
    args = request.get("args") or {}
    return {
        "trace_id": trace["trace_id"],
        "total_us": total_us,
        "segments": segments,
        "model": args.get("model", ""),
        "transport": args.get("transport", ""),
        "wire": args.get("wire", ""),
        "retries": sum(
            1 for i in trace["instants"] if i.get("name") == "retry"
        ),
        "fleet": relay is not None,
    }


def ledger_exemplars(path: str) -> set[str]:
    """Trace ids riding the latest perf-ledger record's serving/fleet
    latency exemplars (the registry's slowest-sample blobs)."""
    ids: set[str] = set()
    try:
        with open(path, encoding="utf-8") as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return ids
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        found = False
        for key in ("serving", "fleet", "refresh"):
            blob = rec.get(key)
            trace = blob.get("trace") if isinstance(blob, dict) else None
            if not isinstance(trace, dict):
                continue
            for ex_key in ("latency_exemplars", "queue_exemplars"):
                for pair in trace.get(ex_key, ()):
                    if isinstance(pair, (list, tuple)) and len(pair) == 2:
                        ids.add(str(pair[1]))
                        found = True
        if found:
            return ids
    return ids


def build_report(
    events: list[dict], *, percentile: float = 99.0, top: int = 5,
    model: str = "",
) -> dict:
    """The tail-attribution payload over a merged event stream."""
    traces = tracectx.stitch_all(events)
    rows = []
    for t in traces.values():
        if not t["complete"]:
            continue
        row = decompose(t)
        if row is None:
            continue
        if model and row["model"] != model:
            continue
        rows.append(row)
    rows.sort(key=lambda r: -r["total_us"])
    cov = tracectx.coverage(events)
    if not rows:
        return {
            "percentile": percentile, "requests": 0, "coverage": cov,
            "tail": [], "segments_us": {}, "dominant_segment": None,
            "top": [],
        }
    totals = sorted(r["total_us"] for r in rows)
    idx = min(len(totals) - 1, int(percentile / 100.0 * len(totals)))
    cut_us = totals[idx]
    tail = [r for r in rows if r["total_us"] >= cut_us]
    budget = {
        seg: sum(r["segments"][seg] for r in tail) / len(tail)
        for seg in SEGMENTS
    }
    tail_total = sum(budget.values()) or 1.0
    dominant = max(budget, key=lambda seg: budget[seg])
    return {
        "percentile": percentile,
        "requests": len(rows),
        "coverage": cov,
        f"p{percentile:g}_us": cut_us,
        "p50_us": totals[min(len(totals) - 1, len(totals) // 2)],
        "tail_requests": len(tail),
        "segments_us": {k: round(v, 1) for k, v in budget.items()},
        "segments_share": {
            k: round(v / tail_total, 4) for k, v in budget.items()
        },
        "dominant_segment": dominant,
        "retried_requests": sum(1 for r in rows if r["retries"]),
        "top": rows[:top],
    }


def _fmt_us(v: float) -> str:
    return f"{v / 1e3:.3f}ms" if v >= 1e3 else f"{v:.0f}us"


def print_report(rep: dict, exemplar_ids: set[str], out=sys.stdout) -> None:
    cov = rep["coverage"]
    print(
        f"stitched {rep['requests']} request trace(s) "
        f"({cov['complete']}/{cov['traces']} complete, "
        f"{cov['orphan_spans']} orphan spans)",
        file=out,
    )
    if not rep["requests"]:
        print("nothing to attribute: no complete serve.request traces",
              file=out)
        return
    pkey = f"p{rep['percentile']:g}_us"
    print(
        f"p50 {_fmt_us(rep['p50_us'])}   "
        f"p{rep['percentile']:g} {_fmt_us(rep[pkey])}   "
        f"tail = {rep['tail_requests']} request(s) at/above the cut",
        file=out,
    )
    if rep["retried_requests"]:
        print(f"{rep['retried_requests']} request(s) survived a replica "
              "crash retry", file=out)
    print(f"\np{rep['percentile']:g} budget by segment (tail mean):",
          file=out)
    for seg in SEGMENTS:
        us = rep["segments_us"][seg]
        share = rep["segments_share"][seg]
        flag = "  << dominant" if seg == rep["dominant_segment"] else ""
        print(f"  {seg:<9} {_fmt_us(us):>10}  {share:>6.1%}{flag}",
              file=out)
    print(f"\ndominant segment: {rep['dominant_segment']}", file=out)
    print("\nslowest stitched traces (* = rides a ledger latency exemplar):",
          file=out)
    for r in rep["top"]:
        star = "*" if r["trace_id"] in exemplar_ids else " "
        where = "fleet" if r["fleet"] else (r["transport"] or "local")
        segs = " ".join(
            f"{seg}={_fmt_us(r['segments'][seg])}"
            for seg in SEGMENTS
            if r["segments"][seg]
        )
        retry = f" retries={r['retries']}" if r["retries"] else ""
        print(
            f" {star} {r['trace_id']} {_fmt_us(r['total_us']):>10} "
            f"{r['model']:<14} {where:<7} {segs}{retry}",
            file=out,
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Decompose serve tail latency from stitched traces"
    )
    ap.add_argument(
        "paths", nargs="+", metavar="PATH",
        help="event streams: fleet event dump JSON, replica .trailer, "
             "Chrome trace JSON or timeline JSONL (merged)",
    )
    ap.add_argument(
        "--percentile", type=float, default=99.0,
        help="tail percentile to attribute (default 99)",
    )
    ap.add_argument(
        "--top", type=int, default=5,
        help="slowest traces to list (default 5)",
    )
    ap.add_argument(
        "--model", default="", help="only attribute this model's requests"
    )
    ap.add_argument(
        "--ledger", default="",
        help="perf ledger JSONL: mark traces riding its latency exemplars",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit the payload as JSON"
    )
    args = ap.parse_args(argv)

    events: list[dict] = []
    for path in args.paths:
        try:
            events.extend(load_events(path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 1

    rep = build_report(
        events, percentile=args.percentile, top=args.top, model=args.model
    )
    exemplar_ids = ledger_exemplars(args.ledger) if args.ledger else set()
    if args.json:
        rep["ledger_exemplars"] = sorted(exemplar_ids)
        print(json.dumps(rep, indent=2))
    else:
        print_report(rep, exemplar_ids)
    return 0 if rep["requests"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
